"""The serving router — chaos-proved placement over disaggregated workers.

One ``paddle_tpu route`` daemon fronts a fleet of serving workers
(:class:`~.daemon.ServingDaemon` decode engines and optional
:class:`~.daemon.PrefillDaemon` prefill workers). It is model-free: it
owns a :class:`~..runtime.membership.MembershipService` the workers join
(PR 14 contract — heartbeat leases, epoch-numbered views, eviction on
TTL) and a windowed health store their load is scraped into (PR 15
contract), and places every client submit over that state:

* **Placement from health TRENDS, not instantaneous scrapes** — each
  candidate decode worker is scored by the EWMA of its windowed
  ``serving.queue_depth`` + ``serving.slots_live`` series
  (:func:`~..obs.health.ewma` over :meth:`TimeSeriesStore.points`), so
  one lucky idle scrape cannot steer a stampede at a saturated worker;
  a fresh worker with no history scores 0 and absorbs traffic first.
* **Disaggregation** — when prefill workers are joined, a submit is
  forwarded to the least-loaded prefill worker (``srv_prefill``) naming
  the chosen decode worker; the prefill worker admits, exports the KV
  pages (serving/ship.py) and ships them; the reply carries the DECODE
  worker's rid. With no prefill workers the router degrades to direct
  ``srv_submit`` on the decode worker.
* **Backpressure aggregation** — a candidate's structured ``overloaded``
  refusal moves placement to the next candidate; when EVERY pool
  refuses, the client gets one structured ``overloaded`` refusal with
  the MINIMUM ``retry_after_s`` hint seen (the soonest any pool expects
  to drain) — never a hang, never a traceback.
* **Re-route on eviction** — the membership subscription marks every
  in-flight request whose worker was evicted; the next poll re-places
  it by RE-PREFILLING ``prompt + delivered tokens`` with the remaining
  budget (greedy determinism makes the continuation exactly the tokens
  the dead worker would have produced; the prefix index makes the
  re-prefill near-free) under a DERIVED submit_key
  (``{key}#r{n}``), and the client-facing token buffer just keeps
  growing — cursors never see the seam, so zero tokens are lost or
  duplicated (tests/test_serving_router.py pins this under kill -9).

Idempotency ladder (docs/design/serving.md "Disaggregation & routing"):
client ``submit_key`` → router replay cache (same rid; a resubmission
may not inflate its ``prefix_len`` claim — the shared replay-hardening
rule) → forwarded to workers under the same key → worker replay cache →
decode-side adopt replay cache. A restarted router holds none of its
records; the client ladder (:class:`RouterClient`) resubmits the
ORIGINAL request under the ORIGINAL key and resumes its cursor at the
last delivered token — whichever worker the retry lands on, greedy
determinism + the replay caches make the continuation exact.
"""

from __future__ import annotations

import threading
import time
import uuid
from typing import Dict, List, Optional, Tuple

import numpy as np

from .. import faults, obs
from ..obs.health import ewma
from ..runtime.master_service import MasterServer
from ..runtime.membership import MembershipService
from ..utils.retry import RetryPolicy
from .batcher import prefix_resubmission_error
from .daemon import ServingClient
from .engine import Overloaded

#: re-routes one request may burn before the router declares it failed
#: (reason="error") — each re-route re-prefills, so a flapping fleet
#: must not grind one stream forever
_MAX_REROUTES = 8


class _RouteRec:
    """One client-visible request: the original submission (enough to
    re-prefill it verbatim), the append-only token buffer client cursors
    read, and the CURRENT worker placement. ``plock`` serializes the
    poll-through/re-route path per request."""

    __slots__ = ("rid", "key", "prompt", "max_new", "eos_id", "timeout_s",
                 "tenant", "slo", "prefix_len", "tokens", "done", "reason",
                 "worker", "remote_rid", "remote_cursor", "reroutes",
                 "lost_reason", "plock")

    def __init__(self, rid, key, prompt, max_new, eos_id, timeout_s,
                 tenant, slo, prefix_len):
        self.rid = rid
        self.key = key
        self.prompt = [int(t) for t in prompt]
        self.max_new = int(max_new)
        self.eos_id = eos_id
        self.timeout_s = timeout_s
        self.tenant = tenant
        self.slo = slo
        self.prefix_len = prefix_len
        self.tokens: List[int] = []
        self.done = False
        self.reason = ""
        self.worker: Optional[str] = None
        self.remote_rid: Optional[int] = None
        self.remote_cursor = 0
        self.reroutes = 0
        #: why the placement went away (set by the eviction subscriber;
        #: consumed as the reroutes_total reason label)
        self.lost_reason: Optional[str] = None
        self.plock = threading.Lock()


class ServingRouter:
    """Router daemon: membership + health + placement + re-route.

    ``start()`` brings up the RPC server, the membership expiry thread
    and the health-scrape pump; workers then ``join_router`` themselves.
    The route_* ops mirror the srv_* client contract (same reply shapes,
    same structured refusal codes), so :class:`RouterClient` is
    :class:`~.daemon.ServingClient` pointed at different op names."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0, *,
                 ttl: float = 3.0, scrape_interval_s: float = 0.25,
                 max_reroutes: int = _MAX_REROUTES):
        self.server = MasterServer(host, port)
        self.membership = MembershipService(ttl=ttl)
        self.membership.attach(self.server)
        self.membership.subscribe(self._on_membership)
        for op, fn in (("route_submit", self._route_submit),
                       ("route_poll", self._route_poll),
                       ("route_cancel", self._route_cancel),
                       ("route_stats", self._route_stats)):
            self.server.register_op(op, self._stamped(fn))
        # per-request timelines: the router records its own phases AND
        # aggregates every worker's (scrape pump + RequestStore) so a
        # re-routed request stitches across workers (obs/requests.py)
        obs.ensure_request_ledger()
        self._scrape_interval = scrape_interval_s
        self._max_reroutes = max_reroutes
        self._lock = threading.Lock()
        self._recs: Dict[int, _RouteRec] = {}
        self._by_key: Dict[str, int] = {}
        self._next_rid = 0
        self._clients: Dict[str, ServingClient] = {}
        self._clients_lock = threading.Lock()
        self._stop = threading.Event()
        self._pump: Optional[threading.Thread] = None

    # -- lifecycle ---------------------------------------------------------
    @property
    def address(self) -> Tuple[str, int]:
        return self.server.address

    def start(self) -> "ServingRouter":
        self.server.start()
        self.membership.start()
        self._pump = threading.Thread(target=self._run_pump, daemon=True,
                                      name="router-pump")
        self._pump.start()
        return self

    def stop(self) -> None:
        if self._stop.is_set():
            return                  # idempotent: restart tests stop twice
        self._stop.set()
        if self._pump is not None:
            self._pump.join(timeout=5.0)
            self._pump = None
        self.membership.stop()
        self.server.stop()
        with self._clients_lock:
            for c in self._clients.values():
                c.close()
            self._clients.clear()

    def _stamped(self, fn):
        """Every route_* reply carries the membership epoch — the client
        plumbing records it (`last_epoch`) and reports it in the final
        reconnect error."""
        def handler(req):
            resp = fn(req)
            if isinstance(resp, dict) and "epoch" not in resp:
                resp = dict(resp, epoch=self.membership.epoch)
            return resp
        return handler

    # -- membership + health ----------------------------------------------
    def _members(self, role: str) -> List[Tuple[str, str, int]]:
        """Live (worker, host, port) triples with the given role cap."""
        out = []
        for m in self.membership.view()["members"]:
            caps = m.get("caps") or {}
            if caps.get("role") == role and "rpc_port" in caps:
                out.append((m["worker"], str(caps.get("rpc_host",
                                                      "127.0.0.1")),
                            int(caps["rpc_port"])))
        return out

    def _worker_client(self, worker: str, host: str,
                       port: int) -> ServingClient:
        with self._clients_lock:
            c = self._clients.get(worker)
            if c is not None and c.endpoints[0] != (host, port):
                c.close()               # same name, new incarnation
                c = None
            if c is None:
                # short reconnect budget: a dead worker must fail the
                # poll/forward fast so the re-route ladder runs, instead
                # of riding the default multi-second backoff
                c = ServingClient(host, port, retries=2, retry_delay=0.05,
                                  call_timeout=10.0)
                self._clients[worker] = c
            return c

    def _on_membership(self, view, joined, left, reason) -> None:
        """Membership subscriber (runs outside the membership lock): a
        departed worker's in-flight requests are marked for re-route —
        the next poll on each re-places it."""
        for w in left:
            with self._clients_lock:
                c = self._clients.pop(w, None)
            if c is not None:
                c.close()
            self.server.aggregator.forget_worker(w)
            # membership notifies reason="evicted" (TTL expiry) vs
            # "leave"/"join" (graceful departure / replaced incarnation)
            why = "evicted" if reason == "evicted" else "left"
            with self._lock:
                for rec in self._recs.values():
                    if rec.worker == w and not rec.done:
                        rec.worker = None
                        rec.remote_rid = None
                        rec.lost_reason = why

    def _run_pump(self) -> None:
        """Health pump: scrape every member's srv_stats into the windowed
        time-series store — the TREND data placement scores read. A
        scrape failure records nothing (the lease TTL owns eviction)."""
        while not self._stop.wait(self._scrape_interval):
            try:
                self._scrape_once()
            except Exception:
                pass    # telemetry must never take the router down

    def _scrape_once(self) -> None:
        hist = self.server.aggregator.history
        n_role = {"decode": 0, "prefill": 0}
        for role in ("decode", "prefill"):
            for worker, host, port in self._members(role):
                n_role[role] += 1
                try:
                    st = self._worker_client(worker, host,
                                             port).serving_stats()
                except Exception:
                    continue
                hist.record_value(worker, "serving.queue_depth",
                                  float(st.get("queue_depth", 0)))
                hist.record_value(worker, "serving.slots_live",
                                  float(st.get("slots_live", 0)))
                try:
                    # timelines ride the same pump: pulled continuously,
                    # so a kill -9'd worker's phases survive here
                    rq = self._worker_client(worker, host,
                                             port).serving_requests()
                except Exception:
                    rq = None
                if rq:
                    self.server.aggregator.push_requests(worker, rq)
        led = obs.request_ledger()
        if led is not None:
            self.server.aggregator.push_requests("router",
                                                 led.export(n=256))
        with self._lock:
            inflight = sum(1 for r in self._recs.values() if not r.done)
        obs.gauge_set("router.inflight", inflight)
        obs.gauge_set("router.workers", n_role["decode"], role="decode")
        obs.gauge_set("router.workers", n_role["prefill"], role="prefill")

    def _score(self, worker: str) -> float:
        """A worker's load score: EWMA over its windowed queue-depth and
        live-slot series. Trends, not the last scrape — and a fresh
        worker with no history scores 0, so it absorbs traffic first."""
        hist = self.server.aggregator.history
        score = 0.0
        for name in ("serving.queue_depth", "serving.slots_live"):
            mean, _ = ewma([v for _, v in hist.points(worker, name)])
            score += 0.0 if mean is None else float(mean)
        return score

    def _candidates(self, role: str) -> List[Tuple[str, str, int]]:
        ms = self._members(role)
        return sorted(ms, key=lambda m: (self._score(m[0]), m[0]))

    # -- placement ---------------------------------------------------------
    def _place(self, prompt, max_new, *, eos_id, timeout_s, tenant, slo,
               prefix_len, submit_key) -> Tuple[str, int]:
        """Forward a submission to the best candidate; walks the
        candidate list past overloaded/unreachable workers. Returns
        ``(worker, remote_rid)``; raises :class:`Overloaded` with the
        minimum retry hint when every pool refused, ConnectionError when
        nothing was reachable."""
        decodes = self._candidates("decode")
        if not decodes:
            raise ConnectionError("no decode workers joined")
        prefills = self._candidates("prefill")
        retry_hints: List[float] = []
        unreachable = 0
        for worker, host, port in decodes:
            faults.fire("route.submit")
            try:
                if prefills:
                    rid = self._forward_via_prefill(
                        prefills, worker, host, port, prompt, max_new,
                        eos_id=eos_id, timeout_s=timeout_s, tenant=tenant,
                        slo=slo, prefix_len=prefix_len,
                        submit_key=submit_key)
                else:
                    rid = self._worker_client(worker, host, port).submit(
                        prompt, max_new, eos_id=eos_id,
                        timeout_s=timeout_s, tenant=tenant, slo=slo,
                        prefix_len=prefix_len, submit_key=submit_key)
            except Overloaded as e:
                retry_hints.append(float(e.retry_after_s))
                continue
            except ConnectionError:
                unreachable += 1
                continue
            return worker, rid
        if retry_hints:
            raise Overloaded(
                f"all {len(decodes)} decode pool(s) are saturated "
                f"({unreachable} unreachable)", min(retry_hints))
        raise ConnectionError(
            f"no decode worker reachable ({len(decodes)} joined)")

    def _forward_via_prefill(self, prefills, decode_worker, decode_host,
                             decode_port, prompt, max_new, *, eos_id,
                             timeout_s, tenant, slo, prefix_len,
                             submit_key) -> int:
        """Disaggregated forward: srv_prefill on the best prefill worker,
        naming the chosen decode worker. Falls past overloaded/dead
        prefill workers; with all of them out, falls back to direct
        decode-side prefill (degraded, but the request completes)."""
        last: Optional[Exception] = None
        for worker, host, port in prefills:
            req = {"op": "srv_prefill",
                   "prompt": [int(t) for t in np.asarray(prompt)
                              .reshape(-1)],
                   "max_new": int(max_new),
                   "decode_host": decode_host,
                   "decode_port": int(decode_port)}
            if eos_id is not None:
                req["eos_id"] = int(eos_id)
            if timeout_s is not None:
                req["timeout_s"] = float(timeout_s)
            if tenant != "default":
                req["tenant"] = str(tenant)
            if slo != "interactive":
                req["slo"] = str(slo)
            if prefix_len is not None:
                req["prefix_len"] = int(prefix_len)
            if submit_key is not None:
                req["submit_key"] = str(submit_key)
            try:
                r = self._worker_client(worker, host, port)._call(req)
            except ConnectionError as e:
                last = e
                continue
            if r.get("ok"):
                return int(r["rid"])
            code = r.get("code")
            if code == "overloaded":
                raise Overloaded(str(r.get("error")),
                                 float(r.get("retry_after_s", 0.2)))
            if code == "invalid_argument":
                raise ValueError(str(r.get("error", "prefill refused")))
            last = ConnectionError(str(r.get("error", "prefill failed")))
        # every prefill worker down or refusing: decode-side prefill
        # still serves the request (degraded but correct)
        obs.count("router.reroutes_total", reason="prefill_fallback")
        return self._worker_client(decode_worker, decode_host,
                                   decode_port).submit(
            prompt, max_new, eos_id=eos_id, timeout_s=timeout_s,
            tenant=tenant, slo=slo, prefix_len=prefix_len,
            submit_key=submit_key)

    # -- op handlers -------------------------------------------------------
    def _route_submit(self, req):
        key = req.get("submit_key")
        if key is not None:
            with self._lock:
                rid = self._by_key.get(str(key))
                rec = self._recs.get(rid) if rid is not None else None
            if rec is not None:
                # the shared replay-hardening rule: a resubmission may
                # not inflate its cached-prefix claim past the original
                err = prefix_resubmission_error(req.get("prefix_len"),
                                                rec.prefix_len)
                if err is not None:
                    obs.count("router.requests_total",
                              outcome="invalid_argument")
                    return {"ok": False, "error": err,
                            "code": "invalid_argument"}
                return {"ok": True, "rid": rec.rid}
        try:
            prompt = np.asarray(req.get("prompt", ()),
                                np.int32).reshape(-1)
            max_new = int(req.get("max_new", 0))
        except (TypeError, ValueError):
            obs.count("router.requests_total", outcome="invalid_argument")
            return {"ok": False, "code": "invalid_argument",
                    "error": "route_submit needs prompt + max_new"}
        eos = req.get("eos_id")
        timeout = req.get("timeout_s")
        prefix = req.get("prefix_len")
        kw = dict(eos_id=None if eos is None else int(eos),
                  timeout_s=None if timeout is None else float(timeout),
                  tenant=str(req.get("tenant", "default")),
                  slo=str(req.get("slo", "interactive")),
                  prefix_len=None if prefix is None else int(prefix))
        obs.req_phase(key, "admitted", via="router")
        try:
            worker, remote_rid = self._place(
                prompt, max_new, submit_key=key, **kw)
        except Overloaded as e:
            obs.count("router.requests_total", outcome="overloaded")
            return {"ok": False, "error": f"overloaded: {e}",
                    "code": "overloaded", "retry_after_s": e.retry_after_s}
        except ValueError as e:
            obs.count("router.requests_total", outcome="invalid_argument")
            return {"ok": False, "error": str(e),
                    "code": "invalid_argument"}
        except ConnectionError as e:
            obs.count("router.requests_total", outcome="unavailable")
            return {"ok": False, "error": str(e), "code": "unavailable"}
        with self._lock:
            # a concurrent identical-key submit may have won the insert
            # race while we forwarded; the first record wins (the extra
            # remote admission is orphaned — never polled, it times out
            # or runs to completion unobserved)
            if key is not None and str(key) in self._by_key:
                return {"ok": True,
                        "rid": self._recs[self._by_key[str(key)]].rid}
            self._next_rid += 1
            rec = _RouteRec(self._next_rid, None if key is None
                            else str(key), prompt, max_new, **kw)
            rec.worker, rec.remote_rid = worker, remote_rid
            self._recs[rec.rid] = rec
            if key is not None:
                self._by_key[str(key)] = rec.rid
            self._prune_done_locked()
        obs.count("router.requests_total", outcome="ok")
        # a point record (explicit zero dur): the forward wall it spans
        # is attributed by the WORKERS' phase records, not double-billed
        obs.req_phase(key, "route", dur=0.0, worker=str(worker))
        return {"ok": True, "rid": rec.rid}

    def _prune_done_locked(self) -> None:
        cap = 4096
        if len(self._recs) <= cap:
            return
        for rid in sorted(self._recs):
            rec = self._recs[rid]
            if rec.done:
                del self._recs[rid]
                if rec.key is not None:
                    self._by_key.pop(rec.key, None)
            if len(self._recs) <= cap:
                return

    def _route_poll(self, req):
        try:
            rid = int(req["rid"])
            cursor = int(req.get("cursor", 0))
        except (KeyError, TypeError, ValueError):
            return {"ok": False, "error": "route_poll needs an integer "
                    "rid (+ optional integer cursor)",
                    "code": "invalid_argument"}
        with self._lock:
            rec = self._recs.get(rid)
        if rec is None:
            return {"ok": False, "error": f"unknown rid {rid} (the "
                    "router may have restarted — resubmit under the "
                    "original submit_key and resume your cursor)",
                    "code": "not_found"}
        if not rec.done:
            self._advance(rec)
        with self._lock:
            toks = rec.tokens[cursor:]
            return {"ok": True, "tokens": [int(t) for t in toks],
                    "done": bool(rec.done), "reason": rec.reason}

    def _advance(self, rec: _RouteRec) -> None:
        """Poll-through: pull new tokens from the request's CURRENT
        worker into the append-only buffer; on a lost worker, re-route.
        Per-rec lock — concurrent client polls must not double-append."""
        with rec.plock:
            if rec.done:
                return
            if rec.worker is None and not self._reroute(rec):
                return
            worker_addr = None
            for w, host, port in self._members("decode"):
                if w == rec.worker:
                    worker_addr = (host, port)
                    break
            if worker_addr is None:
                rec.lost_reason = rec.lost_reason or "evicted"
                rec.worker = None
                self._reroute(rec)
                return
            client = self._worker_client(rec.worker, *worker_addr)
            try:
                toks, done, reason = client.poll(rec.remote_rid,
                                                 rec.remote_cursor)
            except KeyError:
                # the worker restarted (same name, empty engine) or
                # purged the record — the stream is gone there
                rec.lost_reason = "not_found"
                rec.worker = None
                self._reroute(rec)
                return
            except ConnectionError:
                rec.lost_reason = "unreachable"
                rec.worker = None
                self._reroute(rec)
                return
            if done and reason == "error":
                # the engine failed mid-stream (scheduler fault) — the
                # request itself is fine; re-prefill it elsewhere
                rec.lost_reason = "error"
                rec.worker = None
                self._reroute(rec)
                return
            with self._lock:
                rec.tokens.extend(int(t) for t in toks)
                rec.remote_cursor += len(toks)
                if done:
                    rec.done, rec.reason = True, reason

    def _reroute(self, rec: _RouteRec) -> bool:
        """Re-place a request whose worker went away: re-prefill
        ``prompt + delivered`` with the remaining budget under a derived
        submit_key. The buffer keeps growing in place — client cursors
        never see the seam. Returns True when placed (caller's next
        poll pulls from the new worker)."""
        why = rec.lost_reason or "lost"
        rec.lost_reason = None
        with self._lock:
            delivered = list(rec.tokens)
            remaining = rec.max_new - len(delivered)
        if remaining <= 0:
            # the budget was fully delivered before the worker died —
            # nothing is owed; close the stream as a normal completion
            with self._lock:
                rec.done, rec.reason = True, "length"
            return False
        if rec.reroutes >= self._max_reroutes:
            with self._lock:
                rec.done, rec.reason = True, "error"
            return False
        rec.reroutes += 1
        obs.count("router.reroutes_total", reason=why)
        key = (None if rec.key is None
               else f"{rec.key}#r{rec.reroutes}")
        try:
            worker, remote_rid = self._place(
                rec.prompt + delivered, remaining, eos_id=rec.eos_id,
                timeout_s=rec.timeout_s, tenant=rec.tenant, slo=rec.slo,
                prefix_len=rec.prefix_len, submit_key=key)
        except (Overloaded, ConnectionError, ValueError):
            # nowhere to land right now: leave the rec unplaced — the
            # next poll retries the re-route (the client keeps polling;
            # the stream stalls instead of dying)
            rec.reroutes -= 1    # this attempt placed nothing
            rec.lost_reason = why
            return False
        with self._lock:
            rec.worker, rec.remote_rid = worker, remote_rid
            rec.remote_cursor = 0
        # recorded under the BASE key: the new leg's own phases live
        # under the derived {key}#r{n} timeline the workers record
        obs.req_phase(rec.key, "reroute", dur=0.0, why=why,
                      to=str(worker), n=rec.reroutes)
        return True

    def _route_cancel(self, req):
        try:
            rid = int(req["rid"])
        except (KeyError, TypeError, ValueError):
            return {"ok": False, "error": "route_cancel needs an integer "
                    "rid", "code": "invalid_argument"}
        with self._lock:
            rec = self._recs.get(rid)
        if rec is None:
            return {"ok": True, "cancelled": False}
        with rec.plock:
            was_live = not rec.done
            with self._lock:
                if not rec.done:
                    rec.done, rec.reason = True, "cancelled"
            if was_live and rec.worker is not None:
                for w, host, port in self._members("decode"):
                    if w == rec.worker:
                        try:
                            self._worker_client(w, host, port).cancel(
                                rec.remote_rid)
                        except Exception:
                            pass    # its timeout still bounds the slot
                        break
        return {"ok": True, "cancelled": was_live}

    def _route_stats(self, req):
        with self._lock:
            inflight = sum(1 for r in self._recs.values() if not r.done)
        return {"ok": True,
                "n_decode_workers": len(self._members("decode")),
                "n_prefill_workers": len(self._members("prefill")),
                "inflight": inflight,
                "rpc_conns": self.server.active_connections()}


class RouterClient(ServingClient):
    """:class:`~.daemon.ServingClient` pointed at the route_* surface,
    plus the restart-recovery ladder in :meth:`stream`: a ``not_found``
    poll (the router restarted and lost its records) resubmits the
    ORIGINAL request under the ORIGINAL submit_key and resumes the
    cursor at the last delivered token. Whichever worker the retry
    lands on, the worker-side replay caches and greedy determinism make
    the continuation exactly the original stream's remainder — no lost,
    no duplicated tokens, no double admission under one key."""

    _rpc_name = "router rpc"
    _op_submit = "route_submit"
    _op_poll = "route_poll"
    _op_cancel = "route_cancel"
    _op_stats = "route_stats"

    def stream(self, prompt, max_new: int, *, eos_id: Optional[int] = None,
               timeout_s: Optional[float] = None, tenant: str = "default",
               slo: str = "interactive", prefix_len: Optional[int] = None,
               poll_interval_s: float = 0.02,
               policy: Optional[RetryPolicy] = None,
               max_recoveries: int = 8):
        key = uuid.uuid4().hex
        submit = lambda: self.submit_with_backoff(  # noqa: E731
            prompt, max_new, eos_id=eos_id, timeout_s=timeout_s,
            tenant=tenant, slo=slo, prefix_len=prefix_len, policy=policy,
            submit_key=key)
        rid = submit()
        cursor = 0          # tokens DELIVERED to the caller, ever
        recoveries = 0
        finished = False
        try:
            while True:
                try:
                    tokens, done, reason = self.poll(rid, cursor)
                except KeyError:
                    # the router restarted: its record of rid is gone,
                    # but ours isn't — resubmit the identical request
                    # under the identical key and keep our cursor. The
                    # router re-places it; the stream's tail re-emerges
                    # at exactly position `cursor`.
                    recoveries += 1
                    if recoveries > max_recoveries:
                        raise
                    try:
                        rid = submit()
                    except (Overloaded, ConnectionError):
                        # restart window: workers may not have rejoined
                        # the new router yet — wait and retry (the next
                        # poll raises KeyError again, re-entering here)
                        time.sleep(poll_interval_s * 10)
                    continue
                except ConnectionError:
                    # the router itself is down/restarting: bounded wait
                    # for it to come back, then poll again (rid may
                    # still be valid if only the connection dropped)
                    recoveries += 1
                    if recoveries > max_recoveries:
                        raise
                    time.sleep(poll_interval_s * 5)
                    continue
                for t in tokens:
                    yield t
                cursor += len(tokens)
                if done:
                    finished = True
                    if reason == "timeout":
                        raise TimeoutError(
                            f"request {rid} timed out server-side")
                    if reason in ("cancelled", "error"):
                        raise RuntimeError(
                            f"request {rid} ended server-side with "
                            f"reason={reason} after {cursor} token(s)")
                    return
                time.sleep(poll_interval_s)
        finally:
            if not finished:
                try:
                    self.cancel(rid)
                except Exception:
                    pass
