"""KV-page shipping — the disaggregation wire format (prefill → decode).

A prefill worker admits a prompt into its own :class:`~.paged.PagePool`
(full or suffix prefill, first token emitted), then SHIPS the slot's page
contents to a decode worker where the request finishes its life.  This
module owns the serialization contract both ends agree on:

* :func:`pack` — the slot's per-layer ``k{i}``/``v{i}`` page rows (and the
  int8 ``*_scale`` planes when the pool is quantized) concatenate into one
  payload in sorted-name order, described by a manifest carrying every
  array's name/shape/dtype, the pool geometry (``page_block``,
  ``kv_dtype``), the request state (``plen``, ``first``) and a CRC32 over
  the whole payload.
* :func:`unpack` — the decode side re-slices the payload against the
  manifest, refusing structurally (``ShipError``) on a CRC mismatch, a
  short/long payload, or a malformed manifest — a damaged shipment is
  never adopted into a live pool.
* chunking — payloads can exceed the RPC frame guard
  (``runtime.master_service._MAX_FRAME``), so they travel as numbered
  chunks (:func:`iter_chunks` / :class:`ChunkAssembler`), each base64-clean
  for the JSON frame protocol and carrying its OWN CRC32: one corrupted
  chunk is refused on arrival instead of poisoning the reassembly.

Chaos: the ``srv.ship`` fault site filters every raw chunk on the send
edge AFTER its CRC was stamped — an injected corrupt/truncate produces
exactly the damage the receiver-side CRC exists to catch, and the refusal
path (not silent adoption) is what tests/test_serving_ship.py pins.

Bit-exactness is the whole point: the decode worker's pool rows after
adoption are byte-identical to the prefill worker's, so wire-greedy tokens
across the process boundary equal solo single-engine decode for f32 AND
int8 KV (docs/design/serving.md "Disaggregation & routing").
"""

from __future__ import annotations

import base64
import zlib
from typing import Dict, Iterator, List, Optional, Tuple

import numpy as np

from .. import faults, obs

#: raw bytes per shipped chunk. Base64 inflates by 4/3 and the JSON frame
#: adds envelope overhead, so 4 MiB raw stays far under the 64 MiB frame
#: guard while keeping chunk counts small for realistic page loads.
CHUNK_BYTES = 4 << 20

#: wire-format version stamped into every manifest; a receiver refuses a
#: version it does not speak instead of misreading the payload layout
SHIP_VERSION = 1


class ShipError(ValueError):
    """A shipment that must not be adopted: CRC mismatch, short payload,
    malformed manifest, or pool-geometry disagreement. Maps to the
    structured ``code="data_loss"`` refusal on the wire."""


def pack(arrays: Dict[str, np.ndarray], *, plen: int, first: int,
         page_block: int, kv_dtype: Optional[str]) -> Tuple[dict, bytes]:
    """Serialize a slot's page arrays into ``(manifest, payload)``.

    ``arrays`` maps pool-array names (``k0``, ``v0``, ``k0_scale``, ...)
    to the slot's gathered page rows ``[n_pages, page_block, ...]``; the
    payload is their raw bytes concatenated in sorted-name order (the
    order the manifest's ``entries`` list records)."""
    entries: List[dict] = []
    parts: List[bytes] = []
    for name in sorted(arrays):
        a = np.ascontiguousarray(arrays[name])
        entries.append({"name": name, "shape": list(a.shape),
                        "dtype": str(a.dtype), "nbytes": int(a.nbytes)})
        parts.append(a.tobytes())
    payload = b"".join(parts)
    manifest = {"version": SHIP_VERSION, "plen": int(plen),
                "first": int(first), "page_block": int(page_block),
                "kv_dtype": kv_dtype or "",
                "entries": entries, "nbytes": len(payload),
                "crc": zlib.crc32(payload) & 0xFFFFFFFF}
    return manifest, payload


def unpack(manifest: dict, payload: bytes) -> Dict[str, np.ndarray]:
    """Verify + deserialize a shipment; raises :class:`ShipError` rather
    than ever returning damaged arrays."""
    if not isinstance(manifest, dict) or \
            manifest.get("version") != SHIP_VERSION:
        raise ShipError(f"unsupported ship manifest version "
                        f"{manifest.get('version') if isinstance(manifest, dict) else manifest!r} "
                        f"(this end speaks {SHIP_VERSION})")
    entries = manifest.get("entries")
    if not isinstance(entries, list) or not entries:
        raise ShipError("ship manifest carries no payload entries")
    declared = int(manifest.get("nbytes", -1))
    if declared != len(payload):
        raise ShipError(f"ship payload is {len(payload)} bytes but the "
                        f"manifest declares {declared} — a chunk was lost "
                        "or truncated in flight")
    crc = zlib.crc32(payload) & 0xFFFFFFFF
    if crc != int(manifest.get("crc", -1)):
        raise ShipError(f"ship payload CRC {crc:#010x} != manifest "
                        f"{int(manifest.get('crc', -1)):#010x} — refusing "
                        "to adopt corrupted pages")
    out: Dict[str, np.ndarray] = {}
    off = 0
    for e in entries:
        try:
            name = str(e["name"])
            shape = tuple(int(d) for d in e["shape"])
            dtype = np.dtype(str(e["dtype"]))
            nbytes = int(e["nbytes"])
        except (KeyError, TypeError, ValueError) as exc:
            raise ShipError(f"malformed ship manifest entry {e!r}") from exc
        if nbytes != dtype.itemsize * int(np.prod(shape, dtype=np.int64)):
            raise ShipError(f"entry {name!r}: nbytes {nbytes} disagrees "
                            f"with shape {shape} x dtype {dtype}")
        if off + nbytes > len(payload):
            raise ShipError(f"entry {name!r} overruns the payload")
        out[name] = np.frombuffer(payload[off:off + nbytes],
                                  dtype=dtype).reshape(shape)
        off += nbytes
    if off != len(payload):
        raise ShipError(f"{len(payload) - off} trailing payload bytes not "
                        "described by the manifest")
    return out


# -- chunking (the frame-guard discipline) ----------------------------------

def iter_chunks(payload: bytes,
                chunk_bytes: int = CHUNK_BYTES
                ) -> Iterator[Tuple[int, int, dict]]:
    """Yield ``(seq, total, frame)`` wire chunks for ``payload``. Each
    frame dict is JSON-clean: base64 data + the RAW chunk's CRC32, stamped
    BEFORE the ``srv.ship`` fault filter runs — injected corruption is
    therefore detectable, exactly like real wire damage."""
    total = max(1, -(-len(payload) // chunk_bytes))
    for seq in range(total):
        raw = payload[seq * chunk_bytes:(seq + 1) * chunk_bytes]
        crc = zlib.crc32(raw) & 0xFFFFFFFF
        raw = faults.filter_bytes("srv.ship", raw)
        # send-edge wire accounting: what the ship phase's duration in
        # the request timeline is spent ON (obs/requests.py)
        obs.count("serving.ship_chunks_total")
        obs.count("serving.ship_chunk_bytes_total", len(raw))
        yield seq, total, {"seq": seq, "total": total,
                           "data": base64.b64encode(raw).decode("ascii"),
                           "crc": crc}


class ChunkAssembler:
    """Receiver-side reassembly of one shipment's chunk stream. Chunks may
    arrive retried (idempotent: a seq already held is re-verified, not
    duplicated); :meth:`payload` refuses until every chunk landed."""

    def __init__(self, total: int):
        if total < 1:
            raise ShipError(f"chunk stream declares total={total}")
        self.total = int(total)
        self._parts: Dict[int, bytes] = {}

    def add(self, seq: int, data_b64: str, crc: int) -> None:
        seq = int(seq)
        if not (0 <= seq < self.total):
            raise ShipError(f"chunk seq {seq} outside declared total "
                            f"{self.total}")
        try:
            raw = base64.b64decode(data_b64, validate=True)
        except Exception as exc:
            raise ShipError(f"chunk {seq} is not valid base64") from exc
        got = zlib.crc32(raw) & 0xFFFFFFFF
        if got != int(crc) & 0xFFFFFFFF:
            raise ShipError(f"chunk {seq} CRC {got:#010x} != declared "
                            f"{int(crc) & 0xFFFFFFFF:#010x} — corrupted or "
                            "truncated in flight")
        self._parts[seq] = raw

    @property
    def complete(self) -> bool:
        return len(self._parts) == self.total

    def payload(self) -> bytes:
        if not self.complete:
            missing = sorted(set(range(self.total)) - set(self._parts))
            raise ShipError(f"shipment incomplete: missing chunk(s) "
                            f"{missing[:8]} of {self.total}")
        return b"".join(self._parts[i] for i in range(self.total))
