"""Training driver: events, evaluators, checkpoints, pass/batch loop.

The merged analog of paddle/trainer (C++ driver) and python/paddle/v2/trainer.py
(events API) — see trainer.py for the mapping.
"""

from . import event
from .checkpoint import (COMPLETE_MANIFEST, from_tar, latest_pass,
                         load_checkpoint, pass_dir, publish_members,
                         save_checkpoint, to_tar, verify_checkpoint)
from .evaluator import (AucEvaluator, ChunkEvaluator,
                        ClassificationErrorEvaluator, CTCErrorEvaluator,
                        DetectionMAPEvaluator, Evaluator, EvaluatorGroup,
                        MaxIdPrinterEvaluator, PnpairEvaluator,
                        PrecisionRecallEvaluator, SumEvaluator,
                        ValuePrinterEvaluator)
from .elastic import ElasticMaster, ElasticWorker
from .trainer import Trainer

__all__ = ["Trainer", "event", "ElasticMaster", "ElasticWorker",
           "Evaluator", "EvaluatorGroup", "ClassificationErrorEvaluator",
           "SumEvaluator", "AucEvaluator", "PrecisionRecallEvaluator",
           "ChunkEvaluator", "CTCErrorEvaluator", "DetectionMAPEvaluator",
           "PnpairEvaluator", "ValuePrinterEvaluator", "MaxIdPrinterEvaluator",
           "to_tar", "from_tar", "save_checkpoint", "load_checkpoint",
           "latest_pass", "pass_dir", "publish_members",
           "verify_checkpoint", "COMPLETE_MANIFEST"]
