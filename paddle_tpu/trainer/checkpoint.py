"""Checkpoint / resume.

Capability parity with the reference (SURVEY.md §5 'Checkpoint / resume'):
* per-pass directories ``output/pass-%05d`` (trainer/ParamUtil.cpp:50-67)
* tar parameter archives with versioned headers (v2/parameters.py:296-358
  to_tar/from_tar; parameter/Parameter.cpp save/load)
* resume via ``--init_model_path`` / ``--start_pass`` -> :func:`latest_pass` +
  :func:`load_checkpoint`
* CRC-checked payloads like the Go pserver checkpoints (go/pserver/service.go:119-126).

Format: a real tarfile, one ``.npy`` member per parameter path plus a JSON
``__meta__`` member carrying {version, crc32 per member, pytree paths}; works for
any params/optimizer-state pytree.
"""

from __future__ import annotations

import io
import json
import os
import re
import tarfile
import zlib
from typing import Any, Dict, Optional, Tuple

import jax
import numpy as np

from ..core.pytree import flatten_path_tree, tree_spec, unflatten_path_tree

FORMAT_VERSION = 1
_META = "__meta__.json"


def _flatten(tree) -> Dict[str, np.ndarray]:
    return {path: np.asarray(jax.device_get(leaf))
            for path, leaf in flatten_path_tree(tree)}


# -- tar serialization ----------------------------------------------------------

def to_tar(f, params) -> None:
    """Serialize a params pytree into an open binary file object (v2
    parameters.to_tar analog, with CRC32 like go pserver checkpoints)."""
    flat = _flatten(params)
    # Container structure (incl. empty dicts/lists and tuple-ness) travels in
    # meta so from_tar restores the exact pytree — an SGD state whose per-param
    # slots are {} must round-trip, not collapse to {'step': ...} (ADVICE r1).
    meta = {"version": FORMAT_VERSION, "crc32": {}, "order": list(flat),
            "structure": tree_spec(params)}
    with tarfile.open(fileobj=f, mode="w") as tar:
        for path, arr in flat.items():
            buf = io.BytesIO()
            np.save(buf, arr)
            payload = buf.getvalue()
            meta["crc32"][path] = zlib.crc32(payload) & 0xFFFFFFFF
            info = tarfile.TarInfo(name=path.replace("/", "%2F") + ".npy")
            info.size = len(payload)
            tar.addfile(info, io.BytesIO(payload))
        mb = json.dumps(meta).encode()
        info = tarfile.TarInfo(name=_META)
        info.size = len(mb)
        tar.addfile(info, io.BytesIO(mb))


def from_tar(f):
    """Load a params pytree; verifies version + CRC (Parameter.cpp load +
    go/pserver/service.go:156-201 load-with-checksum analog)."""
    with tarfile.open(fileobj=f, mode="r") as tar:
        meta_m = tar.extractfile(_META)
        if meta_m is None:
            raise ValueError("checkpoint missing metadata member")
        meta = json.loads(meta_m.read().decode())
        if meta.get("version") != FORMAT_VERSION:
            raise ValueError(f"unsupported checkpoint version {meta.get('version')}")
        flat = {}
        for member in tar.getmembers():
            if member.name == _META:
                continue
            path = member.name[:-len(".npy")].replace("%2F", "/")
            payload = tar.extractfile(member).read()
            want = meta["crc32"].get(path)
            got = zlib.crc32(payload) & 0xFFFFFFFF
            if want is not None and got != want:
                raise ValueError(f"CRC mismatch for {path}: {got} != {want}")
            flat[path] = np.load(io.BytesIO(payload), allow_pickle=False)
    return unflatten_path_tree(flat, spec=meta.get("structure"))


# -- pass directories -----------------------------------------------------------

def pass_dir(output_dir: str, pass_id: int) -> str:
    """output/pass-%05d naming (ParamUtil.cpp:56)."""
    return os.path.join(output_dir, f"pass-{pass_id:05d}")


def save_checkpoint(output_dir: str, pass_id: int, params,
                    opt_state=None, extra: Optional[Dict[str, Any]] = None) -> str:
    d = pass_dir(output_dir, pass_id)
    os.makedirs(d, exist_ok=True)
    with open(os.path.join(d, "params.tar"), "wb") as f:
        to_tar(f, params)
    if opt_state is not None:
        with open(os.path.join(d, "opt_state.tar"), "wb") as f:
            to_tar(f, opt_state)
    state = {"pass_id": pass_id, "version": FORMAT_VERSION}
    if extra:
        state.update(extra)
    with open(os.path.join(d, "state.json"), "w") as f:
        json.dump(state, f)
    return d


def load_checkpoint(output_dir: str, pass_id: Optional[int] = None
                    ) -> Tuple[Any, Optional[Any], Dict[str, Any]]:
    """Load (params, opt_state_or_None, state). pass_id None -> latest."""
    if pass_id is None:
        pass_id = latest_pass(output_dir)
        if pass_id is None:
            raise FileNotFoundError(f"no checkpoints under {output_dir}")
    d = pass_dir(output_dir, pass_id)
    with open(os.path.join(d, "params.tar"), "rb") as f:
        params = from_tar(f)
    opt_state = None
    op = os.path.join(d, "opt_state.tar")
    if os.path.exists(op):
        with open(op, "rb") as f:
            opt_state = from_tar(f)
    with open(os.path.join(d, "state.json")) as f:
        state = json.load(f)
    return params, opt_state, state


def latest_pass(output_dir: str) -> Optional[int]:
    """Largest pass-%05d with a complete params.tar (resume point — the
    --start_pass discovery, ParamUtil.h:108-111)."""
    if not os.path.isdir(output_dir):
        return None
    best = None
    for name in os.listdir(output_dir):
        m = re.fullmatch(r"pass-(\d{5})", name)
        if m and os.path.exists(os.path.join(output_dir, name, "params.tar")):
            best = max(best if best is not None else -1, int(m.group(1)))
    return best
