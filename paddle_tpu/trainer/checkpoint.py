"""Checkpoint / resume.

Capability parity with the reference (SURVEY.md §5 'Checkpoint / resume'):
* per-pass directories ``output/pass-%05d`` (trainer/ParamUtil.cpp:50-67)
* tar parameter archives with versioned headers (v2/parameters.py:296-358
  to_tar/from_tar; parameter/Parameter.cpp save/load)
* resume via ``--init_model_path`` / ``--start_pass`` -> :func:`latest_pass` +
  :func:`load_checkpoint`
* CRC-checked payloads like the Go pserver checkpoints (go/pserver/service.go:119-126).

Format: a real tarfile, one ``.npy`` member per parameter path plus a JSON
``__meta__`` member carrying {version, crc32 per member, pytree paths}; works for
any params/optimizer-state pytree.

Crash safety (ISSUE 2): a pass directory is written as ``pass-%05d.tmp`` —
every member fsynced, per-member CRCs recorded in a ``_COMPLETE`` manifest
written last — then atomically renamed into place. A crash (or ``kill -9``)
at ANY point leaves either the previous durable state or a ``.tmp`` dir that
:func:`latest_pass` never considers. :func:`load_checkpoint` falls back to
the newest *verifiable* pass when the latest fails CRC validation, so a
torn or bit-rotted checkpoint degrades resume by one pass instead of
wedging the job.
"""

from __future__ import annotations

import io
import json
import os
import re
import shutil
import tarfile
import zlib
from typing import Any, Dict, Iterable, List, Optional, Tuple

import jax
import numpy as np

from .. import faults, obs
from ..core.pytree import flatten_path_tree, tree_spec, unflatten_path_tree
from ..utils.logging import get_logger

log = get_logger(__name__)

FORMAT_VERSION = 1
_META = "__meta__.json"
#: completion manifest filename — a pass dir without it is not a checkpoint
COMPLETE_MANIFEST = "_COMPLETE"


def _flatten(tree) -> Dict[str, np.ndarray]:
    # gather-on-save: device_get on a fully-addressable sharded array
    # assembles the global value, so checkpoints are mesh-independent and
    # restore re-places onto whatever mesh the loader runs under
    # (docs/design/spmd.md "Checkpoints across meshes")
    return {path: np.asarray(jax.device_get(leaf))
            for path, leaf in flatten_path_tree(tree)}


# -- tar serialization ----------------------------------------------------------

def to_tar(f, params) -> None:
    """Serialize a params pytree into an open binary file object (v2
    parameters.to_tar analog, with CRC32 like go pserver checkpoints)."""
    flat = _flatten(params)
    # Container structure (incl. empty dicts/lists and tuple-ness) travels in
    # meta so from_tar restores the exact pytree — an SGD state whose per-param
    # slots are {} must round-trip, not collapse to {'step': ...} (ADVICE r1).
    meta = {"version": FORMAT_VERSION, "crc32": {}, "order": list(flat),
            "structure": tree_spec(params)}
    with tarfile.open(fileobj=f, mode="w") as tar:
        for path, arr in flat.items():
            buf = io.BytesIO()
            np.save(buf, arr)
            payload = buf.getvalue()
            meta["crc32"][path] = zlib.crc32(payload) & 0xFFFFFFFF
            info = tarfile.TarInfo(name=path.replace("/", "%2F") + ".npy")
            info.size = len(payload)
            tar.addfile(info, io.BytesIO(payload))
        mb = json.dumps(meta).encode()
        info = tarfile.TarInfo(name=_META)
        info.size = len(mb)
        tar.addfile(info, io.BytesIO(mb))


def from_tar(f):
    """Load a params pytree; verifies version + CRC (Parameter.cpp load +
    go/pserver/service.go:156-201 load-with-checksum analog)."""
    with tarfile.open(fileobj=f, mode="r") as tar:
        meta_m = tar.extractfile(_META)
        if meta_m is None:
            raise ValueError("checkpoint missing metadata member")
        meta = json.loads(meta_m.read().decode())
        if meta.get("version") != FORMAT_VERSION:
            raise ValueError(f"unsupported checkpoint version {meta.get('version')}")
        flat = {}
        for member in tar.getmembers():
            if member.name == _META:
                continue
            path = member.name[:-len(".npy")].replace("%2F", "/")
            payload = tar.extractfile(member).read()
            want = meta["crc32"].get(path)
            got = zlib.crc32(payload) & 0xFFFFFFFF
            if want is not None and got != want:
                raise ValueError(f"CRC mismatch for {path}: {got} != {want}")
            flat[path] = np.load(io.BytesIO(payload), allow_pickle=False)
    return unflatten_path_tree(flat, spec=meta.get("structure"))


# -- pass directories -----------------------------------------------------------

def pass_dir(output_dir: str, pass_id: int) -> str:
    """output/pass-%05d naming (ParamUtil.cpp:56)."""
    return os.path.join(output_dir, f"pass-{pass_id:05d}")


def _fsync_file(f) -> None:
    with obs.span("ckpt.fsync", metric="ckpt.fsync_seconds"):
        f.flush()
        os.fsync(f.fileno())


def _fsync_dir(path: str) -> None:
    """Durability of a rename/create requires fsyncing the containing dir;
    best-effort on filesystems that refuse directory fds. Timed under the
    same ``ckpt.fsync`` span/histogram as file fsyncs — on network
    filesystems the directory fsync is often the slowest durability
    step, and the contract says the metric covers both."""
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        with obs.span("ckpt.fsync", metric="ckpt.fsync_seconds", dir=True):
            os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def _write_member(d: str, name: str, payload: bytes) -> Dict[str, int]:
    """Write one checkpoint member, fsynced; returns its manifest entry.

    The CRC is computed on the *intended* payload BEFORE the ``ckpt.write``
    fault hook, so an injected torn/corrupt write is exactly what manifest
    verification later catches — same property as a real partial write.
    """
    entry = {"crc32": zlib.crc32(payload) & 0xFFFFFFFF, "size": len(payload)}
    written = faults.filter_bytes("ckpt.write", payload)
    with obs.span("ckpt.member", metric="ckpt.write_seconds", member=name,
                  bytes=len(written)):
        with open(os.path.join(d, name), "wb") as f:
            f.write(written)
            _fsync_file(f)
    obs.count("ckpt.bytes_total", len(written))
    return entry


def _recover_torn_swap(output_dir: str) -> None:
    """Finish or roll back a pass publication interrupted mid-swap.

    Re-publishing an existing pass moves it aside (``.old``) before renaming
    the ``.tmp`` into place; a crash between those renames leaves the pass
    visible only under suffixed names. Recovery, for each pass whose final
    dir is missing: a ``.tmp`` that carries a *verified* manifest was
    complete — roll it forward; otherwise an ``.old`` is restored. A ``.tmp``
    without a valid manifest is an ordinary torn write and stays ignored.
    Idempotent; called before every discovery scan and publication.
    """
    if not os.path.isdir(output_dir):
        return
    suffixed: Dict[str, Dict[str, str]] = {}
    for name in os.listdir(output_dir):
        m = re.fullmatch(r"(pass-\d{5})\.(old|tmp)", name)
        if m:
            suffixed.setdefault(m.group(1), {})[m.group(2)] = \
                os.path.join(output_dir, name)
    mutated = False
    for base, found in suffixed.items():
        d = os.path.join(output_dir, base)
        try:
            if os.path.exists(d):
                # published pass present: an .old is post-publish garbage
                # from a crash before its rmtree — reclaim it (a .tmp is
                # left to the writer path, which owns the mid-write
                # lifecycle)
                if "old" in found:
                    shutil.rmtree(found["old"], ignore_errors=True)
                    mutated = True
                continue
            tmp, old = found.get("tmp"), found.get("old")
            if tmp is not None and verify_checkpoint(tmp):
                os.rename(tmp, d)
                mutated = True
                log.warning("recovered torn swap: published %s", d)
                if old is not None:
                    shutil.rmtree(old, ignore_errors=True)
            elif old is not None:
                os.rename(old, d)
                mutated = True
                log.warning("recovered torn swap: restored %s", d)
        except OSError as e:
            # lost a race with the writer's own swap, or a read-only
            # mount: discovery must degrade to a pure read, not crash
            log.warning("torn-swap recovery for %s skipped: %s", base, e)
    if mutated:       # keep discovery a pure read in the common case
        _fsync_dir(output_dir)


def publish_members(output_dir: str, pass_id: int,
                    members: Iterable[Tuple[str, bytes]]) -> str:
    """Atomically publish ``output_dir/pass-%05d`` from (name, payload) pairs.

    Write order (each step durable before the next): members into a ``.tmp``
    dir -> ``_COMPLETE`` manifest (per-member CRC32 + size) -> fsync dir ->
    rename to the final name -> fsync parent. A crash anywhere before the
    rename leaves only a ``.tmp`` dir :func:`latest_pass` ignores; a crash
    inside the re-publish swap is healed by :func:`_recover_torn_swap`.
    ``members`` is consumed lazily — one payload in host memory at a time.

    Shared by :func:`save_checkpoint` and the CLI's v2-parameters pass dump,
    so there is exactly one implementation of the durability protocol.
    """
    with obs.span("ckpt.publish", pass_id=pass_id):
        d = _publish_members(output_dir, pass_id, members)
    obs.count("ckpt.saves_total")
    return d


def _publish_members(output_dir: str, pass_id: int,
                     members: Iterable[Tuple[str, bytes]]) -> str:
    _recover_torn_swap(output_dir)
    d = pass_dir(output_dir, pass_id)
    tmp = d + ".tmp"
    if os.path.exists(tmp):            # leftover from a crashed writer
        shutil.rmtree(tmp)
    os.makedirs(tmp)

    manifest = {"version": FORMAT_VERSION, "pass_id": pass_id, "members": {}}
    for name, payload in members:
        manifest["members"][name] = _write_member(tmp, name, payload)
    with open(os.path.join(tmp, COMPLETE_MANIFEST), "w") as f:
        json.dump(manifest, f)
        _fsync_file(f)
    _fsync_dir(tmp)

    try:
        with obs.span("ckpt.rename", metric="ckpt.rename_seconds"):
            if os.path.exists(d):
                # re-saving a pass (e.g. completing one previously
                # preempted): move the old dir aside so the rename stays
                # atomic, then drop it
                old = d + ".old"
                if os.path.exists(old):
                    shutil.rmtree(old)
                os.rename(d, old)
                os.rename(tmp, d)
                shutil.rmtree(old, ignore_errors=True)
            else:
                os.rename(tmp, d)
    except FileNotFoundError:
        # a concurrent discovery scan's torn-swap recovery can publish our
        # .tmp itself; depending on the interleaving our bytes sit at the
        # final name (scanner won the rename race) or were just moved
        # aside as .old (scanner published BEFORE our exists-check, so we
        # renamed our own fresh dir away). Either way the intended state
        # exists — restore it and succeed rather than crash the save.
        old = d + ".old"
        if not verify_checkpoint(d):
            if os.path.exists(old) and verify_checkpoint(old):
                os.rename(old, d)
            else:
                raise
        else:
            shutil.rmtree(old, ignore_errors=True)
    _fsync_dir(output_dir)
    return d


def save_checkpoint(output_dir: str, pass_id: int, params,
                    opt_state=None, extra: Optional[Dict[str, Any]] = None) -> str:
    """Atomically publish ``output_dir/pass-%05d`` (protocol:
    :func:`publish_members`)."""

    def members():
        buf = io.BytesIO()
        to_tar(buf, params)
        yield "params.tar", buf.getvalue()
        if opt_state is not None:
            buf = io.BytesIO()
            to_tar(buf, opt_state)
            yield "opt_state.tar", buf.getvalue()
        state = {"pass_id": pass_id, "version": FORMAT_VERSION,
                 "pass_complete": True}
        if extra:
            state.update(extra)
        yield "state.json", json.dumps(state).encode()

    return publish_members(output_dir, pass_id, members())


def verify_checkpoint(d: str) -> bool:
    """True iff ``d`` has a ``_COMPLETE`` manifest and every member matches
    its recorded size and CRC32 — the resume-safety gate. Members are
    CRC'd in fixed-size chunks: verification of a multi-GB checkpoint must
    not spike host memory by the largest member."""
    try:
        with open(os.path.join(d, COMPLETE_MANIFEST)) as f:
            manifest = json.load(f)
        if manifest.get("version") != FORMAT_VERSION:
            return False
        for name, entry in manifest["members"].items():
            crc, size = 0, 0
            with open(os.path.join(d, name), "rb") as f:
                while True:
                    chunk = f.read(1 << 20)
                    if not chunk:
                        break
                    size += len(chunk)
                    crc = zlib.crc32(chunk, crc)
            if size != entry["size"] or (crc & 0xFFFFFFFF) != entry["crc32"]:
                return False
        return True
    except (OSError, ValueError, KeyError, TypeError):
        return False


def _complete_passes(output_dir: str) -> List[int]:
    """pass ids carrying a ``_COMPLETE`` manifest, ascending."""
    if not os.path.isdir(output_dir):
        return []
    _recover_torn_swap(output_dir)
    out, legacy = [], []
    for name in os.listdir(output_dir):
        m = re.fullmatch(r"pass-(\d{5})", name)
        if not m:
            continue
        if os.path.exists(os.path.join(output_dir, name, COMPLETE_MANIFEST)):
            out.append(int(m.group(1)))
        elif os.path.exists(os.path.join(output_dir, name, "params.tar")):
            legacy.append(name)
    if legacy and not out:
        # an upgraded job pointed at a pre-manifest output_dir: those dirs
        # are indistinguishable from torn writes, so resume ignores them —
        # say so LOUDLY, because the first new save of the same pass id
        # will replace them
        log.warning(
            "%s holds %d pass dir(s) without a %s manifest (%s …): "
            "written before crash-safe checkpointing or torn mid-write; "
            "they are ignored for resume and will be replaced when their "
            "pass id is saved again", output_dir, len(legacy),
            COMPLETE_MANIFEST, sorted(legacy)[-1])
    return sorted(out)


def _load_dir(d: str) -> Tuple[Any, Optional[Any], Dict[str, Any]]:
    with open(os.path.join(d, "params.tar"), "rb") as f:
        params = from_tar(f)
    opt_state = None
    op = os.path.join(d, "opt_state.tar")
    if os.path.exists(op):
        with open(op, "rb") as f:
            opt_state = from_tar(f)
    with open(os.path.join(d, "state.json")) as f:
        state = json.load(f)
    return params, opt_state, state


def load_checkpoint(output_dir: str, pass_id: Optional[int] = None
                    ) -> Tuple[Any, Optional[Any], Dict[str, Any]]:
    """Load (params, opt_state_or_None, state). pass_id None -> the newest
    pass that passes full manifest verification: an unverifiable latest pass
    (torn write that survived the crash window, later bit rot) is skipped
    with a warning and the previous good pass is used instead. An explicit
    pass_id is gated by the same verification — it names a pass, not an
    escape hatch around the safety contract."""
    if pass_id is not None:
        d = pass_dir(output_dir, pass_id)
        if not verify_checkpoint(d):
            raise ValueError(
                f"checkpoint {d} fails manifest/CRC verification")
        return _load_dir(d)
    candidates = _complete_passes(output_dir)
    for pid in reversed(candidates):
        d = pass_dir(output_dir, pid)
        if not verify_checkpoint(d):
            log.warning("checkpoint %s fails CRC/manifest verification; "
                        "falling back to the previous pass", d)
            continue
        try:
            return _load_dir(d)
        except (OSError, ValueError) as e:
            log.warning("checkpoint %s unreadable (%s); falling back", d, e)
    raise FileNotFoundError(f"no verifiable checkpoints under {output_dir}")


def latest_pass(output_dir: str, *, verify: bool = False) -> Optional[int]:
    """Largest pass-%05d carrying a ``_COMPLETE`` manifest (resume point —
    the --start_pass discovery, ParamUtil.h:108-111). Mere existence of
    ``params.tar`` is NOT enough: a dir without the manifest is a torn
    write. ``verify=True`` additionally demands CRC validation."""
    candidates = _complete_passes(output_dir)
    for pid in reversed(candidates):
        if not verify or verify_checkpoint(pass_dir(output_dir, pid)):
            return pid
    return None
