"""Elastic data-parallel training — workers join, leave, and die mid-pass.

Why this is NOT jax.distributed: a synchronous SPMD job is a single
compiled program over a fixed device set — losing one collective
participant kills the program, so recovery there is job-grained (tear
down, relaunch, resume; ``cli.py cluster_train --restart-on-failure``).
This module is the complementary mode the reference's Go master heritage
actually supports (PAPER.md layer 7, trainers-as-stateless-consumers):
**elasticity comes from the data plane**. Each worker is an independent
process/thread with its own local devices; the global step is synchronous
but its gradient work travels over the master RPC plane:

* the master splits every global batch into ``shards_per_step`` fixed
  *shard tasks* and serves them through the native
  :class:`~paddle_tpu.runtime.master.TaskMaster` queue (timeout
  re-dispatch, failure requeue — go/master/service.go semantics);
* workers under a membership heartbeat lease
  (:mod:`paddle_tpu.runtime.membership`) pull shard tasks (``ela_task``),
  compute the shard's gradients on their local mesh, and push them back
  (``ela_grad``), fenced by member token + membership epoch;
* the master reduces the shard gradients **in shard-index order** and
  applies ONE optimizer update (Adam slots and all, placed through the
  PR 6 mesh/layout machinery when given) — so the parameter trajectory is
  **byte-stable**: independent of which workers computed which shards, of
  the worker count, and of joins/leaves/deaths mid-pass. A ``kill -9``'d
  worker costs one re-bucketed shard dispatch, never the pass — the
  failure mode the Ascend field study (PAPERS.md) documents clusters
  dying from.

Membership changes barrier at the next step boundary by construction: the
master only publishes new-step tasks after the previous update applied,
and on any epoch bump it immediately requeues the departed members'
in-flight tasks (``cluster.rebucket_tasks_total``) instead of waiting out
the dispatch timeout. Workers that observe a newer epoch (heartbeat
reply, ``ela_task`` reply, or a structured ``stale_epoch`` refusal)
re-fetch the canonical state and **re-place it onto their local
mesh/layout** (gather happened on the wire; re-place is
``parallel.sharding.shard_params`` — the PR 6 restore path), then resume
the same pass.

Master restarts are survivable: state snapshots ride the crash-safe
checkpoint protocol (``trainer/checkpoint.py`` CRC manifests) each step,
clients retry connection-refused against the restore window
(``MasterClient`` reconnect hardening), and workers whose heartbeats come
back ``unknown_member`` simply re-register (HeartbeatKeeper re-join).

Homogeneous workers (same local mesh shape) reproduce bit-identical
parameters; heterogeneous fleets agree to float-reduction noise — the
chaos tests in tests/test_elastic.py pin both bars.
"""

from __future__ import annotations

import base64
import io
import json
import threading
import time
import uuid
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np

from .. import faults, obs
from ..runtime.master_service import (CODE_STALE_EPOCH, CODE_STALE_STEP,
                                      MasterServer, StaleMemberError)
from ..runtime.membership import (MembershipClient, MembershipService,
                                  HeartbeatKeeper, _err)
from ..utils.logging import get_logger
from .checkpoint import from_tar, latest_pass, load_checkpoint, \
    save_checkpoint, to_tar

log = get_logger(__name__)


class _Stopped(Exception):
    """Internal: the master was stop()ed while a step was collecting."""


# -- wire encoding ---------------------------------------------------------------

def _pack_tree(tree) -> str:
    """pytree -> base64 tar (CRC'd .npy members — the checkpoint format,
    so gather-on-save semantics and structure round-tripping are shared
    with trainer/checkpoint.py)."""
    buf = io.BytesIO()
    to_tar(buf, tree)
    return base64.b64encode(buf.getvalue()).decode()


def _unpack_tree(data: str):
    return from_tar(io.BytesIO(base64.b64decode(data)))


def _pack_arrays(arrays: Sequence[np.ndarray]) -> str:
    buf = io.BytesIO()
    np.savez(buf, **{f"a{i}": np.asarray(a) for i, a in enumerate(arrays)})
    return base64.b64encode(buf.getvalue()).decode()


def _unpack_arrays(data: str) -> List[np.ndarray]:
    z = np.load(io.BytesIO(base64.b64decode(data)), allow_pickle=False)
    return [z[f"a{i}"] for i in range(len(z.files))]


# -- master ----------------------------------------------------------------------

class ElasticMaster:
    """The elastic training master: membership + shard dispatch + the one
    optimizer update.

    Args:
      loss_fn: ``(params, *batch) -> scalar`` mean loss over ITS rows.
      optimizer: a :mod:`paddle_tpu.optimizer` optimizer (Adam slots ride
        the canonical state here, sharded by ``layout`` when given).
      shards_per_step: the fixed shard count every global batch splits
        into — the elasticity quantum. Deliberately NOT tied to the
        worker count: byte-stability of the reduce requires the shard
        partition to be membership-independent.
      ttl: membership heartbeat lease (workers heartbeat at ttl/3;
        eviction after ttl).
      task_timeout_s / failure_max: TaskMaster re-dispatch knobs. The
        elastic default failure_max is high — a shard requeued off dead
        workers must never be *discarded* (that would wedge the step).
      mesh/layout: optional local mesh + SpecLayout for the canonical
        params AND optimizer slots (PR 6 placement; checkpoint restore
        re-places through the same rules).
      snapshot_dir: crash-safe state home. When set, every
        ``snapshot_every_steps`` the (params, opt_state, pass, step,
        membership epoch) publish under the checkpoint CRC protocol and a
        restarted master resumes the same pass at the same step.
      on_step: ``fn(pass_id, step, loss)`` after each applied update
        (tests use it to inject chaos at exact step boundaries).
    """

    def __init__(self, loss_fn: Callable, optimizer, *,
                 host: str = "127.0.0.1", port: int = 0,
                 shards_per_step: int = 4, min_workers: int = 1,
                 ttl: float = 5.0, task_timeout_s: float = 5.0,
                 failure_max: int = 100, tick_interval: float = 0.25,
                 mesh=None, layout=None,
                 snapshot_dir: Optional[str] = None,
                 snapshot_every_steps: int = 1,
                 on_step: Optional[Callable[[int, int, float], None]] = None):
        self.loss_fn = loss_fn
        self.opt = optimizer
        self.shards_per_step = int(shards_per_step)
        if self.shards_per_step < 1:
            raise ValueError("shards_per_step must be >= 1")
        self.min_workers = min_workers
        self.mesh = mesh
        self.layout = layout
        self.snapshot_dir = snapshot_dir
        self.snapshot_every = max(int(snapshot_every_steps), 1)
        self.on_step = on_step
        self.server = MasterServer(host, port, timeout_s=task_timeout_s,
                                   failure_max=failure_max,
                                   tick_interval=tick_interval)
        self.membership = MembershipService(ttl=ttl)
        self.membership.attach(self.server)
        self.membership.subscribe(self._on_membership_change)
        self.server.register_op("ela_task", self._op_task)
        self.server.register_op("ela_grad", self._op_grad)
        self.server.register_op("ela_state", self._op_state)
        self.server.register_op("ela_status", self._op_status)
        # one jitted update: grads -> (params, opt_state). The mesh path
        # runs it under the mesh context so sharded states stay sharded.
        self._update = jax.jit(
            lambda g, s, p: optimizer.update(g, s, p))
        self._mu = threading.Lock()
        self._cv = threading.Condition(self._mu)
        self._params = None
        self._opt_state = None
        self._pass = 0
        self._step = 0
        self._done = False
        self._stopped = threading.Event()
        # current step's collection state
        self._pending: Optional[Tuple[int, int]] = None   # (pass, step)
        self._shard_rows: List[int] = []
        self._grads: Dict[int, Any] = {}
        self._losses: Dict[int, float] = {}
        self._assigned: Dict[int, str] = {}               # task id -> worker
        self._state_blob: Optional[str] = None

    # -- lifecycle ---------------------------------------------------------
    @property
    def address(self) -> Tuple[str, int]:
        return self.server.address

    def start(self) -> "ElasticMaster":
        if self.snapshot_dir and latest_pass(self.snapshot_dir) is not None:
            params, opt_state, st = load_checkpoint(self.snapshot_dir)
            self._params = self._place(params)
            self._opt_state = self._place_opt(opt_state)
            self._pass = int(st.get("pass_id", 0))
            self._step = int(st.get("elastic_step", -1)) + 1
            if st.get("pass_complete"):
                self._pass += 1
                self._step = 0
            self.membership.epoch = int(st.get("membership_epoch", 0))
            log.info("elastic master restored: resuming pass %d step %d "
                     "(membership epoch %d)", self._pass, self._step,
                     self.membership.epoch)
            self._publish_state()
        self.server.start()
        self.membership.start()
        return self

    def stop(self, drain_s: float = 0.0) -> None:
        """Tear the server down. ``drain_s`` > 0 first gives live members
        that window to observe the done signal and leave gracefully
        (``ela_task`` keeps answering ``done: True`` meanwhile) — without
        it a worker polling at the wrong moment sees a severed connection
        instead of completion and exits through its lost-membership path.
        Returns early as soon as the member table empties; a dead-but-not-
        yet-evicted member bounds the wait at min(ttl, drain_s)."""
        if drain_s > 0:
            deadline = time.monotonic() + drain_s
            while time.monotonic() < deadline and self.membership.members():
                self.membership.expire()
                time.sleep(0.05)
        self._stopped.set()
        with self._cv:
            self._cv.notify_all()
        self.membership.stop()
        self.server.stop()

    # -- placement (PR 6 machinery) ----------------------------------------
    def _place(self, params):
        if self.mesh is None:
            return jax.device_put(params)
        from ..parallel.sharding import shard_params
        return shard_params(params, self.mesh, self.layout)

    def _place_opt(self, opt_state):
        if opt_state is None:
            return None
        if self.mesh is None:
            return jax.device_put(opt_state)
        from ..parallel.sharding import replicate
        if hasattr(self.layout, "apply"):
            # SpecLayout: slot paths embed their parameter's path, so Adam
            # moments shard exactly like their params (PR 6 contract)
            return self.layout.apply(self.mesh, opt_state)
        return jax.device_put(opt_state, replicate(self.mesh))

    # -- the training loop -------------------------------------------------
    def fit(self, batches: Sequence[Tuple], params=None, *,
            num_passes: int = 1, max_steps: Optional[int] = None,
            progress_timeout: float = 120.0) -> Tuple[Any, Any, float]:
        """Drive ``num_passes`` over ``batches`` (a list of global-batch
        tuples of host arrays); returns (params, opt_state, last_loss).

        ``max_steps`` bounds the number of applied updates THIS call (the
        rolling-restart tests stop a master mid-pass at an exact step
        boundary; the successor's ``fit`` resumes from the snapshot).
        ``progress_timeout`` bounds the wait for ANY shard gradient — a
        fleet that died entirely surfaces as a TimeoutError carrying the
        queue state, not a silent hang.
        """
        with self._mu:
            if self._params is None:
                if params is None:
                    raise ValueError("no restored state: fit() needs params")
                self._params = self._place(params)
                self._opt_state = self._place_opt(self.opt.init(self._params))
            self._done = False
            self._publish_state_locked()
        self._wait_workers(progress_timeout)
        last_loss = float("nan")
        applied = 0
        total_passes = self._pass + num_passes
        while self._pass < total_passes and not self._stopped.is_set():
            pass_id = self._pass
            for step in range(self._step, len(batches)):
                if max_steps is not None and applied >= max_steps:
                    return self._params, self._opt_state, last_loss
                if self._stopped.is_set():
                    return self._params, self._opt_state, last_loss
                try:
                    last_loss = self._run_step(pass_id, step, batches[step],
                                               progress_timeout)
                except _Stopped:
                    return self._params, self._opt_state, last_loss
                applied += 1
                if self.on_step is not None:
                    self.on_step(pass_id, step, last_loss)
            with self._mu:
                self._pass += 1
                self._step = 0
            if self.snapshot_dir:
                self._snapshot(pass_id, len(batches) - 1, complete=True)
            log.info("elastic pass %d complete (loss %.6f, epoch %d)",
                     pass_id, last_loss, self.membership.epoch)
        with self._cv:
            self._done = True
            self._cv.notify_all()
        return self._params, self._opt_state, last_loss

    def status(self) -> Dict[str, Any]:
        with self._mu:
            todo, pending, done, disc, _ = self.server.master.stats()
            return {"pass": self._pass, "step": self._step,
                    "epoch": self.membership.epoch, "done": self._done,
                    "members": len(self.membership.members()),
                    "todo": todo, "pending": pending, "discarded": disc}

    # -- internals ---------------------------------------------------------
    def _wait_workers(self, timeout: float) -> None:
        deadline = time.monotonic() + timeout
        while len(self.membership.members()) < self.min_workers:
            if self._stopped.is_set():
                return
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"{len(self.membership.members())} worker(s) joined "
                    f"within {timeout}s; min_workers={self.min_workers}")
            time.sleep(0.02)

    def _shard_bounds(self, n_rows: int) -> List[Tuple[int, int]]:
        """Fixed, membership-independent contiguous row partition."""
        S = min(self.shards_per_step, n_rows) or 1
        base, rem = divmod(n_rows, S)
        bounds, lo = [], 0
        for j in range(S):
            hi = lo + base + (1 if j < rem else 0)
            bounds.append((lo, hi))
            lo = hi
        return bounds

    def _run_step(self, pass_id: int, step: int, batch: Tuple,
                  progress_timeout: float) -> float:
        arrays = [np.asarray(a) for a in batch]
        n_rows = int(arrays[0].shape[0])
        bounds = self._shard_bounds(n_rows)
        payloads = []
        for j, (lo, hi) in enumerate(bounds):
            payloads.append(json.dumps({
                "pass": pass_id, "step": step, "shard": j,
                "n_shards": len(bounds), "rows": hi - lo,
                "global_rows": n_rows,
                "batch": _pack_arrays([a[lo:hi] for a in arrays])}))
        with self._cv:
            self._pending = (pass_id, step)
            self._shard_rows = [hi - lo for lo, hi in bounds]
            self._grads = {}
            self._losses = {}
            self._assigned.clear()
            self.server.master.set_dataset(payloads)
            last_n = 0
            deadline = time.monotonic() + progress_timeout
            while len(self._grads) < len(bounds):
                if self._stopped.is_set():
                    raise _Stopped()
                self._cv.wait(timeout=0.05)
                if len(self._grads) > last_n:
                    last_n = len(self._grads)
                    deadline = time.monotonic() + progress_timeout
                elif time.monotonic() > deadline:
                    st = self.server.master.stats()
                    raise TimeoutError(
                        f"no shard progress within {progress_timeout}s at "
                        f"pass {pass_id} step {step} "
                        f"({last_n}/{len(bounds)} shards, queue "
                        f"todo/pending/done/discarded={st[:4]}, "
                        f"{len(self.membership.members())} live member(s))")
            grads = dict(self._grads)
            losses = dict(self._losses)
            self._pending = None
        # reduce in shard-index order — THE byte-stability invariant: the
        # float sum must not depend on completion order or fleet shape
        weights = [r / n_rows for r in self._shard_rows]
        acc = None
        for j in range(len(bounds)):
            g = grads[j]
            acc = (jax.tree_util.tree_map(
                       lambda x, w=weights[j]: np.asarray(x, np.float32) * w,
                       g) if acc is None
                   else jax.tree_util.tree_map(
                       lambda a, x, w=weights[j]:
                       a + np.asarray(x, np.float32) * w, acc, g))
        if self.mesh is not None:
            with self.mesh:
                new_params, new_opt = self._update(acc, self._opt_state,
                                                   self._params)
        else:
            new_params, new_opt = self._update(acc, self._opt_state,
                                               self._params)
        with self._mu:
            self._params, self._opt_state = new_params, new_opt
            self._step = step + 1
            self._publish_state_locked()
        if self.snapshot_dir and (step + 1) % self.snapshot_every == 0:
            self._snapshot(pass_id, step, complete=False)
        # step loss: shard-weighted mean of the workers' reported losses
        # (same fixed reduce order — byte-stable like the grads)
        return float(sum(w * losses.get(j, float("nan"))
                         for j, w in enumerate(weights)))

    def _publish_state(self) -> None:
        with self._mu:
            self._publish_state_locked()

    def _publish_state_locked(self) -> None:
        # INVALIDATE only: the base64 tar of the whole tree (host gather
        # + CRC + encode) is built lazily by the first ela_state fetch of
        # this (pass, step) and cached — a step nobody syncs against
        # (idle fleet, master warming up) costs nothing
        self._state_blob = None

    def _snapshot(self, pass_id: int, step: int, *, complete: bool) -> None:
        save_checkpoint(self.snapshot_dir, pass_id, self._params,
                        self._opt_state,
                        extra={"pass_complete": complete,
                               "elastic_step": step,
                               "membership_epoch": self.membership.epoch})

    def _on_membership_change(self, view, *, joined, left, reason) -> None:
        """Re-bucket: requeue the departed members' in-flight shard tasks
        NOW instead of waiting out the dispatch timeout, and wake the fit
        loop so its progress deadline resets against the new fleet."""
        if left:
            requeued = 0
            with self._mu:
                for tid, w in list(self._assigned.items()):
                    if w in left:
                        del self._assigned[tid]
                        # failures count toward failure_max; the elastic
                        # default (100) keeps requeues from ever discarding
                        self.server.master.task_failed(tid)
                        requeued += 1
            if requeued:
                obs.count("cluster.rebucket_tasks_total", requeued)
                log.warning("membership %s (%s): requeued %d in-flight "
                            "shard task(s) -> epoch %d", reason,
                            ",".join(left), requeued, view["epoch"])
        with self._cv:
            self._cv.notify_all()

    # -- op handlers (native fallback threads) ------------------------------
    def _op_task(self, req):
        # the same deposed-master guard the mbr_* ops carry: a fenced
        # master handing out shards or accepting grads is the split-brain
        # membership fencing exists to stop (latent until a lease is
        # attached to the underlying MasterServer, but the guard must not
        # wait for that deployment to exist)
        fenced = self.membership._fenced_master()
        if fenced is not None:
            return fenced
        err = self.membership.validate(str(req.get("worker", "")),
                                       req.get("member_token"))
        if err is not None:
            obs.count("cluster.stale_rpcs_total", code=err["code"])
            return err
        with self._mu:
            if self._done:
                return {"ok": True, "task": None, "done": True,
                        "epoch": self.membership.epoch}
            t = self.server.master.get_task()
            resp = {"ok": True, "done": False,
                    "epoch": self.membership.epoch,
                    "pass": self._pass, "step": self._step}
            if t is None:
                resp["task"] = None
            else:
                self._assigned[t[0]] = str(req["worker"])
                resp["task"] = {"id": t[0], "payload": t[1]}
            return resp

    def _requeue_refused(self, req) -> None:
        """A fence-refused submission must not strand its task until the
        dispatch timeout: the shard is provably still needed (or the step
        moved on and the id is already gone — task_failed on an unknown id
        is a no-op), so requeue it NOW for a current worker."""
        tid = req.get("task_id")
        if tid is None:
            return
        with self._mu:
            self._assigned.pop(int(tid), None)
            self.server.master.task_failed(int(tid))

    def _op_grad(self, req):
        fenced = self.membership._fenced_master()
        if fenced is not None:
            return fenced
        worker = str(req.get("worker", ""))
        err = (self.membership.validate(worker, req.get("member_token"))
               or self.membership.fence(req.get("epoch")))
        if err is not None:
            if err["code"] != CODE_STALE_EPOCH:   # fence() already counted
                obs.count("cluster.stale_rpcs_total", code=err["code"])
            self._requeue_refused(req)
            return err
        with self._cv:
            key = (int(req.get("pass", -1)), int(req.get("step", -1)))
            if self._pending is None or key != self._pending:
                obs.count("cluster.stale_rpcs_total", code=CODE_STALE_STEP)
                tid = req.get("task_id")
                if tid is not None:
                    # current-step ids were cleared by set_dataset; a
                    # stale one is unknown to the queue — harmless
                    self._assigned.pop(int(tid), None)
                    self.server.master.task_failed(int(tid))
                return _err(CODE_STALE_STEP,
                            f"shard for pass/step {key} but the master is "
                            f"at {self._pending or (self._pass, self._step)}",
                            epoch=self.membership.epoch)
            shard = int(req["shard"])
            tid = req.get("task_id")
            if tid is not None:
                self._assigned.pop(int(tid), None)
                self.server.master.task_finished(int(tid))
            if shard in self._grads:
                return {"ok": True, "duplicate": True,
                        "epoch": self.membership.epoch}
            self._grads[shard] = _unpack_tree(req["grad"])
            if req.get("loss") is not None:
                self._losses[shard] = float(req["loss"])
            self._cv.notify_all()
        # feed the fleet health plane OUTSIDE the step lock: the worker-
        # reported shard wall time is the straggler score's raw signal
        # (obs/health.py; duplicates were answered above and don't count)
        el = req.get("elapsed_s")
        if el is not None:
            try:
                el = float(el)
            except (TypeError, ValueError):
                el = None
        if el is not None and el >= 0:
            obs.observe("cluster.shard_seconds", el, worker=worker)
            agg = getattr(self.server, "aggregator", None)
            if agg is not None and getattr(agg, "health", None) is not None:
                agg.health.note_shard(worker, el)
        return {"ok": True, "duplicate": False,
                "epoch": self.membership.epoch}

    def _op_state(self, req):
        with self._mu:
            if self._params is None:
                return {"ok": False, "error": "no state published yet"}
            if self._state_blob is None:
                self._state_blob = _pack_tree(self._params)
            return {"ok": True, "pass": self._pass, "step": self._step,
                    "epoch": self.membership.epoch,
                    "params": self._state_blob}

    def _op_status(self, req):
        st = self.status()
        st["ok"] = True
        return st


# -- worker ----------------------------------------------------------------------

class ElasticWorker:
    """A stateless elastic consumer: join → (heartbeat ‖ pull shard →
    grad → push) → leave. Holds only a replica of the canonical params,
    re-fetched and re-placed onto its LOCAL mesh/layout at every step or
    epoch barrier the master signals.
    """

    def __init__(self, loss_fn: Callable, endpoints, *,
                 worker: Optional[str] = None, mesh=None, layout=None,
                 poll: float = 0.02, retries: int = 8, caps=None,
                 clock: Callable[[], float] = time.monotonic):
        if isinstance(endpoints, tuple) and len(endpoints) == 2 and \
                isinstance(endpoints[1], int):
            endpoints = [endpoints]
        self.endpoints = list(endpoints)
        self.worker = worker or f"elastic-{uuid.uuid4().hex[:8]}"
        self.mesh = mesh
        self.layout = layout
        self.poll = poll
        self.caps = caps or {}
        self.retries = retries
        self.loss_fn = loss_fn
        # shard wall-time source (injectable: fake-clock chaos tests) —
        # the measured duration rides each ela_grad and feeds the
        # master-side straggler score (obs/health.py)
        self._shard_clock = clock
        self._vg = jax.jit(jax.value_and_grad(loss_fn))
        self._params = None
        self._version: Optional[Tuple[int, int]] = None
        self._resync = threading.Event()
        self.steps_contributed = 0
        self.shards_contributed = 0
        self.last_epoch = 0

    # -- state sync --------------------------------------------------------
    def _fetch_state(self, client) -> bool:
        """Pull + re-place the canonical params; False when the master has
        no state published yet (joined before fit() — wait, don't die)."""
        r = client._call({"op": "ela_state"})
        if not r.get("ok"):
            return False
        params = _unpack_tree(r["params"])
        # gather happened on the wire (host arrays); re-place onto OUR
        # mesh/layout — the PR 6 restore path, per worker
        if self.mesh is not None:
            from ..parallel.sharding import shard_params
            params = shard_params(params, self.mesh, self.layout)
        else:
            params = jax.device_put(params)
        self._params = params
        self._version = (int(r["pass"]), int(r["step"]))
        self.last_epoch = int(r["epoch"])
        obs.count("cluster.resyncs_total")
        return True

    def _timed_grad(self, payload: dict):
        """(loss, grads, elapsed_s) — the shard compute under the shard
        wall clock. The elapsed time rides the ela_grad push and feeds
        the master-side straggler score (obs/health.py), so the timing
        boundary and the chaos site live in ONE place."""
        t0 = self._shard_clock()
        loss, grads = self._grad_of(payload)
        return loss, grads, max(self._shard_clock() - t0, 0.0)

    def _grad_of(self, payload: dict):
        # the elastic shard twin of the trainer's step.grad chaos site: a
        # `delay` rule here makes THIS worker a straggler (its inflated
        # shard time rides the ela_grad push into the health plane); a
        # `raise` kills the shard like any injected worker failure
        faults.fire("step.grad")
        arrays = _unpack_arrays(payload["batch"])
        if self.mesh is not None:
            # data-sharding is an optimization, not a requirement: an
            # uneven shard (rows not divisible by the data axis — the
            # tail shard of a ragged partition) computes unsharded
            # rather than crashing the worker on a placement error
            rows = int(arrays[0].shape[0])
            n_data = int(np.prod(self.mesh.devices.shape))
            if rows % n_data == 0:
                from ..parallel.sharding import shard_batch
                arrays = shard_batch(tuple(arrays), self.mesh)
        loss, grads = self._vg(self._params, *arrays)
        return float(loss), jax.device_get(grads)

    # -- the loop ----------------------------------------------------------
    def run(self, stop: Optional[threading.Event] = None,
            max_seconds: Optional[float] = None) -> Dict[str, Any]:
        """Serve until the master reports the job done (or ``stop`` is
        set / ``max_seconds`` elapse). Returns a contribution summary."""
        stop = stop or threading.Event()
        deadline = (time.monotonic() + max_seconds
                    if max_seconds is not None else None)
        client = MembershipClient(endpoints=self.endpoints,
                                  retries=self.retries)
        token, epoch, reply = client.join(self.worker, self.caps)
        self.last_epoch = epoch
        keeper = HeartbeatKeeper(
            client, self.worker, token,
            ttl=float(reply.get("ttl", 5.0)),
            epoch=epoch, caps=self.caps,
            on_epoch=lambda e: self._resync.set(),
            on_rejoin=lambda t, e: self._resync.set(),
            on_lost=stop.set).start()
        done = False
        try:
            while not stop.is_set() and not done:
                if deadline is not None and time.monotonic() > deadline:
                    break
                try:
                    done = self._serve_once(client, keeper)
                except ConnectionError:
                    # reconnect budget spent (master restarting longer
                    # than one window): keep polling — the heartbeat
                    # keeper owns the give-up decision (on_lost)
                    time.sleep(self.poll)
        finally:
            keeper.stop()
            try:
                client.leave(self.worker, keeper.token)
            except Exception:  # noqa: BLE001 - master may already be gone
                pass
            client.close()
        return {"worker": self.worker, "done": done,
                "steps": self.steps_contributed,
                "shards": self.shards_contributed,
                "epoch": self.last_epoch}

    def _serve_once(self, client, keeper) -> bool:
        """One poll cycle; returns True when the master says done."""
        try:
            r = client._call({"op": "ela_task", "worker": self.worker,
                              "member_token": keeper.token})
        except StaleMemberError:
            # evicted / superseded: the keeper's heartbeat will re-join
            # (or declare the membership lost); don't hot-spin meanwhile
            time.sleep(self.poll)
            return False
        if r.get("done"):
            return True
        epoch = int(r.get("epoch", self.last_epoch))
        if epoch != self.last_epoch or self._resync.is_set():
            # membership changed: barrier here (the step boundary) and
            # re-place the canonical state before taking more work
            self._resync.clear()
            self.last_epoch = epoch
            if not self._fetch_state(client):
                self._resync.set()        # nothing published yet: re-ask
                time.sleep(self.poll)
                return False
        task = r.get("task")
        if task is None:
            time.sleep(self.poll)
            return False
        payload = json.loads(task["payload"])
        version = (int(payload["pass"]), int(payload["step"]))
        if self._version != version:
            if not self._fetch_state(client) or self._version != version:
                # the master moved past this shard while we synced; let
                # the dispatch timeout requeue it for someone current
                time.sleep(self.poll)
                return False
        loss, grads, elapsed = self._timed_grad(payload)
        try:
            resp = client._call({
                "op": "ela_grad", "worker": self.worker,
                "member_token": keeper.token, "epoch": self.last_epoch,
                "pass": version[0], "step": version[1],
                "shard": int(payload["shard"]), "task_id": task["id"],
                "loss": loss, "grad": _pack_tree(grads),
                "elapsed_s": elapsed})
        except StaleMemberError as e:
            if e.code == CODE_STALE_EPOCH or e.code == CODE_STALE_STEP:
                self._resync.set()
                if e.epoch is not None:
                    self.last_epoch = int(e.epoch)
                return False
            time.sleep(self.poll)
            return False
        if resp.get("ok") and not resp.get("duplicate"):
            self.shards_contributed += 1
            if int(payload["shard"]) == 0:
                self.steps_contributed += 1
        return False
