"""Streaming evaluators — the gserver Evaluator zoo re-provided.

Reference: abstract Evaluator with start/eval/finish accumulation
(gserver/evaluators/Evaluator.h:42; registry Evaluator.cpp:172-1357:
classification_error, sum, rank-AUC, precision-recall, chunk NER-F1, CTC error).

TPU-native: each evaluator owns small host-side accumulators; the per-batch
statistics are computed on device by ops/metrics.py (jit-fusable alongside the
train step) and merged here. ``result()`` returns a dict for events/logging.
"""

from __future__ import annotations

from typing import Dict, Optional

import jax.numpy as jnp
import numpy as np

from ..ops import metrics as M


class Evaluator:
    name = "evaluator"

    def start(self):
        raise NotImplementedError

    def update(self, **batch_outputs):
        raise NotImplementedError

    def result(self) -> Dict[str, float]:
        raise NotImplementedError


class ClassificationErrorEvaluator(Evaluator):
    """Error-rate (1 - accuracy), the default classification metric
    (Evaluator.cpp ClassificationErrorEvaluator)."""

    name = "classification_error"

    def __init__(self):
        self.start()

    def start(self):
        self.wrong = 0.0
        self.total = 0.0

    def update(self, logits=None, labels=None, correct=None, count=None, **_):
        if correct is None:
            correct, count = M.accuracy(logits, labels)
        self.wrong += float(count) - float(correct)
        self.total += float(count)

    def result(self):
        err = self.wrong / max(self.total, 1.0)
        return {"classification_error": err, "accuracy": 1.0 - err}


class SumEvaluator(Evaluator):
    """Accumulate a scalar (cost) across batches (Evaluator.cpp SumEvaluator)."""

    name = "sum"

    def __init__(self, key: str = "cost"):
        self.key = key
        self.start()

    def start(self):
        self.total = 0.0
        self.count = 0

    def update(self, **kw):
        v = kw.get(self.key)
        if v is not None:
            self.total += float(v)
            self.count += 1

    def result(self):
        return {f"avg_{self.key}": self.total / max(self.count, 1),
                f"sum_{self.key}": self.total}


class AucEvaluator(Evaluator):
    """Rank-AUC via fixed-threshold histograms (AucEvaluator analog) — the
    histogram update runs on device (ops/metrics.py:auc_histogram)."""

    name = "auc"

    def __init__(self, num_thresholds: int = 200):
        self.n = num_thresholds
        self.start()

    def start(self):
        self.pos = np.zeros(self.n, np.float64)
        self.neg = np.zeros(self.n, np.float64)

    def update(self, probs=None, labels=None, **_):
        p, n = M.auc_histogram(probs, labels, self.n)
        self.pos += np.asarray(p, np.float64)
        self.neg += np.asarray(n, np.float64)

    def result(self):
        auc = M.auc_from_histogram(jnp.asarray(self.pos), jnp.asarray(self.neg))
        return {"auc": float(auc)}


class PrecisionRecallEvaluator(Evaluator):
    """Per-class and macro precision/recall/F1 (PrecisionRecallEvaluator)."""

    name = "precision_recall"

    def __init__(self, num_classes: int):
        self.num_classes = num_classes
        self.start()

    def start(self):
        self.tp = np.zeros(self.num_classes, np.float64)
        self.fp = np.zeros(self.num_classes, np.float64)
        self.fn = np.zeros(self.num_classes, np.float64)

    def update(self, pred=None, labels=None, **_):
        counts = np.asarray(M.precision_recall_counts(pred, labels,
                                                      self.num_classes), np.float64)
        self.tp += counts[:, 0]
        self.fp += counts[:, 1]
        self.fn += counts[:, 2]

    def result(self):
        prec = self.tp / np.maximum(self.tp + self.fp, 1.0)
        rec = self.tp / np.maximum(self.tp + self.fn, 1.0)
        f1 = 2 * prec * rec / np.maximum(prec + rec, 1e-12)
        return {"macro_precision": float(prec.mean()),
                "macro_recall": float(rec.mean()),
                "macro_f1": float(f1.mean())}


class ChunkEvaluator(Evaluator):
    """Chunk (NER) F1 over IOB tags (ChunkEvaluator.cpp analog)."""

    name = "chunk"

    def __init__(self, num_tag_types: int, scheme: str = "IOB"):
        self.num_tag_types = num_tag_types
        self.scheme = scheme
        self.start()

    def start(self):
        self.n_pred = 0.0
        self.n_label = 0.0
        self.n_correct = 0.0

    def update(self, pred_tags=None, label_tags=None, lengths=None, **_):
        nc, np_, nl = M.chunk_count(pred_tags, label_tags, lengths,
                                    scheme=self.scheme,
                                    num_chunk_types=self.num_tag_types)
        self.n_pred += float(np_)
        self.n_label += float(nl)
        self.n_correct += float(nc)

    def result(self):
        p = self.n_correct / max(self.n_pred, 1.0)
        r = self.n_correct / max(self.n_label, 1.0)
        f1 = 2 * p * r / max(p + r, 1e-12)
        return {"chunk_precision": p, "chunk_recall": r, "chunk_f1": f1}


class EvaluatorGroup:
    """Evaluator composition the way NeuralNetwork combines them
    (gserver combined evaluator): start/update/result fan out."""

    def __init__(self, *evaluators: Evaluator):
        self.evaluators = list(evaluators)

    def start(self):
        for e in self.evaluators:
            e.start()

    def update(self, **kw):
        for e in self.evaluators:
            e.update(**kw)

    def result(self) -> Dict[str, float]:
        out: Dict[str, float] = {}
        for e in self.evaluators:
            out.update(e.result())
        return out


class CTCErrorEvaluator(Evaluator):
    """Sequence error rate: edit distance between CTC greedy decodes and
    label sequences over total label length (CTCErrorEvaluator.cpp)."""

    name = "ctc_error"

    def __init__(self, blank: int = 0):
        self.blank = blank
        self.start()

    def start(self):
        self.dist = 0.0
        self.label_len = 0.0
        self.seq_errs = 0.0
        self.n_seq = 0.0

    def update(self, log_probs=None, logit_lengths=None, labels=None,
               label_lengths=None, decoded=None, decoded_lengths=None, **_):
        from ..ops.ctc import ctc_greedy_decode
        if decoded is None:
            decoded, decoded_lengths = ctc_greedy_decode(
                log_probs, logit_lengths, blank=self.blank)
        d = np.asarray(M.edit_distance(decoded, decoded_lengths, labels,
                                       label_lengths), np.float64)
        self.dist += float(d.sum())
        self.label_len += float(np.asarray(label_lengths).sum())
        self.seq_errs += float((d > 0).sum())
        self.n_seq += d.shape[0]

    def result(self):
        return {"ctc_error_rate": self.dist / max(self.label_len, 1.0),
                "ctc_seq_error": self.seq_errs / max(self.n_seq, 1.0)}


class PnpairEvaluator(Evaluator):
    """Positive-negative pair ordering (PnpairEvaluator.cpp): ratio of
    correctly-ordered same-query pairs; ties count half."""

    name = "pnpair"

    def __init__(self):
        self.start()

    def start(self):
        self.pos = 0.0
        self.neg = 0.0
        self.spe = 0.0

    def update(self, scores=None, labels=None, query_ids=None, **_):
        p, n, s = M.pnpair_counts(jnp.ravel(scores), jnp.ravel(labels),
                                  jnp.ravel(query_ids))
        self.pos += float(p)
        self.neg += float(n)
        self.spe += float(s)

    def result(self):
        denom = max(self.neg + self.spe / 2.0, 1e-12)
        return {"pnpair_ratio": (self.pos + self.spe / 2.0) / denom,
                "pnpair_pos": self.pos, "pnpair_neg": self.neg}


class DetectionMAPEvaluator(Evaluator):
    """Mean average precision over detection outputs
    (DetectionMAPEvaluator.cpp, integral mode).

    update() takes per-image detections [N, 6] rows (class, score, x1, y1,
    x2, y2) — the detection_output op's format — and ground truth [M, 5]
    rows (class, x1, y1, x2, y2)."""

    name = "detection_map"

    def __init__(self, num_classes: int, iou_threshold: float = 0.5,
                 background: int = 0):
        self.num_classes = num_classes
        self.iou = iou_threshold
        self.background = background
        self.start()

    def start(self):
        self.scores = {c: [] for c in range(self.num_classes)}
        self.matched = {c: [] for c in range(self.num_classes)}
        self.n_gt = {c: 0 for c in range(self.num_classes)}

    def update(self, detections=None, gt=None, **_):
        from ..ops.detection import iou_matrix
        det = np.asarray(detections, np.float64)
        gts = np.asarray(gt, np.float64)
        for c in range(self.num_classes):
            if c == self.background:
                continue
            d = det[det[:, 0] == c]
            g = gts[gts[:, 0] == c]
            self.n_gt[c] += len(g)
            if len(d) == 0:
                continue
            d = d[np.argsort(-d[:, 1])]
            taken = np.zeros(len(g), bool)
            # ONE batched [D, M] IoU call per class (not per detection row)
            all_ious = (np.asarray(iou_matrix(jnp.asarray(d[:, 2:6]),
                                              jnp.asarray(g[:, 1:5])))
                        if len(g) else np.zeros((len(d), 0)))
            for row, ious in zip(d, all_ious):
                self.scores[c].append(row[1])
                if ious.size == 0:
                    self.matched[c].append(0.0)
                    continue
                best = int(ious.argmax())
                if ious[best] >= self.iou and not taken[best]:
                    taken[best] = True
                    self.matched[c].append(1.0)
                else:
                    self.matched[c].append(0.0)

    def result(self):
        aps = []
        for c in range(self.num_classes):
            if c == self.background or self.n_gt[c] == 0:
                continue
            aps.append(M.average_precision(self.scores[c], self.matched[c],
                                           self.n_gt[c]))
        return {"detection_map": float(np.mean(aps)) if aps else 0.0}


class ValuePrinterEvaluator(Evaluator):
    """Printer evaluator (Evaluator.cpp ValuePrinter): logs a named batch
    output every ``period`` updates — debugging aid, contributes no metric."""

    name = "value_printer"

    def __init__(self, key: str, period: int = 1, max_items: int = 8,
                 log_fn=None):
        from ..utils.logging import get_logger
        self.key = key
        self.period = period
        self.max_items = max_items
        self.log = log_fn or get_logger(__name__).info
        self.start()

    def start(self):
        self.n = 0

    def update(self, **kw):
        self.n += 1
        if self.key in kw and self.n % self.period == 0:
            v = np.asarray(kw[self.key])
            self.log("value_printer[%s] shape=%s head=%s", self.key, v.shape,
                     np.ravel(v)[: self.max_items])

    def result(self):
        return {}


class MaxIdPrinterEvaluator(Evaluator):
    """Printer (Evaluator.cpp MaxIdPrinter): logs argmax ids of an output."""

    name = "max_id_printer"

    def __init__(self, key: str = "logits", period: int = 1, max_items: int = 8,
                 log_fn=None):
        from ..utils.logging import get_logger
        self.key = key
        self.period = period
        self.max_items = max_items
        self.log = log_fn or get_logger(__name__).info
        self.start()

    def start(self):
        self.n = 0

    def update(self, **kw):
        self.n += 1
        if self.key in kw and self.n % self.period == 0:
            ids = np.asarray(kw[self.key]).argmax(-1)
            self.log("max_id[%s]: %s", self.key,
                     np.ravel(ids)[: self.max_items])

    def result(self):
        return {}
