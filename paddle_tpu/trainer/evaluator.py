"""Streaming evaluators — the gserver Evaluator zoo re-provided.

Reference: abstract Evaluator with start/eval/finish accumulation
(gserver/evaluators/Evaluator.h:42; registry Evaluator.cpp:172-1357:
classification_error, sum, rank-AUC, precision-recall, chunk NER-F1, CTC error).

TPU-native: each evaluator owns small host-side accumulators; the per-batch
statistics are computed on device by ops/metrics.py (jit-fusable alongside the
train step) and merged here. ``result()`` returns a dict for events/logging.
"""

from __future__ import annotations

from typing import Dict, Optional

import jax.numpy as jnp
import numpy as np

from ..ops import metrics as M


class Evaluator:
    name = "evaluator"

    def start(self):
        raise NotImplementedError

    def update(self, **batch_outputs):
        raise NotImplementedError

    def result(self) -> Dict[str, float]:
        raise NotImplementedError


class ClassificationErrorEvaluator(Evaluator):
    """Error-rate (1 - accuracy), the default classification metric
    (Evaluator.cpp ClassificationErrorEvaluator)."""

    name = "classification_error"

    def __init__(self):
        self.start()

    def start(self):
        self.wrong = 0.0
        self.total = 0.0

    def update(self, logits=None, labels=None, correct=None, count=None, **_):
        if correct is None:
            correct, count = M.accuracy(logits, labels)
        self.wrong += float(count) - float(correct)
        self.total += float(count)

    def result(self):
        err = self.wrong / max(self.total, 1.0)
        return {"classification_error": err, "accuracy": 1.0 - err}


class SumEvaluator(Evaluator):
    """Accumulate a scalar (cost) across batches (Evaluator.cpp SumEvaluator)."""

    name = "sum"

    def __init__(self, key: str = "cost"):
        self.key = key
        self.start()

    def start(self):
        self.total = 0.0
        self.count = 0

    def update(self, **kw):
        v = kw.get(self.key)
        if v is not None:
            self.total += float(v)
            self.count += 1

    def result(self):
        return {f"avg_{self.key}": self.total / max(self.count, 1),
                f"sum_{self.key}": self.total}


class AucEvaluator(Evaluator):
    """Rank-AUC via fixed-threshold histograms (AucEvaluator analog) — the
    histogram update runs on device (ops/metrics.py:auc_histogram)."""

    name = "auc"

    def __init__(self, num_thresholds: int = 200):
        self.n = num_thresholds
        self.start()

    def start(self):
        self.pos = np.zeros(self.n, np.float64)
        self.neg = np.zeros(self.n, np.float64)

    def update(self, probs=None, labels=None, **_):
        p, n = M.auc_histogram(probs, labels, self.n)
        self.pos += np.asarray(p, np.float64)
        self.neg += np.asarray(n, np.float64)

    def result(self):
        auc = M.auc_from_histogram(jnp.asarray(self.pos), jnp.asarray(self.neg))
        return {"auc": float(auc)}


class PrecisionRecallEvaluator(Evaluator):
    """Per-class and macro precision/recall/F1 (PrecisionRecallEvaluator)."""

    name = "precision_recall"

    def __init__(self, num_classes: int):
        self.num_classes = num_classes
        self.start()

    def start(self):
        self.tp = np.zeros(self.num_classes, np.float64)
        self.fp = np.zeros(self.num_classes, np.float64)
        self.fn = np.zeros(self.num_classes, np.float64)

    def update(self, pred=None, labels=None, **_):
        counts = np.asarray(M.precision_recall_counts(pred, labels,
                                                      self.num_classes), np.float64)
        self.tp += counts[:, 0]
        self.fp += counts[:, 1]
        self.fn += counts[:, 2]

    def result(self):
        prec = self.tp / np.maximum(self.tp + self.fp, 1.0)
        rec = self.tp / np.maximum(self.tp + self.fn, 1.0)
        f1 = 2 * prec * rec / np.maximum(prec + rec, 1e-12)
        return {"macro_precision": float(prec.mean()),
                "macro_recall": float(rec.mean()),
                "macro_f1": float(f1.mean())}


class ChunkEvaluator(Evaluator):
    """Chunk (NER) F1 over IOB tags (ChunkEvaluator.cpp analog)."""

    name = "chunk"

    def __init__(self, num_tag_types: int, scheme: str = "IOB"):
        self.num_tag_types = num_tag_types
        self.scheme = scheme
        self.start()

    def start(self):
        self.n_pred = 0.0
        self.n_label = 0.0
        self.n_correct = 0.0

    def update(self, pred_tags=None, label_tags=None, lengths=None, **_):
        nc, np_, nl = M.chunk_count(pred_tags, label_tags, lengths,
                                    scheme=self.scheme,
                                    num_chunk_types=self.num_tag_types)
        self.n_pred += float(np_)
        self.n_label += float(nl)
        self.n_correct += float(nc)

    def result(self):
        p = self.n_correct / max(self.n_pred, 1.0)
        r = self.n_correct / max(self.n_label, 1.0)
        f1 = 2 * p * r / max(p + r, 1e-12)
        return {"chunk_precision": p, "chunk_recall": r, "chunk_f1": f1}


class EvaluatorGroup:
    """Evaluator composition the way NeuralNetwork combines them
    (gserver combined evaluator): start/update/result fan out."""

    def __init__(self, *evaluators: Evaluator):
        self.evaluators = list(evaluators)

    def start(self):
        for e in self.evaluators:
            e.start()

    def update(self, **kw):
        for e in self.evaluators:
            e.update(**kw)

    def result(self) -> Dict[str, float]:
        out: Dict[str, float] = {}
        for e in self.evaluators:
            out.update(e.result())
        return out
