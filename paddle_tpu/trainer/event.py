"""Training events delivered to user callbacks.

Same event set as the reference's v2 API (python/paddle/v2/event.py:
BeginPass/EndPass/BeginIteration/EndIteration/TestResult), fired from the
train loop at the same points (v2/trainer.py:124-202).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, Optional


@dataclass
class BeginPass:
    pass_id: int


@dataclass
class EndPass:
    pass_id: int
    evaluator_result: Optional[Dict[str, float]] = None


@dataclass
class BeginIteration:
    pass_id: int
    batch_id: int


@dataclass
class EndIteration:
    pass_id: int
    batch_id: int
    cost: float
    evaluator_result: Optional[Dict[str, float]] = None
    metrics: Dict[str, Any] = field(default_factory=dict)


@dataclass
class TestResult:
    pass_id: int
    cost: float
    evaluator_result: Optional[Dict[str, float]] = None
