"""Training-curve plotter (python/paddle/v2/plot/plot.py:32 Ploter analog).

Collects (step, value) series per title and renders via matplotlib when
available (``plot.py`` falls back to text in non-notebook contexts; here the
fallback is a no-op draw with the data still query-able for tests/tools).
"""

from __future__ import annotations

from typing import Dict, List, Tuple


class Ploter:
    def __init__(self, *titles: str):
        self.titles = list(titles)
        self.data: Dict[str, Tuple[List[float], List[float]]] = {
            t: ([], []) for t in titles}

    def append(self, title: str, step: float, value: float):
        xs, ys = self.data[title]
        xs.append(step)
        ys.append(value)

    def reset(self):
        for t in self.titles:
            self.data[t] = ([], [])

    def plot(self, path: str = None):
        try:
            import matplotlib
            matplotlib.use("Agg")
            import matplotlib.pyplot as plt
        except Exception:
            return None
        fig, ax = plt.subplots()
        for t in self.titles:
            xs, ys = self.data[t]
            ax.plot(xs, ys, label=t)
        ax.set_xlabel("step")
        ax.legend()
        if path:
            fig.savefig(path)
        plt.close(fig)
        return path
