"""SGD training driver.

Re-provides the reference's two drivers as one:
* C++ Trainer: pass/batch loops, evaluator wiring, testing, gradient check,
  per-pass checkpoints (trainer/Trainer.cpp:265, TrainerInternal.cpp:66-172,
  Tester.cpp, ParamUtil.cpp:50-67, --job=train/test/checkgrad/time
  TrainerMain.cpp:54).
* Python v2 SGD: events to user callbacks, reader-driven batches
  (v2/trainer.py:124-202).

TPU-native: the batch step is ONE jitted function (forward+backward+update fused
by XLA; the reference's per-parameter update callback pipelining,
TrainerInternal.cpp:70-73, is recovered by XLA's latency-hiding scheduler); data
parallelism is the SPMD mesh (parallel/data_parallel.py), not trainer threads;
host-side prep overlaps via DoubleBuffer.
"""

from __future__ import annotations

import time
from typing import Any, Callable, Dict, Iterable, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..data.prefetch import DoubleBuffer
from ..parallel.data_parallel import DataParallel
from ..utils.logging import get_logger
from ..utils.stats import StatSet
from . import event as EV
from .checkpoint import latest_pass, load_checkpoint, save_checkpoint
from .evaluator import EvaluatorGroup

log = get_logger(__name__)


class Trainer:
    """Drive (loss_fn, optimizer) over reader batches with events/evaluators.

    Args:
      loss_fn: (params, *batch) -> scalar loss.
      optimizer: paddle_tpu optimizer.
      mesh: optional jax Mesh -> SPMD data-parallel step over its 'data' axis.
      outputs_fn: optional (params, *batch) -> dict of device metrics handed to
        evaluators (e.g. {'logits':..., 'labels':...}). Evaluated INSIDE the
        fused train step on the PRE-update parameters — the reference's
        semantics (TrainerInternal.cpp:144-148 evaluates the training
        forward's outputs, which precede the update) and one forward cheaper
        than a separate post-update pass.
      evaluators: EvaluatorGroup or list of Evaluators.
      output_dir: if set, save pass-%05d checkpoints (ParamUtil semantics).
    """

    def __init__(self, loss_fn: Callable, optimizer, *, mesh=None,
                 outputs_fn: Optional[Callable] = None,
                 evaluators=None, output_dir: Optional[str] = None,
                 prefetch: int = 2, log_period: int = 0,
                 param_stats_period: int = 0,
                 nan_guard: bool = True):
        self.loss_fn = loss_fn
        self.opt = optimizer
        self.outputs_fn = jax.jit(outputs_fn) if outputs_fn is not None else None
        if evaluators is None:
            self.evaluators = EvaluatorGroup()
        elif isinstance(evaluators, EvaluatorGroup):
            self.evaluators = evaluators
        else:
            self.evaluators = EvaluatorGroup(*evaluators)
        self.output_dir = output_dir
        self.prefetch = prefetch
        self.log_period = log_period
        # --show_parameter_stats_period analog (TrainerInternal.cpp:80-87):
        # 0 = off; falls back to the global flag when unset
        if param_stats_period == 0:
            from ..utils.flags import FLAGS
            param_stats_period = FLAGS.show_parameter_stats_period
        self.param_stats_period = param_stats_period
        self.nan_guard = nan_guard
        self.stats = StatSet()
        self.mesh = mesh
        if mesh is not None:
            self._dp = DataParallel(loss_fn, optimizer, mesh=mesh,
                                    aux_fn=outputs_fn)
            self._step = None
        else:
            self._dp = None

            def _step(params, opt_state, *batch):
                loss, grads = jax.value_and_grad(loss_fn)(params, *batch)
                # eval outputs computed inside the SAME jitted step (XLA
                # shares the forward) — no second per-batch forward dispatch
                outs = outputs_fn(params, *batch) if outputs_fn else None
                params, opt_state = optimizer.update(grads, opt_state, params)
                if outputs_fn is not None:
                    return params, opt_state, loss, outs
                return params, opt_state, loss

            self._step = jax.jit(_step, donate_argnums=(0, 1))
        self._loss_jit = jax.jit(loss_fn)

    # ------------------------------------------------------------------ train
    def _log_param_stats(self, params):
        """Per-parameter magnitude dump — the --show_parameter_stats_period
        observability of TrainerInternal.cpp:80-87,156 (value stats; grads
        are not retained past the fused update step)."""
        from ..nn.module import Module
        for name, value in Module.named_parameters(jax.device_get(params)):
            a = np.abs(np.asarray(value, np.float32))
            log.info("param %-40s shape=%-16s absmax=%.4e absmean=%.4e",
                     name, str(tuple(a.shape)), float(a.max(initial=0.0)),
                     float(a.mean()) if a.size else 0.0)

    def train(self, reader: Callable[[], Iterable], params, *,
              num_passes: int = 1, event_handler: Optional[Callable] = None,
              feeder: Optional[Callable] = None,
              test_reader: Optional[Callable] = None,
              resume: bool = False):
        """Run the pass/batch loop; returns (params, opt_state).

        reader yields raw row-batches; ``feeder`` converts one row-batch to the
        loss_fn's *batch arrays (identity if None).
        """
        event_handler = event_handler or (lambda e: None)
        start_pass = 0
        opt_state = None
        if resume and self.output_dir and latest_pass(self.output_dir) is not None:
            params, opt_state, st = load_checkpoint(self.output_dir)
            start_pass = st["pass_id"] + 1
            log.info("resumed from pass %d", st["pass_id"])
        if opt_state is None:
            if self._dp is not None:
                params, opt_state = self._dp.init(params)
            else:
                opt_state = self.opt.init(params)
        elif self._dp is not None:
            params, opt_state = self._dp.init(params, opt_state)

        for pass_id in range(start_pass, start_pass + num_passes):
            event_handler(EV.BeginPass(pass_id))
            self.evaluators.start()
            batches = self._batches(reader, feeder)
            for batch_id, batch in enumerate(batches):
                event_handler(EV.BeginIteration(pass_id, batch_id))
                with self.stats.timer("TrainBatch"):
                    if self._dp is not None:
                        batch = self._dp.shard_batch(batch)
                        res = self._dp.step(params, opt_state, *batch)
                    else:
                        res = self._step(params, opt_state, *batch)
                if self.outputs_fn is not None:
                    params, opt_state, cost, outs = res
                else:
                    params, opt_state, cost = res
                    outs = None
                cost_f = float(cost)
                if self.nan_guard and not np.isfinite(cost_f):
                    # the feenableexcept(FE_INVALID|DIVBYZERO|OVERFLOW) analog
                    # (TrainerMain.cpp:49): fail fast, don't train on garbage
                    raise FloatingPointError(
                        f"non-finite loss {cost_f} at pass {pass_id} batch "
                        f"{batch_id}; re-run with "
                        f"jax.config.update('jax_debug_nans', True) to locate "
                        f"the producing op")
                ev_result = None
                if outs is not None:
                    with self.stats.timer("Eval"):
                        self.evaluators.update(cost=cost_f, **outs)
                        ev_result = self.evaluators.result()
                if self.log_period and (batch_id + 1) % self.log_period == 0:
                    log.info("pass %d batch %d cost %.6f", pass_id, batch_id, cost_f)
                if (self.param_stats_period and
                        (batch_id + 1) % self.param_stats_period == 0):
                    self._log_param_stats(params)
                event_handler(EV.EndIteration(pass_id, batch_id, cost_f,
                                              ev_result))
            pass_result = (self.evaluators.result()
                           if self.outputs_fn is not None else None)
            if test_reader is not None:
                tr = self.test(test_reader, params, feeder=feeder)
                event_handler(EV.TestResult(pass_id, tr["cost"],
                                            tr.get("evaluator_result")))
            if self.output_dir:
                save_checkpoint(self.output_dir, pass_id, params, opt_state)
            event_handler(EV.EndPass(pass_id, pass_result))
        return params, opt_state

    def _batches(self, reader, feeder):
        if feeder is None:
            return iter(reader())
        return iter(DoubleBuffer(reader, depth=self.prefetch, transform=feeder))

    # ------------------------------------------------------------------- test
    def test(self, reader, params, *, feeder=None) -> Dict[str, Any]:
        """Average cost (+ evaluator results) over a test reader (Tester.cpp)."""
        total, n = 0.0, 0
        self.evaluators.start()
        for batch in self._batches(reader, feeder):
            cost = self._loss_jit(params, *batch)
            total += float(cost)
            n += 1
            if self.outputs_fn is not None:
                outs = self.outputs_fn(params, *batch)
                self.evaluators.update(cost=float(cost), **outs)
        out: Dict[str, Any] = {"cost": total / max(n, 1)}
        if self.outputs_fn is not None:
            out["evaluator_result"] = self.evaluators.result()
        return out

    # -------------------------------------------------------------- checkgrad
    def check_gradient(self, params, batch: Tuple, *, eps: float = 1e-3,
                       rtol: float = 5e-2, max_checks_per_param: int = 5,
                       seed: int = 0) -> bool:
        """Central-difference gradient check (--job=checkgrad,
        Trainer.h:84; LayerGradUtil perturbation semantics, SURVEY §4.1).
        Runs in float64 (enable_x64) — float32 losses don't resolve the
        perturbation; returns True when analytic and numeric agree."""
        import contextlib

        @contextlib.contextmanager
        def enable_x64():
            prev = jax.config.jax_enable_x64
            jax.config.update("jax_enable_x64", True)
            try:
                yield
            finally:
                jax.config.update("jax_enable_x64", prev)

        def to64(x):
            x = np.asarray(jax.device_get(x))
            return x.astype(np.float64) if np.issubdtype(x.dtype, np.floating) else x

        with enable_x64():
            params64 = jax.tree_util.tree_map(to64, params)
            batch64 = jax.tree_util.tree_map(to64, batch)
            loss64 = jax.jit(self.loss_fn)
            grads = jax.jit(jax.grad(self.loss_fn))(params64, *batch64)
            leaves, treedef = jax.tree_util.tree_flatten(params64)
            gleaves = jax.tree_util.tree_leaves(grads)
            rs = np.random.RandomState(seed)
            ok = True
            for li, (p, g) in enumerate(zip(leaves, gleaves)):
                p_host = np.asarray(jax.device_get(p), np.float64)
                flat = p_host.reshape(-1)
                n_checks = min(max_checks_per_param, flat.size)
                for idx in rs.choice(flat.size, size=n_checks, replace=False):
                    orig = flat[idx]
                    vals = {}
                    for sign in (+1, -1):
                        flat[idx] = orig + sign * eps
                        leaves2 = list(leaves)
                        leaves2[li] = jnp.asarray(p_host)
                        vals[sign] = float(loss64(
                            jax.tree_util.tree_unflatten(treedef, leaves2),
                            *batch64))
                    flat[idx] = orig
                    numeric = (vals[+1] - vals[-1]) / (2 * eps)
                    analytic = float(np.asarray(jax.device_get(g)).reshape(-1)[idx])
                    denom = max(abs(numeric), abs(analytic), 1e-6)
                    if abs(numeric - analytic) / denom > rtol:
                        log.warning("checkgrad mismatch leaf %d idx %d: "
                                    "numeric %.6g analytic %.6g", li, idx,
                                    numeric, analytic)
                        ok = False
        return ok

    # ------------------------------------------------------------------- time
    def benchmark(self, reader, params, *, feeder=None, warmup: int = 3,
                  iters: int = 20,
                  profile_dir: Optional[str] = None) -> Dict[str, float]:
        """--job=time analog (TrainerBenchmark.cpp): steady-state ms/batch.
        ``profile_dir`` wraps the timed loop in an XLA trace
        (utils/profiler — the hl_profiler_start/WITH_PROFILER analog)."""
        opt_state = self.opt.init(params) if self._dp is None else None
        if self._dp is not None:
            params, opt_state = self._dp.init(params)
        batches = list(self._batches(reader, feeder))
        if not batches:
            raise ValueError("empty reader")
        step = (self._step if self._dp is None
                else lambda p, s, *b: self._dp.step(p, s, *b))
        i = 0
        for _ in range(warmup):
            res = step(params, opt_state, *batches[i % len(batches)])
            params, opt_state, loss = res[0], res[1], res[2]
            i += 1
        jax.block_until_ready(loss)
        from ..utils import profiler as _prof
        import contextlib
        prof_cm = (_prof.profile(profile_dir) if profile_dir
                   else contextlib.nullcontext())
        with prof_cm:
            t0 = time.perf_counter()
            for _ in range(iters):
                with self.stats.timer("BenchBatch"):
                    res = step(params, opt_state, *batches[i % len(batches)])
                    params, opt_state, loss = res[0], res[1], res[2]
                i += 1
            jax.block_until_ready(loss)
            # timed INSIDE the profiler context: stop_trace() serialization
            # must not inflate the reported steady-state number
            ms = (time.perf_counter() - t0) / iters * 1e3
        return {"ms_per_batch": ms}
