"""SGD training driver.

Re-provides the reference's two drivers as one:
* C++ Trainer: pass/batch loops, evaluator wiring, testing, gradient check,
  per-pass checkpoints (trainer/Trainer.cpp:265, TrainerInternal.cpp:66-172,
  Tester.cpp, ParamUtil.cpp:50-67, --job=train/test/checkgrad/time
  TrainerMain.cpp:54).
* Python v2 SGD: events to user callbacks, reader-driven batches
  (v2/trainer.py:124-202).

TPU-native: the batch step is ONE jitted function (forward+backward+update fused
by XLA; the reference's per-parameter update callback pipelining,
TrainerInternal.cpp:70-73, is recovered by XLA's latency-hiding scheduler); data
parallelism is the SPMD mesh (parallel/data_parallel.py), not trainer threads;
host-side prep overlaps via DoubleBuffer.
"""

from __future__ import annotations

import itertools
import signal
import threading
import time
from collections.abc import Mapping
from typing import Any, Callable, Dict, Iterable, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .. import faults, obs
from ..obs.goodput import maybe_bucket
from ..data.prefetch import DoubleBuffer
from ..parallel.data_parallel import DataParallel
from ..utils.logging import get_logger
from ..utils.stats import StatSet
from . import event as EV
from .checkpoint import load_checkpoint, save_checkpoint
from .evaluator import EvaluatorGroup

log = get_logger(__name__)

_NONFINITE_POLICIES = ("raise", "skip", "halt", "off")


def _timed_input(batches, gp):
    """Yield from ``batches`` timing each pull into the goodput ledger's
    ``host_input`` bucket — the reader/feeder wait as the driver loop
    experiences it (prefetch overlap shows up as near-zero pulls)."""
    it = iter(batches)
    while True:
        with gp.bucket("host_input"):
            try:
                batch = next(it)
            except StopIteration:
                return
        yield batch


class _TrainStatsView(Mapping):
    """Read-only compatibility view of the legacy ``train_stats`` dict.

    The robustness counters moved to typed obs counters on the trainer's
    own registry (ISSUE 3); existing callers and tests keep reading the
    old keys through this Mapping. It is intentionally not writable —
    the counters are the single source of truth."""

    _KEYS = {"nonfinite_batches": "trainer.nonfinite_total",
             "skipped_batches": "trainer.skipped_total",
             "preemptions": "trainer.preemptions_total"}

    def __init__(self, registry: obs.MetricsRegistry):
        self._registry = registry

    def __getitem__(self, key: str) -> int:
        return int(self._registry.counter(self._KEYS[key]).get())

    def __iter__(self):
        return iter(self._KEYS)

    def __len__(self) -> int:
        return len(self._KEYS)

    def __repr__(self):
        return repr(dict(self))


class Trainer:
    """Drive (loss_fn, optimizer) over reader batches with events/evaluators.

    Args:
      loss_fn: (params, *batch) -> scalar loss.
      optimizer: paddle_tpu optimizer.
      mesh: optional jax Mesh -> SPMD data-parallel step over its 'data' axis.
      layout: optional :class:`paddle_tpu.parallel.SpecLayout` (or
        ShardingRules) resolving parameter paths to PartitionSpecs —
        params and optimizer slots shard across the mesh (fsdp/tp) instead
        of replicating, and checkpoint restore re-places them onto the
        current mesh via the same rules.
      outputs_fn: optional (params, *batch) -> dict of device metrics handed to
        evaluators (e.g. {'logits':..., 'labels':...}). Evaluated INSIDE the
        fused train step on the PRE-update parameters — the reference's
        semantics (TrainerInternal.cpp:144-148 evaluates the training
        forward's outputs, which precede the update) and one forward cheaper
        than a separate post-update pass.
      evaluators: EvaluatorGroup or list of Evaluators.
      output_dir: if set, save pass-%05d checkpoints (ParamUtil semantics).
      nan_guard: legacy on/off switch for the non-finite-loss check.
      on_nonfinite: what a non-finite loss does — "raise" (fail fast, the
        feenableexcept analog), "skip" (drop the batch's update, count it,
        warn), "halt" (drop the update, checkpoint the last finite state,
        then raise), or "off". Defaults to "raise" when nan_guard else
        "off".
      prefetch_timeout: watchdog on the prefetch DoubleBuffer — if no batch
        arrives within this many seconds, raise TimeoutError instead of
        hanging the pod (a stalled data source on a TPU slice otherwise
        wedges every chip behind the collective).
      metrics: injectable :class:`paddle_tpu.obs.MetricsRegistry` backing
        the robustness counters (``trainer.nonfinite_total`` etc.) and the
        ``train_stats`` compatibility view; a fresh per-trainer registry
        by default so parallel trainers don't share counts. Hot-path step
        metrics additionally flow to the installed obs session (zero-cost
        when none is).
    """

    def __init__(self, loss_fn: Callable, optimizer, *, mesh=None,
                 layout=None,
                 outputs_fn: Optional[Callable] = None,
                 evaluators=None, output_dir: Optional[str] = None,
                 prefetch: int = 2, log_period: int = 0,
                 param_stats_period: int = 0,
                 nan_guard: bool = True,
                 on_nonfinite: Optional[str] = None,
                 prefetch_timeout: Optional[float] = None,
                 metrics: Optional[obs.MetricsRegistry] = None):
        self.loss_fn = loss_fn
        self.opt = optimizer
        self.outputs_fn = jax.jit(outputs_fn) if outputs_fn is not None else None
        if evaluators is None:
            self.evaluators = EvaluatorGroup()
        elif isinstance(evaluators, EvaluatorGroup):
            self.evaluators = evaluators
        else:
            self.evaluators = EvaluatorGroup(*evaluators)
        self.output_dir = output_dir
        self.prefetch = prefetch
        self.log_period = log_period
        # --show_parameter_stats_period analog (TrainerInternal.cpp:80-87):
        # 0 = off; falls back to the global flag when unset
        if param_stats_period == 0:
            from ..utils.flags import FLAGS
            param_stats_period = FLAGS.show_parameter_stats_period
        self.param_stats_period = param_stats_period
        if on_nonfinite is None:
            on_nonfinite = "raise" if nan_guard else "off"
        if on_nonfinite not in _NONFINITE_POLICIES:
            raise ValueError(f"on_nonfinite must be one of "
                             f"{_NONFINITE_POLICIES}, got {on_nonfinite!r}")
        self.on_nonfinite = on_nonfinite
        self.nan_guard = on_nonfinite != "off"
        self.prefetch_timeout = prefetch_timeout
        self.stats = StatSet()
        #: typed robustness counters (trainer.* catalogue names)
        self.metrics = metrics if metrics is not None else \
            obs.MetricsRegistry()
        #: legacy read-only view over the counters (ISSUE 3 compat)
        self.train_stats: Mapping = _TrainStatsView(self.metrics)
        # hot-path counters bound once: the per-batch cost is one locked
        # float add on the trainer's own registry (the obs session mirror
        # stays gated on is_active)
        self._c_steps = self.metrics.counter("trainer.steps_total")
        self._c_examples = self.metrics.counter("trainer.examples_total")
        self._preempt = threading.Event()
        self.preempted = False
        # skip AND halt both need the update dropped on a non-finite loss:
        # skip to continue from the last finite state, halt to checkpoint it
        # (checkpointing the NaN-poisoned trees would make resume start from
        # garbage — worse than no checkpoint at all)
        guard_mode = on_nonfinite in ("skip", "halt")
        if layout is not None and mesh is None:
            from ..parallel.mesh import current_mesh
            mesh = current_mesh()
            if mesh is None:
                raise ValueError("Trainer(layout=...) needs mesh=... or an "
                                 "enclosing parallel.use_mesh(...)")
        self.mesh = mesh
        self.layout = layout
        if mesh is not None:
            # the revert needs the pre-update trees alive after the step,
            # so buffer donation is off on that path
            self._dp = DataParallel(loss_fn, optimizer, mesh=mesh,
                                    param_rules=layout,
                                    aux_fn=outputs_fn, donate=not guard_mode)
            self._step = None
        else:
            self._dp = None

            def _step(params, opt_state, *batch):
                loss, grads = jax.value_and_grad(loss_fn)(params, *batch)
                # eval outputs computed inside the SAME jitted step (XLA
                # shares the forward) — no second per-batch forward dispatch
                outs = outputs_fn(params, *batch) if outputs_fn else None
                new_params, new_opt = optimizer.update(grads, opt_state,
                                                       params)
                if guard_mode:
                    # drop-the-batch INSIDE the jitted step: select the
                    # pre-update trees when the loss is non-finite — donation
                    # stays legal because the select reads both operands
                    ok = jnp.isfinite(loss)
                    new_params = jax.tree_util.tree_map(
                        lambda n, o: jnp.where(ok, n, o), new_params, params)
                    new_opt = jax.tree_util.tree_map(
                        lambda n, o: jnp.where(ok, n, o), new_opt, opt_state)
                if outputs_fn is not None:
                    return new_params, new_opt, loss, outs
                return new_params, new_opt, loss

            # cost-instrumented jit: first call per batch signature AOT-
            # compiles and records FLOPs/bytes in the roofline ledger, so
            # a training run under an obs session accumulates
            # fluid.device_flops_total and the derived roofline.mfu gauge
            # as a byproduct of just running
            self._step = obs.roofline.instrument(
                jax.jit(_step, donate_argnums=(0, 1)), "trainer.step")
        self._loss_jit = jax.jit(loss_fn)

    # ------------------------------------------------------------------ train
    def _log_param_stats(self, params):
        """Per-parameter magnitude dump — the --show_parameter_stats_period
        observability of TrainerInternal.cpp:80-87,156 (value stats; grads
        are not retained past the fused update step)."""
        from ..nn.module import Module
        for name, value in Module.named_parameters(jax.device_get(params)):
            a = np.abs(np.asarray(value, np.float32))
            log.info("param %-40s shape=%-16s absmax=%.4e absmean=%.4e",
                     name, str(tuple(a.shape)), float(a.max(initial=0.0)),
                     float(a.mean()) if a.size else 0.0)

    # -- preemption --------------------------------------------------------
    def request_preemption(self):
        """Ask the train loop to checkpoint and exit after the current batch
        — what the SIGTERM/SIGINT handlers call; safe from any thread."""
        self._preempt.set()

    def _install_preemption_handlers(self):
        """SIGTERM/SIGINT -> checkpoint-then-exit. On a TPU pod preemption
        is the COMMON case (maintenance events deliver SIGTERM), not the
        exception. A SECOND SIGINT raises KeyboardInterrupt — a batch hung
        inside a wedged step/collective never reaches the between-batch
        preemption check, and Ctrl-C must still offer an escape. Returns
        the previous handlers for restoration; no-op off the main thread
        (signal.signal would raise)."""

        def handler(signum, frame):
            if signum == signal.SIGINT and self._preempt.is_set():
                raise KeyboardInterrupt
            self.request_preemption()

        prev = {}
        try:
            for sig in (signal.SIGTERM, signal.SIGINT):
                prev[sig] = signal.signal(sig, handler)
        except ValueError:
            pass
        return prev

    def _mirror(self, name: str, n: float = 1) -> None:
        """Mirror a count into the installed obs session — unless the
        session shares this trainer's registry (Trainer(metrics=
        obs.REGISTRY) under a default session), where mirroring would
        double-count."""
        s = obs.session()
        if s is not None and s.registry is not self.metrics:
            s.registry.counter(name).inc(n)

    def _count(self, name: str, n: float = 1) -> None:
        """Robustness counter: the trainer's own registry is the always-on
        source of truth (train_stats view); the session gets a mirror so
        exports include it."""
        self.metrics.counter(name).inc(n)
        self._mirror(name, n)

    def _checkpoint_preempted(self, pass_id, batch_id, params, opt_state):
        # the flight ring first (no-op unless armed): if the checkpoint
        # write itself dies, the post-mortem still shows the final batches
        obs.flight_dump("preemption")
        if self.output_dir:
            with obs.span("trainer.checkpoint", pass_id=pass_id,
                          reason="preemption"):
                save_checkpoint(self.output_dir, pass_id, params, opt_state,
                                extra={"pass_complete": False,
                                       "batch_id": batch_id})
            log.warning("preempted at pass %d batch %d: checkpoint saved; "
                        "resume re-runs this pass", pass_id, batch_id)
        else:
            log.warning("preempted at pass %d batch %d with no output_dir: "
                        "nothing durable to save", pass_id, batch_id)
        self._count("trainer.preemptions_total")
        self.preempted = True

    def _handle_nonfinite(self, cost_f, pass_id, batch_id, params, opt_state):
        self._count("trainer.nonfinite_total")
        if self.on_nonfinite == "skip":
            # the jitted step (or the host-side revert on the mesh path)
            # already dropped the update; account for it and move on
            self._count("trainer.skipped_total")
            log.warning("non-finite loss %s at pass %d batch %d: batch "
                        "skipped (%d skipped so far)", cost_f, pass_id,
                        batch_id, self.train_stats["skipped_batches"])
            return
        if self.on_nonfinite == "halt" and self.output_dir:
            # durable state first, then fail: params/opt_state were reverted
            # to the pre-update (last finite) trees, so the operator restarts
            # from the last finite step instead of losing the pass
            with obs.span("trainer.checkpoint", pass_id=pass_id,
                          reason="halt"):
                save_checkpoint(self.output_dir, pass_id, params, opt_state,
                                extra={"pass_complete": False,
                                       "batch_id": batch_id, "halted": True})
            log.error("non-finite loss at pass %d batch %d: state "
                      "checkpointed before halting", pass_id, batch_id)
        # the feenableexcept(FE_INVALID|DIVBYZERO|OVERFLOW) analog
        # (TrainerMain.cpp:49): fail fast, don't train on garbage
        raise FloatingPointError(
            f"non-finite loss {cost_f} at pass {pass_id} batch "
            f"{batch_id}; re-run with "
            f"jax.config.update('jax_debug_nans', True) to locate "
            f"the producing op")

    def train(self, reader: Callable[[], Iterable], params, *,
              num_passes: int = 1, event_handler: Optional[Callable] = None,
              feeder: Optional[Callable] = None,
              test_reader: Optional[Callable] = None,
              resume: bool = False, checkpoint_every: int = 1,
              handle_signals: bool = True):
        """Run the pass/batch loop; returns (params, opt_state).

        reader yields raw row-batches; ``feeder`` converts one row-batch to the
        loss_fn's *batch arrays (identity if None).

        ``resume=True`` restarts from the newest verifiable checkpoint. A
        pass checkpointed as incomplete (preemption/halt) resumes at its
        next batch: the checkpoint holds post-batch state, so the first
        ``batch_id + 1`` reader batches are skipped rather than re-applied —
        with a deterministic reader the continuation is byte-identical to an
        uninterrupted run. ``checkpoint_every=N`` saves every Nth pass (the
        final pass always saves); preemption checkpoints ignore the cadence.
        ``handle_signals`` installs SIGTERM/SIGINT checkpoint-then-exit
        handlers for the duration of the call (main thread only).
        """
        event_handler = event_handler or (lambda e: None)
        if checkpoint_every < 1:
            raise ValueError("checkpoint_every must be >= 1")
        start_pass = 0
        skip_batches = 0
        opt_state = None
        self.preempted = False
        self._preempt.clear()
        if resume and self.output_dir:
            # one load_checkpoint call does discovery + verification + read
            # in a single pass over the members; a dir with no verifiable
            # checkpoint falls through to fresh init
            try:
                params, opt_state, st = load_checkpoint(self.output_dir)
            except FileNotFoundError:
                st = None
                log.info("resume requested but no verifiable checkpoint "
                         "under %s; starting fresh", self.output_dir)
            if st is not None and st.get("pass_complete", True):
                start_pass = st["pass_id"] + 1
                log.info("resumed from completed pass %d", st["pass_id"])
            elif st is not None:
                # the preemption checkpoint holds state AFTER batch_id, so
                # the interrupted pass continues at batch_id + 1
                start_pass = st["pass_id"]
                skip_batches = st.get("batch_id", -1) + 1
                log.info("resumed preempted pass %d at batch %d",
                         st["pass_id"], skip_batches)
        if opt_state is None:
            if self._dp is not None:
                params, opt_state = self._dp.init(params)
            else:
                opt_state = self.opt.init(params)
        elif self._dp is not None:
            params, opt_state = self._dp.init(params, opt_state)

        prev_handlers = (self._install_preemption_handlers()
                         if handle_signals else {})
        # goodput ledger (None when the obs plane is off): splits this
        # call's wall time into compile / host_input / device / host_sync
        # / idle — goodput.*_seconds_total + the goodput.ratio gauge
        gp = obs.goodput.open_ledger("trainer")
        try:
            last_pass = start_pass + num_passes - 1
            for pass_id in range(start_pass, start_pass + num_passes):
              # pass-scoped trace span: reader RPC pulls, checkpoint saves
              # and every step nest under it on this thread (the Perfetto
              # trainer -> ckpt/rpc containment of docs/design/observability)
              with obs.span("trainer.pass", pass_id=pass_id):
                event_handler(EV.BeginPass(pass_id))
                self.evaluators.start()
                first_batch = skip_batches if pass_id == start_pass else 0
                batches = self._batches(reader, feeder, skip=first_batch)
                if gp is not None:
                    batches = _timed_input(batches, gp)
                for batch_id, batch in enumerate(batches, start=first_batch):
                    event_handler(EV.BeginIteration(pass_id, batch_id))
                    if (self.on_nonfinite in ("skip", "halt")
                            and self._dp is not None):
                        # mesh path: revert host-side (donation disabled)
                        prev_params, prev_opt = params, opt_state
                    with obs.span("trainer.step",
                                  metric="trainer.step_seconds"):
                        with self.stats.timer("TrainBatch"), \
                                obs.span("trainer.device_step"), \
                                maybe_bucket(gp, "device"):
                            if self._dp is not None:
                                batch = self._dp.shard_batch(batch)
                                res = self._dp.step(params, opt_state,
                                                    *batch)
                            else:
                                res = self._step(params, opt_state, *batch)
                            if gp is not None:
                                # under async dispatch (TPU) the step's wall
                                # time surfaces at the FIRST host block — the
                                # bucket contract puts that block here, so
                                # block now rather than at float(cost) below
                                # (which would book device time as host_sync;
                                # nothing runs between dispatch and that sync,
                                # so this costs no overlap)
                                jax.block_until_ready(res)
                        if self.outputs_fn is not None:
                            params, opt_state, cost, outs = res
                        else:
                            params, opt_state, cost = res
                            outs = None
                        with obs.span("trainer.host_sync",
                                      metric="trainer.sync_seconds"), \
                                maybe_bucket(gp, "host_sync"):
                            cost_f = faults.filter_value("step.grad",
                                                         float(cost))
                    self._c_steps.inc()
                    self._mirror("trainer.steps_total")
                    lead = (getattr(batch[0], "shape", None)
                            if isinstance(batch, (tuple, list)) and batch
                            else None)
                    if lead:
                        self._c_examples.inc(lead[0])
                        self._mirror("trainer.examples_total", lead[0])
                    if self.nan_guard and not np.isfinite(cost_f):
                        if (self.on_nonfinite in ("skip", "halt")
                                and self._dp is not None):
                            params, opt_state = prev_params, prev_opt
                        self._handle_nonfinite(cost_f, pass_id, batch_id,
                                               params, opt_state)
                        event_handler(EV.EndIteration(pass_id, batch_id,
                                                      cost_f, None))
                        if self._preempt.is_set():
                            self._checkpoint_preempted(pass_id, batch_id,
                                                       params, opt_state)
                            return params, opt_state
                        continue
                    ev_result = None
                    if outs is not None:
                        with self.stats.timer("Eval"):
                            self.evaluators.update(cost=cost_f, **outs)
                            ev_result = self.evaluators.result()
                    if self.log_period and (batch_id + 1) % self.log_period == 0:
                        log.info("pass %d batch %d cost %.6f", pass_id,
                                 batch_id, cost_f)
                    if (self.param_stats_period and
                            (batch_id + 1) % self.param_stats_period == 0):
                        self._log_param_stats(params)
                    event_handler(EV.EndIteration(pass_id, batch_id, cost_f,
                                                  ev_result))
                    if self._preempt.is_set():
                        self._checkpoint_preempted(pass_id, batch_id,
                                                   params, opt_state)
                        return params, opt_state
                pass_result = (self.evaluators.result()
                               if self.outputs_fn is not None else None)
                if test_reader is not None:
                    tr = self.test(test_reader, params, feeder=feeder)
                    event_handler(EV.TestResult(pass_id, tr["cost"],
                                                tr.get("evaluator_result")))
                if self.output_dir and (
                        (pass_id - start_pass + 1) % checkpoint_every == 0
                        or pass_id == last_pass):
                    with obs.span("trainer.checkpoint", pass_id=pass_id,
                                  reason="pass_end"):
                        save_checkpoint(self.output_dir, pass_id, params,
                                        opt_state)
                event_handler(EV.EndPass(pass_id, pass_result))
        finally:
            if gp is not None:
                gp.close()
            for sig, handler in prev_handlers.items():
                try:
                    signal.signal(sig, handler)
                except (ValueError, TypeError):
                    pass
        return params, opt_state

    def _batches(self, reader, feeder, skip: int = 0):
        if skip:
            # resume: slice the RAW reader, before the feeder transform —
            # re-running host-side conversion on thousands of about-to-be-
            # discarded batches would delay the restart by their full cost
            raw, reader = reader, (lambda: itertools.islice(raw(), skip,
                                                            None))
        if feeder is None and self.prefetch_timeout is None:
            return iter(reader())
        # a feeder wants the prefetch thread for overlap; a prefetch_timeout
        # needs it too — the watchdog only works with a producer thread to
        # watch, so the timeout must not be silently ignored without one
        return iter(DoubleBuffer(reader, depth=self.prefetch, transform=feeder,
                                 timeout=self.prefetch_timeout))

    # ---------------------------------------------------------------- summary
    def summary(self) -> str:
        """Operator-facing report: the trainer's typed counters plus
        immutable :class:`~paddle_tpu.utils.stats.StatSnapshot` rows —
        ``obs.summary`` subsumes the old ``StatSet.report()`` table."""
        return obs.summary({"metrics": self.metrics.collect()},
                           stats=self.stats.items().values())

    # ------------------------------------------------------------------- test
    def test(self, reader, params, *, feeder=None) -> Dict[str, Any]:
        """Average cost (+ evaluator results) over a test reader (Tester.cpp)."""
        total, n = 0.0, 0
        self.evaluators.start()
        for batch in self._batches(reader, feeder):
            cost = self._loss_jit(params, *batch)
            total += float(cost)
            n += 1
            if self.outputs_fn is not None:
                outs = self.outputs_fn(params, *batch)
                self.evaluators.update(cost=float(cost), **outs)
        out: Dict[str, Any] = {"cost": total / max(n, 1)}
        if self.outputs_fn is not None:
            out["evaluator_result"] = self.evaluators.result()
        return out

    # -------------------------------------------------------------- checkgrad
    def check_gradient(self, params, batch: Tuple, *, eps: float = 1e-3,
                       rtol: float = 5e-2, max_checks_per_param: int = 5,
                       seed: int = 0) -> bool:
        """Central-difference gradient check (--job=checkgrad,
        Trainer.h:84; LayerGradUtil perturbation semantics, SURVEY §4.1).
        Runs in float64 (enable_x64) — float32 losses don't resolve the
        perturbation; returns True when analytic and numeric agree."""
        import contextlib

        @contextlib.contextmanager
        def enable_x64():
            prev = jax.config.jax_enable_x64
            jax.config.update("jax_enable_x64", True)
            try:
                yield
            finally:
                jax.config.update("jax_enable_x64", prev)

        def to64(x):
            # one host transfer: device_get already yields ndarray (the old
            # np.asarray(jax.device_get(x)) chain materialized the leaf
            # twice). astype keeps its default copy — device_get can return
            # a READ-ONLY view, and the check loop below writes into these
            # leaves through p_host, so they must be owned writable copies
            x = jax.device_get(x)
            if not hasattr(x, "dtype"):
                x = np.asarray(x)
            return (x.astype(np.float64)
                    if np.issubdtype(x.dtype, np.floating) else x)

        with enable_x64():
            params64 = jax.tree_util.tree_map(to64, params)
            batch64 = jax.tree_util.tree_map(to64, batch)
            loss64 = jax.jit(self.loss_fn)
            grads = jax.jit(jax.grad(self.loss_fn))(params64, *batch64)
            leaves, treedef = jax.tree_util.tree_flatten(params64)
            gleaves = jax.tree_util.tree_leaves(grads)
            rs = np.random.RandomState(seed)
            ok = True
            for li, (p, g) in enumerate(zip(leaves, gleaves)):
                # host copies hoisted OUT of the perturbation loop: the old
                # code re-transferred the whole gradient leaf from device
                # once per checked index (np.asarray(device_get(g)) inside
                # the loop) — n_checks transfers where one suffices
                p_host = np.asarray(jax.device_get(p), np.float64)
                g_flat = np.asarray(jax.device_get(g),
                                    np.float64).reshape(-1)
                flat = p_host.reshape(-1)
                n_checks = min(max_checks_per_param, flat.size)
                for idx in rs.choice(flat.size, size=n_checks, replace=False):
                    orig = flat[idx]
                    vals = {}
                    for sign in (+1, -1):
                        flat[idx] = orig + sign * eps
                        leaves2 = list(leaves)
                        leaves2[li] = jnp.asarray(p_host)
                        vals[sign] = float(loss64(
                            jax.tree_util.tree_unflatten(treedef, leaves2),
                            *batch64))
                    flat[idx] = orig
                    numeric = (vals[+1] - vals[-1]) / (2 * eps)
                    analytic = float(g_flat[idx])
                    denom = max(abs(numeric), abs(analytic), 1e-6)
                    if abs(numeric - analytic) / denom > rtol:
                        log.warning("checkgrad mismatch leaf %d idx %d: "
                                    "numeric %.6g analytic %.6g", li, idx,
                                    numeric, analytic)
                        ok = False
        return ok

    # ------------------------------------------------------------------- time
    def benchmark(self, reader, params, *, feeder=None, warmup: int = 3,
                  iters: int = 20,
                  profile_dir: Optional[str] = None) -> Dict[str, float]:
        """--job=time analog (TrainerBenchmark.cpp): steady-state ms/batch.
        ``profile_dir`` wraps the timed loop in an XLA trace
        (utils/profiler — the hl_profiler_start/WITH_PROFILER analog)."""
        opt_state = self.opt.init(params) if self._dp is None else None
        if self._dp is not None:
            params, opt_state = self._dp.init(params)
        batches = list(self._batches(reader, feeder))
        if not batches:
            raise ValueError("empty reader")
        step = (self._step if self._dp is None
                else lambda p, s, *b: self._dp.step(p, s, *b))
        i = 0
        for _ in range(warmup):
            res = step(params, opt_state, *batches[i % len(batches)])
            params, opt_state, loss = res[0], res[1], res[2]
            i += 1
        jax.block_until_ready(loss)
        from ..utils import profiler as _prof
        import contextlib
        prof_cm = (_prof.profile(profile_dir) if profile_dir
                   else contextlib.nullcontext())
        with prof_cm:
            t0 = time.perf_counter()
            for _ in range(iters):
                with self.stats.timer("BenchBatch"):
                    res = step(params, opt_state, *batches[i % len(batches)])
                    params, opt_state, loss = res[0], res[1], res[2]
                i += 1
            jax.block_until_ready(loss)
            # timed INSIDE the profiler context: stop_trace() serialization
            # must not inflate the reported steady-state number
            ms = (time.perf_counter() - t0) / iters * 1e3
        return {"ms_per_batch": ms}
