"""paddle_tpu.tune — the measured autotuning plane (ROADMAP item 3).

Closes the TVM loop: the plan knobs every Pallas route used to hard-code
(``_fused_plan``'s wide-tile preference, the ``SHORT_SEQ_DENSE`` decode
crossover, the paged-cache ``page_block``) become enumerable plan spaces
(:mod:`~paddle_tpu.tune.spaces`), a measurement driver
(:mod:`~paddle_tpu.tune.driver`, ``paddle_tpu tune``) times every
candidate on the current backend, and winners persist in a versioned
cache (:mod:`~paddle_tpu.tune.cache`) the routing entries consult first.

The consult functions here are the routing entries' ONLY doorway into the
cache, and they are fail-safe by construction: any miss, hash staleness,
schema mismatch, or illegal plan returns the "no tuned entry" answer and
the caller's heuristic decides — tuned plans change speed, never
numerics (tests/test_autotune.py holds route/plan choice to bit parity).
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

from .cache import (CACHE_ENV, DISABLE_ENV, SCHEMA_VERSION, AutotuneCache,
                    default_cache_path, get_cache, load_cache, reset,
                    set_cache)
from .spaces import (PROFILES, SPACE_DEFS, SPACE_NAMES, fused_candidates,
                     fused_family, space_hash)

__all__ = [
    "AutotuneCache", "CACHE_ENV", "DISABLE_ENV", "SCHEMA_VERSION",
    "default_cache_path", "load_cache", "get_cache", "set_cache", "reset",
    "SPACE_DEFS", "SPACE_NAMES", "PROFILES", "space_hash", "fused_family",
    "fused_candidates", "run_tune", "results_markdown", "MISS",
    "fused_plan", "decode_kernel_min_len", "page_block", "bucket_grid",
    "plan_source",
]

#: sentinel for "no tuned entry applies — the heuristic decides". Distinct
#: from None, which several plans use as a real value (e.g. a tuned
#: ``kernel_min_len: null`` = "the dense route won everywhere, measured").
MISS = object()


def _device_kind() -> str:
    from ..obs.roofline import _device_kind as dk
    return dk()


def _fresh_entry(space: str, kernel: str,
                 family: str) -> Optional[Dict[str, Any]]:
    """The active cache's entry for (space, kernel, device_kind, family),
    or None — misses include hash-stale entries (the plan space changed
    under the cache; ``paddle_tpu lint`` reports those as L008)."""
    cache = get_cache()
    if cache is None:
        return None
    entry = cache.get(space, kernel, _device_kind(), family)
    if entry is None or entry.get("space_hash") != space_hash(space):
        return None
    return entry


def fused_plan(kernel: str, *, T: int, H: int, gates: int,
               seq_h_units: int, batch: int,
               budget_bytes: int = 15_500_000,
               double_buffer_always: bool = False
               ) -> Optional[Tuple[int, int]]:
    """Tuned (block_b, chunk_t) for one fused-RNN launch, or None.

    The plan is re-validated against :func:`ops.rnn.plan_is_legal` on
    THIS machine before it is honored — a cache copied from a different
    chip (or hand-edited) can cost a heuristic fallback, never an illegal
    kernel launch."""
    entry = _fresh_entry("fused_rnn", kernel,
                         fused_family(gates=gates, T=T, H=H, batch=batch))
    if entry is None:
        return None
    plan = entry.get("plan")
    if (not isinstance(plan, (list, tuple)) or len(plan) != 2
            or not all(isinstance(v, int) and v > 0 for v in plan)):
        return None
    blk, chunk = plan
    from ..ops.rnn import plan_is_legal
    if not plan_is_legal(T, H, gates, seq_h_units, batch, blk, chunk,
                         budget_bytes=budget_bytes,
                         double_buffer_always=double_buffer_always):
        return None
    return blk, chunk


def decode_kernel_min_len():
    """Tuned decode-route crossover: the read length from which the
    Pallas kernel route wins on this device_kind. Returns :data:`MISS`
    when no tuned entry applies (heuristic decides), None when the tuned
    verdict is "dense everywhere", else a positive int."""
    entry = _fresh_entry("decode_route", "decode_attention", "default")
    if entry is None:
        return MISS
    plan = entry.get("plan")
    if not isinstance(plan, dict) or "kernel_min_len" not in plan:
        return MISS
    v = plan["kernel_min_len"]
    if v is None:
        return None
    if isinstance(v, int) and v >= 1:
        return v
    return MISS


def page_block(max_len: int, cache_bucket: int) -> Optional[int]:
    """Tuned paged-KV page size, validated against the caller's grid
    (must divide ``max_len`` and ``cache_bucket``), or None."""
    entry = _fresh_entry("page_block", "paged_decode_attention", "default")
    if entry is None:
        return None
    plan = entry.get("plan")
    if not isinstance(plan, dict):
        return None
    bs = plan.get("page_block")
    if (isinstance(bs, int) and bs >= 1 and max_len % bs == 0
            and cache_bucket % bs == 0):
        return bs
    return None


def bucket_grid(kind: str, *, max_len: Optional[int] = None,
                divisor: Optional[int] = None) -> Optional[Tuple[int, ...]]:
    """Tuned prompt/cache bucket grid for serving compiles, or None.

    ``kind`` is ``"prompt"`` or ``"cache"`` (the two bucket_grid
    families).  The winner is re-validated for legality HERE, against the
    caller's own constraints: strictly ascending unique positive ints,
    every bucket ≤ ``max_len`` (buckets past the model horizon are
    dropped; an emptied grid is a miss), and — when ``divisor`` is given —
    every surviving bucket divisible by it (``PagePool`` passes its
    ``page_block``; an indivisible bucket can't page).  Any violation
    returns None and the caller's heuristic grid decides."""
    entry = _fresh_entry("bucket_grid", "prefill_dispatch", kind)
    if entry is None:
        return None
    plan = entry.get("plan")
    if not isinstance(plan, dict):
        return None
    buckets = plan.get("buckets")
    if (not isinstance(buckets, (list, tuple)) or not buckets
            or not all(isinstance(b, int) and b >= 1 for b in buckets)
            or list(buckets) != sorted(set(buckets))):
        return None
    if max_len is not None:
        buckets = [b for b in buckets if b <= max_len]
        if not buckets:
            return None
    if divisor is not None and any(b % divisor for b in buckets):
        return None
    return tuple(buckets)


def plan_source() -> str:
    """"tuned" when an autotune cache with at least one current-hash entry
    for THIS device_kind is active, else "heuristic" — the bench rows'
    ``plan_source`` stamp (analysis/bench_schema.py): it records whether
    the process's kernel-plan consults could resolve against measured
    winners during the row."""
    cache = get_cache()
    if cache is None:
        return "heuristic"
    dk = _device_kind()
    for entry in cache.entries.values():
        if (entry.get("device_kind") == dk
                and entry.get("space") in SPACE_DEFS
                and entry.get("space_hash")
                == space_hash(entry["space"])):
            return "tuned"
    return "heuristic"


def run_tune(*args, **kwargs):
    """Lazy veneer over :func:`tune.driver.run_tune` (keeps ``import
    paddle_tpu`` free of the driver's jax-heavy measurement path)."""
    from .driver import run_tune as _run
    return _run(*args, **kwargs)


def results_markdown(report):
    from .driver import results_markdown as _md
    return _md(report)
