"""The autotune cache — measured plan winners, persisted and versioned.

One JSON file holds every tuned decision: ``{schema_version, entries}``
where each entry is keyed ``space|kernel|device_kind|family`` and carries
the winning ``plan``, the ``space_hash`` of the plan space that produced
it, and the measurement evidence (``tuned_ms`` / ``heuristic_ms`` /
``methodology="measured"``). The routing entries (``ops.rnn._fused_plan``,
``ops.pallas_kernels.decode_route``, ``serving.paged.PagePool``) consult
the loaded cache FIRST and fall back to their built-in heuristics on any
miss — so a cache can only ever change *speed*, never numerics, and a
deleted/corrupt/stale cache degrades to exactly the pre-autotune behavior.

Staleness contract (docs/design/autotune.md):

* ``schema_version`` mismatch -> the whole file is ignored (warn once).
* per-entry ``space_hash`` != the current plan space's hash -> that entry
  is ignored at consult time, and ``paddle_tpu lint`` reports it as L008
  (the plan space changed under the cache; re-run ``paddle_tpu tune``).
* entries whose plan fails the target's legality check (VMEM model,
  divisibility) are ignored at consult time — a cache written on one
  machine cannot produce an illegal kernel launch on another.

Location: ``$PADDLE_TPU_AUTOTUNE_CACHE`` if set, else
``~/.paddle_tpu/autotune.json``. ``PADDLE_TPU_AUTOTUNE=0`` disables
consultation entirely (heuristics only; the tune CLI still writes).
"""

from __future__ import annotations

import json
import os
import tempfile
import threading
import warnings
from typing import Any, Dict, Optional

SCHEMA_VERSION = 1
CACHE_ENV = "PADDLE_TPU_AUTOTUNE_CACHE"
DISABLE_ENV = "PADDLE_TPU_AUTOTUNE"


def default_cache_path() -> str:
    env = os.environ.get(CACHE_ENV)
    if env:
        return env
    return os.path.join(os.path.expanduser("~"), ".paddle_tpu",
                        "autotune.json")


def _entry_key(space: str, kernel: str, device_kind: str,
               family: str) -> str:
    return "|".join((space, kernel, device_kind, family))


class AutotuneCache:
    """In-memory view of one autotune file. Entries are plain dicts so the
    JSON round trip is the identity; :meth:`put`/:meth:`get` own the key
    convention."""

    def __init__(self, entries: Optional[Dict[str, Dict[str, Any]]] = None,
                 schema_version: int = SCHEMA_VERSION):
        self.schema_version = schema_version
        self.entries: Dict[str, Dict[str, Any]] = dict(entries or {})

    def put(self, space: str, kernel: str, device_kind: str, family: str,
            plan: Any, space_hash: str, **meta) -> Dict[str, Any]:
        entry = {"space": space, "kernel": kernel,
                 "device_kind": device_kind, "family": family,
                 "plan": plan, "space_hash": space_hash}
        entry.update(meta)
        self.entries[_entry_key(space, kernel, device_kind, family)] = entry
        return entry

    def get(self, space: str, kernel: str, device_kind: str,
            family: str) -> Optional[Dict[str, Any]]:
        return self.entries.get(_entry_key(space, kernel, device_kind,
                                           family))

    def to_dict(self) -> Dict[str, Any]:
        return {"schema_version": self.schema_version,
                "entries": self.entries}

    @classmethod
    def from_dict(cls, data: Dict[str, Any]) -> "AutotuneCache":
        if not isinstance(data, dict) or "entries" not in data:
            raise ValueError("autotune cache must be a dict with 'entries'")
        version = data.get("schema_version")
        if version != SCHEMA_VERSION:
            raise ValueError(
                f"autotune cache schema_version {version!r} != supported "
                f"{SCHEMA_VERSION}; re-run `paddle_tpu tune`")
        entries = data["entries"]
        if not isinstance(entries, dict):
            raise ValueError("autotune cache 'entries' must be a dict")
        return cls(entries={k: v for k, v in entries.items()
                            if isinstance(v, dict)}, schema_version=version)

    def save(self, path: Optional[str] = None) -> str:
        """Atomic write (tmp + rename): a crashed tune run never leaves a
        torn file behind for the next process to trip on."""
        path = path or default_cache_path()
        d = os.path.dirname(os.path.abspath(path))
        os.makedirs(d, exist_ok=True)
        fd, tmp = tempfile.mkstemp(dir=d, prefix=".autotune-",
                                   suffix=".tmp")
        try:
            with os.fdopen(fd, "w") as f:
                json.dump(self.to_dict(), f, indent=1, sort_keys=True)
            os.replace(tmp, path)
        except BaseException:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise
        return path


def load_cache(path: Optional[str] = None) -> AutotuneCache:
    """Load (and schema-validate) a cache file; raises OSError /
    ValueError — callers on the consult path go through :func:`get_cache`
    which demotes failures to a once-per-process warning."""
    path = path or default_cache_path()
    with open(path) as f:
        return AutotuneCache.from_dict(json.load(f))


# -- the consult-path singleton ------------------------------------------------
# Loaded lazily on first lookup and cached (including the negative "no
# file" result): the routing entries consult from trace-time hot paths,
# so a consult is a dict get, never filesystem traffic.

_UNSET = object()
_active: Any = _UNSET
_load_lock = threading.Lock()
_warned_load = False


def _disabled() -> bool:
    return os.environ.get(DISABLE_ENV, "").strip() in ("0", "off", "false")


def get_cache() -> Optional[AutotuneCache]:
    """The process's active autotune cache, or None (disabled / no file /
    unreadable file — the heuristics then own every decision)."""
    global _active, _warned_load
    if _active is not _UNSET:
        return _active
    with _load_lock:
        if _active is not _UNSET:
            return _active
        if _disabled():
            _active = None
            return None
        path = default_cache_path()
        if not os.path.exists(path):
            _active = None
            return None
        try:
            _active = load_cache(path)
        except (OSError, ValueError) as e:
            _active = None
            if not _warned_load:
                _warned_load = True
                warnings.warn(
                    f"ignoring unreadable autotune cache {path!r}: {e} "
                    "(heuristic plans apply; re-run `paddle_tpu tune`)",
                    RuntimeWarning, stacklevel=2)
    return _active


def set_cache(cache: Optional[AutotuneCache]) -> None:
    """Install ``cache`` as the active consult target (tests, embedders).
    Pass None to force the no-cache/heuristic state without touching env."""
    global _active
    _active = cache


def reset() -> None:
    """Forget the loaded cache so the next consult re-resolves from disk —
    call after changing $PADDLE_TPU_AUTOTUNE_CACHE or writing a new file."""
    global _active
    _active = _UNSET
