"""The measurement driver — `paddle_tpu tune`'s engine.

TVM's lesson (PAPERS.md): cost models belong INSIDE the system loop.
PR 9 built the measurement half (the roofline ledger); this closes it:
enumerate the candidates of each plan space (tune/spaces.py), measure
every candidate on the CURRENT backend — warmup/compile strictly outside
the timed region, best-of-``reps`` timing, ``methodology="measured"`` —
and persist the winners in the versioned autotune cache the routing
entries consult (tune/cache.py).

The CPU ``interpret=True`` path is a first-class tuning backend here, not
a parity-only mode: off-TPU sweeps run the SAME kernels through the
Pallas interpreter at proxy dims (entries say so in ``note``/``backend``),
so the whole loop — enumerate, measure, persist, consult — is exercised
end-to-end in CI, and an on-chip session only changes the numbers, never
the machinery. Relative interpreter timings do not transfer to the chip;
what transfers is the contract that every cached plan was MEASURED on the
device_kind it is keyed under.
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

from . import cache as _cache
from . import spaces as _spaces


def _device_kind() -> str:
    from ..obs.roofline import _device_kind as dk
    return dk()


def _on_tpu() -> bool:
    from ..ops.pallas_kernels import _on_tpu as f
    return f()


def measure_callable(fn, args: Sequence[Any], *, reps: int = 3,
                     space: str = "unknown") -> float:
    """Best-of-``reps`` seconds for one dispatch of ``fn(*args)``.

    The first (untimed) call pays trace + compile — warmup stays outside
    the timing window, same discipline as ``paddle_tpu profile`` — and
    every timed call blocks on the result, so async dispatch cannot
    deflate the figure. Each measurement counts
    ``tune.measurements_total{space=...}`` on the obs plane."""
    import jax

    from .. import obs
    jax.block_until_ready(fn(*args))          # compile + warm, untimed
    best = float("inf")
    for _ in range(max(1, reps)):
        t0 = time.perf_counter()
        jax.block_until_ready(fn(*args))
        best = min(best, time.perf_counter() - t0)
        obs.count("tune.measurements_total", space=space)
    return best


# -- per-space sweeps ----------------------------------------------------------

def _sweep_fused_family(fam: Dict[str, Any], reps: int) -> Dict[str, Any]:
    import functools

    import jax
    import numpy as np
    import jax.numpy as jnp

    from ..ops import pallas_kernels as pk
    from ..ops import rnn
    kernel_name = fam["kernel"]
    gates, T, H, B = fam["gates"], fam["T"], fam["H"], fam["batch"]
    seq_h_units = fam.get("seq_h_units", gates + 1)
    kfn = (pk.lstm_sequence_fused if kernel_name == "lstm_sequence_fused"
           else pk.gru_sequence_fused)
    rs = np.random.RandomState(0)
    xw = jnp.asarray(rs.randn(B, T, gates * H) * 0.1, jnp.float32)
    lens = jnp.asarray(rs.randint(1, T + 1, B), jnp.int32)
    u = jnp.asarray(rs.randn(H, gates * H) * 0.1, jnp.float32)
    b = jnp.asarray(rs.randn(gates * H) * 0.1, jnp.float32)

    candidates = _spaces.fused_candidates(T=T, H=H, gates=gates,
                                          seq_h_units=seq_h_units, batch=B)
    heuristic = rnn._fused_plan(T, H, gates, seq_h_units, B)
    if heuristic is not None and tuple(heuristic) not in candidates:
        # the heuristic's chunk is avail//per_step, which rarely lands on
        # the candidate grid (e.g. (64, 34) for textcls h256) — time it
        # anyway, or the tuned-vs-heuristic speedup the whole sweep
        # exists for would be null exactly on the real bench shapes
        candidates.append(tuple(heuristic))
    timed: List[Tuple[Tuple[int, int], float]] = []
    for blk, chunk in candidates:
        fn = jax.jit(functools.partial(kfn, block_b=blk, chunk_t=chunk))
        timed.append(((blk, chunk),
                      measure_callable(fn, (xw, lens, u, b), reps=reps,
                                       space="fused_rnn")))
    if not timed:
        return {"space": "fused_rnn", "kernel": kernel_name,
                "family": _spaces.fused_family(gates=gates, T=T, H=H,
                                               batch=B),
                "plan": None, "note": fam.get("note", ""),
                "skipped": "no legal candidates (scan route owns this "
                           "family)"}
    plan, tuned_s = min(timed, key=lambda kv: kv[1])
    heur_s = None
    if heuristic is not None:
        for cand, sec in timed:
            if cand == tuple(heuristic):
                heur_s = sec
                break
    return {
        "space": "fused_rnn", "kernel": kernel_name,
        "family": _spaces.fused_family(gates=gates, T=T, H=H, batch=B),
        "plan": list(plan), "tuned_ms": round(tuned_s * 1e3, 4),
        "heuristic_plan": list(heuristic) if heuristic else None,
        "heuristic_ms": (round(heur_s * 1e3, 4)
                         if heur_s is not None else None),
        "speedup": (round(heur_s / tuned_s, 3)
                    if heur_s and tuned_s else None),
        "candidates": len(timed), "note": fam.get("note", ""),
        "sweep": [{"plan": list(c), "ms": round(s * 1e3, 4)}
                  for c, s in timed],
    }


def _sweep_decode(cfg: Dict[str, Any], reps: int) -> Dict[str, Any]:
    import functools

    import jax
    import numpy as np
    import jax.numpy as jnp

    from ..ops import pallas_kernels as pk
    B, Hh, Dh = cfg["batch"], cfg["n_heads"], cfg["d_head"]
    rs = np.random.RandomState(1)
    q = jnp.asarray(rs.randn(B, Hh, Dh), jnp.float32)
    per_len: List[Dict[str, Any]] = []
    for L in cfg["lengths"]:
        k = jnp.asarray(rs.randn(B, L, Hh, Dh), jnp.float32)
        v = jnp.asarray(rs.randn(B, L, Hh, Dh), jnp.float32)
        pos = jnp.full((B,), L - 1, jnp.int32)
        times = {}
        for route in _spaces.SPACE_DEFS["decode_route"]["routes"]:
            fn = jax.jit(functools.partial(pk.decode_attention, route=route))
            times[route] = measure_callable(fn, (q, k, v, pos), reps=reps,
                                            space="decode_route")
        per_len.append({"len": L,
                        "dense_ms": round(times["dense"] * 1e3, 4),
                        "kernel_ms": round(times["kernel"] * 1e3, 4)})
    # the crossover: smallest length from which the kernel route stays
    # faster through the rest of the grid; null = dense wins everywhere
    kernel_min_len = None
    for i, row in enumerate(per_len):
        if all(r["kernel_ms"] < r["dense_ms"] for r in per_len[i:]):
            kernel_min_len = row["len"]
            break
    heuristic = pk.SHORT_SEQ_DENSE if _on_tpu() else None
    return {
        "space": "decode_route", "kernel": "decode_attention",
        "family": "default",
        "plan": {"kernel_min_len": kernel_min_len},
        "heuristic_plan": {"kernel_min_len": heuristic},
        "sweep": per_len, "note": cfg.get("note", ""),
        "candidates": 2 * len(per_len),
    }


def _sweep_page_block(cfg: Dict[str, Any], reps: int) -> Dict[str, Any]:
    import functools

    import jax
    import numpy as np
    import jax.numpy as jnp

    from ..ops import pallas_kernels as pk
    B, Hh, Dh = cfg["batch"], cfg["n_heads"], cfg["d_head"]
    read_pages = cfg["read_pages"]
    rs = np.random.RandomState(2)
    q = jnp.asarray(rs.randn(B, Hh, Dh), jnp.float32)
    route = "kernel" if _on_tpu() else "dense"
    timed: List[Tuple[int, float]] = []
    for bs in cfg["blocks"]:
        L = read_pages * bs
        P = B * read_pages + 1
        k_pool = jnp.asarray(rs.randn(P, bs, Hh, Dh), jnp.float32)
        v_pool = jnp.asarray(rs.randn(P, bs, Hh, Dh), jnp.float32)
        tables = jnp.asarray(
            1 + np.arange(B * read_pages).reshape(B, read_pages) % (P - 1),
            jnp.int32)
        pos = jnp.full((B,), L - 1, jnp.int32)
        fn = jax.jit(functools.partial(pk.paged_decode_attention,
                                       route=route))
        timed.append((bs, measure_callable(
            fn, (q, k_pool, v_pool, tables, pos), reps=reps,
            space="page_block")))
    # same total read length per candidate (read_pages * bs varies with
    # bs) would confound block size with cache size; normalize per token
    # read: compare ms per position read
    per_tok = [(bs, sec / (read_pages * bs)) for bs, sec in timed]
    win_bs, _ = min(per_tok, key=lambda kv: kv[1])
    heur = 64
    heur_ms = next((sec for bs, sec in timed if bs == heur), None)
    tuned_ms = next(sec for bs, sec in timed if bs == win_bs)
    return {
        "space": "page_block", "kernel": "paged_decode_attention",
        "family": "default", "plan": {"page_block": win_bs},
        "tuned_ms": round(tuned_ms * 1e3, 4),
        "heuristic_plan": {"page_block": heur},
        "heuristic_ms": (round(heur_ms * 1e3, 4)
                         if heur_ms is not None else None),
        "route": route, "note": cfg.get("note", ""),
        "sweep": [{"page_block": bs, "ms": round(sec * 1e3, 4),
                   "ms_per_token": round(mt * 1e3, 6)}
                  for (bs, sec), (_, mt) in zip(timed, per_tok)],
        "candidates": len(timed),
    }


def _sweep_fusion(cfg: Dict[str, Any], reps: int) -> List[Dict[str, Any]]:
    """One row per certified group of the MLP proxy program, measured
    fused-vs-unfused through the whole executor pipeline (fusion.py owns
    the harness; this is just the profile-dims veneer)."""
    from . import fusion as _fusion
    main, startup, feed, fetch = _fusion.build_proxy_program(
        batch=cfg["batch"], width=cfg["width"], depth=cfg["depth"])
    rows = _fusion.measure_fusion(main, startup, feed, fetch, reps=reps,
                                  note=cfg.get("note", ""))
    if not rows:
        return [{"space": "fusion", "kernel": "fused_region",
                 "family": "none", "plan": None,
                 "skipped": "oracle certified no schedulable groups on "
                            "the proxy program",
                 "note": cfg.get("note", "")}]
    return rows


def _sweep_bucket_grid(cfg: Dict[str, Any],
                       reps: int) -> List[Dict[str, Any]]:
    """Measure whole bucket GRIDS, not buckets: a grid's cost over a
    deterministic zipf-ish length sample is the replayed per-request
    dispatch time at each request's padded bucket plus one compile cost
    per distinct bucket the sample touches. More buckets = tighter
    padding but more compiles — the exact tradeoff serving guesses at;
    here it's measured. One row per kind (``prompt``/``cache``)."""
    import jax
    import numpy as np
    import jax.numpy as jnp

    from ..data.feeder import next_bucket
    B, D, max_len = cfg["batch"], cfg["d_model"], cfg["max_len"]
    rs = np.random.RandomState(3)
    # zipf tail scaled up so the sample spans the grid instead of piling
    # onto the smallest bucket (raw zipf(1.2) mass sits at 1-4 tokens)
    raw = rs.zipf(cfg["zipf_a"], cfg["samples"])
    lens = np.minimum(raw * max(1, max_len // 64), max_len).astype(int)
    rs2 = np.random.RandomState(4)
    w1 = jnp.asarray(rs2.randn(D, D) * 0.05, jnp.float32)
    w2 = jnp.asarray(rs2.randn(D, D) * 0.05, jnp.float32)

    proxy = jax.jit(lambda x: jnp.tanh(x @ w1) @ w2)

    rows: List[Dict[str, Any]] = []
    dispatch_s: Dict[int, float] = {}
    compile_s: Dict[int, float] = {}

    def measured(bucket: int) -> Tuple[float, float]:
        """(dispatch seconds, compile seconds) for one padded length."""
        if bucket not in dispatch_s:
            x = jnp.asarray(rs2.randn(B, bucket, D) * 0.1, jnp.float32)
            t0 = time.perf_counter()
            jax.block_until_ready(proxy(x))       # trace + compile + run
            first = time.perf_counter() - t0
            best = measure_callable(proxy, (x,), reps=reps,
                                    space="bucket_grid")
            dispatch_s[bucket] = best
            compile_s[bucket] = max(0.0, first - best)
        return dispatch_s[bucket], compile_s[bucket]

    heuristics = {"prompt": [32, 64, 128, 256, 512], "cache": [256]}
    for kind in _spaces.SPACE_DEFS["bucket_grid"]["kinds"]:
        grids = [tuple(b for b in g if b <= max_len)
                 for g in _spaces.SPACE_DEFS["bucket_grid"]["grids"][kind]]
        grids = [g for g in dict.fromkeys(grids) if g]
        heur = tuple(b for b in heuristics[kind] if b <= max_len)
        if heur and heur not in grids:
            grids.append(heur)    # timed for the speedup column even when
            #                       off the candidate grid (fused_rnn idiom)
        timed: List[Tuple[Tuple[int, ...], float, int]] = []
        for grid in grids:
            used = sorted({next_bucket(int(n), grid) for n in lens})
            cost = sum(measured(b)[1] for b in used)       # compiles
            for n in lens:
                cost += measured(next_bucket(int(n), grid))[0]
            timed.append((grid, cost, len(used)))
        win, tuned_c, _ = min(timed, key=lambda kv: kv[1])
        heur_c = next((c for g, c, _ in timed if g == heur), None)
        rows.append({
            "space": "bucket_grid", "kernel": "prefill_dispatch",
            "family": kind, "plan": {"buckets": list(win)},
            "tuned_ms": round(tuned_c * 1e3, 4),
            "heuristic_plan": {"buckets": list(heur)},
            "heuristic_ms": (round(heur_c * 1e3, 4)
                             if heur_c is not None else None),
            "speedup": (round(heur_c / tuned_c, 3)
                        if heur_c and tuned_c else None),
            "candidates": len(timed), "note": cfg.get("note", ""),
            "sweep": [{"buckets": list(g), "ms": round(c * 1e3, 4),
                       "distinct_buckets": nb} for g, c, nb in timed],
        })
    return rows


# -- ledger seeding ------------------------------------------------------------

#: substring → (plan space, fused-RNN kernel filter) hints mapping the
#: profile ledger's hottest op sites onto the spaces that can move them.
#: Order matters: first match wins (paged_decode before decode).
_LEDGER_HINTS: Tuple[Tuple[str, str, Optional[str]], ...] = (
    ("lstm", "fused_rnn", "lstm_sequence_fused"),
    ("gru", "fused_rnn", "gru_sequence_fused"),
    ("paged_decode_attention", "page_block", None),
    ("decode_attention", "decode_route", None),
    ("prefill", "bucket_grid", None),
    ("prompt", "bucket_grid", None),
    ("fused_", "fusion", None),
    ("elementwise", "fusion", None),
    ("matmul", "fusion", None),
    ("mul", "fusion", None),
    ("fc", "fusion", None),
)


def _ledger_sites(path: str, topk: int = 8) -> List[Dict[str, Any]]:
    """Top-``topk`` op sites by self time from a PR 9 profile ledger.

    Accepts the profiler's xplane protobuf (``.pb``/``.xplane``, read via
    ``obs.xplane``) or a JSON/JSONL row dump (``[{"op": ..., "self_ns":
    ...}, ...]`` — the testable form ``paddle_tpu profile --json``
    emits)."""
    import json as _json
    if path.endswith((".json", ".jsonl")):
        with open(path) as f:
            txt = f.read()
        try:
            data = _json.loads(txt)
        except ValueError:
            data = [_json.loads(ln) for ln in txt.splitlines() if ln.strip()]
        if isinstance(data, dict):
            data = data.get("rows") or data.get("ops") or []
        rows = [{"op": str(r.get("op", "")),
                 "self_ns": int(r.get("self_ns", r.get("total_ns", 0)))}
                for r in data if isinstance(r, dict) and r.get("op")]
    else:
        from ..obs import xplane
        space = xplane.read_xspace(path)
        rows = [{"op": r["op"], "self_ns": r["self_ns"]}
                for r in xplane.op_totals(space)]
    rows.sort(key=lambda r: -r["self_ns"])
    return rows[:max(1, topk)]


def _ledger_seeding(sites: List[Dict[str, Any]]
                    ) -> Tuple[List[str], List[str], List[Dict[str, Any]]]:
    """(implicated spaces, implicated fused-RNN kernels, annotated sites)."""
    spaces_hit: List[str] = []
    kernels: List[str] = []
    annotated: List[Dict[str, Any]] = []
    for site in sites:
        op = site["op"].lower()
        hit_space = None
        for needle, space, kern in _LEDGER_HINTS:
            if needle in op:
                hit_space = space
                if space not in spaces_hit:
                    spaces_hit.append(space)
                if kern and kern not in kernels:
                    kernels.append(kern)
                break
        annotated.append(dict(site, space=hit_space))
    return spaces_hit, kernels, annotated


# -- the entry point -----------------------------------------------------------

def run_tune(spaces: Optional[Sequence[str]] = None,
             profile: Optional[str] = None,
             cache_path: Optional[str] = None,
             reps: Optional[int] = None,
             save: bool = True,
             from_ledger: Optional[str] = None,
             ledger_topk: int = 8) -> Dict[str, Any]:
    """Sweep ``spaces`` under ``profile``, persist winners, return results.

    ``profile=None`` auto-selects: ``bench`` on a TPU, ``cpu`` elsewhere.
    ``from_ledger`` seeds the sweep from a PR 9 profile ledger (xplane
    protobuf or JSON row dump): the top-``ledger_topk`` op sites by self
    time pick which plan spaces (and fused-RNN kernels) get swept — when
    the caller pinned no ``spaces`` explicitly, only the implicated
    spaces run, so tuning effort lands where the measured time went.
    Each ledger-seeded family counts
    ``tune.ledger_seeded_families_total`` on the obs plane.
    The returned dict carries ``device_kind``, ``backend``
    (``device``/``interpret``), the per-family ``results`` (full sweeps
    included), the ``ledger`` seeding report when ``from_ledger`` was
    given, and the ``cache_path`` written (None with ``save=False``).
    Winners merge into an existing cache file — a fused-RNN re-tune does
    not drop the decode entry."""
    from .. import obs
    if profile is None:
        profile = "bench" if _on_tpu() else "cpu"
    prof = _spaces.PROFILES[profile]
    user_pinned = bool(spaces)
    spaces = tuple(spaces) if spaces else _spaces.SPACE_NAMES
    for s in spaces:
        if s not in _spaces.SPACE_DEFS:
            raise ValueError(f"unknown plan space {s!r} "
                             f"(known: {list(_spaces.SPACE_NAMES)})")
    n_reps = reps if reps is not None else prof["reps"]
    device_kind = _device_kind()
    backend = "device" if _on_tpu() else "interpret"

    ledger_report = None
    ledger_kernels: List[str] = []
    seeded_spaces: List[str] = []
    if from_ledger:
        sites = _ledger_sites(from_ledger, ledger_topk)
        seeded_spaces, ledger_kernels, annotated = _ledger_seeding(sites)
        if seeded_spaces and not user_pinned:
            # effort follows the measured time: sweep only implicated
            # spaces (an explicit --spaces list always wins over the hint)
            spaces = tuple(s for s in _spaces.SPACE_NAMES
                           if s in seeded_spaces)
        ledger_report = {"path": from_ledger, "topk": ledger_topk,
                         "sites": annotated,
                         "seeded_spaces": seeded_spaces,
                         "swept_spaces": list(spaces)}

    results: List[Dict[str, Any]] = []
    if "fused_rnn" in spaces:
        fams = prof["fused_families"]
        if ledger_kernels:
            hit = [f for f in fams if f["kernel"] in ledger_kernels]
            fams = hit or fams
        for fam in fams:
            results.append(_sweep_fused_family(fam, n_reps))
    if "decode_route" in spaces:
        results.append(_sweep_decode(prof["decode"], n_reps))
    if "page_block" in spaces:
        results.append(_sweep_page_block(prof["page_block"], n_reps))
    if "fusion" in spaces:
        results.extend(_sweep_fusion(prof["fusion"], n_reps))
    if "bucket_grid" in spaces:
        results.extend(_sweep_bucket_grid(prof["bucket_grid"], n_reps))

    if from_ledger:
        for r in results:
            if r["space"] in seeded_spaces and not (
                    r.get("plan") is None and "skipped" in r):
                obs.count("tune.ledger_seeded_families_total")

    out_path = None
    if save:
        path = cache_path or _cache.default_cache_path()
        try:
            existing = _cache.load_cache(path)
        except (OSError, ValueError):
            existing = _cache.AutotuneCache()
        for r in results:
            if r.get("plan") is None and "skipped" in r:
                continue
            meta = {k: r[k] for k in ("tuned_ms", "heuristic_ms",
                                      "heuristic_plan", "speedup", "note",
                                      "sweep", "certificate",
                                      "program_signature", "shape_family",
                                      "fused_ms", "unfused_ms") if k in r}
            meta.update(methodology="measured", backend=backend,
                        profile=profile)
            existing.put(r["space"], r["kernel"], device_kind, r["family"],
                         r["plan"], _spaces.space_hash(r["space"]), **meta)
        out_path = existing.save(path)
        _cache.reset()       # the fresh file is the consult target now
    report = {"device_kind": device_kind, "backend": backend,
              "profile": profile, "results": results,
              "cache_path": out_path}
    if ledger_report is not None:
        report["ledger"] = ledger_report
    return report


def results_markdown(report: Dict[str, Any]) -> str:
    """Render one run's winners as the markdown crossover table
    docs/design/kernels.md embeds (regenerate with
    ``paddle_tpu tune --markdown``)."""
    lines = [
        f"| space | kernel | family | tuned plan | tuned ms | heuristic "
        f"plan | heuristic ms | speedup |",
        "|---|---|---|---|---|---|---|---|",
    ]
    for r in report["results"]:
        if r.get("plan") is None and "skipped" in r:
            lines.append(f"| {r['space']} | {r['kernel']} | {r['family']} "
                         f"| — (scan) | — | — | — | — |")
            continue
        lines.append(
            f"| {r['space']} | {r['kernel']} | {r['family']} "
            f"| {r.get('plan')} | {r.get('tuned_ms', '—')} "
            f"| {r.get('heuristic_plan')} | {r.get('heuristic_ms', '—')} "
            f"| {r.get('speedup', '—')} |")
    lines.append("")
    lines.append(f"(device_kind={report['device_kind']}, "
                 f"backend={report['backend']}, "
                 f"profile={report['profile']})")
    return "\n".join(lines)
