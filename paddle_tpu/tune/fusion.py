"""The graph-level fusion pass — spending the PR 16 oracle, measured-only.

Closes ROADMAP item 3(c): `analysis.dataflow.fusable_groups()` emits
legality-certified fusion candidates (elementwise chains and
producer→consumer epilogues, each with a dependence certificate); this
module decides WHICH certified groups the Executor rewrites into single
fused dispatch regions, and the answer is never a heuristic — it comes
from the autotune cache's ``fusion`` plan space, where every entry
records a fused-vs-unfused measurement of THIS program family on THIS
``device_kind`` (TVM's measure→plan→codegen loop; the Tensor Processing
Primitives paper's compose-micro-kernels-then-measure discipline).

The consult chain, fail-safe at every link (a fusion that doesn't win on
this backend never ships; any doubt means "run unfused"):

1. the oracle must certify the group TODAY (``fusable_groups``);
2. the rewrite must be schedulable (``analysis.region_schedulable`` —
   hoisting members to one slot crosses no interfering op);
3. a cache entry must exist under the exact key
   ``fusion | group kind | device_kind | program_sig:shape_family:group_sig``
   with a fresh ``space_hash``;
4. the entry's persisted certificate must still match the group
   (``analysis.certificate_matches`` — a program edit that shifts op
   indices or rewires an edge refuses the stale proof);
5. the entry's measured verdict must be ``fuse: true`` — an entry that
   measured SLOWER is kept (it documents the measured loss and stops
   re-measurement) but never activates.

Every rejection is counted on ``fluid.fusion_rejected_total{reason}``
and every activation on ``fluid.fused_regions_total{source}`` — once per
plan decision (the executor memoizes plans alongside its compiled-fn
cache), not per run.
"""

from __future__ import annotations

import hashlib
import json
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Mapping, Optional, Sequence, Tuple

from .cache import get_cache
from .spaces import space_hash

FUSION_SPACE = "fusion"

#: consult-refusal reasons (the bounded label set of
#: ``fluid.fusion_rejected_total``)
REJECT_REASONS = ("no_entry", "stale", "invalid_plan", "cert_invalid",
                  "measured_slower", "not_schedulable")


def _device_kind() -> str:
    from ..obs.roofline import _device_kind as dk
    return dk()


# --------------------------------------------------------------------------
# keys: program signature + shape family + group signature
# --------------------------------------------------------------------------

def program_signature(program) -> str:
    """Content hash of the global block's op list — the stable half of a
    ``fusion`` family key.  ``Program._serial`` is process-monotonic and
    useless across processes; this digest is a pure function of the desc
    (op types, io names, non-callable attrs), so a tuned entry written by
    ``paddle_tpu tune`` resolves in the serving process that rebuilt the
    same program."""
    block = program.blocks[0]
    blob = [{"type": op.type,
             "inputs": {k: list(v) for k, v in sorted(op.inputs.items())},
             "outputs": {k: list(v) for k, v in sorted(op.outputs.items())},
             "attrs": {k: repr(v) for k, v in sorted(op.attrs.items())
                       if not callable(v)}}
            for op in block.ops]
    raw = json.dumps(blob, sort_keys=True).encode()
    return hashlib.sha1(raw).hexdigest()[:12]


def certificate(program, group) -> Dict[str, Any]:
    """The persistable form of one group's dependence certificate:
    ``FusionGroup.to_dict()`` plus the member op types (indices alone
    can't detect an op swapped in place)."""
    block = program.blocks[group.block_idx]
    d = group.to_dict()
    d["op_types"] = [block.ops[i].type for i in group.op_idxs]
    return d


def group_signature(cert: Mapping[str, Any]) -> str:
    """Digest of one certificate's identity-bearing fields — the third
    component of a fusion family key, recomputable by L008 from the
    persisted entry alone."""
    blob = json.dumps(
        {"kind": cert.get("kind"),
         "op_idxs": list(cert.get("op_idxs") or []),
         "op_types": list(cert.get("op_types") or []),
         "inputs": list(cert.get("inputs") or []),
         "outputs": list(cert.get("outputs") or []),
         "edges": [e.get("var") for e in (cert.get("edges") or [])]},
        sort_keys=True)
    return hashlib.sha1(blob.encode()).hexdigest()[:10]


def _pow2(n: int) -> int:
    v = 1
    while v < n:
        v *= 2
    return v


def shape_family(feed_shapes: Mapping[str, Tuple[int, ...]]) -> str:
    """Digest of the feed signature with every dim rounded up to a power
    of two — the shape-family half of the key: a measured verdict holds
    for the shape *family* it was measured on (batch jitter within a
    pow-2 bucket shares the entry), never interpolates across families."""
    parts = "|".join(
        f"{n}:{'x'.join(str(_pow2(max(1, int(d)))) for d in shp)}"
        for n, shp in sorted(feed_shapes.items()))
    return hashlib.sha1(parts.encode()).hexdigest()[:10]


def fusion_family(prog_sig: str, shape_fam: str, group_sig: str) -> str:
    """``program_sig:shape_family:group_sig`` — L008 re-derives the third
    component from the entry's persisted certificate and flags any
    mismatch (a hand-edited or wrongly merged cache)."""
    return f"{prog_sig}:{shape_fam}:{group_sig}"


# --------------------------------------------------------------------------
# the consult: FusionPlan
# --------------------------------------------------------------------------

@dataclass
class FusionPlan:
    """One plan decision for (program, feed shapes, fetch): the activated
    groups, their family keys, the per-family rejections, and the source
    stamp. ``key()`` joins the executor's compiled-fn cache key so fused
    and unfused decisions compile separate entries."""

    groups: List[Any] = field(default_factory=list)
    families: List[str] = field(default_factory=list)
    rejected: List[Tuple[str, str]] = field(default_factory=list)
    source: str = "off"          # "tuned" | "forced" | "off"

    def key(self) -> Tuple:
        return tuple((g.kind, tuple(g.op_idxs)) for g in self.groups)


EMPTY_PLAN = FusionPlan()


def cache_has_fusion_entries(device_kind: Optional[str] = None) -> bool:
    """Cheap pre-gate for the executor's hot path: with no ``fusion``
    entries for this device_kind in the active cache, the measured-only
    answer is 'unfused' for every group — skip the dataflow analysis
    entirely."""
    cache = get_cache()
    if cache is None:
        return False
    dk = device_kind or _device_kind()
    return any(e.get("space") == FUSION_SPACE
               and e.get("device_kind") == dk
               for e in cache.entries.values())


def plan_for(program, feed_shapes: Mapping[str, Tuple[int, ...]], *,
             fetch: Sequence[str] = (), feed: Sequence[str] = (),
             force: Any = None) -> FusionPlan:
    """The fusion decision for one (program, feed shapes, fetch).

    ``force=None`` is the production path: consult the autotune cache,
    activate only measured winners.  ``force=True`` activates every
    schedulable certified group; a set of first-op indices activates
    exactly those groups (the measurement harness's per-group knob).
    Both forced forms still require certification AND schedulability —
    forcing can cost speed, never correctness."""
    from .. import obs
    from ..analysis.dataflow import (certificate_matches, fusable_groups,
                                     region_schedulable)
    groups = fusable_groups(program, fetch=fetch, feed=feed)
    if not groups:
        return EMPTY_PLAN
    block = program.blocks[0]
    plan = FusionPlan(source="forced" if force is not None else "tuned")

    prog_sig = shp = dk = None
    cache = None
    if force is None:
        cache = get_cache()
        prog_sig = program_signature(program)
        shp = shape_family(feed_shapes)
        dk = _device_kind()

    for g in groups:
        cert = certificate(program, g)
        if force is not None:
            wanted = (force is True
                      or (hasattr(force, "__contains__")
                          and g.op_idxs[0] in force))
            if not wanted:
                continue
            fam = f"forced:g{g.op_idxs[0]}"
            if not region_schedulable(block, g):
                plan.rejected.append((fam, "not_schedulable"))
                obs.count("fluid.fusion_rejected_total",
                          reason="not_schedulable")
                continue
            plan.groups.append(g)
            plan.families.append(fam)
            obs.count("fluid.fused_regions_total", source="forced")
            continue

        fam = fusion_family(prog_sig, shp, group_signature(cert))

        def reject(reason: str) -> None:
            plan.rejected.append((fam, reason))
            obs.count("fluid.fusion_rejected_total", reason=reason)

        entry = (cache.get(FUSION_SPACE, g.kind, dk, fam)
                 if cache is not None else None)
        if entry is None:
            reject("no_entry")
            continue
        if entry.get("space_hash") != space_hash(FUSION_SPACE):
            reject("stale")
            continue
        p = entry.get("plan")
        if not isinstance(p, dict) or not isinstance(p.get("fuse"), bool):
            reject("invalid_plan")
            continue
        if (entry.get("program_signature") != prog_sig
                or not certificate_matches(entry.get("certificate"), g,
                                           cert["op_types"])):
            reject("cert_invalid")
            continue
        if not region_schedulable(block, g):
            reject("not_schedulable")
            continue
        if not p["fuse"]:
            reject("measured_slower")
            continue
        plan.groups.append(g)
        plan.families.append(fam)
        obs.count("fluid.fused_regions_total", source="tuned")
    if not plan.groups and not plan.rejected:
        return EMPTY_PLAN
    return plan


# --------------------------------------------------------------------------
# the measurement: fused-vs-unfused per certified group
# --------------------------------------------------------------------------

def _time_run(exe, program, feed, fetch, reps: int) -> float:
    """Best-of-``reps`` seconds of one whole ``exe.run`` dispatch — warmup
    (trace + XLA compile) strictly outside the window, every timed run
    host-synced by the numpy fetch read, same discipline as
    :func:`tune.driver.measure_callable`."""
    from .. import obs
    exe.run(program, feed=feed, fetch_list=fetch)     # trace+compile, untimed
    best = float("inf")
    for _ in range(max(1, reps)):
        t0 = time.perf_counter()
        exe.run(program, feed=feed, fetch_list=fetch)
        best = min(best, time.perf_counter() - t0)
        obs.count("tune.measurements_total", space="fusion")
    return best


def measure_fusion(program, startup, feed: Dict[str, Any],
                   fetch: Sequence[str], *, reps: int = 2,
                   note: str = "") -> List[Dict[str, Any]]:
    """Measure every certified group of ``program`` fused vs unfused —
    whole-pipeline executor dispatches, one group toggled at a time — and
    return one cache-entry row per group (``plan: {"fuse": bool}`` plus
    the certificate and both timings).  A group only earns ``fuse: true``
    by beating the unfused baseline on THIS backend; rows for losing
    groups persist too, so the consult can distinguish "measured slower"
    from "never measured"."""
    import numpy as np

    from ..fluid.executor import Executor, Scope
    fetch_names = [v if isinstance(v, str) else v.name for v in fetch]
    groups_src = _certified(program, feed, fetch_names)
    if not groups_src:
        return []
    prog_sig = program_signature(program)
    shp = shape_family({k: np.shape(v) for k, v in feed.items()})

    def timed(fuse) -> float:
        exe = Executor(scope=Scope(), fuse=fuse)
        if startup is not None:
            exe.run(startup)
        return _time_run(exe, program, feed, fetch_names, reps)

    base_s = timed(False)
    rows: List[Dict[str, Any]] = []
    for g in groups_src:
        cert = certificate(program, g)
        fused_s = timed(frozenset((g.op_idxs[0],)))
        fuse = fused_s < base_s
        rows.append({
            "space": FUSION_SPACE, "kernel": g.kind,
            "family": fusion_family(prog_sig, shp, group_signature(cert)),
            "plan": {"fuse": fuse},
            "tuned_ms": round(min(fused_s, base_s) * 1e3, 4),
            "heuristic_plan": {"fuse": False},
            "heuristic_ms": round(base_s * 1e3, 4),
            "fused_ms": round(fused_s * 1e3, 4),
            "unfused_ms": round(base_s * 1e3, 4),
            "speedup": round(base_s / fused_s, 3) if fused_s else None,
            "program_signature": prog_sig,
            "shape_family": shp,
            "certificate": cert,
            "n_ops": len(g.op_idxs),
            "candidates": 2,
            "note": note,
        })
    return rows


def _certified(program, feed, fetch_names):
    """Schedulable certified groups only — measuring an unschedulable
    group would time the unfused fallback twice and could persist a
    meaningless 'win'."""
    from ..analysis.dataflow import fusable_groups, region_schedulable
    block = program.blocks[0]
    return [g for g in fusable_groups(program, fetch=fetch_names,
                                      feed=list(feed))
            if region_schedulable(block, g)]


def build_proxy_program(*, batch: int = 32, width: int = 64,
                        depth: int = 3, seed: int = 0):
    """The driver's fusion-sweep workload: an MLP regression step whose
    graph carries BOTH certified group kinds — each fc layer's
    bias-add+activation is an elementwise chain, and a scale/add epilogue
    rides the logits — plus SGD, so donation interacts with the fused
    path exactly as in a real training loop.

    Resets the default programs (same contract as the benchmarks) and
    returns ``(main_program, startup_program, feed, fetch_names)``."""
    import numpy as np

    import paddle_tpu.fluid as fluid
    fluid.reset_default_programs()
    x = fluid.layers.data("fusion_x", shape=(width,))
    y = fluid.layers.data("fusion_y", shape=(1,))
    h = x
    for _ in range(depth):
        h = fluid.layers.fc(h, width, act="relu")
    out = fluid.layers.fc(h, 1)
    # elementwise epilogue chain on the residual: sub -> mul (squared err)
    err = fluid.layers.elementwise_sub(out, y)
    loss = fluid.layers.mean(fluid.layers.elementwise_mul(err, err))
    fluid.SGDOptimizer(1e-2).minimize(loss)
    rs = np.random.RandomState(seed)
    feed = {"fusion_x": rs.randn(batch, width).astype(np.float32),
            "fusion_y": rs.randn(batch, 1).astype(np.float32)}
    return (fluid.default_main_program(), fluid.default_startup_program(),
            feed, [loss.name])
