"""Plan spaces — the enumerable candidate sets the tuner searches.

The Tensor-Processing-Primitives shape (PAPERS.md): each hand kernel
exposes a SMALL spec of micro-kernel parameters, and tuning is a measured
search over their composition rather than a hand-written preference list.
Three spaces ship:

* ``fused_rnn`` — (block_b, chunk_t) launch plans for the whole-sequence
  LSTM/GRU kernels, per ``(kernel, shape family)``. Candidates are exactly
  the plans ``ops.rnn.plan_is_legal`` admits (one owner for the VMEM cost
  model), so a cached winner can never be an illegal launch.
* ``decode_route`` — the dense-vs-kernel crossover length for
  ``decode_attention`` / ``paged_decode_attention``: the tuner measures
  both routes over a length grid and persists the smallest length from
  which the kernel route stays faster (``kernel_min_len``; null when the
  dense route wins everywhere — the measured truth on CPU hosts).
* ``page_block`` — the paged KV-cache page size: candidates are the
  power-of-two blocks; ``PagePool(page_block=None)`` consults the winner
  and validates divisibility against its own ``max_len``/``cache_bucket``.
* ``fusion`` — per certified :func:`analysis.dataflow.fusable_groups`
  group: fuse into one dispatch region, or don't. The candidate set is
  binary but the key is rich — program signature + feed shape family +
  group signature per ``(group kind, device_kind)`` — and entries carry
  the dependence certificate they were measured under, so a consult can
  refuse anything the current program no longer proves
  (tune/fusion.py; the MEASURED-ONLY gate of ROADMAP item 3c).
* ``bucket_grid`` — the prompt/cache bucket grids serving compiles
  against: candidates are whole grids; the measured cost of a grid is
  the replayed dispatch time of a deterministic length sample plus the
  compile cost of every distinct bucket the sample touches — the
  compile-count × padding-waste tradeoff measured instead of guessed.
  ``PagePool(prompt_buckets=None / cache_bucket=None)`` and
  ``BucketSpec({"feed": "tuned"})`` consult the winner with legality
  validation (ascending, positive, bounded by the caller's max_len).

Every space carries a static ``SPACE_DEFS`` literal; :func:`space_hash`
digests it. Entries persist the hash they were tuned under, so a code
change to a candidate set invalidates old winners — ignored at consult
time, reported by ``paddle_tpu lint`` as L008.
"""

from __future__ import annotations

import hashlib
import json
from typing import Any, Dict, List, Optional, Tuple

#: static, hash-stable definition of each plan space. Bump ``version`` (or
#: change any constant) to invalidate previously tuned entries.
SPACE_DEFS: Dict[str, Dict[str, Any]] = {
    "fused_rnn": {
        "version": 1,
        "blocks": [8, 16, 32, 64],
        "chunks": [8, 16, 32, 64, 128, 256],
        "budget_bytes": 15_500_000,
    },
    "decode_route": {
        "version": 1,
        "routes": ["dense", "kernel"],
        "plan": "kernel_min_len",
    },
    "page_block": {
        "version": 1,
        "blocks": [16, 32, 64, 128],
    },
    "fusion": {
        "version": 1,
        "kinds": ["elementwise_chain", "producer_consumer"],
        "plan": "fuse",
    },
    "bucket_grid": {
        "version": 1,
        "kinds": ["prompt", "cache"],
        "grids": {
            "prompt": [[32, 64, 128, 256, 512], [64, 128, 256, 512],
                       [64, 256, 512], [128, 256, 512],
                       [32, 64, 128, 256], [256, 512]],
            "cache": [[128, 256, 512, 1024], [256, 512, 1024],
                      [256, 1024], [512, 1024]],
        },
    },
}

SPACE_NAMES = tuple(sorted(SPACE_DEFS))


def _hash_def(name: str) -> str:
    blob = json.dumps({"space": name, "def": SPACE_DEFS[name]},
                      sort_keys=True)
    return hashlib.sha1(blob.encode()).hexdigest()[:12]


#: digests computed ONCE at import — consults run on trace-time paths, so
#: space_hash must be a dict get, not a re-serialization
_SPACE_HASHES: Dict[str, str] = {n: _hash_def(n) for n in SPACE_DEFS}


def space_hash(name: str) -> str:
    """Stable digest of one plan space's candidate-set definition."""
    return _SPACE_HASHES[name]


def fused_family(*, gates: int, T: int, H: int, batch: int) -> str:
    """The fused-RNN shape-family key — exact (gates, T, H, B): a tuned
    tile plan is only as good as the shape it was measured on, so lookups
    never interpolate across shapes (a near-miss falls back to the
    heuristic, which handles any shape)."""
    return f"g{gates}_t{T}_h{H}_b{batch}"


def fused_candidates(*, T: int, H: int, gates: int,
                     seq_h_units: Optional[int] = None,
                     batch: int,
                     double_buffer_always: bool = False
                     ) -> List[Tuple[int, int]]:
    """Every legal (block_b, chunk_t) for one fused-RNN family, via the
    ONE VMEM legality model (``ops.rnn.plan_is_legal``)."""
    from ..ops import rnn
    d = SPACE_DEFS["fused_rnn"]
    if seq_h_units is None:
        seq_h_units = gates + 1
    blocks = [b for b in d["blocks"] if b <= max(batch, 8)]
    if batch < 8:
        blocks = [batch]
    out: List[Tuple[int, int]] = []
    chunks = sorted({min(c, T) for c in d["chunks"] if c <= T} | {T})
    for blk in blocks:
        for chunk in chunks:
            if rnn.plan_is_legal(T, H, gates, seq_h_units, batch, blk,
                                 chunk, budget_bytes=d["budget_bytes"],
                                 double_buffer_always=double_buffer_always):
                out.append((blk, chunk))
    return out


#: measurement profiles: which families/lengths the driver sweeps.
#: ``smoke`` is the CI/--check profile (seconds, CPU interpret); ``cpu``
#: is the default off-TPU profile — PROXY dims of the textcls/NMT
#: families sized for the interpreter (noted on every row/entry);
#: ``bench`` is the on-chip profile with the real bench-family shapes.
PROFILES: Dict[str, Dict[str, Any]] = {
    "smoke": {
        "reps": 1,
        "fused_families": [
            {"kernel": "lstm_sequence_fused", "gates": 4, "seq_h_units": 6,
             "T": 8, "H": 8, "batch": 8, "note": "smoke"},
        ],
        "decode": {"lengths": [32, 64], "batch": 2, "n_heads": 2,
                   "d_head": 8, "note": "smoke"},
        "page_block": {"read_pages": 4, "batch": 2, "n_heads": 2,
                       "d_head": 8, "blocks": [16, 32], "note": "smoke"},
        "fusion": {"batch": 8, "width": 16, "depth": 2, "note": "smoke"},
        "bucket_grid": {"batch": 2, "d_model": 16, "max_len": 128,
                        "samples": 16, "zipf_a": 1.2, "note": "smoke"},
    },
    "cpu": {
        "reps": 2,
        "fused_families": [
            # textcls-h256 proxy (interpret-sized: same gate structure,
            # reduced T/H/B so the sweep finishes in CI time)
            {"kernel": "lstm_sequence_fused", "gates": 4, "seq_h_units": 6,
             "T": 16, "H": 32, "batch": 16, "note": "textcls proxy"},
            # NMT-encoder GRU proxy
            {"kernel": "gru_sequence_fused", "gates": 3, "seq_h_units": 4,
             "T": 16, "H": 32, "batch": 16, "note": "nmt-encoder proxy"},
        ],
        "decode": {"lengths": [64, 128, 256], "batch": 4, "n_heads": 4,
                   "d_head": 8, "note": "serving-dims proxy"},
        "page_block": {"read_pages": 8, "batch": 4, "n_heads": 4,
                       "d_head": 8, "blocks": [16, 32, 64],
                       "note": "serving-dims proxy"},
        # MLP-with-epilogues proxy: carries both certified group kinds
        # (fc->act producer_consumer epilogues + scale/add chains)
        "fusion": {"batch": 32, "width": 64, "depth": 3,
                   "note": "mlp proxy"},
        "bucket_grid": {"batch": 4, "d_model": 64, "max_len": 512,
                        "samples": 48, "zipf_a": 1.2,
                        "note": "serving-dims proxy"},
    },
    "bench": {
        "reps": 3,
        "fused_families": [
            {"kernel": "lstm_sequence_fused", "gates": 4, "seq_h_units": 6,
             "T": 64, "H": 256, "batch": 64, "note": "textcls h256"},
            {"kernel": "lstm_sequence_fused", "gates": 4, "seq_h_units": 6,
             "T": 64, "H": 512, "batch": 64, "note": "textcls h512"},
            {"kernel": "gru_sequence_fused", "gates": 3, "seq_h_units": 4,
             "T": 32, "H": 512, "batch": 64, "note": "nmt encoder"},
        ],
        "decode": {"lengths": [128, 256, 512, 1024, 2048], "batch": 8,
                   "n_heads": 12, "d_head": 64, "note": "gpt2s decode"},
        "page_block": {"read_pages": 16, "batch": 8, "n_heads": 12,
                       "d_head": 64, "blocks": [16, 32, 64, 128],
                       "note": "gpt2s decode"},
        "fusion": {"batch": 256, "width": 256, "depth": 4,
                   "note": "mlp bench dims"},
        "bucket_grid": {"batch": 8, "d_model": 768, "max_len": 2048,
                        "samples": 96, "zipf_a": 1.2,
                        "note": "gpt2s serving"},
    },
}
