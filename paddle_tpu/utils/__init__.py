from .flags import FLAGS, Flags
from .logging import get_logger, logger
from .registry import Registry
from .retry import RetryBudgetExceeded, RetryPolicy
from .stats import GLOBAL_STATS, StatSet, StatSnapshot, timer

__all__ = ["FLAGS", "Flags", "Registry", "StatSet", "StatSnapshot",
           "GLOBAL_STATS", "timer",
           "get_logger", "logger", "RetryPolicy", "RetryBudgetExceeded"]
