from .flags import FLAGS, Flags
from .logging import get_logger, logger
from .registry import Registry
from .retry import RetryBudgetExceeded, RetryPolicy
from .stats import GLOBAL_STATS, StatSet, timer

__all__ = ["FLAGS", "Flags", "Registry", "StatSet", "GLOBAL_STATS", "timer",
           "get_logger", "logger", "RetryPolicy", "RetryBudgetExceeded"]
