"""Global runtime flags.

Analog of the reference's gflags wrapper (paddle/utils/Flags.h:19-43) which centralizes
process-level knobs (``use_gpu``, ``trainer_count``, ``trainer_id``, ``log_period``,
``parallel_nn``, ...). Here flags are a typed namespace that can be overridden from the
environment (``PDTPU_<NAME>``) or programmatically; the TPU-relevant set replaces the
GPU/pserver knobs with mesh/platform ones.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field, fields
from typing import Any, Optional, Tuple


def _env(name: str, default, cast):
    raw = os.environ.get("PDTPU_" + name.upper())
    if raw is None:
        return default
    if cast is bool:
        return raw.lower() in ("1", "true", "yes", "on")
    return cast(raw)


@dataclass
class Flags:
    # platform selection: "tpu" | "cpu" | "" (= let jax pick)
    platform: str = ""
    # mesh shape for data/model axes when using the default mesh helpers
    trainer_count: int = 0            # 0 = all local devices (ref: Flags.h trainer_count)
    trainer_id: int = 0               # process index in multi-host runs
    # numerics
    default_dtype: str = "float32"
    matmul_precision: str = "default"  # "default" | "bfloat16" | "highest"
    # logging / metrics cadence (ref: --log_period)
    log_period: int = 100
    show_parameter_stats_period: int = 0
    # checkpointing (ref: --saving_period / save_dir)
    save_dir: str = "output"
    saving_period: int = 1
    # data pipeline
    prefetch_depth: int = 2           # double-buffer depth (ref DataProvider DoubleBuffer)
    seed: int = 0

    def update(self, **kw):
        for k, v in kw.items():
            if not hasattr(self, k):
                raise AttributeError(f"unknown flag '{k}'")
            setattr(self, k, v)
        return self


def _from_env() -> Flags:
    f = Flags()
    for fld in fields(Flags):
        setattr(f, fld.name, _env(fld.name, getattr(f, fld.name), type(getattr(f, fld.name))))
    return f


FLAGS = _from_env()
