"""Logging setup — analog of paddle/utils/Logging.h (glog-style)."""

from __future__ import annotations

import logging
import sys

_FORMAT = "%(levelname).1s %(asctime)s %(name)s] %(message)s"


def get_logger(name: str = "paddle_tpu") -> logging.Logger:
    logger = logging.getLogger(name)
    if not logger.handlers:
        handler = logging.StreamHandler(sys.stderr)
        handler.setFormatter(logging.Formatter(_FORMAT, datefmt="%m%d %H:%M:%S"))
        logger.addHandler(handler)
        logger.setLevel(logging.INFO)
        logger.propagate = False
    return logger


logger = get_logger()
