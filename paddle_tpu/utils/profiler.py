"""Device profiling — the hl_profiler_start/hl_profiler_stop analog.

Reference: `hl_profiler_start/end` wrap cudaProfilerStart/Stop
(cuda/src/hl_cuda_device.cc:675-677, WITH_PROFILER gate; exercised by
math/tests/test_GpuProfiler.cpp with nvprof markers). TPU-native: the jax/XLA
profiler — traces carry XLA op timelines, HBM usage, and host annotations,
viewable in TensorBoard/xprof/Perfetto.

* :func:`start` / :func:`stop` — begin/end a trace into a log dir.
* :func:`profile` — context manager form.
* :func:`annotate` — named host-span annotation appearing on the trace
  (the REGISTER_TIMER_INFO marker analog); StatSet timers also annotate
  when a trace is active.
"""

from __future__ import annotations

import glob
import os
from contextlib import contextmanager
from typing import Optional

import jax

_active_dir: Optional[str] = None


def start(logdir: str):
    """Begin an XLA trace (cudaProfilerStart analog)."""
    global _active_dir
    os.makedirs(logdir, exist_ok=True)
    jax.profiler.start_trace(logdir)
    _active_dir = logdir


def stop() -> Optional[str]:
    """End the trace; returns the logdir (traces land under
    plugins/profile/<ts>/ as .xplane.pb)."""
    global _active_dir
    jax.profiler.stop_trace()
    d, _active_dir = _active_dir, None
    return d


def is_active() -> bool:
    return _active_dir is not None


@contextmanager
def profile(logdir: str):
    start(logdir)
    try:
        yield logdir
    finally:
        stop()


def annotate(name: str):
    """Named span on the device trace (TraceAnnotation) — pairs with the
    scoped StatSet timers the way REGISTER_TIMER_INFO named GPU ranges."""
    return jax.profiler.TraceAnnotation(name)


def trace_files(logdir: str):
    """The .xplane.pb artifacts produced under ``logdir``."""
    return sorted(glob.glob(os.path.join(logdir, "plugins", "profile",
                                         "*", "*.xplane.pb")))


def device_memory_stats(device=None) -> dict:
    """Live HBM statistics for a device (the memory/ observability the
    reference exposed through its allocator counters): bytes_in_use,
    peak_bytes_in_use, bytes_limit where the backend reports them."""
    if device is None:
        device = jax.devices()[0]
    stats = getattr(device, "memory_stats", lambda: None)()
    return dict(stats) if stats else {}


def save_device_memory_profile(path: str, backend: Optional[str] = None):
    """Dump a pprof-format device memory profile (jax.profiler
    .save_device_memory_profile) — who holds HBM right now.

    Backend-dependent: some remote PJRT plugins (e.g. tunneled dev chips)
    do not implement the heap-profile callbacks and abort the process —
    call on direct-attached devices / the CPU backend."""
    jax.profiler.save_device_memory_profile(path, backend=backend)
    return path
