"""Class/function registry.

TPU-native analog of the reference's ``ClassRegistrar`` (paddle/utils/ClassRegistrar.h)
and the op/layer registration macros (``REGISTER_LAYER`` at gserver/layers/Layer.h:31,
``REGISTER_OP*`` at framework/op_registry.h:129-233). One generic registry class is
enough here: layers, ops, activations, evaluators, datasets and readers each hold an
instance.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Iterable, Optional


class Registry:
    """Name -> callable registry with decorator-style registration."""

    def __init__(self, kind: str):
        self.kind = kind
        self._entries: Dict[str, Any] = {}

    def register(self, name: Optional[str] = None, obj: Any = None):
        """Register ``obj`` under ``name``.

        Usable as ``@registry.register()``, ``@registry.register("name")`` or
        directly ``registry.register("name", obj)``.
        """
        if obj is not None:
            self._register(name or getattr(obj, "__name__"), obj)
            return obj

        def deco(fn):
            self._register(name or fn.__name__, fn)
            return fn

        return deco

    def _register(self, name: str, obj: Any):
        if name in self._entries:
            raise KeyError(f"{self.kind} '{name}' registered twice")
        self._entries[name] = obj

    def get(self, name: str) -> Any:
        try:
            return self._entries[name]
        except KeyError:
            known = ", ".join(sorted(self._entries))
            raise KeyError(f"unknown {self.kind} '{name}'; known: {known}") from None

    def __contains__(self, name: str) -> bool:
        return name in self._entries

    def __iter__(self) -> Iterable[str]:
        return iter(self._entries)

    def keys(self):
        return self._entries.keys()

    def items(self):
        return self._entries.items()
