"""Shared retry/backoff policy for every network edge of the runtime.

The reference retries ad-hoc: go/connection/conn.go reconnects in a bare
loop, the v2 master client sleeps a linear multiple of a base delay. Under a
real outage linear sleeps either hammer the server (too short) or waste the
recovery window (too long), and a loop with no overall deadline can wedge a
trainer forever. :class:`RetryPolicy` centralises the discipline:

* exponential backoff: ``base_delay * multiplier**attempt``
* decorrelated jitter: each delay is scaled by a uniform draw from
  ``[1-jitter, 1+jitter]`` (seedable — deterministic in tests)
* ``max_delay`` cap, so backoff never exceeds one recovery probe interval
* overall ``deadline`` (seconds from first attempt): when the budget is
  spent the last error is re-raised — a caller never blocks unboundedly
* a ``retryable`` exception predicate: anything else propagates immediately

Time is injectable (``clock``/``sleep``) so chaos tests drive a fake clock
and the whole suite runs with **no real sleeps** (ISSUE 2 CI constraint).
"""

from __future__ import annotations

import random
import time
from typing import Callable, Optional, Tuple, Type, Union

RetryableSpec = Union[Type[BaseException], Tuple[Type[BaseException], ...],
                      Callable[[BaseException], bool]]


class RetryBudgetExceeded(ConnectionError):
    """Raised when attempts/deadline are exhausted; carries the tally."""

    def __init__(self, msg: str, *, attempts: int,
                 last_error: Optional[BaseException] = None):
        super().__init__(msg)
        self.attempts = attempts
        self.last_error = last_error


class RetryPolicy:
    """Exponential-backoff retry schedule with jitter, cap and deadline.

    Args:
      max_attempts: total tries (first call included). ``None`` = unbounded
        (then ``deadline`` must bound the loop).
      base_delay: pre-jitter delay after the first failure, seconds.
      multiplier: exponential growth factor per attempt.
      max_delay: cap applied before jitter.
      deadline: overall budget in seconds from the first attempt; ``None``
        disables it.
      jitter: +/- fraction of each delay randomised (0 = deterministic).
      retryable: exception class(es) or predicate deciding what to retry.
      sleep/clock: injectable time functions (fake clock in tests).
      seed: seeds the jitter RNG for reproducible schedules.
      observer: optional stats callback ``observer(event, **info)`` —
        ``"attempt"`` (kw: attempt, delay, error) before each backoff
        sleep, ``"giveup"`` (kw: attempts, error) when the budget is
        spent, ``"success"`` (kw: attempts) on a retried call that then
        succeeded. This is how the observability plane subscribes
        (``paddle_tpu.obs.retry_observer``) without this module importing
        ``obs`` — the policy stays dependency-free and the callback is
        plain data out.
    """

    def __init__(self, *, max_attempts: Optional[int] = 5,
                 base_delay: float = 0.05, multiplier: float = 2.0,
                 max_delay: float = 2.0, deadline: Optional[float] = None,
                 jitter: float = 0.25,
                 retryable: RetryableSpec = (OSError, ConnectionError),
                 sleep: Callable[[float], None] = time.sleep,
                 clock: Callable[[], float] = time.monotonic,
                 seed: Optional[int] = None,
                 observer: Optional[Callable[..., None]] = None):
        if max_attempts is None and deadline is None:
            raise ValueError("unbounded policy: set max_attempts or deadline")
        if max_attempts is not None and max_attempts < 1:
            raise ValueError("max_attempts must be >= 1")
        if not 0.0 <= jitter < 1.0:
            raise ValueError("jitter must be in [0, 1)")
        self.max_attempts = max_attempts
        self.base_delay = base_delay
        self.multiplier = multiplier
        self.max_delay = max_delay
        self.deadline = deadline
        self.jitter = jitter
        self.retryable = retryable
        self.sleep = sleep
        self.clock = clock
        self.observer = observer
        self._rng = random.Random(seed)

    def _observe(self, event: str, **info) -> None:
        if self.observer is not None:
            self.observer(event, **info)

    def delay_for(self, attempt: int) -> float:
        """Pre-jitter delay after failed attempt ``attempt`` (0-based)."""
        return min(self.base_delay * (self.multiplier ** attempt),
                   self.max_delay)

    def _jittered(self, delay: float) -> float:
        if self.jitter == 0.0:
            return delay
        return delay * self._rng.uniform(1.0 - self.jitter, 1.0 + self.jitter)

    def is_retryable(self, exc: BaseException) -> bool:
        if callable(self.retryable) and not isinstance(self.retryable, type):
            return bool(self.retryable(exc))
        return isinstance(exc, self.retryable)  # type: ignore[arg-type]

    def call(self, fn: Callable, *args,
             on_retry: Optional[Callable[[int, BaseException], None]] = None,
             describe: str = "operation", **kw):
        """Run ``fn(*args, **kw)`` under the policy.

        Non-retryable exceptions propagate untouched. On budget exhaustion
        raises :class:`RetryBudgetExceeded` naming the attempt count — the
        "surface attempt count in the final ConnectionError" contract of
        ISSUE 2 — chaining the last underlying error.
        """
        start = self.clock()
        attempt = 0
        last: Optional[BaseException] = None
        while True:
            try:
                result = fn(*args, **kw)
                if attempt:
                    self._observe("success", attempts=attempt + 1)
                return result
            except BaseException as e:
                if not self.is_retryable(e):
                    raise
                last = e
            attempt += 1
            if self.max_attempts is not None and attempt >= self.max_attempts:
                break
            delay = self._jittered(self.delay_for(attempt - 1))
            if self.deadline is not None and \
                    (self.clock() - start) + delay > self.deadline:
                break
            if on_retry is not None:
                on_retry(attempt, last)
            self._observe("attempt", attempt=attempt, delay=delay,
                          error=last)
            if delay > 0:
                self.sleep(delay)
        self._observe("giveup", attempts=attempt, error=last)
        raise RetryBudgetExceeded(
            f"{describe} failed after {attempt} attempt(s): {last}",
            attempts=attempt, last_error=last) from last
