"""Scoped-timer statistics.

Analog of the reference's ``StatSet`` / ``REGISTER_TIMER*`` machinery
(paddle/utils/Stat.h:63-242), used along the whole train path
(TrainerInternal.cpp:94-152, NeuralNetwork.cpp:260). Python-side timers cover the host
loop; device time comes from jax profiler traces. A native C++ StatSet with the same
semantics lives in native/ (see paddle_tpu.utils.native) for the C++ runtime components.
"""

from __future__ import annotations

import threading
import time
from contextlib import contextmanager
from typing import Dict, NamedTuple


class StatSnapshot(NamedTuple):
    """Immutable point-in-time view of one timer — what :meth:`StatSet.items`
    hands out. The live :class:`StatItem` never leaves the lock: returning
    it let callers read ``total``/``count`` mid-update from another thread
    (torn averages) or mutate accumulator state they don't own."""

    name: str
    total: float
    count: int
    max: float
    avg: float


class StatItem:
    __slots__ = ("name", "total", "count", "max")

    def __init__(self, name: str):
        self.name = name
        self.total = 0.0
        self.count = 0
        self.max = 0.0

    def add(self, seconds: float):
        self.total += seconds
        self.count += 1
        if seconds > self.max:
            self.max = seconds

    @property
    def avg(self) -> float:
        return self.total / self.count if self.count else 0.0

    def __repr__(self):
        return (f"Stat={self.name:<30} total={self.total * 1e3:10.2f}ms "
                f"avg={self.avg * 1e3:8.3f}ms max={self.max * 1e3:8.3f}ms count={self.count}")


class StatSet:
    """Accumulates named timers; thread-safe like the reference's global StatSet."""

    def __init__(self):
        self._items: Dict[str, StatItem] = {}
        self._lock = threading.Lock()

    def add(self, name: str, seconds: float) -> None:
        """Accumulate one sample under the lock — the only mutation path,
        so concurrent timers never race on a shared StatItem."""
        with self._lock:
            item = self._items.get(name)
            if item is None:
                item = self._items[name] = StatItem(name)
            item.add(seconds)

    @contextmanager
    def timer(self, name: str):
        from contextlib import nullcontext

        from . import profiler
        # named span on the device trace (REGISTER_TIMER_INFO analog)
        span = profiler.annotate(name) if profiler.is_active() else nullcontext()
        t0 = time.perf_counter()
        with span:
            try:
                yield
            finally:
                self.add(name, time.perf_counter() - t0)

    def reset(self):
        with self._lock:
            self._items.clear()

    def report(self) -> str:
        with self._lock:
            lines = [repr(i) for i in sorted(self._items.values(), key=lambda i: -i.total)]
        return "\n".join(lines)

    def items(self) -> Dict[str, StatSnapshot]:
        """Immutable snapshots keyed by name (see :class:`StatSnapshot`)."""
        with self._lock:
            return {n: StatSnapshot(i.name, i.total, i.count, i.max, i.avg)
                    for n, i in self._items.items()}


GLOBAL_STATS = StatSet()
timer = GLOBAL_STATS.timer
