"""v2-style user API — the reference's ``paddle.v2`` facade.

Reference surface (python/paddle/v2/: trainer.py:24 SGD, layer.py, topology,
parameters.py, inference.py:111 infer, event.py, minibatch batch). Design:
unlike the reference — which kept two engines (gserver behind SWIG for v2,
the op framework for fluid) — this facade is a SECOND FRONT END over the same
fluid Program IR (the convergence the reference's refactorization doc aimed
for, doc/design/refactorization.md): ``v2.layer.*`` emit ops into a fluid
Program, and ``v2.trainer.SGD`` drives the fluid Executor.
"""

from .. import data as _data
from ..data import dataset
from ..trainer import event
from . import attr, data_type, evaluator, layer, networks, optimizer, topology
from .topology import Topology
from .inference import infer
from .parameters import Parameters
from .trainer import SGD

batch = _data.batch
reader = _data.reader

_initialized = {}


def init(**kwargs):
    """paddle.init analog: capture runtime flags (use_gpu->use_tpu etc.)."""
    _initialized.update(kwargs)
    return _initialized


__all__ = ["init", "layer", "networks", "data_type", "optimizer", "event",
           "evaluator", "attr", "dataset", "topology", "Topology",
           "batch", "reader", "SGD", "Parameters", "infer"]
