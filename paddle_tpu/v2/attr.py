"""Parameter / layer attributes — the ``paddle.v2.attr`` facade.

Reference surface: ``trainer_config_helpers/attrs.py`` ParameterAttribute
(:52 — name, is_static, initial_std/mean/max/min, l2_rate, learning_rate,
sparse_update) and ExtraLayerAttribute (:183 — drop_rate), re-exported by
``python/paddle/v2/attr.py``. The TPU-native mapping: attrs lower to
fluid-parameter settings at layer-build time — an exact ``name`` makes a
SECOND layer reuse the SAME parameter variable (the reference's name-based
weight sharing, e.g. between a training decoder and its generation
sub-model), ``is_static`` freezes it (no grad/update), and
``l2_rate``/``learning_rate`` ride the Program as per-variable fields that
``fluid.optimizer`` consumes.
"""

from __future__ import annotations

from typing import Optional

from ..nn import initializer as I


class ParameterAttribute:
    def __init__(self, name: Optional[str] = None, is_static: bool = False,
                 initial_std: Optional[float] = None,
                 initial_mean: Optional[float] = None,
                 initial_max: Optional[float] = None,
                 initial_min: Optional[float] = None,
                 l2_rate: Optional[float] = None,
                 learning_rate: Optional[float] = None,
                 sparse_update: bool = False):
        self.name = name
        self.is_static = is_static
        self.initial_std = initial_std
        self.initial_mean = initial_mean
        self.initial_max = initial_max
        self.initial_min = initial_min
        self.l2_rate = l2_rate
        self.learning_rate = learning_rate
        # advisory: the sparse path is chosen by the data type (SelectedRows
        # flows through ShardedEmbedding); kept for config compatibility
        self.sparse_update = sparse_update

    def initializer(self) -> Optional[I.Initializer]:
        if self.initial_max is not None or self.initial_min is not None:
            lo = self.initial_min if self.initial_min is not None else 0.0
            hi = self.initial_max if self.initial_max is not None else 1.0
            return I.uniform(lo, hi)
        if self.initial_std is not None or self.initial_mean is not None:
            return I.normal(self.initial_mean or 0.0,
                            self.initial_std if self.initial_std is not None
                            else 0.01)
        return None

    def to_fluid(self) -> dict:
        """The dict fluid.layers._create_parameter(attr=...) consumes."""
        d: dict = {}
        if self.name is not None:
            d["name"] = self.name
        if self.is_static:
            d["is_static"] = True
        init = self.initializer()
        if init is not None:
            d["init"] = init
        if self.l2_rate is not None:
            d["l2_rate"] = self.l2_rate
        if self.learning_rate is not None:
            d["lr_scale"] = self.learning_rate
        return d


class ExtraLayerAttribute:
    """Per-layer extras (attrs.py:183); ``drop_rate`` is the one with
    behavior — layers that take ``layer_attr`` append dropout after their
    activation."""

    def __init__(self, drop_rate: Optional[float] = None):
        self.drop_rate = drop_rate


# the reference's short aliases (v2/attr.py __all__)
Param = ParameterAttribute
ParamAttr = ParameterAttribute
Extra = ExtraLayerAttribute
ExtraAttr = ExtraLayerAttribute

__all__ = ["ParameterAttribute", "ExtraLayerAttribute", "Param", "ParamAttr",
           "Extra", "ExtraAttr"]
