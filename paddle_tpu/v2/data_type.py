"""Input-type declarations (paddle.v2.data_type analog).

Maps the reference's canonical feature taxonomy (SURVEY.md §8.2:
dense_vector / integer_value / sparse_binary_vector / sparse_float_vector,
each optionally *_sequence) onto feeder slots (data/feeder.py).
"""

from __future__ import annotations

from dataclasses import dataclass

from ..data.feeder import DenseSlot, IndexSlot, SeqSlot, SparseSlot


@dataclass
class InputType:
    slot: object
    is_seq: bool = False
    vocab: int = 0       # value range for integer types (embedding table size)


def dense_vector(dim: int) -> InputType:
    return InputType(DenseSlot(dim))


def integer_value(value_range: int) -> InputType:
    return InputType(IndexSlot(), vocab=value_range)


def integer_value_sequence(value_range: int) -> InputType:
    return InputType(SeqSlot(), is_seq=True, vocab=value_range)


def dense_vector_sequence(dim: int) -> InputType:
    return InputType(SeqSlot(elem_dim=dim), is_seq=True)


def sparse_binary_vector(dim: int) -> InputType:
    return InputType(SparseSlot(dim))


def sparse_float_vector(dim: int) -> InputType:
    return InputType(SparseSlot(dim, with_values=True))


def integer_value_sub_sequence(value_range: int) -> InputType:
    """2-level LoD id input (the reference's *_sub_sequence types feeding
    nested recurrent groups) -> NestedSeqBatch."""
    return InputType(SeqSlot(nested=True), is_seq=True, vocab=value_range)


def dense_vector_sub_sequence(dim: int) -> InputType:
    return InputType(SeqSlot(elem_dim=dim, nested=True), is_seq=True)
