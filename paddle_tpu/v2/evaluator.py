"""v2 evaluator DSL (trainer_config_helpers/evaluators.py analog).

The reference attaches evaluators inside the model config
(classification_error_evaluator:211, auc_evaluator:263, sum_evaluator:519,
value_printer:576 ...); each becomes part of the proto and is computed by
the C++ Evaluator zoo every batch. Here each ``*_evaluator`` call emits the
metric as in-graph ops and returns a LayerOutput — pass it to
``SGD(..., extra_layers=[...])`` and the per-batch value arrives in the
EndIteration event's metrics dict (one fused computation with the train
step, no second forward).

Host-side streaming accumulation across batches (AUC histograms, chunk F1,
detection mAP, CTC error) lives in :mod:`paddle_tpu.trainer.evaluator`;
these in-graph evaluators are the per-batch config-DSL surface.
"""

from __future__ import annotations

from typing import Optional

from ..fluid import layers as FL
from .layer import LayerOutput, _emit, _shape


def classification_error_evaluator(input: LayerOutput,
                                   label: LayerOutput) -> LayerOutput:
    """Per-batch error rate 1 - accuracy (evaluators.py:211). The metric
    arrives in EndIteration.metrics keyed by the returned layer's var name."""
    acc = FL.accuracy(input.var, label.var)
    err = _emit("scale", {"X": [acc.name]}, {"scale": -1.0, "bias": 1.0},
                out_shape=())
    return LayerOutput(err)


def auc_evaluator(input: LayerOutput, label: LayerOutput,
                  num_thresholds: int = 200,
                  positive_label: int = 1) -> LayerOutput:
    """Per-batch AUC (evaluators.py:263). ``input`` may be [B, C] logits
    (the positive-class softmax probability is extracted) or already-[B]
    positive scores."""
    var = input.var
    shp = _shape(input)
    if len(shp) >= 2 and shp[-1] != 1:
        probs = _emit("softmax", {"X": [var.name]}, out_shape=shp)
        col = _emit("crop", {"X": [probs.name]},
                    {"offsets": [0, positive_label], "shape": [-1, 1]},
                    out_shape=shp[:-1] + (1,))
        var = _emit("squeeze", {"X": [col.name]}, {"axis": -1},
                    out_shape=shp[:-1])
    elif len(shp) >= 2:           # [B, 1] scores: drop the unit column too
        var = _emit("squeeze", {"X": [var.name]}, {"axis": -1},
                    out_shape=shp[:-1])
    v = FL.auc(var, label.var, num_thresholds=num_thresholds)
    return LayerOutput(v)


def sum_evaluator(input: LayerOutput) -> LayerOutput:
    """Sum of the input over the batch (evaluators.py:519)."""
    v = _emit("reduce_sum", {"X": [input.var.name]}, {"dim": None},
              out_shape=())
    return LayerOutput(v)


def column_sum_evaluator(input: LayerOutput) -> LayerOutput:
    """Per-column sums (evaluators.py:545)."""
    v = _emit("reduce_sum", {"X": [input.var.name]}, {"dim": 0},
              out_shape=_shape(input)[1:])
    return LayerOutput(v)


def precision_recall_evaluator(input: LayerOutput, label: LayerOutput,
                               positive_label: int = 1) -> LayerOutput:
    """Per-batch F1 for one positive class (evaluators.py:340's role; the
    streaming multi-class version is trainer.PrecisionRecallEvaluator).
    Lowers to the registry's ``binary_f1`` op (built on
    ops/metrics.precision_recall_counts)."""
    v = _emit("binary_f1",
              {"X": [input.var.name], "Label": [label.var.name]},
              {"positive_label": positive_label}, out_shape=())
    return LayerOutput(v)


def value_printer_evaluator(input: LayerOutput,
                            head: int = 8) -> LayerOutput:
    """Printer evaluator (evaluators.py:576): surfaces the first values of a
    layer as a fetchable metric vector (host logging decides formatting)."""
    shp = _shape(input)
    known = all(d and d > 0 for d in shp[1:])   # batch dim may be dynamic
    if known and len(shp) >= 1:
        # static bound on the slice: never larger than one sample row
        per_row = 1
        for d in shp[1:]:
            per_row *= d
        head = min(head, max(per_row, 1))
    flat = _emit("reshape", {"X": [input.var.name]}, {"shape": (-1,)},
                 out_shape=(-1,))
    # the flattened batch can still be shorter than `head` at runtime (tiny
    # batch, dynamic row size): pad up to `head` so the crop never reads
    # out of bounds
    padded = _emit("pad", {"X": [flat.name]}, {"paddings": [[0, head]]},
                   out_shape=(-1,))
    v = _emit("crop", {"X": [padded.name]}, {"offsets": [0], "shape": [head]},
              out_shape=(head,))
    return LayerOutput(v)


def maxid_printer_evaluator(input: LayerOutput) -> LayerOutput:
    """Printer of argmax ids (evaluators.py:622)."""
    v = _emit("argmax", {"X": [input.var.name]},
              out_shape=_shape(input)[:-1], out_dtype="int32")
    return LayerOutput(v)
