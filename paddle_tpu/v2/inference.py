"""infer() facade (python/paddle/v2/inference.py:111)."""

from __future__ import annotations

from typing import Optional, Sequence

import numpy as np

from .layer import LayerOutput
from .trainer import SGD, _V2Feeder


def infer(output_layer: LayerOutput, trainer: SGD, input,
          feeding: Optional[Sequence[LayerOutput]] = None) -> np.ndarray:
    """Run the trained program forward and fetch ``output_layer`` for a batch
    of raw rows (same reader-row format as training)."""
    feed = _V2Feeder(feeding)(input) if feeding else input
    out, = trainer.exe.run(feed=feed, fetch_list=[output_layer.var])
    return np.asarray(out)
