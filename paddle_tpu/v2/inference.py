"""infer() facade (python/paddle/v2/inference.py:111).

Field selection follows the reference Inference.infer: ``field`` may be one
name or a list drawn from {'value', 'prob', 'id'}; multiple fields return a
tuple in the requested order. 'value'/'prob' fetch the activation tensor;
'id' fetches integer outputs directly or the argmax of a float distribution
(the reference reads Arguments.ids, which its id-emitting layers populate).
Sequence outputs (a lengths-carrying LayerOutput) come back as a list of
per-sample arrays trimmed to their true lengths, the analog of the
reference's row-slicing by sequence start positions.
"""

from __future__ import annotations

from typing import Optional, Sequence, Union

import numpy as np

from .layer import LayerOutput
from .trainer import SGD, _V2Feeder

_FIELDS = ("value", "prob", "id")


def _select(field: str, out: np.ndarray):
    if field not in _FIELDS:
        raise ValueError(f"field must be one of {_FIELDS}, got {field!r}")
    if field == "id" and not np.issubdtype(out.dtype, np.integer):
        out = np.argmax(out, axis=-1).astype(np.int32)
    return out


def infer(output_layer: Union[LayerOutput, Sequence[LayerOutput]],
          trainer: SGD, input,
          feeding: Optional[Sequence[LayerOutput]] = None,
          field: Union[str, Sequence[str]] = "value"):
    """Run the trained program forward and fetch ``output_layer`` for a batch
    of raw rows (same reader-row format as training).

    Returns one result per (layer, field) pair, flattened in layer-major
    order like the reference; a single pair returns the bare result.
    """
    layers = ([output_layer] if isinstance(output_layer, LayerOutput)
              else list(output_layer))
    fields = [field] if isinstance(field, str) else list(field)
    for f in fields:                         # fail fast, before device work
        if f not in _FIELDS:
            raise ValueError(f"field must be one of {_FIELDS}, got {f!r}")
    feed = _V2Feeder(feeding)(input) if feeding else input

    fetch_vars = [l.var for l in layers]
    len_idx = {}
    for i, l in enumerate(layers):
        if l.lengths is not None:
            len_idx[i] = len(fetch_vars)
            fetch_vars.append(l.lengths)
    outs = trainer.exe.run(feed=feed, fetch_list=fetch_vars)

    results = []
    for i, l in enumerate(layers):
        raw = np.asarray(outs[i])
        for f in fields:
            sel = _select(f, raw)
            if i in len_idx:
                lens = np.asarray(outs[len_idx[i]]).astype(np.int64)
                sel = [sel[b, : lens[b]] for b in range(sel.shape[0])]
            results.append(sel)
    return results[0] if len(results) == 1 else tuple(results)
