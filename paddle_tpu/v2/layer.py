"""v2 layer DSL emitting fluid ops.

Mirrors the surface of python/paddle/v2/layer.py + trainer_config_helpers/
layers.py (fc, embedding, lstmemory, conv, pooling, costs), but each call
appends to the fluid default programs. Sequence-typed layers carry a paired
``<name>__len__`` lengths variable (the LoD metadata under the static-shape
regime — core/lod.py).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Optional

from ..fluid import layers as FL
from ..fluid.framework import Variable, default_main_program
from ..nn import initializer as I
from .data_type import InputType


@dataclass
class LayerOutput:
    var: Variable
    lengths: Optional[Variable] = None      # set for sequence outputs
    input_type: Optional[InputType] = None
    sub_lengths: Optional[Variable] = None  # set for nested (2-level LoD) data

    @property
    def name(self):
        return self.var.name


def data(name: str, type: InputType) -> LayerOutput:
    """paddle.v2.layer.data analog; sequence types get a lengths feed var,
    nested (sub-sequence) types additionally a [S] sub-lengths feed var."""
    if type.is_seq:
        elem = getattr(type.slot, "elem_dim", None)
        nested = getattr(type.slot, "nested", False)
        if nested:
            shape = (-1, -1) if elem is None else (-1, -1, elem)
            dtype = "int32" if elem is None else "float32"
            v = FL.data(name, shape=shape, dtype=dtype)        # [B, S, T(, D)]
            sublens = FL.data(name + "__sublen__", shape=(-1,), dtype="int32")
            lens = FL.data(name + "__len__", shape=(), dtype="int32")
            return LayerOutput(v, lens, type, sub_lengths=sublens)
        if elem is None:
            v = FL.data(name, shape=(-1,), dtype="int32")
        else:
            v = FL.data(name, shape=(-1, elem), dtype="float32")
        lens = FL.data(name + "__len__", shape=(), dtype="int32")
        return LayerOutput(v, lens, type)
    from ..data.feeder import DenseSlot, IndexSlot, SparseSlot
    if isinstance(type.slot, DenseSlot):
        v = FL.data(name, shape=(type.slot.dim,))
    elif isinstance(type.slot, IndexSlot):
        v = FL.data(name, shape=(), dtype="int32")
    else:  # sparse: padded (ids, vals) pair
        v = FL.data(name, shape=(-1,), dtype="int32")
        vals = FL.data(name + "__vals__", shape=(-1,), dtype="float32")
    return LayerOutput(v, None, type)


def fc(input, size: int, act: Optional[str] = None,
       bias_attr: bool = True, name: Optional[str] = None) -> LayerOutput:
    """Accepts a single layer or a list (concatenated, like the reference's
    multi-input fc). ``name`` registers the output for memory() binding
    inside a recurrent_group/beam_search step."""
    if isinstance(input, (list, tuple)):
        var = FL.concat([i.var for i in input], axis=-1)
    else:
        var = input.var
    out = FL.fc(var, size, act=act, bias_attr=bias_attr)
    _register_named(name, out)
    return LayerOutput(out)


def embedding(input: LayerOutput, size: int) -> LayerOutput:
    t = input.input_type
    if t is None or not t.vocab:
        raise ValueError("embedding needs a data layer typed "
                         "integer_value[_sequence](vocab_size)")
    out = FL.embedding(input.var, (t.vocab, size))
    return LayerOutput(out, input.lengths, input.input_type,
                       sub_lengths=input.sub_lengths)


def _seq_op(op_type, input: LayerOutput, extra_attrs=None, out_shape=None,
            seq_out=False, params=None) -> LayerOutput:
    b = default_main_program().global_block()
    out = b.create_var(shape=out_shape or input.var.shape,
                       dtype="float32")
    inputs = {"X": [input.var.name], "Lengths": [input.lengths.name]}
    if params:
        inputs.update(params)
    b.append_op(op_type, inputs, {"Out": [out.name]}, extra_attrs or {})
    return LayerOutput(out, input.lengths if seq_out else None,
                       input.input_type if seq_out else None)


def lstmemory(input: LayerOutput, size: int, reverse: bool = False,
              forget_bias: float = 1.0) -> LayerOutput:
    """Whole-sequence masked LSTM (simple_lstm/lstmemory analog)."""
    b = default_main_program().global_block()
    in_dim = input.var.shape[-1]
    w = FL._create_parameter("lstm_w", (in_dim, 4 * size), "float32",
                             I.uniform(-0.08, 0.08))
    u = FL._create_parameter("lstm_u", (size, 4 * size), "float32",
                             I.uniform(-0.08, 0.08))
    bias = FL._create_parameter("lstm_b", (4 * size,), "float32", I.zeros)
    out = b.create_var(shape=input.var.shape[:-1] + (size,), dtype="float32")
    last_h = b.create_var(shape=(-1, size), dtype="float32")
    last_c = b.create_var(shape=(-1, size), dtype="float32")
    b.append_op("lstm",
                {"X": [input.var.name], "Lengths": [input.lengths.name],
                 "W": [w.name], "U": [u.name], "B": [bias.name]},
                {"Out": [out.name], "LastH": [last_h.name],
                 "LastC": [last_c.name]},
                {"reverse": reverse, "forget_bias": forget_bias})
    return LayerOutput(out, input.lengths, input.input_type)


def grumemory(input: LayerOutput, size: int, reverse: bool = False) -> LayerOutput:
    b = default_main_program().global_block()
    in_dim = input.var.shape[-1]
    w = FL._create_parameter("gru_w", (in_dim, 3 * size), "float32",
                             I.uniform(-0.08, 0.08))
    u = FL._create_parameter("gru_u", (size, 3 * size), "float32",
                             I.uniform(-0.08, 0.08))
    bias = FL._create_parameter("gru_b", (3 * size,), "float32", I.zeros)
    out = b.create_var(shape=input.var.shape[:-1] + (size,), dtype="float32")
    last = b.create_var(shape=(-1, size), dtype="float32")
    b.append_op("gru",
                {"X": [input.var.name], "Lengths": [input.lengths.name],
                 "W": [w.name], "U": [u.name], "B": [bias.name]},
                {"Out": [out.name], "LastH": [last.name]},
                {"reverse": reverse})
    return LayerOutput(out, input.lengths, input.input_type)


def pooling(input: LayerOutput, pooling_type: str = "max") -> LayerOutput:
    """Sequence pooling (SequencePoolLayer): max|average|sum."""
    return _seq_op("sequence_pool", input,
                   {"pool_type": pooling_type},
                   out_shape=(-1, input.var.shape[-1]))


def last_seq(input: LayerOutput) -> LayerOutput:
    return _seq_op("sequence_last_step", input,
                   out_shape=(-1, input.var.shape[-1]))


def first_seq(input: LayerOutput) -> LayerOutput:
    return _seq_op("sequence_first_step", input,
                   out_shape=(-1, input.var.shape[-1]))


def concat(inputs: List[LayerOutput], axis: int = -1) -> LayerOutput:
    return LayerOutput(FL.concat([i.var for i in inputs], axis=axis))


def dropout(input: LayerOutput, dropout_rate: float) -> LayerOutput:
    return LayerOutput(FL.dropout(input.var, dropout_rate, is_test=False),
                       input.lengths, input.input_type)


def img_conv(input: LayerOutput, num_filters: int, filter_size: int,
             stride: int = 1, padding: int = 0,
             act: Optional[str] = "relu") -> LayerOutput:
    return LayerOutput(FL.conv2d(input.var, num_filters, filter_size,
                                 stride=stride, padding=padding, act=act))


def img_pool(input: LayerOutput, pool_size: int = 2, pool_type: str = "max",
             stride: Optional[int] = None) -> LayerOutput:
    return LayerOutput(FL.pool2d(input.var, pool_size, pool_type,
                                 pool_stride=stride))


# ------------------------------------------------------------------- costs ---

def classification_cost(input: LayerOutput, label: LayerOutput) -> LayerOutput:
    loss = FL.softmax_with_cross_entropy(input.var, label.var)
    return LayerOutput(FL.mean(loss))


def cross_entropy_cost(input: LayerOutput, label: LayerOutput) -> LayerOutput:
    return LayerOutput(FL.mean(FL.cross_entropy(input.var, label.var)))


def square_error_cost(input: LayerOutput, label: LayerOutput) -> LayerOutput:
    d = FL.elementwise_sub(input.var, label.var)
    return LayerOutput(FL.mean(FL.elementwise_mul(d, d)))


# =============================================================================
# recurrent_group / memory / StaticInput / beam generation
# (trainer_config_helpers/layers.py:3939 recurrent_group, :3909 StaticInput,
# memory; RecurrentGradientMachine.cpp:964 generateSequence, :1020 beamSearch).
# TPU-native lowering: recurrent_group -> one lax.scan (fluid StaticRNN op);
# generation -> the on-device masked-top-k beam decode (ops/beam_search.py)
# with the user's step net traced as the per-step function.
# =============================================================================

import contextlib as _ctxlib

from .. import fluid as _fluid


class StaticInput:
    """Non-scanned input visible unchanged at every step (layers.py:3909).
    In generation it is tiled across beams together with the memories."""

    def __init__(self, input: LayerOutput):
        self.layer = input


class GeneratedInput:
    """The generation feedback input: at step t the decoder receives the
    embedding of the token emitted at t-1 (GeneratedInput in the reference's
    beam-gen DSL). ``embedding_param`` shares a training-time embedding
    table; otherwise a fresh [vocab, embedding_size] table is created."""

    def __init__(self, size: int, embedding_size: int, embedding_param=None):
        self.vocab_size = size
        self.embedding_size = embedding_size
        self.embedding_param = embedding_param


class _RGContext:
    def __init__(self, kind, rnn=None, sub=None):
        self.kind = kind               # "rg" | "beam"
        self.rnn = rnn
        self.sub = sub
        self.batch_ref = None          # a step-input var for zero boots
        self.memories = []             # (name, mem Variable, boot_name|None)
        self.named_outputs = {}        # name -> Variable


_rg_stack: List[_RGContext] = []


def _active_rg() -> Optional[_RGContext]:
    return _rg_stack[-1] if _rg_stack else None


@_ctxlib.contextmanager
def _push_rg(ctx: _RGContext):
    _rg_stack.append(ctx)
    try:
        yield ctx
    finally:
        _rg_stack.pop()


def _register_named(name: Optional[str], var: Variable):
    ctx = _active_rg()
    if ctx is not None and name:
        ctx.named_outputs[name] = var


def memory(name: str, size: int,
           boot_layer: Optional[LayerOutput] = None) -> LayerOutput:
    """Previous-step value of the step-net output called ``name``
    (layers.py memory semantics: the layer with the matching name updates
    this memory). Booted from ``boot_layer`` (an outer-graph layer — the
    MemoryFrameLine bootLayer, RecurrentGradientMachine.h:329) or zeros."""
    ctx = _active_rg()
    if ctx is None:
        raise ValueError("memory() is only valid inside a recurrent_group "
                         "or beam_search step function")
    if ctx.kind == "rg":
        if boot_layer is not None:
            mem = ctx.rnn.memory(init=boot_layer.var)
        else:
            mem = ctx.rnn.memory(shape=(size,), value=0.0,
                                 batch_ref=ctx.batch_ref)
        ctx.memories.append((name, mem, None))
        return LayerOutput(mem)
    # beam: inner var fed from the (beam-tiled) cell each step
    if boot_layer is None:
        raise ValueError("generation memories need boot_layer= (decoder "
                         "state boots from the encoder)")
    v = ctx.sub.create_var(shape=(-1, size), dtype="float32")
    ctx.memories.append((name, v, boot_layer.var.name))
    return LayerOutput(v)


def identity(input: LayerOutput, name: Optional[str] = None) -> LayerOutput:
    """Name a step-net output so a memory() can bind to it (the reference
    binds by layer name; our builders auto-name, so this is the explicit
    binding point)."""
    _register_named(name, input.var)
    return input


def recurrent_group(step, input, reverse: bool = False):
    """User-composed step network scanned over a sequence — the signature
    capability of RecurrentGradientMachine, compiled to ONE lax.scan.

    ``input``: a sequence LayerOutput, or a list mixing sequence layers and
    StaticInput wrappers. ``step(*step_args)`` builds the per-step net with
    v2 layers; memories declared via ``memory(name=...)`` update from the
    step output registered under the same name (fc(..., name=...) or
    identity(..., name=...)).
    """
    inputs = list(input) if isinstance(input, (list, tuple)) else [input]
    seq_inputs = [i for i in inputs if isinstance(i, LayerOutput)]
    if not seq_inputs:
        raise ValueError("recurrent_group needs at least one sequence input")
    lengths = next((i.lengths for i in seq_inputs if i.lengths is not None),
                   None)
    if reverse:
        if any(i.lengths is None for i in seq_inputs):
            raise ValueError(
                "recurrent_group(reverse=True) needs sequence inputs with "
                "lengths (sequence_reverse is length-aware); wrap plain "
                "tensors in a LayerOutput carrying the lengths var")
        inputs = [_seq_op("sequence_reverse", i, seq_out=True)
                  if isinstance(i, LayerOutput) else i for i in inputs]

    rnn = _fluid.StaticRNN()
    ctx = _RGContext("rg", rnn=rnn)
    with rnn.step(), _push_rg(ctx):
        args = []
        for i in inputs:
            if isinstance(i, StaticInput):
                args.append(i.layer)          # outer var, closed over
            else:
                x_t = rnn.step_input(i.var)
                if ctx.batch_ref is None:
                    ctx.batch_ref = x_t
                args.append(LayerOutput(x_t))
        outs = step(*args)
        outs = [outs] if isinstance(outs, LayerOutput) else list(outs)
        for name, mem, _ in ctx.memories:
            if name not in ctx.named_outputs:
                raise ValueError(
                    f"memory '{name}' has no matching named step output; "
                    f"name one with fc(..., name='{name}') or identity()")
            rnn.update_memory(mem, LayerOutput(ctx.named_outputs[name]).var)
        for o in outs:
            rnn.step_output(o.var)
    result = rnn()
    wrapped = []
    for v in result:
        lo = LayerOutput(v, lengths)
        if reverse:
            lo = _seq_op("sequence_reverse", lo, seq_out=True)
        wrapped.append(lo)
    return wrapped[0] if len(wrapped) == 1 else wrapped


def beam_search(step, input, bos_id: int, eos_id: int, beam_size: int = 5,
                max_length: int = 20, length_penalty: float = 0.0):
    """Beam-search generation over a user step net (layers.py beam_search /
    generateSequence:964). Returns (tokens, scores) LayerOutputs with shapes
    [B, beam, max_length] / [B, beam], best-first.

    ``input``: one GeneratedInput (prev-token embedding feedback) plus any
    StaticInputs (encoder outputs etc. — tiled across beams). Memories boot
    from outer layers via memory(..., boot_layer=...). The step must return
    per-class *probabilities* [_, vocab] (softmax output, like the
    reference's generating sub-model).
    """
    main = default_main_program()
    gens = [i for i in input if isinstance(i, GeneratedInput)]
    if len(gens) != 1:
        raise ValueError("beam_search needs exactly one GeneratedInput")
    g = gens[0]
    if g.embedding_param is not None:
        embed_w = g.embedding_param
    else:
        embed_w = FL._create_parameter(
            "gen_embed_w", (g.vocab_size, g.embedding_size), "float32",
            I.normal(0.0, 0.01))

    parent = main.current_block()
    sub = main.create_block()
    ctx = _RGContext("beam", sub=sub)
    static_outer, static_inner = [], []
    with main.block_guard(sub), _push_rg(ctx):
        tok_embed = sub.create_var(shape=(-1, g.embedding_size),
                                   dtype="float32")
        args = []
        for i in input:
            if isinstance(i, GeneratedInput):
                args.append(LayerOutput(tok_embed))
                continue
            lo = i.layer
            inner = sub.create_var(shape=lo.var.shape, dtype=lo.var.dtype)
            static_outer.append(lo.var.name)
            static_inner.append(inner.name)
            inner_len = None
            if lo.lengths is not None:
                inner_len = sub.create_var(shape=lo.lengths.shape,
                                           dtype=lo.lengths.dtype)
                static_outer.append(lo.lengths.name)
                static_inner.append(inner_len.name)
            args.append(LayerOutput(inner, inner_len))
        out = step(*args)
        for name, _, _ in ctx.memories:
            if name not in ctx.named_outputs:
                raise ValueError(f"memory '{name}' has no matching named "
                                 "step output")

    tokens = parent.create_var(shape=(-1, beam_size, max_length),
                               dtype="int32")
    scores = parent.create_var(shape=(-1, beam_size), dtype="float32")
    parent.append_op(
        "beam_search_gen",
        {"Embed": [embed_w.name]},
        {"Tokens": [tokens.name], "Scores": [scores.name]},
        {"sub_block_idx": sub.idx,
         "embed_param": embed_w.name,
         "token_embed_name": tok_embed.name,
         "static_outer": static_outer,
         "static_in_names": static_inner,
         "boot_mems": [boot for _, _, boot in ctx.memories],
         "mem_names": [m.name for _, m, _ in ctx.memories],
         "mem_update_names": [ctx.named_outputs[n].name
                              for n, _, _ in ctx.memories],
         "prob_name": out.var.name,
         "beam_size": beam_size, "max_length": max_length,
         "bos_id": bos_id, "eos_id": eos_id,
         "length_penalty": length_penalty})
    return LayerOutput(tokens), LayerOutput(scores)


# ------------------------------------------------ nested (2-level LoD) layers

def _nested_inputs(input: LayerOutput):
    if input.sub_lengths is None:
        raise ValueError("layer requires nested sequence input "
                         "(integer_value_sub_sequence / "
                         "dense_vector_sub_sequence data)")
    return {"X": [input.var.name], "SubLengths": [input.sub_lengths.name],
            "SeqLengths": [input.lengths.name]}


def nested_pooling(input: LayerOutput, pooling_type: str = "average"
                   ) -> LayerOutput:
    """Pool each sub-sequence -> ordinary sequence of sub-seq summaries
    [B, S, D] + outer lengths (SubNestedSequence pooling analog)."""
    b = default_main_program().current_block()
    out = b.create_var(shape=(-1, -1, input.var.shape[-1]), dtype="float32")
    b.append_op("nested_seq_pool", _nested_inputs(input), {"Out": [out.name]},
                {"pool_type": pooling_type})
    return LayerOutput(out, input.lengths)


def nested_last_seq(input: LayerOutput) -> LayerOutput:
    b = default_main_program().current_block()
    out = b.create_var(shape=(-1, -1, input.var.shape[-1]), dtype="float32")
    b.append_op("nested_last_step", _nested_inputs(input), {"Out": [out.name]})
    return LayerOutput(out, input.lengths)


def nested_lstmemory(input: LayerOutput, size: int,
                     reverse: bool = False) -> LayerOutput:
    """Inner LSTM over every sub-sequence (memory resets at boundaries);
    returns the sequence of per-sub-sequence last states [B, S, size] —
    ready for an outer recurrent layer (the nested recurrent_group stack)."""
    b = default_main_program().current_block()
    in_dim = input.var.shape[-1]
    w = FL._create_parameter("nlstm_w", (in_dim, 4 * size), "float32",
                             I.uniform(-0.08, 0.08))
    u = FL._create_parameter("nlstm_u", (size, 4 * size), "float32",
                             I.uniform(-0.08, 0.08))
    bias = FL._create_parameter("nlstm_b", (4 * size,), "float32", I.zeros)
    ins = _nested_inputs(input)
    ins.update({"W": [w.name], "U": [u.name], "B": [bias.name]})
    out = b.create_var(shape=(-1, -1, -1, size), dtype="float32")
    last = b.create_var(shape=(-1, -1, size), dtype="float32")
    b.append_op("nested_lstm", ins,
                {"Out": [out.name], "LastH": [last.name]},
                {"reverse": reverse})
    return LayerOutput(last, input.lengths)
