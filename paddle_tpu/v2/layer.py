"""v2 layer DSL emitting fluid ops.

Mirrors the surface of python/paddle/v2/layer.py + trainer_config_helpers/
layers.py (fc, embedding, lstmemory, conv, pooling, costs), but each call
appends to the fluid default programs. Sequence-typed layers carry a paired
``<name>__len__`` lengths variable (the LoD metadata under the static-shape
regime — core/lod.py).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Optional

from ..fluid import layers as FL
from ..fluid.framework import Variable, default_main_program
from ..nn import initializer as I
from .data_type import InputType


@dataclass
class LayerOutput:
    var: Variable
    lengths: Optional[Variable] = None      # set for sequence outputs
    input_type: Optional[InputType] = None

    @property
    def name(self):
        return self.var.name


def data(name: str, type: InputType) -> LayerOutput:
    """paddle.v2.layer.data analog; sequence types get a lengths feed var."""
    if type.is_seq:
        elem = getattr(type.slot, "elem_dim", None)
        if elem is None:
            v = FL.data(name, shape=(-1,), dtype="int32")
        else:
            v = FL.data(name, shape=(-1, elem), dtype="float32")
        lens = FL.data(name + "__len__", shape=(), dtype="int32")
        return LayerOutput(v, lens, type)
    from ..data.feeder import DenseSlot, IndexSlot, SparseSlot
    if isinstance(type.slot, DenseSlot):
        v = FL.data(name, shape=(type.slot.dim,))
    elif isinstance(type.slot, IndexSlot):
        v = FL.data(name, shape=(), dtype="int32")
    else:  # sparse: padded (ids, vals) pair
        v = FL.data(name, shape=(-1,), dtype="int32")
        vals = FL.data(name + "__vals__", shape=(-1,), dtype="float32")
    return LayerOutput(v, None, type)


def fc(input: LayerOutput, size: int, act: Optional[str] = None,
       bias_attr: bool = True) -> LayerOutput:
    return LayerOutput(FL.fc(input.var, size, act=act, bias_attr=bias_attr))


def embedding(input: LayerOutput, size: int) -> LayerOutput:
    t = input.input_type
    if t is None or not t.vocab:
        raise ValueError("embedding needs a data layer typed "
                         "integer_value[_sequence](vocab_size)")
    out = FL.embedding(input.var, (t.vocab, size))
    return LayerOutput(out, input.lengths, input.input_type)


def _seq_op(op_type, input: LayerOutput, extra_attrs=None, out_shape=None,
            seq_out=False, params=None) -> LayerOutput:
    b = default_main_program().global_block()
    out = b.create_var(shape=out_shape or input.var.shape,
                       dtype="float32")
    inputs = {"X": [input.var.name], "Lengths": [input.lengths.name]}
    if params:
        inputs.update(params)
    b.append_op(op_type, inputs, {"Out": [out.name]}, extra_attrs or {})
    return LayerOutput(out, input.lengths if seq_out else None,
                       input.input_type if seq_out else None)


def lstmemory(input: LayerOutput, size: int, reverse: bool = False,
              forget_bias: float = 1.0) -> LayerOutput:
    """Whole-sequence masked LSTM (simple_lstm/lstmemory analog)."""
    b = default_main_program().global_block()
    in_dim = input.var.shape[-1]
    w = FL._create_parameter("lstm_w", (in_dim, 4 * size), "float32",
                             I.uniform(-0.08, 0.08))
    u = FL._create_parameter("lstm_u", (size, 4 * size), "float32",
                             I.uniform(-0.08, 0.08))
    bias = FL._create_parameter("lstm_b", (4 * size,), "float32", I.zeros)
    out = b.create_var(shape=input.var.shape[:-1] + (size,), dtype="float32")
    last_h = b.create_var(shape=(-1, size), dtype="float32")
    last_c = b.create_var(shape=(-1, size), dtype="float32")
    b.append_op("lstm",
                {"X": [input.var.name], "Lengths": [input.lengths.name],
                 "W": [w.name], "U": [u.name], "B": [bias.name]},
                {"Out": [out.name], "LastH": [last_h.name],
                 "LastC": [last_c.name]},
                {"reverse": reverse, "forget_bias": forget_bias})
    return LayerOutput(out, input.lengths, input.input_type)


def grumemory(input: LayerOutput, size: int, reverse: bool = False) -> LayerOutput:
    b = default_main_program().global_block()
    in_dim = input.var.shape[-1]
    w = FL._create_parameter("gru_w", (in_dim, 3 * size), "float32",
                             I.uniform(-0.08, 0.08))
    u = FL._create_parameter("gru_u", (size, 3 * size), "float32",
                             I.uniform(-0.08, 0.08))
    bias = FL._create_parameter("gru_b", (3 * size,), "float32", I.zeros)
    out = b.create_var(shape=input.var.shape[:-1] + (size,), dtype="float32")
    last = b.create_var(shape=(-1, size), dtype="float32")
    b.append_op("gru",
                {"X": [input.var.name], "Lengths": [input.lengths.name],
                 "W": [w.name], "U": [u.name], "B": [bias.name]},
                {"Out": [out.name], "LastH": [last.name]},
                {"reverse": reverse})
    return LayerOutput(out, input.lengths, input.input_type)


def pooling(input: LayerOutput, pooling_type: str = "max") -> LayerOutput:
    """Sequence pooling (SequencePoolLayer): max|average|sum."""
    return _seq_op("sequence_pool", input,
                   {"pool_type": pooling_type},
                   out_shape=(-1, input.var.shape[-1]))


def last_seq(input: LayerOutput) -> LayerOutput:
    return _seq_op("sequence_last_step", input,
                   out_shape=(-1, input.var.shape[-1]))


def first_seq(input: LayerOutput) -> LayerOutput:
    return _seq_op("sequence_first_step", input,
                   out_shape=(-1, input.var.shape[-1]))


def concat(inputs: List[LayerOutput], axis: int = -1) -> LayerOutput:
    return LayerOutput(FL.concat([i.var for i in inputs], axis=axis))


def dropout(input: LayerOutput, dropout_rate: float) -> LayerOutput:
    return LayerOutput(FL.dropout(input.var, dropout_rate, is_test=False),
                       input.lengths, input.input_type)


def img_conv(input: LayerOutput, num_filters: int, filter_size: int,
             stride: int = 1, padding: int = 0,
             act: Optional[str] = "relu") -> LayerOutput:
    return LayerOutput(FL.conv2d(input.var, num_filters, filter_size,
                                 stride=stride, padding=padding, act=act))


def img_pool(input: LayerOutput, pool_size: int = 2, pool_type: str = "max",
             stride: Optional[int] = None) -> LayerOutput:
    return LayerOutput(FL.pool2d(input.var, pool_size, pool_type,
                                 pool_stride=stride))


# ------------------------------------------------------------------- costs ---

def classification_cost(input: LayerOutput, label: LayerOutput) -> LayerOutput:
    loss = FL.softmax_with_cross_entropy(input.var, label.var)
    return LayerOutput(FL.mean(loss))


def cross_entropy_cost(input: LayerOutput, label: LayerOutput) -> LayerOutput:
    return LayerOutput(FL.mean(FL.cross_entropy(input.var, label.var)))


def square_error_cost(input: LayerOutput, label: LayerOutput) -> LayerOutput:
    d = FL.elementwise_sub(input.var, label.var)
    return LayerOutput(FL.mean(FL.elementwise_mul(d, d)))
