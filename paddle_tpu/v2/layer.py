"""v2 layer DSL emitting fluid ops.

Mirrors the surface of python/paddle/v2/layer.py + trainer_config_helpers/
layers.py (fc, embedding, lstmemory, conv, pooling, costs), but each call
appends to the fluid default programs. Sequence-typed layers carry a paired
``<name>__len__`` lengths variable (the LoD metadata under the static-shape
regime — core/lod.py).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Optional

from ..fluid import layers as FL
from ..fluid.framework import Variable, default_main_program
from ..nn import initializer as I
from .data_type import InputType


@dataclass
class LayerOutput:
    var: Variable
    lengths: Optional[Variable] = None      # set for sequence outputs
    input_type: Optional[InputType] = None
    sub_lengths: Optional[Variable] = None  # set for nested (2-level LoD) data
    values: Optional[Variable] = None       # set for sparse (ids, vals) data
    #: secondary outputs by arg_name (the reference's multi-output layers,
    #: e.g. lstm_step's 'state') — fetched via get_output_layer()
    outputs: Optional[dict] = None

    @property
    def name(self):
        return self.var.name


def data(name: str, type: InputType) -> LayerOutput:
    """paddle.v2.layer.data analog; sequence types get a lengths feed var,
    nested (sub-sequence) types additionally a [S] sub-lengths feed var."""
    if type.is_seq:
        elem = getattr(type.slot, "elem_dim", None)
        nested = getattr(type.slot, "nested", False)
        if nested:
            shape = (-1, -1) if elem is None else (-1, -1, elem)
            dtype = "int32" if elem is None else "float32"
            v = FL.data(name, shape=shape, dtype=dtype)        # [B, S, T(, D)]
            sublens = FL.data(name + "__sublen__", shape=(-1,), dtype="int32")
            lens = FL.data(name + "__len__", shape=(), dtype="int32")
            return LayerOutput(v, lens, type, sub_lengths=sublens)
        if elem is None:
            v = FL.data(name, shape=(-1,), dtype="int32")
        else:
            v = FL.data(name, shape=(-1, elem), dtype="float32")
        lens = FL.data(name + "__len__", shape=(), dtype="int32")
        return LayerOutput(v, lens, type)
    from ..data.feeder import DenseSlot, IndexSlot, SparseSlot
    if isinstance(type.slot, DenseSlot):
        v = FL.data(name, shape=(type.slot.dim,))
    elif isinstance(type.slot, IndexSlot):
        v = FL.data(name, shape=(), dtype="int32")
    else:  # sparse: padded COO pair (ids [B,K], vals [B,K]); vals carry the
        # padding mask (0 where padded) — consumed by embedding()/fc()
        v = FL.data(name, shape=(-1,), dtype="int32")
        vals = FL.data(name + "__vals__", shape=(-1,), dtype="float32")
        return LayerOutput(v, None, type, values=vals)
    return LayerOutput(v, None, type)


def _sparse_weighted_sum(ids_var, vals_var, table, size):
    """sum_k vals[b,k] * table[ids[b,k]] -> [B, size]: the padded-COO
    SelectedRows path (sparse_binary/float_vector inputs to fc/embedding;
    math/SparseRowMatrix + getParameterSparse analog — only touched rows
    enter the matmul)."""
    b = default_main_program().current_block()
    looked = b.create_var(shape=(-1, -1, size), dtype="float32")
    b.append_op("lookup_table", {"W": [table.name], "Ids": [ids_var.name]},
                {"Out": [looked.name]}, {})
    vals3 = b.create_var(shape=(-1, -1, 1), dtype="float32")
    b.append_op("unsqueeze", {"X": [vals_var.name]}, {"Out": [vals3.name]},
                {"axis": -1})
    weighted = b.create_var(shape=(-1, -1, size), dtype="float32")
    b.append_op("elementwise_mul", {"X": [looked.name], "Y": [vals3.name]},
                {"Out": [weighted.name]}, {})
    out = b.create_var(shape=(-1, size), dtype="float32")
    b.append_op("reduce_sum", {"X": [weighted.name]}, {"Out": [out.name]},
                {"dim": 1})
    return out


def _attr_dict(a):
    """ParamAttr -> fluid attr dict (None/bool pass through as None)."""
    if a is None or isinstance(a, bool):
        return None
    return a.to_fluid() if hasattr(a, "to_fluid") else dict(a)


def fc(input, size: int, act: Optional[str] = None,
       bias_attr: bool = True, name: Optional[str] = None,
       param_attr=None, layer_attr=None) -> LayerOutput:
    """Accepts a single layer or a list (concatenated, like the reference's
    multi-input fc). Sparse inputs (sparse_binary/float_vector data layers)
    take the weighted-row-sum path — the reference's sparse fc
    (quick_start LR config). ``name`` registers the output for memory()
    binding inside a recurrent_group/beam_search step. ``param_attr`` is a
    :class:`paddle.attr.ParamAttr` (name-based sharing, init, is_static,
    per-param lr/l2); ``bias_attr`` may be bool or a ParamAttr;
    ``layer_attr`` an ExtraAttr whose drop_rate appends dropout."""
    inputs = list(input) if isinstance(input, (list, tuple)) else [input]
    sparse = [i for i in inputs if i.values is not None]
    dense = [i for i in inputs if i.values is None]
    # a NAMED ParamAttr names ONE weight matrix; with several weight-bearing
    # parts (each sparse table + the dense block) a single name would force
    # accidental sharing/shape clashes, so require one part (the reference
    # takes a per-input attr list; pass attrs per separate fc there)
    n_parts = len(sparse) + (1 if dense else 0)
    if (n_parts > 1 and param_attr is not None
            and not isinstance(param_attr, bool)
            and _attr_dict(param_attr) and "name" in _attr_dict(param_attr)):
        raise ValueError(
            "fc with multiple weight-bearing inputs cannot take a single "
            "named param_attr (it would share one matrix across parts with "
            "different shapes); build per-input fc/mixed projections instead")
    parts = []
    for s in sparse:
        dim = s.input_type.slot.dim
        table = FL._create_parameter("sparse_fc_w", (dim, size), "float32",
                                     I.xavier(), attr=_attr_dict(param_attr))
        parts.append(_sparse_weighted_sum(s.var, s.values, table, size))
    if dense:
        var = (FL.concat([i.var for i in dense], axis=-1)
               if len(dense) > 1 else dense[0].var)
        parts.append(FL.fc(var, size, act=None, bias_attr=False,
                           param_attr=_attr_dict(param_attr)))
    b = default_main_program().current_block()
    acc = parts[0]
    if len(parts) > 1:
        summed = b.create_var(shape=(-1, size), dtype="float32")
        b.append_op("sum", {"X": [p.name for p in parts]},
                    {"Out": [summed.name]}, {})
        acc = summed
    if bias_attr:
        bias = FL._create_parameter("fc_b", (size,), "float32", I.zeros,
                                    attr=_attr_dict(bias_attr))
        acc = FL.elementwise_add(acc, bias)
    if act:
        acc = FL.activation(acc, act)
    if layer_attr is not None and getattr(layer_attr, "drop_rate", None):
        acc = FL.dropout(acc, layer_attr.drop_rate)
    _register_named(name, acc)
    return LayerOutput(acc)


def embedding(input: LayerOutput, size: int, param_attr=None) -> LayerOutput:
    t = input.input_type
    if input.values is not None:
        # sparse input -> weighted-sum embedding [B, size] (bag-of-features)
        dim = t.slot.dim
        table = FL._create_parameter("embedding_w", (dim, size), "float32",
                                     I.normal(0.0, 0.01),
                                     attr=_attr_dict(param_attr))
        out = _sparse_weighted_sum(input.var, input.values, table, size)
        return LayerOutput(out)
    if t is None or not t.vocab:
        raise ValueError("embedding needs a data layer typed "
                         "integer_value[_sequence](vocab_size) or a sparse "
                         "vector type")
    out = FL.embedding(input.var, (t.vocab, size),
                       param_attr=_attr_dict(param_attr))
    return LayerOutput(out, input.lengths, input.input_type,
                       sub_lengths=input.sub_lengths)


def _seq_op(op_type, input: LayerOutput, extra_attrs=None, out_shape=None,
            seq_out=False, params=None) -> LayerOutput:
    # current (not global) block: seq layers compose inside rg/nested steps
    b = default_main_program().current_block()
    out = b.create_var(shape=out_shape or input.var.shape,
                       dtype="float32")
    inputs = {"X": [input.var.name], "Lengths": [input.lengths.name]}
    if params:
        inputs.update(params)
    b.append_op(op_type, inputs, {"Out": [out.name]}, extra_attrs or {})
    return LayerOutput(out, input.lengths if seq_out else None,
                       input.input_type if seq_out else None)


def lstmemory(input: LayerOutput, size: int, reverse: bool = False,
              forget_bias: float = 1.0) -> LayerOutput:
    """Whole-sequence masked LSTM (simple_lstm/lstmemory analog)."""
    b = default_main_program().current_block()
    in_dim = input.var.shape[-1]
    w = FL._create_parameter("lstm_w", (in_dim, 4 * size), "float32",
                             I.uniform(-0.08, 0.08))
    u = FL._create_parameter("lstm_u", (size, 4 * size), "float32",
                             I.uniform(-0.08, 0.08))
    bias = FL._create_parameter("lstm_b", (4 * size,), "float32", I.zeros)
    out = b.create_var(shape=input.var.shape[:-1] + (size,), dtype="float32")
    last_h = b.create_var(shape=(-1, size), dtype="float32")
    last_c = b.create_var(shape=(-1, size), dtype="float32")
    b.append_op("lstm",
                {"X": [input.var.name], "Lengths": [input.lengths.name],
                 "W": [w.name], "U": [u.name], "B": [bias.name]},
                {"Out": [out.name], "LastH": [last_h.name],
                 "LastC": [last_c.name]},
                {"reverse": reverse, "forget_bias": forget_bias})
    return LayerOutput(out, input.lengths, input.input_type)


def grumemory(input: LayerOutput, size: int, reverse: bool = False) -> LayerOutput:
    b = default_main_program().current_block()
    in_dim = input.var.shape[-1]
    w = FL._create_parameter("gru_w", (in_dim, 3 * size), "float32",
                             I.uniform(-0.08, 0.08))
    u = FL._create_parameter("gru_u", (size, 3 * size), "float32",
                             I.uniform(-0.08, 0.08))
    bias = FL._create_parameter("gru_b", (3 * size,), "float32", I.zeros)
    out = b.create_var(shape=input.var.shape[:-1] + (size,), dtype="float32")
    last = b.create_var(shape=(-1, size), dtype="float32")
    b.append_op("gru",
                {"X": [input.var.name], "Lengths": [input.lengths.name],
                 "W": [w.name], "U": [u.name], "B": [bias.name]},
                {"Out": [out.name], "LastH": [last.name]},
                {"reverse": reverse})
    return LayerOutput(out, input.lengths, input.input_type)


def pooling(input: LayerOutput, pooling_type: str = "max") -> LayerOutput:
    """Sequence pooling (SequencePoolLayer): max|average|sum."""
    return _seq_op("sequence_pool", input,
                   {"pool_type": pooling_type},
                   out_shape=(-1, input.var.shape[-1]))


def last_seq(input: LayerOutput) -> LayerOutput:
    return _seq_op("sequence_last_step", input,
                   out_shape=(-1, input.var.shape[-1]))


def first_seq(input: LayerOutput) -> LayerOutput:
    return _seq_op("sequence_first_step", input,
                   out_shape=(-1, input.var.shape[-1]))


def concat(inputs: List[LayerOutput], axis: int = -1) -> LayerOutput:
    return LayerOutput(FL.concat([i.var for i in inputs], axis=axis))


def dropout(input: LayerOutput, dropout_rate: float) -> LayerOutput:
    return LayerOutput(FL.dropout(input.var, dropout_rate, is_test=False),
                       input.lengths, input.input_type)


def img_conv(input: LayerOutput, num_filters: int, filter_size: int,
             stride: int = 1, padding: int = 0,
             act: Optional[str] = "relu") -> LayerOutput:
    return LayerOutput(FL.conv2d(input.var, num_filters, filter_size,
                                 stride=stride, padding=padding, act=act))


def img_pool(input: LayerOutput, pool_size: int = 2, pool_type: str = "max",
             stride: Optional[int] = None) -> LayerOutput:
    return LayerOutput(FL.pool2d(input.var, pool_size, pool_type,
                                 pool_stride=stride))


# ------------------------------------------------------------------- costs ---

def classification_cost(input: LayerOutput, label: LayerOutput) -> LayerOutput:
    loss = FL.softmax_with_cross_entropy(input.var, label.var)
    return LayerOutput(FL.mean(loss))


def cross_entropy_cost(input: LayerOutput, label: LayerOutput) -> LayerOutput:
    return LayerOutput(FL.mean(FL.cross_entropy(input.var, label.var)))


def square_error_cost(input: LayerOutput, label: LayerOutput) -> LayerOutput:
    d = FL.elementwise_sub(input.var, label.var)
    return LayerOutput(FL.mean(FL.elementwise_mul(d, d)))


# =============================================================================
# recurrent_group / memory / StaticInput / beam generation
# (trainer_config_helpers/layers.py:3939 recurrent_group, :3909 StaticInput,
# memory; RecurrentGradientMachine.cpp:964 generateSequence, :1020 beamSearch).
# TPU-native lowering: recurrent_group -> one lax.scan (fluid StaticRNN op);
# generation -> the on-device masked-top-k beam decode (ops/beam_search.py)
# with the user's step net traced as the per-step function.
# =============================================================================

import contextlib as _ctxlib

from .. import fluid as _fluid


class StaticInput:
    """Non-scanned input visible unchanged at every step (layers.py:3909).
    In generation it is tiled across beams together with the memories."""

    def __init__(self, input: LayerOutput):
        self.layer = input


class BaseGeneratedInput:
    """Base of the generation feedback inputs (layers.py:4061
    BaseGeneratedInput): carries the bos/eos bookkeeping that beam_search
    fills in; subclasses define how the previous step's emission is fed
    back into the next step."""

    def __init__(self):
        self.bos_id = None
        self.eos_id = None


class GeneratedInput(BaseGeneratedInput):
    """The generation feedback input: at step t the decoder receives the
    embedding of the token emitted at t-1 (GeneratedInput in the reference's
    beam-gen DSL). ``embedding_param`` shares a training-time embedding
    table; otherwise a fresh [vocab, embedding_size] table is created."""

    def __init__(self, size: int, embedding_size: int, embedding_param=None):
        super().__init__()
        self.vocab_size = size
        self.embedding_size = embedding_size
        self.embedding_param = embedding_param


class _RGContext:
    def __init__(self, kind, rnn=None, sub=None):
        self.kind = kind               # "rg" | "beam"
        self.rnn = rnn
        self.sub = sub
        self.batch_ref = None          # a step-input var for zero boots
        self.memories = []             # (name, mem Variable, boot_name|None)
        self.named_outputs = {}        # name -> Variable


_rg_stack: List[_RGContext] = []


def _active_rg() -> Optional[_RGContext]:
    return _rg_stack[-1] if _rg_stack else None


@_ctxlib.contextmanager
def _push_rg(ctx: _RGContext):
    _rg_stack.append(ctx)
    try:
        yield ctx
    finally:
        _rg_stack.pop()


def _register_named(name: Optional[str], var: Variable):
    ctx = _active_rg()
    if ctx is not None and name:
        ctx.named_outputs[name] = var


def memory(name: str, size: int,
           boot_layer: Optional[LayerOutput] = None) -> LayerOutput:
    """Previous-step value of the step-net output called ``name``
    (layers.py memory semantics: the layer with the matching name updates
    this memory). Booted from ``boot_layer`` (an outer-graph layer — the
    MemoryFrameLine bootLayer, RecurrentGradientMachine.h:329) or zeros."""
    ctx = _active_rg()
    if ctx is None:
        raise ValueError("memory() is only valid inside a recurrent_group "
                         "or beam_search step function")
    if ctx.kind == "rg":
        if boot_layer is not None:
            mem = ctx.rnn.memory(init=boot_layer.var)
        else:
            mem = ctx.rnn.memory(shape=(size,), value=0.0,
                                 batch_ref=ctx.batch_ref)
        ctx.memories.append((name, mem, None))
        return LayerOutput(mem)
    # beam: inner var fed from the (beam-tiled) cell each step
    if boot_layer is None:
        raise ValueError("generation memories need boot_layer= (decoder "
                         "state boots from the encoder)")
    v = ctx.sub.create_var(shape=(-1, size), dtype="float32")
    ctx.memories.append((name, v, boot_layer.var.name))
    return LayerOutput(v)


def identity(input: LayerOutput, name: Optional[str] = None) -> LayerOutput:
    """Name a step-net output so a memory() can bind to it (the reference
    binds by layer name; our builders auto-name, so this is the explicit
    binding point)."""
    _register_named(name, input.var)
    return input


def recurrent_group(step, input, reverse: bool = False):
    """User-composed step network scanned over a sequence — the signature
    capability of RecurrentGradientMachine, compiled to ONE lax.scan.

    ``input``: a sequence LayerOutput, or a list mixing sequence layers and
    StaticInput wrappers. ``step(*step_args)`` builds the per-step net with
    v2 layers; memories declared via ``memory(name=...)`` update from the
    step output registered under the same name (fc(..., name=...) or
    identity(..., name=...)).
    """
    inputs = list(input) if isinstance(input, (list, tuple)) else [input]
    seq_inputs = [i for i in inputs if isinstance(i, LayerOutput)]
    if not seq_inputs:
        raise ValueError("recurrent_group needs at least one sequence input")
    lengths = next((i.lengths for i in seq_inputs if i.lengths is not None),
                   None)
    if reverse:
        if any(i.lengths is None for i in seq_inputs):
            raise ValueError(
                "recurrent_group(reverse=True) needs sequence inputs with "
                "lengths (sequence_reverse is length-aware); wrap plain "
                "tensors in a LayerOutput carrying the lengths var")
        inputs = [_seq_op("sequence_reverse", i, seq_out=True)
                  if isinstance(i, LayerOutput) else i for i in inputs]

    rnn = _fluid.StaticRNN()
    ctx = _RGContext("rg", rnn=rnn)
    with rnn.step(), _push_rg(ctx):
        args = []
        for i in inputs:
            if isinstance(i, StaticInput):
                args.append(i.layer)          # outer var, closed over
            else:
                x_t = rnn.step_input(i.var)
                if ctx.batch_ref is None:
                    ctx.batch_ref = x_t
                args.append(LayerOutput(x_t))
        outs = step(*args)
        outs = [outs] if isinstance(outs, LayerOutput) else list(outs)
        for name, mem, _ in ctx.memories:
            if name not in ctx.named_outputs:
                raise ValueError(
                    f"memory '{name}' has no matching named step output; "
                    f"name one with fc(..., name='{name}') or identity()")
            rnn.update_memory(mem, LayerOutput(ctx.named_outputs[name]).var)
        for o in outs:
            rnn.step_output(o.var)
    result = rnn()
    wrapped = []
    for v in result:
        lo = LayerOutput(v, lengths)
        if reverse:
            lo = _seq_op("sequence_reverse", lo, seq_out=True)
        wrapped.append(lo)
    return wrapped[0] if len(wrapped) == 1 else wrapped


def beam_search(step, input, bos_id: int, eos_id: int, beam_size: int = 5,
                max_length: int = 20, length_penalty: float = 0.0,
                constraint: Optional[str] = None):
    """Beam-search generation over a user step net (layers.py beam_search /
    generateSequence:964). Returns (tokens, scores) LayerOutputs with shapes
    [B, beam, max_length] / [B, beam], best-first.

    ``input``: one GeneratedInput (prev-token embedding feedback) plus any
    StaticInputs (encoder outputs etc. — tiled across beams). Memories boot
    from outer layers via memory(..., boot_layer=...). The step must return
    per-class *probabilities* [_, vocab] (softmax output, like the
    reference's generating sub-model).

    ``constraint`` names a logits-mask hook registered via
    :func:`paddle_tpu.ops.beam_search.register_constraint` — the user-callback
    capability of the reference's BeamSearchControlCallbacks
    (RecurrentGradientMachine.h:106-123) as a token-masking function; the
    name (not the callable) is stored in the Program so it stays
    JSON-serializable.
    """
    main = default_main_program()
    gens = [i for i in input if isinstance(i, GeneratedInput)]
    if len(gens) != 1:
        raise ValueError("beam_search needs exactly one GeneratedInput")
    g = gens[0]
    if g.embedding_param is not None:
        # a fluid Variable shares directly; a ParamAttr/dict shares by name
        # with a training-time table (the train-config/gen-config workflow)
        if hasattr(g.embedding_param, "to_fluid") or isinstance(
                g.embedding_param, dict):
            embed_w = FL._create_parameter(
                "gen_embed_w", (g.vocab_size, g.embedding_size), "float32",
                I.normal(0.0, 0.01), attr=_attr_dict(g.embedding_param))
        else:
            embed_w = g.embedding_param
    else:
        embed_w = FL._create_parameter(
            "gen_embed_w", (g.vocab_size, g.embedding_size), "float32",
            I.normal(0.0, 0.01))

    parent = main.current_block()
    sub = main.create_block()
    ctx = _RGContext("beam", sub=sub)
    static_outer, static_inner = [], []
    with main.block_guard(sub), _push_rg(ctx):
        tok_embed = sub.create_var(shape=(-1, g.embedding_size),
                                   dtype="float32")
        args = []
        for i in input:
            if isinstance(i, GeneratedInput):
                args.append(LayerOutput(tok_embed))
                continue
            lo = i.layer
            inner = sub.create_var(shape=lo.var.shape, dtype=lo.var.dtype)
            static_outer.append(lo.var.name)
            static_inner.append(inner.name)
            inner_len = None
            if lo.lengths is not None:
                inner_len = sub.create_var(shape=lo.lengths.shape,
                                           dtype=lo.lengths.dtype)
                static_outer.append(lo.lengths.name)
                static_inner.append(inner_len.name)
            args.append(LayerOutput(inner, inner_len))
        out = step(*args)
        for name, _, _ in ctx.memories:
            if name not in ctx.named_outputs:
                raise ValueError(f"memory '{name}' has no matching named "
                                 "step output")

    tokens = parent.create_var(shape=(-1, beam_size, max_length),
                               dtype="int32")
    scores = parent.create_var(shape=(-1, beam_size), dtype="float32")
    parent.append_op(
        "beam_search_gen",
        {"Embed": [embed_w.name]},
        {"Tokens": [tokens.name], "Scores": [scores.name]},
        {"sub_block_idx": sub.idx,
         "embed_param": embed_w.name,
         "token_embed_name": tok_embed.name,
         "static_outer": static_outer,
         "static_in_names": static_inner,
         "boot_mems": [boot for _, _, boot in ctx.memories],
         "mem_names": [m.name for _, m, _ in ctx.memories],
         "mem_update_names": [ctx.named_outputs[n].name
                              for n, _, _ in ctx.memories],
         "prob_name": out.var.name,
         "beam_size": beam_size, "max_length": max_length,
         "bos_id": bos_id, "eos_id": eos_id,
         "length_penalty": length_penalty,
         "constraint": constraint or ""})
    return LayerOutput(tokens), LayerOutput(scores)


# ------------------------------------------------ nested (2-level LoD) layers

def _nested_inputs(input: LayerOutput):
    if input.sub_lengths is None:
        raise ValueError("layer requires nested sequence input "
                         "(integer_value_sub_sequence / "
                         "dense_vector_sub_sequence data)")
    return {"X": [input.var.name], "SubLengths": [input.sub_lengths.name],
            "SeqLengths": [input.lengths.name]}


def nested_pooling(input: LayerOutput, pooling_type: str = "average"
                   ) -> LayerOutput:
    """Pool each sub-sequence -> ordinary sequence of sub-seq summaries
    [B, S, D] + outer lengths (SubNestedSequence pooling analog)."""
    b = default_main_program().current_block()
    out = b.create_var(shape=(-1, -1, input.var.shape[-1]), dtype="float32")
    b.append_op("nested_seq_pool", _nested_inputs(input), {"Out": [out.name]},
                {"pool_type": pooling_type})
    return LayerOutput(out, input.lengths)


def nested_last_seq(input: LayerOutput) -> LayerOutput:
    b = default_main_program().current_block()
    out = b.create_var(shape=(-1, -1, input.var.shape[-1]), dtype="float32")
    b.append_op("nested_last_step", _nested_inputs(input), {"Out": [out.name]})
    return LayerOutput(out, input.lengths)


def nested_lstmemory(input: LayerOutput, size: int,
                     reverse: bool = False) -> LayerOutput:
    """Inner LSTM over every sub-sequence (memory resets at boundaries);
    returns the sequence of per-sub-sequence last states [B, S, size] —
    ready for an outer recurrent layer (the nested recurrent_group stack)."""
    b = default_main_program().current_block()
    in_dim = input.var.shape[-1]
    w = FL._create_parameter("nlstm_w", (in_dim, 4 * size), "float32",
                             I.uniform(-0.08, 0.08))
    u = FL._create_parameter("nlstm_u", (size, 4 * size), "float32",
                             I.uniform(-0.08, 0.08))
    bias = FL._create_parameter("nlstm_b", (4 * size,), "float32", I.zeros)
    ins = _nested_inputs(input)
    ins.update({"W": [w.name], "U": [u.name], "B": [bias.name]})
    out = b.create_var(shape=(-1, -1, -1, size), dtype="float32")
    last = b.create_var(shape=(-1, -1, size), dtype="float32")
    b.append_op("nested_lstm", ins,
                {"Out": [out.name], "LastH": [last.name]},
                {"reverse": reverse})
    return LayerOutput(last, input.lengths)


# =============================================================================
# Gen-1 layer-zoo breadth (trainer_config_helpers/layers.py — the 106
# *_layer surface). Each function cites the gserver layer / CostLayer.cpp
# entry it re-provides; all lower onto registered fluid ops.
# =============================================================================

def _emit(op_type, ins, attrs=None, out_shape=None, out_dtype="float32",
          n_out=1, out_slot="Out"):
    """Append one registered op; returns its output Variable(s)."""
    b = default_main_program().current_block()
    outs = [b.create_var(shape=out_shape or (-1,), dtype=out_dtype)
            for _ in range(n_out)]
    b.append_op(op_type, ins, {out_slot: [o.name for o in outs]}, attrs or {})
    return outs[0] if n_out == 1 else outs


def _shape(l: LayerOutput):
    return tuple(l.var.shape)


# ------------------------------------------------------------ mixed / proj ---
# Projections return (emit_fn, out_size); mixed_layer sums their outputs
# (gserver Mixed layer + Projection.h: FullMatrix/Table/Context/DotMul/
# Scaling/Identity/Slice projections, DotMulOperator).

class _Projection:
    def __init__(self, emit, size, src: Optional[LayerOutput] = None):
        self.emit = emit        # () -> Variable with last dim == size
        self.size = size
        self.src = src          # source layer (sequence metadata propagation)


def full_matrix_projection(input: LayerOutput, size: int) -> _Projection:
    """FullMatrixProjection: x W."""
    in_dim = _shape(input)[-1]
    def emit():
        w = FL._create_parameter("proj_w", (in_dim, size), "float32",
                                 I.xavier())
        return _emit("mul", {"X": [input.var.name], "Y": [w.name]},
                     {"x_num_col_dims": len(_shape(input)) - 1},
                     out_shape=_shape(input)[:-1] + (size,))
    return _Projection(emit, size, src=input)


def trans_full_matrix_projection(input: LayerOutput, size: int) -> _Projection:
    """TransposedFullMatrixProjection: x Wᵀ (weight stored [size, in])."""
    in_dim = _shape(input)[-1]
    def emit():
        w = FL._create_parameter("tproj_w", (size, in_dim), "float32",
                                 I.xavier())
        return _emit("matmul", {"X": [input.var.name], "Y": [w.name]},
                     {"transpose_Y": True},
                     out_shape=_shape(input)[:-1] + (size,))
    return _Projection(emit, size, src=input)


def table_projection(input: LayerOutput, size: int) -> _Projection:
    """TableProjection: embedding lookup of integer input."""
    t = input.input_type
    if t is None or not t.vocab:
        raise ValueError("table_projection needs integer_value input")
    def emit():
        w = FL._create_parameter("table_w", (t.vocab, size), "float32",
                                 I.normal(0.0, 0.01))
        return _emit("lookup_table", {"W": [w.name], "Ids": [input.var.name]},
                     out_shape=_shape(input) + (size,))
    return _Projection(emit, size, src=input)


def identity_projection(input: LayerOutput, offset: Optional[int] = None,
                        size: Optional[int] = None) -> _Projection:
    """IdentityProjection / IdentityOffsetProjection (feature slice)."""
    in_dim = _shape(input)[-1]
    if offset is None:
        return _Projection(lambda: input.var, in_dim, src=input)
    end = offset + (size or (in_dim - offset))
    def emit():
        ndim = len(_shape(input))
        starts = [0] * (ndim - 1) + [offset]
        shape = [-1] * (ndim - 1) + [end - offset]   # -1: full batch extent
        return _emit("crop", {"X": [input.var.name]},
                     {"offsets": starts, "shape": shape},
                     out_shape=_shape(input)[:-1] + (end - offset,))
    return _Projection(emit, end - offset, src=input)


def dotmul_projection(input: LayerOutput) -> _Projection:
    """DotMulProjection: per-dimension learned weight, y = w ⊙ x."""
    in_dim = _shape(input)[-1]
    def emit():
        w = FL._create_parameter("dotmul_w", (in_dim,), "float32", I.ones)
        return _emit("elementwise_mul",
                     {"X": [input.var.name], "Y": [w.name]},
                     out_shape=_shape(input))
    return _Projection(emit, in_dim, src=input)


def scaling_projection(input: LayerOutput) -> _Projection:
    """ScalingProjection: one learned scalar, y = w * x."""
    in_dim = _shape(input)[-1]
    def emit():
        w = FL._create_parameter("scaling_w", (), "float32", I.ones)
        return _emit("elementwise_mul",
                     {"X": [input.var.name], "Y": [w.name]},
                     out_shape=_shape(input))
    return _Projection(emit, in_dim, src=input)


def context_projection_layer(input: LayerOutput, context_len: int,
                             context_start: Optional[int] = None) -> _Projection:
    """ContextProjection: concat of shifted frames (sequence input)."""
    in_dim = _shape(input)[-1]
    start = context_start if context_start is not None else -(context_len // 2)
    size = in_dim * context_len
    def emit():
        return _emit("context_projection",
                     {"X": [input.var.name],
                      "Lengths": [input.lengths.name]},
                     {"context_length": context_len, "context_start": start},
                     out_shape=_shape(input)[:-1] + (size,))
    return _Projection(emit, size, src=input)


def dotmul_operator(a: LayerOutput, b: LayerOutput,
                    scale: float = 1.0) -> _Projection:
    """DotMulOperator: scale * (a ⊙ b) — a Mixed-layer binary operator."""
    in_dim = _shape(a)[-1]
    def emit():
        prod = _emit("elementwise_mul", {"X": [a.var.name], "Y": [b.var.name]},
                     out_shape=_shape(a))
        if scale == 1.0:
            return prod
        return _emit("scale", {"X": [prod.name]}, {"scale": scale},
                     out_shape=_shape(a))
    return _Projection(emit, in_dim, src=a)


def mixed_layer(size: Optional[int] = None, input=None,
                act: Optional[str] = None, bias_attr: bool = False,
                name: Optional[str] = None) -> LayerOutput:
    """MixedLayer: sum of projections/operators, + bias, + activation."""
    projs: List[_Projection] = list(input or [])
    if not projs:
        raise ValueError("mixed_layer needs at least one projection")
    size = size or projs[0].size
    for p in projs:
        if p.size != size:
            raise ValueError(f"projection size {p.size} != mixed size {size}")
    outs = [p.emit() for p in projs]
    b = default_main_program().current_block()
    acc = outs[0]
    if len(outs) > 1:
        acc = _emit("sum", {"X": [o.name for o in outs]},
                    out_shape=tuple(outs[0].shape))
    if bias_attr:
        bias = FL._create_parameter("mixed_b", (size,), "float32", I.zeros)
        acc = _emit("elementwise_add", {"X": [acc.name], "Y": [bias.name]},
                    out_shape=tuple(acc.shape))
    if act:
        acc = _emit(act, {"X": [acc.name]}, out_shape=tuple(acc.shape))
    _register_named(name, acc)
    # propagate sequence metadata from the first sequence-typed source so a
    # mixed_layer output feeds seq layers (crf, pooling) without rewrapping
    seq_src = next((p.src for p in projs
                    if p.src is not None and p.src.lengths is not None), None)
    if seq_src is not None:
        return LayerOutput(acc, seq_src.lengths, seq_src.input_type)
    return LayerOutput(acc)


# ----------------------------------------------------------------- misc ------

def addto_layer(input: List[LayerOutput], act: Optional[str] = None,
                bias_attr: bool = False) -> LayerOutput:
    """AddtoLayer: elementwise sum of N inputs (+act)."""
    out = _emit("sum", {"X": [i.var.name for i in input]},
                out_shape=_shape(input[0]))
    if act:
        out = _emit(act, {"X": [out.name]}, out_shape=tuple(out.shape))
    return LayerOutput(out, input[0].lengths, input[0].input_type)


def cos_sim(a: LayerOutput, b: LayerOutput, scale: float = 1.0) -> LayerOutput:
    """CosSimLayer."""
    bkl = default_main_program().current_block()
    out = bkl.create_var(shape=(_shape(a)[0],), dtype="float32")
    bkl.append_op("cos_sim", {"X": [a.var.name], "Y": [b.var.name]},
                  {"Out": [out.name]}, {"scale": scale})
    return LayerOutput(out)


def power_layer(input: LayerOutput) -> LayerOutput:
    """PowerLayer: y = x^w with a learned scalar exponent."""
    w = FL._create_parameter("power_w", (), "float32", I.ones)
    out = _emit("power", {"X": [input.var.name], "W": [w.name]},
                out_shape=_shape(input))
    return LayerOutput(out, input.lengths, input.input_type)


def scaling_layer(input: LayerOutput, weight: LayerOutput) -> LayerOutput:
    """ScalingLayer: rows of ``input`` scaled by per-row ``weight`` [B, 1]."""
    out = _emit("elementwise_mul",
                {"X": [input.var.name], "Y": [weight.var.name]},
                out_shape=_shape(input))
    return LayerOutput(out, input.lengths, input.input_type)


def slope_intercept_layer(input: LayerOutput, slope: float = 1.0,
                          intercept: float = 0.0) -> LayerOutput:
    out = _emit("slope_intercept", {"X": [input.var.name]},
                {"slope": slope, "intercept": intercept},
                out_shape=_shape(input))
    return LayerOutput(out, input.lengths, input.input_type)


def sum_to_one_norm_layer(input: LayerOutput) -> LayerOutput:
    out = _emit("sum_to_one_norm", {"X": [input.var.name]},
                out_shape=_shape(input))
    return LayerOutput(out, input.lengths, input.input_type)


def interpolation_layer(input: List[LayerOutput],
                        weight: LayerOutput) -> LayerOutput:
    """InterpolationLayer: w*a + (1-w)*b with per-row w."""
    a, b = input
    out = _emit("interpolation",
                {"X": [a.var.name], "Y": [b.var.name],
                 "W": [weight.var.name]},
                out_shape=_shape(a))
    return LayerOutput(out)


def linear_comb_layer(weights: LayerOutput, vectors: LayerOutput,
                      size: int) -> LayerOutput:
    """LinearCombinationLayer (convex_comb_layer)."""
    out = _emit("linear_comb",
                {"X": [vectors.var.name], "W": [weights.var.name]},
                out_shape=(_shape(vectors)[0], size))
    return LayerOutput(out)


def bilinear_interp_layer(input: LayerOutput, out_h: int,
                          out_w: int) -> LayerOutput:
    """BilinearInterpLayer ([B, H, W, C] maps)."""
    shp = _shape(input)
    out = _emit("bilinear_interp", {"X": [input.var.name]},
                {"out_h": out_h, "out_w": out_w},
                out_shape=(shp[0], out_h, out_w, shp[-1]))
    return LayerOutput(out)


def repeat_layer(input: LayerOutput, num_repeats: int) -> LayerOutput:
    """FeatureMapExpandLayer."""
    shp = _shape(input)
    out = _emit("repeat", {"X": [input.var.name]}, {"times": num_repeats},
                out_shape=shp[:-1] + (shp[-1] * num_repeats,))
    return LayerOutput(out, input.lengths, input.input_type)


def rotate_layer(input: LayerOutput) -> LayerOutput:
    shp = _shape(input)
    out = _emit("rotate", {"X": [input.var.name]},
                out_shape=(shp[0], shp[2], shp[1], shp[3]))
    return LayerOutput(out)


def trans_layer(input: LayerOutput) -> LayerOutput:
    """TransLayer: matrix transpose of [B, D] -> handled as [D, B]."""
    shp = _shape(input)
    out = _emit("transpose", {"X": [input.var.name]}, {"axis": (1, 0)},
                out_shape=(shp[1], shp[0]))
    return LayerOutput(out)


def seq_reshape_layer(input: LayerOutput, reshape_size: int) -> LayerOutput:
    shp = _shape(input)
    out = _emit("seq_reshape", {"X": [input.var.name]},
                {"new_dim": reshape_size},
                out_shape=(shp[0], -1, reshape_size))
    return LayerOutput(out, input.lengths, input.input_type)


def expand_layer(input: LayerOutput, expand_as: LayerOutput) -> LayerOutput:
    """ExpandLayer: broadcast per-sequence rows to every step of expand_as."""
    out = _emit("sequence_expand",
                {"X": [input.var.name],
                 "RefLengths": [expand_as.lengths.name],
                 "Ref": [expand_as.var.name]},
                out_shape=(_shape(input)[0], _shape(expand_as)[1],
                           _shape(input)[-1]))
    return LayerOutput(out, expand_as.lengths, expand_as.input_type)


def max_id_layer(input: LayerOutput) -> LayerOutput:
    """MaxIdLayer."""
    out = _emit("argmax", {"X": [input.var.name]},
                out_shape=_shape(input)[:-1], out_dtype="int32")
    return LayerOutput(out, input.lengths)


def sampling_id_layer(input: LayerOutput, seed: int = 0) -> LayerOutput:
    """SamplingIdLayer."""
    out = _emit("sampling_id", {"X": [input.var.name]}, {"seed": seed},
                out_shape=_shape(input)[:-1], out_dtype="int32")
    return LayerOutput(out)


def clip_layer(input: LayerOutput, min: float, max: float) -> LayerOutput:
    out = _emit("clip", {"X": [input.var.name]}, {"min": min, "max": max},
                out_shape=_shape(input))
    return LayerOutput(out, input.lengths, input.input_type)


def pad_layer(input: LayerOutput, pad) -> LayerOutput:
    shp = _shape(input)
    out_shape = tuple(s + lo + hi if s > 0 else s
                      for s, (lo, hi) in zip(shp, pad))
    out = _emit("pad", {"X": [input.var.name]}, {"paddings": pad},
                out_shape=out_shape)
    return LayerOutput(out)


def crop_layer(input: LayerOutput, offsets, shape) -> LayerOutput:
    out = _emit("crop", {"X": [input.var.name]},
                {"offsets": offsets, "shape": shape},
                out_shape=tuple(shape))
    return LayerOutput(out)


def multiplex_layer(index: LayerOutput,
                    inputs: List[LayerOutput]) -> LayerOutput:
    """MultiplexLayer: per-row selection among candidate inputs."""
    out = _emit("multiplex",
                {"Ids": [index.var.name],
                 "X": [i.var.name for i in inputs]},
                out_shape=_shape(inputs[0]))
    return LayerOutput(out)


def tensor_layer(a: LayerOutput, b: LayerOutput, size: int,
                 act: Optional[str] = None) -> LayerOutput:
    """TensorLayer: bilinear form aᵀ W_k b for k in 1..size."""
    da, db = _shape(a)[-1], _shape(b)[-1]
    w = FL._create_parameter("tensor_w", (size, da, db), "float32",
                             I.xavier())
    out = _emit("bilinear_tensor_product",
                {"X": [a.var.name], "Y": [b.var.name], "Weight": [w.name]},
                out_shape=(_shape(a)[0], size))
    if act:
        out = _emit(act, {"X": [out.name]}, out_shape=tuple(out.shape))
    return LayerOutput(out)


def conv_shift_layer(a: LayerOutput, b: LayerOutput) -> LayerOutput:
    """ConvShiftLayer (circular convolution, NTM-style addressing)."""
    out = _emit("conv_shift", {"X": [a.var.name], "Y": [b.var.name]},
                out_shape=_shape(a))
    return LayerOutput(out)


def block_expand_layer(input: LayerOutput, block_x: int, block_y: int,
                       stride_x: int = 1, stride_y: int = 1) -> LayerOutput:
    """BlockExpandLayer (im2col as a layer)."""
    shp = _shape(input)
    out = _emit("block_expand", {"X": [input.var.name]},
                {"block": (block_y, block_x),
                 "strides": (stride_y, stride_x), "paddings": 0},
                out_shape=(shp[0], -1, block_x * block_y * shp[-1]))
    return LayerOutput(out)


def maxout_layer(input: LayerOutput, groups: int) -> LayerOutput:
    """MaxOutLayer."""
    shp = _shape(input)
    out = _emit("maxout", {"X": [input.var.name]}, {"groups": groups},
                out_shape=shp[:-1] + (shp[-1] // groups,))
    return LayerOutput(out)


def row_conv_layer(input: LayerOutput, future_context: int) -> LayerOutput:
    """RowConvLayer (lookahead conv, DeepSpeech2)."""
    d = _shape(input)[-1]
    w = FL._create_parameter("rowconv_w", (future_context + 1, d), "float32",
                             I.xavier())
    out = _emit("row_conv",
                {"X": [input.var.name], "Filter": [w.name]},
                out_shape=_shape(input))
    return LayerOutput(out, input.lengths, input.input_type)


def roi_pool_layer(input: LayerOutput, rois: LayerOutput, pooled_height: int,
                   pooled_width: int, spatial_scale: float = 1.0) -> LayerOutput:
    """ROIPoolLayer (detection)."""
    shp = _shape(input)
    out = _emit("roi_pool",
                {"X": [input.var.name], "ROIs": [rois.var.name]},
                {"pooled_height": pooled_height, "pooled_width": pooled_width,
                 "spatial_scale": spatial_scale},
                out_shape=(-1, pooled_height, pooled_width, shp[-1]))
    return LayerOutput(out)


def batch_norm_layer(input: LayerOutput, act: Optional[str] = None,
                     momentum: float = 0.9, epsilon: float = 1e-5,
                     is_test: bool = False) -> LayerOutput:
    """BatchNormLayer (3 gserver impls + operators/batch_norm_op.cc) — uses
    the TRAINING-mode fluid batch_norm (running stats updated in-graph)."""
    out = FL.batch_norm(input.var, act=act, momentum=momentum,
                        epsilon=epsilon, is_test=is_test)
    return LayerOutput(out, input.lengths, input.input_type)


def img_cmrnorm_layer(input: LayerOutput, size: int = 5, scale: float = 1e-4,
                      power: float = 0.75) -> LayerOutput:
    """CMRProjectionNormLayer (local response norm across channels)."""
    out = _emit("lrn", {"X": [input.var.name]},
                {"n": size, "alpha": scale, "beta": power},
                out_shape=_shape(input))
    return LayerOutput(out)


def img_conv3d(input: LayerOutput, num_filters: int, filter_size: int,
               stride: int = 1, padding: int = 0,
               act: Optional[str] = "relu") -> LayerOutput:
    """3-D convolution layer (operators/conv3d)."""
    shp = _shape(input)
    k = (filter_size,) * 3 if isinstance(filter_size, int) else filter_size
    w = FL._create_parameter("conv3d_w", tuple(k) + (shp[-1], num_filters),
                             "float32", I.xavier())
    out = _emit("conv3d", {"Input": [input.var.name], "Filter": [w.name]},
                {"strides": stride, "paddings": padding},
                out_shape=(shp[0], -1, -1, -1, num_filters))
    if act:
        out = _emit(act, {"X": [out.name]}, out_shape=tuple(out.shape))
    return LayerOutput(out)


def img_pool3d(input: LayerOutput, pool_size: int = 2, pool_type: str = "max",
               stride: Optional[int] = None) -> LayerOutput:
    shp = _shape(input)
    out = _emit("pool3d", {"X": [input.var.name]},
                {"ksize": pool_size, "pooling_type": pool_type,
                 "strides": stride or pool_size},
                out_shape=(shp[0], -1, -1, -1, shp[-1]))
    return LayerOutput(out)


def img_conv_transpose(input: LayerOutput, num_filters: int, filter_size: int,
                       stride: int = 1, padding: int = 0,
                       act: Optional[str] = None) -> LayerOutput:
    """Transposed convolution (operators/conv2d_transpose; GAN generators)."""
    shp = _shape(input)
    k = (filter_size,) * 2 if isinstance(filter_size, int) else filter_size
    w = FL._create_parameter("convT_w", tuple(k) + (shp[-1], num_filters),
                             "float32", I.xavier())
    out = _emit("conv2d_transpose",
                {"Input": [input.var.name], "Filter": [w.name]},
                {"strides": stride, "paddings": padding},
                out_shape=(shp[0], -1, -1, num_filters))
    if act:
        out = _emit(act, {"X": [out.name]}, out_shape=tuple(out.shape))
    return LayerOutput(out)


def spp_layer(input: LayerOutput, pyramid_height: int = 3,
              pool_type: str = "max") -> LayerOutput:
    """SpatialPyramidPoolLayer."""
    shp = _shape(input)
    bins = sum(4 ** i for i in range(pyramid_height))
    out = _emit("spp", {"X": [input.var.name]},
                {"pyramid_height": pyramid_height, "pooling_type": pool_type},
                out_shape=(shp[0], bins * shp[-1]))
    return LayerOutput(out)


def prelu_layer(input: LayerOutput) -> LayerOutput:
    d = _shape(input)[-1]
    alpha = FL._create_parameter("prelu_alpha", (d,), "float32",
                                 I.constant(0.25))
    out = _emit("prelu", {"X": [input.var.name], "Alpha": [alpha.name]},
                out_shape=_shape(input))
    return LayerOutput(out, input.lengths, input.input_type)


# ------------------------------------------------------------- cost zoo ------
# CostLayer.cpp: 20+ losses; each cost returns a SCALAR mean cost layer.

def _mean_of(var) -> LayerOutput:
    return LayerOutput(_emit("mean", {"X": [var.name]}, out_shape=()))


def mse_cost(input: LayerOutput, label: LayerOutput) -> LayerOutput:
    return square_error_cost(input, label)


regression_cost = mse_cost


def multi_binary_label_cross_entropy_cost(input: LayerOutput,
                                          label: LayerOutput) -> LayerOutput:
    """CostLayer.cpp MultiBinaryLabelCrossEntropy."""
    v = _emit("multi_binary_label_cross_entropy",
              {"X": [input.var.name], "Label": [label.var.name]},
              out_shape=(_shape(input)[0],))
    return _mean_of(v)


def soft_binary_class_cross_entropy_cost(input: LayerOutput,
                                         label: LayerOutput) -> LayerOutput:
    v = _emit("soft_binary_class_cross_entropy",
              {"X": [input.var.name], "Label": [label.var.name]},
              out_shape=(_shape(input)[0],))
    return _mean_of(v)


def huber_regression_cost(input: LayerOutput, label: LayerOutput,
                          delta: float = 1.0) -> LayerOutput:
    v = _emit("huber_loss",
              {"X": [input.var.name], "Label": [label.var.name]},
              {"delta": delta}, out_shape=(_shape(input)[0],))
    return _mean_of(v)


def huber_classification_cost(input: LayerOutput,
                              label: LayerOutput) -> LayerOutput:
    """HuberTwoClassification ({-1,+1} labels)."""
    v = _emit("huber_classification",
              {"X": [input.var.name], "Label": [label.var.name]},
              out_shape=(_shape(input)[0],))
    return _mean_of(v)


def rank_cost(left: LayerOutput, right: LayerOutput,
              label: LayerOutput) -> LayerOutput:
    """RankingCost (pairwise logistic)."""
    v = _emit("rank_loss",
              {"Left": [left.var.name], "Right": [right.var.name],
               "Label": [label.var.name]},
              out_shape=(_shape(left)[0],))
    return _mean_of(v)


def lambda_cost(score: LayerOutput, label: LayerOutput) -> LayerOutput:
    """LambdaCost (LambdaRank with |ΔNDCG| pair weights) over a sequence of
    candidate scores per query."""
    v = _emit("lambda_cost",
              {"X": [score.var.name], "Label": [label.var.name],
               "Lengths": [score.lengths.name]},
              out_shape=(_shape(score)[0],))
    return _mean_of(v)


def cross_entropy_with_selfnorm_cost(input: LayerOutput, label: LayerOutput,
                                     softmax_selfnorm_alpha: float = 0.1
                                     ) -> LayerOutput:
    v = _emit("cross_entropy_over_selfnorm",
              {"X": [input.var.name], "Label": [label.var.name]},
              {"softmax_selfnorm_alpha": softmax_selfnorm_alpha},
              out_shape=(_shape(input)[0],))
    return _mean_of(v)


def smooth_l1_cost(input: LayerOutput, label: LayerOutput) -> LayerOutput:
    v = _emit("smooth_l1_loss",
              {"X": [input.var.name], "Label": [label.var.name]},
              out_shape=(_shape(input)[0],))
    return _mean_of(v)


def hinge_cost(input: LayerOutput, label: LayerOutput) -> LayerOutput:
    v = _emit("hinge_loss",
              {"X": [input.var.name], "Label": [label.var.name]},
              out_shape=(_shape(input)[0],))
    return _mean_of(v)


def log_loss_cost(input: LayerOutput, label: LayerOutput,
                  epsilon: float = 1e-7) -> LayerOutput:
    v = _emit("log_loss",
              {"Predicted": [input.var.name], "Label": [label.var.name]},
              {"eps": epsilon}, out_shape=(_shape(input)[0],))
    return _mean_of(v)


def sum_cost(input: LayerOutput) -> LayerOutput:
    """SumCost: sum of the input as the cost."""
    v = _emit("reduce_sum", {"X": [input.var.name]}, {"dim": None},
              out_shape=())
    return LayerOutput(v)


def sigmoid_cross_entropy_cost(input: LayerOutput,
                               label: LayerOutput) -> LayerOutput:
    v = _emit("sigmoid_cross_entropy_with_logits",
              {"X": [input.var.name], "Label": [label.var.name]},
              out_shape=_shape(input))
    return _mean_of(v)


def crf_layer(input: LayerOutput, label: LayerOutput,
              size: Optional[int] = None) -> LayerOutput:
    """CRFLayer (linear-chain CRF negative log-likelihood).

    The transition parameter is exposed as ``.transitions`` on the returned
    cost layer — pass it to :func:`crf_decoding_layer` so Viterbi decoding
    uses the TRAINED matrix (the reference shares it by parameter name)."""
    n_tags = size or _shape(input)[-1]
    trans = FL._create_parameter("crf_trans", (n_tags + 2, n_tags), "float32",
                                 I.constant(0.0))
    v = _emit("linear_chain_crf",
              {"Emission": [input.var.name], "Label": [label.var.name],
               "Transition": [trans.name],
               "Lengths": [input.lengths.name]},
              out_shape=(_shape(input)[0],), out_slot="LogLikelihood")
    neg = _emit("scale", {"X": [v.name]}, {"scale": -1.0},
                out_shape=(_shape(input)[0],))
    cost = _mean_of(neg)
    cost.transitions = LayerOutput(trans)
    return cost


def crf_decoding_layer(input: LayerOutput, size: Optional[int] = None,
                       transitions: Optional[LayerOutput] = None
                       ) -> LayerOutput:
    """CRFDecodingLayer (Viterbi). Pass ``transitions`` from the training
    crf_layer's ``.transitions`` to decode with the learned matrix; omitting
    it creates a FRESH zero matrix (argmax-of-emissions decoding)."""
    n_tags = size or _shape(input)[-1]
    if transitions is not None:
        trans = transitions.var
    else:
        trans = FL._create_parameter("crf_trans", (n_tags + 2, n_tags),
                                     "float32", I.constant(0.0))
    v = _emit("crf_decoding",
              {"Emission": [input.var.name], "Transition": [trans.name],
               "Lengths": [input.lengths.name]},
              out_shape=_shape(input)[:-1], out_dtype="int32",
              out_slot="ViterbiPath")
    return LayerOutput(v, input.lengths)


def ctc_layer(input: LayerOutput, label: LayerOutput, size: int,
              blank: int = 0) -> LayerOutput:
    """CTCLayer / WarpCTCLayer."""
    v = _emit("warpctc",
              {"Logits": [input.var.name], "Label": [label.var.name],
               "LogitsLengths": [input.lengths.name],
               "LabelLengths": [label.lengths.name]},
              {"blank": blank}, out_shape=(_shape(input)[0],),
              out_slot="Loss")
    return _mean_of(v)


def nce_layer(input: LayerOutput, label: LayerOutput, num_classes: int,
              num_neg_samples: int = 10, seed: int = 0) -> LayerOutput:
    """NCELayer (noise-contrastive estimation)."""
    d = _shape(input)[-1]
    w = FL._create_parameter("nce_w", (num_classes, d), "float32",
                             I.normal(0.0, 0.01))
    bias = FL._create_parameter("nce_b", (num_classes,), "float32", I.zeros)
    v = _emit("nce",
              {"Input": [input.var.name], "Label": [label.var.name],
               "Weight": [w.name], "Bias": [bias.name]},
              {"num_neg_samples": num_neg_samples, "seed": seed,
               "num_classes": num_classes},
              out_shape=(_shape(input)[0],), out_slot="Cost")
    return _mean_of(v)


def hsigmoid_layer(input: LayerOutput, label: LayerOutput,
                   num_classes: int) -> LayerOutput:
    """HierarchicalSigmoidLayer: O(log V) softmax over a Huffman-ish tree;
    paths/codes are derived in-op from the static num_classes attr."""
    d = _shape(input)[-1]
    w = FL._create_parameter("hsig_w", (2 * num_classes, d), "float32",
                             I.normal(0.0, 0.01))
    bias = FL._create_parameter("hsig_b", (2 * num_classes,), "float32",
                                I.zeros)
    v = _emit("hierarchical_sigmoid",
              {"Input": [input.var.name], "Label": [label.var.name],
               "InnerW": [w.name], "InnerB": [bias.name]},
              {"num_classes": num_classes},
              out_shape=(), out_slot="Cost")
    return LayerOutput(v)


# ======================================================================
# gen-1 tail (round 3): the last ~20 trainer_config_helpers/layers.py
# functions — see docs/v2_layer_parity.md for the name-for-name table.
# ======================================================================

def lstm_step_layer(input: LayerOutput, state: LayerOutput,
                    size: Optional[int] = None,
                    forget_bias: float = 0.0, bias_attr: bool = True,
                    name: Optional[str] = None) -> LayerOutput:
    """LSTM step with PRE-PROJECTED gates + peephole connections, for use
    inside recurrent_group (layers.py:3544; LstmStepLayer.cpp). ``input``
    is Wx_t + Wh_{t-1} [_, 4*size] built with mixed_layer projections;
    ``state`` the c_{t-1} memory. Default output h_t; the cell is the
    'state' secondary output (get_output_layer(out, 'state') — the
    reference's exact idiom for wiring the cell memory)."""
    if size is None:
        size = _shape(input)[-1] // 4
    w_peep = FL._create_parameter("lstm_step_peep", (3, size), "float32",
                                  I.zeros)
    ins = {"X": [input.var.name], "CPrev": [state.var.name],
           "WPeep": [w_peep.name]}
    if bias_attr:
        bias = FL._create_parameter("lstm_step_b", (4 * size,), "float32",
                                    I.zeros)
        ins["B"] = [bias.name]
    b = default_main_program().current_block()
    h = b.create_var(shape=(-1, size), dtype="float32")
    c = b.create_var(shape=(-1, size), dtype="float32")
    b.append_op("lstm_step", ins, {"H": [h.name], "C": [c.name]},
                {"forget_bias": forget_bias})
    _register_named(name, h)
    return LayerOutput(h, outputs={"state": c})


def gru_step_layer(input: LayerOutput, output_mem: LayerOutput,
                   size: Optional[int] = None, bias_attr: bool = True,
                   name: Optional[str] = None) -> LayerOutput:
    """GRU step for recurrent_group (layers.py:3642; GruStepLayer.cpp):
    ``input`` is x_t @ W [_, 3*size] (projected outside, as the reference
    requires); the recurrent transform of ``output_mem`` (h_{t-1}) happens
    here via the step's own U parameter."""
    if size is None:
        size = _shape(input)[-1] // 3
    u = FL._create_parameter("gru_step_u", (size, 3 * size), "float32",
                             I.uniform(-0.08, 0.08))
    ins = {"X": [input.var.name], "HPrev": [output_mem.var.name],
           "U": [u.name]}
    if bias_attr:
        bias = FL._create_parameter("gru_step_b", (3 * size,), "float32",
                                    I.zeros)
        ins["B"] = [bias.name]
    b = default_main_program().current_block()
    h = b.create_var(shape=(-1, size), dtype="float32")
    b.append_op("gru_unit", ins, {"H": [h.name]}, {})
    _register_named(name, h)
    return LayerOutput(h)


def get_output_layer(input: LayerOutput, arg_name: str,
                     name: Optional[str] = None) -> LayerOutput:
    """Fetch a layer's secondary output by name (layers.py:3802), e.g.
    lstm_step_layer's 'state' (the cell)."""
    if not input.outputs or arg_name not in input.outputs:
        have = sorted(input.outputs or {})
        raise ValueError(f"layer has no output {arg_name!r}; it has {have}")
    v = input.outputs[arg_name]
    _register_named(name, v)
    return LayerOutput(v, input.lengths, input.input_type)


def selective_fc_layer(input, size: int, select: Optional[LayerOutput] = None,
                       act: Optional[str] = "tanh",
                       bias_attr: bool = True,
                       name: Optional[str] = None) -> LayerOutput:
    """Selective fc (layers.py:4967, SelectiveFullyConnectedLayer.cpp):
    only the columns flagged by ``select`` (a 0/1 mask [B, size]) are
    produced. The reference exploits output sparsity on CPU
    (mul_ratio heuristics); on TPU a masked dense matmul IS the fast
    path — the MXU computes the full [B, size] tile either way, so the
    select mask is applied to the result (zeros where unselected, matching
    the reference's sparse output semantics). Without ``select`` it is
    exactly fc_layer."""
    out = fc(input, size, act=act, bias_attr=bias_attr, name=None)
    if select is None:
        _register_named(name, out.var)
        return out
    masked = _emit("elementwise_mul",
                   {"X": [out.var.name], "Y": [select.var.name]},
                   out_shape=(-1, size))
    _register_named(name, masked)
    return LayerOutput(masked)


def gated_unit_layer(input: LayerOutput, size: int,
                     act: Optional[str] = None,
                     name: Optional[str] = None) -> LayerOutput:
    """Gated linear unit y = act(XW + b) * sigmoid(XV + c)
    (layers.py:6589, after arXiv:1612.08083). Sequence inputs keep their
    lengths: the projections are per-position matmuls (fc would flatten
    the time dim)."""
    d = _shape(input)[-1]
    w = FL._create_parameter("gated_w", (d, size), "float32", I.xavier())
    v_ = FL._create_parameter("gated_v", (d, size), "float32", I.xavier())
    bw = FL._create_parameter("gated_bw", (size,), "float32", I.zeros)
    bv = FL._create_parameter("gated_bv", (size,), "float32", I.zeros)
    shp = _shape(input)[:-1] + (size,)
    proj = _emit("matmul", {"X": [input.var.name], "Y": [w.name]},
                 out_shape=shp)
    proj = _emit("elementwise_add", {"X": [proj.name], "Y": [bw.name]},
                 out_shape=shp)
    if act:
        proj = _emit(act, {"X": [proj.name]}, out_shape=shp)
    gate = _emit("matmul", {"X": [input.var.name], "Y": [v_.name]},
                 out_shape=shp)
    gate = _emit("elementwise_add", {"X": [gate.name], "Y": [bv.name]},
                 out_shape=shp)
    gate = _emit("sigmoid", {"X": [gate.name]}, out_shape=shp)
    out = _emit("elementwise_mul", {"X": [proj.name], "Y": [gate.name]},
                out_shape=shp)
    _register_named(name, out)
    return LayerOutput(out, input.lengths, input.input_type)


def dot_prod_layer(input1: LayerOutput, input2: LayerOutput) -> LayerOutput:
    """Row-wise dot product [B, D] x [B, D] -> [B, 1] (layers.py:4146)."""
    prod = _emit("elementwise_mul",
                 {"X": [input1.var.name], "Y": [input2.var.name]},
                 out_shape=_shape(input1))
    out = _emit("reduce_sum", {"X": [prod.name]},
                {"dim": -1, "keep_dim": True}, out_shape=(-1, 1))
    return LayerOutput(out)


def out_prod_layer(input1: LayerOutput, input2: LayerOutput) -> LayerOutput:
    """Outer product [B, D1] x [B, D2] -> [B, D1*D2] (layers.py:4185)."""
    d1, d2 = _shape(input1)[-1], _shape(input2)[-1]
    a3 = _emit("unsqueeze", {"X": [input1.var.name]}, {"axis": -1},
               out_shape=(-1, d1, 1))
    b3 = _emit("unsqueeze", {"X": [input2.var.name]}, {"axis": 1},
               out_shape=(-1, 1, d2))
    m = _emit("matmul", {"X": [a3.name], "Y": [b3.name]},
              out_shape=(-1, d1, d2))
    out = _emit("reshape", {"X": [m.name]}, {"shape": (-1, d1 * d2)},
                out_shape=(-1, d1 * d2))
    return LayerOutput(out)


def eos_layer(input: LayerOutput, eos_id: int) -> LayerOutput:
    """1 where the id equals eos_id (layers.py:4224, EosIdCheckLayer) —
    the recurrent-group stop predicate."""
    v = _emit("equal_scalar", {"X": [input.var.name]}, {"value": eos_id},
              out_shape=_shape(input), out_dtype="int32")
    return LayerOutput(v, input.lengths, input.input_type)


def cross_channel_norm_layer(input: LayerOutput,
                             channels: Optional[int] = None) -> LayerOutput:
    """SSD's cross-channel L2 norm with a trainable per-channel scale
    (layers.py:1357, NormProjectionLayer cross-channel-norm). NHWC: the
    channel axis is last."""
    c = channels or _shape(input)[-1]
    scale = FL._create_parameter("ccn_scale", (c,), "float32", I.ones)
    normed = _emit("l2_normalize", {"X": [input.var.name]}, {"axis": -1},
                   out_shape=_shape(input))
    out = _emit("elementwise_mul", {"X": [normed.name], "Y": [scale.name]},
                out_shape=_shape(input))
    return LayerOutput(out, input.lengths, input.input_type)


def row_l2_norm_layer(input: LayerOutput) -> LayerOutput:
    """Row-wise L2 normalization (layers.py:3191, RowL2NormLayer)."""
    v = _emit("l2_normalize", {"X": [input.var.name]}, {"axis": -1},
              out_shape=_shape(input))
    return LayerOutput(v, input.lengths, input.input_type)


def scale_shift_layer(input: LayerOutput, bias_attr: bool = True) -> LayerOutput:
    """y = w * x + b with SCALAR trainable w (and b) — layers.py:7114,
    ScaleShiftLayer (the trainable SlopeIntercept)."""
    w = FL._create_parameter("scale_shift_w", (1,), "float32", I.ones)
    out = _emit("elementwise_mul", {"X": [input.var.name], "Y": [w.name]},
                out_shape=_shape(input))
    if bias_attr:
        bias = FL._create_parameter("scale_shift_b", (1,), "float32", I.zeros)
        out = _emit("elementwise_add", {"X": [out.name], "Y": [bias.name]},
                    out_shape=_shape(input))
    return LayerOutput(out, input.lengths, input.input_type)


def resize_layer(input: LayerOutput, size: int) -> LayerOutput:
    """Reflow the batch matrix to row width ``size`` (layers.py:7155,
    ResizeLayer): [H, W] -> [H*W/size, size]."""
    v = _emit("reshape", {"X": [input.var.name]}, {"shape": (-1, size)},
              out_shape=(-1, size))
    return LayerOutput(v)


def switch_order_layer(input: LayerOutput) -> LayerOutput:
    """NCHW -> NHWC transpose (layers.py:6682, SwitchOrderLayer). This
    build is NHWC-native (XLA's preferred TPU conv layout), so this layer
    exists for reference configs that interleave layout switches; it
    performs the same permutation on an explicitly NCHW tensor."""
    s = _shape(input)
    if len(s) != 4:
        raise ValueError(f"switch_order_layer needs a 4-D NCHW input, "
                         f"got shape {s}")
    v = _emit("transpose", {"X": [input.var.name]}, {"axis": (0, 2, 3, 1)},
              out_shape=(s[0], s[2], s[3], s[1]))
    return LayerOutput(v)


# ------------------------------------------------- sub-sequence family ---

def sub_seq_layer(input: LayerOutput, offsets: LayerOutput,
                  sizes: LayerOutput) -> LayerOutput:
    """Per-sequence slice by (offset, size) index layers (layers.py:7176,
    SubSequenceLayer). Output lengths are the sizes."""
    if input.lengths is None:
        raise ValueError("sub_seq_layer needs a sequence input")
    max_t = _shape(input)[1] if len(_shape(input)) > 2 else -1
    b = default_main_program().current_block()
    out = b.create_var(shape=_shape(input), dtype="float32")
    b.append_op("sequence_slice",
                {"X": [input.var.name], "Lengths": [input.lengths.name],
                 "Offset": [offsets.var.name], "Length": [sizes.var.name]},
                {"Out": [out.name]},
                {"max_out": max_t} if max_t and max_t > 0 else {})
    return LayerOutput(out, sizes.var, input.input_type)


def seq_slice_layer(input: LayerOutput, starts: Optional[LayerOutput],
                    ends: Optional[LayerOutput]) -> LayerOutput:
    """Slice each sequence between per-sample start/end indices
    (layers.py:6861, SequenceSliceLayer). starts=None slices from the
    beginning; ends=None to the sequence end. (The reference's multi-slice
    form — several (start, end) pairs per sequence — is expressed by
    calling this layer per pair and seq_concat_layer-ing the results.)"""
    if input.lengths is None:
        raise ValueError("seq_slice_layer needs a sequence input")
    if starts is None and ends is None:
        raise ValueError("give at least one of starts/ends")
    if starts is None:
        start_var = _emit("scale", {"X": [input.lengths.name]}, {"scale": 0},
                          out_shape=(-1,), out_dtype="int32")
    else:
        start_var = starts.var
    end_var = input.lengths if ends is None else ends.var
    length = _emit("elementwise_sub", {"X": [end_var.name],
                                       "Y": [start_var.name]},
                   out_shape=(-1,), out_dtype="int32")
    return sub_seq_layer(input, LayerOutput(start_var), LayerOutput(length))


def seq_concat_layer(a: LayerOutput, b: LayerOutput) -> LayerOutput:
    """Concatenate two sequences per sample: [a1..am, b1..bn]
    (layers.py:3391, SequenceConcatLayer)."""
    if a.lengths is None or b.lengths is None:
        raise ValueError("seq_concat_layer needs two sequence inputs")
    blk = default_main_program().current_block()
    ta = _shape(a)[1] if len(_shape(a)) > 2 else -1
    tb = _shape(b)[1] if len(_shape(b)) > 2 else -1
    t_out = (ta + tb) if (ta and tb and ta > 0 and tb > 0) else -1
    out = blk.create_var(shape=(_shape(a)[0], t_out) + _shape(a)[2:],
                         dtype="float32")
    lens = blk.create_var(shape=(-1,), dtype="int32")
    blk.append_op("sequence_concat",
                  {"X": [a.var.name], "XLengths": [a.lengths.name],
                   "Y": [b.var.name], "YLengths": [b.lengths.name]},
                  {"Out": [out.name], "OutLengths": [lens.name]}, {})
    return LayerOutput(out, lens, a.input_type)


def kmax_seq_score_layer(input: LayerOutput,
                         beam_size: int = 1) -> LayerOutput:
    """Indices of the beam_size highest-scoring positions per sequence
    (layers.py:6927, KmaxSeqScoreLayer); padding never selected."""
    if input.lengths is None:
        raise ValueError("kmax_seq_score_layer needs a sequence input")
    v = _emit("kmax_seq_score",
              {"X": [input.var.name], "Lengths": [input.lengths.name]},
              {"beam_size": beam_size}, out_shape=(-1, beam_size),
              out_dtype="int32")
    return LayerOutput(v)


def sub_nested_seq_layer(input: LayerOutput,
                         selected_indices: LayerOutput) -> LayerOutput:
    """Trim a nested sequence to the selected sub-sequences
    (layers.py:6781, SubNestedSequenceLayer — the beam-training trim);
    pairs with kmax_seq_score_layer."""
    if input.sub_lengths is None:
        raise ValueError("sub_nested_seq_layer needs a nested sequence "
                         "input (sub_lengths)")
    blk = default_main_program().current_block()
    k = _shape(selected_indices)[-1]
    out = blk.create_var(shape=(_shape(input)[0], k) + _shape(input)[2:],
                         dtype=input.var.dtype)
    sub = blk.create_var(shape=(-1, k), dtype="int32")
    blk.append_op("sub_nested_seq",
                  {"X": [input.var.name],
                   "SubLengths": [input.sub_lengths.name],
                   "Indices": [selected_indices.var.name]},
                  {"Out": [out.name], "SubLengthsOut": [sub.name]}, {})
    lens = _emit("scale", {"X": [input.lengths.name]},
                 {"scale": 0, "bias": k}, out_shape=(-1,), out_dtype="int32")
    return LayerOutput(out, lens, input.input_type, sub_lengths=sub)


# ------------------------------------------------- detection DSL trio ---

def _concat_heads(inputs, last_dim: int) -> Variable:
    """Normalize one-or-list of per-feature-map heads to a single
    [B, P_total, last_dim] variable (SSD multi-scale head concat)."""
    heads = inputs if isinstance(inputs, (list, tuple)) else [inputs]
    if len(heads) == 1:
        return heads[0].var
    return _emit("concat", {"X": [h.var.name for h in heads]}, {"axis": 1},
                 out_shape=(-1, -1, last_dim))


def priorbox_layer(input: LayerOutput, image: LayerOutput,
                   aspect_ratio, variance, min_size, max_size=(),
                   flip: bool = True, clip: bool = True) -> LayerOutput:
    """SSD prior boxes for one feature map (layers.py:1114). NHWC shapes
    are read statically from the feature/image layers; returns boxes
    [P, 4] with the variances as the 'variances' secondary output."""
    fh, fw = _shape(input)[1], _shape(input)[2]
    ih, iw = _shape(image)[1], _shape(image)[2]
    mins = list(min_size) if isinstance(min_size, (list, tuple)) else [min_size]
    maxs = list(max_size) if isinstance(max_size, (list, tuple)) else [max_size]
    blk = default_main_program().current_block()
    box_parts, var_parts = [], []
    for i, mn in enumerate(mins):
        boxes = blk.create_var(shape=(-1, 4), dtype="float32")
        variances = blk.create_var(shape=(-1, 4), dtype="float32")
        blk.append_op("prior_box", {},
                      {"Boxes": [boxes.name], "Variances": [variances.name]},
                      {"feature_hw": (fh, fw), "image_hw": (ih, iw),
                       "min_size": mn,
                       "max_size": maxs[i] if i < len(maxs) else None,
                       "aspect_ratios": tuple(aspect_ratio), "flip": flip,
                       "clip": clip, "variance": tuple(variance)})
        box_parts.append(boxes)
        var_parts.append(variances)
    if len(box_parts) == 1:
        return LayerOutput(box_parts[0], outputs={"variances": var_parts[0]})

    # Cell-major interleave across sizes (PriorBoxLayer.cpp: per cell, ALL
    # sizes' priors are contiguous) so prior rows line up with conv heads
    # that emit priors-per-cell; a plain axis-0 concat would be size-major.
    cells = fh * fw
    n_ratio = len(tuple(aspect_ratio)) * (2 if flip else 1)

    def per_cell(var, i):
        p_i = 1 + (1 if i < len(maxs) and maxs[i] is not None else 0) + n_ratio
        return _emit("reshape", {"X": [var.name]},
                     {"shape": (cells, p_i, 4)}, out_shape=(cells, p_i, 4))

    boxes3 = _emit("concat",
                   {"X": [per_cell(b, i).name
                          for i, b in enumerate(box_parts)]},
                   {"axis": 1}, out_shape=(cells, -1, 4))
    vars3 = _emit("concat",
                  {"X": [per_cell(v, i).name
                         for i, v in enumerate(var_parts)]},
                  {"axis": 1}, out_shape=(cells, -1, 4))
    boxes = _emit("reshape", {"X": [boxes3.name]}, {"shape": (-1, 4)},
                  out_shape=(-1, 4))
    variances = _emit("reshape", {"X": [vars3.name]}, {"shape": (-1, 4)},
                      out_shape=(-1, 4))
    return LayerOutput(boxes, outputs={"variances": variances})


def multibox_loss_layer(input_loc, input_conf, priorbox: LayerOutput,
                        label: LayerOutput, num_classes: int,
                        overlap_threshold: float = 0.5,
                        neg_pos_ratio: float = 3.0,
                        background_id: int = 0) -> LayerOutput:
    """SSD loss (layers.py:1160): localization smooth-L1 + mined softmax
    confidence vs matched priors. ``label`` packs ground truth as
    (boxes [B,G,4], classes [B,G], mask [B,G]) secondary outputs of a
    ground-truth data composite (see tests) or a LayerOutput with
    .outputs {'gt_label','gt_mask'}."""
    loc = _concat_heads(input_loc, 4)
    conf = _concat_heads(input_conf, num_classes)
    if not label.outputs or not {"gt_label", "gt_mask"} <= set(label.outputs):
        raise ValueError("multibox_loss_layer label needs outputs "
                         "{'gt_label', 'gt_mask'} (ground-truth composite)")
    v = _emit("multibox_loss",
              {"Loc": [loc.name], "Conf": [conf.name],
               "PriorBox": [priorbox.var.name],
               "PriorVar": [priorbox.outputs["variances"].name],
               "GTBox": [label.var.name],
               "GTLabel": [label.outputs["gt_label"].name],
               "GTMask": [label.outputs["gt_mask"].name]},
              {"overlap_threshold": overlap_threshold,
               "neg_pos_ratio": neg_pos_ratio,
               "background_id": background_id},
              out_shape=(-1,), out_slot="Loss")
    return _mean_of(v)


def detection_output_layer(input_loc, input_conf, priorbox: LayerOutput,
                           num_classes: int, nms_threshold: float = 0.45,
                           confidence_threshold: float = 0.01,
                           keep_top_k: int = 100,
                           background_id: int = 0) -> LayerOutput:
    """SSD inference head (layers.py:1233): decode + per-class NMS. Boxes
    are the default output; scores and the valid mask are secondary
    outputs ('scores', 'valid')."""
    loc = _concat_heads(input_loc, 4)
    conf = _concat_heads(input_conf, num_classes)
    blk = default_main_program().current_block()
    nc = num_classes - 1                     # per non-background class
    boxes = blk.create_var(shape=(-1, nc, keep_top_k, 4), dtype="float32")
    scores = blk.create_var(shape=(-1, nc, keep_top_k), dtype="float32")
    valid = blk.create_var(shape=(-1, nc, keep_top_k), dtype="float32")
    blk.append_op("detection_output",
                  {"Loc": [loc.name], "Conf": [conf.name],
                   "PriorBox": [priorbox.var.name],
                   "PriorVar": [priorbox.outputs["variances"].name]},
                  {"Boxes": [boxes.name], "Scores": [scores.name],
                   "Valid": [valid.name]},
                  {"num_classes": num_classes,
                   "nms_threshold": nms_threshold,
                   "score_threshold": confidence_threshold,
                   "keep_top_k": keep_top_k,
                   "background_id": background_id})
    return LayerOutput(boxes, outputs={"scores": scores, "valid": valid})


def conv_operator(img: LayerOutput, filter: LayerOutput, filter_size: int,
                  num_filters: int, num_channels: Optional[int] = None,
                  stride: int = 1, padding: int = 0) -> _Projection:
    """Conv with a DYNAMIC filter input inside mixed_layer
    (layers.py conv_operator; ConvOperator.cpp): the second input IS the
    filter tensor (parameter-free), e.g. attention-generated kernels.
    ``filter``: [B, num_filters*C*k*k] per-sample filters flattened in the
    reference's (num_filters, C, k, k) order; the conv runs per sample
    (vmap in the op)."""
    c = num_channels or _shape(img)[-1]

    def emit():
        return _emit("dyn_conv2d",
                     {"X": [img.var.name], "Filter": [filter.var.name]},
                     {"filter_size": filter_size, "num_filters": num_filters,
                      "channels": c, "stride": stride, "padding": padding},
                     out_shape=(-1, -1, -1, num_filters))
    return _Projection(emit, num_filters, src=img)


def conv_projection(input: LayerOutput, filter_size: int, num_filters: int,
                    num_channels: Optional[int] = None, stride: int = 1,
                    padding: int = 0) -> _Projection:
    """Conv with a TRAINABLE filter as a mixed_layer projection
    (layers.py conv_projection; ConvProjection.cpp). NHWC."""
    c = num_channels or _shape(input)[-1]

    def emit():
        w = FL._create_parameter(
            "convproj_w", (filter_size, filter_size, c, num_filters),
            "float32", I.msra())
        return _emit("conv2d", {"Input": [input.var.name],
                                "Filter": [w.name]},
                     {"strides": stride, "paddings": padding},
                     out_shape=(-1, -1, -1, num_filters))
    return _Projection(emit, num_filters, src=input)


def scale_sub_region_layer(input: LayerOutput, indices: LayerOutput,
                           value: float) -> LayerOutput:
    """Scale a per-sample sub-region of a CHW/HWC feature map by ``value``
    (layers.py scale_sub_region_layer; ScaleSubRegionLayer.cpp). indices:
    [B, 6] = (C_start, C_end, H_start, H_end, W_start, W_end), 1-based
    inclusive, matching the reference layout."""
    v = _emit("scale_sub_region",
              {"X": [input.var.name], "Indices": [indices.var.name]},
              {"value": value}, out_shape=_shape(input))
    return LayerOutput(v)


def slice_projection(input: LayerOutput, slices) -> _Projection:
    """Concatenate feature slices [(start, end), ...] of the input
    (SliceProjection, layers.py slice_projection)."""
    total = sum(e - s for s, e in slices)

    def emit():
        parts = []
        ndim = len(_shape(input))
        for s, e in slices:
            starts = [0] * (ndim - 1) + [s]
            shape = [-1] * (ndim - 1) + [e - s]
            parts.append(_emit("crop", {"X": [input.var.name]},
                               {"offsets": starts, "shape": shape},
                               out_shape=_shape(input)[:-1] + (e - s,)))
        if len(parts) == 1:
            return parts[0]
        return _emit("concat", {"X": [p.name for p in parts]}, {"axis": -1},
                     out_shape=_shape(input)[:-1] + (total,))
    return _Projection(emit, total, src=input)


def cross_entropy_over_beam(scores: LayerOutput, gold_index: LayerOutput,
                            gold_score: Optional[LayerOutput] = None) -> LayerOutput:
    """Beam-training cross entropy (CrossEntropyOverBeamLayer,
    layers.py cross_entropy_over_beam): softmax CE over each sample's beam
    candidate scores [B, K] with the gold candidate's beam position as the
    label. When the gold fell OUT of the beam, pass gold_index = K and its
    model score via ``gold_score`` — it joins as a (K+1)-th slot, the
    reference's append-gold construction. In-beam samples never see the
    appended slot (it is masked), so their gold score is counted exactly
    once in the softmax partition."""
    ins = {"X": [scores.var.name], "GoldIdx": [gold_index.var.name]}
    if gold_score is not None:
        ins["GoldScore"] = [gold_score.var.name]
    v = _emit("cross_entropy_over_beam", ins, out_shape=(-1,))
    return _mean_of(v)


def print_layer(input: LayerOutput, head: int = 8) -> LayerOutput:
    """Forward-value printer (layers.py print_layer / PrintLayer): registers
    a fetchable head-of-values metric (the v2 evaluator DSL's printer) and
    passes the input through unchanged — host-side logging decides
    formatting, as in the reference."""
    from .evaluator import value_printer_evaluator
    value_printer_evaluator(input, head=head)
    return input


# ---------------------------------------------------------------------------
# Verbatim name parity with the reference DSL. Every name in the reference's
# __all__ (trainer_config_helpers/layers.py:34-140, 115 names) is importable
# under its reference spelling — either the canonical function above or an
# alias/enum here. Swept by tests/test_v2_import_parity.py.
# ---------------------------------------------------------------------------

class AggregateLevel:
    """Aggregation level enum (layers.py:284): TO_NO_SEQUENCE pools each
    (sub-)sequence down to one vector; TO_SEQUENCE pools each nested
    sub-sequence to one timestep of the outer sequence (our nested_* ops)."""
    TO_NO_SEQUENCE = "non-seq"
    TO_SEQUENCE = "seq"
    # deprecated spellings kept by the reference for old configs
    EACH_TIMESTEP = TO_NO_SEQUENCE
    EACH_SEQUENCE = TO_SEQUENCE


class ExpandLevel:
    """Expansion level enum (layers.py:1816) — the inverse of
    AggregateLevel, used by expand_layer."""
    FROM_NO_SEQUENCE = AggregateLevel.TO_NO_SEQUENCE
    FROM_SEQUENCE = AggregateLevel.TO_SEQUENCE
    FROM_TIMESTEP = FROM_NO_SEQUENCE


class LayerType:
    """Layer type string enum (layers.py:153). The v2 DSL here compiles to
    Program IR ops rather than proto layer configs, so these are parity
    constants: ``LayerOutput``s don't carry them, but configs written
    against the reference enum keep importing and comparing."""
    DATA = "data"
    MIXED_LAYER = "mixed"
    LSTMEMORY = "lstmemory"
    GRUMEMORY = "gated_recurrent"
    SEQUENCE_LAST_INSTANCE = "seqlastins"
    SEQUENCE_FIRST_INSTANCE = "seqfirstins"
    SEQUENCE_RESHAPE = "seqreshape"
    POOLING_MAX = "max"
    POOLING_AVG = "average"
    FC_LAYER = "fc"
    COST = "cost"
    COSINE_SIM = "cos"
    HSIGMOID = "hsigmoid"
    CONV_LAYER = "conv"
    CONVTRANS_LAYER = "convt"
    POOL_LAYER = "pool"
    POOL3D_LAYER = "pool3d"
    BATCH_NORM_LAYER = "batch_norm"
    NORM_LAYER = "norm"
    SUM_TO_ONE_NORM_LAYER = "sum_to_one_norm"
    ROW_L2_NORM_LAYER = "row_l2_norm"
    ADDTO_LAYER = "addto"
    CONCAT_LAYER = "concat"
    CONCAT_PROJ_LAYER = "concat2"
    SEQUENCE_CONCAT_LAYER = "seqconcat"
    LSTM_STEP_LAYER = "lstm_step"
    GRU_STEP_LAYER = "gru_step"
    GET_OUTPUT_LAYER = "get_output"
    EXPAND_LAYER = "expand"
    INTERPOLATION_LAYER = "interpolation"
    BILINEAR_INTERP_LAYER = "bilinear_interp"
    POWER_LAYER = "power"
    SCALING_LAYER = "scaling"
    TRANS_LAYER = "trans"
    ROTATE_LAYER = "rotate"
    DROPOUT_LAYER = "dropout"
    TENSOR_LAYER = "tensor"
    SELECTIVE_FC_LAYER = "selective_fc"
    SAMPLING_ID_LAYER = "sampling_id"
    SLOPE_INTERCEPT_LAYER = "slope_intercept"
    LINEAR_COMBINATION_LAYER = "convex_comb"
    BLOCK_EXPAND = "blockexpand"
    MAXOUT = "maxout"
    SPP_LAYER = "spp"
    PAD_LAYER = "pad"
    MULTIPLEX_LAYER = "multiplex"
    ROW_CONV_LAYER = "row_conv"
    PRINT_LAYER = "print"
    PRIORBOX_LAYER = "priorbox"
    MULTIBOX_LOSS_LAYER = "multibox_loss"
    DETECTION_OUTPUT_LAYER = "detection_output"
    CTC_LAYER = "ctc"
    WARP_CTC_LAYER = "warp_ctc"
    CRF_LAYER = "crf"
    CRF_DECODING_LAYER = "crf_decoding"
    NCE_LAYER = "nce"
    MAXID_LAYER = "maxid"
    EOSID_LAYER = "eos_id"
    RECURRENT_LAYER = "recurrent"
    CROP_LAYER = "crop"
    SUB_NESTED_SEQ = "sub_nested_seq"
    CLIP_LAYER = "clip"
    SEQ_SLICE = "seq_slice"
    KMAX_SEQ_SCORE = "kmax_seq_score"
    SCALE_SHIFT_LAYER = "scale_shift"
    RESIZE = "resize"
    SUB_SEQ_LAYER = "subseq"
    SCALE_SUB_REGION_LAYER = "scale_sub_region"

    @classmethod
    def is_layer_type(cls, type_name) -> bool:
        return any(getattr(cls, k) == type_name for k in dir(cls)
                   if not k.startswith("_") and
                   isinstance(getattr(cls, k), str))


def SubsequenceInput(input: LayerOutput) -> LayerOutput:
    """DEPRECATED in the reference (layers.py:3925) and here: nested
    sub-sequence inputs to recurrent_group are detected from the layer's
    own input_type, so this marker is an identity passthrough."""
    return input


def layer_support(*attrs):
    """Parity decorator (layers.py:388). The reference uses it to validate
    ExtraLayerAttribute support per layer; Program-IR layers take plain
    keyword attrs, so this wraps the function unchanged."""
    def decorator(method):
        return method
    return decorator


class BeamInput:
    """One beam for cross_entropy_over_beam (layers.py:6206): candidate
    scores over the beam, the selected candidate ids, and the gold index."""

    def __init__(self, candidate_scores: LayerOutput,
                 selected_candidates: LayerOutput, gold: LayerOutput):
        self.candidate_scores = candidate_scores
        self.selected_candidates = selected_candidates
        self.gold = gold


def recurrent_layer(input: LayerOutput, act: Optional[str] = None,
                    bias_attr: bool = True,
                    reverse: bool = False) -> LayerOutput:
    """Simple (Elman) full-matrix recurrence over a sequence
    (layers.py:3846 recurrent_layer; gserver/layers/RecurrentLayer.cpp):
    h_t = act(x_t + h_{t-1} @ U + b). As in the reference, the input is
    NOT projected — its width is the state width; compose with fc/mixed
    for the input transform. Compiles to one masked lax.scan."""
    b = default_main_program().current_block()
    size = _shape(input)[-1]
    u = FL._create_parameter("rnn_u", (size, size), "float32",
                             I.uniform(-0.08, 0.08))
    ins = {"X": [input.var.name], "Lengths": [input.lengths.name],
           "U": [u.name]}
    if bias_attr:
        bias = FL._create_parameter("rnn_b", (size,), "float32", I.zeros)
        ins["B"] = [bias.name]
    out = b.create_var(shape=input.var.shape, dtype="float32")
    last = b.create_var(shape=(-1, size), dtype="float32")
    b.append_op("simple_rnn", ins,
                {"Out": [out.name], "LastH": [last.name]},
                {"act": act or "tanh", "reverse": reverse})
    return LayerOutput(out, input.lengths, input.input_type)


def warp_ctc_layer(input: LayerOutput, label: LayerOutput, size: int,
                   blank: int = 0,
                   norm_by_times: bool = False) -> LayerOutput:
    """warp_ctc_layer (layers.py WarpCTCLayer): the reference keeps two CTC
    backends (CTCLayer and Baidu's warp-ctc) with identical loss semantics;
    here one XLA implementation serves both names. ``norm_by_times`` is
    accepted for signature parity — the returned loss is already
    batch-mean-normalized, matching the trainer's use."""
    return ctc_layer(input, label, size, blank=blank)


# name-parity aliases (the reference exports these spellings in __all__)
convex_comb_layer = linear_comb_layer
cross_entropy = cross_entropy_cost
cross_entropy_with_selfnorm = cross_entropy_with_selfnorm_cost
multi_binary_label_cross_entropy = multi_binary_label_cross_entropy_cost
hsigmoid = hsigmoid_layer
data_layer = data
embedding_layer = embedding
fc_layer = fc
pooling_layer = pooling
img_conv_layer = img_conv
img_pool_layer = img_pool
img_pool3d_layer = img_pool3d
img_conv3d_layer = img_conv3d
concat_layer = concat
dropout_layer = dropout
context_projection = context_projection_layer
maxid_layer = max_id_layer
printer_layer = print_layer
# gru_step_naive_layer (layers.py:3713) differs from gru_step_layer only in
# dropping the fused-kernel constraint on gate layout; one XLA gru_unit op
# serves both spellings
gru_step_naive_layer = gru_step_layer
