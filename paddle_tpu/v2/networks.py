"""Prebuilt networks (trainer_config_helpers/networks.py analog):
simple_lstm:553, bidirectional_lstm:1230, text_conv_pool, simple_img_conv_pool:144,
vgg_16_network:468."""

from __future__ import annotations

from typing import Optional

from ..fluid import layers as FL
from ..fluid.framework import default_main_program
from ..nn import initializer as I
from . import layer as L
from .layer import LayerOutput


def simple_lstm(input: LayerOutput, size: int, reverse: bool = False) -> LayerOutput:
    """networks.py simple_lstm — the reference projects inputs to 4*size then
    runs lstmemory; our lstm op fuses that projection (one MXU matmul)."""
    return L.lstmemory(input, size, reverse=reverse)


def bidirectional_lstm(input: LayerOutput, size: int,
                       return_concat: bool = True) -> LayerOutput:
    fwd = L.lstmemory(input, size)
    bwd = L.lstmemory(input, size, reverse=True)
    last_f = L.last_seq(fwd)
    first_b = L.first_seq(bwd)
    return L.concat([last_f, first_b], axis=-1)


def text_conv_pool(input: LayerOutput, hidden_size: int,
                   context_len: int = 3) -> LayerOutput:
    """sequence conv + max pool (networks.py text_conv_pool)."""
    b = default_main_program().global_block()
    in_dim = input.var.shape[-1]
    filt = FL._create_parameter("seqconv_w", (context_len * in_dim, hidden_size),
                                "float32", I.uniform(-0.08, 0.08))
    out = b.create_var(shape=input.var.shape[:-1] + (hidden_size,),
                       dtype="float32")
    b.append_op("sequence_conv",
                {"X": [input.var.name], "Lengths": [input.lengths.name],
                 "Filter": [filt.name]},
                {"Out": [out.name]},
                {"context_start": -(context_len // 2),
                 "context_length": context_len})
    h = LayerOutput(FL.relu(out), input.lengths, input.input_type)
    return L.pooling(h, "max")


def simple_img_conv_pool(input: LayerOutput, filter_size: int,
                         num_filters: int, pool_size: int,
                         act: str = "relu") -> LayerOutput:
    conv = L.img_conv(input, num_filters, filter_size, act=act)
    return L.img_pool(conv, pool_size)


def vgg_16_network(input_image: LayerOutput, num_classes: int = 1000,
                   width_mult: float = 1.0) -> LayerOutput:
    """VGG-16 conv stack (networks.py vgg_16_network:468)."""
    cfg = [(2, 64), (2, 128), (3, 256), (3, 512), (3, 512)]
    h = input_image
    for n, ch in cfg:
        ch = max(8, int(ch * width_mult))
        for _ in range(n):
            h = L.img_conv(h, ch, 3, padding=1, act="relu")
        h = L.img_pool(h, 2)
    h = LayerOutput(FL.pool2d(h.var, global_pooling=True))
    h = L.fc(h, 512, act="relu")
    h = L.fc(h, 512, act="relu")
    return L.fc(h, num_classes)


def simple_attention(encoded_sequence: LayerOutput,
                     encoded_proj: LayerOutput,
                     decoder_state: LayerOutput,
                     name: Optional[str] = None) -> LayerOutput:
    """Bahdanau-style attention context (networks.py:654 simple_attention).

    For use inside a recurrent_group / beam_search step: ``encoded_sequence``
    [B, T, H] and ``encoded_proj`` [B, T, A] come in as StaticInputs (with
    lengths); ``decoder_state`` is the current [B, S] memory. Returns the
    [B, H] context vector. The reference expands the decoder state over the
    sequence and runs sequence_softmax over the scores — identical math here,
    as fixed-shape masked ops.

    ``name`` fixes the internal parameter names (``<name>_dp_w``,
    ``<name>_v``) so a second call — e.g. the generation sub-model reusing a
    training decoder's attention — shares the SAME weights, the reference's
    name-based sharing in its networks.py helpers.
    """
    A = encoded_proj.var.shape[-1]
    # project decoder state to attention space: [B, A]
    dp = FL.fc(decoder_state.var, A, bias_attr=False,
               param_attr={"name": f"{name}_dp_w"} if name else None)
    dp3 = FL.reshape(dp, (-1, 1, A))
    summed = FL.elementwise_add(encoded_proj.var, dp3)     # broadcast over T
    e = FL.activation(summed, "tanh")
    # per-step score: contract the attention dim with a learned vector
    v = FL._create_parameter("att_v", (A, 1), "float32",
                             I.uniform(-0.1, 0.1),
                             attr={"name": f"{name}_v"} if name else None)
    scores3 = FL.matmul(e, v)                              # [B, T, 1]
    scores = FL.squeeze(scores3, -1)                       # [B, T]
    weights = FL.sequence_softmax(scores, encoded_sequence.lengths)
    w3 = FL.unsqueeze(weights, -1)                         # [B, T, 1]
    weighted = FL.elementwise_mul(encoded_sequence.var, w3)
    context = FL.reduce_sum(weighted, dim=1)               # [B, H]
    return LayerOutput(context)


def simple_gru(input: LayerOutput, size: int,
               reverse: bool = False) -> LayerOutput:
    """networks.py simple_gru / simple_gru2 — fused projection + GRU scan."""
    return L.grumemory(input, size, reverse=reverse)


def bidirectional_gru(input: LayerOutput, size: int) -> LayerOutput:
    """networks.py bidirectional_gru: concat(last fwd state, first bwd)."""
    fwd = L.grumemory(input, size)
    bwd = L.grumemory(input, size, reverse=True)
    return L.concat([L.last_seq(fwd), L.first_seq(bwd)], axis=-1)


def sequence_conv_pool(input: LayerOutput, context_len: int,
                       hidden_size: int,
                       pool_type: str = "max") -> LayerOutput:
    """networks.py sequence_conv_pool: context window FC + sequence pool."""
    proj = L.mixed_layer(
        size=hidden_size,
        input=[L.full_matrix_projection(
            L.mixed_layer(size=input.var.shape[-1] * context_len,
                          input=[L.context_projection_layer(
                              input, context_len)]),
            hidden_size)],
        act="relu")
    ctx = LayerOutput(proj.var, input.lengths, input.input_type)
    return L.pooling(ctx, pool_type)


def img_conv_group(input: LayerOutput, conv_filters,
                   pool_size: int = 2) -> LayerOutput:
    """networks.py img_conv_group: N conv+BN blocks then one pool (channel
    count inferred from the input)."""
    h = input
    for nf in conv_filters:
        h = L.img_conv(h, nf, 3, padding=1, act=None)
        h = L.batch_norm_layer(h, act="relu")
    return L.img_pool(h, pool_size)


def simple_attention_pool(encoded: LayerOutput,
                          hidden: int = 64) -> LayerOutput:
    """Self-attentive pooling: tanh hidden projection then a learned scalar
    query over encoder states — the building block behind networks.py
    simple_attention when used without a decoder state."""
    # projections handle the [B, T, D] rank (plain fc would flatten the time
    # dim into the feature dim)
    h = L.mixed_layer(size=hidden,
                      input=[L.full_matrix_projection(encoded, hidden)],
                      act="tanh")
    scores = L.mixed_layer(size=1, input=[L.full_matrix_projection(h, 1)])
    b = default_main_program().current_block()
    flat = b.create_var(shape=scores.var.shape[:-1], dtype="float32")
    b.append_op("squeeze", {"X": [scores.var.name]}, {"Out": [flat.name]},
                {"axis": -1})
    sm = b.create_var(shape=flat.shape, dtype="float32")
    b.append_op("sequence_softmax",
                {"X": [flat.name], "Lengths": [encoded.lengths.name]},
                {"Out": [sm.name]}, {})
    w3 = b.create_var(shape=tuple(sm.shape) + (1,), dtype="float32")
    b.append_op("unsqueeze", {"X": [sm.name]}, {"Out": [w3.name]},
                {"axis": -1})
    weighted = L.scaling_layer(encoded, LayerOutput(w3))
    return L.pooling(LayerOutput(weighted.var, encoded.lengths,
                                 encoded.input_type), "sum")
