"""v2 optimizer facade (python/paddle/v2/optimizer.py analog) — maps the
settings() vocabulary onto fluid program-level optimizers."""

from __future__ import annotations

from ..fluid.optimizer import (AdamOptimizer, MomentumOptimizer, SGDOptimizer)


class Optimizer:
    def __init__(self, fluid_opt):
        self.fluid_opt = fluid_opt


def SGD(learning_rate: float = 0.01):  # noqa: N802 — reference name
    return Optimizer(SGDOptimizer(learning_rate))


def Momentum(learning_rate: float = 0.01, momentum: float = 0.9):  # noqa: N802
    return Optimizer(MomentumOptimizer(learning_rate, momentum))


def Adam(learning_rate: float = 1e-3, beta1: float = 0.9,  # noqa: N802
         beta2: float = 0.999, epsilon: float = 1e-8):
    return Optimizer(AdamOptimizer(learning_rate, beta1, beta2, epsilon))
