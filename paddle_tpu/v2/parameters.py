"""Parameters facade (python/paddle/v2/parameters.py analog): numpy get/set
over the executor scope + tar serialization (:296-358 to_tar/from_tar)."""

from __future__ import annotations

from typing import List

import jax.numpy as jnp
import numpy as np

from ..fluid.executor import Scope
from ..fluid.framework import Program
from ..trainer.checkpoint import from_tar, to_tar


class Parameters:
    def __init__(self, scope: Scope, program: Program):
        self._scope = scope
        self._program = program

    def names(self) -> List[str]:
        b = self._program.global_block()
        return [n for n, v in b.vars.items()
                if v.persistable and self._scope.has(n)]

    def get(self, name: str) -> np.ndarray:
        return np.asarray(self._scope.get(name))

    def set(self, name: str, value: np.ndarray):
        self._scope.set(name, jnp.asarray(value))

    def __getitem__(self, name):
        return self.get(name)

    def __setitem__(self, name, value):
        self.set(name, value)

    def to_tar(self, f):
        to_tar(f, {n: self.get(n) for n in self.names()})

    def from_tar(self, f):
        for name, arr in from_tar(f).items():
            self.set(name, arr)
