"""Topology — the ``paddle.v2.topology`` facade (v2/topology.py:27).

The reference's Topology wrapped the cost layer(s), validated the config,
and serialized the ModelConfig proto the gserver engine consumed. Here the
engine artifact is the fluid Program (JSON-serializable), so Topology wraps
the cost and exposes the same surface: ``proto()`` (the serialized model —
a Program dict), ``data_type()`` (ordered (name, InputType) feed slots),
``get_layer_proto(name)`` (a var's serialized desc), and
``serialize_for_inference(outputs)`` (the pruned forward program, the
merged-model role of Topology.serialize_for_inference).
"""

from __future__ import annotations

import json
from typing import List, Optional, Sequence, Tuple, Union

from ..fluid.framework import default_main_program
from .layer import LayerOutput


class Topology:
    def __init__(self, cost: Union[LayerOutput, Sequence[LayerOutput]],
                 program=None):
        if isinstance(cost, LayerOutput):
            self.costs = [cost]
        elif isinstance(cost, (list, tuple)):
            self.costs = list(cost)
        else:
            raise ValueError("Topology expects LayerOutput cost(s), "
                             f"got {type(cost).__name__}")
        self.program = program or default_main_program()
        for c in self.costs:          # validation, as the reference's
            if not isinstance(c, LayerOutput):   # Topology.__init__ did
                raise ValueError("Topology expects LayerOutput cost(s), "
                                 f"got {type(c).__name__}")

    def proto(self) -> dict:
        """The serialized model config (Program dict; ModelConfig analog)."""
        return self.program.to_dict()

    def serialize(self) -> str:
        return json.dumps(self.proto())

    def data_type(self) -> List[Tuple[str, object]]:
        """Ordered (name, InputType-or-None) for every feed slot — the
        DataFeeder contract (reference Topology.data_type)."""
        out = []
        for blk in self.program.blocks:
            for v in blk.vars.values():
                if getattr(v, "is_data", False):
                    out.append((v.name, getattr(v, "input_type", None)))
        return out

    def get_layer_proto(self, name: str) -> Optional[dict]:
        for blk in self.program.blocks:
            if name in blk.vars:
                return blk.vars[name].to_dict()
        return None

    def serialize_for_inference(self,
                                outputs: Sequence[LayerOutput]) -> dict:
        """Pruned forward-only program reaching ``outputs`` (the
        merge-model/inference topology artifact)."""
        names = [o.var.name for o in outputs]
        return self.program.prune(names).to_dict()
