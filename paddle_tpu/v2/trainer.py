"""v2 SGD trainer facade (python/paddle/v2/trainer.py:24-202).

Same event-driven reader loop as the reference's SGD.train, executing the
fluid Program the v2 layers emitted (one compiled XLA step, executable-cached
by the Executor).
"""

from __future__ import annotations

from typing import Callable, Dict, Iterable, List, Optional, Sequence

import numpy as np

from ..core.lod import SeqBatch
from ..data.feeder import DataFeeder
from ..fluid.executor import Executor, Scope
from ..fluid.framework import (default_main_program, default_startup_program)
from ..trainer import event as EV
from .layer import LayerOutput
from .parameters import Parameters


class _V2Feeder:
    """Map reader rows -> executor feed dict per the data layers' types.

    Sequence slots expand to (name, name__len__) feeds (the LoD pair)."""

    def __init__(self, data_layers: Sequence[LayerOutput]):
        self.layers = list(data_layers)
        self.feeder = DataFeeder([dl.input_type.slot for dl in self.layers])

    def __call__(self, rows) -> Dict[str, np.ndarray]:
        cols = self.feeder.feed(rows)
        feed: Dict[str, np.ndarray] = {}
        from ..core.lod import NestedSeqBatch
        for dl, col in zip(self.layers, cols):
            base = dl.var.name
            if isinstance(col, NestedSeqBatch):
                feed[base] = col.data
                feed[base + "__sublen__"] = col.sub_lengths
                feed[base + "__len__"] = col.seq_lengths
            elif isinstance(col, SeqBatch):
                feed[base] = col.data
                feed[base + "__len__"] = col.lengths
            elif isinstance(col, tuple):      # sparse (ids, vals)
                feed[base] = col[0]
                feed[base + "__vals__"] = col[1]
            else:
                feed[base] = col
        return feed


class SGD:
    """trainer.SGD(cost, parameters=None, update_equation=optimizer)."""

    def __init__(self, cost: LayerOutput, update_equation,
                 extra_layers: Optional[List[LayerOutput]] = None):
        self.cost = cost
        self.extra = extra_layers or []
        self.exe = Executor(scope=Scope())
        update_equation.fluid_opt.minimize(cost.var)
        self.exe.run(default_startup_program())
        self.parameters = Parameters(self.exe.scope, default_main_program())

    def train(self, reader: Callable[[], Iterable], *, num_passes: int = 1,
              event_handler: Optional[Callable] = None,
              feeding: Optional[Sequence[LayerOutput]] = None):
        """reader yields row-batches (use paddle_tpu.v2.batch); ``feeding``
        lists the data layers in row order."""
        event_handler = event_handler or (lambda e: None)
        feeder = _V2Feeder(feeding) if feeding else None
        fetches = [self.cost.var] + [e.var for e in self.extra]
        # goodput ledger (None when the obs plane is off): reader pulls +
        # feeding are host_input, exe.run (a synchronous fetch) is device,
        # result reads host_sync; compile seconds steal themselves out via
        # the jax.monitoring bridge — obs/goodput.py owns the bucket math
        from .. import obs
        from ..obs.goodput import maybe_bucket
        gp = obs.goodput.open_ledger("v2_sgd")
        try:
            for pass_id in range(num_passes):
                event_handler(EV.BeginPass(pass_id))
                it = iter(reader())
                batch_id = 0
                while True:
                    with maybe_bucket(gp, "host_input"):
                        try:
                            rows = next(it)
                        except StopIteration:
                            break
                    # BeginIteration between the reader pull and the feed
                    # conversion — exactly where the plain for-loop fired it
                    event_handler(EV.BeginIteration(pass_id, batch_id))
                    with maybe_bucket(gp, "host_input"):
                        feed = feeder(rows) if feeder else rows
                    with maybe_bucket(gp, "device"):
                        outs = self.exe.run(feed=feed, fetch_list=fetches)
                    with maybe_bucket(gp, "host_sync"):
                        metrics = {e.var.name: float(np.asarray(o).mean())
                                   for e, o in zip(self.extra, outs[1:])}
                        cost = float(outs[0])
                    event_handler(EV.EndIteration(pass_id, batch_id,
                                                  cost, None, metrics))
                    self._maybe_param_stats(batch_id)
                    batch_id += 1
                event_handler(EV.EndPass(pass_id))
        finally:
            if gp is not None:
                gp.close()

    def _maybe_param_stats(self, batch_id: int):
        """--show_parameter_stats_period analog (TrainerInternal.cpp:80-87)
        over the fluid scope, gated by the global flag
        (PDTPU_SHOW_PARAMETER_STATS_PERIOD)."""
        from ..utils.flags import FLAGS
        from ..utils.logging import get_logger
        period = FLAGS.show_parameter_stats_period
        if not period or (batch_id + 1) % period:
            return
        log = get_logger("paddle_tpu.v2.trainer")
        from ..fluid.framework import default_main_program
        for p in default_main_program().global_block().all_parameters():
            if not self.exe.scope.has(p.name):
                continue
            a = np.abs(np.asarray(self.exe.scope.get(p.name), np.float32))
            log.info("param %-40s shape=%-16s absmax=%.4e absmean=%.4e",
                     p.name, str(tuple(a.shape)), float(a.max(initial=0.0)),
                     float(a.mean()) if a.size else 0.0)

    def test(self, reader, feeding: Optional[Sequence[LayerOutput]] = None):
        feeder = _V2Feeder(feeding) if feeding else None
        total, n = 0.0, 0
        for rows in reader():
            feed = feeder(rows) if feeder else rows
            c, = self.exe.run(feed=feed, fetch_list=[self.cost.var])
            total += float(c)
            n += 1
        return EV.TestResult(0, total / max(n, 1))
