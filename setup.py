"""Build shim: `pip install .` also builds the native host runtime when a
toolchain is present (the CMake WITH_* option surface of the reference's
build, reduced to one make invocation; paddle_tpu.runtime.lib falls back to
pure-Python stand-ins when the .so is absent)."""

import os
import subprocess

from setuptools import setup
from setuptools.command.build_py import build_py


class BuildWithNative(build_py):
    def run(self):
        here = os.path.dirname(os.path.abspath(__file__))
        native = os.path.join(here, "native")
        if os.path.isdir(native):
            try:
                subprocess.run(["make", "-C", native], check=True)
                # ship the libraries INSIDE the package so wheel installs
                # find them (runtime/lib.py checks paddle_tpu/_native/ after
                # the repo-relative path)
                import glob
                import shutil
                dest = os.path.join(here, "paddle_tpu", "_native")
                os.makedirs(dest, exist_ok=True)
                for so in glob.glob(os.path.join(native, "*.so")):
                    shutil.copy2(so, dest)
            except (OSError, subprocess.CalledProcessError) as e:
                print(f"[paddle_tpu] native build skipped ({e}); "
                      f"runtime falls back to gated pure-Python paths")
        super().run()


setup(cmdclass={"build_py": BuildWithNative})
