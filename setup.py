"""Build shim: `pip install .` also builds the native host runtime when a
toolchain is present (the CMake WITH_* option surface of the reference's
build, reduced to one make invocation; paddle_tpu.runtime.lib falls back to
pure-Python stand-ins when the .so is absent)."""

import os
import subprocess

from setuptools import setup
from setuptools.command.build_py import build_py


class BuildWithNative(build_py):
    def run(self):
        here = os.path.dirname(os.path.abspath(__file__))
        native = os.path.join(here, "native")
        if os.path.isdir(native):
            try:
                subprocess.run(["make", "-C", native], check=True)
            except (OSError, subprocess.CalledProcessError) as e:
                print(f"[paddle_tpu] native build skipped ({e}); "
                      f"runtime falls back to gated pure-Python paths")
        super().run()


setup(cmdclass={"build_py": BuildWithNative})
