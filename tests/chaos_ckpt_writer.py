"""Subprocess victim for the kill-9-mid-checkpoint chaos test.

Writes a complete pass-0 checkpoint, then starts the pass-1 checkpoint with
a fault rule whose exception *factory* touches a sentinel file and stalls —
the parent waits for the sentinel and delivers SIGKILL while the pass-1
``.tmp`` directory holds partially written members and no ``_COMPLETE``
manifest: a real torn-write crash window, not a simulation of one.

Usage: python tests/chaos_ckpt_writer.py OUTPUT_DIR SENTINEL_PATH
"""

import os
import sys
import time

import numpy as np

# ``python tests/chaos_ckpt_writer.py`` puts tests/ on sys.path, not the
# repo root — add it so ``paddle_tpu`` imports without an installed package.
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from paddle_tpu import faults
from paddle_tpu.trainer.checkpoint import save_checkpoint

PARAMS = {"w": np.arange(64, dtype=np.float32).reshape(8, 8),
          "b": np.ones(8, dtype=np.float32)}


def main():
    out, sentinel = sys.argv[1], sys.argv[2]
    save_checkpoint(out, 0, PARAMS)

    def stall_then_die():
        # signal the parent we are inside the pass-1 write, then hang until
        # it SIGKILLs us (the timeout is only a safety net)
        with open(sentinel, "w"):
            pass
        time.sleep(60)
        return RuntimeError("parent never killed us")

    plan = faults.FaultPlan()
    # nth=2: params.tar is fully written, state.json + _COMPLETE are not —
    # the nastiest torn state (a plausible-looking tar with no manifest)
    plan.add("ckpt.write", "raise", nth=2, exc=stall_then_die)
    with plan.installed():
        save_checkpoint(out, 1, PARAMS)


if __name__ == "__main__":
    main()
