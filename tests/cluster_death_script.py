"""Worker script for the host-death test: 2 workers join one DP job and run
real collective steps; mid-run, rank 1 SIGKILLs itself (simulated machine
loss). Rank 0 then idles in the input-wait part of its loop; the launcher
must detect the death, SIGTERM rank 0, whose multihost teardown handler
writes the `clean-exit-<rank>` marker (standing in for a final checkpoint)
before exiting with TEARDOWN_EXIT_CODE."""

import os
import signal
import sys
import time

import jax
jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

from paddle_tpu.parallel import multihost


def main():
    info = multihost.initialize()
    rank = info["process_index"]
    out_dir = os.environ["DEATH_TEST_DIR"]

    def write_marker():
        with open(os.path.join(out_dir, f"clean-exit-{rank}"), "w") as f:
            f.write("checkpointed\n")

    multihost.on_job_teardown(write_marker)

    mesh = multihost.global_mesh(data=info["global_devices"])
    # a few REAL coupled steps while both workers are alive
    from jax.sharding import NamedSharding, PartitionSpec as P

    @jax.jit
    def global_sum(x):
        return x.sum()

    for step in range(3):
        local = np.full((info["local_devices"], 4), rank + 1, np.float32)
        gx = multihost.make_global_array(
            local, mesh) if info["process_count"] > 1 else jax.device_put(
                local, NamedSharding(mesh, P("data")))
        assert float(global_sum(gx)) > 0

    if rank == 1:
        os.kill(os.getpid(), signal.SIGKILL)   # the machine "loses power"

    # survivor: waiting for the next input chunk (the master-service data
    # plane); the launcher's SIGTERM must interrupt this cleanly
    for _ in range(600):
        time.sleep(0.1)
    print("survivor was never torn down", flush=True)
    sys.exit(5)


if __name__ == "__main__":
    main()
