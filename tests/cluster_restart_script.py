"""Worker script for the elastic-restart test: 2 workers train a
deterministic DP model for 3 passes, checkpointing params after each pass
(the trainer's pass-%05d discipline, boiled down). On attempt 0, rank 1
SIGKILLs itself after the pass-1 checkpoint lands (machine loss mid-job);
the launcher's --restart-on-failure relaunches both workers, which resume
from the latest checkpoint and finish. Rank 0 writes final.npz, which the
test compares against an uninterrupted run — the elastic restart must be
math-invisible."""

import os
import signal
import sys
import time

import jax
jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax.numpy as jnp
import numpy as np

from paddle_tpu import nn, parallel as pp
from paddle_tpu.optimizer import SGD
from paddle_tpu.parallel import multihost

PASSES = 3
STEPS_PER_PASS = 2


def build():
    class Net(nn.Module):
        def __init__(self):
            super().__init__()
            self.fc = nn.Linear(4, 2)

        def __call__(self, params, x, **kw):
            return self.fc(params["fc"], x)

    model = Net()

    def loss(params, x, y):
        logp = jax.nn.log_softmax(model(params, x))
        return -jnp.take_along_axis(logp, y[:, None], 1).mean()

    return model, loss


def pass_batches(pass_idx):
    """Deterministic per-pass data: same on every attempt."""
    rs = np.random.RandomState(100 + pass_idx)
    GB = 16
    for _ in range(STEPS_PER_PASS):
        yield (rs.randn(GB, 4).astype(np.float32),
               rs.randint(0, 2, GB).astype(np.int32))


def latest_checkpoint(ckpt_dir):
    done = sorted(f for f in os.listdir(ckpt_dir)
                  if f.startswith("pass-") and f.endswith(".npz"))
    return os.path.join(ckpt_dir, done[-1]) if done else None


def main():
    info = multihost.initialize()
    rank = info["process_index"]
    attempt = int(os.environ.get("PADDLE_TPU_RESTART_COUNT", "0"))
    ckpt_dir = os.environ["RESTART_TEST_DIR"]
    mesh = multihost.global_mesh(data=info["global_devices"])

    model, loss = build()
    host_params = jax.device_get(model.init(jax.random.PRNGKey(0)))
    start_pass = 0
    ck = latest_checkpoint(ckpt_dir)
    if ck is not None:
        data = np.load(ck)
        host_params = {"fc": {"w": data["w"], "b": data["b"]}}
        start_pass = int(data["pass_idx"]) + 1

    params = multihost.replicate_from_host(mesh, host_params)
    dp = pp.DataParallel(loss, SGD(0.1), mesh=mesh)
    opt_state = multihost.replicate_from_host(
        mesh, jax.device_get(dp.opt.init(host_params)))

    for pass_idx in range(start_pass, PASSES):
        for X, Y in pass_batches(pass_idx):
            sl = multihost.process_batch_slice(len(X))
            bx, by = multihost.make_global_batch(mesh, (X[sl], Y[sl]))
            params, opt_state, l = dp.step(params, opt_state, bx, by)
        if rank == 0:
            hp = jax.device_get(params)
            tmp = os.path.join(ckpt_dir, f".pass-{pass_idx:05d}.tmp.npz")
            np.savez(tmp, w=hp["fc"]["w"], b=hp["fc"]["b"],
                     pass_idx=pass_idx)
            os.replace(tmp, os.path.join(ckpt_dir,
                                         f"pass-{pass_idx:05d}.npz"))
        if attempt == 0 and pass_idx == 1 and rank == 1:
            # wait until rank 0's pass-1 checkpoint is durable, then die
            target = os.path.join(ckpt_dir, "pass-00001.npz")
            deadline = time.time() + 60
            while not os.path.exists(target) and time.time() < deadline:
                time.sleep(0.05)
            os.kill(os.getpid(), signal.SIGKILL)

    if rank == 0:
        hp = jax.device_get(params)
        np.savez(os.path.join(ckpt_dir, "final.npz"),
                 w=hp["fc"]["w"], b=hp["fc"]["b"])
    print(f"worker {rank} attempt {attempt} done", flush=True)


if __name__ == "__main__":
    main()
