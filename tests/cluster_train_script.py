"""Worker script for the cluster_train launcher test: joins the job via
multihost.initialize() (PADDLE_TPU_* env), trains a toy DP model over the
global mesh, and asserts the job really is multi-process."""

import os
import sys

import jax
jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax.numpy as jnp
import numpy as np

from paddle_tpu import nn, parallel as pp
from paddle_tpu.optimizer import SGD
from paddle_tpu.parallel import multihost


def main():
    info = multihost.initialize()
    assert info["process_count"] == int(os.environ["PADDLE_TPU_NUM_PROCESSES"])
    mesh = multihost.global_mesh(data=info["global_devices"])

    class Net(nn.Module):
        def __init__(self):
            super().__init__()
            self.fc = nn.Linear(4, 2)

        def __call__(self, params, x, **kw):
            return self.fc(params["fc"], x)

    model = Net()

    def loss(params, x, y):
        logp = jax.nn.log_softmax(model(params, x))
        return -jnp.take_along_axis(logp, y[:, None], 1).mean()

    rs = np.random.RandomState(0)
    GB = 16
    X = rs.randn(GB, 4).astype(np.float32)
    Y = rs.randint(0, 2, GB).astype(np.int32)
    sl = multihost.process_batch_slice(GB)

    params = multihost.replicate_from_host(
        mesh, jax.device_get(model.init(jax.random.PRNGKey(0))))
    dp = pp.DataParallel(loss, SGD(0.1), mesh=mesh)
    opt_state = multihost.replicate_from_host(
        mesh, jax.device_get(dp.opt.init(jax.device_get(params))))
    bx, by = multihost.make_global_batch(mesh, (X[sl], Y[sl]))
    l0 = None
    for i in range(5):
        params, opt_state, l = dp.step(params, opt_state, bx, by)
        if i == 0:
            l0 = float(l)
    assert float(l) < l0
    print(f"worker {info['process_index']} OK", flush=True)


if __name__ == "__main__":
    main()
