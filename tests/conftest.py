"""Test bootstrap: force an 8-device virtual CPU mesh before jax import.

Mirrors the reference's strategy of testing distributed logic in-process
(SURVEY.md §4.3: pserver tests on localhost, MultiGradientMachine with threads):
sharding/collective tests run on 8 virtual CPU devices so no TPU pod is needed.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
prev = os.environ.get("XLA_FLAGS", "")
if "host_platform_device_count" not in prev:
    os.environ["XLA_FLAGS"] = (prev + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

# The image's sitecustomize registers the axon TPU plugin and forces
# jax_platforms="axon,cpu"; override it so tests run on the virtual CPU mesh.
jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import pytest  # noqa: E402


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "chaos: deterministic fault-injection tests "
        "(tests/test_faults.py); tier-1, no real sleeps, <60s total")
    config.addinivalue_line(
        "markers", "slow: excluded from the tier-1 `-m 'not slow'` run")
    config.addinivalue_line(
        "markers", "obs: observability-plane tests (tests/test_obs.py); "
        "tier-1, fake clocks, no real sleeps")


@pytest.fixture
def rng():
    return jax.random.PRNGKey(0)


@pytest.fixture
def np_rng():
    return np.random.RandomState(0)
