"""Test bootstrap: force an 8-device virtual CPU mesh before jax import.

Mirrors the reference's strategy of testing distributed logic in-process
(SURVEY.md §4.3: pserver tests on localhost, MultiGradientMachine with threads):
sharding/collective tests run on 8 virtual CPU devices so no TPU pod is needed.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
prev = os.environ.get("XLA_FLAGS", "")
if "host_platform_device_count" not in prev:
    os.environ["XLA_FLAGS"] = (prev + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

# The image's sitecustomize registers the axon TPU plugin and forces
# jax_platforms="axon,cpu"; override it so tests run on the virtual CPU mesh.
jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture(scope="session", autouse=True)
def _session_compile_cache(tmp_path_factory):
    """Session-scoped persistent XLA compile cache (ROADMAP item 5).

    Many tests trace structurally-identical small programs into FRESH jit
    closures (every Executor/Trainer instantiation mints new callables),
    so jax's in-memory cache never hits across tests — the persistent
    cache keys on the serialized computation and does. Honors an external
    $PADDLE_TPU_COMPILE_CACHE_DIR (e.g. a CI cache mount); otherwise a
    session tmp dir so repeated shape families compile once per run. The
    env var is exported so subprocess-spawning tests inherit the cache.
    """
    import paddle_tpu
    path = os.environ.get(paddle_tpu.COMPILE_CACHE_ENV)
    if not path:
        path = str(tmp_path_factory.mktemp("xla_compile_cache"))
        os.environ[paddle_tpu.COMPILE_CACHE_ENV] = path
    paddle_tpu.enable_compile_cache(path)
    yield


@pytest.fixture(scope="session", autouse=True)
def _hermetic_autotune(tmp_path_factory):
    """Point the autotune consult at a session-local (absent) cache file:
    a developer's real ~/.paddle_tpu/autotune.json must never steer test
    plans (tuned plans are parity-safe by construction, but the suite's
    route/plan assertions pin exact heuristic decisions). Tests that
    exercise the consult install their own caches via
    paddle_tpu.tune.set_cache / $PADDLE_TPU_AUTOTUNE_CACHE; the env var
    is exported so subprocess tests inherit the hermetic path."""
    from paddle_tpu import tune
    if not os.environ.get(tune.CACHE_ENV):
        os.environ[tune.CACHE_ENV] = str(
            tmp_path_factory.mktemp("autotune") / "autotune.json")
        tune.reset()
    yield


@pytest.fixture(scope="session")
def paged_model_and_params():
    """ONE TransformerLM (the shared serving dims: VOCAB=97, D=32, H=4,
    L=2, MAX_LEN=128) for the paged/prefix serving suites — ROADMAP
    item 5's shared-executable fixture. PagePool shares its jitted
    admission/segment programs PER MODEL INSTANCE
    (serving/paged.py _SHARED_FNS), so a session-scoped model means each
    shape family traces once for the whole suite instead of once per
    test, and the model's own generate/prefill jit caches carry the solo
    references across files too."""
    from paddle_tpu.models import TransformerLM
    model = TransformerLM(97, d_model=32, n_heads=4, n_layers=2,
                          max_len=128)
    params = model.init(jax.random.PRNGKey(0))
    return model, params


_MP_CPU_PROBE = None

_MP_PROBE_SRC = r"""
import os, sys
port, pid = sys.argv[1], int(sys.argv[2])
import jax
jax.config.update("jax_platforms", "cpu")
jax.distributed.initialize(f"localhost:{port}", num_processes=2,
                           process_id=pid)
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
mesh = Mesh(np.array(jax.devices()), ("d",))
x = jax.make_array_from_process_local_data(
    NamedSharding(mesh, P("d")), np.ones((1,), np.float32))
assert float(jax.jit(lambda a: a.sum())(x)) == 2.0
print("MP_OK")
"""


def multiprocess_cpu_support():
    """(supported, reason): can this jaxlib run a COMPILED computation
    across two CPU processes? ``jax.distributed.initialize`` succeeding is
    NOT enough — some jaxlib builds join the job fine and then fail every
    cross-process computation with 'Multiprocess computations aren't
    implemented on the CPU backend'. The probe runs the real thing (a
    2-process 1-float reduction over a global mesh) once per session, so
    the multiprocess-on-CPU tests skip with the actual backend error as
    the reason instead of failing red on a capability the environment
    never had."""
    global _MP_CPU_PROBE
    if _MP_CPU_PROBE is not None:
        return _MP_CPU_PROBE
    import socket
    import subprocess
    import sys
    s = socket.socket()
    s.bind(("localhost", 0))
    port = s.getsockname()[1]
    s.close()
    env = {k: v for k, v in os.environ.items() if k != "XLA_FLAGS"}
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
    procs = [subprocess.Popen(
        [sys.executable, "-c", _MP_PROBE_SRC, str(port), str(i)],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
        for i in range(2)]
    outs, ok = [], True
    try:
        for p in procs:
            out, _ = p.communicate(timeout=90)
            outs.append(out.decode(errors="replace"))
            ok = ok and p.returncode == 0
    except subprocess.TimeoutExpired:
        ok = False
        outs.append("probe timed out after 90s")
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    if ok:
        _MP_CPU_PROBE = (True, "")
    else:
        tail = [ln for o in outs for ln in o.strip().splitlines()
                if ln.strip()]
        reason = tail[-1] if tail else "probe subprocess failed"
        _MP_CPU_PROBE = (False, reason[:300])
    return _MP_CPU_PROBE


def require_multiprocess_cpu():
    """Capability gate for tests that need REAL cross-process collectives
    on the CPU backend (tests/test_multiprocess_dp.py + the launcher's
    training e2es). A skip here always names the backend's own error, so
    a red tier-1 run means a genuine regression, never a missing
    environment capability."""
    ok, reason = multiprocess_cpu_support()
    if not ok:
        pytest.skip("multiprocess-on-CPU collectives unavailable in this "
                    f"environment: {reason}")


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "chaos: deterministic fault-injection tests "
        "(tests/test_faults.py); tier-1, no real sleeps, <60s total")
    config.addinivalue_line(
        "markers", "slow: excluded from the tier-1 `-m 'not slow'` run")
    config.addinivalue_line(
        "markers", "obs: observability-plane tests (tests/test_obs.py); "
        "tier-1, fake clocks, no real sleeps")
    config.addinivalue_line(
        "markers", "perf: wall-clock budget tests (generous bounds; "
        "override via PADDLE_TPU_VERIFY_BUDGET_S)")


@pytest.fixture
def rng():
    return jax.random.PRNGKey(0)


@pytest.fixture
def np_rng():
    return np.random.RandomState(0)
