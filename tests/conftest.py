"""Test bootstrap: force an 8-device virtual CPU mesh before jax import.

Mirrors the reference's strategy of testing distributed logic in-process
(SURVEY.md §4.3: pserver tests on localhost, MultiGradientMachine with threads):
sharding/collective tests run on 8 virtual CPU devices so no TPU pod is needed.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
prev = os.environ.get("XLA_FLAGS", "")
if "host_platform_device_count" not in prev:
    os.environ["XLA_FLAGS"] = (prev + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

# The image's sitecustomize registers the axon TPU plugin and forces
# jax_platforms="axon,cpu"; override it so tests run on the virtual CPU mesh.
jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture(scope="session", autouse=True)
def _session_compile_cache(tmp_path_factory):
    """Session-scoped persistent XLA compile cache (ROADMAP item 5).

    Many tests trace structurally-identical small programs into FRESH jit
    closures (every Executor/Trainer instantiation mints new callables),
    so jax's in-memory cache never hits across tests — the persistent
    cache keys on the serialized computation and does. Honors an external
    $PADDLE_TPU_COMPILE_CACHE_DIR (e.g. a CI cache mount); otherwise a
    session tmp dir so repeated shape families compile once per run. The
    env var is exported so subprocess-spawning tests inherit the cache.
    """
    import paddle_tpu
    path = os.environ.get(paddle_tpu.COMPILE_CACHE_ENV)
    if not path:
        path = str(tmp_path_factory.mktemp("xla_compile_cache"))
        os.environ[paddle_tpu.COMPILE_CACHE_ENV] = path
    paddle_tpu.enable_compile_cache(path)
    yield


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "chaos: deterministic fault-injection tests "
        "(tests/test_faults.py); tier-1, no real sleeps, <60s total")
    config.addinivalue_line(
        "markers", "slow: excluded from the tier-1 `-m 'not slow'` run")
    config.addinivalue_line(
        "markers", "obs: observability-plane tests (tests/test_obs.py); "
        "tier-1, fake clocks, no real sleeps")


@pytest.fixture
def rng():
    return jax.random.PRNGKey(0)


@pytest.fixture
def np_rng():
    return np.random.RandomState(0)
