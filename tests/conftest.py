"""Test bootstrap: force an 8-device virtual CPU mesh before jax import.

Mirrors the reference's strategy of testing distributed logic in-process
(SURVEY.md §4.3: pserver tests on localhost, MultiGradientMachine with threads):
sharding/collective tests run on 8 virtual CPU devices so no TPU pod is needed.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
prev = os.environ.get("XLA_FLAGS", "")
if "host_platform_device_count" not in prev:
    os.environ["XLA_FLAGS"] = (prev + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

# The image's sitecustomize registers the axon TPU plugin and forces
# jax_platforms="axon,cpu"; override it so tests run on the virtual CPU mesh.
jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture(scope="session", autouse=True)
def _session_compile_cache(tmp_path_factory):
    """Session-scoped persistent XLA compile cache (ROADMAP item 5).

    Many tests trace structurally-identical small programs into FRESH jit
    closures (every Executor/Trainer instantiation mints new callables),
    so jax's in-memory cache never hits across tests — the persistent
    cache keys on the serialized computation and does. Honors an external
    $PADDLE_TPU_COMPILE_CACHE_DIR (e.g. a CI cache mount); otherwise a
    session tmp dir so repeated shape families compile once per run. The
    env var is exported so subprocess-spawning tests inherit the cache.
    """
    import paddle_tpu
    path = os.environ.get(paddle_tpu.COMPILE_CACHE_ENV)
    if not path:
        path = str(tmp_path_factory.mktemp("xla_compile_cache"))
        os.environ[paddle_tpu.COMPILE_CACHE_ENV] = path
    paddle_tpu.enable_compile_cache(path)
    yield


@pytest.fixture(scope="session", autouse=True)
def _hermetic_autotune(tmp_path_factory):
    """Point the autotune consult at a session-local (absent) cache file:
    a developer's real ~/.paddle_tpu/autotune.json must never steer test
    plans (tuned plans are parity-safe by construction, but the suite's
    route/plan assertions pin exact heuristic decisions). Tests that
    exercise the consult install their own caches via
    paddle_tpu.tune.set_cache / $PADDLE_TPU_AUTOTUNE_CACHE; the env var
    is exported so subprocess tests inherit the hermetic path."""
    from paddle_tpu import tune
    if not os.environ.get(tune.CACHE_ENV):
        os.environ[tune.CACHE_ENV] = str(
            tmp_path_factory.mktemp("autotune") / "autotune.json")
        tune.reset()
    yield


@pytest.fixture(scope="session")
def paged_model_and_params():
    """ONE TransformerLM (the shared serving dims: VOCAB=97, D=32, H=4,
    L=2, MAX_LEN=128) for the paged/prefix serving suites — ROADMAP
    item 5's shared-executable fixture. PagePool shares its jitted
    admission/segment programs PER MODEL INSTANCE
    (serving/paged.py _SHARED_FNS), so a session-scoped model means each
    shape family traces once for the whole suite instead of once per
    test, and the model's own generate/prefill jit caches carry the solo
    references across files too."""
    from paddle_tpu.models import TransformerLM
    model = TransformerLM(97, d_model=32, n_heads=4, n_layers=2,
                          max_len=128)
    params = model.init(jax.random.PRNGKey(0))
    return model, params


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "chaos: deterministic fault-injection tests "
        "(tests/test_faults.py); tier-1, no real sleeps, <60s total")
    config.addinivalue_line(
        "markers", "slow: excluded from the tier-1 `-m 'not slow'` run")
    config.addinivalue_line(
        "markers", "obs: observability-plane tests (tests/test_obs.py); "
        "tier-1, fake clocks, no real sleeps")


@pytest.fixture
def rng():
    return jax.random.PRNGKey(0)


@pytest.fixture
def np_rng():
    return np.random.RandomState(0)
