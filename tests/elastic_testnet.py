"""Shared tiny workload for the elastic-cluster tests.

One definition of (net, loss, optimizer, data) imported by BOTH the test
process (master + thread workers + references) and the subprocess worker
script (tests/elastic_worker_script.py), so every participant of a chaos
run computes identical per-shard math — the byte-stability assertions
compare apples to apples.
"""

import numpy as np


def build(steps: int = 5, batch: int = 32, seed: int = 0):
    """-> (loss_fn, params0_fn, make_optimizer, batches)."""
    import jax
    import jax.numpy as jnp

    from paddle_tpu import nn
    from paddle_tpu.optimizer import Adam

    class Net(nn.Module):
        def __init__(self):
            super().__init__()
            self.fc1 = nn.Linear(8, 16, act="relu")
            self.fc2 = nn.Linear(16, 2)

        def __call__(self, params, x, **kw):
            return self.fc2(params["fc2"], self.fc1(params["fc1"], x))

    model = Net()

    def loss_fn(params, x, y):
        logits = model(params, x)
        logp = jax.nn.log_softmax(logits)
        return -jnp.take_along_axis(logp, y[:, None], 1).mean()

    rs = np.random.RandomState(seed)
    batches = [(rs.randn(batch, 8).astype(np.float32),
                rs.randint(0, 2, batch).astype(np.int32))
               for _ in range(steps)]

    def params0():
        return model.init(jax.random.PRNGKey(7))

    # Adam deliberately: its moment slots ride the master's canonical
    # state, so restarts/resharding cover "Adam slots included"
    return loss_fn, params0, (lambda: Adam(0.01)), batches
