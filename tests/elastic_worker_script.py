"""Subprocess elastic worker for the kill -9 chaos tests.

Usage: python elastic_worker_script.py HOST PORT WORKER_ID [MAX_SECONDS]

Joins the elastic master at HOST:PORT under a heartbeat lease, serves
shard-gradient tasks until the master reports the job done, then leaves
gracefully and exits 0. A worker the test SIGKILLs mid-pass obviously
never reaches the leave — that is the point: its eviction + task
re-bucketing is what the test asserts.
"""

import os
import sys


def main():
    host, port, worker_id = sys.argv[1], int(sys.argv[2]), sys.argv[3]
    max_seconds = float(sys.argv[4]) if len(sys.argv) > 4 else 120.0
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import jax
    jax.config.update("jax_platforms", "cpu")
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    sys.path.insert(0, os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    from elastic_testnet import build
    from paddle_tpu.trainer.elastic import ElasticWorker

    loss_fn, _, _, _ = build()
    worker = ElasticWorker(loss_fn, (host, port), worker=worker_id)
    summary = worker.run(max_seconds=max_seconds)
    print("WORKER_DONE", summary["worker"], summary["shards"], flush=True)
    sys.exit(0 if summary["done"] else 2)


if __name__ == "__main__":
    main()
