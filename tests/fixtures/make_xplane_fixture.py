"""Regenerate tests/fixtures/tiny.xplane.pb — a hand-built XSpace whose
wire bytes exercise the whole off-TPU xplane pipeline (parse -> device
planes -> site attribution -> chrome merge) without a TPU or xprof.

The shape mimics a real TPU trace: one device plane with an "XLA Ops"
line whose op names carry the fluid Executor's named-scope stamps
(executor._scope_tag: b{B}_op{I}_{type}) the way XLA embeds scopes in
fused op names, plus a nested module event (self-time computation), and
a host plane that must NOT count as a device lane.

Run from the repo root:  python tests/fixtures/make_xplane_fixture.py
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))))

from paddle_tpu.obs.xplane import encode_xspace  # noqa: E402

#: epoch anchor (2023-01-01 00:00:00 UTC) in ns — fixed so the fixture
#: bytes are reproducible
T0 = 1672531200 * 10**9

PLANES = [
    {"name": "/device:TPU:0",
     "lines": [
         {"name": "XLA Modules", "timestamp_ns": T0,
          "events": [
              # the module span CONTAINS every op below: its self time
              # must come out as the uncovered 100us tail
              {"name": "jit_train_step", "offset_ps": 0,
               "duration_ps": 1_000_000_000},          # 1 ms
          ]},
         {"name": "XLA Ops", "timestamp_ns": T0,
          "events": [
              # scope-stamped ops (two sites, one op fused twice)
              {"name": "fusion.7/b0_op3_mul.1", "offset_ps": 0,
               "duration_ps": 400_000_000},            # 400 us
              {"name": "fusion.7/b0_op3_mul.1", "offset_ps": 400_000_000,
               "duration_ps": 200_000_000},            # 200 us
              {"name": "custom-call.2/b1_op0_lstm_fused",
               "offset_ps": 600_000_000,
               "duration_ps": 250_000_000},            # 250 us
              # an unstamped op: site must resolve to None
              {"name": "copy.3", "offset_ps": 850_000_000,
               "duration_ps": 50_000_000},             # 50 us
          ]},
     ]},
    {"name": "/host:CPU",
     "lines": [
         {"name": "python", "timestamp_ns": T0,
          "events": [
              {"name": "PjitFunction(train_step)", "offset_ps": 0,
               "duration_ps": 1_200_000_000},
          ]},
     ]},
]

if __name__ == "__main__":
    out = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                       "tiny.xplane.pb")
    with open(out, "wb") as f:
        f.write(encode_xspace(PLANES))
    print(f"wrote {out} ({os.path.getsize(out)} bytes)")
