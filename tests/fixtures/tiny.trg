a house
the car is red
a car
