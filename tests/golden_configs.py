"""Representative model configs for the golden-program tests — the analog of
trainer_config_helpers/tests/configs/* whose emitted protos are diffed
against protostr/ goldens (SURVEY.md §4.4)."""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import paddle_tpu.fluid as fluid
import paddle_tpu.v2 as paddle

L = paddle.layer
DT = paddle.data_type


def _reset():
    fluid.reset_default_programs()
    from paddle_tpu.fluid import layers as FL
    FL._seed_counter[0] = 0        # deterministic init seeds for goldens


def mlp_classifier():
    """fit_a_line / recognize_digits style MLP."""
    _reset()
    x = L.data("x", DT.dense_vector(64))
    y = L.data("y", DT.integer_value(10))
    h = L.fc(x, 32, act="tanh")
    logits = L.fc(h, 10)
    L.classification_cost(logits, y)
    return fluid.default_main_program()


def lstm_text_model():
    """quick_start LSTM text classification."""
    _reset()
    words = L.data("words", DT.integer_value_sequence(100))
    label = L.data("label", DT.integer_value(2))
    emb = L.embedding(words, 16)
    lstm = L.lstmemory(emb, 16)
    pooled = L.pooling(lstm, "max")
    L.classification_cost(L.fc(pooled, 2), label)
    return fluid.default_main_program()


def mixed_projection_model():
    """Mixed-layer projection algebra (the gen-1 signature surface)."""
    _reset()
    x = L.data("x", DT.dense_vector(8))
    ids = L.data("ids", DT.integer_value(20))
    out = L.mixed_layer(size=8, input=[
        L.full_matrix_projection(x, 8),
        L.identity_projection(x),
        L.table_projection(ids, 8),
    ], act="relu", bias_attr=True)
    L.mse_cost(out, L.data("t", DT.dense_vector(8)))
    return fluid.default_main_program()


CONFIGS = {
    "mlp_classifier": mlp_classifier,
    "lstm_text_model": lstm_text_model,
    "mixed_projection_model": mixed_projection_model,
}
