"""Worker process for the multi-process data-parallel equivalence test.

Launched by tests/test_multiprocess_dp.py as N separate OS processes, each
owning 2 virtual CPU devices — the real multi-host code path
(jax.distributed + Gloo collectives across processes), the in-process
analog of the reference's pserver tests that spin real trainers against real
localhost servers (gserver/tests/test_CompareSparse.cpp:64-73).

Usage: python mp_dp_worker.py <process_id> <num_processes> <port> <out.npz>
"""

import os
import sys


def main():
    pid, nproc, port, out = (int(sys.argv[1]), int(sys.argv[2]),
                             int(sys.argv[3]), sys.argv[4])
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    import jax
    jax.config.update("jax_platforms", "cpu")
    jax.distributed.initialize(f"localhost:{port}", num_processes=nproc,
                               process_id=pid)
    import jax.numpy as jnp
    import numpy as np

    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    from paddle_tpu import nn, parallel as pp
    from paddle_tpu.optimizer import SGD
    from paddle_tpu.parallel import multihost

    n_dev = len(jax.devices())              # nproc * 2
    mesh = multihost.global_mesh(data=n_dev)

    class Net(nn.Module):
        def __init__(self):
            super().__init__()
            self.fc1 = nn.Linear(8, 16, act="relu")
            self.fc2 = nn.Linear(16, 2)

        def __call__(self, params, x, **kw):
            return self.fc2(params["fc2"], self.fc1(params["fc1"], x))

    model = Net()

    def loss(params, x, y):
        logits = model(params, x)
        logp = jax.nn.log_softmax(logits)
        return -jnp.take_along_axis(logp, y[:, None], 1).mean()

    # deterministic global data; every process slices out its own rows
    rs = np.random.RandomState(0)
    GB = 32
    X = rs.randn(GB, 8).astype(np.float32)
    Y = rs.randint(0, 2, GB).astype(np.int32)
    sl = multihost.process_batch_slice(GB)

    params0 = model.init(jax.random.PRNGKey(7))
    params = multihost.replicate_from_host(mesh, jax.device_get(params0))
    dp = pp.DataParallel(loss, SGD(0.1), mesh=mesh)
    opt_state = multihost.replicate_from_host(
        mesh, jax.device_get(dp.opt.init(params0)))

    bx, by = multihost.make_global_batch(mesh, (X[sl], Y[sl]))
    for _ in range(5):
        params, opt_state, l = dp.step(params, opt_state, bx, by)

    if pid == 0:
        flat = {k: np.asarray(v)
                for k, v in nn.Module.named_parameters(jax.device_get(params))}
        np.savez(out, **flat)
    jax.effects_barrier()


if __name__ == "__main__":
    main()
