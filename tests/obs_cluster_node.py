"""Subprocess node for the distributed-tracing chaos e2e
(tests/test_obs_distributed.py) — one script, two roles:

* ``master OUT DONE_FILE`` — serve a real MasterServer (native dispatch +
  Python fallback for obs ops) over a tiny chunked dataset, print
  ``ADDR <host> <port>``, then wait for DONE_FILE and save a clean obs
  dump to OUT.
* ``worker OUT HOST PORT`` — train from the master via cloud_reader with
  an armed flight recorder and a fault plan that RAISES mid-pass: the
  process dies with the pass unfinished and the flight dump at OUT is all
  that survives — exactly the artifact the test stitches with the
  master's dump.

Both roles share one trace id via PADDLE_TPU_TRACE_ID (set by the test).
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402


def run_master(out, done_file):
    os.environ.setdefault("PADDLE_TPU_OBS_PROCESS", "master")
    from paddle_tpu import obs
    from paddle_tpu.data.chunks import dump_to_chunks
    from paddle_tpu.runtime.master_service import MasterServer

    session = obs.ObsSession(registry=obs.MetricsRegistry()).install()
    rec = obs.FlightRecorder(session, out).arm()

    rs = np.random.RandomState(0)

    def samples():
        for _ in range(24):
            yield (rs.randn(4).astype(np.float32),
                   rs.randn(1).astype(np.float32))

    chunk_dir = os.path.join(os.path.dirname(out), "chunks")
    paths = dump_to_chunks(samples, chunk_dir, samples_per_chunk=4)
    srv = MasterServer().start()
    srv._dispatch({"op": "set_dataset", "payloads": paths})
    print(f"ADDR {srv.address[0]} {srv.address[1]}", flush=True)
    deadline = time.time() + 120
    while not os.path.exists(done_file) and time.time() < deadline:
        time.sleep(0.1)
    srv.stop()
    rec.disarm()
    session.uninstall()
    session.save(out)


def run_worker(out, host, port):
    os.environ.setdefault("PADDLE_TPU_OBS_PROCESS", "worker-0")
    import jax.numpy as jnp

    from paddle_tpu import faults, obs
    from paddle_tpu.data.chunks import cloud_reader
    from paddle_tpu.optimizer import SGD
    from paddle_tpu.runtime.master_service import MasterClient
    from paddle_tpu.trainer import Trainer

    session = obs.ObsSession(registry=obs.MetricsRegistry()).install()
    obs.FlightRecorder(session, out).arm()

    client = MasterClient(host, int(port))
    # one explicit snapshot push before training: guarantees a client
    # rpc.call span whose server-side master.dispatch peer lands in the
    # master's dump even though the crash below cuts the run short
    client.obs_push("worker-0", session.registry.collect())

    raw = cloud_reader(client)

    def batches():
        buf = []
        for s in raw():
            buf.append(s)
            if len(buf) == 4:
                yield (np.stack([b[0] for b in buf]),
                       np.stack([b[1] for b in buf]))
                buf = []

    def loss(params, x, y):
        return jnp.mean((x @ params["w"] - y) ** 2)

    # the chaos: the 3rd batch's loss hook raises -> uncaught -> the
    # process dies mid-pass; the flight recorder's excepthook (and the
    # faults-plane pre-raise dump) leave OUT behind
    plan = faults.FaultPlan().add("step.grad", "raise", nth=3)
    plan.install()
    t = Trainer(loss, SGD(0.1))
    t.train(batches, {"w": np.zeros((4, 1), np.float32)}, num_passes=1,
            handle_signals=False)
    raise SystemExit("unreachable: the injected fault should have killed us")


def main():
    role = sys.argv[1]
    if role == "master":
        run_master(sys.argv[2], sys.argv[3])
    elif role == "worker":
        run_worker(sys.argv[2], sys.argv[3], sys.argv[4])
    else:
        raise SystemExit(f"unknown role {role!r}")


if __name__ == "__main__":
    main()
