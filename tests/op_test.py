"""Numeric-gradient checking harness.

Port of the reference's test backbone (SURVEY §4.1): central-difference numeric
gradients vs analytic gradients — gen-2 ``op_test.py:get_numeric_gradient`` (:80) and
gen-1 ``LayerGradUtil`` perturbation machinery. Here the analytic side is jax.grad;
the check still matters because many ops are hand-written dynamic programs (CRF, CTC,
masked scans) where a subtle masking bug produces a *valid* but *wrong* gradient.
"""

from __future__ import annotations

from typing import Callable, Sequence

import jax
import jax.numpy as jnp
import numpy as np


def numeric_grad(f: Callable, args: Sequence[np.ndarray], wrt: int,
                 eps: float = 1e-3) -> np.ndarray:
    """Central differences d f / d args[wrt]; f returns a scalar."""
    args = [np.asarray(a, dtype=np.float64 if np.issubdtype(np.asarray(a).dtype, np.floating) else None)
            for a in args]
    x = np.array(args[wrt], dtype=np.float64)
    g = np.zeros_like(x)
    flat = x.reshape(-1)
    gflat = g.reshape(-1)
    for i in range(flat.size):
        orig = flat[i]
        flat[i] = orig + eps
        fp = float(f(*[a if j != wrt else x.astype(np.float32) for j, a in enumerate(args)]))
        flat[i] = orig - eps
        fm = float(f(*[a if j != wrt else x.astype(np.float32) for j, a in enumerate(args)]))
        flat[i] = orig
        gflat[i] = (fp - fm) / (2 * eps)
    return g


def check_grad(f: Callable, args: Sequence[np.ndarray], wrt: int = 0,
               eps: float = 1e-3, rtol: float = 5e-2, atol: float = 2e-3):
    """Assert analytic jax.grad matches central differences.

    Tolerances are loose like the reference's (op_test.py uses max-relative-error
    thresholds ~0.005-0.05) because eps-discretization and f32 round-off interact.
    """
    f32_args = [jnp.asarray(a) for a in args]
    ana = jax.grad(lambda *xs: f(*xs), argnums=wrt)(*f32_args)
    num = numeric_grad(f, args, wrt, eps)
    np.testing.assert_allclose(np.asarray(ana), num, rtol=rtol, atol=atol,
                               err_msg=f"gradient mismatch wrt arg {wrt}")
