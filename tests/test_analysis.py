"""paddle_tpu.analysis — static verifier + shape interpreter + lint catalogue.

Tier-1 (JAX_PLATFORMS=cpu safe; conftest forces the virtual CPU mesh).
Covers the acceptance contract: every golden config and every config-style
example verifies clean (zero error-severity diagnostics), while crafted
malformed programs — undefined var, unregistered op, duplicate write, bad
sub-block scope/index, shape mismatch, dead op — are each rejected with a
structured Diagnostic, both through the library API, ``paddle_tpu lint``,
and ``Executor.run(verify=True)``.
"""

import os
import sys

import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import paddle_tpu.analysis as A
import paddle_tpu.fluid as fluid
from golden_configs import CONFIGS
from paddle_tpu.fluid import layers

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# config-style examples (module-level `cost`): the ones `paddle_tpu train`
# accepts and therefore the ones `paddle_tpu lint` must pass
CONFIG_EXAMPLES = [
    "examples/fit_a_line.py",
    "examples/mnist_lenet.py",
    "examples/quick_start_sentiment.py",
    "examples/sequence_tagging.py",
    "examples/traffic_prediction.py",
]


@pytest.fixture(autouse=True)
def fresh_programs():
    fluid.reset_default_programs()
    fluid.executor._global_scope = fluid.Scope()
    yield


def _codes(diags):
    return [d.code for d in diags]


def _error_codes(diags):
    return [d.code for d in A.errors(diags)]


# ------------------------------------------------------- known-good programs --

@pytest.mark.parametrize("name", sorted(CONFIGS))
def test_golden_config_verifies_clean(name):
    prog = CONFIGS[name]()
    diags = A.analyze_program(prog)
    assert not A.errors(diags), A.format_diagnostics(diags)
    sdiags = A.analyze_program(fluid.default_startup_program())
    assert not A.errors(sdiags), A.format_diagnostics(sdiags)


@pytest.mark.parametrize("cfg", CONFIG_EXAMPLES)
def test_example_config_lints_clean(cfg, capsys):
    from paddle_tpu import cli
    rc = cli.main(["lint", "--config", os.path.join(REPO, cfg)])
    out = capsys.readouterr().out
    assert rc == 0, out
    assert "0 error(s)" in out


def test_control_flow_program_verifies_clean():
    """while + TensorArray greedy-decode shape (the hardest scoping case:
    sub-block ops read parent vars, parent fetches loop results)."""
    V, T = 5, 6
    table = layers.data("table", shape=(V,))
    start = layers.data("start", shape=())
    i = layers.fill_constant((), "int32", 0)
    n = layers.fill_constant((), "int32", T - 1)
    cur = layers.cast(start, "int64")
    toks = layers.array_write(cur, i, capacity=T)
    cond = layers.less_than(i, n)
    with fluid.While(cond).block():
        b = fluid.default_main_program().current_block()
        row = b.create_var(shape=(V,), dtype="float32")
        b.append_op("gather", {"X": [table.name], "Index": [cur.name]},
                    {"Out": [row.name]})
        _, idx = layers.topk(row, 1)
        nxt = layers.cast(layers.reshape(idx, ()), "int64")
        layers.assign(nxt, cur)
        layers.increment(i)
        layers.array_write(cur, i, array=toks)
        layers.less_than(i, n, cond=cond)
    # un-batched decode: analysis must use the REAL feed shapes (a (V, V)
    # transition table, a scalar start token), not the declared -1 batch dims
    diags = A.analyze_program(fluid.default_main_program(),
                              feed={"table": np.zeros((V, V), np.float32),
                                    "start": np.asarray(0.0, np.float32)},
                              fetch=[toks.name])
    assert not A.errors(diags), A.format_diagnostics(diags)


# --------------------------------------------------- crafted malformed programs

def test_rejects_undefined_input_var():
    x = layers.data("x", shape=(4,))
    g = fluid.default_main_program().global_block()
    out = g.create_var(shape=(-1, 4))
    g.append_op("elementwise_add", {"X": [x.name], "Y": ["ghost"]},
                {"Out": [out.name]})
    diags = A.analyze_program(fluid.default_main_program())
    assert "V001" in _error_codes(diags)
    d = next(d for d in diags if d.code == "V001")
    assert d.var == "ghost" and d.op_type == "elementwise_add"
    assert d.location() == "block 0, op #0 (elementwise_add)"


def test_rejects_unregistered_op():
    x = layers.data("x", shape=(4,))
    layers.fc(x, 8)
    prog = fluid.default_main_program()
    prog.global_block().ops[0].type = "totally_bogus_op"
    diags = A.analyze_program(prog)
    assert "V002" in _error_codes(diags)


def test_rejects_duplicate_output_write():
    x = layers.data("x", shape=(4,))
    g = fluid.default_main_program().global_block()
    a = g.create_var(shape=(-1, 4))
    g.append_op("scale", {"X": [x.name]}, {"Out": [a.name]}, {"scale": 2.0})
    g.append_op("scale", {"X": [x.name]}, {"Out": [a.name]}, {"scale": 3.0})
    diags = A.analyze_program(fluid.default_main_program(), fetch=[a.name])
    assert "V003" in _error_codes(diags)
    # read-then-rewrite (in-place update) is NOT a duplicate write
    fluid.reset_default_programs()
    x = layers.data("x", shape=(4,))
    g = fluid.default_main_program().global_block()
    a = g.create_var(shape=(-1, 4))
    g.append_op("scale", {"X": [x.name]}, {"Out": [a.name]}, {"scale": 2.0})
    g.append_op("elementwise_add", {"X": [a.name], "Y": [x.name]},
                {"Out": [a.name]})
    diags = A.analyze_program(fluid.default_main_program(), fetch=[a.name])
    assert "V003" not in _codes(diags)


def test_rejects_sibling_branch_scope_violation():
    """A var declared in the true branch is NOT visible in the false branch
    (parent-scope lookup goes UP, never sideways)."""
    x = layers.data("x", shape=())
    outv = layers.fill_constant((), "float32", 0.0)
    thresh = layers.fill_constant((), "float32", 5.0)
    pred = layers.greater_than(x, thresh)
    c = fluid.Cond(pred)
    with c.true_block():
        doubled = layers.elementwise_add(x, x)
        layers.assign(doubled, outv)
    with c.false_block():
        b = fluid.default_main_program().current_block()
        bad = b.create_var(shape=(), dtype="float32")
        b.append_op("scale", {"X": [doubled.name]}, {"Out": [bad.name]},
                    {"scale": 1.0})
        layers.assign(bad, outv)
    diags = A.analyze_program(fluid.default_main_program(),
                              fetch=[outv.name])
    errs = [d for d in A.errors(diags) if d.code == "V001"]
    assert errs and errs[0].var == doubled.name
    assert "sibling" in (errs[0].hint or "")


def test_rejects_invalid_sub_block_index():
    i = layers.fill_constant((), "int32", 0)
    n = layers.fill_constant((), "int32", 3)
    cond = layers.less_than(i, n)
    with fluid.While(cond).block():
        layers.increment(i)
        layers.less_than(i, n, cond=cond)
    prog = fluid.default_main_program()
    prog.global_block().ops[-1].attrs["sub_block_idx"] = 99
    diags = A.analyze_program(prog, fetch=[i.name])
    assert "V004" in _error_codes(diags)


def test_rejects_cyclic_sub_block():
    i = layers.fill_constant((), "int32", 0)
    n = layers.fill_constant((), "int32", 3)
    cond = layers.less_than(i, n)
    with fluid.While(cond).block():
        layers.increment(i)
        layers.less_than(i, n, cond=cond)
    prog = fluid.default_main_program()
    # make the sub-block's own op point back at itself
    sub = prog.blocks[1]
    sub.append_op("while", {"Condition": [cond.name]}, {},
                  {"sub_block_idx": 1})
    diags = A.analyze_program(prog, fetch=[i.name])
    assert any(d.code == "V004" and "cycle" in d.message
               for d in A.errors(diags))


def test_rejects_while_condition_never_updated():
    i = layers.fill_constant((), "int32", 0)
    n = layers.fill_constant((), "int32", 3)
    cond = layers.less_than(i, n)
    with fluid.While(cond).block():
        layers.increment(i)       # cond never written in the body
    diags = A.analyze_program(fluid.default_main_program(), fetch=[i.name])
    assert "V005" in _error_codes(diags)


def test_rejects_shape_mismatch_statically():
    x = layers.data("x", shape=(8,))
    g = fluid.default_main_program().global_block()
    g.create_var(name="w", shape=(4, 2), persistable=True)
    o = g.create_var(shape=(-1, 2))
    g.append_op("mul", {"X": [x.name], "Y": ["w"]}, {"Out": [o.name]})
    diags = A.analyze_program(fluid.default_main_program(), fetch=[o.name])
    errs = [d for d in A.errors(diags) if d.code == "S001"]
    assert errs and errs[0].op_type == "mul"


def test_rejects_loop_carry_shape_change():
    """A while body that changes a carried var's dtype is statically
    rejected (XLA loop carries must be invariant)."""
    i = layers.fill_constant((), "int32", 0)
    n = layers.fill_constant((), "int32", 3)
    v = layers.fill_constant((), "float32", 0.0)
    cond = layers.less_than(i, n)
    with fluid.While(cond).block():
        b = fluid.default_main_program().current_block()
        b.append_op("cast", {"X": [v.name]}, {"Out": [v.name]},
                    {"dtype": "int32"})      # v: float32 -> int32 in carry
        layers.increment(i)
        layers.less_than(i, n, cond=cond)
    diags = A.analyze_program(fluid.default_main_program(), fetch=[v.name])
    assert "S003" in _error_codes(diags)


def test_flags_dead_op():
    x = layers.data("x", shape=(4,))
    layers.fc(x, 8)                      # dead: nothing reads or fetches it
    loss = layers.mean(layers.elementwise_mul(x, x))
    diags = A.analyze_program(fluid.default_main_program(),
                              fetch=[loss.name])
    dead = [d for d in diags if d.code == "L001"]
    assert dead and dead[0].severity == A.Severity.WARNING
    # promotable to a hard failure
    diags = A.lint_program(fluid.default_main_program(), fetch=[loss.name],
                           severity_overrides={"L001": A.Severity.ERROR})
    assert "L001" in _error_codes(diags)


def test_fetch_of_undefined_var_rejected():
    x = layers.data("x", shape=(4,))
    layers.fc(x, 8)
    diags = A.analyze_program(fluid.default_main_program(),
                              fetch=["never_defined"])
    assert "V006" in _error_codes(diags)


# --------------------------------------------------------------- lint extras --

def test_trace_safety_lint_flags_callable_attr():
    x = layers.data("x", shape=(4,))
    g = fluid.default_main_program().global_block()
    o = g.create_var(shape=(-1, 4))
    g.append_op("scale", {"X": [x.name]}, {"Out": [o.name]},
                {"scale": 1.0, "post_hook": lambda v: v})
    diags = A.lint_program(fluid.default_main_program(), fetch=[o.name])
    assert any(d.code == "L003" for d in diags)
    # fill_init's host init callable is the sanctioned exception
    layers.fc(x, 4)
    sdiags = A.lint_program(fluid.default_startup_program())
    assert not any(d.code == "L003" for d in sdiags)


def test_sharding_annotation_lint_and_roundtrip():
    x = layers.data("x", shape=(4,), sharding=("data", None))
    ok = A.lint_program(fluid.default_main_program(), fetch=[x.name])
    assert not any(d.code == "L004" for d in ok)
    # a repeated axis is always an error; an unknown axis is a warning
    # against the default CANONICAL_ORDER (make_mesh allows custom names)
    # but an error when the caller pins mesh_axes explicitly
    y = layers.data("y", shape=(4,), sharding=("warp", "warp"))
    diags = A.lint_program(fluid.default_main_program(),
                           fetch=[x.name, y.name])
    unknown = next(d for d in diags if d.code == "L004"
                   and "unknown mesh axis 'warp'" in d.message)
    repeated = next(d for d in diags if d.code == "L004"
                    and "repeats" in d.message)
    assert unknown.severity == A.Severity.WARNING
    assert repeated.severity == A.Severity.ERROR
    strict = A.lint_program(fluid.default_main_program(),
                            fetch=[x.name, y.name],
                            mesh_axes=["data", "model"])
    assert any(d.code == "L004" and "unknown mesh axis 'warp'" in d.message
               and d.severity == A.Severity.ERROR for d in strict)
    # a malformed op-level spec is reported, not crashed on
    g = fluid.default_main_program().global_block()
    o = g.create_var(shape=(-1, 4))
    g.append_op("scale", {"X": [x.name]}, {"Out": [o.name]},
                {"scale": 1.0, "sharding": 7})
    bad = A.lint_program(fluid.default_main_program(), fetch=[o.name])
    assert any(d.code == "L004" and "not a sharding spec" in d.message
               for d in bad)
    # a bare-string spec means ONE axis, not its characters
    z = layers.data("z", shape=(4,), sharding="data")
    assert z.sharding == ("data",)
    # annotation rides Program JSON
    clone = fluid.Program.from_dict(fluid.default_main_program().to_dict())
    assert clone.global_block().var("x").sharding == ("data", None)


def test_unused_var_lint():
    layers.data("x", shape=(4,))
    g = fluid.default_main_program().global_block()
    g.create_var(name="orphan", shape=(3,))
    diags = A.lint_program(fluid.default_main_program())
    assert any(d.code == "L002" and d.var == "orphan" for d in diags)


# ----------------------------------------------------------- executor wiring --

def test_executor_verify_true_runs_good_program():
    x = layers.data("x", shape=(4,))
    h = layers.fc(x, 8, act="tanh")
    loss = layers.mean(h)
    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())
    out, = exe.run(feed={"x": np.zeros((2, 4), np.float32)},
                   fetch_list=[loss], verify=True)
    assert np.isfinite(out)


def test_executor_verify_true_rejects_before_trace():
    x = layers.data("x", shape=(4,))
    g = fluid.default_main_program().global_block()
    o = g.create_var(shape=(-1, 4))
    g.append_op("elementwise_add", {"X": [x.name], "Y": ["ghost"]},
                {"Out": [o.name]})
    exe = fluid.Executor()
    with pytest.raises(A.ProgramVerificationError) as ei:
        exe.run(feed={"x": np.zeros((2, 4), np.float32)},
                fetch_list=[o], verify=True)
    assert any(d.code == "V001" for d in ei.value.diagnostics)


def test_executor_verify_true_uses_real_feed_shapes():
    """A rank-breaking feed is rejected statically with the op site."""
    x = layers.data("x", shape=(4,))
    w = layers.data("w", shape=(4,))
    out = layers.elementwise_add(x, w)
    exe = fluid.Executor()
    with pytest.raises(A.ProgramVerificationError) as ei:
        exe.run(feed={"x": np.zeros((2, 4), np.float32),
                      "w": np.zeros((2, 5), np.float32)},
                fetch_list=[out], verify=True)
    assert any(d.code == "S001" for d in ei.value.diagnostics)


# ------------------------------------------------------------------ CLI path --

def test_cli_lint_rejects_bad_config(tmp_path, capsys):
    from paddle_tpu import cli
    bad = tmp_path / "bad_cfg.py"
    bad.write_text(
        "import paddle_tpu.fluid as fluid\n"
        "from paddle_tpu.fluid import layers\n"
        "x = layers.data('x', shape=(4,))\n"
        "g = fluid.default_main_program().global_block()\n"
        "o = g.create_var(shape=(-1, 4))\n"
        "g.append_op('elementwise_add', {'X': [x.name], 'Y': ['ghost']},"
        " {'Out': [o.name]})\n")
    rc = cli.main(["lint", "--config", str(bad)])
    out = capsys.readouterr().out
    assert rc == 1 and "V001" in out


def test_cli_lint_fail_on_warning_promotes_dead_op(tmp_path, capsys):
    from paddle_tpu import cli
    cfg = tmp_path / "dead_cfg.py"
    cfg.write_text(
        "import paddle_tpu.fluid as fluid\n"
        "from paddle_tpu.fluid import layers\n"
        "x = layers.data('x', shape=(4,))\n"
        "dead = layers.fc(x, 8)\n"
        "cost = layers.mean(layers.elementwise_mul(x, x))\n")
    assert cli.main(["lint", "--config", str(cfg)]) == 0   # warning only
    capsys.readouterr()
    rc = cli.main(["lint", "--config", str(cfg), "--fail-on", "warning"])
    out = capsys.readouterr().out
    assert rc == 1 and "L001" in out


def test_cli_lint_json_output(tmp_path, capsys):
    import json
    from paddle_tpu import cli
    cfg = tmp_path / "cfg.py"
    cfg.write_text(
        "from paddle_tpu.fluid import layers\n"
        "x = layers.data('x', shape=(4,))\n"
        "cost = layers.mean(layers.fc(x, 2))\n")
    rc = cli.main(["lint", "--config", str(cfg), "--json"])
    captured = capsys.readouterr()
    assert rc == 0
    # stdout is PURE JSON (summary goes to stderr) so `lint --json | jq` works
    payload = json.loads(captured.out)
    assert isinstance(payload, list)
    assert "lint:" in captured.err
    # every diagnostic carries its program structurally, not via message text
    assert all(d["program"] in ("main", "startup") for d in payload)


def test_cli_lint_missing_config_is_usage_error(tmp_path, capsys):
    """Exit 2 (usage), distinguishable from exit 1 (findings)."""
    from paddle_tpu import cli
    rc = cli.main(["lint", "--config", str(tmp_path / "nope.py")])
    assert rc == 2
    assert "cannot load config" in capsys.readouterr().err


# ------------------------------------------------------------- extensibility --

def test_register_shape_infer_rule_for_custom_op():
    from paddle_tpu.analysis import register_shape_infer
    from paddle_tpu.fluid.registry import OpRegistry

    @OpRegistry.register("test_analysis_double")
    def _double(ins, attrs):
        return {"Out": [ins["X"][0] * 2]}

    calls = []

    @register_shape_infer("test_analysis_double")
    def _infer(op, ins, ctx):
        calls.append(op.type)
        s = ins["X"][0]
        import jax
        return {"Out": [jax.ShapeDtypeStruct(s.shape, s.dtype)]}

    try:
        x = layers.data("x", shape=(4,))
        g = fluid.default_main_program().global_block()
        o = g.create_var(shape=(-1, 4))
        g.append_op("test_analysis_double", {"X": [x.name]},
                    {"Out": [o.name]})
        diags = A.analyze_program(fluid.default_main_program(),
                                  fetch=[o.name])
        assert not A.errors(diags) and calls == ["test_analysis_double"]
    finally:
        OpRegistry._ops.pop("test_analysis_double", None)
        A.ShapeInferRegistry._rules.pop("test_analysis_double", None)


def test_operator_to_dict_keeps_callable_attr_keys():
    """Satellite: serialized ops must keep attr KEYS for callables (named
    placeholder), not silently drop them."""
    x = layers.data("x", shape=(4,))
    layers.fc(x, 8)
    startup = fluid.default_startup_program()
    fill = next(op for op in startup.global_block().ops
                if op.type == "fill_init")
    d = fill.to_dict()
    assert "init" in d["attrs"], "callable attr key was dropped"
    assert isinstance(d["attrs"]["init"], str)
    assert d["attrs"]["init"].startswith("<callable:")
    import json
    json.dumps(d)  # placeholder must be JSON-able


def test_diagnostic_location_matches_runtime_provenance():
    """Static diagnostics and trace-time error notes cite the same site
    format ('block B, op #I (...)')."""
    assert A.op_site(0, 3, "concat") == "block 0, op #3 (concat)"
    x = layers.data("x", shape=(4,))
    h = layers.fc(x, 8, act="relu")
    y = layers.data("y", shape=(3,))
    bad = layers.concat([h, y], axis=0)
    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())
    with pytest.raises(Exception) as ei:
        exe.run(fluid.default_main_program(),
                feed={"x": np.zeros((2, 4), np.float32),
                      "y": np.zeros((2, 3), np.float32)},
                fetch_list=[bad])
    msg = str(ei.value) + "\n".join(getattr(ei.value, "__notes__", []))
    assert "block 0, op #" in msg
    # and the same defect is caught statically, citing the same block
    diags = A.analyze_program(fluid.default_main_program(),
                              fetch=[bad.name])
    errs = [d for d in A.errors(diags) if d.code == "S001"]
    assert errs and errs[0].block_idx == 0 and errs[0].op_type == "concat"

def test_structural_diags_in_sub_blocks_carry_block_path():
    """Every pass's diagnostics cite nested sub-blocks by the full parent
    chain — analyze_program fills block_path from diagnostics.block_paths
    for V0xx/S0xx findings too, not only the dataflow lints."""
    i = layers.fill_constant(shape=(), dtype="int32", value=0)
    n = layers.fill_constant(shape=(), dtype="int32", value=2)
    cond = layers.less_than(i, n)
    with fluid.While(cond).block():
        b = fluid.default_main_program().current_block()
        out = b.create_var(shape=(4,), dtype="float32")
        b.append_op("elementwise_add", {"X": ["ghost"], "Y": ["ghost"]},
                    {"Out": [out.name]})
        layers.increment(i)
        layers.less_than(i, n, cond=cond)
    diags = A.analyze_program(fluid.default_main_program())
    errs = [d for d in A.errors(diags) if d.block_idx not in (None, 0)]
    assert errs, A.format_diagnostics(diags)
    assert all(d.block_path and d.block_path.startswith("0.")
               for d in errs), A.format_diagnostics(errs)
    assert any("block 0.1" in d.location() for d in errs)


def test_legacy_json_carries_new_fields_backward_compatibly(tmp_path,
                                                            capsys):
    """The legacy --json flat list keeps its shape; the Diagnostic dict
    simply grew block_path/explain keys (None when unset)."""
    import json
    from paddle_tpu import cli
    cfg = tmp_path / "ok.py"
    cfg.write_text(
        "import paddle_tpu.fluid as fluid\n"
        "from paddle_tpu.fluid import layers\n"
        "x = layers.data('x', shape=(4,))\n"
        "unused = layers.data('unused', shape=(4,))\n"
        "cost = layers.mean(x)\n")
    rc = cli.main(["lint", "--config", str(cfg), "--json"])
    payload = json.loads(capsys.readouterr().out)
    assert rc == 0
    assert isinstance(payload, list) and payload
    for d in payload:
        assert "block_path" in d and "explain" in d
        assert d["program"] in ("main", "startup")
