"""The measured autotuning plane (paddle_tpu/tune + the routing consults).

Contracts under test:

* CACHE — versioned round trip, env-path resolution, schema refusal,
  atomic save; a corrupt/stale/illegal entry degrades to the heuristic,
  never to an error or an illegal launch;
* CONSULT — `_fused_plan`/`decode_route`/`PagePool` actually read the
  installed cache (kernels.routes_total flips, plans swap);
* PARITY — the tentpole invariant: tuned plans change SPEED, never
  outputs. Fused-RNN forward AND backward are bit-equal across plans
  (and match the scan reference); greedy tokens through a tuned decode
  route equal the dense-route stream token for token;
* LINT — L008 flags schema/space-hash staleness;
* CLI — `paddle_tpu tune --check` closes the measure→persist→consult
  loop end to end on the CPU interpret backend.

Decode dims are the shared serving dims (VOCAB=97, D=32, H=4, L=2,
MAX_LEN=128) so the session compile cache absorbs trace costs.
"""

import json
import os

import jax
import numpy as np
import jax.numpy as jnp
import pytest

from paddle_tpu import obs, tune
from paddle_tpu.ops import pallas_kernels as pk
from paddle_tpu.ops import rnn as R

VOCAB, D, H, L, MAX_LEN = 97, 32, 4, 2, 128


@pytest.fixture
def tune_cache():
    """An empty installed AutotuneCache the test can drop entries into;
    uninstalls afterwards (the session env points consults at a
    nonexistent file, so post-test lookups miss)."""
    c = tune.AutotuneCache()
    tune.set_cache(c)
    yield c
    tune.reset()


def _put_fused(cache, kernel, plan, *, gates, T, H_, batch, stale=False):
    return cache.put(
        "fused_rnn", kernel, "cpu",
        tune.fused_family(gates=gates, T=T, H=H_, batch=batch), list(plan),
        "deadbeef" if stale else tune.space_hash("fused_rnn"),
        methodology="measured")


# -- cache mechanics -----------------------------------------------------

def test_cache_roundtrip_env_and_schema(tmp_path, monkeypatch):
    c = tune.AutotuneCache()
    c.put("decode_route", "decode_attention", "cpu", "default",
          {"kernel_min_len": 96}, tune.space_hash("decode_route"),
          methodology="measured", tuned_ms=1.0)
    path = c.save(str(tmp_path / "autotune.json"))
    loaded = tune.load_cache(path)
    e = loaded.get("decode_route", "decode_attention", "cpu", "default")
    assert e is not None and e["plan"]["kernel_min_len"] == 96
    assert e["methodology"] == "measured"
    # the consult honors $PADDLE_TPU_AUTOTUNE_CACHE
    monkeypatch.setenv(tune.CACHE_ENV, path)
    tune.reset()
    try:
        assert tune.decode_kernel_min_len() == 96
        assert tune.plan_source() == "tuned"
    finally:
        tune.reset()
    # a future schema version is refused loudly at load...
    bad = dict(c.to_dict(), schema_version=99)
    (tmp_path / "bad.json").write_text(json.dumps(bad))
    with pytest.raises(ValueError, match="schema_version"):
        tune.load_cache(str(tmp_path / "bad.json"))
    # ...and silently (warn-once) ignored on the consult path
    monkeypatch.setenv(tune.CACHE_ENV, str(tmp_path / "bad.json"))
    tune.reset()
    try:
        with pytest.warns(RuntimeWarning, match="autotune cache"):
            assert tune.decode_kernel_min_len() is tune.MISS
    finally:
        tune.reset()


def test_consult_rejects_stale_and_illegal_entries(tune_cache):
    heur = R._fused_plan(32, 16, seq_h_units=6, batch=16)
    # a stale-hash entry is invisible: heuristic decides
    _put_fused(tune_cache, "lstm_sequence_fused", (8, 8), gates=4, T=32,
               H_=16, batch=16, stale=True)
    assert R._fused_plan(32, 16, seq_h_units=6, batch=16,
                         kernel="lstm_sequence_fused") == heur
    # an illegal plan (batch tile not a multiple of 8, nor the whole
    # batch) is rejected by plan_is_legal -> heuristic again
    _put_fused(tune_cache, "lstm_sequence_fused", (12, 8), gates=4, T=32,
               H_=16, batch=16)
    assert R._fused_plan(32, 16, seq_h_units=6, batch=16,
                         kernel="lstm_sequence_fused") == heur
    # malformed plans never raise
    tune_cache.put("page_block", "paged_decode_attention", "cpu",
                   "default", {"page_block": "huge"},
                   tune.space_hash("page_block"))
    assert tune.page_block(128, 32) is None
    tune_cache.put("decode_route", "decode_attention", "cpu", "default",
                   {"wrong_key": 1}, tune.space_hash("decode_route"))
    assert tune.decode_kernel_min_len() is tune.MISS


def test_fused_plan_consult_swaps_plan(tune_cache):
    heur = R._fused_plan(32, 16, seq_h_units=6, batch=16)
    cands = tune.fused_candidates(T=32, H=16, gates=4, seq_h_units=6,
                                  batch=16)
    other = next(c for c in cands if c != heur)
    _put_fused(tune_cache, "lstm_sequence_fused", other, gates=4, T=32,
               H_=16, batch=16)
    assert R._fused_plan(32, 16, seq_h_units=6, batch=16,
                         kernel="lstm_sequence_fused") == other
    # a different family (batch 8) misses -> heuristic
    assert R._fused_plan(32, 16, seq_h_units=6, batch=8,
                         kernel="lstm_sequence_fused") \
        == R._fused_plan(32, 16, seq_h_units=6, batch=8)


# -- the tentpole parity property ---------------------------------------

def test_tuned_fused_plans_change_speed_never_outputs(tune_cache):
    """Forward AND backward: every legal (block_b, chunk_t) launch of the
    fused LSTM kernel produces BIT-identical outputs and gradients — so a
    tuned plan (injected synthetic cache entry) can only change launch
    geometry, never numerics. The scan reference bounds them all."""
    T, B, H_ = 12, 8, 8
    rs = np.random.RandomState(0)
    x = jnp.asarray(rs.randn(B, T, 5) * 0.3, jnp.float32)
    lens = jnp.asarray(rs.randint(1, T + 1, B), jnp.int32)
    w = jnp.asarray(rs.randn(5, 4 * H_) * 0.3, jnp.float32)
    u = jnp.asarray(rs.randn(H_, 4 * H_) * 0.3, jnp.float32)
    b = jnp.asarray(rs.randn(4 * H_) * 0.3, jnp.float32)
    h0 = jnp.zeros((B, H_), jnp.float32)

    heur = R._fused_plan(T, H_, seq_h_units=6, batch=B)
    assert heur is not None
    tuned = next(c for c in tune.fused_candidates(
        T=T, H=H_, gates=4, seq_h_units=6, batch=B) if c != heur)
    _put_fused(tune_cache, "lstm_sequence_fused", tuned, gates=4, T=T,
               H_=H_, batch=B)
    # inject a synthetic BACKWARD plan too (keyed separately), so the
    # gradient path consults the cache as well
    bwd_heur = R._fused_plan(T, H_, 4, 11, B, double_buffer_always=True)
    bwd_cands = [c for c in tune.fused_candidates(
        T=T, H=H_, gates=4, seq_h_units=11, batch=B,
        double_buffer_always=True) if c != bwd_heur]
    if bwd_cands:
        tune_cache.put(
            "fused_rnn", "lstm_sequence_fused_bwd", "cpu",
            tune.fused_family(gates=4, T=T, H=H_, batch=B),
            list(bwd_cands[0]), tune.space_hash("fused_rnn"))
        assert R._fused_bwd_plan(T, H_, 4, 11, B,
                                 kernel="lstm_sequence_fused_bwd") \
            == bwd_cands[0]
    consulted = R._fused_plan(T, H_, seq_h_units=6, batch=B,
                              kernel="lstm_sequence_fused")
    assert consulted == tuned != heur

    def run(plan):
        def f(x, w, u, b, h0):
            out, ht, ct = R._lstm_fused(x, lens, w, u, b, h0, h0, 0.5,
                                        plan[0], plan[1])
            return out, ht, ct

        out = f(x, w, u, b, h0)
        loss = lambda *a: sum(jnp.sum(o * (i + 1.0))
                              for i, o in enumerate(f(*a)))
        grads = jax.grad(loss, argnums=(0, 1, 2, 3, 4))(x, w, u, b, h0)
        return out, grads

    out_t, g_t = run(consulted)
    out_h, g_h = run(heur)
    for a, bb in zip(out_t, out_h):        # plan choice: bit parity
        np.testing.assert_array_equal(np.asarray(a), np.asarray(bb))
    for a, bb in zip(g_t, g_h):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(bb))
    # and both match the scan reference (shared math, fp tolerance)
    ref_out, ref_state = R._lstm_scan(x, lens, w, u, b, h0, h0, False, 0.5)
    np.testing.assert_allclose(np.asarray(out_t[0]), np.asarray(ref_out),
                               rtol=2e-5, atol=2e-5)
    np.testing.assert_allclose(np.asarray(out_t[1]),
                               np.asarray(ref_state.h), rtol=2e-5,
                               atol=2e-5)


def test_tuned_decode_route_greedy_token_parity(tune_cache,
                                                paged_model_and_params):
    """End to end through the model: an injected decode-route entry with
    kernel_min_len=1 forces EVERY cache read onto the Pallas kernel route
    (interpret on CPU — the promoted tuning/CI backend), and the greedy
    stream is token-for-token equal to the dense-route stream. Route
    consult is proven via kernels.routes_total."""
    from paddle_tpu.models import TransformerLM
    model, params = paged_model_and_params
    rs = np.random.RandomState(11)
    prompt = rs.randint(0, VOCAB, 7)
    base = np.asarray(model.generate_cached(
        params, jnp.asarray(prompt[None]), steps=12))
    tune_cache.put("decode_route", "decode_attention", "cpu", "default",
                   {"kernel_min_len": 1},
                   tune.space_hash("decode_route"),
                   methodology="measured")
    assert pk.decode_route(32) == "kernel"
    # a FRESH model instance retraces its decode steps under the tuned
    # route (the first model's jit cache pinned the dense executables)
    model2 = TransformerLM(VOCAB, d_model=D, n_heads=H, n_layers=L,
                           max_len=MAX_LEN)
    params2 = model2.init(jax.random.PRNGKey(0))
    reg = obs.MetricsRegistry()
    with obs.ObsSession(registry=reg).installed():
        got = np.asarray(model2.generate_cached(
            params2, jnp.asarray(prompt[None]), steps=12))
    np.testing.assert_array_equal(got, base)
    routes = [s for s in reg.collect()
              if s["name"] == "kernels.routes_total"
              and s["labels"].get("kernel") == "decode_attention"]
    assert any(s["labels"].get("route") == "kernel" and s["value"] > 0
               for s in routes), routes


def test_tuned_page_block_consult(tune_cache, paged_model_and_params):
    from paddle_tpu.serving import PagePool
    model, params = paged_model_and_params
    # no entry -> the 64 heuristic
    assert PagePool(model, params, slots=2, cache_bucket=128).bs == 64
    tune_cache.put("page_block", "paged_decode_attention", "cpu",
                   "default", {"page_block": 32},
                   tune.space_hash("page_block"),
                   methodology="measured")
    assert PagePool(model, params, slots=2, cache_bucket=128).bs == 32
    # explicit page_block always wins over the cache
    assert PagePool(model, params, slots=2, page_block=8,
                    cache_bucket=32).bs == 8
    # a winner that does not divide this pool's grid falls back
    tune_cache.put("page_block", "paged_decode_attention", "cpu",
                   "default", {"page_block": 48},
                   tune.space_hash("page_block"))
    assert PagePool(model, params, slots=2, cache_bucket=128).bs == 64


# -- lint + CLI ----------------------------------------------------------

def test_lint_autotune_staleness_l008(tmp_path):
    from paddle_tpu.analysis import lint_autotune_cache
    # missing file: clean (nothing tuned, nothing stale)
    assert lint_autotune_cache(str(tmp_path / "none.json")) == []
    c = tune.AutotuneCache()
    c.put("fused_rnn", "lstm_sequence_fused", "cpu", "g4_t8_h8_b8",
          [8, 8], tune.space_hash("fused_rnn"))
    path = c.save(str(tmp_path / "fresh.json"))
    assert lint_autotune_cache(path) == []
    # stale space hash -> one L008 naming the entry
    c.put("fused_rnn", "gru_sequence_fused", "cpu", "g3_t8_h8_b8",
          [8, 8], "0ld5pacehash")
    path = c.save(str(tmp_path / "stale.json"))
    diags = lint_autotune_cache(path)
    assert len(diags) == 1 and diags[0].code == "L008"
    assert "STALE" in diags[0].message
    # unknown space -> flagged; schema mismatch -> whole-file finding
    c2 = tune.AutotuneCache()
    c2.put("warp_drive", "k", "cpu", "f", [1], "x")
    diags = lint_autotune_cache(c2.save(str(tmp_path / "unk.json")))
    assert len(diags) == 1 and "unknown plan space" in diags[0].message
    (tmp_path / "old.json").write_text(
        json.dumps({"schema_version": 0, "entries": {}}))
    diags = lint_autotune_cache(str(tmp_path / "old.json"))
    assert len(diags) == 1 and "schema_version" in diags[0].message


def test_tune_check_cli_smoke(tmp_path, capsys):
    """`paddle_tpu tune --check`: the CI smoke — a seconds-long smoke
    sweep on the interpret backend, persisted, reloaded, and consulted
    through the real entry points. Also covers `lint --autotune-cache`
    standalone over the file it wrote."""
    from paddle_tpu.cli import main
    path = str(tmp_path / "autotune.json")
    rc = main(["tune", "--check", "--cache", path])
    out = capsys.readouterr().out
    assert rc == 0, out
    assert "--check OK" in out
    cache = tune.load_cache(path)
    assert len(cache.entries) >= 3          # >= 2 plan spaces end-to-end
    spaces = {e["space"] for e in cache.entries.values()}
    assert {"fused_rnn", "decode_route", "page_block"} <= spaces
    for e in cache.entries.values():
        assert e["methodology"] == "measured"
        assert e["space_hash"] == tune.space_hash(e["space"])
    rc = main(["lint", "--autotune-cache", path, "--fail-on", "warning"])
    assert rc == 0
    # markdown table (the kernels.md regeneration surface) renders
    rc = main(["tune", "--profile", "smoke", "--dry-run", "--markdown",
               "--spaces", "page_block"])
    out = capsys.readouterr().out
    assert rc == 0 and "| space | kernel |" in out


def test_plan_source_stamp(tune_cache):
    assert tune.plan_source() == "heuristic"      # empty cache
    tune_cache.put("decode_route", "decode_attention", "cpu", "default",
                   {"kernel_min_len": None},
                   tune.space_hash("decode_route"))
    assert tune.plan_source() == "tuned"
    # stale entries do not count as tuned
    stale = tune.AutotuneCache()
    stale.put("decode_route", "decode_attention", "cpu", "default",
              {"kernel_min_len": None}, "0ld")
    tune.set_cache(stale)
    assert tune.plan_source() == "heuristic"
