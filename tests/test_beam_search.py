"""Beam-search tests: known-distribution decoding (analog of
test_RecurrentGradientMachine generation tests + beam_search_op tests)."""

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.ops import beam_search as bs


def _fixed_step(table):
    """Decoder whose next-token log-probs depend only on current token."""
    def step(cell, tokens):
        logp = jnp.log(table[tokens] + 1e-9)
        return logp, cell
    return step


def test_greedy_follows_argmax_chain():
    # vocab 4, token i -> deterministic next token (i+1) % 3, eos=3 after token 2
    V = 4
    table = np.full((V, V), 1e-6, np.float32)
    table[0, 1] = 1.0
    table[1, 2] = 1.0
    table[2, 3] = 1.0  # -> eos
    table[3, 3] = 1.0
    table /= table.sum(-1, keepdims=True)
    toks, score = bs.greedy_search({}, _fixed_step(jnp.asarray(table)),
                                   batch_size=2, max_len=5, bos_id=0, eos_id=3)
    np.testing.assert_array_equal(np.asarray(toks[0]), [1, 2, 3, 3, 3])


def test_beam_finds_higher_prob_path():
    # greedy takes token 1 first (p=.6) but the 2-step path through 2 is better:
    # p(1)*best_after_1 = .6*.4 = .24 < p(2)*best_after_2 = .4*.9 = .36
    V = 4
    eos = 3
    table = np.full((V, V), 1e-9, np.float32)
    table[0, 1] = 0.6
    table[0, 2] = 0.4
    table[1, eos] = 0.4
    table[1, 1] = 0.6  # continuing costs more later
    table[1, 2] = 1e-9
    table[2, eos] = 0.9
    table[2, 1] = 0.1
    table[eos, eos] = 1.0
    table /= table.sum(-1, keepdims=True)
    toks, scores = bs.beam_search(
        {}, _fixed_step(jnp.asarray(table)), batch_size=1, beam_size=3, max_len=4,
        vocab_size=V, bos_id=0, eos_id=eos)
    # best beam should start with 2 then eos
    np.testing.assert_array_equal(np.asarray(toks[0, 0, :2]), [2, eos])
    # scores sorted descending
    s = np.asarray(scores[0])
    assert np.all(np.diff(s) <= 1e-5)


def test_beam_constraint_fn_masks_tokens():
    V = 4
    eos = 3
    table = np.full((V, V), 0.25, np.float32)

    def forbid_token_1(logp, step):
        return logp.at[..., 1].set(-1e9)

    toks, _ = bs.beam_search(
        {}, _fixed_step(jnp.asarray(table)), batch_size=1, beam_size=2, max_len=4,
        vocab_size=V, bos_id=0, eos_id=eos, constraint_fn=forbid_token_1)
    assert not np.any(np.asarray(toks) == 1)


def test_beam_state_gather():
    """Recurrent state must follow its beam when beams are reordered."""
    V, eos = 5, 4

    def step(cell, tokens):
        # state accumulates the token history sum; logp prefers token = state%3 + 1
        new_cell = {"acc": cell["acc"] + tokens}
        logp = jax.nn.log_softmax(
            jax.nn.one_hot((new_cell["acc"] % 3) + 1, V) * 5.0, -1)
        return logp, new_cell

    init = {"acc": jnp.zeros((2,), jnp.int32)}
    toks, scores = bs.beam_search(
        init, step, batch_size=2, beam_size=2, max_len=3, vocab_size=V,
        bos_id=0, eos_id=eos)
    assert toks.shape == (2, 2, 3)
