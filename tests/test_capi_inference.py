"""C inference ABI (native/capi_inference.cc — capi/gradient_machine.h:36-88
analog): create from the merged inference bundle, forward-only, callable from
plain C (driven here via ctypes), multi-thread safe (the reference's
multi_thread example)."""

import ctypes
import os
import threading

import numpy as np
import pytest

import paddle_tpu.fluid as fluid

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
LIB_PATH = os.path.join(REPO, "native", "libpaddle_tpu_capi.so")


@pytest.fixture(autouse=True)
def fresh_programs():
    fluid.reset_default_programs()
    yield


def _load():
    if not os.path.exists(LIB_PATH):
        pytest.skip("capi library not built (make -C native)")
    lib = ctypes.CDLL(LIB_PATH)
    lib.pti_create.restype = ctypes.c_void_p
    lib.pti_create.argtypes = [ctypes.c_char_p]
    lib.pti_forward.restype = ctypes.c_int
    lib.pti_forward.argtypes = [
        ctypes.c_void_p,
        ctypes.POINTER(ctypes.c_void_p),      # inputs
        ctypes.POINTER(ctypes.c_longlong),    # shapes (concatenated)
        ctypes.POINTER(ctypes.c_int),         # ndims
        ctypes.POINTER(ctypes.c_int),         # dtypes
        ctypes.c_int, ctypes.c_int,
        ctypes.POINTER(ctypes.c_float), ctypes.c_longlong,
        ctypes.POINTER(ctypes.c_longlong), ctypes.POINTER(ctypes.c_int)]
    lib.pti_destroy.argtypes = [ctypes.c_void_p]
    lib.pti_last_error.restype = ctypes.c_char_p
    return lib


def _export_model(tmp_path):
    x = fluid.layers.data("x", shape=(4,))
    h = fluid.layers.fc(x, 8, act="tanh")
    out = fluid.layers.fc(h, 2)
    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())
    d = str(tmp_path / "model")
    fluid.io.export_inference_model(d, ["x"], [out], exe)
    xs = np.random.RandomState(0).randn(3, 4).astype(np.float32)
    ref = np.asarray(exe.run(fluid.default_main_program(), feed={"x": xs},
                             fetch_list=[out])[0])
    return d, xs, ref


def _forward(lib, h, xs, out_elems=64):
    buf = np.ascontiguousarray(xs)
    inputs = (ctypes.c_void_p * 1)(buf.ctypes.data)
    shapes = (ctypes.c_longlong * 2)(*buf.shape)
    ndims = (ctypes.c_int * 1)(2)
    dtypes = (ctypes.c_int * 1)(0)
    out = np.zeros(out_elems, np.float32)
    out_shape = (ctypes.c_longlong * 8)()
    out_ndim = ctypes.c_int(0)
    rc = lib.pti_forward(
        h, inputs, shapes, ndims, dtypes, 1, 0,
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        out_elems, out_shape, ctypes.byref(out_ndim))
    assert rc >= 0, lib.pti_last_error().decode()
    shape = tuple(out_shape[i] for i in range(out_ndim.value))
    return out[:rc].reshape(shape)


def test_capi_create_forward_destroy(tmp_path):
    lib = _load()
    d, xs, ref = _export_model(tmp_path)
    h = lib.pti_create(d.encode())
    assert h, lib.pti_last_error().decode()
    got = _forward(lib, h, xs)
    np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-5)
    lib.pti_destroy(h)


def test_capi_create_bad_dir_reports_error():
    lib = _load()
    h = lib.pti_create(b"/nonexistent/model/dir")
    assert not h
    assert lib.pti_last_error()


def test_capi_multi_thread(tmp_path):
    """capi/examples/model_inference/multi_thread analog: concurrent
    forwards on one handle must all produce correct results."""
    lib = _load()
    d, xs, ref = _export_model(tmp_path)
    h = lib.pti_create(d.encode())
    assert h, lib.pti_last_error().decode()
    errs = []

    def worker():
        try:
            for _ in range(5):
                got = _forward(lib, h, xs)
                np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-5)
        except Exception as e:   # noqa: BLE001
            errs.append(e)

    threads = [threading.Thread(target=worker) for _ in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errs, errs
    lib.pti_destroy(h)


def test_capi_small_buffer_reports_size(tmp_path):
    lib = _load()
    d, xs, _ = _export_model(tmp_path)
    h = lib.pti_create(d.encode())
    buf = np.ascontiguousarray(xs)
    inputs = (ctypes.c_void_p * 1)(buf.ctypes.data)
    shapes = (ctypes.c_longlong * 2)(*buf.shape)
    ndims = (ctypes.c_int * 1)(2)
    dtypes = (ctypes.c_int * 1)(0)
    out = np.zeros(1, np.float32)
    out_shape = (ctypes.c_longlong * 8)()
    out_ndim = ctypes.c_int(0)
    rc = lib.pti_forward(
        h, inputs, shapes, ndims, dtypes, 1, 0,
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)), 1,
        out_shape, ctypes.byref(out_ndim))
    assert rc == -2          # too small; shape still reported for retry
    assert tuple(out_shape[i] for i in range(out_ndim.value)) == (3, 2)
    lib.pti_destroy(h)
