"""C inference ABI (native/capi_inference.cc — capi/gradient_machine.h:36-88
analog): create from the merged inference bundle, forward-only, callable from
plain C (driven here via ctypes), multi-thread safe (the reference's
multi_thread example)."""

import ctypes
import os
import threading

import numpy as np
import pytest

import paddle_tpu.fluid as fluid

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
LIB_PATH = os.path.join(REPO, "native", "libpaddle_tpu_capi.so")


@pytest.fixture(autouse=True)
def fresh_programs():
    fluid.reset_default_programs()
    yield


def _load():
    if not os.path.exists(LIB_PATH):
        pytest.skip("capi library not built (make -C native)")
    lib = ctypes.CDLL(LIB_PATH)
    lib.pti_create.restype = ctypes.c_void_p
    lib.pti_create.argtypes = [ctypes.c_char_p]
    lib.pti_forward.restype = ctypes.c_int
    lib.pti_forward.argtypes = [
        ctypes.c_void_p,
        ctypes.POINTER(ctypes.c_void_p),      # inputs
        ctypes.POINTER(ctypes.c_longlong),    # shapes (concatenated)
        ctypes.POINTER(ctypes.c_int),         # ndims
        ctypes.POINTER(ctypes.c_int),         # dtypes
        ctypes.c_int, ctypes.c_int,
        ctypes.POINTER(ctypes.c_float), ctypes.c_longlong,
        ctypes.POINTER(ctypes.c_longlong), ctypes.POINTER(ctypes.c_int)]
    lib.pti_destroy.argtypes = [ctypes.c_void_p]
    lib.pti_last_error.restype = ctypes.c_char_p
    return lib


def _export_model(tmp_path):
    x = fluid.layers.data("x", shape=(4,))
    h = fluid.layers.fc(x, 8, act="tanh")
    out = fluid.layers.fc(h, 2)
    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())
    d = str(tmp_path / "model")
    fluid.io.export_inference_model(d, ["x"], [out], exe)
    xs = np.random.RandomState(0).randn(3, 4).astype(np.float32)
    ref = np.asarray(exe.run(fluid.default_main_program(), feed={"x": xs},
                             fetch_list=[out])[0])
    return d, xs, ref


def _forward(lib, h, xs, out_elems=64):
    buf = np.ascontiguousarray(xs)
    inputs = (ctypes.c_void_p * 1)(buf.ctypes.data)
    shapes = (ctypes.c_longlong * 2)(*buf.shape)
    ndims = (ctypes.c_int * 1)(2)
    dtypes = (ctypes.c_int * 1)(0)
    out = np.zeros(out_elems, np.float32)
    out_shape = (ctypes.c_longlong * 8)()
    out_ndim = ctypes.c_int(0)
    rc = lib.pti_forward(
        h, inputs, shapes, ndims, dtypes, 1, 0,
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        out_elems, out_shape, ctypes.byref(out_ndim))
    assert rc >= 0, lib.pti_last_error().decode()
    shape = tuple(out_shape[i] for i in range(out_ndim.value))
    return out[:rc].reshape(shape)


def test_capi_create_forward_destroy(tmp_path):
    lib = _load()
    d, xs, ref = _export_model(tmp_path)
    h = lib.pti_create(d.encode())
    assert h, lib.pti_last_error().decode()
    got = _forward(lib, h, xs)
    np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-5)
    lib.pti_destroy(h)


def test_capi_create_bad_dir_reports_error():
    lib = _load()
    h = lib.pti_create(b"/nonexistent/model/dir")
    assert not h
    assert lib.pti_last_error()


def test_capi_multi_thread(tmp_path):
    """capi/examples/model_inference/multi_thread analog: concurrent
    forwards on one handle must all produce correct results."""
    lib = _load()
    d, xs, ref = _export_model(tmp_path)
    h = lib.pti_create(d.encode())
    assert h, lib.pti_last_error().decode()
    errs = []

    def worker():
        try:
            for _ in range(5):
                got = _forward(lib, h, xs)
                np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-5)
        except Exception as e:   # noqa: BLE001
            errs.append(e)

    threads = [threading.Thread(target=worker) for _ in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errs, errs
    lib.pti_destroy(h)


def test_capi_small_buffer_reports_size(tmp_path):
    lib = _load()
    d, xs, _ = _export_model(tmp_path)
    h = lib.pti_create(d.encode())
    buf = np.ascontiguousarray(xs)
    inputs = (ctypes.c_void_p * 1)(buf.ctypes.data)
    shapes = (ctypes.c_longlong * 2)(*buf.shape)
    ndims = (ctypes.c_int * 1)(2)
    dtypes = (ctypes.c_int * 1)(0)
    out = np.zeros(1, np.float32)
    out_shape = (ctypes.c_longlong * 8)()
    out_ndim = ctypes.c_int(0)
    rc = lib.pti_forward(
        h, inputs, shapes, ndims, dtypes, 1, 0,
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)), 1,
        out_shape, ctypes.byref(out_ndim))
    assert rc == -2          # too small; shape still reported for retry
    assert tuple(out_shape[i] for i in range(out_ndim.value)) == (3, 2)
    lib.pti_destroy(h)


def _build_and_run_c_example(tmp_path, name, argv, extra_cc=()):
    """Compile native/examples/<name>.c against the capi .so and run it as
    its own process (its own embedded-CPython init — ensure_python's cold
    path). Skips when the toolchain or library is missing."""
    import shutil
    import subprocess

    _load()   # skip if lib not built
    if shutil.which("gcc") is None:
        pytest.skip("no C toolchain")
    src = os.path.join(REPO, "native", "examples", name + ".c")
    exe = str(tmp_path / name)
    lib_dir = os.path.join(REPO, "native")
    cc = subprocess.run(
        ["gcc", src, "-o", exe, *extra_cc, "-L" + lib_dir,
         "-lpaddle_tpu_capi"],
        capture_output=True, text=True)
    assert cc.returncode == 0, cc.stderr
    env = dict(os.environ)
    env["LD_LIBRARY_PATH"] = lib_dir + ":" + env.get("LD_LIBRARY_PATH", "")
    env["PYTHONPATH"] = REPO + ":" + env.get("PYTHONPATH", "")
    env["JAX_PLATFORMS"] = "cpu"
    return subprocess.run([exe, *argv], env=env, cwd=REPO,
                          capture_output=True, text=True, timeout=300)


def test_c_example_program_standalone(tmp_path):
    """capi/examples/model_inference/dense analog: a REAL C program compiled
    with gcc, linked against the capi .so, output compared to the in-process
    executor."""
    d, _, _ = _export_model(tmp_path)
    n, dim = 3, 4
    out = _build_and_run_c_example(tmp_path, "infer_dense",
                                   [d, str(n), str(dim)])
    assert out.returncode == 0, out.stdout + out.stderr
    rows = [list(map(float, line.split()))
            for line in out.stdout.strip().splitlines()]
    assert len(rows) == n and len(rows[0]) == 2

    # compare against the same inputs through the Python host. The C
    # program's embedded interpreter runs on the DEFAULT platform (the real
    # TPU under the driver — the image's sitecustomize ignores JAX_PLATFORMS
    # env) while this test process is pinned to CPU, so tolerances are the
    # cross-backend matmul kind (TensorCheck tiering, SURVEY §7).
    from paddle_tpu.runtime.capi_host import InferenceHost
    x = (np.arange(n * dim) % 7).astype(np.float32) * 0.1 - 0.3
    ref = InferenceHost(d).run([x.reshape(n, dim)])
    np.testing.assert_allclose(np.asarray(rows), ref, rtol=5e-2, atol=5e-3)


def _export_sequence_model(tmp_path, vocab=40, emb=8, max_len=6):
    """Lengths-carrying text classifier: embedding -> masked average pool
    (padding ids must NOT leak into the pool) -> fc. The lengths slot is the
    second feed, as an i32 vector — the TPU-native LoD encoding."""
    ids = fluid.layers.data("ids", shape=(max_len,), dtype="int32")
    lens = fluid.layers.data("lens", shape=(), dtype="int32")
    emb_out = fluid.layers.embedding(ids, size=(vocab, emb))
    pooled = fluid.layers.sequence_pool(emb_out, lens, pool_type="average")
    out = fluid.layers.fc(pooled, 3)
    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())
    d = str(tmp_path / "seq_model")
    fluid.io.export_inference_model(d, ["ids", "lens"], [out], exe)
    return d


def test_c_example_sequence(tmp_path):
    """capi/examples/model_inference/sequence analog: ragged int32 sequences
    with a true-lengths slot through the C ABI; results must match the
    in-process executor on identical inputs (so the padded tail is provably
    masked)."""
    batch, max_len, vocab = 3, 6, 40
    d = _export_sequence_model(tmp_path, vocab=vocab, max_len=max_len)
    out = _build_and_run_c_example(tmp_path, "infer_sequence",
                                   [d, str(batch), str(max_len), str(vocab)])
    assert out.returncode == 0, out.stdout + out.stderr
    rows = [list(map(float, line.split()))
            for line in out.stdout.strip().splitlines()]
    assert len(rows) == batch and len(rows[0]) == 3

    # same deterministic inputs as the C program builds
    ids = np.zeros((batch, max_len), np.int32)
    lens = np.zeros((batch,), np.int32)
    for b in range(batch):
        n = max(1, max_len - b)
        lens[b] = n
        for t in range(n):
            ids[b, t] = (b * 31 + t * 7) % vocab
    from paddle_tpu.runtime.capi_host import InferenceHost
    ref = InferenceHost(d).run([ids, lens])
    # cross-backend tolerance: the C process runs on the default platform
    np.testing.assert_allclose(np.asarray(rows), ref, rtol=5e-2, atol=5e-3)


def _export_sparse_binary_model(tmp_path, dim=50, emb=6, max_nnz=5):
    """Multi-hot classifier: active-feature ids + nnz counts -> embedded
    row SUM (the weighted-row-sum sparse-fc path) -> fc."""
    ids = fluid.layers.data("ids", shape=(max_nnz,), dtype="int32")
    counts = fluid.layers.data("counts", shape=(), dtype="int32")
    emb_out = fluid.layers.embedding(ids, size=(dim, emb))
    summed = fluid.layers.sequence_pool(emb_out, counts, pool_type="sum")
    out = fluid.layers.fc(summed, 2)
    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())
    d = str(tmp_path / "sb_model")
    fluid.io.export_inference_model(d, ["ids", "counts"], [out], exe)
    return d


def test_c_example_sparse_binary(tmp_path):
    """capi/examples/model_inference/sparse_binary analog: multi-hot rows
    as padded index lists + counts through the C ABI; results must match
    the in-process executor (padding indices provably masked)."""
    batch, max_nnz, dim = 4, 5, 50
    d = _export_sparse_binary_model(tmp_path, dim=dim, max_nnz=max_nnz)
    out = _build_and_run_c_example(
        tmp_path, "infer_sparse_binary",
        [d, str(batch), str(max_nnz), str(dim)])
    assert out.returncode == 0, out.stdout + out.stderr
    rows = [list(map(float, line.split()))
            for line in out.stdout.strip().splitlines()]
    assert len(rows) == batch and len(rows[0]) == 2

    ids = np.zeros((batch, max_nnz), np.int32)
    counts = np.zeros((batch,), np.int32)
    for b in range(batch):
        nnz = max_nnz - (b % max_nnz)
        counts[b] = nnz
        for j in range(nnz):
            ids[b, j] = (b * 13 + j * 5) % dim
    from paddle_tpu.runtime.capi_host import InferenceHost
    ref = InferenceHost(d).run([ids, counts])
    np.testing.assert_allclose(np.asarray(rows), ref, rtol=5e-2, atol=5e-3)


def test_c_example_multi_thread(tmp_path):
    """capi/examples/model_inference/multi_thread analog: a REAL pthread C
    program — 4 threads x 5 forwards on one shared handle must all bit-match
    the single-threaded reference forward."""
    d, _, _ = _export_model(tmp_path)
    out = _build_and_run_c_example(
        tmp_path, "infer_multi_thread", [d, "4", "5", "3", "4"],
        extra_cc=("-pthread",))
    assert out.returncode == 0, out.stdout + out.stderr
    assert out.stdout.strip().splitlines()[-1] == "OK 4x5"
