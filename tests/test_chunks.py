"""Reader<->chunk bridge + cloud reader end-to-end (the distributed data
plane: dump -> master shards chunks -> consumers stream, with failure
re-dispatch)."""

import numpy as np
import pytest

from paddle_tpu.runtime import native_available

pytestmark = pytest.mark.skipif(not native_available(),
                                reason="native toolchain unavailable")

from paddle_tpu.data.chunks import chunk_reader, cloud_reader, dump_to_chunks  # noqa: E402
from paddle_tpu.data.dataset import mnist  # noqa: E402
from paddle_tpu.runtime.master_service import MasterClient, MasterServer  # noqa: E402


def test_dump_and_chunk_reader_roundtrip(tmp_path):
    paths = dump_to_chunks(mnist.train(100), str(tmp_path),
                           samples_per_chunk=32)
    assert len(paths) == 4                      # 32+32+32+4
    back = list(chunk_reader(paths)())
    orig = list(mnist.train(100)())
    assert len(back) == 100
    np.testing.assert_allclose(back[0][0], orig[0][0])
    assert back[50][1] == orig[50][1]


def test_cloud_reader_full_pass_and_redispatch(tmp_path):
    paths = dump_to_chunks(mnist.train(64), str(tmp_path),
                           samples_per_chunk=16)
    srv = MasterServer(timeout_s=0.5, failure_max=3, tick_interval=0.1).start()
    try:
        c0 = MasterClient(*srv.address)
        c0.set_dataset(paths)
        # consumer A takes a task and dies
        dead = c0.get_task()
        c0.close()
        # consumer B streams the whole pass, incl. the re-dispatched chunk
        cb = MasterClient(*srv.address)
        samples = list(cloud_reader(cb)())
        assert len(samples) == 64
    finally:
        srv.stop()


def test_cloud_reader_skips_corrupt_chunk(tmp_path):
    paths = dump_to_chunks(mnist.train(48), str(tmp_path),
                           samples_per_chunk=16)
    # corrupt the middle chunk's payload
    raw = bytearray(open(paths[1], "rb").read())
    raw[20] ^= 0xFF
    open(paths[1], "wb").write(bytes(raw))
    srv = MasterServer(timeout_s=5.0, failure_max=2, tick_interval=0.1).start()
    try:
        c = MasterClient(*srv.address)
        c.set_dataset(paths)
        samples = list(cloud_reader(c)())
        # the corrupt chunk is retried then discarded; the rest arrives
        assert len(samples) == 32
        assert c.stats()[3] == 1               # one discarded task
    finally:
        srv.stop()
