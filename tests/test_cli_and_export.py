"""CLI + inference-export tests (paddle CLI submit_local.sh.in job parity;
merged inference model of MergeModel.cpp/capi)."""

import json
import os
import subprocess
import sys
import textwrap

import numpy as np
import pytest

import paddle_tpu.fluid as fluid

CONFIG = textwrap.dedent("""
    import paddle_tpu.v2 as paddle
    from paddle_tpu.data.dataset import uci_housing

    x = paddle.layer.data("x", paddle.data_type.dense_vector(13))
    y = paddle.layer.data("y", paddle.data_type.dense_vector(1))
    pred = paddle.layer.fc(x, 1)
    cost = paddle.layer.square_error_cost(pred, y)
    optimizer = paddle.optimizer.SGD(0.05)
    train_reader = paddle.batch(uci_housing.train(128), 32)
    test_reader = paddle.batch(uci_housing.test(64), 32)
    feeding = [x, y]
    outputs = [pred]
""")


@pytest.fixture
def config_file(tmp_path):
    p = tmp_path / "cfg.py"
    p.write_text(CONFIG)
    return str(p)


def _run(*argv):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    r = subprocess.run([sys.executable, "-m", "paddle_tpu", *argv],
                       capture_output=True, text=True, env=env, timeout=240)
    assert r.returncode == 0, r.stderr[-2000:]
    return r.stdout


def test_cli_version():
    out = _run("version")
    assert "paddle_tpu" in out


def test_cli_serve_bad_flags_structured_error():
    """`serve` answers an invalid flag combination (page_block off the
    max_len grid) with the same structured stderr + exit 2 as a bad
    --config, not a construction traceback."""
    r = subprocess.run([sys.executable, "-m", "paddle_tpu", "serve",
                        "--vocab", "67", "--d_model", "16",
                        "--n_heads", "2", "--n_layers", "1",
                        "--max_len", "128", "--page_block", "48"],
                       capture_output=True, text=True, timeout=240)
    assert r.returncode == 2
    assert "serve: page_block 48" in r.stderr
    assert "Traceback" not in r.stderr
    # a bind failure (port already in use) gets the same structured
    # refusal, not a traceback with a half-started engine behind it
    import socket
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    s.listen(1)
    try:
        r = subprocess.run([sys.executable, "-m", "paddle_tpu", "serve",
                            "--vocab", "67", "--d_model", "16",
                            "--n_heads", "2", "--n_layers", "1",
                            "--max_len", "128",
                            "--port", str(s.getsockname()[1])],
                           capture_output=True, text=True, timeout=240)
    finally:
        s.close()
    assert r.returncode == 2
    assert "serve: cannot bind" in r.stderr
    assert "Traceback" not in r.stderr


def test_lint_bench_rows_schema(tmp_path):
    """`paddle_tpu lint --bench-rows` (no --config needed): well-formed
    rows pass; a row missing its family's roofline column (mfu for
    *_train_*, hbm_bw_util for *_decode_*) or a required key fails with
    B001 findings — malformed rows die in CI, not in the trend data."""
    import json

    good = tmp_path / "good.jsonl"
    good.write_text(
        json.dumps({"metric": "x_train_ms_per_batch", "value": 1.0,
                    "unit": "ms", "vs_baseline": None, "mfu": 0.2,
                    "methodology": "measured",
                    "plan_source": "heuristic"}) + "\n"
        + json.dumps({"metric": "z_serve_daemon_tokens_per_sec",
                      "value": 9.0, "unit": "tok/s", "vs_baseline": None,
                      "ttft_p50_ms": 12.0, "tpot_p50_ms": 3.0,
                      "methodology": "measured"}) + "\n"
        + json.dumps({"metric": "r_route_disagg_tokens_per_sec",
                      "value": 7.0, "unit": "tok/s", "vs_baseline": None,
                      "ttft_p50_ms": 20.0, "tpot_p50_ms": 4.0,
                      "n_decode_workers": 2,
                      "ttft_breakdown": {"queued": 1.0, "prefill": 12.0,
                                         "ship": 4.0, "adopt": 2.0}})
        + "\n")
    bad = tmp_path / "bad.jsonl"
    bad.write_text(
        json.dumps({"metric": "y_decode_tokens_per_sec", "value": 5.0,
                    "unit": "tok/s", "vs_baseline": None}) + "\n"
        + json.dumps({"metric": "z_serve_daemon_tokens_per_sec",
                      "value": 9.0, "unit": "tok/s",
                      "vs_baseline": None}) + "\n"
        + json.dumps({"metric": "w_train_ms_per_batch", "value": 1.0,
                      "unit": "ms", "vs_baseline": None, "mfu": 0.2,
                      "methodology": "guessed",
                      "plan_source": "vibes"}) + "\n"
        + json.dumps({"metric": "r_route_disagg_tokens_per_sec",
                      "value": 7.0, "unit": "tok/s", "vs_baseline": None,
                      "ttft_p50_ms": 20.0, "tpot_p50_ms": 4.0}) + "\n")
    out = _run("lint", "--bench-rows", str(good))
    assert "0 problem(s)" in out
    r = subprocess.run([sys.executable, "-m", "paddle_tpu", "lint",
                        "--bench-rows", str(bad)],
                       capture_output=True, text=True, timeout=240)
    assert r.returncode == 1
    assert "B001" in r.stdout and "hbm_bw_util" in r.stdout
    # the _serve_ family rule (PR 8): a serving row without its SLO pair
    # (ttft_p50_ms / tpot_p50_ms) is rejected
    assert "ttft_p50_ms" in r.stdout and "tpot_p50_ms" in r.stdout
    # methodology is required on roofline/SLO rows and must be one of
    # measured|modeled — on-chip vs projected stays distinguishable
    assert "methodology" in r.stdout and "guessed" in r.stdout
    # plan_source is required on _train_/_decode_ rows (tuned-vs-heuristic
    # deltas stay machine-checkable) and must be tuned|heuristic
    assert "plan_source" in r.stdout and "vibes" in r.stdout
    # the _route_ family rule (disaggregated serving): a routed row
    # without the fleet size it was spread over is not comparable, and
    # without its phase-decomposed TTFT (request-timeline ledger) a
    # routed-TTFT regression can't name which hop moved
    assert "n_decode_workers" in r.stdout
    assert "ttft_breakdown" in r.stdout


def test_cli_train_test_time_dump(config_file, tmp_path):
    save = str(tmp_path / "out")
    cc = str(tmp_path / "compile_cache")
    out = _run("train", "--config", config_file, "--num_passes", "2",
               "--save_dir", save, "--log_period", "2",
               "--compile_cache", cc)
    # --compile_cache wired through paddle_tpu.enable_compile_cache: the
    # run persists its XLA executables for a preemption-resume to reload
    assert os.path.isdir(cc) and os.listdir(cc)
    assert "pass 1 done" in out
    assert os.path.exists(os.path.join(save, "pass-00001", "params.tar"))
    assert os.path.exists(os.path.join(save, "inference", "model.json"))

    out = _run("test", "--config", config_file, "--init_model_path",
               os.path.join(save, "pass-00001", "params.tar"))
    assert json.loads(out.strip().splitlines()[-1])["cost"] >= 0

    out = _run("time", "--config", config_file, "--iters", "4")
    assert json.loads(out.strip().splitlines()[-1])["ms_per_batch"] > 0

    out = _run("dump_config", "--config", config_file)
    d = json.loads(out)
    assert d["blocks"][0]["ops"]


def test_cli_train_local_master(config_file, tmp_path):
    """One-binary bring-up (TrainerMain.cpp:32-49 --start_pserver analog):
    one `train --local_master` process self-hosts the task-master RPC plane
    and trains from it, multi-pass, same artifacts as a plain train.
    ``--obs_out`` rides along: the run arms a flight recorder, obs_pushes
    its snapshots to the in-process master, and leaves a dump the obs CLI
    reads back (the ISSUE 4 smoke)."""
    from paddle_tpu.runtime import native_available
    if not native_available():
        pytest.skip("native task master not built")
    save = str(tmp_path / "out")
    obs_out = str(tmp_path / "run.jsonl")
    out = _run("train", "--config", config_file, "--num_passes", "2",
               "--save_dir", save, "--local_master",
               "--samples_per_chunk", "2", "--obs_out", obs_out)
    assert "local master:" in out            # chunks really dispatched
    assert "pass 1 done" in out              # second pass got data
    assert os.path.exists(os.path.join(save, "pass-00001", "params.tar"))
    assert "observability dump written" in out
    from paddle_tpu import obs
    dump = obs.read_jsonl(obs_out)
    # clean exit: the FULL session dump superseded the flight ring
    assert not dump["meta"].get("flight")
    names = {m["name"] for m in dump["metrics"]}
    # the v2 CLI trainer drives the fluid Executor + RPC data plane
    assert "fluid.runs_total" in names
    assert "rpc.calls_total" in names
    # the obs_push path really ran against the in-process master
    assert "obs.pushes_total" in names
    assert "master.requests_total" in names
    out = _run("obs", "summary", "--input", obs_out)
    assert "fluid.runs_total" in out
    out = _run("obs", "export", "--input", obs_out, "--format", "prom")
    assert "paddle_tpu_fluid_runs_total" in out


def test_export_load_inference_model(tmp_path):
    fluid.reset_default_programs()
    fluid.executor._global_scope = fluid.Scope()
    x = fluid.layers.data("x", shape=(4,))
    h = fluid.layers.fc(x, 8, act="tanh")
    out = fluid.layers.fc(h, 2)
    loss = fluid.layers.mean(out)
    fluid.SGDOptimizer(0.1).minimize(loss)   # training ops present
    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())
    xs = np.ones((3, 4), np.float32)
    d = str(tmp_path / "model")
    fluid.io.export_inference_model(d, ["x"], [out], exe)
    # reference forward via the pruned program (running the full training
    # block would also fire the sgd op and mutate params)
    infer_prog = fluid.default_main_program().prune([out.name])
    ref = exe.run(infer_prog, feed={"x": xs}, fetch_list=[out])[0]

    # fresh scope + executor; the loaded program must not contain training ops
    exe2 = fluid.Executor(scope=fluid.Scope())
    prog, feeds, fetches = fluid.io.load_inference_model(d, exe2)
    assert feeds == ["x"] and fetches == [out.name]
    types = {op.type for op in prog.global_block().ops}
    assert "autodiff_grad" not in types and "sgd" not in types
    got = exe2.run(prog, feed={"x": xs}, fetch_list=fetches)[0]
    np.testing.assert_allclose(got, ref, rtol=1e-6)


def test_export_keeps_lstm_fused_auto(tmp_path):
    """Inference bundles leave recurrent ops on fused=auto: the runtime
    picks the Pallas whole-sequence kernel for small latency-bound batches
    and XLA's scan for large ones (the measured crossover is documented in
    docs/design/fused_rnn_bench.md). An explicit fused attr would pin one
    path for every deployment batch size — exactly what the bench showed
    to be wrong."""
    import json

    import numpy as np

    from paddle_tpu.v2 import layer as L
    from paddle_tpu.v2.data_type import dense_vector_sequence

    fluid.reset_default_programs()
    x = L.data("x", dense_vector_sequence(4))
    h = L.lstmemory(x, 6)
    out = L.last_seq(h)
    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())
    d = str(tmp_path / "m")
    fluid.io.export_inference_model(d, ["x", "x__len__"], [out.var], exe)

    meta = json.load(open(d + "/model.json"))
    lstm_ops = [op for blk in meta["program"]["blocks"]
                for op in blk["ops"] if op["type"] == "lstm"]
    assert lstm_ops and all("fused" not in op["attrs"] for op in lstm_ops)

    # loaded bundle still computes the same numbers (kernel == scan math)
    exe2 = fluid.Executor()
    prog, feeds, fetches = fluid.io.load_inference_model(d, exe2)
    xs = np.random.RandomState(0).randn(3, 5, 4).astype(np.float32)
    lens = np.array([5, 3, 2], np.int32)
    got = exe2.run(prog, feed={"x": xs, "x__len__": lens},
                   fetch_list=fetches)[0]
    ref = exe.run(fluid.default_main_program().prune([out.var.name]),
                  feed={"x": xs, "x__len__": lens},
                  fetch_list=[out.var.name])[0]
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=1e-6, atol=1e-6)


def test_mnist_lenet_example_config(tmp_path):
    """examples/mnist_lenet.py (v1_api_demo/mnist analog) trains through
    the CLI; with PADDLE_TPU_MNIST_DIR unset it uses the synthetic
    fallback (the real-idx path is covered by test_data_parsers)."""
    cfg = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "examples", "mnist_lenet.py")
    out = _run("train", "--config", cfg, "--num_passes", "1",
               "--log_period", "16")
    assert "pass 0 done" in out


def test_traffic_prediction_example_config(tmp_path):
    """examples/traffic_prediction.py (v1_api_demo/traffic_prediction
    analog): LSTM time-series regression trains through the CLI."""
    cfg = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "examples", "traffic_prediction.py")
    out = _run("train", "--config", cfg, "--num_passes", "1",
               "--log_period", "8")
    assert "pass 0 done" in out


@pytest.mark.slow
def test_gan_vae_example_smoke():
    """examples/gan_vae_mnist.py (v1_api_demo/{gan,vae} analog): both
    demos train mechanically on short budgets.

    slow: ~13s example smoke; the generative-model substance is tier-1
    in tests/test_generative.py and the example-runner plumbing in the
    sibling example smokes (PR 7 precedent: sequence_tagging/serving_llm
    demotions; PR 12 --durations=25 triage)."""
    import importlib
    mod = importlib.import_module("examples.gan_vae_mnist")
    mod.train_gan(steps=40)
    mod.train_vae(steps=150)


def test_model_zoo_features_example():
    """examples/model_zoo_features.py (v1_api_demo/model_zoo analog):
    params-tar round trip into a fresh topology + multi-layer feature
    fetch; consumer predictions match the publisher."""
    import importlib
    mod = importlib.import_module("examples.model_zoo_features")
    mod.main()


def test_cluster_train_num_workers_warning_sentinel():
    """--hosts mode warns on ANY explicitly-passed --num_workers —
    including the old default value 2 (the sentinel is now None, resolved
    to 2 only in local mode; ADVICE r5)."""
    env = dict(os.environ, JAX_PLATFORMS="cpu")

    def run(*extra):
        return subprocess.run(
            [sys.executable, "-m", "paddle_tpu", "cluster_train", "s.py",
             "--hosts", "h1,h2", "--dry-run", *extra],
            capture_output=True, text=True, env=env, timeout=120)

    r = run("--num_workers", "2")
    assert r.returncode == 0
    assert "ignoring --num_workers 2" in r.stderr
    r = run("--num_workers", "5")
    assert "ignoring --num_workers 5" in r.stderr
    r = run()                                    # not passed: no warning
    assert r.returncode == 0
    assert "ignoring --num_workers" not in r.stderr
    assert len([l for l in r.stdout.splitlines() if l.strip()]) == 2
