"""cluster_train launcher (scripts/cluster_train/paddle.py / fabric/openmpi
analogs): N workers join one jax.distributed job via PADDLE_TPU_* env and
train data-parallel; worker failure tears the job down."""

import os
import sys

from paddle_tpu.cli import main as cli_main

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SCRIPT = os.path.join(REPO, "tests", "cluster_train_script.py")


def test_cluster_train_two_workers():
    from conftest import require_multiprocess_cpu
    require_multiprocess_cpu()
    rc = cli_main(["cluster_train", SCRIPT, "--num_workers", "2",
                   "--devices_per_worker", "2", "--timeout", "240"])
    assert rc == 0


def test_cluster_train_propagates_failure(tmp_path):
    bad = tmp_path / "bad.py"
    bad.write_text("import sys; sys.exit(3)\n")
    rc = cli_main(["cluster_train", str(bad), "--num_workers", "2",
                   "--devices_per_worker", "1", "--timeout", "60"])
    assert rc != 0


def test_cluster_restart_on_failure_resumes_and_matches(tmp_path, monkeypatch):
    """Elastic recovery (go/master/service.go:311-321 trainers-as-stateless-
    consumers): rank 1 SIGKILLs itself mid-job on attempt 0; with
    --restart-on-failure the launcher relaunches the whole job on a fresh
    coordinator, workers resume from the latest pass checkpoint, training
    completes (rc 0), and the final params are numerically IDENTICAL to an
    uninterrupted run's."""
    import subprocess

    import numpy as np

    from conftest import require_multiprocess_cpu
    require_multiprocess_cpu()

    script = os.path.join(REPO, "tests", "cluster_restart_script.py")
    kill_dir = tmp_path / "killed"
    kill_dir.mkdir()
    monkeypatch.setenv("RESTART_TEST_DIR", str(kill_dir))
    rc = cli_main(["cluster_train", script, "--num_workers", "2",
                   "--devices_per_worker", "1", "--timeout", "240",
                   "--grace", "20", "--restart-on-failure", "2"])
    assert rc == 0
    assert (kill_dir / "final.npz").exists()

    # uninterrupted reference run (single worker process, same global math)
    ref_dir = tmp_path / "ref"
    ref_dir.mkdir()
    env = dict(os.environ, RESTART_TEST_DIR=str(ref_dir),
               PADDLE_TPU_RESTART_COUNT="1")   # never self-kill
    for k in ("PADDLE_TPU_COORDINATOR", "PADDLE_TPU_NUM_PROCESSES",
              "PADDLE_TPU_PROCESS_ID"):
        env.pop(k, None)
    subprocess.run([sys.executable, script], env=env, check=True,
                   timeout=240)
    got = np.load(kill_dir / "final.npz")
    ref = np.load(ref_dir / "final.npz")
    # 2-process Gloo reduction order vs single-process: f32 noise only
    np.testing.assert_allclose(got["w"], ref["w"], rtol=1e-5, atol=1e-7)
    np.testing.assert_allclose(got["b"], ref["b"], rtol=1e-5, atol=1e-7)


def test_cluster_worker_death_reaps_job_cleanly(tmp_path, monkeypatch):
    """Host-death behavior (doc/design/cluster_train/README.md
    trainer-as-stateless-task-consumer): SIGKILL one worker mid-run; the
    launcher must reap the job promptly (well inside --timeout) with a
    nonzero rc, and the SURVIVOR must exit through the clean teardown path
    (its on_job_teardown hook ran => checkpoint marker written) — not be
    SIGKILLed. The dead worker, by construction, never reaches its hook."""
    import time

    from conftest import require_multiprocess_cpu
    require_multiprocess_cpu()

    script = os.path.join(REPO, "tests", "cluster_death_script.py")
    monkeypatch.setenv("DEATH_TEST_DIR", str(tmp_path))
    t0 = time.time()
    rc = cli_main(["cluster_train", script, "--num_workers", "2",
                   "--devices_per_worker", "1", "--timeout", "240",
                   "--grace", "20"])
    elapsed = time.time() - t0
    assert rc != 0                      # the SIGKILLed worker's rc propagates
    assert elapsed < 120, elapsed       # reaped on death, not on timeout
    assert (tmp_path / "clean-exit-0").exists()      # survivor's hook ran
    assert not (tmp_path / "clean-exit-1").exists()  # dead worker's did not


# ---------------------------------------------------------------------------
# multi-host mode (--hosts/--hostfile/--ssh-template): the ssh/fabric
# launcher capability (scripts/cluster_train/paddle.py job_prepare+job_start)
# re-targeted at jax.distributed membership env.
# ---------------------------------------------------------------------------

def test_cluster_train_hosts_dry_run_renders_commands(capsys):
    rc = cli_main(["cluster_train", "/job/train.py", "lr=0.1",
                   "--hosts", "tpu-a,tpu-b,tpu-c",
                   "--coordinator-port", "7164",
                   "--dry-run"])
    assert rc == 0
    lines = capsys.readouterr().out.strip().splitlines()
    assert len(lines) == 3
    for i, (line, host) in enumerate(zip(lines, ["tpu-a", "tpu-b", "tpu-c"])):
        assert line.startswith(f"ssh {host} ")
        # every node: same coordinator (node 0's host), its own process id
        assert "PADDLE_TPU_COORDINATOR=tpu-a:7164" in line
        assert "PADDLE_TPU_NUM_PROCESSES=3" in line
        assert f"PADDLE_TPU_PROCESS_ID={i}" in line
        assert "python3 /job/train.py lr=0.1" in line


def test_cluster_train_hosts_user_at_host_and_job_marker(capsys):
    """ssh login prefixes (user@host) must NOT leak into the coordinator
    address, and every rendered command must carry the PADDLE_TPU_JOB_ID
    marker that makes the remote job reapable by pkill."""
    rc = cli_main(["cluster_train", "train.py",
                   "--hosts", "ubuntu@tpu-a,ubuntu@tpu-b",
                   "--dry-run"])
    assert rc == 0
    lines = capsys.readouterr().out.strip().splitlines()
    assert len(lines) == 2
    for line in lines:
        assert "PADDLE_TPU_COORDINATOR=tpu-a:7164" in line   # no ubuntu@
        assert "PADDLE_TPU_JOB_ID=" in line
        assert "trap" in line                                # TERM forwarder
    assert lines[0].startswith("ssh ubuntu@tpu-a ")


def test_cluster_train_hostfile_and_template(tmp_path, capsys):
    hf = tmp_path / "hosts"
    hf.write_text("# training pool\nnode-1\nnode-2   # rack 7\n\n")
    rc = cli_main(["cluster_train", "train.py",
                   "--hostfile", str(hf),
                   "--ssh-template", "ssh -p 2222 -i /keys/id {host} {cmd}",
                   "--remote-python", "/opt/py/bin/python",
                   "--dry-run"])
    assert rc == 0
    lines = capsys.readouterr().out.strip().splitlines()
    assert len(lines) == 2              # comments/blank lines stripped
    assert lines[0].startswith("ssh -p 2222 -i /keys/id node-1 ")
    assert lines[1].startswith("ssh -p 2222 -i /keys/id node-2 ")
    assert "PADDLE_TPU_COORDINATOR=node-1:7164" in lines[0]
    assert "/opt/py/bin/python train.py" in lines[0]


def test_cluster_train_hosts_executes_rendered_commands(tmp_path,
                                                        monkeypatch):
    """End-to-end through the multi-host path without ssh: a bash -c
    template runs each rendered command locally; the script records its
    membership env, proving the rendered commands really launch a
    consistent jax.distributed job spec."""
    out = tmp_path / "seen"
    out.mkdir()
    script = tmp_path / "record_env.py"
    script.write_text(
        "import os, pathlib\n"
        "d = os.environ['RECORD_DIR']\n"
        "i = os.environ['PADDLE_TPU_PROCESS_ID']\n"
        "pathlib.Path(d, f'node-{i}').write_text(\n"
        "    os.environ['PADDLE_TPU_COORDINATOR'] + ' ' +\n"
        "    os.environ['PADDLE_TPU_NUM_PROCESSES'])\n")
    monkeypatch.setenv("RECORD_DIR", str(out))
    rc = cli_main(["cluster_train", str(script),
                   "--hosts", "localhost,localhost",
                   "--ssh-template", "bash -c {cmd}",
                   "--remote-python", sys.executable,
                   "--timeout", "60"])
    assert rc == 0
    got = sorted(p.name for p in out.iterdir())
    assert got == ["node-0", "node-1"]
    for p in out.iterdir():
        assert p.read_text() == "localhost:7164 2"
