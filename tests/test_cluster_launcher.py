"""cluster_train launcher (scripts/cluster_train/paddle.py / fabric/openmpi
analogs): N workers join one jax.distributed job via PADDLE_TPU_* env and
train data-parallel; worker failure tears the job down."""

import os
import sys

from paddle_tpu.cli import main as cli_main

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SCRIPT = os.path.join(REPO, "tests", "cluster_train_script.py")


def test_cluster_train_two_workers():
    rc = cli_main(["cluster_train", SCRIPT, "--num_workers", "2",
                   "--devices_per_worker", "2", "--timeout", "240"])
    assert rc == 0


def test_cluster_train_propagates_failure(tmp_path):
    bad = tmp_path / "bad.py"
    bad.write_text("import sys; sys.exit(3)\n")
    rc = cli_main(["cluster_train", str(bad), "--num_workers", "2",
                   "--devices_per_worker", "1", "--timeout", "60"])
    assert rc != 0
