"""cluster_train launcher (scripts/cluster_train/paddle.py / fabric/openmpi
analogs): N workers join one jax.distributed job via PADDLE_TPU_* env and
train data-parallel; worker failure tears the job down."""

import os
import sys

from paddle_tpu.cli import main as cli_main

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SCRIPT = os.path.join(REPO, "tests", "cluster_train_script.py")


def test_cluster_train_two_workers():
    rc = cli_main(["cluster_train", SCRIPT, "--num_workers", "2",
                   "--devices_per_worker", "2", "--timeout", "240"])
    assert rc == 0


def test_cluster_train_propagates_failure(tmp_path):
    bad = tmp_path / "bad.py"
    bad.write_text("import sys; sys.exit(3)\n")
    rc = cli_main(["cluster_train", str(bad), "--num_workers", "2",
                   "--devices_per_worker", "1", "--timeout", "60"])
    assert rc != 0


def test_cluster_worker_death_reaps_job_cleanly(tmp_path, monkeypatch):
    """Host-death behavior (doc/design/cluster_train/README.md
    trainer-as-stateless-task-consumer): SIGKILL one worker mid-run; the
    launcher must reap the job promptly (well inside --timeout) with a
    nonzero rc, and the SURVIVOR must exit through the clean teardown path
    (its on_job_teardown hook ran => checkpoint marker written) — not be
    SIGKILLed. The dead worker, by construction, never reaches its hook."""
    import time

    script = os.path.join(REPO, "tests", "cluster_death_script.py")
    monkeypatch.setenv("DEATH_TEST_DIR", str(tmp_path))
    t0 = time.time()
    rc = cli_main(["cluster_train", script, "--num_workers", "2",
                   "--devices_per_worker", "1", "--timeout", "240",
                   "--grace", "20"])
    elapsed = time.time() - t0
    assert rc != 0                      # the SIGKILLed worker's rc propagates
    assert elapsed < 120, elapsed       # reaped on death, not on timeout
    assert (tmp_path / "clean-exit-0").exists()      # survivor's hook ran
    assert not (tmp_path / "clean-exit-1").exists()  # dead worker's did not
