"""Network lease/fence/blob coordination (runtime/coord.py) — the etcd
analog over TCP. These mirror the FileLease fencing tests
(test_master_service.py) with NO shared filesystem: the coordination
service runs in a SEPARATE PROCESS, leases are network TTLs judged by the
server clock, and the master snapshot lives in the fenced blob store
(go/master/etcd_client.go lease+revision semantics; go/master/service.go
snapshot-to-etcd)."""

from __future__ import annotations

import socket as _socket
import subprocess
import sys
import time

import pytest

from paddle_tpu.runtime import (CoordServer, NetworkFencedStore, NetworkLease)
from paddle_tpu.runtime.master_service import MasterClient, MasterServer


@pytest.fixture
def coord_proc():
    """CoordServer in its own process — a real network boundary."""
    p = subprocess.Popen(
        [sys.executable, "-m", "paddle_tpu.runtime.coord"],
        stdout=subprocess.PIPE, text=True)
    line = p.stdout.readline().split()
    assert line[0] == "LISTENING"
    try:
        yield line[1], int(line[2])
    finally:
        p.terminate()
        p.wait(timeout=10)


def free_port():
    s = _socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def test_network_lease_tokens_monotonic_and_ttl(coord_proc):
    """Acquire/release/expiry takeover across owners: strictly increasing
    fencing tokens, server-judged TTL."""
    host, port = coord_proc
    a = NetworkLease(host, port, owner="a", ttl=5.0)
    assert a.try_acquire()
    t1 = a.token
    assert t1 is not None and t1 >= 1
    assert a.held_by_me()
    assert a.current_token() == t1

    b = NetworkLease(host, port, owner="b", ttl=5.0)
    assert not b.try_acquire()            # a's lease is live
    a.release()
    assert a.token is None
    assert b.try_acquire()
    assert b.token > t1                   # monotonic across the release gap

    # expiry takeover (short TTL, no renewal) also bumps
    c = NetworkLease(host, port, owner="c", ttl=0.3)
    b.release()
    assert c.try_acquire()
    time.sleep(0.5)                       # c expires (never renewed)
    d = NetworkLease(host, port, owner="d", ttl=5.0)
    assert d.try_acquire()
    assert d.token > c.token
    for lease in (a, b, c, d):
        lease.close()


def test_master_failover_network_lease_no_shared_fs(coord_proc):
    """The failover-election scenario of
    test_master_failover_lease_election, with the lease AND the snapshot
    served over the network: master A dies without releasing; standby B
    waits out the TTL, restores the task state from the blob store, and the
    client's endpoint rotation makes it transparent. No path is shared."""
    host, port = coord_proc
    pa, pb = free_port(), free_port()

    lease_a = NetworkLease(host, port, owner="master-a", ttl=0.6)
    store_a = NetworkFencedStore(host, port)
    a = MasterServer(port=pa, snapshot_store=store_a, tick_interval=0.05,
                     lease=lease_a).start()
    client = MasterClient(endpoints=[("127.0.0.1", pa), ("127.0.0.1", pb)])
    try:
        client.set_dataset(["chunk-0", "chunk-1", "chunk-2"])
        t0 = client.get_task()
        assert t0 is not None
        time.sleep(0.2)                  # let a snapshot land in the store

        a.stop(release_lease=False)      # crash without releasing

        lease_b = NetworkLease(host, port, owner="master-b", ttl=0.6)
        assert not lease_b.try_acquire()           # A's TTL still running
        assert lease_b.wait_acquire(poll=0.1, timeout=10)
        store_b = NetworkFencedStore(host, port)
        b = MasterServer(port=pb, snapshot_store=store_b, tick_interval=0.05,
                         lease=lease_b).start()
        try:
            assert b.fence_token > a.fence_token
            seen = set()
            for _ in range(6):
                t = client.get_task()
                if t is None:
                    break
                seen.add(t[1])
                client.task_finished(t[0])
            assert seen == {"chunk-0", "chunk-1", "chunk-2"}
        finally:
            b.stop()
    finally:
        client.close()


def test_deposed_master_network_writes_are_fenced(coord_proc):
    """The GC-pause scenario of test_deposed_master_writes_are_fenced over
    the network: a master that stalls past its TTL finds both its snapshot
    puts and its mutating RPCs refused once the standby's higher token has
    claimed the blob."""
    host, port = coord_proc
    pa, pb = free_port(), free_port()

    lease_a = NetworkLease(host, port, owner="master-a", ttl=0.5)
    a = MasterServer(port=pa, snapshot_store=NetworkFencedStore(host, port),
                     tick_interval=60.0, lease=lease_a).start()
    ca = MasterClient("127.0.0.1", pa)
    try:
        ca.set_dataset(["chunk-0", "chunk-1"])
        assert a.try_snapshot()

        a._keeper.stop(release=False)    # paused: renewal stops
        a._keeper = None
        lease_b = NetworkLease(host, port, owner="master-b", ttl=5.0)
        deadline = time.time() + 10
        while not lease_b.try_acquire():
            assert time.time() < deadline
            time.sleep(0.1)

        store_b = NetworkFencedStore(host, port)
        b = MasterServer(port=pb, snapshot_store=store_b,
                         tick_interval=60.0, lease=lease_b).start()
        try:
            assert b.fence_token > a.fence_token
            assert b.try_snapshot()
            assert not a.try_snapshot()          # stale put refused
            assert store_b._recorded() == b.fence_token

            r = a._dispatch({"op": "set_dataset", "payloads": ["rogue"]})
            assert r["ok"] is False and "fenced" in r["error"]
            r = a._dispatch({"op": "task_finished", "task_id": 0})
            assert r["ok"] is False
            assert a._dispatch({"op": "stats"})["ok"] is True
        finally:
            b.stop()
    finally:
        ca.close()
        a.stop(release_lease=False)


def test_blob_store_roundtrip_and_fencing():
    """Blob put/get basics with in-process server: lower token refused after
    a higher token publishes."""
    srv = CoordServer().start()
    try:
        host, port = srv.address
        st = NetworkFencedStore(host, port, key="k")
        assert st.fetch_to("/dev/null") is False   # empty store
        assert st.write(3, lambda p: open(p, "w").write("gen3"))
        assert not st.write(2, lambda p: open(p, "w").write("stale"))
        import tempfile
        with tempfile.NamedTemporaryFile() as f:
            assert st.fetch_to(f.name)
            assert open(f.name).read() == "gen3"
        st.close()
    finally:
        srv.stop()


def test_coord_server_survives_hostile_frames():
    """The coordination service is a control-plane daemon every worker and
    standby master talks to: garbage JSON, unknown ops, truncated frames,
    and oversized length headers must never take it down — a well-formed
    client keeps working afterwards."""
    import json
    import socket
    import struct

    from paddle_tpu.runtime.coord import CoordServer, NetworkLease
    from paddle_tpu.runtime.master_service import _recv_exact

    srv = CoordServer().start()
    try:
        addr = srv.address

        def raw(payload: bytes, half_close: bool = False):
            s = socket.create_connection(addr, timeout=10.0)
            try:
                s.sendall(payload)
                if half_close:
                    s.shutdown(socket.SHUT_WR)
                hdr = _recv_exact(s, 4)
                if hdr is None:
                    return None
                (n,) = struct.unpack("<I", hdr)
                return _recv_exact(s, n)
            finally:
                s.close()

        def frame(obj) -> bytes:
            body = json.dumps(obj).encode()
            return struct.pack("<I", len(body)) + body

        # unknown op -> structured error
        r = json.loads(raw(frame({"op": "no_such_op"})))
        assert r["ok"] is False and "unknown op" in r["error"]
        # garbage JSON / truncated frame: the connection may drop, but the
        # server must survive each
        raw(struct.pack("<I", 12) + b"not-json-at!")
        raw(struct.pack("<I", 100) + b"short", half_close=True)
        # oversized length header: dropped WITHOUT attempting the
        # allocation (_recv_msg's _MAX_FRAME guard) — no reply
        assert raw(struct.pack("<I", 1 << 30), half_close=True) is None

        # ...and still serve a real client
        lease = NetworkLease(addr[0], addr[1], "jobs/master", owner="m-a",
                             ttl=5.0)
        try:
            assert lease.try_acquire()
            assert lease.holder()[0] == "m-a"
            lease.release()
        finally:
            lease.close()
    finally:
        srv.stop()
