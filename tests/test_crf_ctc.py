"""CRF and CTC tests: brute-force equivalence on tiny cases + gradient checks
(analog of gserver/tests/test_CRFLayerGrad.cpp, test_LinearChainCRF.cpp,
test_WarpCTCLayer.cpp)."""

import itertools

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.ops import crf, ctc
from op_test import check_grad
import pytest


def brute_force_log_norm(em, start, end, trans, length):
    """Enumerate all tag paths (tiny N, T)."""
    N = em.shape[-1]
    scores = []
    for path in itertools.product(range(N), repeat=length):
        s = start[path[0]] + em[0, path[0]]
        for t in range(1, length):
            s += trans[path[t - 1], path[t]] + em[t, path[t]]
        s += end[path[-1]]
        scores.append(s)
    m = max(scores)
    return m + np.log(sum(np.exp(np.array(scores) - m)))


def test_crf_log_norm_matches_brute_force(np_rng):
    N, T = 3, 4
    em = np_rng.randn(2, T, N).astype(np.float32)
    start = np_rng.randn(N).astype(np.float32)
    end = np_rng.randn(N).astype(np.float32)
    trans = np_rng.randn(N, N).astype(np.float32)
    lengths = np.array([4, 2], np.int32)
    logz = crf.crf_log_norm(jnp.asarray(em), jnp.asarray(lengths), start, end, trans)
    for b, L in enumerate(lengths):
        expect = brute_force_log_norm(em[b], start, end, trans, L)
        np.testing.assert_allclose(float(logz[b]), expect, rtol=1e-4)


def test_crf_decode_matches_brute_force(np_rng):
    N, T = 3, 4
    em = np_rng.randn(1, T, N).astype(np.float32)
    start = np_rng.randn(N).astype(np.float32)
    end = np_rng.randn(N).astype(np.float32)
    trans = np_rng.randn(N, N).astype(np.float32)
    lengths = np.array([T], np.int32)
    tags, score = crf.crf_decode(jnp.asarray(em), jnp.asarray(lengths), start, end, trans)
    # brute force best path
    best, best_s = None, -1e30
    for path in itertools.product(range(N), repeat=T):
        s = start[path[0]] + em[0, 0, path[0]]
        for t in range(1, T):
            s += trans[path[t - 1], path[t]] + em[0, t, path[t]]
        s += end[path[-1]]
        if s > best_s:
            best, best_s = path, s
    np.testing.assert_array_equal(np.asarray(tags[0]), np.array(best))
    np.testing.assert_allclose(float(score[0]), best_s, rtol=1e-4)


# slow: central-difference CRF grad (18s) — the registry numeric-gradient sweep
# covers linear_chain_crf grads in tier-1
@pytest.mark.slow
def test_crf_loss_grad(np_rng):
    N, T = 3, 3
    em = np_rng.randn(2, T, N).astype(np.float32)
    start = np_rng.randn(N).astype(np.float32)
    end = np_rng.randn(N).astype(np.float32)
    trans = np_rng.randn(N, N).astype(np.float32)
    tags = jnp.asarray(np_rng.randint(0, N, (2, T)).astype(np.int32))
    lengths = jnp.array([3, 2], jnp.int32)

    def f(e, s, en, tr):
        return jnp.sum(crf.crf_loss(e, tags, lengths, s, en, tr))

    check_grad(f, [em, start, end, trans], wrt=0)
    check_grad(f, [em, start, end, trans], wrt=3)


def brute_force_ctc(logp, label, blank=0):
    """Sum prob over all alignments, tiny T/V."""
    T, V = logp.shape
    total = 0.0
    for path in itertools.product(range(V), repeat=T):
        # collapse
        collapsed = []
        prev = None
        for p in path:
            if p != blank and p != prev:
                collapsed.append(p)
            prev = p
        if collapsed == list(label):
            total += np.exp(sum(logp[t, path[t]] for t in range(T)))
    return -np.log(total)


def test_ctc_matches_brute_force(np_rng):
    T, V = 4, 3
    logits = np_rng.randn(1, T, V).astype(np.float32)
    logp = np.asarray(jax.nn.log_softmax(jnp.asarray(logits), -1))
    labels = np.array([[1, 2]], np.int32)
    loss = ctc.ctc_loss(jnp.asarray(logp), jnp.array([T]), jnp.asarray(labels),
                        jnp.array([2]))
    expect = brute_force_ctc(logp[0], [1, 2])
    np.testing.assert_allclose(float(loss[0]), expect, rtol=1e-4)


# slow: central-difference CTC grad (22s) — the registry sweep covers warpctc
@pytest.mark.slow
def test_ctc_grad(np_rng):
    T, V = 4, 3
    logits = np_rng.randn(2, T, V).astype(np.float32) * 0.5
    labels = jnp.asarray(np.array([[1, 2], [2, 0]], np.int32))
    in_len = jnp.array([4, 3])
    lab_len = jnp.array([2, 1])

    def f(lg):
        lp = jax.nn.log_softmax(lg, -1)
        return jnp.sum(ctc.ctc_loss(lp, in_len, labels, lab_len))

    check_grad(f, [logits], wrt=0)


def test_ctc_greedy_decode():
    # path: [1, 1, 0, 2, 2] -> collapse -> [1, 2]
    V = 3
    logp = jnp.full((1, 5, V), -10.0)
    path = [1, 1, 0, 2, 2]
    logp = logp.at[0, jnp.arange(5), jnp.array(path)].set(0.0)
    toks, lens = ctc.ctc_greedy_decode(logp, jnp.array([5]))
    assert int(lens[0]) == 2
    np.testing.assert_array_equal(np.asarray(toks[0, :2]), [1, 2])
