"""Data-path tests (SURVEY.md §4.5: reader decorators, datasets, feeder)."""

import numpy as np
import pytest

from paddle_tpu import data as pdata
from paddle_tpu.core import SeqBatch
from paddle_tpu.data import (DataFeeder, DenseSlot, DoubleBuffer, IndexSlot,
                             SeqSlot, SparseSlot, batch, buffered, chain,
                             compose, firstn, map_readers, shuffle, xmap_readers)
from paddle_tpu.data.dataset import (cifar, conll05, criteo, imdb, imikolov,
                                     mnist, movielens, mq2007, uci_housing,
                                     wmt14)


def _r(xs):
    return lambda: iter(xs)


def test_reader_decorators():
    assert list(map_readers(lambda a, b: a + b, _r([1, 2]), _r([10, 20]))()) == [11, 22]
    assert sorted(shuffle(_r(range(10)), 4, seed=0)()) == list(range(10))
    assert list(chain(_r([1]), _r([2, 3]))()) == [1, 2, 3]
    assert list(compose(_r([1, 2]), _r([(3, 4), (5, 6)]))()) == [(1, 3, 4), (2, 5, 6)]
    assert list(buffered(_r(range(5)), 2)()) == list(range(5))
    assert list(firstn(_r(range(100)), 3)()) == [0, 1, 2]
    got = sorted(xmap_readers(lambda x: x * 2, _r(range(8)), 3, 4)())
    assert got == [0, 2, 4, 6, 8, 10, 12, 14]
    got = list(xmap_readers(lambda x: x * 2, _r(range(8)), 3, 4, order=True)())
    assert got == [0, 2, 4, 6, 8, 10, 12, 14]
    bs = list(batch(_r(range(7)), 3)())
    assert bs == [[0, 1, 2], [3, 4, 5], [6]]
    assert list(batch(_r(range(7)), 3, drop_last=True)()) == [[0, 1, 2], [3, 4, 5]]


def test_compose_misaligned_raises():
    with pytest.raises(ValueError):
        list(compose(_r([1]), _r([1, 2]))())


def test_buffered_propagates_errors():
    def bad():
        yield 1
        raise RuntimeError("boom")
    with pytest.raises(RuntimeError):
        list(buffered(lambda: bad(), 2)())


def test_feeder_dense_index_seq_sparse():
    feeder = DataFeeder([DenseSlot(3), IndexSlot(), SeqSlot(),
                         SparseSlot(100)])
    rows = [
        (np.ones(3), 1, [1, 2, 3], [4, 7]),
        (np.zeros(3), 0, [5], [9]),
    ]
    dense, idx, seq, (sp_ids, sp_vals) = feeder.feed(rows)
    assert dense.shape == (2, 3)
    assert idx.shape == (2,) and int(idx[0]) == 1
    assert isinstance(seq, SeqBatch)
    assert seq.data.shape[0] == 2 and int(seq.lengths[0]) == 3
    assert sp_ids.shape == sp_vals.shape and sp_ids.shape[0] == 2
    np.testing.assert_allclose(np.asarray(sp_vals[0])[:2], [1.0, 1.0])


def test_feeder_nested_seq():
    feeder = DataFeeder([SeqSlot(nested=True)])
    rows = [([[1, 2], [3]],), ([[4]],)]
    (nb,) = feeder.feed(rows)
    # 2-level LoD: [B, S, T] + sub/seq lengths (Argument.h:84-90 analog)
    assert nb.data.shape[:2] == (2, 2)
    np.testing.assert_array_equal(np.asarray(nb.seq_lengths), [2, 1])
    np.testing.assert_array_equal(np.asarray(nb.sub_lengths),
                                  [[2, 1], [1, 0]])
    np.testing.assert_array_equal(np.asarray(nb.data[0, 0, :2]), [1, 2])


def test_double_buffer_order_and_errors():
    out = list(DoubleBuffer(lambda: iter(range(10)), depth=3))
    assert out == list(range(10))
    def bad():
        yield 1
        raise ValueError("x")
    with pytest.raises(ValueError):
        list(DoubleBuffer(lambda: bad()))


@pytest.mark.parametrize("ds,checks", [
    (mnist, lambda s: (len(s[0]) == 784, 0 <= s[1] < 10)),
    (uci_housing, lambda s: (len(s[0]) == 13, len(s[1]) == 1)),
])
def test_dense_datasets(ds, checks):
    samples = list(firstn(ds.train(64), 5)())
    assert len(samples) == 5
    for s in samples:
        assert all(checks(s))
    # deterministic
    again = list(firstn(ds.train(64), 5)())
    np.testing.assert_allclose(again[0][0], samples[0][0])


def test_seq_datasets_schema():
    for ids, label in firstn(imdb.train(16), 4)():
        assert all(0 <= i < imdb.VOCAB for i in ids) and label in (0, 1)
    for tup in firstn(imikolov.train(16), 4)():
        assert len(tup) == 5
    for src, tin, tout in firstn(wmt14.train(16), 4)():
        assert len(tin) == len(tout) == len(src) + 1
        assert tin[0] == wmt14.START and tout[-1] == wmt14.END
    for words, tags in firstn(conll05.train(16), 4)():
        assert len(words) == len(tags)
    for u, g, a, j, m, cats, r in firstn(movielens.train(16), 4)():
        assert 1.0 <= r <= 5.0 and len(cats) >= 1
    for q, x, rel in firstn(mq2007.train(4), 4)():
        assert x.shape == (46,) and rel in (0, 1, 2)
    for dense, ids, y in firstn(criteo.train(16), 4)():
        assert len(dense) == 13 and len(ids) == 26 and y in (0, 1)
    for img, label in firstn(cifar.train10(8), 2)():
        assert len(img) == 3072
