"""Data-path tests (SURVEY.md §4.5: reader decorators, datasets, feeder)."""

import numpy as np
import pytest

from paddle_tpu import data as pdata
from paddle_tpu.core import SeqBatch
from paddle_tpu.data import (DataFeeder, DenseSlot, DoubleBuffer, IndexSlot,
                             SeqSlot, SparseSlot, batch, buffered, chain,
                             compose, firstn, map_readers, shuffle, xmap_readers)
from paddle_tpu.data.dataset import (cifar, conll05, criteo, imdb, imikolov,
                                     mnist, movielens, mq2007, uci_housing,
                                     wmt14)


def _r(xs):
    return lambda: iter(xs)


def test_reader_decorators():
    assert list(map_readers(lambda a, b: a + b, _r([1, 2]), _r([10, 20]))()) == [11, 22]
    assert sorted(shuffle(_r(range(10)), 4, seed=0)()) == list(range(10))
    assert list(chain(_r([1]), _r([2, 3]))()) == [1, 2, 3]
    assert list(compose(_r([1, 2]), _r([(3, 4), (5, 6)]))()) == [(1, 3, 4), (2, 5, 6)]
    assert list(buffered(_r(range(5)), 2)()) == list(range(5))
    assert list(firstn(_r(range(100)), 3)()) == [0, 1, 2]
    got = sorted(xmap_readers(lambda x: x * 2, _r(range(8)), 3, 4)())
    assert got == [0, 2, 4, 6, 8, 10, 12, 14]
    got = list(xmap_readers(lambda x: x * 2, _r(range(8)), 3, 4, order=True)())
    assert got == [0, 2, 4, 6, 8, 10, 12, 14]
    bs = list(batch(_r(range(7)), 3)())
    assert bs == [[0, 1, 2], [3, 4, 5], [6]]
    assert list(batch(_r(range(7)), 3, drop_last=True)()) == [[0, 1, 2], [3, 4, 5]]


def test_compose_misaligned_raises():
    with pytest.raises(ValueError):
        list(compose(_r([1]), _r([1, 2]))())


def test_buffered_propagates_errors():
    def bad():
        yield 1
        raise RuntimeError("boom")
    with pytest.raises(RuntimeError):
        list(buffered(lambda: bad(), 2)())


def test_pad_to_bucket_and_next_bucket():
    from paddle_tpu.data.feeder import BucketSpec, next_bucket, pad_to_bucket
    assert next_bucket(5, (8, 16)) == 8
    assert next_bucket(9, (8, 16)) == 16
    assert next_bucket(17, (8, 16)) == 32     # pow-2 overflow past the list
    assert next_bucket(3) == 4                # no list: pure pow-2
    arr = np.arange(10, dtype=np.float32).reshape(2, 5)
    padded, true_len = pad_to_bucket(arr, 1, (8,))
    assert padded.shape == (2, 8) and true_len == 5
    np.testing.assert_array_equal(padded[:, :5], arr)
    assert np.all(padded[:, 5:] == 0)
    same, n = pad_to_bucket(padded, 1, (8,))  # already on a bucket: no-op
    assert same is padded and n == 8
    spec = BucketSpec({"w": (8,), "x": {"axis": 0, "buckets": (4,)}})
    p, n = spec.pad("w", arr)                 # default axis 1 for rank-2
    assert p.shape == (2, 8) and n == 5
    p, n = spec.pad("x", arr)                 # pinned axis 0
    assert p.shape == (4, 5) and n == 2


def test_feeder_dense_index_seq_sparse():
    feeder = DataFeeder([DenseSlot(3), IndexSlot(), SeqSlot(),
                         SparseSlot(100)])
    rows = [
        (np.ones(3), 1, [1, 2, 3], [4, 7]),
        (np.zeros(3), 0, [5], [9]),
    ]
    dense, idx, seq, (sp_ids, sp_vals) = feeder.feed(rows)
    assert dense.shape == (2, 3)
    assert idx.shape == (2,) and int(idx[0]) == 1
    assert isinstance(seq, SeqBatch)
    assert seq.data.shape[0] == 2 and int(seq.lengths[0]) == 3
    assert sp_ids.shape == sp_vals.shape and sp_ids.shape[0] == 2
    np.testing.assert_allclose(np.asarray(sp_vals[0])[:2], [1.0, 1.0])


def test_feeder_nested_seq():
    feeder = DataFeeder([SeqSlot(nested=True)])
    rows = [([[1, 2], [3]],), ([[4]],)]
    (nb,) = feeder.feed(rows)
    # 2-level LoD: [B, S, T] + sub/seq lengths (Argument.h:84-90 analog)
    assert nb.data.shape[:2] == (2, 2)
    np.testing.assert_array_equal(np.asarray(nb.seq_lengths), [2, 1])
    np.testing.assert_array_equal(np.asarray(nb.sub_lengths),
                                  [[2, 1], [1, 0]])
    np.testing.assert_array_equal(np.asarray(nb.data[0, 0, :2]), [1, 2])


def test_double_buffer_order_and_errors():
    out = list(DoubleBuffer(lambda: iter(range(10)), depth=3))
    assert out == list(range(10))
    def bad():
        yield 1
        raise ValueError("x")
    with pytest.raises(ValueError):
        list(DoubleBuffer(lambda: bad()))


@pytest.mark.parametrize("ds,checks", [
    (mnist, lambda s: (len(s[0]) == 784, 0 <= s[1] < 10)),
    (uci_housing, lambda s: (len(s[0]) == 13, len(s[1]) == 1)),
])
def test_dense_datasets(ds, checks):
    samples = list(firstn(ds.train(64), 5)())
    assert len(samples) == 5
    for s in samples:
        assert all(checks(s))
    # deterministic
    again = list(firstn(ds.train(64), 5)())
    np.testing.assert_allclose(again[0][0], samples[0][0])


def test_seq_datasets_schema():
    for ids, label in firstn(imdb.train(16), 4)():
        assert all(0 <= i < imdb.VOCAB for i in ids) and label in (0, 1)
    for tup in firstn(imikolov.train(16), 4)():
        assert len(tup) == 5
    for src, tin, tout in firstn(wmt14.train(16), 4)():
        assert len(tin) == len(tout) == len(src) + 1
        assert tin[0] == wmt14.START and tout[-1] == wmt14.END
    for words, tags in firstn(conll05.train(16), 4)():
        assert len(words) == len(tags)
    for u, g, a, j, m, cats, r in firstn(movielens.train(16), 4)():
        assert 1.0 <= r <= 5.0 and len(cats) >= 1
    for q, x, rel in firstn(mq2007.train(4), 4)():
        assert x.shape == (46,) and rel in (0, 1, 2)
    for dense, ids, y in firstn(criteo.train(16), 4)():
        assert len(dense) == 13 and len(ids) == 26 and y in (0, 1)
    for img, label in firstn(cifar.train10(8), 2)():
        assert len(img) == 3072


def test_image_pipeline_extras(tmp_path):
    """image.py parity additions: to_chw, PIL decode, load_and_transform,
    batch_images_from_tar (python/paddle/v2/image.py)."""
    import tarfile

    from PIL import Image

    from paddle_tpu.data import image as I

    im = np.random.RandomState(0).randint(0, 255, (40, 50, 3)).astype(np.uint8)
    chw = I.to_chw(im)
    assert chw.shape == (3, 40, 50)

    p = str(tmp_path / "im.png")
    Image.fromarray(im).save(p)
    back = I.load_image(p)
    np.testing.assert_array_equal(back, im)
    gray = I.load_image(p, is_color=False)
    assert gray.shape == (40, 50, 1)

    out = I.load_and_transform(p, resize=32, crop=24, is_train=False,
                               mean=[127.5, 127.5, 127.5])
    assert out.shape == (24, 24, 3)

    # tar batching
    tar_p = str(tmp_path / "imgs.tar")
    with tarfile.open(tar_p, "w") as tf:
        for i in range(5):
            q = str(tmp_path / f"i{i}.png")
            Image.fromarray(im).save(q)
            tf.add(q, arcname=f"i{i}.png")
    listfile = I.batch_images_from_tar(
        tar_p, "toy", {f"i{i}.png": i for i in range(5)}, num_per_batch=2)
    import pickle
    batches = open(listfile).read().splitlines()
    assert len(batches) == 3
    b0 = pickle.load(open(batches[0], "rb"))
    assert len(b0["data"]) == 2 and b0["label"] == [0, 1]
    assert I.load_image_bytes(b0["data"][0]).shape == (40, 50, 3)


def test_flowers_voc_datasets():
    from paddle_tpu.data.dataset import flowers, voc2012

    im, lb = next(iter(flowers.train(4)()))
    assert im.shape == (64, 64, 3) and im.dtype == np.uint8
    assert 0 <= lb < flowers.CLASSES
    # mapper pipeline like flowers.default_mapper
    from paddle_tpu.data import image as I
    mapped = next(iter(flowers.train(
        4, mapper=lambda s: (I.simple_transform(s[0], 48, 32, True), s[1]))()))
    assert mapped[0].shape == (32, 32, 3)

    img, mask = next(iter(voc2012.train(2)()))
    assert img.shape == (64, 64, 3) and mask.shape == (64, 64)
    assert mask.max() < voc2012.CLASSES


def test_mix_reader_ratio_and_drain():
    """MultiDataProvider analog: ratio-weighted interleave, exhausted
    sub-readers drop out, every sample eventually delivered."""
    from paddle_tpu.data import mix

    a = lambda: iter([("a", i) for i in range(30)])
    b = lambda: iter([("b", i) for i in range(10)])
    got = list(mix([(a, 3.0), (b, 1.0)], seed=0)())
    assert len(got) == 40
    assert sum(1 for s in got if s[0] == "a") == 30
    first20 = [s[0] for s in got[:20]]
    assert first20.count("a") > first20.count("b")   # ratio bias visible

    import pytest as _pytest
    with _pytest.raises(ValueError):
        mix([(a, 1.0), (b, 0.0)])


def test_binary_dataformat_roundtrip(tmp_path):
    """proto DataFormat parity (SURVEY §8.2): header+samples stream with the
    full slot taxonomy (dense / sparse ±value / index / string, each
    optionally (nested) sequence) round-trips and feeds the pipeline."""
    from paddle_tpu.data import batch, format as F

    slots = [
        F.SlotDef(F.DENSE, dim=3),
        F.SlotDef(F.SPARSE_NON_VALUE, dim=100),
        F.SlotDef(F.SPARSE_VALUE, dim=100),
        F.SlotDef(F.INDEX),
        F.SlotDef(F.STRING),
        F.SlotDef(F.INDEX, seq=F.SEQ),
        F.SlotDef(F.DENSE, dim=2, seq=F.SUB_SEQ),
    ]
    samples = [
        (np.array([1.0, 2.0, 3.0], np.float32),
         [3, 7, 42],
         [(1, 0.5), (9, 2.5)],
         4,
         "hello world",
         [5, 6, 7, 8],
         [[np.array([1.0, 2.0], np.float32)],
          [np.array([3.0, 4.0], np.float32),
           np.array([5.0, 6.0], np.float32)]]),
        (np.array([9.0, 8.0, 7.0], np.float32),
         [],
         [],
         0,
         "",
         [1],
         [[np.array([0.5, 0.5], np.float32)]]),
    ]
    path = str(tmp_path / "data.ptdf")
    with open(path, "wb") as f:
        w = F.DataWriter(f, slots)
        for s in samples:
            w.write(s)

    with open(path, "rb") as f:
        r = F.DataReader(f)
        assert r.slots == slots
        back = list(r)
    assert len(back) == 2
    np.testing.assert_allclose(back[0][0], samples[0][0])
    assert back[0][1] == [3, 7, 42]
    assert back[0][2] == [(1, 0.5), (9, 2.5)]
    assert back[0][3] == 4 and back[0][4] == "hello world"
    assert back[0][5] == [5, 6, 7, 8]
    np.testing.assert_allclose(back[0][6][1][1], [5.0, 6.0])
    assert back[1][1] == [] and back[1][4] == ""

    # plugs into the decorator pipeline
    rows = list(batch(F.reader_creator(path), 2)())
    assert len(rows) == 1 and len(rows[0]) == 2

    # corrupted magic fails loudly
    with open(path, "rb") as f:
        bad = bytearray(f.read())
    bad[0] ^= 0xFF
    (tmp_path / "bad.ptdf").write_bytes(bytes(bad))
    with open(str(tmp_path / "bad.ptdf"), "rb") as f, \
            pytest.raises(IOError):
        F.DataReader(f)

    # corrupt in-record count fails loudly too (not silent truncation)
    good = bytearray(bad)
    good[0] ^= 0xFF                        # restore magic
    good[-30] ^= 0x7F                      # scramble a payload count/byte
    (tmp_path / "bad2.ptdf").write_bytes(bytes(good))
    with open(str(tmp_path / "bad2.ptdf"), "rb") as f:
        with pytest.raises((IOError, UnicodeDecodeError, ValueError)):
            list(F.DataReader(f))

    # dim enforcement at write time
    with open(str(tmp_path / "x.ptdf"), "wb") as f:
        w2 = F.DataWriter(f, [F.SlotDef(F.DENSE, dim=3)])
        with pytest.raises(ValueError):
            w2.write((np.zeros(5, np.float32),))
