"""Real-format dataset parsers against checked-in fixtures — the parser
half of the reference's dataset zoo (dataset/mnist.py:42-75 idx,
cifar.py pickled tar, conll05.py column corpus, wmt14.py parallel text,
common.py md5/cache discipline). The fixtures are REAL bytes in the real
formats (tests/fixtures/), so these tests parse what a deployment would."""

import os

import numpy as np
import pytest

from paddle_tpu.data import parsers

FIX = os.path.join(os.path.dirname(__file__), "fixtures")


def test_mnist_idx_parsing_real_bytes():
    r = parsers.mnist_reader(os.path.join(FIX, "mnist-10-images.idx3.gz"),
                             os.path.join(FIX, "mnist-10-labels.idx1.gz"))
    samples = list(r())
    assert len(samples) == 10
    img, label = samples[3]
    assert img.shape == (784,) and img.dtype == np.float32
    assert -1.0 <= img.min() and img.max() <= 1.0
    assert [l for _, l in samples] == list(range(10))


def test_mnist_idx_bad_magic_is_loud(tmp_path):
    import struct
    p = tmp_path / "bad.idx3"
    p.write_bytes(struct.pack(">IIII", 1234, 1, 28, 28) + b"\0" * 784)
    with pytest.raises(IOError, match="magic"):
        parsers.parse_idx_images(str(p))


def test_mnist_idx_truncation_is_loud(tmp_path):
    import struct
    p = tmp_path / "trunc.idx3"
    p.write_bytes(struct.pack(">IIII", 2051, 10, 28, 28) + b"\0" * 100)
    with pytest.raises(IOError, match="truncated"):
        parsers.parse_idx_images(str(p))


def test_cifar_pickled_tar_parsing():
    r = parsers.cifar_reader(os.path.join(FIX, "cifar-tiny.tar.gz"))
    samples = list(r())
    assert len(samples) == 8                    # two batches of 4
    img, label = samples[0]
    assert img.shape == (3072,) and 0 <= label < 10
    assert -1.0 <= img.min() and img.max() <= 1.0


def test_conll_column_parsing_and_dicts():
    r = parsers.conll_reader(os.path.join(FIX, "tiny.conll"))
    sents = list(r())
    assert len(sents) == 3
    words, tags = sents[0]
    assert len(words) == len(tags) == 4
    # dict round trip: same surface word -> same id across sentences
    w1, _ = sents[0]
    w3, _ = sents[2]
    assert w1[0] == w3[0]                       # "The"
    assert w1[1] == w3[1]                       # "cat"
    # frequency-ordered: "." (3 occurrences) gets the smallest non-special id
    assert r.word_dict["."] == 1 and r.word_dict["<unk>"] == 0
    # unknown words at read time map to <unk> when reusing train dicts
    r2 = parsers.conll_reader(os.path.join(FIX, "tiny.conll"),
                              word_dict={"<unk>": 0, "The": 1},
                              tag_dict=r.tag_dict)
    w, _ = next(iter(r2()))
    assert w == [1, 0, 0, 0]


def test_parallel_text_reader_nmt_triples():
    r = parsers.parallel_text_reader(os.path.join(FIX, "tiny.src"),
                                     os.path.join(FIX, "tiny.trg"))
    samples = list(r())
    assert len(samples) == 3
    src, tin, tout = samples[1]
    assert len(src) == 4
    assert tin[0] == r.trg_dict["<s>"] and tout[-1] == r.trg_dict["<e>"]
    assert tin[1:] == tout[:-1]
    # alignment check is loud
    with pytest.raises(IOError, match="misaligned"):
        parsers.parallel_text_reader(os.path.join(FIX, "tiny.src"),
                                     os.path.join(FIX, "tiny.conll"))


def test_download_cache_and_md5_discipline(tmp_path, monkeypatch):
    data = tmp_path / "corpus.bin"
    data.write_bytes(b"hello dataset")
    good = parsers.md5file(str(data))
    # file:// path with matching md5 is accepted
    assert parsers.download(f"file://{data}", "m", good) == str(data)
    with pytest.raises(IOError, match="md5 mismatch"):
        parsers.download(f"file://{data}", "m", "0" * 32)
    # uncached remote url fails loudly (no egress)
    monkeypatch.setattr(parsers, "DATA_HOME", str(tmp_path / "cache"))
    with pytest.raises(IOError, match="no network egress"):
        parsers.download("http://example.com/x.tgz", "m")


def test_real_mnist_feeds_training():
    """End-to-end: the idx fixture flows through batch/DataFeeder into an
    MLP training step (the reference's book tests train on real MNIST —
    fluid/tests/book/test_recognize_digits_mlp.py)."""
    import jax
    import jax.numpy as jnp

    from paddle_tpu.data import DataFeeder, DenseSlot, IndexSlot, batch
    from paddle_tpu.models import MnistMLP
    from paddle_tpu.optimizer import Adam

    r = parsers.mnist_reader(os.path.join(FIX, "mnist-10-images.idx3.gz"),
                             os.path.join(FIX, "mnist-10-labels.idx1.gz"))
    feeder = DataFeeder([DenseSlot(784), IndexSlot()])
    batches = [feeder.feed(rows) for rows in batch(r, 5)()]
    assert batches and batches[0][0].shape == (5, 784)

    model = MnistMLP(in_dim=784, hidden=16, classes=10)
    params = model.init(jax.random.PRNGKey(0))
    opt = Adam(1e-2)
    state = opt.init(params)

    @jax.jit
    def step(params, state, x, y):
        l, g = jax.value_and_grad(model.loss)(params, x, y)
        params, state = opt.update(g, state, params)
        return params, state, l

    losses = []
    for _ in range(10):
        for x, y in batches:
            params, state, l = step(params, state, jnp.asarray(x),
                                    jnp.asarray(y))
            losses.append(float(l))
    assert losses[-1] < losses[0]
