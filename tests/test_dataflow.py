"""paddle_tpu.analysis.dataflow — def-use chains, liveness, aliasing,
effects, and the three planes built on them: the donation-safety proof
(L011 + Executor auto-downgrade), the fusion-legality oracle (bit-parity
certified), and lints L010/L012 with full nested-block-path citations.

Tier-1 (JAX_PLATFORMS=cpu safe).  Also the home of the satellite gates:
the tree-clean sweep over every in-repo example/benchmark Program, the
``lint --format=json`` schema round-trip, the randomized shape-interpreter
vs ``jax.eval_shape`` cross-check, and the verify=True perf budget.
"""

import json
import os
import sys
import time
import warnings

import jax
import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import paddle_tpu.analysis as A
import paddle_tpu.fluid as fluid
from paddle_tpu.analysis import dataflow as DF
from paddle_tpu.fluid import layers
from paddle_tpu.fluid.registry import OpRegistry

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def fresh_programs():
    fluid.reset_default_programs()
    fluid.executor._global_scope = fluid.Scope()
    yield


def _codes(diags):
    return [d.code for d in diags]


# ---------------------------------------------------------------- builders --

def _read_after_donate_program():
    """The seeded hazard: v aliases persistable w (reshape view), sgd
    overwrites w in place, then v is read — the read may observe the
    post-update buffer if w's buffer were donated."""
    prog = fluid.default_main_program()
    b = prog.global_block()
    w = b.create_var(name="w", shape=[4], dtype="float32", persistable=True)
    x = layers.data(name="x", shape=[4], dtype="float32")
    v = b.create_var(name="v", shape=[4], dtype="float32")
    b.append_op("reshape", {"X": [w.name]}, {"Out": [v.name]},
                {"shape": [4]})
    g = b.create_var(name="g", shape=[4], dtype="float32")
    b.append_op("fill_constant", {}, {"Out": [g.name]},
                {"shape": [4], "dtype": "float32", "value": 1.0})
    lr = b.create_var(name="lr", shape=[1], dtype="float32")
    b.append_op("fill_constant", {}, {"Out": [lr.name]},
                {"shape": [1], "dtype": "float32", "value": 0.1})
    b.append_op("sgd", {"Param": [w.name], "Grad": [g.name],
                        "LearningRate": [lr.name]},
                {"ParamOut": [w.name]}, {"learning_rate": 0.1})
    z = b.create_var(name="z", shape=[4], dtype="float32")
    b.append_op("elementwise_add", {"X": [v.name], "Y": [x.name]},
                {"Out": [z.name]}, {})
    return prog, b, z


def _train_program():
    x = layers.data(name="x", shape=[8], dtype="float32")
    y = layers.fc(input=x, size=4)
    loss = layers.mean(y)
    fluid.AdamOptimizer(1e-3).minimize(loss)
    return fluid.default_main_program(), loss


# --------------------------------------------------- def-use chain building --

def test_def_use_chain_and_entry_defs():
    prog, b, z = _read_after_donate_program()
    df = A.analyze_dataflow(prog, fetch=[z.name])
    # w: entry def + the sgd overwrite
    defs_w = df.defs_of("w")
    assert [d.kind for d in defs_w] == ["entry", "op"]
    assert defs_w[1].op_type == "sgd"
    # v's single def roots back to w's ENTRY def (view aliasing)
    (dv,) = [d for d in df.defs_of("v") if d.kind == "op"]
    assert df.entry_defs["w"] in dv.roots
    # v is read once, by the add, and that read reaches only dv
    (uv,) = df.uses_of("v")
    assert uv.op_type == "elementwise_add" and uv.defs == {dv}
    # the sgd's own read of w reaches the ENTRY def, not its own output
    reads_w = [u for u in df.uses_of("w") if u.op_type == "sgd"]
    assert reads_w and all(defs_w[0] in u.defs for u in reads_w)


def test_effect_classification():
    prog, b, z = _read_after_donate_program()
    df = A.analyze_dataflow(prog, fetch=[z.name])
    eff = {b.ops[i].type: df.effects[(0, i)] for i in range(len(b.ops))}
    assert eff["reshape"] == A.Effect.PURE
    assert eff["fill_constant"] == A.Effect.PURE
    assert eff["elementwise_add"] == A.Effect.PURE
    assert eff["sgd"] == A.Effect.INPLACE


def test_effect_classification_control_and_side_effect():
    i = layers.fill_constant(shape=[1], dtype="int64", value=0)
    n = layers.fill_constant(shape=[1], dtype="int64", value=2)
    cond = layers.less_than(i, n)
    with fluid.While(cond).block():
        layers.increment(i)
        layers.less_than(i, n, cond=cond)
    b = fluid.default_main_program().global_block()
    r = b.create_var(shape=[3], dtype="float32")
    b.append_op("gaussian_random", {}, {"Out": [r.name]},
                {"shape": [3], "mean": 0.0, "std": 1.0, "seed": 7,
                 "dtype": "float32"})
    df = A.analyze_dataflow(fluid.default_main_program())
    by_type = {b.ops[i].type: df.effects[(0, i)] for i in range(len(b.ops))}
    assert by_type["while"] == A.Effect.CONTROL
    assert by_type["gaussian_random"] == A.Effect.SIDE_EFFECT


def test_explain_var_chain_text():
    prog, b, z = _read_after_donate_program()
    df = A.analyze_dataflow(prog, fetch=[z.name])
    s = A.explain_var(df, "w")
    assert "defined on entry" in s
    assert "redefined at block 0, op #3 (sgd)" in s
    s2 = A.explain_var(df, "v")
    assert "defined at block 0, op #0 (reshape)" in s2
    assert "last read at block 0, op #4 (elementwise_add)" in s2
    assert A.explain_var(df, "no_such_var") is None


# --------------------------------------------------- donation-safety proof --

def test_donation_hazard_detected_with_sites():
    prog, b, z = _read_after_donate_program()
    hz = A.donation_hazards(prog, fetch=[z.name])
    assert [h.name for h in hz] == ["w"]
    msg = hz[0].describe()
    assert "overwritten at block 0, op #3 (sgd)" in msg
    assert "read at block 0, op #4 (elementwise_add) via alias 'v'" in msg


def test_training_program_proves_donation_safe():
    """The critical no-false-positive baseline: a real fc+Adam training
    step donates every parameter and the proof must go through — Adam's
    reads of the OLD parameter values all happen before (or at) the
    in-place update, and nothing reads them afterwards."""
    prog, loss = _train_program()
    assert A.donation_hazards(prog, fetch=[loss.name]) == []


def test_verify_true_refuses_read_after_donate():
    prog, b, z = _read_after_donate_program()
    exe = fluid.Executor()
    exe.scope.set("w", np.arange(4, dtype=np.float32))
    feed = {"x": np.zeros(4, dtype=np.float32)}
    with pytest.raises(A.ProgramVerificationError) as ei:
        exe.run(prog, feed=feed, fetch_list=[z], verify=True, donate=True)
    s = str(ei.value)
    assert "L011" in s
    # the refusal cites both the overwrite (def) and the stale read (use)
    assert "block 0, op #3 (sgd)" in s
    assert "block 0, op #4 (elementwise_add)" in s


def test_verify_true_donation_off_only_warns():
    """Same program, donation off: the hazard is advisory (donation is a
    run-time switch), so verify must NOT refuse."""
    prog, b, z = _read_after_donate_program()
    exe = fluid.Executor()
    exe.scope.set("w", np.arange(4, dtype=np.float32))
    out, = exe.run(prog, feed={"x": np.zeros(4, np.float32)},
                   fetch_list=[z], verify=True, donate=False)
    np.testing.assert_array_equal(np.asarray(out),
                                  np.arange(4, dtype=np.float32))


def test_executor_auto_downgrades_hazardous_donation():
    """verify=False + donate=True: the Executor must not corrupt values —
    it downgrades the hazardous persistable to keep, warns once naming
    L011, and produces bit-identical results to donate=False."""
    feed = {"x": np.zeros(4, dtype=np.float32)}

    def run(donate):
        fluid.reset_default_programs()
        fluid.executor._global_scope = fluid.Scope()
        prog, b, z = _read_after_donate_program()
        exe = fluid.Executor()
        exe.scope.set("w", np.arange(4, dtype=np.float32))
        with warnings.catch_warnings(record=True) as rec:
            warnings.simplefilter("always")
            out, = exe.run(prog, feed=feed, fetch_list=[z], verify=False,
                           donate=donate)
            # second run (same scope state): the warning is once-per-program
            exe.scope.set("w", np.arange(4, dtype=np.float32))
            out2, = exe.run(prog, feed=feed, fetch_list=[z], verify=False,
                            donate=donate)
        np.testing.assert_array_equal(np.asarray(out), np.asarray(out2))
        l011 = [w for w in rec if "L011" in str(w.message)]
        return np.asarray(out), l011

    donated, warned = run(True)
    kept, not_warned = run(False)
    assert np.array_equal(donated, kept)
    # z = reshape(w_old) + 0 — the pre-update value, proving no corruption
    np.testing.assert_array_equal(donated, np.arange(4, dtype=np.float32))
    assert len(warned) == 1 and "'w'" in str(warned[0].message)
    assert not_warned == []


def test_safe_training_program_keeps_donation():
    """The downgrade must not fire on provably-safe programs: a training
    step's params stay donated (no L011 warning) and training works."""
    prog, loss = _train_program()
    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())
    feed = {"x": np.ones((2, 8), np.float32)}
    with warnings.catch_warnings(record=True) as rec:
        warnings.simplefilter("always")
        l0, = exe.run(prog, feed=feed, fetch_list=[loss], verify=True,
                      donate=True)
        l1, = exe.run(prog, feed=feed, fetch_list=[loss], verify=True,
                      donate=True)
    assert not [w for w in rec if "L011" in str(w.message)]
    assert float(np.asarray(l1)) != float(np.asarray(l0))  # params moved


# ------------------------------------------------- fusion-legality oracle --

def _run_group(block, group, feeds, fused):
    """Execute one certified group the way the executor would: inside ONE
    jitted trace (the executor compiles a whole Program into one jit).

    ``fused=False`` is the standard sequential trace — every group op runs
    through its registered compute, every intermediate is a named binding.
    ``fused=True`` replaces the group with a single fused callable built
    STRICTLY from the certificate: it may touch only ``group.inputs`` and
    must yield exactly ``group.outputs``.  A certificate missing an input,
    leaking an intermediate, or mis-ordering the region fails loudly here.
    """
    def step(env, i):
        op = block.ops[i]
        compute = OpRegistry.get(op.type)
        ins = {k: [env[n] for n in vs] for k, vs in op.inputs.items()}
        outs = compute(ins, op.attrs)
        for k, names in op.outputs.items():
            for n, v in zip(names, outs[k]):
                env[n] = v

    def run_unfused(env):
        env = dict(env)
        for i in group.op_idxs:
            step(env, i)
        return [env[n] for n in group.outputs]

    def fused_fn(*args):
        # the fused region: sees ONLY the certified inputs
        env = dict(zip(group.inputs, args))
        for i in group.op_idxs:
            step(env, i)
        return tuple(env[n] for n in group.outputs)

    def run_fused(env):
        outs = fused_fn(*[env[n] for n in group.inputs])
        return list(outs)

    fn = jax.jit(run_fused if fused else run_unfused)
    return [np.asarray(v) for v in fn(feeds)]


def _assert_groups_bit_identical(prog, groups, shapes, seed=0):
    rs = np.random.RandomState(seed)
    block = prog.blocks[0]
    for g in groups:
        feeds = {n: rs.randn(*shapes[n]).astype(np.float32)
                 for n in g.inputs}
        fused = _run_group(block, g, feeds, fused=True)
        unfused = _run_group(block, g, feeds, fused=False)
        for a, b_ in zip(fused, unfused):
            assert a.dtype == b_.dtype and np.array_equal(a, b_), g.to_dict()


def test_elementwise_chain_certified_and_bit_identical():
    x = layers.data(name="x", shape=[8], dtype="float32")
    y = layers.data(name="y", shape=[8], dtype="float32")
    b = fluid.default_main_program().global_block()
    t1 = b.create_var(shape=[-1, 8], dtype="float32")
    b.append_op("elementwise_add", {"X": [x.name], "Y": [y.name]},
                {"Out": [t1.name]}, {})
    t2 = b.create_var(shape=[-1, 8], dtype="float32")
    b.append_op("elementwise_mul", {"X": [t1.name], "Y": [x.name]},
                {"Out": [t2.name]}, {})
    t3 = b.create_var(shape=[-1, 8], dtype="float32")
    b.append_op("relu", {"X": [t2.name]}, {"Out": [t3.name]}, {})
    w = b.create_var(name="wm", shape=[8, 4], dtype="float32",
                     persistable=True)
    out = b.create_var(shape=[-1, 4], dtype="float32")
    b.append_op("matmul", {"X": [t3.name], "Y": [w.name]},
                {"Out": [out.name]}, {})
    prog = fluid.default_main_program()
    groups = A.fusable_groups(prog, fetch=[out.name])
    chains = [g for g in groups if g.kind == "elementwise_chain"]
    assert len(chains) == 1
    g = chains[0]
    assert g.op_idxs == [0, 1, 2]
    assert set(g.inputs) == {x.name, y.name}
    assert g.outputs == [t3.name]
    # the dependence certificate: every internal edge is single-consumer
    assert {(e["var"], e["n_consumers"]) for e in g.edges} == {
        (t1.name, 1), (t2.name, 1)}
    _assert_groups_bit_identical(prog, chains,
                                 {x.name: (3, 8), y.name: (3, 8)})


def test_producer_consumer_epilogue_certified_and_bit_identical():
    x = layers.data(name="x", shape=[8], dtype="float32")
    b = fluid.default_main_program().global_block()
    w = b.create_var(name="wm", shape=[8, 4], dtype="float32",
                     persistable=True)
    m = b.create_var(shape=[-1, 4], dtype="float32")
    b.append_op("matmul", {"X": [x.name], "Y": [w.name]},
                {"Out": [m.name]}, {})
    r = b.create_var(shape=[-1, 4], dtype="float32")
    b.append_op("relu", {"X": [m.name]}, {"Out": [r.name]}, {})
    prog = fluid.default_main_program()
    groups = A.fusable_groups(prog, fetch=[r.name])
    assert [g.kind for g in groups] == ["producer_consumer"]
    g = groups[0]
    assert g.op_idxs == [0, 1]
    assert [e["var"] for e in g.edges] == [m.name]
    _assert_groups_bit_identical(prog, groups,
                                 {x.name: (3, 8), w.name: (8, 4)})


def test_shared_consumer_rejected():
    """The counterexample the oracle must refuse: t feeds TWO consumers,
    so op 0 can be in no group, while the single-consumer diamond join
    downstream (u1 + u2 -> z) is still legally fusable."""
    x = layers.data(name="x", shape=[4], dtype="float32")
    y = layers.data(name="y", shape=[4], dtype="float32")
    b = fluid.default_main_program().global_block()
    t = b.create_var(shape=[-1, 4], dtype="float32")
    b.append_op("elementwise_add", {"X": [x.name], "Y": [y.name]},
                {"Out": [t.name]}, {})
    u1 = b.create_var(shape=[-1, 4], dtype="float32")
    b.append_op("elementwise_mul", {"X": [t.name], "Y": [x.name]},
                {"Out": [u1.name]}, {})
    u2 = b.create_var(shape=[-1, 4], dtype="float32")
    b.append_op("elementwise_sub", {"X": [t.name], "Y": [y.name]},
                {"Out": [u2.name]}, {})
    z = b.create_var(shape=[-1, 4], dtype="float32")
    b.append_op("elementwise_add", {"X": [u1.name], "Y": [u2.name]},
                {"Out": [z.name]}, {})
    prog = fluid.default_main_program()
    groups = A.fusable_groups(prog, fetch=[z.name])
    for g in groups:
        assert 0 not in g.op_idxs, g.to_dict()
    chains = [g for g in groups if g.kind == "elementwise_chain"]
    assert len(chains) == 1 and chains[0].op_idxs == [1, 2, 3]
    _assert_groups_bit_identical(
        prog, chains, {t.name: (2, 4), x.name: (2, 4), y.name: (2, 4)})


def test_fetched_and_impure_values_never_fused():
    """A fetched intermediate escapes (must materialize); an in-place op
    has ordering obligations — neither may appear inside a group."""
    x = layers.data(name="x", shape=[4], dtype="float32")
    b = fluid.default_main_program().global_block()
    t = b.create_var(shape=[-1, 4], dtype="float32")
    b.append_op("relu", {"X": [x.name]}, {"Out": [t.name]}, {})
    u = b.create_var(shape=[-1, 4], dtype="float32")
    b.append_op("elementwise_mul", {"X": [t.name], "Y": [t.name]},
                {"Out": [u.name]}, {})
    prog = fluid.default_main_program()
    # fetching t makes the relu->mul edge escape: no group may contain it
    assert A.fusable_groups(prog, fetch=[t.name, u.name]) == []
    # not fetched: the chain is certified
    assert [g.op_idxs for g in A.fusable_groups(prog, fetch=[u.name])] \
        == [[0, 1]]


def test_elementwise_chain_sweep_bit_parity():
    """Sweep randomized elementwise chains: every certified group must be
    bit-identical fused vs unfused (the oracle's soundness contract)."""
    rs = np.random.RandomState(7)
    unary = ["relu", "tanh", "sigmoid", "square", "abs_act", "exponential"]
    binary = ["elementwise_add", "elementwise_mul", "elementwise_sub"]
    for trial in range(6):
        fluid.reset_default_programs()
        x = layers.data(name="x", shape=[5], dtype="float32")
        y = layers.data(name="y", shape=[5], dtype="float32")
        b = fluid.default_main_program().global_block()
        cur = x.name
        for _ in range(int(rs.randint(2, 6))):
            out = b.create_var(shape=[-1, 5], dtype="float32")
            if rs.rand() < 0.5:
                b.append_op(unary[rs.randint(len(unary))],
                            {"X": [cur]}, {"Out": [out.name]}, {})
            else:
                b.append_op(binary[rs.randint(len(binary))],
                            {"X": [cur], "Y": [y.name]},
                            {"Out": [out.name]}, {})
            cur = out.name
        prog = fluid.default_main_program()
        groups = A.fusable_groups(prog, fetch=[cur])
        assert groups and groups[0].kind == "elementwise_chain"
        assert groups[0].op_idxs == list(range(len(b.ops)))
        _assert_groups_bit_identical(
            prog, groups, {x.name: (2, 5), y.name: (2, 5)},
            seed=100 + trial)


# ------------------------------------------------------- lints L010 / L012 --

def test_l010_dead_write_cross_sub_block():
    """An outer write killed inside a sub-block (and vice versa) is L010's
    domain — V003 owns same-block duplicate writes."""
    t = layers.fill_constant(shape=[1], dtype="float32", value=1.0)
    i = layers.fill_constant(shape=[1], dtype="int64", value=0)
    n = layers.fill_constant(shape=[1], dtype="int64", value=2)
    cond = layers.less_than(i, n)
    with fluid.While(cond).block():
        layers.assign(layers.fill_constant(shape=[1], dtype="float32",
                                           value=2.0), t)
        layers.increment(i)
        layers.less_than(i, n, cond=cond)
    # this post-loop overwrite kills BOTH earlier writes on every path —
    # the pre-loop fill (killed in the sub-block first) and the loop-body
    # assign; each is a cross-block dead write, which is L010's domain
    layers.assign(layers.fill_constant(shape=[1], dtype="float32",
                                       value=3.0), t)
    out = layers.relu(t)
    diags = A.analyze_program(fluid.default_main_program(),
                              fetch=[out.name])
    l010 = [d for d in diags if d.code == "L010"]
    assert l010, A.format_diagnostics(diags)
    # the finding cites the killing write's full nested path
    assert any("block 0.1" in d.message for d in l010), \
        A.format_diagnostics(l010)


def test_no_l010_on_loop_carried_state():
    """Loop counters/accumulators are written every iteration and read on
    the NEXT one (back edge): never dead."""
    i = layers.fill_constant(shape=[1], dtype="int64", value=0)
    n = layers.fill_constant(shape=[1], dtype="int64", value=3)
    acc = layers.fill_constant(shape=[1], dtype="float32", value=0.0)
    cond = layers.less_than(i, n)
    with fluid.While(cond).block():
        layers.assign(layers.elementwise_add(acc, acc), acc)
        layers.increment(i)
        layers.less_than(i, n, cond=cond)
    diags = A.analyze_program(fluid.default_main_program(),
                              fetch=[acc.name])
    assert not [d for d in diags if d.code in ("L010", "L012")], \
        A.format_diagnostics(diags)


def test_l012_alias_escape_from_sub_block():
    """A sub-block op that rebinds a VIEW of an outer var into a fresh
    name leaks aliasing across the scope boundary."""
    m = layers.fill_constant(shape=[4], dtype="float32", value=1.0)
    i = layers.fill_constant(shape=[1], dtype="int64", value=0)
    n = layers.fill_constant(shape=[1], dtype="int64", value=2)
    cond = layers.less_than(i, n)
    with fluid.While(cond).block():
        v = layers.reshape(m, shape=[2, 2])
        s = layers.reduce_sum(v)
        b = fluid.default_main_program().current_block()
        fresh = b.create_var(shape=[2, 2], dtype="float32")
        b.append_op("assign", {"X": [v.name]}, {"Out": [fresh.name]}, {})
        del s  # read site for v exists; its value is otherwise unused
        layers.increment(i)
        layers.less_than(i, n, cond=cond)
    diags = A.analyze_program(fluid.default_main_program())
    l012 = [d for d in diags if d.code == "L012"]
    assert l012, A.format_diagnostics(diags)
    assert l012[0].severity == A.Severity.WARNING
    assert "block 0.1" in (l012[0].block_path or "") or \
        l012[0].block_path == "0.1"


def test_l011_advisory_without_donate_flag():
    """Static lint (donate unknown): the hazard is a WARNING with the
    advisory qualifier; with donate=True it is an ERROR."""
    prog, b, z = _read_after_donate_program()
    advisory = [d for d in A.analyze_program(prog, fetch=[z.name])
                if d.code == "L011"]
    assert advisory and advisory[0].severity == A.Severity.WARNING
    assert "advisory" in advisory[0].message
    hard = [d for d in A.analyze_program(prog, fetch=[z.name], donate=True)
            if d.code == "L011"]
    assert hard and hard[0].severity == A.Severity.ERROR
    off = [d for d in A.analyze_program(prog, fetch=[z.name], donate=False)
           if d.code == "L011"]
    assert off == []


def test_dataflow_lints_gated_by_structural_errors():
    """L010-L012 reason over sub-block indices the verifier validates —
    with V0xx errors present they must not fire (garbage chains)."""
    b = fluid.default_main_program().global_block()
    out = b.create_var(shape=[4], dtype="float32")
    b.append_op("elementwise_add", {"X": ["ghost"], "Y": ["ghost2"]},
                {"Out": [out.name]}, {})
    diags = A.analyze_program(fluid.default_main_program())
    assert A.errors(diags)
    assert not [d for d in diags if d.code in ("L010", "L011", "L012")]


# -------------------------------------------- nested block-path diagnostics --

def test_lint_catalogue_has_l010_l011_l012():
    assert A.LINT_CATALOGUE["L010"] == ("dead-write", A.Severity.WARNING)
    assert A.LINT_CATALOGUE["L011"] == ("donation-hazard", A.Severity.ERROR)
    assert A.LINT_CATALOGUE["L012"] == ("alias-escape", A.Severity.WARNING)


def test_block_paths_nested_chain():
    i = layers.fill_constant(shape=[1], dtype="int64", value=0)
    n = layers.fill_constant(shape=[1], dtype="int64", value=2)
    cond = layers.less_than(i, n)
    with fluid.While(cond).block():
        j = layers.fill_constant(shape=[1], dtype="int64", value=0)
        m = layers.fill_constant(shape=[1], dtype="int64", value=2)
        cond2 = layers.less_than(j, m)
        with fluid.While(cond2).block():
            layers.increment(j)
            layers.less_than(j, m, cond=cond2)
        layers.increment(i)
        layers.less_than(i, n, cond=cond)
    prog = fluid.default_main_program()
    paths = A.block_paths(prog)
    assert paths[0] == "0"
    inner = [p for p in paths.values() if p.count(".") == 2]
    assert inner and all(p.startswith("0.") for p in inner)
    # root sites keep the historical format; nested cite the chain
    assert A.op_site(0, 3, "concat", block_path=paths[0]) \
        == "block 0, op #3 (concat)"
    bidx = [b for b, p in paths.items() if p.count(".") == 2][0]
    assert A.op_site(bidx, 0, "increment", block_path=paths[bidx]) \
        == f"block {paths[bidx]}, op #0 (increment)"


def test_runtime_trace_error_cites_nested_path():
    i = layers.fill_constant(shape=[1], dtype="int64", value=0)
    n = layers.fill_constant(shape=[1], dtype="int64", value=3)
    acc = layers.fill_constant(shape=[2], dtype="float32", value=0.0)
    cond = layers.less_than(i, n)
    with fluid.While(cond).block():
        bad = layers.reshape(acc, shape=[7])     # 2 -> 7 fails in trace
        layers.assign(bad, acc)
        layers.increment(i)
        layers.less_than(i, n, cond=cond)
    exe = fluid.Executor()
    with pytest.raises(Exception) as ei:
        exe.run(fluid.default_main_program(), fetch_list=[acc],
                verify=False)
    notes = "\n".join(getattr(ei.value, "__notes__", []) or [str(ei.value)])
    assert "block 0.1, op #0 (reshape)" in notes


# ----------------------------------------------------- tree-clean lint gate --

# every in-repo example; script-style ones (no module-level `cost` config
# contract) are explicitly waived WITH the reason — additions to examples/
# without a waiver must lint clean
EXAMPLE_WAIVERS = {
    "gan_vae_mnist.py": "script-style (builds programs inside main())",
    "machine_translation.py": "script-style (imperative train/infer flow)",
    "model_zoo_features.py": "script-style feature tour, no single config",
    "serving_llm.py": "script-style serving daemon, no training config",
    "README.md": "not a Python config",
}


def _tree_examples():
    return sorted(os.listdir(os.path.join(REPO, "examples")))


def test_every_example_linted_or_waived():
    for name in _tree_examples():
        assert name.endswith(".py") or name in EXAMPLE_WAIVERS
    stale = set(EXAMPLE_WAIVERS) - set(_tree_examples())
    assert not stale, f"waivers for deleted examples: {stale}"


@pytest.mark.parametrize("name", [n for n in sorted(os.listdir(
    os.path.join(REPO, "examples"))) if n not in EXAMPLE_WAIVERS])
def test_example_tree_clean(name, capsys):
    from paddle_tpu import cli
    rc = cli.main(["lint", "--config",
                   os.path.join(REPO, "examples", name)])
    out = capsys.readouterr().out
    assert rc == 0, out
    assert "0 error(s)" in out


def test_benchmark_program_tree_clean():
    """benchmarks/fluid_executor.py's MLP training Program (replicated —
    the benchmark builds it inside run()); the only benchmark that goes
    through Program IR.  Zero findings, including L010-L012."""
    img = layers.data("img", shape=(784,))
    label = layers.data("label", shape=(), dtype="int32")
    h1 = layers.fc(img, 256, act="relu")
    h2 = layers.fc(h1, 64, act="relu")
    logits = layers.fc(h2, 10)
    loss = layers.mean(
        layers.softmax_with_cross_entropy(logits, label))
    fluid.AdamOptimizer(1e-3).minimize(loss)
    for prog, fetch in ((fluid.default_main_program(), [loss.name]),
                        (fluid.default_startup_program(), [])):
        diags = A.analyze_program(prog, fetch=fetch, donate=True)
        assert not diags, A.format_diagnostics(diags)


# ------------------------------------------------ lint --format=json schema --

def test_lint_format_json_schema_roundtrip(capsys, tmp_path):
    from paddle_tpu import cli
    rc = cli.main(["lint", "--config",
                   os.path.join(REPO, "examples", "fit_a_line.py"),
                   "--format=json", "--explain"])
    out = capsys.readouterr().out
    payload = json.loads(out)          # stdout is PURE json
    assert rc == 0
    assert payload["version"] == 1
    assert set(payload) == {"version", "findings", "summary"}
    assert set(payload["summary"]) == {"errors", "warnings", "info",
                                       "total"}
    assert payload["summary"]["errors"] == 0
    for f in payload["findings"]:
        assert set(f) == {"code", "severity", "message", "hint",
                          "explain", "site"}
        assert set(f["site"]) == {"program", "block", "block_path", "op",
                                  "op_type", "var"}
    # round-trip: re-serialize identically (stable key order)
    assert json.loads(json.dumps(payload, sort_keys=True)) == payload


def test_lint_format_json_findings_sites(capsys, tmp_path):
    """A config with a real finding: the JSON site block carries the
    nested path and --explain fills the chain."""
    cfg = tmp_path / "dead_cfg.py"
    cfg.write_text(
        "import paddle_tpu.fluid as fluid\n"
        "from paddle_tpu.fluid import layers\n"
        "x = layers.data('x', shape=(4,))\n"
        "unused = layers.data('unused', shape=(4,))\n"
        "dead = layers.relu(x)\n"   # never read, not fetched
        "cost = layers.mean(x)\n")
    from paddle_tpu import cli
    rc = cli.main(["lint", "--config", str(cfg), "--format=json",
                   "--explain", "--fail-on", "warning"])
    out = capsys.readouterr().out
    payload = json.loads(out)
    assert rc == 1
    findings = payload["findings"]
    assert findings and payload["summary"]["total"] == len(findings)
    flagged = [f for f in findings if f["site"]["var"]]
    assert flagged
    assert any(f["explain"] for f in flagged)


def test_lint_exit_code_contract(capsys, tmp_path):
    from paddle_tpu import cli
    # 2: usage error (unloadable config)
    rc = cli.main(["lint", "--config", str(tmp_path / "missing.py")])
    capsys.readouterr()
    assert rc == 2
    # 0: clean
    rc = cli.main(["lint", "--config",
                   os.path.join(REPO, "examples", "fit_a_line.py")])
    capsys.readouterr()
    assert rc == 0


# ------------------------------------- property test: shapes vs eval_shape --

_PROP_UNARY = ["relu", "tanh", "sigmoid", "square"]
_PROP_BINARY = ["elementwise_add", "elementwise_mul", "elementwise_sub"]


def _random_program(rs):
    """A random straight-line program over the core op vocabulary; returns
    (program, {feed name: concrete array})."""
    batch = int(rs.randint(1, 5))
    width = int(rs.randint(2, 7))
    x = layers.data(name="px", shape=[width], dtype="float32")
    b = fluid.default_main_program().global_block()
    feeds = {"px": rs.randn(batch, width).astype(np.float32)}
    avail = [("px", width)]
    for k in range(int(rs.randint(2, 7))):
        name, w = avail[rs.randint(len(avail))]
        kind = rs.randint(5)
        out = b.create_var(shape=[-1, w], dtype="float32")
        if kind == 0:
            b.append_op(_PROP_UNARY[rs.randint(len(_PROP_UNARY))],
                        {"X": [name]}, {"Out": [out.name]}, {})
            avail.append((out.name, w))
        elif kind == 1:
            other = [n for n, ww in avail if ww == w]
            rhs = other[rs.randint(len(other))]
            b.append_op(_PROP_BINARY[rs.randint(len(_PROP_BINARY))],
                        {"X": [name], "Y": [rhs]},
                        {"Out": [out.name]}, {})
            avail.append((out.name, w))
        elif kind == 2:
            w2 = int(rs.randint(2, 7))
            wm = b.create_var(shape=[w, w2], dtype="float32",
                              persistable=True)
            out2 = b.create_var(shape=[-1, w2], dtype="float32")
            b.append_op("matmul", {"X": [name], "Y": [wm.name]},
                        {"Out": [out2.name]}, {})
            avail.append((out2.name, w2))
        elif kind == 3:
            out2 = b.create_var(shape=[-1], dtype="float32")
            b.append_op("reduce_sum", {"X": [name]}, {"Out": [out2.name]},
                        {"dim": [1], "keep_dim": False})
        else:
            out2 = b.create_var(shape=[-1, w], dtype="float16")
            b.append_op("cast", {"X": [name]}, {"Out": [out2.name]},
                        {"dtype": "float16"})
    return fluid.default_main_program(), feeds


@pytest.mark.parametrize("seed", range(8))
def test_shape_interpreter_matches_eval_shape(seed):
    """Randomized cross-check: for every var the interpreter resolves, its
    (shape, dtype) must equal jax.eval_shape of the actual op computes."""
    rs = np.random.RandomState(seed)
    prog, feeds = _random_program(rs)
    block = prog.blocks[0]
    env, diags = A.infer_program_shapes(
        prog, feed_shapes={k: (v.shape, v.dtype.name)
                           for k, v in feeds.items()})
    assert not A.errors(diags), A.format_diagnostics(diags)

    # ground truth: eval_shape the op computes over abstract inputs
    truth = {k: jax.ShapeDtypeStruct(v.shape, v.dtype)
             for k, v in feeds.items()}
    for name, v in block.vars.items():
        if v.persistable:
            truth[name] = jax.ShapeDtypeStruct(
                tuple(v.shape), np.dtype(v.dtype))
    for op in block.ops:
        compute = OpRegistry.get(op.type)
        ins = {k: [truth[n] for n in vs] for k, vs in op.inputs.items()}
        outs = jax.eval_shape(lambda i, c=compute, a=dict(op.attrs):
                              c(i, a), ins)
        for k, names in op.outputs.items():
            for n, s in zip(names, outs[k]):
                truth[n] = s

    checked = 0
    for name, s in env.items():
        if s is A.UNKNOWN or name not in truth:
            continue
        if any(d < 0 for d in getattr(s, "shape", ())):
            continue
        assert tuple(s.shape) == tuple(truth[name].shape), name
        assert np.dtype(s.dtype) == np.dtype(truth[name].dtype), name
        checked += 1
    assert checked >= len(block.ops) // 2  # the check has teeth


# --------------------------------------------------------------- perf budget --

@pytest.mark.perf
def test_verify_preflight_fits_wall_budget():
    """verify=True pre-flight (structural + shapes + dataflow + lints)
    over a GPT-2-small-sized Program must stay interactive.  Budget is
    generous vs CI jitter but catches accidental quadratic blowups."""
    x = layers.data(name="x", shape=[768], dtype="float32")
    h = x
    for _ in range(12):
        m = layers.fc(h, 3072, act="gelu")
        o = layers.fc(m, 768)
        h = layers.elementwise_add(o, h)
        h = layers.activation(h, "tanh")
    loss = layers.mean(h)
    fluid.AdamOptimizer(1e-4).minimize(loss)
    prog = fluid.default_main_program()
    n_ops = sum(len(b.ops) for b in prog.blocks)
    assert n_ops > 120, n_ops     # really GPT-2-small sized

    t0 = time.perf_counter()
    diags = A.check_or_raise(prog, fetch=[loss.name], donate=True)
    elapsed = time.perf_counter() - t0
    assert not A.errors(diags)
    # also prove the dataflow piece alone is cheap enough to re-run
    t1 = time.perf_counter()
    df = A.analyze_dataflow(prog, fetch=[loss.name])
    hz = A.donation_hazards(prog, df=df)
    grp = A.fusable_groups(prog, fetch=[loss.name], df=df)
    dflow = time.perf_counter() - t1
    assert hz == []
    assert grp      # a transformer block is full of fusable epilogues
    budget = float(os.environ.get("PADDLE_TPU_VERIFY_BUDGET_S", "20"))
    assert elapsed + dflow < budget, (elapsed, dflow)
