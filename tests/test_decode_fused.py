"""Fused decode step (ISSUE 7): CPU ``interpret=True`` parity for the
decode-attention kernel, the quantized-KV numerics contract, the
one-dispatch-per-token obs evidence, the multi-token verify step, and the
widened fused-RNN coverage (reverse direction + wide batch tiles).

The contract under test (docs/design/kernels.md): route choice — dense
reference math vs the Pallas kernel, full-precision vs int8 cache reads —
NEVER changes which greedy token comes out; int8 changes logits only
through the documented quantize-dequant of cache reads, identically on
every route."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu import obs
from paddle_tpu.models import TransformerLM
from paddle_tpu.ops import pallas_kernels as pk

VOCAB, D, H, L, MAX_LEN = 97, 32, 4, 2, 512


@pytest.fixture(scope="module")
def model_and_params():
    model = TransformerLM(VOCAB, d_model=D, n_heads=H, n_layers=L,
                          max_len=MAX_LEN)
    params = model.init(jax.random.PRNGKey(0))
    return model, params


def _prompt(b=2, t=7, seed=0):
    rs = np.random.RandomState(seed)
    return jnp.asarray(rs.randint(0, VOCAB, (b, t)), jnp.int32)


# -- the auto-routing entry point -----------------------------------------


@pytest.mark.parametrize("quant", [False, True])
def test_decode_attention_kernel_matches_dense(quant):
    """The Pallas decode kernel (interpret=True on CPU) and the dense
    reference route share one masked-softmax formulation: same output to
    float tolerance on identical inputs, and exact masking — rows past
    pos contribute nothing on either route."""
    rs = np.random.RandomState(3)
    B, Lc, Hh, Dh = 3, 64, 4, 8
    q = jnp.asarray(rs.randn(B, Hh, Dh), jnp.float32)
    pos = jnp.asarray([5, 0, 63], jnp.int32)
    if quant:
        kf = rs.randn(B, Lc, Hh, Dh).astype(np.float32)
        vf = rs.randn(B, Lc, Hh, Dh).astype(np.float32)
        k, ks = pk.quantize_kv(jnp.asarray(kf))
        v, vs = pk.quantize_kv(jnp.asarray(vf))
    else:
        k = jnp.asarray(rs.randn(B, Lc, Hh, Dh), jnp.float32)
        v = jnp.asarray(rs.randn(B, Lc, Hh, Dh), jnp.float32)
        ks = vs = None
    dense = pk.decode_attention(q, k, v, pos, k_scale=ks, v_scale=vs,
                                route="dense")
    kern = pk.decode_attention(q, k, v, pos, k_scale=ks, v_scale=vs,
                               route="kernel", interpret=True)
    np.testing.assert_allclose(np.asarray(kern), np.asarray(dense),
                               rtol=1e-5, atol=1e-5)
    # masking: zeroing every row PAST pos must not change the output
    j = np.arange(Lc)
    live = jnp.asarray((j[None, :] <= np.asarray(pos)[:, None]))
    kz = jnp.where(live[..., None, None], k, jnp.zeros((), k.dtype))
    vz = jnp.where(live[..., None, None], v, jnp.zeros((), v.dtype))
    kern_z = pk.decode_attention(q, kz, vz, pos, k_scale=ks, v_scale=vs,
                                 route="kernel", interpret=True)
    np.testing.assert_allclose(np.asarray(kern_z), np.asarray(kern),
                               rtol=1e-6, atol=1e-6)


def test_quantize_kv_roundtrip_bound():
    """Symmetric int8: per-row max-abs scale bounds the dequant error at
    scale/2 per element (half a code step)."""
    rs = np.random.RandomState(5)
    x = jnp.asarray(rs.randn(4, 16, 3, 8) * 3.0, jnp.float32)
    q, s = pk.quantize_kv(x)
    assert q.dtype == jnp.int8 and s.shape == x.shape[:-1]
    err = np.abs(np.asarray(q, np.float32) * np.asarray(s)[..., None]
                 - np.asarray(x))
    assert (err <= np.asarray(s)[..., None] * 0.5 + 1e-6).all()


# -- the fused decode step -------------------------------------------------


@pytest.mark.parametrize("bucket", [None, 32])
def test_generate_fused_greedy_matches_cached(model_and_params, bucket):
    """Greedy parity of the fused single-dispatch-per-token loop against
    the reference generate_cached scan, bucketed and not."""
    model, params = model_and_params
    prompt = _prompt()
    want = np.asarray(model.generate_cached(params, prompt, steps=12,
                                            bucket=bucket))
    got = np.asarray(model.generate_fused(params, prompt, steps=12,
                                          bucket=bucket))
    np.testing.assert_array_equal(got, want)


def test_generate_fused_kernel_route_matches_dense(model_and_params):
    """Forcing the Pallas kernel route (interpret on CPU) through the whole
    model must leave greedy tokens identical — the auto-routing contract."""
    model, params = model_and_params
    prompt = _prompt(seed=2)
    want = np.asarray(model.generate_fused(params, prompt, steps=8,
                                           attn_route="dense"))
    got = np.asarray(model.generate_fused(params, prompt, steps=8,
                                          attn_route="kernel"))
    np.testing.assert_array_equal(got, want)


def test_generate_fused_int8_routes_agree(model_and_params):
    """int8 numerics contract: the quantization error is the MODEL's
    (introduced by quantize_kv at append), not the kernel's — dense and
    kernel routes over the same int8 cache emit identical tokens."""
    model, params = model_and_params
    prompt = _prompt(seed=3)
    a = np.asarray(model.generate_fused(params, prompt, steps=10,
                                        kv_dtype="int8",
                                        attn_route="dense"))
    b = np.asarray(model.generate_fused(params, prompt, steps=10,
                                        kv_dtype="int8",
                                        attn_route="kernel"))
    np.testing.assert_array_equal(a, b)


def test_generate_fused_dispatch_counter(model_and_params):
    """THE acceptance assert: one compiled dispatch per generated token —
    1 prefill (emits the first token) + steps-1 fused steps — visible on
    decode.dispatches_total; tokens_total counts every emitted token."""
    model, params = model_and_params
    prompt = _prompt(b=3)
    steps = 9
    r = obs.MetricsRegistry()
    with obs.ObsSession(registry=r).installed():
        model.generate_fused(params, prompt, steps=steps)
    disp = {s["labels"]["route"]: s["value"] for s in r.collect()
            if s["name"] == "decode.dispatches_total"}
    assert disp == {"prefill": 1, "step": steps - 1}
    toks = [s["value"] for s in r.collect()
            if s["name"] == "decode.tokens_total"]
    assert toks == [3 * steps]
    # the modeled kernel bytes rode along
    assert any(s["name"] == "kernels.bytes_total"
               and s["labels"]["kernel"] == "decode_attention"
               and s["value"] > 0 for s in r.collect())


def test_generate_fused_topk_sampling(model_and_params):
    """top-k sampling: deterministic under a fixed key, tokens stay inside
    the top-k set of the reference logits at every step."""
    model, params = model_and_params
    prompt = _prompt(b=1, seed=4)
    key = jax.random.PRNGKey(11)
    a = np.asarray(model.generate_fused(params, prompt, steps=6,
                                        sample="topk", top_k=5, key=key))
    b = np.asarray(model.generate_fused(params, prompt, steps=6,
                                        sample="topk", top_k=5, key=key))
    np.testing.assert_array_equal(a, b)
    with pytest.raises(ValueError, match="top_k and key"):
        model.generate_fused(params, prompt, steps=2, sample="topk")


# -- verify step (speculative building block) ------------------------------


def test_verify_step_bit_exact_vs_sequential(model_and_params):
    """verify_step's span logits must BIT-match running decode_step
    sequentially over the same tokens — the exactness speculative decoding
    inherits (serving.SpeculativeDecoder)."""
    model, params = model_and_params
    prompt = _prompt(seed=6)
    cell, last = model.prefill(params, prompt)
    cur = jnp.argmax(last, -1).astype(prompt.dtype)
    toks, logits, c = [cur], [], dict(cell)
    for _ in range(6):
        lg, c = model.decode_step(params, c, toks[-1])
        logits.append(lg)
        toks.append(jnp.argmax(lg, -1).astype(prompt.dtype))
    span = jnp.stack(toks[:6], axis=1)
    vlg, c2 = model.verify_step(params, cell, span)
    for i in range(6):
        np.testing.assert_array_equal(np.asarray(vlg[:, i]),
                                      np.asarray(logits[i]))
    np.testing.assert_array_equal(np.asarray(c2["pos"]),
                                  np.asarray(cell["pos"]) + 6)


def test_verify_step_int8_matches_sequential_int8(model_and_params):
    """Same check on an int8 cell: append-quantize + dequant-read agree
    between the span and sequential paths (greedy tokens identical)."""
    model, params = model_and_params
    prompt = _prompt(seed=7)
    cell, last = model.prefill(params, prompt, kv_dtype="int8")
    cur = jnp.argmax(last, -1).astype(prompt.dtype)
    toks, c = [cur], dict(cell)
    for _ in range(5):
        lg, c = model.decode_step(params, c, toks[-1])
        toks.append(jnp.argmax(lg, -1).astype(prompt.dtype))
    span = jnp.stack(toks[:5], axis=1)
    vlg, _ = model.verify_step(params, cell, span)
    t = np.asarray(jnp.argmax(vlg, -1))
    np.testing.assert_array_equal(
        t, np.stack([np.asarray(x) for x in toks[1:6]], axis=1))


# -- widened fused-RNN coverage --------------------------------------------


def _lstm_inputs(seed, B=5, T=9, D=4, Hh=6):
    rs = np.random.RandomState(seed)
    x = jnp.asarray(rs.randn(B, T, D), jnp.float32)
    lens = jnp.asarray(rs.randint(1, T + 1, B), jnp.int32)
    w = jnp.asarray(rs.randn(D, 4 * Hh) * 0.3, jnp.float32)
    u = jnp.asarray(rs.randn(Hh, 4 * Hh) * 0.3, jnp.float32)
    b = jnp.asarray(rs.randn(4 * Hh) * 0.1, jnp.float32)
    return x, lens, w, u, b


def test_reverse_within_length_roundtrip():
    from paddle_tpu.ops import rnn as R
    rs = np.random.RandomState(8)
    x = jnp.asarray(rs.randn(3, 5, 2), jnp.float32)
    lens = jnp.asarray([5, 3, 1], jnp.int32)
    y = R._reverse_within_length(x, lens)
    # sample 1 (len 3): first three steps flipped, tail zero
    np.testing.assert_array_equal(np.asarray(y[1, :3]),
                                  np.asarray(x[1, :3][::-1]))
    assert (np.asarray(y[1, 3:]) == 0).all()
    # flipping twice restores the live prefix
    z = R._reverse_within_length(y, lens)
    np.testing.assert_array_equal(np.asarray(z[1, :3]),
                                  np.asarray(x[1, :3]))


def test_fused_lstm_reverse_matches_scan():
    """reverse=True through the fused kernel (within-length flip around the
    forward kernel) vs the masked reverse scan: outputs AND final state."""
    from paddle_tpu.ops import rnn as R
    x, lens, w, u, b = _lstm_inputs(9)
    B, T, _ = x.shape
    Hh = u.shape[0]
    ref_out, ref_state = R.lstm(x, lens, w, u, b, reverse=True, fused=False,
                                forget_bias=1.0)
    h0 = jnp.zeros((B, Hh), x.dtype)
    xk = R._reverse_within_length(x, lens)
    out, ht, ct = R._lstm_fused(xk, lens, w, u, b, h0, h0, 1.0, 5, 3)
    out = R._reverse_within_length(out, lens)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref_out),
                               rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(np.asarray(ht), np.asarray(ref_state.h),
                               rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(np.asarray(ct), np.asarray(ref_state.c),
                               rtol=1e-5, atol=1e-6)


def test_fused_lstm_reverse_grads_match_scan():
    """Gradients flow through the flip gathers around the fused kernel's
    custom VJP: reverse-direction training parity (the bidirectional
    textcls/NMT encoder case)."""
    from paddle_tpu.ops import rnn as R
    x, lens, w, u, b = _lstm_inputs(10)
    B, T, _ = x.shape
    Hh = u.shape[0]
    wo = jnp.asarray(np.random.RandomState(1).randn(B, T, Hh), jnp.float32)
    h0 = jnp.zeros((B, Hh), x.dtype)

    def ref(x, w, u, b):
        out, _ = R.lstm(x, lens, w, u, b, reverse=True, fused=False,
                        forget_bias=1.0)
        return jnp.sum(out * wo)

    def fused(x, w, u, b):
        xk = R._reverse_within_length(x, lens)
        out, ht, ct = R._lstm_fused(xk, lens, w, u, b, h0, h0, 1.0, 5, 4)
        return jnp.sum(R._reverse_within_length(out, lens) * wo)

    g_ref = jax.grad(ref, argnums=(0, 1, 2, 3))(x, w, u, b)
    g_fused = jax.grad(fused, argnums=(0, 1, 2, 3))(x, w, u, b)
    for name, a, bb in zip("x w u b".split(), g_ref, g_fused):
        np.testing.assert_allclose(np.asarray(bb), np.asarray(a),
                                   rtol=2e-5, atol=2e-5, err_msg=name)


def test_fused_lstm_multichunk_backward_matches_scan(monkeypatch):
    """Force a small backward time-chunk so the multi-launch reverse
    recurrence (boundary state from the saved out/c sequences) is
    exercised at test scale — at real scale it engages for long T."""
    from paddle_tpu.ops import rnn as R
    x, lens, w, u, b = _lstm_inputs(11)
    B, T, _ = x.shape
    Hh = u.shape[0]
    monkeypatch.setattr(R, "_fused_bwd_plan",
                        lambda *a, **k: (B, 3))
    wo = jnp.asarray(np.random.RandomState(2).randn(B, T, Hh), jnp.float32)
    h0 = jnp.zeros((B, Hh), x.dtype)

    def ref(x, w, u, b):
        out, _ = R.lstm(x, lens, w, u, b, fused=False, forget_bias=1.0)
        return jnp.sum(out * wo)

    def fused(x, w, u, b):
        out, ht, ct = R._lstm_fused(x, lens, w, u, b, h0, h0, 1.0, B, None)
        return jnp.sum(out * wo)

    g_ref = jax.grad(ref, argnums=(0, 1, 2, 3))(x, w, u, b)
    g_fused = jax.grad(fused, argnums=(0, 1, 2, 3))(x, w, u, b)
    for name, a, bb in zip("x w u b".split(), g_ref, g_fused):
        np.testing.assert_allclose(np.asarray(bb), np.asarray(a),
                                   rtol=2e-5, atol=2e-5, err_msg=name)


def test_fused_gru_reverse_matches_scan():
    """Same flip construction for the GRU — the seq2seq NMT encoder's
    backward direction."""
    from paddle_tpu.ops import rnn as R
    rs = np.random.RandomState(12)
    B, T, D, Hh = 4, 8, 3, 6
    x = jnp.asarray(rs.randn(B, T, D), jnp.float32)
    lens = jnp.asarray(rs.randint(1, T + 1, B), jnp.int32)
    w = jnp.asarray(rs.randn(D, 3 * Hh) * 0.3, jnp.float32)
    u = jnp.asarray(rs.randn(Hh, 3 * Hh) * 0.3, jnp.float32)
    b = jnp.asarray(rs.randn(3 * Hh) * 0.1, jnp.float32)
    ref_out, ref_h = R.gru(x, lens, w, u, b, reverse=True, fused=False)
    h0 = jnp.zeros((B, Hh), x.dtype)
    xk = R._reverse_within_length(x, lens)
    out, ht = R._gru_fused(xk, lens, w, u, b, h0, 4, 3)
    out = R._reverse_within_length(out, lens)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref_out),
                               rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(np.asarray(ht), np.asarray(ref_h),
                               rtol=1e-5, atol=1e-6)
