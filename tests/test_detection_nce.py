"""Detection suite + NCE/hsigmoid op tests (op-level, SURVEY.md §4.1 style)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu.ops import detection as D
from paddle_tpu.ops import nce as N
from paddle_tpu.ops.conv import bilinear_interp, maxout


def test_prior_box_shapes_and_range():
    boxes, variances = D.prior_box((4, 4), (64, 64), min_size=16.0,
                                   max_size=32.0, aspect_ratios=(2.0,))
    # P = 1(min) + 1(sqrt) + 2(ar 2, flip) = 4 per cell
    assert boxes.shape == (4 * 4 * 4, 4) and variances.shape == boxes.shape
    assert float(boxes.min()) >= 0.0 and float(boxes.max()) <= 1.0
    # xmax > xmin for all
    assert np.all(np.asarray(boxes[:, 2] >= boxes[:, 0]))


def test_iou_and_encode_decode_roundtrip():
    a = jnp.array([[0.0, 0.0, 0.5, 0.5]])
    b = jnp.array([[0.25, 0.25, 0.75, 0.75], [0.0, 0.0, 0.5, 0.5]])
    iou = D.iou_matrix(a, b)
    np.testing.assert_allclose(np.asarray(iou[0]), [0.0625 / 0.4375, 1.0],
                               rtol=1e-5)
    priors = jnp.array([[0.1, 0.1, 0.4, 0.4], [0.5, 0.5, 0.9, 0.9]])
    var = jnp.full((2, 4), 0.1)
    gt = jnp.array([[0.15, 0.12, 0.43, 0.45], [0.52, 0.48, 0.88, 0.95]])
    enc = D.encode_boxes(gt, priors, var)
    dec = D.decode_boxes(enc, priors, var)
    np.testing.assert_allclose(np.asarray(dec), np.asarray(gt), atol=1e-5)


def test_match_priors_force_match():
    priors = jnp.array([[0.0, 0.0, 0.3, 0.3], [0.6, 0.6, 1.0, 1.0]])
    gt = jnp.array([[0.65, 0.6, 0.95, 1.0], [0.0, 0.0, 0.0, 0.0]])
    mask = jnp.array([1.0, 0.0])
    matched, pos = D.match_priors(priors, gt, mask, threshold=0.5)
    assert bool(pos[1]) and not bool(pos[0])
    assert int(matched[1]) == 0


def test_multibox_loss_decreases_with_better_preds():
    rs = np.random.RandomState(0)
    priors, var = D.prior_box((4, 4), (32, 32), min_size=8.0)
    Np = priors.shape[0]
    gt = jnp.array([[0.2, 0.2, 0.5, 0.5]])
    gt_labels = jnp.array([1])
    gt_mask = jnp.array([1.0])
    matched, pos = D.match_priors(priors, gt, gt_mask)
    perfect_loc = D.encode_boxes(gt[matched], priors, var)
    good_conf = jnp.where(pos[:, None],
                          jnp.array([[-5.0, 5.0]]), jnp.array([[5.0, -5.0]]))
    l_good = D.multibox_loss(perfect_loc, good_conf, priors, var, gt,
                             gt_labels, gt_mask)
    bad_loc = jnp.asarray(rs.randn(Np, 4), jnp.float32)
    bad_conf = jnp.asarray(rs.randn(Np, 2), jnp.float32)
    l_bad = D.multibox_loss(bad_loc, bad_conf, priors, var, gt, gt_labels,
                            gt_mask)
    assert float(l_good) < float(l_bad)


def test_nms_suppresses_overlaps():
    boxes = jnp.array([[0.0, 0.0, 0.5, 0.5],
                       [0.01, 0.01, 0.51, 0.51],     # dup of 0
                       [0.6, 0.6, 0.9, 0.9]])
    scores = jnp.array([0.9, 0.8, 0.7])
    b, s, v = D.nms(boxes, scores, iou_threshold=0.5, top_k=3)
    assert np.asarray(v).sum() == 2                   # dup suppressed
    assert float(s[0]) == pytest.approx(0.9)
    assert bool(v[1] == 0)


def test_detection_output_shapes():
    priors, var = D.prior_box((2, 2), (32, 32), min_size=8.0)
    Np = priors.shape[0]
    rs = np.random.RandomState(0)
    loc = jnp.asarray(rs.randn(Np, 4) * 0.1, jnp.float32)
    conf = jnp.asarray(rs.randn(Np, 3), jnp.float32)
    b, s, v = D.detection_output(loc, conf, priors, var, num_classes=3,
                                 keep_top_k=5)
    assert b.shape == (2, 5, 4) and s.shape == (2, 5) and v.shape == (2, 5)


def test_nce_loss_learns_direction():
    """NCE gradient should pull the target row toward the hidden vector."""
    rs = np.random.RandomState(0)
    V, Dm, B = 50, 8, 4
    weight = jnp.asarray(rs.randn(V, Dm) * 0.1, jnp.float32)
    bias = jnp.zeros((V,))
    hidden = jnp.asarray(rs.randn(B, Dm), jnp.float32)
    labels = jnp.array([3, 7, 3, 9])
    rng = jax.random.PRNGKey(0)

    def loss(w):
        return N.nce_loss(hidden, labels, w, bias, rng, num_neg_samples=20)

    l0 = float(loss(weight))
    g = jax.grad(loss)(weight)
    w2 = weight - 0.5 * g
    assert float(loss(w2)) < l0
    # untouched rows (not target, not sampled often) have ~zero grad for most
    assert np.abs(np.asarray(g)[labels]).sum() > 0


def test_hsigmoid_is_valid_distribution_and_trains():
    V, Dm, B = 16, 8, 8
    rs = np.random.RandomState(1)
    paths, codes = N.build_huffman_codes(V)
    inner_w = jnp.asarray(rs.randn(2 * V, Dm) * 0.1, jnp.float32)
    inner_b = jnp.zeros((2 * V,))
    hidden = jnp.asarray(rs.randn(B, Dm), jnp.float32)
    logp = N.hsigmoid_logprobs(hidden, inner_w, inner_b, paths, codes)
    # probabilities over classes sum to 1 (complete binary tree)
    np.testing.assert_allclose(np.exp(np.asarray(logp)).sum(-1),
                               np.ones(B), rtol=1e-4)
    labels = jnp.asarray(rs.randint(0, V, B))

    def loss(w):
        return N.hsigmoid_loss(hidden, labels, w, inner_b, paths, codes)

    l0 = float(loss(inner_w))
    w2 = inner_w - 0.5 * jax.grad(loss)(inner_w)
    assert float(loss(w2)) < l0
    # loss equals NLL computed from the full distribution
    nll = -np.asarray(logp)[np.arange(B), np.asarray(labels)].mean()
    np.testing.assert_allclose(l0, nll, rtol=1e-5)


def test_bilinear_interp_and_maxout():
    x = jnp.arange(16.0).reshape(1, 4, 4, 1)
    up = bilinear_interp(x, 8, 8)
    assert up.shape == (1, 8, 8, 1)
    np.testing.assert_allclose(float(up[0, 0, 0, 0]), 0.0)
    np.testing.assert_allclose(float(up[0, -1, -1, 0]), 15.0)
    # identity when resizing to same size
    same = bilinear_interp(x, 4, 4)
    np.testing.assert_allclose(np.asarray(same), np.asarray(x), atol=1e-6)
    m = maxout(jnp.arange(8.0).reshape(1, 1, 1, 8), groups=2)
    np.testing.assert_allclose(np.asarray(m)[0, 0, 0], [1, 3, 5, 7])
