"""Elastic cluster runtime (ISSUE 14): membership, fencing, chaos.

The contract under test (docs/design/elastic.md): workers register under
a heartbeat lease with fencing tokens; every membership change bumps an
epoch, re-buckets the in-flight shard queue, and barriers workers into a
state resync at the next step boundary; the parameter trajectory is
BYTE-STABLE across fleet shapes, kill -9s, rolling restarts, and master
restarts — because the master reduces the fixed shard partition in shard
order and applies the one optimizer update itself.

Thread workers and subprocess workers run the SAME code over the real TCP
RPC plane (tests/elastic_testnet.py is the shared workload); kill -9
chaos uses real OS processes. None of this needs cross-process
collectives, which is exactly the point — elasticity lives in the data
plane, so it works even where multiprocess-on-CPU XLA does not.
"""

import os
import signal
import subprocess
import sys
import threading
import time

import jax
import numpy as np
import pytest

from elastic_testnet import build
from paddle_tpu import nn, obs
from paddle_tpu.faults import FaultPlan
from paddle_tpu.runtime.master_service import (MasterClient, MasterServer,
                                               StaleMemberError)
from paddle_tpu.runtime.membership import (MembershipService,
                                           autoscale_recommendation)
from paddle_tpu.trainer.elastic import ElasticMaster, ElasticWorker

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WORKER_SCRIPT = os.path.join(REPO, "tests", "elastic_worker_script.py")

LOSS_FN, PARAMS0, MK_OPT, BATCHES = build(steps=6)


def _flat(params):
    return {k: np.asarray(v) for k, v in
            nn.Module.named_parameters(jax.device_get(params))}


def _assert_trees_equal(a, b, *, exact=True):
    fa, fb = _flat(a), _flat(b)
    assert set(fa) == set(fb)
    for k in fa:
        if exact:
            np.testing.assert_array_equal(fa[k], fb[k], err_msg=k)
        else:
            np.testing.assert_allclose(fa[k], fb[k], rtol=2e-5, atol=2e-5,
                                       err_msg=k)


def _thread_worker(host, port, name, stop, mesh=None, layout=None):
    w = ElasticWorker(LOSS_FN, (host, port), worker=name, mesh=mesh,
                      layout=layout)
    t = threading.Thread(target=w.run, kwargs={"stop": stop}, daemon=True)
    t.start()
    return w, t


def _run_static_elastic(n_workers, batches, num_passes=1, shards=4):
    """Reference: a fixed fleet of thread workers, no chaos."""
    em = ElasticMaster(LOSS_FN, MK_OPT(), ttl=5.0, task_timeout_s=10.0,
                       shards_per_step=shards,
                       min_workers=n_workers).start()
    host, port = em.address
    stop = threading.Event()
    pairs = [_thread_worker(host, port, f"static{i}", stop)
             for i in range(n_workers)]
    try:
        params, _, loss = em.fit(batches, PARAMS0(), num_passes=num_passes,
                                 progress_timeout=60.0)
    finally:
        stop.set()
        for _, t in pairs:
            t.join(timeout=10)
        em.stop()
    return params, loss


# ---------------------------------------------------------------------------
# membership service (in-process dispatch, fake clock — no sleeps)
# ---------------------------------------------------------------------------

def test_membership_join_heartbeat_expire_epoch():
    srv = MasterServer()
    clock = [0.0]
    ms = MembershipService(ttl=10.0, clock=lambda: clock[0]).attach(srv)
    r = srv._dispatch({"op": "mbr_join", "worker": "a",
                       "caps": {"devices": 2}})
    assert r["ok"] and r["epoch"] == 1 and r["ttl"] == 10.0
    tok_a = r["member_token"]
    r2 = srv._dispatch({"op": "mbr_join", "worker": "b"})
    assert r2["epoch"] == 2
    view = srv._dispatch({"op": "mbr_view"})
    assert [m["worker"] for m in view["members"]] == ["a", "b"]
    assert view["epoch"] == 2 and view["recommendation"]["action"] in (
        "join", "leave", "hold")
    # heartbeat keeps the lease alive across the clock advance
    clock[0] = 8.0
    assert srv._dispatch({"op": "mbr_heartbeat", "worker": "a",
                          "member_token": tok_a})["ok"]
    clock[0] = 15.0          # b (deadline 10) lapsed; a (deadline 18) lives
    assert ms.expire() == ["b"]
    assert ms.epoch == 3
    assert [m["worker"] for m in ms.members()] == ["a"]
    # the evicted worker's heartbeat is refused with a structured code
    r3 = srv._dispatch({"op": "mbr_heartbeat", "worker": "b",
                        "member_token": r2["member_token"]})
    assert not r3["ok"] and r3["code"] == "unknown_member"
    assert r3["epoch"] == 3
    # graceful leave bumps the epoch once more
    assert srv._dispatch({"op": "mbr_leave", "worker": "a",
                          "member_token": tok_a})["ok"]
    assert ms.epoch == 4 and ms.members() == []


def test_membership_rejoin_fences_old_incarnation():
    srv = MasterServer()
    ms = MembershipService(ttl=10.0).attach(srv)
    t1, e1 = ms.join("w")
    t2, e2 = ms.join("w")           # the newer incarnation wins
    assert t2 > t1 and e2 == e1 + 1
    stale = srv._dispatch({"op": "mbr_heartbeat", "worker": "w",
                           "member_token": t1})
    assert not stale["ok"] and stale["code"] == "stale_member"
    assert srv._dispatch({"op": "mbr_heartbeat", "worker": "w",
                          "member_token": t2})["ok"]
    # epoch fencing: an older view's submission is refused, current passes
    err = ms.fence(e1)
    assert err["code"] == "stale_epoch" and err["epoch"] == e2
    assert ms.fence(e2) is None and ms.fence(None) is None


def test_elastic_grad_submission_fencing():
    em = ElasticMaster(LOSS_FN, MK_OPT())
    join = em.server._dispatch({"op": "mbr_join", "worker": "w"})
    tok, epoch = join["member_token"], join["epoch"]
    # no member / wrong token fence before anything else
    r = em.server._dispatch({"op": "ela_grad", "worker": "ghost",
                             "member_token": 1, "epoch": epoch})
    assert r["code"] == "unknown_member"
    r = em.server._dispatch({"op": "ela_grad", "worker": "w",
                             "member_token": tok + 5, "epoch": epoch})
    assert r["code"] == "stale_member"
    # stale epoch: join another worker (epoch moves), then submit old
    em.server._dispatch({"op": "mbr_join", "worker": "w2"})
    r = em.server._dispatch({"op": "ela_grad", "worker": "w",
                             "member_token": tok, "epoch": epoch})
    assert r["code"] == "stale_epoch" and r["epoch"] == epoch + 1
    # current epoch but no step collecting -> structured stale_step
    r = em.server._dispatch({"op": "ela_grad", "worker": "w",
                             "member_token": tok, "epoch": epoch + 1,
                             "pass": 0, "step": 0, "shard": 0})
    assert r["code"] == "stale_step"
    # a fence-refused submission must requeue its task immediately — NOT
    # strand it in pending until the dispatch timeout (review fix): the
    # shard is still needed and a current worker must get it now
    em.server.master.set_dataset(["shard-payload"])
    tid, _ = em.server.master.get_task()
    assert em.server.master.stats()[:2] == (0, 1)      # dispatched
    r = em.server._dispatch({"op": "ela_grad", "worker": "w",
                             "member_token": tok, "epoch": epoch,
                             "task_id": tid, "pass": 0, "step": 0,
                             "shard": 0, "grad": ""})
    assert r["code"] == "stale_epoch"
    assert em.server.master.stats()[:2] == (1, 0)      # back in todo


def test_mesh_worker_handles_uneven_shard():
    """A worker with a local data mesh must compute a ragged tail shard
    (rows not divisible by the axis) unsharded instead of crashing on the
    placement error (review fix): sharding is an optimization."""
    from paddle_tpu.parallel.mesh import make_mesh
    from paddle_tpu.trainer.elastic import _pack_arrays
    mesh = make_mesh(data=2)
    w = ElasticWorker(LOSS_FN, ("127.0.0.1", 1), mesh=mesh)
    w._params = jax.device_put(PARAMS0())
    rs = np.random.RandomState(0)
    for rows in (7, 8):                 # ragged tail + divisible shard
        x = rs.randn(rows, 8).astype(np.float32)
        y = rs.randint(0, 2, rows).astype(np.int32)
        loss, grads = w._grad_of({"batch": _pack_arrays([x, y])})
        assert np.isfinite(loss) and jax.tree_util.tree_leaves(grads)


def test_autoscale_recommendation_branches():
    r = autoscale_recommendation(members=0, todo=3, pending=0)
    assert r["action"] == "join"
    r = autoscale_recommendation(members=2, todo=9, pending=1)
    assert r["action"] == "join" and r["backlog_per_worker"] == 5.0
    r = autoscale_recommendation(
        members=3, todo=0, pending=0,
        samples=[{"name": "goodput.ratio", "value": 0.1,
                  "labels": {"worker": "a"}}])
    assert r["action"] == "leave" and r["goodput_ratio"] == 0.1
    r = autoscale_recommendation(
        members=2, todo=0, pending=0,
        samples=[{"name": "data.giveups_total", "value": 4.0}])
    assert r["action"] == "leave" and "starvation" in r["reason"]
    r = autoscale_recommendation(members=2, todo=2, pending=0)
    assert r["action"] == "hold"
    # a lone busy worker is never scaled away
    r = autoscale_recommendation(
        members=1, todo=0, pending=0,
        samples=[{"name": "goodput.ratio", "value": 0.05}])
    assert r["action"] == "hold"


# ---------------------------------------------------------------------------
# MasterClient._call reconnect hardening (ISSUE 14 satellite)
# ---------------------------------------------------------------------------

def test_client_fails_fast_on_structured_fence():
    srv = MasterServer()
    calls = []

    def fenced(req):
        calls.append(1)
        return {"ok": False, "code": "stale_epoch",
                "error": "request epoch 1 != current 7", "epoch": 7}

    # the op name matters: only mbr_*/ela_* replies stamp last_epoch
    # (the built-in "stats" op answers a TaskMaster epoch, not ours)
    srv.register_op("ela_fence", fenced)
    srv.start()
    try:
        c = MasterClient(*srv.address)
        with pytest.raises(StaleMemberError) as ei:
            c._call({"op": "ela_fence"})
        assert ei.value.code == "stale_epoch" and ei.value.epoch == 7
        assert len(calls) == 1          # no reconnect budget burned
        assert c.last_epoch == 7        # the view rode the refusal
        # ...and a stats reply does NOT overwrite it with the queue epoch
        c._call({"op": "stats"})
        assert c.last_epoch == 7
        c.close()
    finally:
        srv.stop()


def test_client_retries_refused_and_reports_attempts_and_epoch():
    srv = MasterServer()
    MembershipService(ttl=10.0).attach(srv)
    srv.start()
    host, port = srv.address
    c = MasterClient(host, port, retries=3, retry_delay=0.01)
    r = c._call({"op": "mbr_join", "worker": "probe"})
    assert r["ok"] and c.last_epoch == 1
    srv.stop()
    t0 = time.monotonic()
    with pytest.raises(ConnectionError) as ei:
        c._call({"op": "mbr_view"})
    msg = str(ei.value)
    # connection-refused was retried (3 attempts), and the final error
    # names both the attempt count and the last membership view we held
    assert "3 attempt(s)" in msg
    assert "last seen membership epoch 1" in msg
    assert time.monotonic() - t0 < 10.0
    c.close()


# ---------------------------------------------------------------------------
# elastic training: equivalence + chaos
# ---------------------------------------------------------------------------

def _sequential_reference(batches, num_passes=1):
    opt = MK_OPT()
    params = jax.device_put(PARAMS0())
    state = opt.init(params)
    upd = jax.jit(lambda g, s, p: opt.update(g, s, p))
    vg = jax.jit(jax.value_and_grad(LOSS_FN))
    loss = float("nan")
    for _ in range(num_passes):
        for bx, by in batches:
            loss, grads = vg(params, bx, by)
            params, state = upd(jax.device_get(grads), state, params)
    return params, float(loss)


def test_elastic_two_workers_matches_sequential():
    """The DP math: shard-ordered weighted reduce == whole-batch gradient
    (to f32 reduction noise), across two real RPC workers."""
    params, loss = _run_static_elastic(2, BATCHES, num_passes=2)
    ref_params, ref_loss = _sequential_reference(BATCHES, num_passes=2)
    _assert_trees_equal(params, ref_params, exact=False)
    assert abs(loss - ref_loss) < 1e-4


@pytest.mark.chaos
def test_kill9_worker_mid_pass_matches_static_run(tmp_path):
    """THE acceptance e2e: 3 subprocess workers under live traffic,
    kill -9 one mid-pass -> heartbeat eviction bumps the epoch, the dead
    worker's in-flight shard re-buckets onto the survivors (dispatch
    timeout deliberately too long to help), the pass completes, and the
    final parameters are BYTE-IDENTICAL to a static 2-worker run's."""
    batches = build(steps=8)[3]
    reg = obs.MetricsRegistry()
    with obs.ObsSession(registry=reg).installed():
        em = ElasticMaster(LOSS_FN, MK_OPT(), ttl=1.2,
                           task_timeout_s=60.0,   # eviction must re-bucket
                           shards_per_step=4, min_workers=3).start()
        host, port = em.address
        env = {k: v for k, v in os.environ.items() if k != "XLA_FLAGS"}
        env["JAX_PLATFORMS"] = "cpu"
        procs = [subprocess.Popen(
            [sys.executable, WORKER_SCRIPT, host, str(port), f"kw{i}",
             "180"], env=env, cwd=REPO, stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT) for i in range(3)]
        state = {"killed": False, "epoch_at_kill": None}

        def killer():
            # SIGKILL kw0 the moment it HOLDS an in-flight shard of a
            # step past the first — the step then cannot complete until
            # the eviction re-buckets that shard onto the survivors
            # (task_timeout_s=60 rules the timeout path out)
            deadline = time.monotonic() + 60.0
            while time.monotonic() < deadline:
                with em._mu:
                    holding = "kw0" in em._assigned.values()
                    step = em._step
                if step >= 1 and holding:
                    state["epoch_at_kill"] = em.membership.epoch
                    os.kill(procs[0].pid, signal.SIGKILL)
                    state["killed"] = True
                    return
                time.sleep(0.001)

        kt = threading.Thread(target=killer, daemon=True)
        kt.start()
        try:
            params, _, loss = em.fit(batches, PARAMS0(), num_passes=1,
                                     progress_timeout=90.0)
            kt.join(timeout=10)
            # the view at pass completion: resharded onto the 2 survivors
            survivors_at_finish = len(em.membership.members())
        finally:
            logs = []
            for p in procs[1:]:
                try:
                    out, _ = p.communicate(timeout=30)
                    logs.append(out.decode(errors="replace"))
                except subprocess.TimeoutExpired:
                    p.kill()
                    logs.append("survivor hung")
            procs[0].wait()
            em.stop()
        assert state["killed"]
        # eviction (not graceful leave) bumped the epoch mid-pass
        assert em.membership.epoch > state["epoch_at_kill"], logs
        assert survivors_at_finish == 2
        assert reg.counter("cluster.leaves_total").get(
            reason="evicted") >= 1
        # the dead worker's in-flight shard re-bucketed via the epoch
        # change (task_timeout_s=60 rules out the timeout path)
        assert reg.counter("cluster.rebucket_tasks_total").get() >= 1
        # survivors exited through the done/leave path
        assert all(p.returncode == 0 for p in procs[1:]), logs

    static_params, static_loss = _run_static_elastic(2, batches)
    _assert_trees_equal(params, static_params, exact=True)
    assert loss == static_loss


@pytest.mark.chaos
def test_rolling_restart_completes_pass_byte_stably():
    """Leave -> rejoin every worker, one at a time, at successive step
    boundaries (the barrier semantics: the cycle runs between updates).
    The pass is never lost or restarted, every rejoin re-fetches and
    re-places the state, and the result is byte-identical to an
    undisturbed fleet's."""
    batches = build(steps=6)[3]
    reg = obs.MetricsRegistry()
    with obs.ObsSession(registry=reg).installed():
        em = ElasticMaster(LOSS_FN, MK_OPT(), ttl=5.0, task_timeout_s=10.0,
                           shards_per_step=4, min_workers=3).start()
        host, port = em.address
        fleet = {}
        for i in range(3):
            stop = threading.Event()
            w, t = _thread_worker(host, port, f"rw{i}", stop)
            fleet[f"rw{i}"] = (w, t, stop)

        def cycle(name):
            w, t, stop = fleet[name]
            stop.set()                      # graceful leave on the way out
            t.join(timeout=10)
            assert not t.is_alive()
            stop2 = threading.Event()
            w2, t2 = _thread_worker(host, port, name, stop2)
            fleet[name] = (w2, t2, stop2)

        def on_step(pass_id, step, loss):
            if step in (1, 2, 3):           # between-update barrier
                cycle(f"rw{step - 1}")

        em.on_step = on_step
        try:
            params, _, loss = em.fit(batches, PARAMS0(), num_passes=1,
                                     progress_timeout=60.0)
        finally:
            for _, t, stop in fleet.values():
                stop.set()
            for _, t, stop in fleet.values():
                t.join(timeout=10)
            em.stop()
        # 3 joins + 3 cycles of (leave + join) = epoch >= 9, no evictions
        assert em.membership.epoch >= 9
        assert reg.counter("cluster.leaves_total").get(
            reason="graceful") >= 3
        assert reg.counter("cluster.joins_total").get() >= 6
        assert reg.counter("cluster.resyncs_total").get() >= 3

    static_params, static_loss = _run_static_elastic(3, batches)
    _assert_trees_equal(params, static_params, exact=True)
    assert loss == static_loss


@pytest.mark.chaos
def test_heartbeat_fault_evicts_and_worker_rejoins():
    """faults-plane chaos on the new ``mbr.heartbeat`` site: injected
    heartbeat failures starve the lease -> the master evicts the worker
    and bumps the epoch; the keeper's next good heartbeat comes back
    ``unknown_member`` and triggers an automatic re-join; the pass
    completes on the re-registered worker."""
    batches = build(steps=10)[3]
    reg = obs.MetricsRegistry()
    plan = FaultPlan(seed=3).add("mbr.heartbeat", "raise", nth=2, count=4)
    with obs.ObsSession(registry=reg).installed(), plan.installed():
        em = ElasticMaster(LOSS_FN, MK_OPT(), ttl=0.75,
                           task_timeout_s=30.0, shards_per_step=2,
                           min_workers=1).start()
        host, port = em.address
        stop = threading.Event()
        w, t = _thread_worker(host, port, "hbw", stop)
        em.on_step = lambda p, s, l: time.sleep(0.2)   # pass spans the chaos
        try:
            params, _, loss = em.fit(batches, PARAMS0(), num_passes=1,
                                     progress_timeout=60.0)
        finally:
            stop.set()
            t.join(timeout=15)
            em.stop()
    assert plan.fired and plan.fired[0][0] == "mbr.heartbeat"
    assert reg.counter("faults.injected_total").get(
        site="mbr.heartbeat", action="raise") >= 1
    # evicted, then re-registered (join counted twice), epoch moved twice+
    assert reg.counter("cluster.leaves_total").get(reason="evicted") >= 1
    assert reg.counter("cluster.joins_total").get() >= 2
    assert em.membership.epoch >= 3
    assert np.isfinite(loss)


@pytest.mark.chaos
def test_master_restart_snapshot_restore_resumes_pass(tmp_path):
    """Master dies mid-pass and restarts on the same port from its
    crash-safe snapshot: workers ride the reconnect budget through the
    refused window, re-register (unknown_member -> re-join), and the SAME
    pass resumes at the snapshotted step — final state byte-identical to
    an uninterrupted run."""
    batches = build(steps=6)[3]
    snap = str(tmp_path / "elastic_snap")
    em1 = ElasticMaster(LOSS_FN, MK_OPT(), ttl=5.0, task_timeout_s=10.0,
                        shards_per_step=4, min_workers=2,
                        snapshot_dir=snap).start()
    host, port = em1.address
    stop = threading.Event()
    pairs = [_thread_worker(host, port, f"mrw{i}", stop) for i in range(2)]
    try:
        em1.fit(batches, PARAMS0(), num_passes=1, max_steps=2,
                progress_timeout=60.0)
        epoch1 = em1.membership.epoch
        em1.stop()                 # connections sever; workers retry
        em2 = ElasticMaster(LOSS_FN, MK_OPT(), host=host, port=port,
                            ttl=5.0, task_timeout_s=10.0,
                            shards_per_step=4, min_workers=2,
                            snapshot_dir=snap).start()
        # restored mid-pass position + persisted epoch (fencing stays
        # monotonic across the restart), members re-register fresh
        assert (em2._pass, em2._step) == (0, 2)
        assert em2.membership.epoch >= epoch1
        params, _, loss = em2.fit(batches, num_passes=1,
                                  progress_timeout=90.0)
        em2.stop()
    finally:
        stop.set()
        for _, t in pairs:
            t.join(timeout=15)
    ref_params, ref_loss = _run_static_elastic(2, batches)
    _assert_trees_equal(params, ref_params, exact=True)
    assert loss == ref_loss


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------

ELASTIC_CFG = """
import os, sys
sys.path.insert(0, {tests_dir!r})
from elastic_testnet import build

def elastic_workload():
    loss_fn, params0, mk_opt, batches = build(steps=4)
    return {{"loss_fn": loss_fn, "params": params0(),
             "optimizer": mk_opt(), "batches": batches}}
"""


@pytest.mark.slow
def test_train_elastic_cli_smoke(tmp_path):
    """`paddle_tpu train --elastic master` + a `--elastic worker`
    subprocess complete one pass over the wire and both exit 0."""
    import socket

    from paddle_tpu.cli import main as cli_main
    cfg = tmp_path / "elastic_cfg.py"
    cfg.write_text(ELASTIC_CFG.format(
        tests_dir=os.path.join(REPO, "tests")))
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    env = {k: v for k, v in os.environ.items() if k != "XLA_FLAGS"}
    env["JAX_PLATFORMS"] = "cpu"
    worker = subprocess.Popen(
        [sys.executable, "-m", "paddle_tpu", "train", "--config", str(cfg),
         "--elastic", "worker", "--master_addr", f"127.0.0.1:{port}",
         "--worker_id", "cli-w0"],
        env=env, cwd=REPO, stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT)
    try:
        rc = cli_main(["train", "--config", str(cfg), "--elastic", "master",
                       "--master_addr", f"127.0.0.1:{port}",
                       "--min_workers", "1", "--num_passes", "1"])
        assert rc == 0
        out, _ = worker.communicate(timeout=60)
        assert worker.returncode == 0, out.decode(errors="replace")
        assert b"job done: True" in out
    finally:
        if worker.poll() is None:
            worker.kill()
