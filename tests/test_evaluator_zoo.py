"""Evaluator-zoo completions: CTC error, detection mAP, pnpair, printers
(gserver/evaluators registry Evaluator.cpp:172-1357, CTCErrorEvaluator.cpp,
DetectionMAPEvaluator.cpp, PnpairEvaluator.cpp)."""

import numpy as np

from paddle_tpu.trainer import (CTCErrorEvaluator, DetectionMAPEvaluator,
                                MaxIdPrinterEvaluator, PnpairEvaluator,
                                ValuePrinterEvaluator)


def test_ctc_error_evaluator():
    import jax.numpy as jnp
    ev = CTCErrorEvaluator()
    # perfect decode: logits peaked on [blank, 1, blank, 2] -> decode [1, 2]
    T, C = 4, 4
    lp = np.full((2, T, C), -10.0, np.float32)
    for b in range(2):
        for t, c in enumerate([0, 1, 0, 2]):
            lp[b, t, c] = 0.0
    labels = np.array([[1, 2], [1, 3]], np.int32)   # row1 has one sub error
    ev.update(log_probs=jnp.asarray(lp),
              logit_lengths=jnp.asarray([4, 4]),
              labels=jnp.asarray(labels),
              label_lengths=jnp.asarray([2, 2]))
    r = ev.result()
    assert abs(r["ctc_error_rate"] - 1 / 4) < 1e-6   # 1 edit / 4 label tokens
    assert abs(r["ctc_seq_error"] - 0.5) < 1e-6


def test_pnpair_evaluator():
    ev = PnpairEvaluator()
    ev.update(scores=np.array([0.9, 0.1, 0.2, 0.8], np.float32),
              labels=np.array([1, 0, 1, 0], np.int32),
              query_ids=np.array([0, 0, 1, 1], np.int32))
    r = ev.result()
    # query0 ordered correctly, query1 wrongly -> ratio 1.0
    assert r["pnpair_pos"] == 1.0 and r["pnpair_neg"] == 1.0
    assert abs(r["pnpair_ratio"] - 1.0) < 1e-9


def test_detection_map_evaluator():
    ev = DetectionMAPEvaluator(num_classes=3)
    gt = np.array([[1, 0, 0, 10, 10],
                   [2, 20, 20, 30, 30]], np.float32)
    det = np.array([
        [1, 0.9, 0, 0, 10, 10],       # perfect match class 1
        [2, 0.8, 21, 21, 30, 30],     # good match class 2
        [2, 0.7, 50, 50, 60, 60],     # false positive class 2
    ], np.float32)
    ev.update(detections=det, gt=gt)
    r = ev.result()
    assert 0.5 < r["detection_map"] <= 1.0


def test_printer_evaluators():
    lines = []
    vp = ValuePrinterEvaluator("logits", log_fn=lambda *a: lines.append(a))
    mp = MaxIdPrinterEvaluator("logits", log_fn=lambda *a: lines.append(a))
    logits = np.array([[0.1, 0.9], [0.8, 0.2]], np.float32)
    vp.update(logits=logits)
    mp.update(logits=logits)
    assert len(lines) == 2
    assert vp.result() == {} and mp.result() == {}
