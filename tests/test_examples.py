"""The examples/ demo configs (v1_api_demo / book-test analogs) train through
the real CLI — the reference's demo-as-acceptance-test discipline."""

import os
import re
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run_cli(*args):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    out = subprocess.run(
        [sys.executable, "-m", "paddle_tpu"] + list(args),
        cwd=REPO, env=env, capture_output=True, text=True, timeout=600)
    assert out.returncode == 0, out.stdout + out.stderr
    return out.stdout


@pytest.mark.parametrize("config,passes", [
    ("examples/fit_a_line.py", "4"),
    ("examples/quick_start_sentiment.py", "2"),
    # slow: ~20s subprocess; the tagger stack it smokes (CRF + recurrent
    # layers) has dedicated tier-1 coverage in test_crf_ctc/test_models,
    # and quick_start keeps the example CLI path itself hot
    pytest.param("examples/sequence_tagging.py", "2",
                 marks=pytest.mark.slow),
])
def test_example_trains_and_cost_falls(config, passes):
    out = _run_cli("train", "--config", config, "--num_passes", passes,
                   "--log_period", "1")
    costs = [float(m) for m in re.findall(r"cost ([-\d.]+)", out)]
    assert len(costs) >= 2, out
    assert costs[-1] < costs[0], out


@pytest.mark.slow
def test_serving_example_runs():
    """examples/serving_llm.py: the continuous-batching serving demo serves
    every request and reports delivered throughput (CI shape).

    slow: ~19s subprocess whose substance (batcher exactness, scheduling,
    parking, int8, speculative) is tier-1-covered by tests/test_serving.py;
    this case only proves the demo SCRIPT wiring (ROADMAP item 5)."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["SERVING_DEMO_SMALL"] = "1"
    out = subprocess.run(
        [sys.executable, "examples/serving_llm.py"],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=600)
    assert out.returncode == 0, out.stdout + out.stderr
    assert "served 10 requests" in out.stdout
    assert "tok/s delivered" in out.stdout


def test_checkgrad_job():
    """--job=checkgrad parity (TrainerMain.cpp:54): numeric vs analytic
    gradients through the executor on a demo config."""
    out = _run_cli("checkgrad", "--config", "examples/fit_a_line.py")
    assert "checkgrad PASS" in out, out


def test_make_diagram_job(tmp_path):
    """make_diagram parity (submit_local.sh.in:13): emits a graphviz dot."""
    out = str(tmp_path / "model.dot")
    txt = _run_cli("make_diagram", "--config", "examples/fit_a_line.py",
                   "--output", out)
    assert "wrote" in txt
    dot = open(out).read()
    assert dot.startswith("digraph G {") and "shape=box" in dot
    assert "square_error" in dot or "mul" in dot
