"""Chaos suite (ISSUE 2): deterministic fault injection against the
checkpoint, RPC, lease and reader layers.

Every test here follows the same discipline:
* failures come from :mod:`paddle_tpu.faults` (seeded, Nth-hit exact) or a
  real SIGKILL/SIGTERM — never from timing races;
* retry/backoff time is driven through fake clocks where possible, so the
  whole file stays inside the tier-1 60s budget;
* the assertion is always *recovery*, not just the failure: training
  resumes byte-identically, the previous good pass survives, the deposed
  holder's write is refused.
"""

import os
import signal
import subprocess
import sys
import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu import faults
from paddle_tpu.data.chunks import (_Starved, chunk_reader, cloud_reader,
                                    dump_to_chunks)
from paddle_tpu.data.prefetch import DoubleBuffer
from paddle_tpu.optimizer import SGD
from paddle_tpu.runtime import native_available
from paddle_tpu.runtime.coord import CoordServer, NetworkFencedStore, \
    NetworkLease, _CoordClient
from paddle_tpu.runtime.lease import FencedFile, FileLease, LeaseKeeper
from paddle_tpu.trainer import Trainer
from paddle_tpu.trainer.checkpoint import (COMPLETE_MANIFEST, latest_pass,
                                           load_checkpoint, pass_dir,
                                           save_checkpoint, verify_checkpoint)
from paddle_tpu.utils.retry import RetryBudgetExceeded, RetryPolicy

pytestmark = pytest.mark.chaos

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# -- deterministic tiny training problem ---------------------------------------

def _make_batches(n=4, bs=8, d=4, seed=0):
    rs = np.random.RandomState(seed)
    return [(rs.randn(bs, d).astype(np.float32),
             rs.randn(bs, 1).astype(np.float32)) for _ in range(n)]


def _loss(params, x, y):
    return jnp.mean((x @ params["w"] + params["b"] - y) ** 2)


def _init(d=4):
    return {"w": np.zeros((d, 1), np.float32), "b": np.zeros(1, np.float32)}


def _param_bytes(params):
    return b"".join(np.asarray(jax.device_get(leaf)).tobytes()
                    for leaf in jax.tree_util.tree_leaves(params))


def _fake_time():
    """(sleep, clock) pair over a virtual clock — no real sleeping."""
    t = [0.0]

    def sleep(s):
        t[0] += s

    return sleep, (lambda: t[0]), t


# -- FaultPlan semantics -------------------------------------------------------

def test_fault_plan_nth_count_window():
    plan = faults.FaultPlan()
    plan.add("rpc.send", "truncate", nth=2, count=2, truncate_to=3)
    with plan.installed():
        out = [faults.filter_bytes("rpc.send", b"abcdef") for _ in range(4)]
    assert out == [b"abcdef", b"abc", b"abc", b"abcdef"]
    assert plan.fired == [("rpc.send", 2, "truncate"),
                          ("rpc.send", 3, "truncate")]
    assert plan.hits["rpc.send"] == 4


def test_fault_plan_zero_cost_when_uninstalled():
    plan = faults.FaultPlan()
    plan.add("rpc.send", "raise")
    # not installed: hooks are no-ops and count nothing
    assert faults.filter_bytes("rpc.send", b"x") == b"x"
    faults.fire("rpc.recv")
    assert not faults.is_active()
    assert plan.hits == {}


def test_fault_plan_exclusive_install_and_bad_site():
    with pytest.raises(ValueError, match="unknown injection site"):
        faults.Fault("not.a.site")
    a, b = faults.FaultPlan(), faults.FaultPlan()
    with a.installed():
        with pytest.raises(RuntimeError, match="already installed"):
            b.install()
    assert not faults.is_active()


def test_fault_corrupt_is_seed_deterministic():
    outs = []
    for _ in range(2):
        plan = faults.FaultPlan(seed=42)
        plan.add("rpc.send", "corrupt", nth=1)
        with plan.installed():
            outs.append(faults.filter_bytes("rpc.send", b"hello world"))
    assert outs[0] == outs[1] != b"hello world"


def test_fire_site_rejects_payload_actions():
    plan = faults.FaultPlan()
    plan.add("lease.renew", "truncate")
    with plan.installed():
        with pytest.raises(faults.FaultError, match="only supports"):
            faults.fire("lease.renew")


# -- RetryPolicy ---------------------------------------------------------------

def test_retry_policy_exponential_capped_schedule():
    sleep, clock, t = _fake_time()
    slept = []
    pol = RetryPolicy(max_attempts=5, base_delay=0.1, multiplier=2.0,
                      max_delay=0.3, jitter=0.0,
                      sleep=lambda s: (slept.append(s), sleep(s)),
                      clock=clock)
    with pytest.raises(RetryBudgetExceeded) as ei:
        pol.call(lambda: (_ for _ in ()).throw(OSError("down")),
                 describe="probe")
    assert ei.value.attempts == 5
    assert isinstance(ei.value, ConnectionError)
    assert "5 attempt" in str(ei.value)
    np.testing.assert_allclose(slept, [0.1, 0.2, 0.3, 0.3])  # capped


def test_retry_policy_deadline_bounds_total_wait():
    sleep, clock, t = _fake_time()
    pol = RetryPolicy(max_attempts=None, base_delay=1.0, multiplier=1.0,
                      max_delay=1.0, deadline=3.5, jitter=0.0,
                      sleep=sleep, clock=clock)
    with pytest.raises(RetryBudgetExceeded) as ei:
        pol.call(lambda: (_ for _ in ()).throw(ConnectionError("down")))
    assert t[0] <= 3.5
    assert ei.value.attempts == 4           # t=0,1,2,3 then next would bust


def test_retry_policy_jitter_seeded_deterministic():
    def schedule(seed):
        sleep, clock, _ = _fake_time()
        slept = []
        pol = RetryPolicy(max_attempts=4, base_delay=0.1, jitter=0.5,
                          seed=seed, sleep=lambda s: slept.append(s),
                          clock=clock)
        with pytest.raises(RetryBudgetExceeded):
            pol.call(lambda: (_ for _ in ()).throw(OSError()))
        return slept

    assert schedule(7) == schedule(7)
    assert schedule(7) != schedule(8)


def test_retry_policy_nonretryable_propagates_and_success_returns():
    sleep, clock, _ = _fake_time()
    pol = RetryPolicy(max_attempts=5, jitter=0.0, sleep=sleep, clock=clock)
    with pytest.raises(ValueError):
        pol.call(lambda: (_ for _ in ()).throw(ValueError("logic bug")))
    calls = {"n": 0}
    retries = []

    def flaky():
        calls["n"] += 1
        if calls["n"] < 3:
            raise OSError("transient")
        return "ok"

    assert pol.call(flaky, on_retry=lambda a, e: retries.append(a)) == "ok"
    assert retries == [1, 2]


# -- crash-safe checkpointing --------------------------------------------------

def test_crash_mid_write_preserves_previous_pass(tmp_path):
    out = str(tmp_path / "ckpt")
    params = _init()
    save_checkpoint(out, 0, params)
    plan = faults.FaultPlan()
    plan.add("ckpt.write", "raise", nth=1, exc=OSError("torn write"))
    with plan.installed():
        with pytest.raises(OSError):
            save_checkpoint(out, 1, params)
    # the torn pass-1 never became visible; pass 0 is intact
    assert latest_pass(out) == 0
    assert os.path.exists(pass_dir(out, 1) + ".tmp")
    assert not os.path.exists(pass_dir(out, 1))
    p, o, st = load_checkpoint(out)
    assert st["pass_id"] == 0 and st["pass_complete"]
    # a later writer reclaims the leftover .tmp and publishes cleanly
    save_checkpoint(out, 1, params)
    assert latest_pass(out) == 1 and verify_checkpoint(pass_dir(out, 1))


def test_truncated_member_fails_verify_and_falls_back(tmp_path):
    out = str(tmp_path / "ckpt")
    good = {"w": np.arange(16, dtype=np.float32)}
    save_checkpoint(out, 0, good)
    plan = faults.FaultPlan()
    plan.add("ckpt.write", "truncate", nth=1, truncate_to=32)
    with plan.installed():
        save_checkpoint(out, 1, good)       # publishes a torn params.tar
    assert latest_pass(out) == 1            # manifest exists...
    assert not verify_checkpoint(pass_dir(out, 1))
    assert latest_pass(out, verify=True) == 0
    p, o, st = load_checkpoint(out)         # ...but load refuses it
    assert st["pass_id"] == 0
    np.testing.assert_array_equal(p["w"], good["w"])
    # an explicit pass_id is gated by the same verification, not an
    # escape hatch around it
    with pytest.raises(ValueError, match="verification"):
        load_checkpoint(out, 1)


def test_resume_with_only_corrupt_checkpoints_starts_fresh(tmp_path):
    out = str(tmp_path / "ckpt")
    plan = faults.FaultPlan()
    plan.add("ckpt.write", "truncate", nth=1, truncate_to=16)
    with plan.installed():
        save_checkpoint(out, 0, _init())    # every member torn
    assert latest_pass(out) == 0 and latest_pass(out, verify=True) is None
    # resume=True must fall through to fresh init, not die on
    # "no verifiable checkpoints"
    t = Trainer(_loss, SGD(0.1), output_dir=out)
    p, _ = t.train(lambda: _make_batches(n=2), _init(), num_passes=1,
                   resume=True, handle_signals=False)
    assert np.all(np.isfinite(np.asarray(p["w"])))


def test_latest_pass_requires_manifest(tmp_path):
    # mere existence of params.tar is not a checkpoint (the old bug)
    d = str(tmp_path / "out")
    torn = os.path.join(d, "pass-00003")
    os.makedirs(torn)
    with open(os.path.join(torn, "params.tar"), "wb") as f:
        f.write(b"\x00" * 100)              # truncated garbage
    assert latest_pass(d) is None
    save_checkpoint(d, 1, _init())
    assert latest_pass(d) == 1              # manifest-bearing pass wins
    p, o, st = load_checkpoint(d)
    assert st["pass_id"] == 1


def test_kill9_mid_checkpoint_write_then_resume(tmp_path):
    """A real SIGKILL lands while pass-1 members are being written: the
    surviving state must resume from pass 0 with no corrupt-tar load and no
    lost completed pass (ISSUE 2 acceptance criterion)."""
    out = str(tmp_path / "ckpt")
    sentinel = str(tmp_path / "inside-write")
    p = subprocess.Popen(
        [sys.executable, os.path.join(_REPO, "tests", "chaos_ckpt_writer.py"),
         out, sentinel],
        cwd=_REPO, stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)
    try:
        deadline = time.time() + 60
        while not os.path.exists(sentinel):
            assert p.poll() is None, "writer died before reaching the stall"
            assert time.time() < deadline, "writer never reached the stall"
            time.sleep(0.02)
        p.kill()                            # SIGKILL mid-checkpoint-write
    finally:
        p.wait(timeout=10)
    # pass 1 is torn (params.tar written, no manifest); pass 0 survives
    assert latest_pass(out) == 0
    assert os.path.exists(pass_dir(out, 1) + ".tmp")
    params, opt_state, st = load_checkpoint(out)
    assert st["pass_id"] == 0
    np.testing.assert_array_equal(
        params["w"], np.arange(64, dtype=np.float32).reshape(8, 8))
    # and training picks up where the victim left off
    batches = _make_batches(n=2, d=8, seed=3)
    batches = [(x, y[:, :1]) for x, y in batches]

    def loss8(pp, x, y):
        return jnp.mean((x @ pp["w"] + pp["b"] - y) ** 2)

    t = Trainer(loss8, SGD(0.01), output_dir=out)
    t.train(lambda: batches, None, num_passes=1, resume=True,
            handle_signals=False)
    assert latest_pass(out, verify=True) == 1
    assert verify_checkpoint(pass_dir(out, 1))


# -- trainer preemption + byte-identical resume --------------------------------

def test_sigterm_mid_pass_checkpoints_and_resumes_byte_identical(tmp_path):
    batches = _make_batches(n=4)

    # reference: uninterrupted 2-pass run
    ref = Trainer(_loss, SGD(0.1), output_dir=str(tmp_path / "ref"))
    ref_params, _ = ref.train(lambda: batches, _init(), num_passes=2,
                              handle_signals=False)

    # victim: SIGTERM lands during pass 1, batch 1
    out = str(tmp_path / "victim")
    victim = Trainer(_loss, SGD(0.1), output_dir=out)

    def handler(e):
        from paddle_tpu.trainer import event
        if isinstance(e, event.EndIteration) and e.pass_id == 1 \
                and e.batch_id == 1:
            os.kill(os.getpid(), signal.SIGTERM)

    victim.train(lambda: batches, _init(), num_passes=2,
                 event_handler=handler)
    assert victim.preempted
    assert victim.train_stats["preemptions"] == 1
    # the preemption checkpoint is durable, marked incomplete, mid-pass
    pid = latest_pass(out, verify=True)
    assert pid == 1
    _, _, st = load_checkpoint(out)
    assert st["pass_complete"] is False and st["batch_id"] == 1
    # pass 0's completed checkpoint was NOT lost
    assert verify_checkpoint(pass_dir(out, 0))

    # resume: continues pass 1 at batch 2 — byte-identical to uninterrupted
    resumed = Trainer(_loss, SGD(0.1), output_dir=out)
    res_params, _ = resumed.train(lambda: batches, _init(), num_passes=1,
                                  resume=True, handle_signals=False)
    assert _param_bytes(res_params) == _param_bytes(ref_params)
    # the re-saved pass 1 is now complete
    _, _, st = load_checkpoint(out)
    assert st["pass_id"] == 1 and st["pass_complete"]


def test_signal_handlers_installed_and_restored():
    batches = _make_batches(n=1)
    prev_term = signal.getsignal(signal.SIGTERM)
    t = Trainer(_loss, SGD(0.1))
    t.train(lambda: batches, _init(), num_passes=1)   # handle_signals=True
    assert signal.getsignal(signal.SIGTERM) is prev_term


def test_checkpoint_every_cadence(tmp_path):
    out = str(tmp_path / "ckpt")
    t = Trainer(_loss, SGD(0.1), output_dir=out)
    t.train(lambda: _make_batches(n=2), _init(), num_passes=4,
            checkpoint_every=2, handle_signals=False)
    have = {pid for pid in range(4) if os.path.exists(
        os.path.join(pass_dir(out, pid), COMPLETE_MANIFEST))}
    assert have == {1, 3}                   # every 2nd pass (final included)


# -- non-finite loss policy ----------------------------------------------------

def test_on_nonfinite_skip_drops_batch_exactly(tmp_path):
    batches = _make_batches(n=4)
    poisoned = list(batches)
    x2, y2 = poisoned[2]
    poisoned[2] = (np.full_like(x2, np.inf), y2)

    t = Trainer(_loss, SGD(0.1), on_nonfinite="skip")
    p_skip, _ = t.train(lambda: poisoned, _init(), num_passes=1,
                        handle_signals=False)
    assert t.train_stats["skipped_batches"] == 1
    assert t.train_stats["nonfinite_batches"] == 1

    # dropping the poisoned batch must equal never having seen it
    clean = [b for i, b in enumerate(batches) if i != 2]
    t2 = Trainer(_loss, SGD(0.1))
    p_clean, _ = t2.train(lambda: clean, _init(), num_passes=1,
                          handle_signals=False)
    assert _param_bytes(p_skip) == _param_bytes(p_clean)
    assert np.all(np.isfinite(np.asarray(p_skip["w"])))


def test_on_nonfinite_halt_checkpoints_then_raises(tmp_path):
    out = str(tmp_path / "ckpt")
    plan = faults.FaultPlan()
    plan.add("step.grad", "corrupt", nth=2)   # NaN at batch 1
    t = Trainer(_loss, SGD(0.1), output_dir=out, on_nonfinite="halt")
    with plan.installed():
        with pytest.raises(FloatingPointError, match="non-finite"):
            t.train(lambda: _make_batches(n=4), _init(), num_passes=1,
                    handle_signals=False)
    # state was made durable BEFORE the raise
    _, _, st = load_checkpoint(out)
    assert st["halted"] is True and st["pass_complete"] is False
    assert st["batch_id"] == 1


def test_on_nonfinite_halt_checkpoints_last_finite_state(tmp_path):
    """halt must drop the poisoned update before checkpointing: a durable
    NaN tree would make resume start from garbage — worse than no
    checkpoint at all."""
    out = str(tmp_path / "ckpt")
    batches = _make_batches(n=4)
    poisoned = list(batches)
    x2, y2 = poisoned[2]
    poisoned[2] = (np.full_like(x2, np.inf), y2)
    t = Trainer(_loss, SGD(0.1), output_dir=out, on_nonfinite="halt")
    with pytest.raises(FloatingPointError, match="non-finite"):
        t.train(lambda: poisoned, _init(), num_passes=1,
                handle_signals=False)
    p_halt, _, st = load_checkpoint(out)
    assert st["halted"] is True and st["batch_id"] == 2
    assert np.all(np.isfinite(np.asarray(p_halt["w"])))
    # the checkpoint equals training on the finite prefix alone
    t2 = Trainer(_loss, SGD(0.1))
    p_clean, _ = t2.train(lambda: batches[:2], _init(), num_passes=1,
                          handle_signals=False)
    assert _param_bytes(p_halt) == _param_bytes(p_clean)


def test_torn_swap_is_recovered_on_discovery(tmp_path):
    """Re-publishing a pass swaps dirs with two renames; a crash between
    them leaves the pass only under .old/.tmp names. Discovery must heal
    that window: a verified .tmp rolls forward, else .old rolls back."""
    out = str(tmp_path / "ckpt")
    a = {"w": np.zeros((4, 1), np.float32)}
    b = {"w": np.ones((4, 1), np.float32)}

    # roll-back case: crash after rename(d, old), .tmp not yet complete
    save_checkpoint(out, 0, a)
    os.rename(pass_dir(out, 0), pass_dir(out, 0) + ".old")
    assert latest_pass(out) == 0            # recovery restored .old
    p, _, _ = load_checkpoint(out)
    np.testing.assert_array_equal(p["w"], a["w"])

    # roll-forward case: .tmp carries a full verified manifest, d missing
    scratch = str(tmp_path / "scratch")
    save_checkpoint(scratch, 0, b)
    os.rename(pass_dir(out, 0), pass_dir(out, 0) + ".old")
    os.rename(pass_dir(scratch, 0), pass_dir(out, 0) + ".tmp")
    assert latest_pass(out) == 0
    p, _, _ = load_checkpoint(out)
    np.testing.assert_array_equal(p["w"], b["w"])   # newer write won
    assert not os.path.exists(pass_dir(out, 0) + ".old")
    assert not os.path.exists(pass_dir(out, 0) + ".tmp")


def test_on_nonfinite_default_raise_via_fault():
    plan = faults.FaultPlan()
    plan.add("step.grad", "corrupt", nth=1)
    t = Trainer(_loss, SGD(0.1))
    with plan.installed():
        with pytest.raises(FloatingPointError, match="non-finite"):
            t.train(lambda: _make_batches(n=2), _init(), num_passes=1,
                    handle_signals=False)


# -- RPC chaos -----------------------------------------------------------------

def _fast_policy(attempts=5):
    return RetryPolicy(max_attempts=attempts, base_delay=0.001,
                       max_delay=0.002, jitter=0.0, sleep=lambda s: None)


@pytest.mark.skipif(not native_available(),
                    reason="native toolchain unavailable")
def test_master_rpc_dropped_requests_are_retried(tmp_path):
    from paddle_tpu.runtime.master_service import MasterClient, MasterServer
    srv = MasterServer(snapshot_path=str(tmp_path / "m.snap"),
                       tick_interval=0.2).start()
    try:
        c = MasterClient(*srv.address, retry_policy=_fast_policy())
        plan = faults.FaultPlan()
        plan.add("rpc.send", "raise", nth=1, count=2,
                 exc=ConnectionError("injected drop"))
        with plan.installed():
            c.set_dataset(["t0", "t1"])     # survives two dropped sends
        assert [f for f in plan.fired
                if f[0] == "rpc.send"] == [("rpc.send", 1, "raise"),
                                           ("rpc.send", 2, "raise")]
        got = []
        while True:
            task = c.get_task()
            if task is None:
                break
            got.append(task[1])
            c.task_finished(task[0])
        assert sorted(got) == ["t0", "t1"]
        c.close()
    finally:
        srv.stop()


@pytest.mark.skipif(not native_available(),
                    reason="native toolchain unavailable")
def test_master_rpc_budget_exhaustion_surfaces_attempts(tmp_path):
    from paddle_tpu.runtime.master_service import MasterClient, MasterServer
    srv = MasterServer(snapshot_path=str(tmp_path / "m.snap"),
                       tick_interval=0.2).start()
    try:
        c = MasterClient(*srv.address, retry_policy=_fast_policy(attempts=3))
        plan = faults.FaultPlan()
        plan.add("rpc.send", "raise", nth=1, count=99,
                 exc=ConnectionError("injected outage"))
        with plan.installed():
            with pytest.raises(ConnectionError, match="3 attempt"):
                c.stats()
        c.close()
    finally:
        srv.stop()


def test_corrupt_frame_drops_connection_then_recovers():
    """A corrupted request frame must desync-proof the protocol: the server
    severs the connection, the client reconnects and the retried call
    succeeds (CRC-less framing + bit rot handled at the retry layer)."""
    srv = CoordServer().start()
    try:
        c = _CoordClient(*srv.address, retry_policy=_fast_policy())
        plan = faults.FaultPlan()
        plan.add("rpc.send", "corrupt", nth=1)
        with plan.installed():
            r = c.call({"op": "ping"})
        assert r["ok"]
        assert ("rpc.send", 1, "corrupt") in plan.fired
        c.close()
    finally:
        srv.stop()


def test_torn_frame_times_out_then_recovers():
    """A truncated frame (header promises more bytes than arrive) wedges
    the receiver; the sender's per-call socket timeout converts the wedge
    into a retry instead of an indefinite hang."""
    srv = CoordServer().start()
    try:
        c = _CoordClient(*srv.address, call_timeout=0.2,
                         retry_policy=_fast_policy())
        plan = faults.FaultPlan()
        plan.add("rpc.send", "truncate", nth=1, truncate_to=2)
        t0 = time.monotonic()
        with plan.installed():
            r = c.call({"op": "ping"})
        assert r["ok"]
        assert time.monotonic() - t0 < 5.0
        c.close()
    finally:
        srv.stop()


# -- lease renewal stall + fencing ---------------------------------------------

def test_file_lease_renewal_stall_deposes_holder(tmp_path):
    """Renewal stalls past TTL (injected FS outage): the standby takes over
    with a higher token, and the deposed holder's next fenced write is
    refused — the stale master never lands a write."""
    lease_path = str(tmp_path / "lease")
    snap = str(tmp_path / "snap")
    a = FileLease(lease_path, owner="a", ttl=1.0)
    assert a.try_acquire()
    fence = FencedFile(snap)
    assert fence.claim(a.token)
    assert fence.write(a.token, lambda p: open(p, "w").write("gen-a"))

    plan = faults.FaultPlan()
    plan.add("lease.renew", "raise", nth=1, count=99,
             exc=OSError("injected NFS outage"))
    with plan.installed():
        with pytest.raises(OSError):
            a.renew()

    # TTL expires (time travel, no real sleep); standby b takes over
    later = time.time() + a.ttl + 1.0
    b = FileLease(lease_path, owner="b", ttl=1.0)
    assert b.try_acquire(now=later)
    assert b.token > a.token
    assert fence.claim(b.token)

    wrote = {"a": False}

    def stale_writer(p):
        wrote["a"] = True
        with open(p, "w") as f:
            f.write("stale-from-a")

    assert fence.write(a.token, stale_writer) is False
    assert fence.write(b.token, lambda p: open(p, "w").write("gen-b"))
    with open(snap) as f:
        assert f.read() == "gen-b"          # a's write never landed
    # even though a's writer ran, its output was discarded pre-publish
    assert wrote["a"]
    assert fence.write(a.token, stale_writer) is False   # still refused


def test_lease_keeper_declares_lost_after_ttl_of_stalls(tmp_path):
    """LeaseKeeper tolerates transient renew failures only while our TTL
    could still be running; past it, the lease is LOST and on_lost fires."""
    lease = FileLease(str(tmp_path / "lease"), owner="a", ttl=0.45)
    assert lease.try_acquire()
    lost = threading.Event()
    plan = faults.FaultPlan()
    plan.add("lease.renew", "raise", nth=1, count=999,
             exc=OSError("injected stall"))
    keeper = LeaseKeeper(lease, interval=0.1, on_lost=lost.set)
    with plan.installed():
        keeper.start()
        assert lost.wait(timeout=10.0), "keeper never declared the lease lost"
    keeper.stop(release=False)
    assert plan.hits["lease.renew"] >= 2    # it kept trying through the TTL


def test_network_lease_renewal_stall_fenced_write_refused():
    """The NetworkLease variant of the deposition story, server-judged TTL:
    holder a stalls (renewals raise), the lease expires on the server, b
    takes over, and a's fenced snapshot write is refused (ISSUE 2
    satellite)."""
    srv = CoordServer().start()
    try:
        host, port = srv.address
        a = NetworkLease(host, port, owner="a", ttl=0.3)
        assert a.try_acquire()
        store_a = NetworkFencedStore(host, port)
        assert store_a.claim(a.token)
        assert store_a.write(a.token, lambda p: open(p, "w").write("gen-a"))

        plan = faults.FaultPlan()
        plan.add("lease.renew", "raise", nth=1, count=999,
                 exc=ConnectionError("injected stall"))
        with plan.installed():
            with pytest.raises(ConnectionError):
                a.renew()
            time.sleep(0.4)                 # server-side TTL expiry
            b = NetworkLease(host, port, owner="b", ttl=5.0)
            assert b.try_acquire()
            assert b.token > a.token
            store_b = NetworkFencedStore(host, port)
            assert store_b.claim(b.token)
            # deposed holder's write refused; new generation's lands
            assert store_a.write(
                a.token, lambda p: open(p, "w").write("stale")) is False
            assert store_b.write(
                b.token, lambda p: open(p, "w").write("gen-b"))
        import tempfile
        fd, tmp = tempfile.mkstemp()
        os.close(fd)
        try:
            assert store_b.fetch_to(tmp)
            with open(tmp) as f:
                assert f.read() == "gen-b"
        finally:
            os.remove(tmp)
        a.close()
        b.close()
        store_a.close()
        store_b.close()
    finally:
        srv.stop()


# -- reader/prefetch chaos -----------------------------------------------------

class _FakeMaster:
    """Scripted in-process master for reader tests (no network)."""

    def __init__(self, tasks):
        self.todo = dict(tasks)             # id -> payload
        self.pending = {}
        self.failed = []
        self.finished = []

    def get_task(self):
        if not self.todo:
            return None
        tid, payload = next(iter(self.todo.items()))
        self.pending[tid] = self.todo.pop(tid)
        return tid, payload

    def stats(self):
        return len(self.todo), len(self.pending), len(self.finished), 0, 0

    def task_failed(self, tid):
        self.todo[tid] = self.pending.pop(tid)   # immediate re-dispatch
        self.failed.append(tid)
        return False

    def task_finished(self, tid):
        self.finished.append(self.pending.pop(tid))

    def new_pass(self):
        return False


def test_cloud_reader_task_failure_redispatches(tmp_path):
    paths = dump_to_chunks(lambda: iter(range(10)), str(tmp_path / "chunks"),
                           samples_per_chunk=5)
    assert len(paths) == 2
    master = _FakeMaster({i: p for i, p in enumerate(paths)})
    plan = faults.FaultPlan()
    plan.add("reader.next", "raise", nth=1, exc=OSError("injected read error"))
    with plan.installed():
        got = sorted(cloud_reader(master)())
    assert got == list(range(10))           # nothing lost
    assert master.failed == [0]             # first task failed once...
    assert len(master.finished) == 2        # ...then both completed


def test_cloud_reader_starvation_deadline_no_real_sleep():
    sleep, clock, t = _fake_time()

    class Starver:
        def get_task(self):
            return None

        def stats(self):
            return (0, 1, 0, 0, 0)          # pending forever, never done

    policy = RetryPolicy(max_attempts=None, base_delay=0.1, multiplier=1.5,
                         max_delay=1.0, deadline=30.0, jitter=0.0,
                         retryable=_Starved, sleep=sleep, clock=clock)
    with pytest.raises(TimeoutError, match="starved"):
        list(cloud_reader(Starver(), poll_policy=policy)())
    assert t[0] <= 30.0                     # virtual time only


def test_double_buffer_watchdog_times_out():
    stall = threading.Event()

    def wedged():
        yield (np.zeros(2),)
        stall.wait()                        # producer hangs forever

    buf = DoubleBuffer(wedged, depth=2, timeout=0.2)
    it = iter(buf)
    next(it)                                # first batch flows
    with pytest.raises(TimeoutError, match="watchdog"):
        next(it)
    stall.set()                             # release the worker thread
