"""Fleet actor (ISSUE 18): the loop that closes autoscale.

Contract under test (docs/design/fleet.md): the actor polls each
population's control plane, converts hysteresis-stable recommendations
and SLO burn alerts into spawns/drains through the injectable spawn
seam, damped by per-action cooldowns and a fleet-wide churn cap; drains
are graceful-before-evict and NEVER retire the last busy worker or dip
below ``min_workers``; committed actions journal to the master under
single-writer fencing (a second actor deposes the first); under a
shared worker budget, training yields to serving on SLO burn and
reclaims on resolve. All chaos here runs under fake clocks — the only
real-time pieces are the thread-worker integration tests at the bottom.
"""

import threading
import time

import jax
import numpy as np
import pytest

from elastic_testnet import build
from paddle_tpu import nn, obs
from paddle_tpu.cluster import (ActorReporter, FleetActor, FleetScheduler,
                                HookSpawnBackend, MasterProbe, Population,
                                SpawnHandle)
from paddle_tpu.faults import FaultPlan
from paddle_tpu.obs.aggregate import ClusterAggregator
from paddle_tpu.obs.health import health_table
from paddle_tpu.runtime.master_service import MasterServer, StaleMemberError
from paddle_tpu.runtime.membership import MembershipService
from paddle_tpu.trainer.elastic import ElasticMaster, ElasticWorker

LOSS_FN, PARAMS0, MK_OPT, BATCHES = build(steps=6)


# ---------------------------------------------------------------------------
# the fleet scheduler (weighted-fair deficit over workers)
# ---------------------------------------------------------------------------

def test_scheduler_weighted_allocation_favors_serving():
    s = FleetScheduler()                      # serve:4, train:1
    grants = s.allocate(5, {"serve": 4, "train": 4})
    assert grants == {"serve": 4, "train": 1}


def test_scheduler_urgent_population_served_first():
    s = FleetScheduler(weights={"serve": 1.0, "train": 8.0})
    grants = s.allocate(2, {"serve": 2, "train": 2}, urgent={"serve"})
    # urgency beats weight: the burning population takes the whole supply
    assert grants["serve"] == 2 and grants.get("train", 0) == 0


def test_scheduler_idle_population_credit_resets():
    s = FleetScheduler(weights={"a": 1.0, "b": 1.0})
    g = s.allocate(1, {"a": 4, "b": 4})       # the loser banks credit
    loser = "a" if g.get("b") else "b"
    assert s.credits()[loser] > 0.0
    s.allocate(0, {("b" if loser == "a" else "a"): 4})   # loser goes idle
    assert s.credits()[loser] == 0.0          # no banking while idle


def test_scheduler_preempt_picks_lowest_weight_over_floor():
    s = FleetScheduler()
    victim = s.preempt({"serve": 2, "train": 3},
                       {"serve": 1, "train": 1}, "serve")
    assert victim == "train"
    # at its floor the batch population is untouchable
    assert s.preempt({"serve": 2, "train": 1},
                     {"serve": 1, "train": 1}, "serve") is None
    # an urgent population is never a victim
    assert s.preempt({"serve": 2, "train": 3}, {"serve": 1, "train": 1},
                     "serve", urgent={"train"}) is None


# ---------------------------------------------------------------------------
# actor unit tests: a fake in-memory population under a fake clock
# ---------------------------------------------------------------------------

class _FakePool:
    """In-memory population: spawn joins on the next tick, drain leaves
    immediately (the graceful path), tokens are join order."""

    def __init__(self, workers=()):
        self._tok = 0
        self.members = {}
        self.recommendation = None
        self.alerts = []
        self.busy = False
        self.drained = []
        self.killed = []
        for w in workers:
            self.join(w)

    def join(self, worker):
        self._tok += 1
        self.members[worker] = self._tok

    def spawn_fn(self, worker, population):
        self.join(worker)                     # joins before the next probe

    def drain_fn(self, handle):
        self.drained.append(handle.worker)
        self.members.pop(handle.worker, None)

    def kill_fn(self, handle):
        self.killed.append(handle.worker)
        self.members.pop(handle.worker, None)

    def alive_fn(self, handle):
        return handle.worker in self.members

    def backend(self, **kw):
        hooks = {"spawn_fn": self.spawn_fn, "drain_fn": self.drain_fn,
                 "kill_fn": self.kill_fn, "alive_fn": self.alive_fn}
        hooks.update(kw)
        return HookSpawnBackend(hooks.pop("spawn_fn"), **hooks)

    def probe(self):
        return {"members": [{"worker": w, "token": t}
                            for w, t in sorted(self.members.items())],
                "recommendation": self.recommendation,
                "alerts": list(self.alerts), "busy": self.busy}


def _actor(pools, clock, **kw):
    kw.setdefault("cooldown_s", 5.0)
    kw.setdefault("max_churn", 1)
    return FleetActor(pools, clock=lambda: clock[0], **kw)


def test_actor_spawns_on_join_recommendation():
    clock = [0.0]
    pool = _FakePool(["w0", "w1"])
    pool.recommendation = {"action": "join", "reason": "backlog", "backlog": 9}
    pop = Population("train", backend=pool.backend(), probe=pool.probe)
    actor = _actor([pop], clock)
    committed = actor.step()
    assert [e["action"] for e in committed] == ["spawn"]
    assert committed[0]["worker"] in pool.members
    assert committed[0]["signal"] == 1.0
    # the recommendation satisfied, the next tick holds
    pool.recommendation = {"action": "hold"}
    clock[0] = 10.0
    assert actor.step() == []


def test_actor_cooldown_damps_repeat_spawns():
    clock = [0.0]
    pool = _FakePool(["w0"])
    pop = Population("serve", backend=pool.backend(), probe=pool.probe,
                     target=4, max_workers=4)
    actor = _actor([pop], clock, max_churn=4)
    assert len(actor.step()) > 0              # first batch commits
    n_after_first = len(pool.members)
    pool.members.pop(next(iter(pool.members)))  # still under target...
    clock[0] = 1.0                            # ...but inside the cooldown
    assert actor.step() == []
    clock[0] = 6.0                            # cooled: acts again
    assert len(actor.step()) > 0
    assert len(pool.members) >= n_after_first


def test_actor_churn_cap_bounds_one_tick():
    clock = [0.0]
    pool = _FakePool(["w0"])
    pop = Population("serve", backend=pool.backend(), probe=pool.probe,
                     target=5, max_workers=8)
    actor = _actor([pop], clock, max_churn=2)
    committed = actor.step()
    # 4 short of target but only 2 concurrent spawns allowed
    assert [e["action"] for e in committed] == ["spawn", "spawn"]


def test_actor_spawn_failure_is_journaled_not_fatal():
    clock = [0.0]
    reg = obs.MetricsRegistry()
    pool = _FakePool(["w0"])
    pool.recommendation = {"action": "join"}
    pop = Population("train", backend=pool.backend(), probe=pool.probe)
    actor = _actor([pop], clock)
    plan = FaultPlan(seed=0).add("actor.spawn", "raise")
    with obs.ObsSession(registry=reg).installed(), plan.installed():
        committed = actor.step()
    assert [e["action"] for e in committed] == ["spawn_failed"]
    assert committed[0]["signal"] == 0.0
    assert reg.counter("cluster.actor_failures_total").get(
        action="spawn") == 1
    assert reg.counter("faults.injected_total").get(
        site="actor.spawn", action="raise") == 1
    assert len(pool.members) == 1             # nothing half-spawned
    assert not actor.deposed                  # the loop survives chaos


def test_actor_spawn_that_never_joins_fails_after_grace():
    clock = [0.0]
    pool = _FakePool(["w0"])
    pool.recommendation = {"action": "join"}
    # a backend whose processes start but never reach membership
    pop = Population("train",
                     backend=pool.backend(spawn_fn=lambda w, p: None),
                     probe=pool.probe)
    actor = _actor([pop], clock, spawn_grace_s=30.0)
    assert [e["action"] for e in actor.step()] == ["spawn"]
    clock[0] = 31.0
    committed = actor.step()
    assert any(e["action"] == "spawn_failed" for e in committed)


def test_actor_leave_racing_spawn_grace_drains_at_most_one():
    """A `leave` recommendation arriving while a spawn is still inside
    its grace window (a very slow boot: process alive, not yet joined)
    must shrink the pool by ONE live member — the unjoined spawn counts
    toward effective capacity but is not a drainable worker, so it must
    not inflate the drain into a second live departure."""
    clock = [0.0]
    pool = _FakePool(["w0", "w1"])
    pool.recommendation = {"action": "join"}
    # the spawned process starts (alive) but never reaches membership
    pop = Population("serve",
                     backend=pool.backend(spawn_fn=lambda w, p: None,
                                          alive_fn=lambda h: True),
                     probe=pool.probe, min_workers=0)
    actor = _actor([pop], clock, max_churn=8, spawn_grace_s=60.0)
    assert [e["action"] for e in actor.step()] == ["spawn"]
    pool.recommendation = {"action": "leave", "reason": "idle"}
    clock[0] = 10.0                           # inside the spawn grace
    committed = actor.step()
    assert [e["action"] for e in committed] == ["drain"]
    assert len(pool.members) == 1, \
        "leave racing an in-grace spawn double-drained the live pool"
    # the pending spawn itself was neither failed nor drained
    assert len(actor._spawning["serve"]) == 1


def test_actor_drain_escalates_to_evict_after_grace():
    clock = [0.0]
    pool = _FakePool(["w0", "w1", "w2"])
    # a drain that hangs: the worker ignores the graceful request
    pop = Population("serve", backend=pool.backend(
        drain_fn=lambda h: pool.drained.append(h.worker)),
        probe=pool.probe, target=2, min_workers=1)
    actor = _actor([pop], clock, drain_grace_s=20.0)
    committed = actor.step()
    assert [e["action"] for e in committed] == ["drain"]
    victim = committed[0]["worker"]
    assert victim == "w2"                     # newest incarnation first
    clock[0] = 21.0                           # grace expires: escalate
    committed = actor.step()
    assert any(e["action"] == "evict" and e["worker"] == victim
               for e in committed)
    assert victim in pool.killed


def test_actor_faultplan_delay_on_drain_uses_fake_sleep():
    clock = [0.0]
    slept = []
    pool = _FakePool(["w0", "w1"])
    pop = Population("serve", backend=pool.backend(), probe=pool.probe,
                     target=1, min_workers=1)
    actor = _actor([pop], clock)
    plan = FaultPlan(seed=0, sleep=slept.append).add(
        "actor.drain", "delay", delay_s=3.0)
    with plan.installed():
        committed = actor.step()
    assert slept == [3.0]                     # chaos delay, zero real sleep
    assert [e["action"] for e in committed] == ["drain"]


# ---------------------------------------------------------------------------
# the graceful-leave-storm safety bar
# ---------------------------------------------------------------------------

def test_actor_never_drains_below_min_workers():
    clock = [0.0]
    pool = _FakePool(["w0", "w1", "w2"])
    pop = Population("serve", backend=pool.backend(), probe=pool.probe,
                     target=0, min_workers=2)
    actor = _actor([pop], clock, max_churn=8)
    for i in range(10):
        clock[0] = i * 10.0
        actor.step()
        assert len(pool.members) >= 2
    assert len(pool.members) == 2


def test_actor_never_retires_last_busy_worker():
    clock = [0.0]
    pool = _FakePool(["w0", "w1", "w2", "w3"])
    pool.busy = True                          # live decode stream /
    pop = Population("serve", backend=pool.backend(), probe=pool.probe,
                     target=0, min_workers=0)  # in-flight elastic shard
    actor = _actor([pop], clock, max_churn=8)
    for i in range(12):
        clock[0] = i * 10.0
        actor.step()
        assert len(pool.members) >= 1, "rolling drain evicted the fleet"
    assert len(pool.members) == 1             # drained down to the floor...
    pool.busy = False
    clock[0] = 200.0
    actor.step()
    assert len(pool.members) == 0             # ...and out once idle


def test_actor_rolling_drain_storm_is_one_at_a_time():
    clock = [0.0]
    pool = _FakePool([f"w{i}" for i in range(6)])
    pool.busy = True
    pop = Population("serve", backend=pool.backend(), probe=pool.probe,
                     target=1, min_workers=1)
    actor = _actor([pop], clock, max_churn=1, cooldown_s=5.0)
    sizes = []
    for i in range(20):
        clock[0] = i * 6.0
        actor.step()
        sizes.append(len(pool.members))
    # monotone rolling drain, never more than one departure per tick
    assert all(a - b in (0, 1) for a, b in zip(sizes, sizes[1:]))
    assert sizes[-1] == 1


# ---------------------------------------------------------------------------
# train/serve unification: yield on SLO burn, reclaim on resolve
# ---------------------------------------------------------------------------

def test_actor_training_yields_to_burning_serving_and_reclaims():
    clock = [0.0]
    serve, train = _FakePool(["s0", "s1"]), _FakePool(["t0", "t1", "t2"])
    serve_pop = Population("serve", backend=serve.backend(),
                           probe=serve.probe, target=2, min_workers=1,
                           max_workers=6)
    train_pop = Population("train", backend=train.backend(),
                           probe=train.probe, target=3, min_workers=1,
                           max_workers=6)
    actor = _actor([serve_pop, train_pop], clock, total_workers=5,
                   max_churn=2)
    assert actor.step() == []                 # budget-balanced steady state
    serve.alerts = ["serving_ttft_slo_burn"]  # serving starts burning
    clock[0] = 10.0
    committed = actor.step()
    # no free budget: training yields one worker for the urgent pool
    assert [(e["action"], e["population"]) for e in committed] == \
        [("drain", "train")]
    assert "yield" in committed[0]["reason"]
    clock[0] = 20.0
    committed = actor.step()                  # freed slot goes to serving
    assert ("spawn", "serve") in [(e["action"], e["population"])
                                  for e in committed]
    assert len(serve.members) == 3
    serve.alerts = []                         # burn resolves
    clock[0] = 30.0
    committed = actor.step()                  # serving back to target...
    assert [(e["action"], e["population"]) for e in committed] == \
        [("drain", "serve")]
    clock[0] = 40.0
    committed = actor.step()                  # ...and training reclaims
    assert [(e["action"], e["population"]) for e in committed] == \
        [("spawn", "train")]
    assert "reclaim" in committed[0]["reason"]
    assert len(train.members) == 3 and len(serve.members) == 2


# ---------------------------------------------------------------------------
# single-writer fencing + the committed-action journal (act_* ops)
# ---------------------------------------------------------------------------

class _DispatchActClient:
    """MembershipClient.act_* over in-process dispatch (no TCP)."""

    def __init__(self, srv):
        self.srv = srv

    def act_register(self, actor):
        r = self.srv._dispatch({"op": "act_register", "actor": actor})
        assert r.get("ok"), r
        return r["actor_token"], r["epoch"]

    def act_report(self, actor, token, *, action, population, worker,
                   reason="", signal=0.0):
        r = self.srv._dispatch({
            "op": "act_report", "actor": actor, "actor_token": token,
            "action": action, "population": population, "worker": worker,
            "reason": reason, "signal": signal})
        if not r.get("ok"):
            raise StaleMemberError(r.get("error", "?"),
                                   code=r.get("code", "unknown_member"),
                                   epoch=r.get("epoch"))
        return r["epoch"]

    def close(self):
        pass


def test_act_report_single_writer_fencing():
    srv = MasterServer()
    MembershipService(ttl=10.0).attach(srv)
    r1 = srv._dispatch({"op": "act_register", "actor": "a1"})
    assert r1["ok"]
    ok = srv._dispatch({"op": "act_report", "actor": "a1",
                        "actor_token": r1["actor_token"],
                        "action": "spawn", "population": "serve",
                        "worker": "w1", "reason": "scale out",
                        "signal": 1.0})
    assert ok["ok"]
    # a second actor registers: the first one's token goes stale
    r2 = srv._dispatch({"op": "act_register", "actor": "a2"})
    assert r2["actor_token"] > r1["actor_token"]
    stale = srv._dispatch({"op": "act_report", "actor": "a1",
                           "actor_token": r1["actor_token"],
                           "action": "drain", "population": "serve",
                           "worker": "w1", "signal": -1.0})
    assert not stale["ok"] and stale["code"] == "unknown_member"
    wrong_tok = srv._dispatch({"op": "act_report", "actor": "a2",
                               "actor_token": r1["actor_token"],
                               "action": "drain", "population": "serve",
                               "worker": "w1", "signal": -1.0})
    assert not wrong_tok["ok"] and wrong_tok["code"] == "stale_member"
    # only the accepted report landed in the journal
    actions = srv.aggregator.recent_actions()
    assert [a["action"] for a in actions] == ["spawn"]
    # ... and obs_health surfaces it to every health consumer
    h = srv._dispatch({"op": "obs_health"})
    assert h["ok"] and h["actions"][-1]["worker"] == "w1"


def test_deposed_actor_stands_down():
    srv = MasterServer()
    MembershipService(ttl=10.0).attach(srv)
    clock = [0.0]
    pool = _FakePool(["w0"])
    pool.recommendation = {"action": "join"}
    reporter = ActorReporter("x", 0, "actor-1",
                             client=_DispatchActClient(srv))
    pop = Population("train", backend=pool.backend(), probe=pool.probe,
                     reporter=reporter)
    actor = _actor([pop], clock)
    actor.step()
    assert not actor.deposed
    assert srv.aggregator.recent_actions()[-1]["actor"] == "actor-1"
    # a rival actor takes over the fleet
    ActorReporter("x", 0, "actor-2", client=_DispatchActClient(srv))(
        {"action": "spawn", "population": "train", "worker": "wx",
         "reason": "takeover", "signal": 1.0})
    pool.recommendation = {"action": "join"}
    clock[0] = 10.0
    actor.step()                              # report fenced -> stand down
    assert actor.deposed
    # run() exits immediately for a deposed actor
    actor.run(max_ticks=100)


# ---------------------------------------------------------------------------
# obs surfacing: committed gauge, action tail, /alerts endpoint
# ---------------------------------------------------------------------------

def test_note_action_emits_gauge_and_journal():
    reg = obs.MetricsRegistry()
    clock = [100.0]
    agg = ClusterAggregator(clock=lambda: clock[0])
    with obs.ObsSession(registry=reg).installed():
        agg.note_action({"actor": "a", "action": "spawn",
                         "population": "serve", "worker": "s-w1",
                         "reason": "scale out", "signal": 1.0})
        agg.note_action({"actor": "a", "action": "drain",
                         "population": "train", "worker": "t-w9",
                         "reason": "yield: serve SLO burn", "signal": -1.0})
    acts = agg.recent_actions()
    assert [a["action"] for a in acts] == ["spawn", "drain"]
    assert acts[0]["ts"] == 100.0
    # the committed gauge tracks the LAST action's signal
    assert reg.gauge("cluster.autoscale_committed").get() == -1.0
    assert reg.counter("cluster.actor_actions_total").get(
        population="serve", action="spawn") == 1
    # ... and the gauge is in history, so alert rules can threshold it
    from paddle_tpu.obs.health import MASTER_WORKER
    pts = agg.history.points(MASTER_WORKER, "cluster.autoscale_committed",
                             now=clock[0])
    assert [v for _, v in pts] == [1.0, -1.0]


def test_health_table_renders_action_tail():
    acts = [{"ts": 12.0, "actor": "a", "action": "spawn",
             "population": "serve", "worker": "serve-w1",
             "reason": "scale out", "signal": 1.0}]
    txt = health_table({}, actions=acts)
    assert "autoscale actions" in txt
    assert "serve-w1" in txt and "scale out" in txt
    # with workers present the tail rides below the table
    samples = [{"type": "gauge", "name": "goodput.ratio",
                "labels": {"worker": "w1"}, "value": 0.9}]
    txt = health_table(samples, actions=acts)
    assert txt.index("w1") < txt.index("autoscale actions")


def test_alerts_endpoint_serves_actions():
    import http.client
    import json
    from paddle_tpu.obs.aggregate import ObsHttpServer
    dump = {"workers": {}, "alerts": [],
            "actions": [{"ts": 1.0, "actor": "a", "action": "spawn",
                         "population": "serve", "worker": "w1",
                         "reason": "scale out", "signal": 1.0}]}
    srv = ObsHttpServer(lambda: dump).start()
    try:
        host, port = srv.address
        conn = http.client.HTTPConnection(host, port, timeout=5)
        conn.request("GET", "/alerts")
        resp = conn.getresponse()
        body = json.loads(resp.read().decode())
        conn.close()
        assert resp.status == 200
        assert body["actions"][0]["action"] == "spawn"
    finally:
        srv.stop()


# ---------------------------------------------------------------------------
# the chaos bar: kill -9 half the decode pool (fake clock end to end)
# ---------------------------------------------------------------------------

@pytest.mark.chaos
def test_kill_half_decode_pool_recovers_slo_without_flapping():
    """The ISSUE 18 acceptance oracle, reusing the bench simulation
    (benchmarks/fleet_autoscale.py): real membership leases, real
    burn-rate alert engine, real actor; kill -9 modeled as heartbeats
    stopping. Alert TRANSITIONS are the oracle: each degraded series
    fires exactly once and resolves exactly once — a second fire is
    flapping and fails here."""
    from benchmarks.fleet_autoscale import run
    row = run()
    assert row["slo_recovered"] is True
    assert row["flaps"] == 0
    assert row["fired"] == row["resolved"] == 2   # one per survivor series
    assert row["recovery_windows"] is not None
    assert row["recovery_windows"] <= 3           # bounded alert windows
    assert row["spawn_failures"] == 0 and row["evictions"] == 0
    # schema: the _fleet_ family rules hold on the emitted row
    from paddle_tpu.analysis.bench_schema import validate_row
    assert validate_row(row) == []


# ---------------------------------------------------------------------------
# integration: the actor drives a REAL elastic fleet; trajectory is
# byte-stable across every fleet shape it chooses
# ---------------------------------------------------------------------------

def _flat(params):
    return {k: np.asarray(v) for k, v in
            nn.Module.named_parameters(jax.device_get(params))}


def _assert_trees_equal(a, b):
    fa, fb = _flat(a), _flat(b)
    assert set(fa) == set(fb)
    for k in fa:
        np.testing.assert_array_equal(fa[k], fb[k], err_msg=k)


def _run_static_elastic(n_workers, batches):
    em = ElasticMaster(LOSS_FN, MK_OPT(), ttl=5.0, task_timeout_s=10.0,
                       shards_per_step=4, min_workers=n_workers).start()
    host, port = em.address
    stop = threading.Event()
    threads = []
    for i in range(n_workers):
        w = ElasticWorker(LOSS_FN, (host, port), worker=f"static{i}")
        t = threading.Thread(target=w.run, kwargs={"stop": stop},
                             daemon=True)
        t.start()
        threads.append(t)
    try:
        params, _, loss = em.fit(batches, PARAMS0(), num_passes=1,
                                 progress_timeout=60.0)
    finally:
        stop.set()
        for t in threads:
            t.join(timeout=15)
        em.stop()
    return params, loss


@pytest.mark.chaos
def test_actor_scaled_elastic_fleet_is_byte_stable():
    """The actor spawns the training fleet from zero, then drains a
    worker mid-pass (graceful: the worker finishes its in-flight shard
    and leaves at the barrier). The parameter trajectory must equal the
    static two-worker run bit for bit — fleet shape is the actor's
    business, arithmetic is not."""
    em = ElasticMaster(LOSS_FN, MK_OPT(), ttl=5.0, task_timeout_s=10.0,
                       shards_per_step=4, min_workers=1).start()
    host, port = em.address
    stops, threads = {}, {}

    def spawn_fn(worker, population):
        ev = threading.Event()
        w = ElasticWorker(LOSS_FN, (host, port), worker=worker)
        t = threading.Thread(target=w.run, kwargs={"stop": ev},
                             daemon=True)
        t.start()
        stops[worker], threads[worker] = ev, t

    def drain_fn(handle):
        ev = stops.get(handle.worker)
        if ev is not None:
            ev.set()            # graceful: drain at the next barrier

    def alive_fn(handle):
        t = threads.get(handle.worker)
        return t is not None and t.is_alive()

    real_probe = MasterProbe(host, port)

    def probe():
        ob = real_probe()
        ob["recommendation"] = None    # the target alone steers this test
        return ob

    pop = Population("train",
                     backend=HookSpawnBackend(spawn_fn, drain_fn,
                                              alive_fn=alive_fn),
                     probe=probe, min_workers=1, max_workers=2, target=2)
    actor = FleetActor([pop], cooldown_s=0.0, max_churn=2,
                       spawn_grace_s=30.0, drain_grace_s=30.0)
    result = {}

    def fit():
        result["params"], _, result["loss"] = em.fit(
            BATCHES, PARAMS0(), num_passes=1, progress_timeout=60.0)

    ft = threading.Thread(target=fit, daemon=True)
    ft.start()
    try:
        deadline = time.monotonic() + 30.0
        while time.monotonic() < deadline \
                and len(em.membership.members()) < 2:
            actor.step()
            time.sleep(0.05)
        assert len(em.membership.members()) == 2, "actor never built fleet"
        pop.target = 1           # mid-pass scale-in
        while time.monotonic() < deadline and ft.is_alive() \
                and len(em.membership.members()) > 1:
            actor.step()
            time.sleep(0.05)
        ft.join(timeout=60.0)
        assert not ft.is_alive()
    finally:
        for ev in stops.values():
            ev.set()
        for t in threads.values():
            t.join(timeout=15)
        real_probe.close()
        em.stop()
    spawns = [e for e in actor.journal if e["action"] == "spawn"]
    assert len(spawns) == 2 and all(e["population"] == "train"
                                    for e in spawns)
    static_params, static_loss = _run_static_elastic(2, BATCHES)
    _assert_trees_equal(result["params"], static_params)
    assert result["loss"] == static_loss


# ---------------------------------------------------------------------------
# the serving daemon's drain ordering (graceful-drain-before-evict)
# ---------------------------------------------------------------------------

def test_daemon_stop_leaves_router_before_draining():
    """A routed daemon must leave membership FIRST so the router stops
    placing on it and re-routes, and only then wait out in-flight work —
    leaving last would strand every stream placed during the drain."""
    import types
    from paddle_tpu.serving.daemon import ServingDaemon
    calls = []
    d = ServingDaemon.__new__(ServingDaemon)
    d._draining = threading.Event()
    d._stop = threading.Event()
    d._obs_thread = None
    d._keeper = object()                      # joined a router
    d.engine = types.SimpleNamespace(
        stats=lambda: (calls.append("drain-poll"),
                       {"slots_live": 0, "queue_depth": 0})[1],
        pending_results=lambda: 0,
        stop=lambda: calls.append("engine-stop"))
    d.server = types.SimpleNamespace(
        stop=lambda: calls.append("server-stop"),
        conn_count_supported=True,
        active_connections=lambda: 0)
    d._leave_router = lambda: calls.append("leave")
    d.stop(drain_s=0.5)
    assert calls[0] == "leave"                # left BEFORE the drain wait
    assert calls.index("leave") < calls.index("drain-poll")
    assert calls[-2:] == ["server-stop", "engine-stop"]


# ---------------------------------------------------------------------------
# CLI surface
# ---------------------------------------------------------------------------

def test_cluster_autoscale_cli_validation():
    from paddle_tpu.cli import main
    # no populations configured
    assert main(["cluster", "autoscale", "--once"]) == 2
    # malformed endpoint
    assert main(["cluster", "autoscale", "--router", "nohostport",
                 "--decode-cmd", "echo {worker}", "--once"]) == 2
    # launch template without the {worker} placeholder
    assert main(["cluster", "autoscale", "--router", "127.0.0.1:1",
                 "--decode-cmd", "echo hi", "--once"]) == 2


def test_cluster_autoscale_cli_once_survives_down_plane():
    """--once against a dead control plane: the probe fails, the actor
    skips the population, and the command exits cleanly (an actor must
    outlive the planes it watches)."""
    from paddle_tpu.cli import main
    assert main(["cluster", "autoscale", "--router", "127.0.0.1:1",
                 "--decode-cmd", "echo {worker}", "--once"]) == 0
