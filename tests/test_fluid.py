"""Fluid (Program IR + Executor) tests — the book-test shapes of
fluid/tests/book/test_recognize_digits_mlp.py and fit_a_line, plus IR
round-trip and executable-cache behavior."""

import numpy as np
import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu.data.dataset import mnist, uci_housing


@pytest.fixture(autouse=True)
def fresh_programs():
    fluid.reset_default_programs()
    # fresh scope per test
    fluid.executor._global_scope = fluid.Scope()
    yield


def _run_startup(exe):
    exe.run(fluid.default_startup_program())


def test_fit_a_line():
    """fluid/tests/book/test_fit_a_line.py analog: linear regression to low loss."""
    x = fluid.layers.data("x", shape=(13,))
    y = fluid.layers.data("y", shape=(1,))
    pred = fluid.layers.fc(x, 1)
    b = fluid.default_main_program().global_block()
    diff = fluid.layers.elementwise_sub(pred, y)
    sq = fluid.layers.elementwise_mul(diff, diff)
    loss = fluid.layers.mean(sq)
    fluid.SGDOptimizer(0.01).minimize(loss)

    exe = fluid.Executor()
    _run_startup(exe)
    data = list(uci_housing.train(256)())
    xs = np.stack([d[0] for d in data])
    ys = np.stack([d[1] for d in data])
    first = None
    for i in range(50):
        out, = exe.run(feed={"x": xs, "y": ys}, fetch_list=[loss])
        if first is None:
            first = float(out)
    assert float(out) < first * 0.5


def test_recognize_digits_mlp():
    """MNIST MLP book test: train to decreasing loss with Adam + accuracy."""
    img = fluid.layers.data("img", shape=(784,))
    label = fluid.layers.data("label", shape=(), dtype="int32")
    h1 = fluid.layers.fc(img, 128, act="relu")
    h2 = fluid.layers.fc(h1, 64, act="relu")
    logits = fluid.layers.fc(h2, 10)
    loss_vec = fluid.layers.softmax_with_cross_entropy(logits, label)
    loss = fluid.layers.mean(loss_vec)
    acc = fluid.layers.accuracy(logits, label)
    fluid.AdamOptimizer(1e-3).minimize(loss)

    exe = fluid.Executor()
    _run_startup(exe)
    data = list(mnist.train(512)())
    xs = np.stack([d[0] for d in data])
    ys = np.array([d[1] for d in data], np.int32)
    costs = []
    for i in range(30):
        c, a = exe.run(feed={"img": xs, "label": ys},
                       fetch_list=[loss, acc])
        costs.append(float(c))
    assert costs[-1] < costs[0] * 0.5
    assert float(a) > 0.5


def test_executable_cache_reused():
    x = fluid.layers.data("x", shape=(4,))
    out = fluid.layers.fc(x, 2)
    exe = fluid.Executor()
    _run_startup(exe)
    exe.run(feed={"x": np.ones((3, 4), np.float32)}, fetch_list=[out])
    n1 = len(exe._cache)
    exe.run(feed={"x": np.zeros((3, 4), np.float32)}, fetch_list=[out])
    assert len(exe._cache) == n1          # same shapes -> cache hit
    exe.run(feed={"x": np.ones((5, 4), np.float32)}, fetch_list=[out])
    assert len(exe._cache) == n1 + 1      # new batch shape -> new executable


def test_program_serialization_roundtrip():
    x = fluid.layers.data("x", shape=(4,))
    h = fluid.layers.fc(x, 8, act="tanh")
    out = fluid.layers.fc(h, 2)
    prog = fluid.default_main_program()
    d = prog.to_dict()
    import json
    d2 = json.loads(json.dumps(d, default=str))
    back = fluid.Program.from_dict(d)
    assert len(back.global_block().ops) == len(prog.global_block().ops)
    assert set(back.global_block().vars) == set(prog.global_block().vars)


def test_prune_drops_dead_ops():
    x = fluid.layers.data("x", shape=(4,))
    used = fluid.layers.fc(x, 2)
    dead = fluid.layers.fc(x, 3)   # never fetched
    prog = fluid.default_main_program()
    pruned = prog.prune([used.name])
    kept_types = [op.type for op in pruned.global_block().ops]
    assert len(pruned.global_block().ops) < len(prog.global_block().ops)
    # the dead fc's mul op must be gone
    dead_inputs = {n for op in prog.global_block().ops
                   if dead.name in op.output_vars() for n in op.input_vars()}
    for op in pruned.global_block().ops:
        assert dead.name not in op.output_vars()


def test_momentum_optimizer_runs():
    x = fluid.layers.data("x", shape=(4,))
    y = fluid.layers.data("y", shape=(1,))
    pred = fluid.layers.fc(x, 1)
    diff = fluid.layers.elementwise_sub(pred, y)
    loss = fluid.layers.mean(fluid.layers.elementwise_mul(diff, diff))
    fluid.MomentumOptimizer(0.01, momentum=0.9).minimize(loss)
    exe = fluid.Executor()
    _run_startup(exe)
    rs = np.random.RandomState(0)
    xs = rs.randn(32, 4).astype(np.float32)
    ys = (xs @ rs.randn(4, 1)).astype(np.float32)
    c0 = float(exe.run(feed={"x": xs, "y": ys}, fetch_list=[loss])[0])
    for _ in range(30):
        c = float(exe.run(feed={"x": xs, "y": ys}, fetch_list=[loss])[0])
    assert c < c0 * 0.5


def test_save_load_persistables(tmp_path):
    x = fluid.layers.data("x", shape=(4,))
    out = fluid.layers.fc(x, 2)
    exe = fluid.Executor()
    _run_startup(exe)
    r1 = exe.run(feed={"x": np.ones((2, 4), np.float32)}, fetch_list=[out])[0]
    fluid.io.save_persistables(exe, str(tmp_path))
    # clobber the scope, reload, same output
    fluid.executor._global_scope = fluid.Scope()
    exe2 = fluid.Executor()
    fluid.io.load_persistables(exe2, str(tmp_path))
    r2 = exe2.run(fluid.default_main_program(),
                  feed={"x": np.ones((2, 4), np.float32)}, fetch_list=[out])[0]
    np.testing.assert_allclose(r1, r2, rtol=1e-6)


# ------------------------------------------------------------ fast path ------
# donation / device-resident scope / shape bucketing / bounded LRU
# (docs/design/executor_perf.md)

def _donation_supported() -> bool:
    """Whether this backend actually invalidates donated buffers (CPU does
    on current jaxlib; if a backend silently ignores donation, correctness
    asserts still hold — only the invalidation assert is skipped)."""
    import jax
    import jax.numpy as jnp
    x = jnp.ones((2,))
    jax.jit(lambda a: a + 1, donate_argnums=0)(x)
    return x.is_deleted()


def _sgd_line_program():
    x = fluid.layers.data("x", shape=(4,))
    y = fluid.layers.data("y", shape=(1,))
    pred = fluid.layers.fc(x, 1)
    diff = fluid.layers.elementwise_sub(pred, y)
    loss = fluid.layers.mean(fluid.layers.elementwise_mul(diff, diff))
    fluid.SGDOptimizer(0.05).minimize(loss)
    rs = np.random.RandomState(0)
    xs = rs.randn(16, 4).astype(np.float32)
    ys = (xs @ rs.randn(4, 1)).astype(np.float32)
    return loss, {"x": xs, "y": ys}


def test_donation_updates_persistables_in_place():
    """3 donating runs with return_numpy=False: updates land in the scope
    (loss keeps falling), the old parameter buffer is invalidated, and a
    same-shape re-run never re-reads a donated buffer."""
    import jax
    loss, feed = _sgd_line_program()
    wname = next(v.name for v in fluid.default_main_program()
                 .global_block().all_parameters())
    exe = fluid.Executor()
    _run_startup(exe)
    costs = []
    old_refs = []
    for _ in range(3):
        old_refs.append(exe.scope.get(wname))
        out, = exe.run(feed=feed, fetch_list=[loss], return_numpy=False)
        assert isinstance(out, jax.Array)    # lazy fetch: no host sync
        costs.append(float(np.asarray(out)))
    assert costs[2] < costs[0]               # in-place updates are visible
    # scope stays device-resident between runs
    assert isinstance(exe.scope.get(wname), jax.Array)
    if _donation_supported():
        for ref in old_refs:
            assert ref.is_deleted()          # old buffers are gone for good


def test_donation_opt_outs():
    """A persistable that is fetched in the same run is kept readable, and
    donate=False keeps every old buffer alive."""
    loss, feed = _sgd_line_program()
    wname = next(v.name for v in fluid.default_main_program()
                 .global_block().all_parameters())
    # fetched + written -> automatic opt-out for that persistable
    exe = fluid.Executor()
    _run_startup(exe)
    w_old = np.asarray(exe.scope.get(wname))
    out_w, _ = exe.run(feed=feed, fetch_list=[wname, loss])
    assert not np.allclose(out_w, w_old)       # fetch sees the NEW value
    np.testing.assert_allclose(out_w, np.asarray(exe.scope.get(wname)))
    # donate=False escape hatch: the pre-run reference survives
    ref = exe.scope.get(wname)
    exe.run(feed=feed, fetch_list=[loss], donate=False)
    assert not getattr(ref, "is_deleted", lambda: False)()
    np.asarray(ref)                            # still readable


def test_fed_persistable_overrides_scope_value():
    """Feeding a persistable must use the FED value, not the stale scope
    copy (the scope copy doesn't even ride to the device), and a written
    fed persistable syncs its update back to the scope."""
    loss, feed = _sgd_line_program()
    wname = next(v.name for v in fluid.default_main_program()
                 .global_block().all_parameters())
    exe = fluid.Executor()
    _run_startup(exe)
    c_scope = float(exe.run(feed=feed, fetch_list=[loss], donate=False)[0])
    # re-feed wildly different weights: the loss must reflect THEM
    w_shape = np.asarray(exe.scope.get(wname)).shape
    big = np.full(w_shape, 100.0, np.float32)
    c_fed = float(exe.run(feed={**feed, wname: big},
                          fetch_list=[loss])[0])
    assert c_fed > c_scope * 10                # the fed value was used
    # the optimizer update applied ON TOP of the fed value reached the scope
    w_after = np.asarray(exe.scope.get(wname))
    assert np.abs(w_after).max() > 50          # near 100, not the old scope w


def test_donation_while_subblock_persistable():
    """A persistable written only inside a while sub-block updates
    correctly across 3 donating runs (the loop carry flows back to the
    scope and the old buffer is retired)."""
    from paddle_tpu.fluid import layers
    b = fluid.default_main_program().global_block()
    acc = b.create_var(name="acc", shape=(), dtype="int32",
                       persistable=True, trainable=False)
    i = layers.fill_constant((), "int32", 0)
    n = layers.fill_constant((), "int32", 5)
    cond = layers.less_than(i, n)
    with fluid.While(cond).block():
        sb = fluid.default_main_program().current_block()
        sb.append_op("elementwise_add", {"X": [acc.name], "Y": [i.name]},
                     {"Out": [acc.name]})
        layers.increment(i)
        layers.less_than(i, n, cond=cond)
    exe = fluid.Executor()
    exe.scope.set("acc", np.int32(0))
    vals = []
    refs = []
    for _ in range(3):
        refs.append(exe.scope.get("acc"))
        exe.run(feed={}, fetch_list=[i])
        vals.append(int(np.asarray(exe.scope.get("acc"))))
    assert vals == [10, 20, 30]        # += sum(0..4) per run, in place
    if _donation_supported():
        # run 2's input was run 1's device output: donated, hence retired
        # (run 1's input was the host np scalar seed — never donatable)
        assert refs[1].is_deleted() and refs[2].is_deleted()


def test_bucketing_bounds_recompiles_and_matches_unbucketed():
    """8 distinct lengths under a 2-bucket spec compile exactly twice (the
    jax.compiles_total obs bridge is the witness) and agree element-wise
    with the unbucketed run on the true lengths."""
    from paddle_tpu import obs
    w = fluid.layers.data("w", shape=(-1,))
    sq = fluid.layers.elementwise_mul(w, w)
    exe = fluid.Executor(buckets={"w": (8, 16)})
    lengths = (3, 5, 6, 7, 9, 10, 12, 15)
    feeds = {L: np.arange(2 * L, dtype=np.float32).reshape(2, L)
             for L in lengths}
    # warmup OUTSIDE the counted window: a length in a third bucket (pow-2
    # overflow past 16) warms every eager path (scalar @LEN conversion,
    # device_put, fetch) without touching the two buckets under test
    exe.run(feed={"w": np.ones((2, 20), np.float32)}, fetch_list=[sq])
    r = obs.MetricsRegistry()
    with obs.ObsSession(registry=r).installed():
        bucketed = {L: exe.run(feed={"w": feeds[L]}, fetch_list=[sq])[0]
                    for L in lengths}
    assert r.counter("jax.compiles_total").get() == 2
    assert r.counter("fluid.cache_misses_total").get(bucketed="true") == 2
    assert r.counter("fluid.cache_hits_total").get(bucketed="true") == 6
    import warnings
    exe_plain = fluid.Executor()               # no spec: one compile per length
    with warnings.catch_warnings():
        # this comparison loop churns shapes BY DESIGN — scope its L006
        warnings.simplefilter("ignore", RuntimeWarning)
        for L in lengths:
            out_b = bucketed[L]
            assert out_b.shape[1] in (8, 16)   # padded to the bucket
            out_u, = exe_plain.run(feed={"w": feeds[L]}, fetch_list=[sq])
            np.testing.assert_array_equal(out_b[:, :L], out_u)
            assert np.all(out_b[:, L:] == 0)   # zero pad tail


def test_bucketing_feeds_true_length():
    """The true extent rides along as <name>@LEN so programs can mask."""
    w = fluid.layers.data("w", shape=(-1,))
    ln = fluid.default_main_program().global_block().create_var(
        name="w@LEN", shape=(), dtype="int32", is_data=True)
    total = fluid.layers.elementwise_add(
        fluid.layers.mean(w), fluid.layers.cast(ln, "float32"))
    exe = fluid.Executor(buckets={"w": (8,)})
    out, = exe.run(feed={"w": np.zeros((2, 5), np.float32)},
                   fetch_list=[total])
    assert float(out) == 5.0                   # mean(0-pad)=0 + true len 5


def test_cache_lru_bounded_with_evictions():
    from paddle_tpu import obs
    x = fluid.layers.data("x", shape=(4,))
    out = fluid.layers.fc(x, 2)
    exe = fluid.Executor(cache_capacity=2)
    _run_startup(exe)
    r = obs.MetricsRegistry()
    with obs.ObsSession(registry=r).installed():
        for bs in (1, 2, 3):                   # 3 shapes, capacity 2
            exe.run(feed={"x": np.ones((bs, 4), np.float32)},
                    fetch_list=[out])
        assert len(exe._cache) == 2
        # startup fn + 3 feed shapes through a 2-entry LRU = 2 evictions
        assert r.counter("fluid.cache_evictions_total").get() == 2
        assert r.gauge("fluid.cache_size").get() == 2
        # the evicted shape still runs correctly (rebuild, evicting again)
        res, = exe.run(feed={"x": np.ones((1, 4), np.float32)},
                       fetch_list=[out])
    assert res.shape == (1, 2)
    assert len(exe._cache) == 2


def test_shape_churn_warns_l006():
    import warnings
    x = fluid.layers.data("x", shape=(-1,))
    y = fluid.layers.mean(x)
    exe = fluid.Executor()
    with warnings.catch_warnings(record=True) as got:
        warnings.simplefilter("always")
        for L in range(1, 8):                  # unbucketed shape churn
            exe.run(feed={"x": np.ones((2, L), np.float32)},
                    fetch_list=[y])
    msgs = [str(w.message) for w in got if "L006" in str(w.message)]
    assert len(msgs) == 1                      # warns once, names the lint
    assert "buckets" in msgs[0]
    # a bucketed executor never churns -> never warns
    exe_b = fluid.Executor(buckets={"x": (8,)})
    with warnings.catch_warnings(record=True) as got:
        warnings.simplefilter("always")
        for L in range(1, 8):
            exe_b.run(feed={"x": np.ones((2, L), np.float32)},
                      fetch_list=[y])
    assert not [w for w in got if "L006" in str(w.message)]


def test_shape_churn_warns_when_spec_misses_the_varying_feed():
    """A BucketSpec that doesn't cover the feed that actually varies still
    recompiles per length — L006 must fire and say to extend the spec."""
    import warnings
    x = fluid.layers.data("x", shape=(-1,))
    z = fluid.layers.data("z", shape=(-1,))
    out = fluid.layers.elementwise_add(fluid.layers.mean(x),
                                       fluid.layers.mean(z))
    exe = fluid.Executor(buckets={"x": (8,)})   # z is NOT covered
    with warnings.catch_warnings(record=True) as got:
        warnings.simplefilter("always")
        for L in range(1, 8):                   # z churns unbounded
            exe.run(feed={"x": np.ones((2, 3), np.float32),
                          "z": np.ones((2, L), np.float32)},
                    fetch_list=[out])
    msgs = [str(w.message) for w in got if "L006" in str(w.message)]
    assert len(msgs) == 1 and "extend the BucketSpec" in msgs[0]


def test_covering_spec_warmup_is_not_shape_churn():
    """One compile per bucket during warmup of a fully-covering spec is the
    bounded behavior bucketing promises — L006 must stay quiet even when
    the spec has >= _CHURN_STREAK buckets (the threshold scales with the
    spec's own shape-family size)."""
    import warnings
    x = fluid.layers.data("x", shape=(-1,))
    y = fluid.layers.mean(x)
    exe = fluid.Executor(buckets={"x": (2, 4, 8, 16)})
    with warnings.catch_warnings(record=True) as got:
        warnings.simplefilter("always")
        for L in (2, 3, 7, 12, 20):            # one per bucket + overflow
            exe.run(feed={"x": np.ones((2, L), np.float32)},
                    fetch_list=[y])
    assert not [w for w in got if "L006" in str(w.message)]
    assert len(exe._cache) == 5                # every run was a fresh bucket


def test_lru_eviction_thrash_is_not_shape_churn():
    """Cycling a BOUNDED shape family through a too-small LRU re-pays
    compiles, but bucketing can't help — L006 must stay quiet."""
    import warnings
    x = fluid.layers.data("x", shape=(4,))
    out = fluid.layers.fc(x, 2)
    exe = fluid.Executor(cache_capacity=2)
    _run_startup(exe)
    with warnings.catch_warnings(record=True) as got:
        warnings.simplefilter("always")
        for _ in range(3):                      # 9 runs, all misses
            for bs in (1, 2, 3):
                exe.run(feed={"x": np.ones((bs, 4), np.float32)},
                        fetch_list=[out])
    assert not [w for w in got if "L006" in str(w.message)]


def test_bucketing_static_feed_axis_is_an_error():
    """A spec naming a feed with no dynamic non-batch dim (and no pinned
    axis) must fail loudly at the spec boundary, not pad a feature dim."""
    img = fluid.layers.data("img", shape=(784,))
    out = fluid.layers.fc(img, 2)
    exe = fluid.Executor(buckets={"img": (1024,)})
    with pytest.raises(ValueError, match="cannot infer a bucket axis"):
        exe.run(feed={"img": np.ones((2, 784), np.float32)},
                fetch_list=[out])


def test_compile_cache_wiring(tmp_path, monkeypatch):
    """paddle_tpu.init points jax's persistent compilation cache at the
    requested dir (flag wins; env var is the fallback)."""
    import jax

    import paddle_tpu
    prev = jax.config.jax_compilation_cache_dir
    try:
        flags = paddle_tpu.init(compile_cache_dir=str(tmp_path / "cc"))
        assert jax.config.jax_compilation_cache_dir == str(tmp_path / "cc")
        assert flags["compile_cache_dir"] == str(tmp_path / "cc")
        monkeypatch.setenv(paddle_tpu.COMPILE_CACHE_ENV,
                           str(tmp_path / "cc2"))
        paddle_tpu.init()
        assert jax.config.jax_compilation_cache_dir == str(tmp_path / "cc2")
    finally:
        jax.config.update("jax_compilation_cache_dir", prev)


def test_pruned_program_autodiff_grads_run():
    """Pruning dangling forward ops must not break the autodiff replay
    (regression: num_fwd_ops indexed the ORIGINAL op list, so a pruned
    program recursed forever — the replay now uses the op's own position)."""
    fluid.reset_default_programs()
    x = fluid.layers.data("x", shape=(4,))
    side = fluid.layers.fc(x, 3)              # dangling: not in the cost
    h = fluid.layers.fc(x, 8, act="tanh")
    out = fluid.layers.fc(h, 2)
    loss = fluid.layers.mean(out)
    fluid.SGDOptimizer(0.1).minimize(loss)
    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())
    params = [v.name
              for v in fluid.default_main_program().global_block()
              .all_parameters()
              if not v.name.startswith("fc_w_1")]   # drop side's params
    grad_names = [p + "@GRAD" for p in params if "fc" in p]
    pruned = fluid.default_main_program().prune(grad_names)
    xs = np.ones((3, 4), np.float32)
    grads = exe.run(pruned, feed={"x": xs}, fetch_list=grad_names)
    assert all(np.isfinite(np.asarray(g)).all() for g in grads)
