"""Fluid (Program IR + Executor) tests — the book-test shapes of
fluid/tests/book/test_recognize_digits_mlp.py and fit_a_line, plus IR
round-trip and executable-cache behavior."""

import numpy as np
import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu.data.dataset import mnist, uci_housing


@pytest.fixture(autouse=True)
def fresh_programs():
    fluid.reset_default_programs()
    # fresh scope per test
    fluid.executor._global_scope = fluid.Scope()
    yield


def _run_startup(exe):
    exe.run(fluid.default_startup_program())


def test_fit_a_line():
    """fluid/tests/book/test_fit_a_line.py analog: linear regression to low loss."""
    x = fluid.layers.data("x", shape=(13,))
    y = fluid.layers.data("y", shape=(1,))
    pred = fluid.layers.fc(x, 1)
    b = fluid.default_main_program().global_block()
    diff = fluid.layers.elementwise_sub(pred, y)
    sq = fluid.layers.elementwise_mul(diff, diff)
    loss = fluid.layers.mean(sq)
    fluid.SGDOptimizer(0.01).minimize(loss)

    exe = fluid.Executor()
    _run_startup(exe)
    data = list(uci_housing.train(256)())
    xs = np.stack([d[0] for d in data])
    ys = np.stack([d[1] for d in data])
    first = None
    for i in range(50):
        out, = exe.run(feed={"x": xs, "y": ys}, fetch_list=[loss])
        if first is None:
            first = float(out)
    assert float(out) < first * 0.5


def test_recognize_digits_mlp():
    """MNIST MLP book test: train to decreasing loss with Adam + accuracy."""
    img = fluid.layers.data("img", shape=(784,))
    label = fluid.layers.data("label", shape=(), dtype="int32")
    h1 = fluid.layers.fc(img, 128, act="relu")
    h2 = fluid.layers.fc(h1, 64, act="relu")
    logits = fluid.layers.fc(h2, 10)
    loss_vec = fluid.layers.softmax_with_cross_entropy(logits, label)
    loss = fluid.layers.mean(loss_vec)
    acc = fluid.layers.accuracy(logits, label)
    fluid.AdamOptimizer(1e-3).minimize(loss)

    exe = fluid.Executor()
    _run_startup(exe)
    data = list(mnist.train(512)())
    xs = np.stack([d[0] for d in data])
    ys = np.array([d[1] for d in data], np.int32)
    costs = []
    for i in range(30):
        c, a = exe.run(feed={"img": xs, "label": ys},
                       fetch_list=[loss, acc])
        costs.append(float(c))
    assert costs[-1] < costs[0] * 0.5
    assert float(a) > 0.5


def test_executable_cache_reused():
    x = fluid.layers.data("x", shape=(4,))
    out = fluid.layers.fc(x, 2)
    exe = fluid.Executor()
    _run_startup(exe)
    exe.run(feed={"x": np.ones((3, 4), np.float32)}, fetch_list=[out])
    n1 = len(exe._cache)
    exe.run(feed={"x": np.zeros((3, 4), np.float32)}, fetch_list=[out])
    assert len(exe._cache) == n1          # same shapes -> cache hit
    exe.run(feed={"x": np.ones((5, 4), np.float32)}, fetch_list=[out])
    assert len(exe._cache) == n1 + 1      # new batch shape -> new executable


def test_program_serialization_roundtrip():
    x = fluid.layers.data("x", shape=(4,))
    h = fluid.layers.fc(x, 8, act="tanh")
    out = fluid.layers.fc(h, 2)
    prog = fluid.default_main_program()
    d = prog.to_dict()
    import json
    d2 = json.loads(json.dumps(d, default=str))
    back = fluid.Program.from_dict(d)
    assert len(back.global_block().ops) == len(prog.global_block().ops)
    assert set(back.global_block().vars) == set(prog.global_block().vars)


def test_prune_drops_dead_ops():
    x = fluid.layers.data("x", shape=(4,))
    used = fluid.layers.fc(x, 2)
    dead = fluid.layers.fc(x, 3)   # never fetched
    prog = fluid.default_main_program()
    pruned = prog.prune([used.name])
    kept_types = [op.type for op in pruned.global_block().ops]
    assert len(pruned.global_block().ops) < len(prog.global_block().ops)
    # the dead fc's mul op must be gone
    dead_inputs = {n for op in prog.global_block().ops
                   if dead.name in op.output_vars() for n in op.input_vars()}
    for op in pruned.global_block().ops:
        assert dead.name not in op.output_vars()


def test_momentum_optimizer_runs():
    x = fluid.layers.data("x", shape=(4,))
    y = fluid.layers.data("y", shape=(1,))
    pred = fluid.layers.fc(x, 1)
    diff = fluid.layers.elementwise_sub(pred, y)
    loss = fluid.layers.mean(fluid.layers.elementwise_mul(diff, diff))
    fluid.MomentumOptimizer(0.01, momentum=0.9).minimize(loss)
    exe = fluid.Executor()
    _run_startup(exe)
    rs = np.random.RandomState(0)
    xs = rs.randn(32, 4).astype(np.float32)
    ys = (xs @ rs.randn(4, 1)).astype(np.float32)
    c0 = float(exe.run(feed={"x": xs, "y": ys}, fetch_list=[loss])[0])
    for _ in range(30):
        c = float(exe.run(feed={"x": xs, "y": ys}, fetch_list=[loss])[0])
    assert c < c0 * 0.5


def test_save_load_persistables(tmp_path):
    x = fluid.layers.data("x", shape=(4,))
    out = fluid.layers.fc(x, 2)
    exe = fluid.Executor()
    _run_startup(exe)
    r1 = exe.run(feed={"x": np.ones((2, 4), np.float32)}, fetch_list=[out])[0]
    fluid.io.save_persistables(exe, str(tmp_path))
    # clobber the scope, reload, same output
    fluid.executor._global_scope = fluid.Scope()
    exe2 = fluid.Executor()
    fluid.io.load_persistables(exe2, str(tmp_path))
    r2 = exe2.run(fluid.default_main_program(),
                  feed={"x": np.ones((2, 4), np.float32)}, fetch_list=[out])[0]
    np.testing.assert_allclose(r1, r2, rtol=1e-6)


def test_pruned_program_autodiff_grads_run():
    """Pruning dangling forward ops must not break the autodiff replay
    (regression: num_fwd_ops indexed the ORIGINAL op list, so a pruned
    program recursed forever — the replay now uses the op's own position)."""
    fluid.reset_default_programs()
    x = fluid.layers.data("x", shape=(4,))
    side = fluid.layers.fc(x, 3)              # dangling: not in the cost
    h = fluid.layers.fc(x, 8, act="tanh")
    out = fluid.layers.fc(h, 2)
    loss = fluid.layers.mean(out)
    fluid.SGDOptimizer(0.1).minimize(loss)
    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())
    params = [v.name
              for v in fluid.default_main_program().global_block()
              .all_parameters()
              if not v.name.startswith("fc_w_1")]   # drop side's params
    grad_names = [p + "@GRAD" for p in params if "fc" in p]
    pruned = fluid.default_main_program().prune(grad_names)
    xs = np.ones((3, 4), np.float32)
    grads = exe.run(pruned, feed={"x": xs}, fetch_list=grad_names)
    assert all(np.isfinite(np.asarray(g)).all() for g in grads)
