"""Fluid API completion tests: nets, regularizer, evaluator, optimizer zoo
(python/paddle/v2/fluid/{nets,regularizer,evaluator,optimizer}.py analogs)."""

import numpy as np
import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu.fluid import layers, nets


@pytest.fixture(autouse=True)
def fresh_programs():
    fluid.reset_default_programs()
    fluid.executor._global_scope = fluid.Scope()
    yield


def _startup(exe):
    exe.run(fluid.default_startup_program())


def _toy_classification(opt, n_steps=25, regularization=None):
    x = layers.data("x", shape=(10,))
    y = layers.data("y", shape=(), dtype="int64")
    h = layers.fc(x, 16, act="tanh")
    logits = layers.fc(h, 2)
    loss = layers.mean(layers.softmax_with_cross_entropy(logits, y))
    opt.minimize(loss, regularization=regularization)
    exe = fluid.Executor()
    _startup(exe)
    rng = np.random.RandomState(0)
    xs = rng.randn(32, 10).astype(np.float32)
    ys = (xs.sum(1) > 0).astype(np.int64)
    losses = [float(exe.run(feed={"x": xs, "y": ys}, fetch_list=[loss])[0])
              for _ in range(n_steps)]
    return losses, exe


@pytest.mark.parametrize("opt_cls,kw", [
    (fluid.AdagradOptimizer, {"learning_rate": 0.1}),
    (fluid.AdadeltaOptimizer, {"learning_rate": 1.0}),
    (fluid.RMSPropOptimizer, {"learning_rate": 0.01}),
    (fluid.AdamaxOptimizer, {"learning_rate": 0.05}),
    (fluid.DecayedAdagradOptimizer, {"learning_rate": 0.1}),
])
def test_optimizer_zoo_learns(opt_cls, kw):
    losses, _ = _toy_classification(opt_cls(**kw))
    assert losses[-1] < losses[0] * 0.9, (opt_cls.__name__, losses[:3], losses[-3:])


def test_l2_regularization_shrinks_weights():
    losses, exe = _toy_classification(
        fluid.SGDOptimizer(0.1), n_steps=40,
        regularization=fluid.L2Decay(0.5))
    scope = exe.scope
    w = [np.asarray(scope.get(n)) for n in scope.vars if n.startswith("fc_w")]
    norm_reg = sum(float(np.square(a).sum()) for a in w)

    fluid.reset_default_programs()
    fluid.executor._global_scope = fluid.Scope()
    losses2, exe2 = _toy_classification(fluid.SGDOptimizer(0.1), n_steps=40)
    w2 = [np.asarray(exe2.scope.get(n)) for n in exe2.scope.vars
          if n.startswith("fc_w")]
    norm_plain = sum(float(np.square(a).sum()) for a in w2)
    assert norm_reg < norm_plain


def test_l1_regularization_runs():
    losses, _ = _toy_classification(fluid.SGDOptimizer(0.05), n_steps=10,
                                    regularization=fluid.L1Decay(0.01))
    assert np.isfinite(losses[-1])


def test_simple_img_conv_pool_trains():
    img = layers.data("img", shape=(12, 12, 1))
    y = layers.data("y", shape=(), dtype="int64")
    feat = nets.simple_img_conv_pool(img, num_filters=4, filter_size=3,
                                     pool_size=2, pool_stride=2, act="relu")
    logits = layers.fc(feat, 2)
    loss = layers.mean(layers.softmax_with_cross_entropy(logits, y))
    fluid.AdamOptimizer(0.01).minimize(loss)
    exe = fluid.Executor()
    _startup(exe)
    rng = np.random.RandomState(0)
    xs = rng.randn(8, 12, 12, 1).astype(np.float32)
    ys = rng.randint(0, 2, (8,)).astype(np.int64)
    l0 = float(exe.run(feed={"img": xs, "y": ys}, fetch_list=[loss])[0])
    for _ in range(15):
        out = exe.run(feed={"img": xs, "y": ys}, fetch_list=[loss])
    assert float(out[0]) < l0


def test_img_conv_group_with_batchnorm():
    img = layers.data("img", shape=(8, 8, 3))
    feat = nets.img_conv_group(img, conv_num_filter=[4, 4], pool_size=2,
                               pool_stride=2, conv_act="relu",
                               conv_with_batchnorm=True)
    exe = fluid.Executor()
    _startup(exe)
    xs = np.random.RandomState(0).randn(4, 8, 8, 3).astype(np.float32)
    out, = exe.run(feed={"img": xs}, fetch_list=[feat])
    assert out.shape == (4, 4, 4, 4) and np.isfinite(out).all()


def test_accuracy_evaluator_accumulates():
    x = layers.data("x", shape=(4,))
    y = layers.data("y", shape=(), dtype="int64")
    logits = layers.fc(x, 2)
    ev = fluid.AccuracyEvaluator(logits, y)
    exe = fluid.Executor()
    _startup(exe)
    ev.reset(exe)
    rng = np.random.RandomState(0)
    for _ in range(3):
        xs = rng.randn(16, 4).astype(np.float32)
        ys = rng.randint(0, 2, (16,)).astype(np.int64)
        exe.run(feed={"x": xs, "y": ys}, fetch_list=[ev.batch_acc])
    acc = ev.eval(exe)
    assert 0.0 <= acc <= 1.0
    # totals accumulated over 3 batches of 16
    total = float(np.asarray(exe.scope.get(ev._tot_total.name)))
    assert total == 48.0


def test_chunk_evaluator_f1():
    tags = layers.data("tags", shape=(6,), dtype="int32")
    labels = layers.data("labels", shape=(6,), dtype="int32")
    lengths = layers.data("lengths", shape=(), dtype="int32")
    ev = fluid.ChunkEvaluator(tags, labels, lengths)
    exe = fluid.Executor()
    _startup(exe)
    ev.reset(exe)
    # identical tags -> F1 == 1
    t = np.array([[0, 1, 1, 0, 1, 0]], np.int32)
    exe.run(feed={"tags": t, "labels": t,
                  "lengths": np.array([6], np.int32)}, fetch_list=[])
    assert ev.eval(exe) == pytest.approx(1.0)


def test_failing_op_names_itself_in_the_error():
    """A crash deep in a traced Program must name the op and the chain
    leading to it (utils/CustomStackTrace.h:51 layer-stack analog), and
    keep the original exception type."""
    import numpy as np
    import pytest

    import paddle_tpu.fluid as fluid

    fluid.reset_default_programs()
    x = fluid.layers.data("x", shape=(4,))
    h = fluid.layers.fc(x, 8, act="relu")
    y = fluid.layers.data("y", shape=(3,))
    # concat with incompatible trailing dims fails inside the op compute
    bad = fluid.layers.concat([h, y], axis=0)
    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())
    with pytest.raises(Exception) as ei:
        exe.run(fluid.default_main_program(),
                feed={"x": np.zeros((2, 4), np.float32),
                      "y": np.zeros((2, 3), np.float32)},
                fetch_list=[bad])
    # context arrives via add_note (3.11+) so the original exception object —
    # and its structured args — survives; notes are not part of str()
    msg = str(ei.value) + "\n".join(getattr(ei.value, "__notes__", []))
    # op provenance uses the analysis.op_site format so runtime errors and
    # static diagnostics cite the same location
    assert "block 0, op #3 (concat)" in msg and "op chain" in msg
