"""Fluid control flow + expanded registry tests.

Book-style coverage for round-2 additions: while/cond/static_rnn lowering
(while_op.cc, conditional_block_op.cc, recurrent_op.cc analogs), TensorArray
ops, training-mode batch_norm, CRF-in-IR tagger, and a while-loop greedy
decode — the dynamic-model story the round-1 verdict flagged as absent.
"""

import numpy as np
import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu.fluid import layers


@pytest.fixture(autouse=True)
def fresh_programs():
    fluid.reset_default_programs()
    fluid.executor._global_scope = fluid.Scope()
    yield


def _startup(exe):
    exe.run(fluid.default_startup_program())


# ---------------------------------------------------------------- while ------

def test_while_loop_accumulates():
    """sum 0..9 with a while loop over IR scalars."""
    i = layers.fill_constant((), "int32", 0)
    n = layers.fill_constant((), "int32", 10)
    acc = layers.fill_constant((), "int32", 0)
    cond = layers.less_than(i, n)
    with fluid.While(cond).block():
        layers.elementwise_add(acc, i)  # tmp
        # acc += i ; i += 1 ; cond = i < n   (all writing outer vars)
        b = fluid.default_main_program().current_block()
        b.append_op("elementwise_add", {"X": [acc.name], "Y": [i.name]},
                    {"Out": [acc.name]})
        layers.increment(i)
        layers.less_than(i, n, cond=cond)
    exe = fluid.Executor()
    out, iv = exe.run(feed={}, fetch_list=[acc, i])
    assert int(out) == 45 and int(iv) == 10


def test_while_requires_cond_update():
    i = layers.fill_constant((), "int32", 0)
    n = layers.fill_constant((), "int32", 3)
    cond = layers.less_than(i, n)
    with fluid.While(cond).block():
        layers.increment(i)   # cond never updated -> structural error
    exe = fluid.Executor()
    with pytest.raises(ValueError, match="never updated"):
        exe.run(feed={}, fetch_list=[i])


def test_while_array_write_read():
    """TensorArray in a loop: arr[t] = t*t, then read back."""
    cap = 8
    i = layers.fill_constant((), "int32", 0)
    n = layers.fill_constant((), "int32", cap)
    sq = layers.fill_constant((), "float32", 0.0)
    arr = layers.array_write(sq, i, capacity=cap)
    cond = layers.less_than(i, n)
    with fluid.While(cond).block():
        b = fluid.default_main_program().current_block()
        fi = layers.cast(i, "float32")
        t2 = layers.elementwise_mul(fi, fi)
        layers.array_write(t2, i, array=arr)
        layers.increment(i)
        layers.less_than(i, n, cond=cond)
    exe = fluid.Executor()
    out, = exe.run(feed={}, fetch_list=[arr])
    np.testing.assert_allclose(out, np.arange(cap, dtype=np.float32) ** 2)


# ----------------------------------------------------------------- cond ------

def test_conditional_block_both_branches():
    x = layers.data("x", shape=())
    out = layers.fill_constant((), "float32", 0.0)
    thresh = layers.fill_constant((), "float32", 5.0)
    pred = layers.greater_than(x, thresh)
    c = fluid.Cond(pred)
    with c.true_block():
        doubled = layers.elementwise_add(x, x)
        layers.assign(doubled, out)
    with c.false_block():
        layers.assign(x, out)
    exe = fluid.Executor()
    hi, = exe.run(feed={"x": np.float32(7.0)}, fetch_list=[out])
    lo, = exe.run(feed={"x": np.float32(3.0)}, fetch_list=[out])
    assert float(hi) == 14.0 and float(lo) == 3.0


def _build_sibling_branch_read():
    """false branch reads `doubled`, which only the true branch defines —
    parent-scope lookup goes UP, never sideways, so this program is broken."""
    x = layers.data("x", shape=())
    out = layers.fill_constant((), "float32", 0.0)
    thresh = layers.fill_constant((), "float32", 5.0)
    pred = layers.greater_than(x, thresh)
    c = fluid.Cond(pred)
    with c.true_block():
        doubled = layers.elementwise_add(x, x)
        layers.assign(doubled, out)
    with c.false_block():
        b = fluid.default_main_program().current_block()
        bad = b.create_var(shape=(), dtype="float32")
        b.append_op("scale", {"X": [doubled.name]}, {"Out": [bad.name]},
                    {"scale": 1.0})
        layers.assign(bad, out)
    return out, doubled


def test_sibling_branch_read_rejected_by_verifier():
    import paddle_tpu.analysis as A
    out, doubled = _build_sibling_branch_read()
    diags = A.verify_program(fluid.default_main_program(),
                             fetch=[out.name])
    errs = [d for d in A.errors(diags) if d.code == "V001"]
    assert errs and errs[0].var == doubled.name
    # the diagnostic cites the false branch, not the true one
    false_idx = fluid.default_main_program().global_block().ops[-1] \
        .attrs["false_block_idx"]
    assert errs[0].block_idx == false_idx


def test_sibling_branch_read_fails_cleanly_at_trace_time():
    """The same broken program must ALSO fail at trace time with the var
    name and the op-site format the static diagnostic uses — not succeed by
    leaking the true branch's env into the false branch."""
    import paddle_tpu.analysis as A
    out, doubled = _build_sibling_branch_read()
    exe = fluid.Executor()
    with pytest.raises(Exception) as ei:
        exe.run(feed={"x": np.float32(3.0)}, fetch_list=[out])
    msg = str(ei.value) + "\n".join(getattr(ei.value, "__notes__", []))
    assert doubled.name in msg
    assert "op #" in msg and "(scale)" in msg
    # verify=True rejects it BEFORE any tracing, citing the same var
    with pytest.raises(A.ProgramVerificationError) as vi:
        exe.run(feed={"x": np.float32(3.0)}, fetch_list=[out], verify=True)
    assert any(d.code == "V001" and d.var == doubled.name
               for d in vi.value.diagnostics)


def test_while_body_var_not_visible_after_loop():
    """A temp defined only inside a while body is out of scope afterwards:
    the verifier rejects a global-block read of it (and the fetch)."""
    import paddle_tpu.analysis as A
    i = layers.fill_constant((), "int32", 0)
    n = layers.fill_constant((), "int32", 3)
    cond = layers.less_than(i, n)
    with fluid.While(cond).block():
        b = fluid.default_main_program().current_block()
        tmp = b.create_var(shape=(), dtype="int32")
        b.append_op("scale", {"X": [i.name]}, {"Out": [tmp.name]},
                    {"scale": 2.0})
        layers.increment(i)
        layers.less_than(i, n, cond=cond)
    g = fluid.default_main_program().global_block()
    leak = g.create_var(shape=(), dtype="int32")
    g.append_op("scale", {"X": [tmp.name]}, {"Out": [leak.name]},
                {"scale": 1.0})
    diags = A.verify_program(fluid.default_main_program(), fetch=[leak.name])
    errs = [d for d in A.errors(diags) if d.code == "V001"]
    assert errs and errs[0].var == tmp.name and errs[0].block_idx == 0


# ------------------------------------------------------------- static_rnn ----

def test_static_rnn_matches_manual_accumulation():
    """rnn memory h += x_t over time == cumulative sum at the last step."""
    B, T, D = 2, 5, 3
    x = layers.data("x", shape=(T, D))
    rnn = fluid.StaticRNN()
    with rnn.step():
        x_t = rnn.step_input(x)
        h = rnn.memory(shape=(D,), value=0.0, batch_ref=x_t)
        h_new = layers.elementwise_add(h, x_t)
        rnn.update_memory(h, h_new)
        rnn.step_output(h_new)
    out, = rnn()
    exe = fluid.Executor()
    xs = np.random.RandomState(0).randn(B, T, D).astype(np.float32)
    res, = exe.run(feed={"x": xs}, fetch_list=[out])
    np.testing.assert_allclose(res, np.cumsum(xs, axis=1), rtol=1e-5)


def test_static_rnn_trains_through_scan():
    """A learnable RNN built from fc ops inside the step block trains."""
    B, T, D, H = 4, 6, 3, 8
    x = layers.data("x", shape=(T, D))
    y = layers.data("y", shape=(), dtype="int64")
    rnn = fluid.StaticRNN()
    with rnn.step():
        x_t = rnn.step_input(x)
        h = rnn.memory(shape=(H,), value=0.0, batch_ref=x_t)
        merged = layers.concat([x_t, h], axis=1)
        h_new = layers.fc(merged, H, act="tanh")
        rnn.update_memory(h, h_new)
        rnn.step_output(h_new)
    out, = rnn()
    last = rnn.get_last_mem(h)   # h stays in scope after the with-block
    logits = layers.fc(last, 2)
    loss = layers.mean(layers.softmax_with_cross_entropy(logits, y))
    fluid.AdamOptimizer(0.05).minimize(loss)
    exe = fluid.Executor()
    _startup(exe)
    rng = np.random.RandomState(1)
    xs = rng.randn(B, T, D).astype(np.float32)
    ys = (xs.sum(axis=(1, 2)) > 0).astype(np.int64)
    losses = [float(exe.run(feed={"x": xs, "y": ys}, fetch_list=[loss])[0])
              for _ in range(30)]
    assert losses[-1] < losses[0] * 0.7


# ------------------------------------------------------------- batch norm ----

def test_batch_norm_trains_and_updates_stats():
    """A conv+BN net must train AND move its running stats (round-1 gap:
    only batch_norm_infer existed, so no fluid program could train BN)."""
    img = layers.data("img", shape=(8, 8, 3))
    label = layers.data("label", shape=(), dtype="int64")
    c = layers.conv2d(img, num_filters=4, filter_size=3, act=None)
    bn = layers.batch_norm(c, act="relu")
    pool = layers.pool2d(bn, global_pooling=True)
    logits = layers.fc(pool, 2)
    loss = layers.mean(layers.softmax_with_cross_entropy(logits, label))
    fluid.SGDOptimizer(0.1).minimize(loss)

    exe = fluid.Executor()
    _startup(exe)
    scope = fluid.executor._global_scope
    mean_name = [n for n in scope.vars if "bn_mean" in n][0]
    mean0 = np.asarray(scope.get(mean_name)).copy()

    rng = np.random.RandomState(0)
    xs = rng.randn(8, 8, 8, 3).astype(np.float32) + 2.0   # nonzero mean
    ys = rng.randint(0, 2, size=(8,)).astype(np.int64)
    losses = [float(exe.run(feed={"img": xs, "label": ys},
                            fetch_list=[loss])[0]) for _ in range(10)]
    assert losses[-1] < losses[0]
    mean1 = np.asarray(scope.get(mean_name))
    assert np.abs(mean1 - mean0).max() > 1e-3, "running mean never updated"
    # eval mode uses the running stats (is_test path compiles and runs)
    out = np.asarray(exe.run(feed={"img": xs, "label": ys},
                             fetch_list=[loss])[0])
    assert np.isfinite(out)


# ---------------------------------------------------------------- CRF IR -----

def test_crf_tagger_in_ir_trains_and_decodes():
    """BiLSTM-CRF book shape: linear_chain_crf trains through Executor.run,
    crf_decoding recovers training tags on an easy problem."""
    B, T, D, N = 8, 6, 5, 3
    x = layers.data("x", shape=(T, D))
    tags = layers.data("tags", shape=(T,), dtype="int32")
    lengths = layers.data("lengths", shape=(), dtype="int32")
    emission = layers.fc(layers.reshape(x, (-1, D)), N)
    emission = layers.reshape(emission, (B, T, N))
    nll, trans = layers.linear_chain_crf(emission, tags, lengths)
    loss = layers.mean(nll)
    fluid.AdamOptimizer(0.1).minimize(loss)
    path = layers.crf_decoding(emission, lengths, trans)

    exe = fluid.Executor()
    _startup(exe)
    rng = np.random.RandomState(0)
    # easy mapping: tag = argmax of first N dims of x
    xs = rng.randn(B, T, D).astype(np.float32)
    ys = np.argmax(xs[:, :, :N], axis=-1).astype(np.int32)
    ls = np.full((B,), T, np.int32)
    losses = []
    for _ in range(60):
        out = exe.run(feed={"x": xs, "tags": ys, "lengths": ls},
                      fetch_list=[loss])
        losses.append(float(out[0]))
    assert losses[-1] < losses[0] * 0.5
    decoded, = exe.run(feed={"x": xs, "tags": ys, "lengths": ls},
                       fetch_list=[path])
    assert (decoded == ys).mean() > 0.9


# ------------------------------------------------- while-loop greedy decode --

def test_while_loop_greedy_decode():
    """Gen-2 generation story: a decoder loop in IR (array ops + while +
    top_k) emits the argmax token chain of a fixed transition matrix."""
    V, T = 5, 6
    logits_table = layers.data("table", shape=(V,))     # [V, V] rows
    start = layers.data("start", shape=())              # int32 scalar feed
    i = layers.fill_constant((), "int32", 0)
    # T-1 decode steps: slot 0 holds the start token, the loop's post-increment
    # array_write fills slots 1..T-1 (an i==T write would be silently clamped)
    n = layers.fill_constant((), "int32", T - 1)
    cur = layers.cast(start, "int64")
    toks = layers.array_write(cur, i, capacity=T)
    cond = layers.less_than(i, n)
    with fluid.While(cond).block():
        b = fluid.default_main_program().current_block()
        row = b.create_var(shape=(V,), dtype="float32")
        b.append_op("gather", {"X": [logits_table.name], "Index": [cur.name]},
                    {"Out": [row.name]})
        _, idx = layers.topk(row, 1)
        nxt = layers.cast(layers.reshape(idx, ()), "int64")
        layers.assign(nxt, cur)
        layers.increment(i)
        layers.array_write(cur, i, array=toks)
        layers.less_than(i, n, cond=cond)
    exe = fluid.Executor()
    rng = np.random.RandomState(0)
    table = rng.randn(V, V).astype(np.float32)
    out, = exe.run(feed={"table": table, "start": np.int32(2)},
                   fetch_list=[toks])
    # reference chain on host
    want = [2]
    for _ in range(T - 1):
        want.append(int(np.argmax(table[want[-1]])))
    np.testing.assert_array_equal(out[:T], np.asarray(want, np.int64)[:T])


# ------------------------------------------------------------ new op smoke ---

def test_new_optimizer_ops_registered_and_run():
    from paddle_tpu.fluid.registry import OpRegistry
    for name in ("adagrad", "adadelta", "rmsprop", "adamax", "decayed_adagrad",
                 "proximal_gd", "proximal_adagrad", "batch_norm",
                 "linear_chain_crf", "crf_decoding", "warpctc", "nce",
                 "hierarchical_sigmoid", "auc", "chunk_eval", "sequence_expand",
                 "gather", "scatter", "pad", "crop", "conv3d", "pool3d",
                 "conv2d_transpose", "lrn", "maxout", "roi_pool", "row_conv",
                 "while", "conditional_block", "static_rnn", "array_write",
                 "array_read", "less_than", "increment"):
        assert OpRegistry.has(name), f"op '{name}' missing from registry"
    assert len(OpRegistry.registered()) >= 110


def test_bn_stats_not_trainable_and_not_decayed():
    """BN running stats must be excluded from parameters: optimizers and
    regularizers would otherwise update/decay them (review r2 finding)."""
    img = layers.data("img", shape=(4, 4, 2))
    bn = layers.batch_norm(layers.conv2d(img, 2, 3, padding=1))
    loss = layers.mean(bn)
    import paddle_tpu.fluid as F
    params = [v.name for v in
              F.default_main_program().global_block().all_parameters()]
    assert not any("bn_mean" in n or "bn_var" in n for n in params)
    F.SGDOptimizer(0.1).minimize(loss, regularization=F.L2Decay(0.5))
    exe = F.Executor()
    exe.run(F.default_startup_program())
    xs = np.random.RandomState(0).randn(4, 4, 4, 2).astype(np.float32)
    for _ in range(5):
        exe.run(feed={"img": xs}, fetch_list=[loss])
    var_name = [n for n in exe.scope.vars if "bn_var" in n][0]
    v = np.asarray(exe.scope.get(var_name))
    assert v.min() > 0.1, "running variance was decayed toward zero"


def test_persistable_written_in_while_subblock_syncs():
    """A persistable counter incremented inside a while body must reach the
    scope after run() (review r2 finding: written-scan skipped sub-blocks)."""
    import paddle_tpu.fluid as F
    main = F.default_main_program()
    g = main.global_block()
    counter = g.create_var(name="counter", shape=(), dtype="float32",
                           persistable=True, trainable=False)
    F.executor._global_scope.set("counter", np.float32(0.0))
    i = layers.fill_constant((), "int32", 0)
    n = layers.fill_constant((), "int32", 4)
    cond = layers.less_than(i, n)
    one = layers.fill_constant((), "float32", 1.0)
    with F.While(cond).block():
        b = main.current_block()
        b.append_op("elementwise_add", {"X": [counter.name], "Y": [one.name]},
                    {"Out": [counter.name]})
        layers.increment(i)
        layers.less_than(i, n, cond=cond)
    exe = F.Executor()
    exe.run(feed={}, fetch_list=[i])
    assert float(np.asarray(exe.scope.get("counter"))) == 4.0
