"""GSPMD sharding plane: lowering ``Variable.sharding`` through the fluid
Executor onto a named device mesh (docs/design/spmd.md).

Runs on the 8-virtual-device CPU mesh conftest forces — the same in-process
strategy the MULTICHIP harness uses. Covers the acceptance contract:
annotated programs compile through ``jit(..., in_shardings=...)`` with
genuinely sharded parameters (addressable-shard shapes), match the
replicated run element-wise, place <= 1/4 of the replicated parameter
footprint per device on a 4-way fsdp axis, and compose with PR 5's
donation + shape bucketing (specs join the cache key).
"""

import numpy as np
import pytest

import jax
from jax.sharding import PartitionSpec as P

import paddle_tpu.fluid as fluid
from paddle_tpu import obs, parallel as pp


@pytest.fixture(autouse=True)
def fresh_programs():
    fluid.reset_default_programs()
    fluid.executor._global_scope = fluid.Scope()
    yield


def _mesh222():
    return pp.make_mesh(data=2, fsdp=2, tp=2)


def _param_names(prefix=None):
    b = fluid.default_main_program().global_block()
    return [n for n, v in b.vars.items()
            if v.persistable and v.trainable
            and (prefix is None or n.startswith(prefix))]


def _copy_scope(src: fluid.Scope, dst: fluid.Scope):
    for n, v in src.vars.items():
        dst.set(n, np.asarray(v))


def _annotated_program():
    """Embedding (vocab over fsdp) + tp-column fc + replicated head: no
    forward reduction crosses a sharded dim, so the sharded forward is
    bit-identical to the replicated one."""
    ids = fluid.layers.data("ids", shape=(), dtype="int32",
                            sharding=("data",))
    y = fluid.layers.data("y", shape=(1,))
    emb = fluid.layers.embedding(ids, (16, 8),
                                 param_attr={"sharding": ("fsdp", None)})
    h = fluid.layers.fc(emb, 16, act="relu",
                        param_attr={"sharding": (None, "tp")})
    pred = fluid.layers.fc(h, 1)
    diff = fluid.layers.elementwise_sub(pred, y)
    persample = fluid.layers.elementwise_mul(diff, diff)
    loss = fluid.layers.mean(persample)
    fluid.SGDOptimizer(0.05).minimize(loss)
    rs = np.random.RandomState(0)
    feed = {"ids": rs.randint(0, 16, 8).astype(np.int32),
            "y": rs.randn(8, 1).astype(np.float32)}
    return persample, loss, feed


# ------------------------------------------------------------- SpecLayout ----

def test_spec_layout_resolution_contract():
    """annotation > rule > role > replicated, with mesh/shape fitting."""
    mesh = _mesh222()
    lay = pp.SpecLayout(rules=[(r"special/w$", P("tp", None))])
    # 1. annotation wins over everything
    s = lay.resolve(mesh, "special/w", (8, 8), annotation=("fsdp", None))
    assert s.spec == P("fsdp")
    # 2. rule beats role
    assert lay.resolve(mesh, "special/w", (8, 8)).spec == P("tp")
    # 3. role rules: embeddings shard vocab over fsdp x tp; 2-D over
    #    (fsdp, tp); 1-D replicates
    assert lay.resolve(mesh, "embedding_w", (64, 8)).spec == \
        P(("fsdp", "tp"))
    assert lay.resolve(mesh, "fc_w_0", (8, 16)).spec == P("fsdp", "tp")
    assert lay.resolve(mesh, "fc_b_0", (16,)).spec == P()
    # 4. fitting: unknown axes drop, indivisible dims replicate
    assert lay.resolve(mesh, "w", (8, 8),
                       annotation=("seq", None)).spec == P()
    assert lay.resolve(mesh, "w", (7, 16),
                       annotation=("fsdp", "tp")).spec == P(None, "tp")
    # roles=False: nothing implicit
    assert pp.SpecLayout(roles=False).resolve(mesh, "fc_w", (8, 8)).spec \
        == P()


def test_executor_adopts_ambient_mesh():
    with pp.use_mesh(_mesh222()) as m:
        exe = fluid.Executor()
    assert exe.mesh is m
    assert exe.layout is not None
    assert fluid.Executor().mesh is None          # outside the scope


# ----------------------------------------------------------------- parity ----

def test_mesh_sharded_parity_2x2x2():
    """The acceptance run: an annotated program on a 2x2x2 mesh places
    genuinely sharded parameters and matches the replicated run — the
    first forward bit-for-bit, a 3-step training trajectory to float-ulp
    (backward grad psums legitimately reassociate the batch mean)."""
    persample, loss, feed = _annotated_program()
    sc_sh, sc_rep = fluid.Scope(), fluid.Scope()
    exe_sh = fluid.Executor(scope=sc_sh, mesh=_mesh222(),
                            layout=pp.SpecLayout(roles=False))
    exe_rep = fluid.Executor(scope=sc_rep)
    exe_rep.run(fluid.default_startup_program())
    _copy_scope(sc_rep, sc_sh)

    ps_s, l_s = exe_sh.run(feed=feed, fetch_list=[persample, loss])
    ps_r, l_r = exe_rep.run(feed=feed, fetch_list=[persample, loss])
    np.testing.assert_array_equal(ps_s, ps_r)     # element-wise identical
    np.testing.assert_array_equal(l_s, l_r)

    # the parameters really live sharded on the mesh (not replicated)
    emb_name = next(n for n in _param_names() if "embedding" in n)
    fc_name = next(n for n in _param_names() if n.startswith("fc_w"))
    emb_w = sc_sh.get(emb_name)
    assert emb_w.sharding.spec == P("fsdp")
    assert emb_w.addressable_shards[0].data.shape == (8, 8)   # 16/2 rows
    fc_w = sc_sh.get(fc_name)
    assert fc_w.sharding.spec == P(None, "tp")
    assert fc_w.addressable_shards[0].data.shape == (8, 8)    # 16/2 cols

    for _ in range(3):
        _, l_s = exe_sh.run(feed=feed, fetch_list=[persample, loss])
        _, l_r = exe_rep.run(feed=feed, fetch_list=[persample, loss])
        np.testing.assert_allclose(np.asarray(l_s), np.asarray(l_r),
                                   rtol=1e-5, atol=1e-7)
    assert float(l_s) < float(np.asarray(l_r)) * 1.5  # both actually train


def test_per_device_param_bytes_quarter_on_fsdp4():
    """4-way fsdp axis: every trainable parameter annotated over fsdp ->
    per-device parameter bytes are <= 1/4 of the replicated footprint."""
    x = fluid.layers.data("x", shape=(64,))
    h = fluid.layers.fc(x, 128, act="relu",
                        param_attr={"sharding": ("fsdp", None)},
                        bias_param_attr={"sharding": ("fsdp",)})
    out = fluid.layers.fc(h, 8, param_attr={"sharding": ("fsdp", None)},
                          bias_param_attr={"sharding": ("fsdp",)})
    loss = fluid.layers.mean(out)
    fluid.SGDOptimizer(0.01).minimize(loss)
    mesh = pp.make_mesh(data=2, fsdp=4)
    sc = fluid.Scope()
    exe = fluid.Executor(scope=sc, mesh=mesh, layout=pp.SpecLayout())
    exe.run(fluid.default_startup_program())
    exe.run(feed={"x": np.ones((8, 64), np.float32)}, fetch_list=[loss])

    replicated = per_device = 0
    dev0 = mesh.devices.flat[0]
    for n in _param_names():
        arr = sc.get(n)
        replicated += arr.nbytes
        per_device += sum(s.data.nbytes for s in arr.addressable_shards
                          if s.device == dev0)
    assert replicated > 0
    assert per_device <= replicated / 4
    # optimizer slots inherit the annotation (SGD has none; the lr scalar
    # stays replicated) — and the obs gauges surface the layout
    reg = obs.MetricsRegistry()
    with obs.ObsSession(registry=reg).installed():
        exe._mesh_stats_emitted = False
        exe.run(feed={"x": np.ones((8, 64), np.float32)},
                fetch_list=[loss])
    assert reg.gauge("mesh.axis_size").get(axis="fsdp") == 4
    assert reg.gauge("mesh.axis_utilization").get(axis="fsdp") > 0.9
    global_b = reg.gauge("fluid.param_bytes_global").get()
    assert reg.gauge("fluid.param_bytes_per_device").get() < global_b / 3


# ------------------------------------------------------------ composition ----

def test_donation_composes_with_sharding():
    """A donated sharded persistable updates in place: the old sharded
    buffer is invalidated, the new one keeps the SAME sharding, and
    fluid.donated_bytes_total still counts the handed-over bytes."""
    persample, loss, feed = _annotated_program()
    sc = fluid.Scope()
    exe = fluid.Executor(scope=sc, mesh=_mesh222(),
                         layout=pp.SpecLayout(roles=False))
    exe.run(fluid.default_startup_program())
    fc_name = next(n for n in _param_names() if n.startswith("fc_w"))
    exe.run(feed=feed, fetch_list=[loss])          # placement run
    ref = sc.get(fc_name)
    spec_before = ref.sharding.spec
    reg = obs.MetricsRegistry()
    with obs.ObsSession(registry=reg).installed():
        exe.run(feed=feed, fetch_list=[loss], return_numpy=False)
    assert reg.counter("fluid.donated_bytes_total").get() > 0
    assert ref.is_deleted()                        # donated, retired
    new = sc.get(fc_name)
    assert new.sharding.spec == spec_before        # still sharded in place
    # a further run keeps training on the sharded, in-place-updated state
    l1 = float(exe.run(feed=feed, fetch_list=[loss])[0])
    l2 = float(exe.run(feed=feed, fetch_list=[loss])[0])
    assert l2 < l1


def test_bucketing_composes_with_sharding():
    """Specs join the cache key and bucketing still bounds compiles: 4
    distinct lengths under a 2-bucket spec compile exactly twice, with
    sharded parameters throughout."""
    w = fluid.layers.data("w", shape=(-1,))
    sq = fluid.layers.elementwise_mul(w, w)
    mesh = _mesh222()
    exe = fluid.Executor(mesh=mesh, layout=pp.SpecLayout(),
                         buckets={"w": (8, 16)})
    # warmup a third (overflow) bucket outside the counted window
    exe.run(feed={"w": np.ones((2, 20), np.float32)}, fetch_list=[sq])
    reg = obs.MetricsRegistry()
    with obs.ObsSession(registry=reg).installed():
        outs = {}
        for L in (3, 7, 9, 15):
            outs[L], = exe.run(
                feed={"w": np.arange(2 * L, dtype=np.float32)
                      .reshape(2, L)}, fetch_list=[sq])
    # the compiled-fn cache is the witness: 2 misses (one per bucket), 2
    # hits. jax.compiles_total is not 1:1 on the mesh path — multi-device
    # host->mesh feed transfers compile tiny auxiliary programs — so
    # bound it instead of pinning it.
    assert sum(v for _, v in
               reg.counter("fluid.cache_misses_total").samples()) == 2
    assert sum(v for _, v in
               reg.counter("fluid.cache_hits_total").samples()) == 2
    assert reg.counter("jax.compiles_total").get() <= 4
    assert len(exe._cache) == 3                    # 2 buckets + warmup
    for L, out in outs.items():
        assert out.shape[1] in (8, 16)
        np.testing.assert_array_equal(
            out[:, :L], (np.arange(2 * L, dtype=np.float32)
                         .reshape(2, L)) ** 2)


def test_mesh_joins_cache_key():
    """The same program on mesh and off mesh (or on a reshaped mesh) must
    not share a compiled executable."""
    x = fluid.layers.data("x", shape=(8,))
    out = fluid.layers.fc(x, 8, param_attr={"sharding": ("fsdp", "tp")})
    sc = fluid.Scope()
    exe_rep = fluid.Executor(scope=sc)
    exe_rep.run(fluid.default_startup_program())
    feed = {"x": np.ones((4, 8), np.float32)}
    exe_rep.run(feed=feed, fetch_list=[out])
    exe_sh = fluid.Executor(scope=sc, mesh=_mesh222())
    exe_sh.run(feed=feed, fetch_list=[out])
    k_rep = next(iter(exe_rep._cache))
    k_sh = [k for k in exe_sh._cache if k[3]]      # fetch-carrying key
    assert all(k != k_rep for k in k_sh)


# ----------------------------------------------------- restore re-places ----

def test_restore_replaces_onto_current_mesh(tmp_path):
    """save_persistables gathers (host tar); loading through a mesh-aware
    executor re-places values sharded per the layout — and the restored
    program computes the same fetch."""
    persample, loss, feed = _annotated_program()
    sc = fluid.Scope()
    exe = fluid.Executor(scope=sc, mesh=_mesh222(),
                         layout=pp.SpecLayout(roles=False))
    exe.run(fluid.default_startup_program())
    # save BEFORE the fetch run: the program carries optimizer ops, so a
    # run mutates the params after computing the fetch
    fluid.io.save_persistables(exe, str(tmp_path))
    r1, = exe.run(feed=feed, fetch_list=[persample], donate=False)

    sc2 = fluid.Scope()
    exe2 = fluid.Executor(scope=sc2, mesh=pp.make_mesh(data=2, fsdp=2,
                                                       tp=2),
                          layout=pp.SpecLayout(roles=False))
    fluid.io.load_persistables(exe2, str(tmp_path))
    emb_name = next(n for n in _param_names() if "embedding" in n)
    assert sc2.get(emb_name).sharding.spec == P("fsdp")   # eager re-place
    r2, = exe2.run(feed=feed, fetch_list=[persample], donate=False)
    np.testing.assert_array_equal(r1, r2)
