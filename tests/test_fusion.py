"""The measured-only graph fusion pass (tune/fusion.py + executor wiring)
and the bucket_grid consult (ROADMAP item 3c — "spending the oracle").

Contracts under test:

* PARITY — the tentpole invariant: a fused region changes dispatch
  structure, never numerics. Forced fusion of every schedulable certified
  group leaves multi-step fetches AND the parameter trajectory bit-equal
  to the unfused run, composing with donation and bucketing; randomized
  elementwise-chain programs sweep the same invariant.
* MEASURED-ONLY GATE — with no cache entry nothing fuses; a measured
  ``fuse: true`` entry activates (counted on
  ``fluid.fused_regions_total{source=tuned}``); a measured loser, a stale
  space hash, or a tampered certificate refuses with the right reason on
  ``fluid.fusion_rejected_total``.
* SCHEDULABILITY — a certified group whose members straddle an
  interfering producer is refused (``not_schedulable``), even forced.
* ACCOUNTING — fusion lives inside the one jit: AOT cost-analysis FLOPs
  are identical fused vs unfused (MFU honesty).
* BUCKET_GRID — consult legality validation, ``PagePool`` /
  ``BucketSpec("tuned")`` integration.
* LINT + CLI — L008 flags fusion/bucket_grid entry corruption;
  ``paddle_tpu tune --from-ledger --check`` closes the seeded loop.
"""

import json

import numpy as np
import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu import obs, tune
from paddle_tpu.fluid.executor import Executor, Scope
from paddle_tpu.tune import fusion as F


@pytest.fixture
def tune_cache():
    c = tune.AutotuneCache()
    tune.set_cache(c)
    yield c
    tune.reset()


def _proxy(batch=8, width=16, depth=2, seed=0):
    return F.build_proxy_program(batch=batch, width=width, depth=depth,
                                 seed=seed)


def _param_names(program):
    return sorted(n for n, v in program.blocks[0].vars.items()
                  if v.persistable)


def _run_steps(main, startup, feed, fetch, fuse, *, n=4, donate=None,
               buckets=None):
    """(fetches per step, final persistable values) for one fresh scope."""
    exe = Executor(scope=Scope(), fuse=fuse, buckets=buckets)
    exe.run(startup)
    outs = [np.asarray(exe.run(main, feed=feed, fetch_list=fetch,
                               donate=donate)[0]) for _ in range(n)]
    params = {p: np.asarray(exe.scope.get(p)) for p in _param_names(main)
              if exe.scope.has(p)}
    return outs, params


def _assert_bit_equal(a, b):
    outs_a, params_a = a
    outs_b, params_b = b
    for x, y in zip(outs_a, outs_b):
        assert x.tobytes() == y.tobytes()
    assert params_a.keys() == params_b.keys() and params_a
    for k in params_a:
        assert params_a[k].tobytes() == params_b[k].tobytes(), k


def _put_measured(cache, program, feed, rows_or_groups, fuse=True,
                  space_hash=None, mangle_cert=None):
    """Drop fusion entries for every certified group of ``program``."""
    prog_sig = F.program_signature(program)
    shp = F.shape_family({k: np.shape(v) for k, v in feed.items()})
    for g in rows_or_groups:
        cert = F.certificate(program, g)
        fam = F.fusion_family(prog_sig, shp, F.group_signature(cert))
        if mangle_cert is not None:
            cert = mangle_cert(cert)
        cache.put("fusion", g.kind, "cpu", fam, {"fuse": fuse},
                  space_hash or tune.space_hash("fusion"),
                  certificate=cert, program_signature=prog_sig,
                  shape_family=shp, methodology="measured")


# -- parity: the tentpole invariant --------------------------------------

def test_forced_fusion_multi_step_bit_parity():
    main, startup, feed, fetch = _proxy()
    groups = F._certified(main, list(feed), fetch)
    assert groups, "proxy program must certify at least one group"
    un = _run_steps(main, startup, feed, fetch, False)
    fu = _run_steps(main, startup, feed, fetch, True)
    _assert_bit_equal(un, fu)
    # per-group forcing (the measurement harness knob) holds too
    one = _run_steps(main, startup, feed, fetch,
                     frozenset({groups[0].op_idxs[0]}))
    _assert_bit_equal(un, one)


def test_forced_fusion_parity_composes_with_donation():
    main, startup, feed, fetch = _proxy()
    un = _run_steps(main, startup, feed, fetch, False, donate=True)
    fu = _run_steps(main, startup, feed, fetch, True, donate=True)
    _assert_bit_equal(un, fu)


@pytest.mark.parametrize("seed", [1, 2, 3])
def test_randomized_elementwise_chain_parity(seed):
    """Randomized straight-line elementwise programs: whatever the oracle
    certifies, forcing it is bit-invisible (with bucketing in the loop —
    the fused plan joins the compiled-fn key next to the bucket pad)."""
    rs = np.random.RandomState(seed)
    fluid.reset_default_programs()
    width = int(rs.randint(4, 12))
    x = fluid.layers.data("rx", shape=(width,))
    binops = [fluid.layers.elementwise_add, fluid.layers.elementwise_sub,
              fluid.layers.elementwise_mul]
    h = fluid.layers.fc(x, width, act="relu")
    for _ in range(int(rs.randint(2, 6))):
        h = binops[rs.randint(len(binops))](h, x)
    loss = fluid.layers.mean(h)
    fluid.SGDOptimizer(1e-2).minimize(loss)
    main, startup = (fluid.default_main_program(),
                     fluid.default_startup_program())
    feed = {"rx": rs.randn(6, width).astype(np.float32)}
    fetch = [loss.name]
    buckets = {"rx": {"axis": 0, "buckets": (8, 16)}}
    un = _run_steps(main, startup, feed, fetch, False, buckets=buckets)
    fu = _run_steps(main, startup, feed, fetch, True, buckets=buckets)
    _assert_bit_equal(un, fu)


# -- the measured-only gate ----------------------------------------------

def _counter(reg, name):
    return sum(v for _, v in reg.counter(name).samples())


def _labeled(reg, name):
    return {dict(lbls).get(next(iter(dict(lbls)), ""), ""): v
            for lbls, v in reg.counter(name).samples()}


def test_no_entry_means_no_fusion(tune_cache):
    main, startup, feed, fetch = _proxy()
    plan = F.plan_for(main, {k: v.shape for k, v in feed.items()},
                      fetch=fetch, feed=list(feed))
    # the executor's cheap pre-gate never even analyzes: empty cache
    assert not F.cache_has_fusion_entries("cpu")
    assert plan.groups == [] or plan.source != "tuned" or not plan.groups


def test_measured_winner_activates_with_counters(tune_cache):
    main, startup, feed, fetch = _proxy()
    groups = F._certified(main, list(feed), fetch)
    _put_measured(tune_cache, main, feed, groups, fuse=True)
    assert F.cache_has_fusion_entries("cpu")
    reg = obs.MetricsRegistry()
    with obs.ObsSession(registry=reg).installed():
        un = _run_steps(main, startup, feed, fetch, False)
        fu = _run_steps(main, startup, feed, fetch, None)   # consults
    _assert_bit_equal(un, fu)
    assert _counter(reg, "fluid.fused_regions_total") == len(groups)
    assert _counter(reg, "fluid.fusion_rejected_total") == 0


def test_measured_loser_refuses_measured_slower(tune_cache):
    main, startup, feed, fetch = _proxy()
    groups = F._certified(main, list(feed), fetch)
    _put_measured(tune_cache, main, feed, groups, fuse=False)
    plan = F.plan_for(main, {k: v.shape for k, v in feed.items()},
                      fetch=fetch, feed=list(feed))
    assert plan.groups == []
    assert {r for _, r in plan.rejected} == {"measured_slower"}
    assert len(plan.rejected) == len(groups)


def test_stale_space_hash_refused(tune_cache):
    main, startup, feed, fetch = _proxy()
    groups = F._certified(main, list(feed), fetch)
    _put_measured(tune_cache, main, feed, groups, fuse=True,
                  space_hash="deadbeef0000")
    plan = F.plan_for(main, {k: v.shape for k, v in feed.items()},
                      fetch=fetch, feed=list(feed))
    assert plan.groups == []
    assert {r for _, r in plan.rejected} == {"stale"}


def test_tampered_certificate_refused(tune_cache):
    main, startup, feed, fetch = _proxy()
    groups = F._certified(main, list(feed), fetch)

    def swap_an_op(cert):
        cert = dict(cert, op_types=list(cert["op_types"]))
        cert["op_types"][0] = "matmul"       # an op swapped in place
        return cert

    _put_measured(tune_cache, main, feed, groups, fuse=True,
                  mangle_cert=swap_an_op)
    plan = F.plan_for(main, {k: v.shape for k, v in feed.items()},
                      fetch=fetch, feed=list(feed))
    assert plan.groups == []
    assert {r for _, r in plan.rejected} == {"cert_invalid"}


def test_unschedulable_interleaved_producer_refused():
    """a = relu(x); b = matmul(x, w); c = add(a, b): {relu, add} may
    certify as a chain, but hoisting add to relu's slot would read b
    before it exists — region_schedulable must refuse, and forcing must
    honor the refusal (correctness beats the knob)."""
    from paddle_tpu.analysis import region_schedulable
    from paddle_tpu.analysis.dataflow import fusable_groups
    fluid.reset_default_programs()
    x = fluid.layers.data("ux", shape=(4,))
    w = fluid.layers.data("uw", shape=(4,))
    a = fluid.layers.activation(x, "relu")
    b = fluid.layers.elementwise_mul(x, w)       # interferes: writes b
    c = fluid.layers.elementwise_add(a, b)
    out = fluid.layers.mean(c)
    main = fluid.default_main_program()
    block = main.blocks[0]
    groups = fusable_groups(main, fetch=[out.name],
                            feed=["ux", "uw"])
    straddling = [g for g in groups
                  if g.op_idxs[-1] - g.op_idxs[0] + 1 > len(g.op_idxs)]
    for g in straddling:
        assert not region_schedulable(block, g)
    plan = F.plan_for(main, {"ux": (2, 4), "uw": (2, 4)},
                      fetch=[out.name], feed=["ux", "uw"], force=True)
    for g in plan.groups:     # whatever force activated is convex
        assert g.op_idxs[-1] - g.op_idxs[0] + 1 == len(g.op_idxs)


def test_fused_flops_equal_unfused():
    """Fusion stays inside the one jit, so the roofline ledger's AOT
    cost-analysis FLOPs are untouched — the MFU denominator can't be
    gamed by regrouping ops."""
    main, startup, feed, fetch = _proxy()

    def flops(fuse):
        reg = obs.MetricsRegistry()
        with obs.ObsSession(registry=reg).installed():
            _run_steps(main, startup, feed, fetch, fuse, n=2)
        return _counter(reg, "fluid.device_flops_total")

    f_un, f_fu = flops(False), flops(True)
    assert f_un > 0
    assert f_un == f_fu


def test_measure_fusion_rows_and_e2e_consult(tune_cache):
    main, startup, feed, fetch = _proxy()
    rows = F.measure_fusion(main, startup, feed, fetch, reps=1, note="t")
    assert rows
    for r in rows:
        assert r["space"] == "fusion"
        assert isinstance(r["plan"]["fuse"], bool)
        assert r["heuristic_plan"] == {"fuse": False}
        assert r["fused_ms"] > 0 and r["unfused_ms"] > 0
        assert r["certificate"]["op_types"]
        # the family's third component re-derives from the certificate
        assert r["family"].split(":")[2] == F.group_signature(
            r["certificate"])
        tune_cache.put(r["space"], r["kernel"], "cpu", r["family"],
                       r["plan"], tune.space_hash("fusion"),
                       certificate=r["certificate"],
                       program_signature=r["program_signature"],
                       shape_family=r["shape_family"])
    plan = F.plan_for(main, {k: v.shape for k, v in feed.items()},
                      fetch=fetch, feed=list(feed))
    # every persisted verdict resolves: winners activate, losers refuse
    wins = sum(1 for r in rows if r["plan"]["fuse"])
    assert len(plan.groups) == wins
    assert len(plan.rejected) == len(rows) - wins
    assert all(reason == "measured_slower" for _, reason in plan.rejected)


# -- bucket_grid ---------------------------------------------------------

def _put_grid(cache, kind, buckets, space_hash=None):
    cache.put("bucket_grid", "prefill_dispatch", "cpu", kind,
              {"buckets": list(buckets)},
              space_hash or tune.space_hash("bucket_grid"),
              methodology="measured")


def test_bucket_grid_consult_validation(tune_cache):
    assert tune.bucket_grid("prompt") is None          # no entry
    _put_grid(tune_cache, "prompt", [32, 64, 256])
    assert tune.bucket_grid("prompt") == (32, 64, 256)
    assert tune.bucket_grid("prompt", max_len=128) == (32, 64)
    assert tune.bucket_grid("prompt", max_len=16) is None   # emptied
    assert tune.bucket_grid("prompt", divisor=64) is None   # 32 % 64 != 0
    assert tune.bucket_grid("prompt", divisor=32) == (32, 64, 256)
    # illegal grids are refused whole
    _put_grid(tune_cache, "cache", [64, 32])          # not ascending
    assert tune.bucket_grid("cache") is None
    _put_grid(tune_cache, "cache", [])                # empty
    assert tune.bucket_grid("cache") is None
    _put_grid(tune_cache, "cache", [0, 32])           # non-positive
    assert tune.bucket_grid("cache") is None
    _put_grid(tune_cache, "cache", [128, 256], space_hash="0ld")
    assert tune.bucket_grid("cache") is None          # stale


def test_pagepool_and_bucketspec_consult(tune_cache,
                                         paged_model_and_params):
    from paddle_tpu.data.feeder import BucketSpec
    from paddle_tpu.serving import PagePool
    model, params = paged_model_and_params
    # no entries: the heuristic defaults
    pool = PagePool(model, params, slots=2)
    assert pool.cache_bucket == 256
    assert tuple(pool.prompt_buckets) == (32, 64, 128, 256, 512)
    spec = BucketSpec({"words": "tuned"})
    assert spec.spec["words"][1] == (32, 64, 128, 256, 512)
    # tuned entries: consulted with max_len validation (model.max_len=128)
    _put_grid(tune_cache, "prompt", [32, 64, 128, 512])
    _put_grid(tune_cache, "cache", [64, 128])
    pool = PagePool(model, params, slots=2)
    assert tuple(pool.prompt_buckets) == (32, 64, 128)   # 512 > max_len
    assert pool.cache_bucket == 128                      # grid[-1]
    assert BucketSpec({"words": "tuned"}).spec["words"][1] \
        == (32, 64, 128, 512)
    # explicit args always win over the cache
    pool = PagePool(model, params, slots=2, cache_bucket=64,
                    prompt_buckets=(16, 32))
    assert pool.cache_bucket == 64
    assert tuple(pool.prompt_buckets) == (16, 32)


# -- lint + CLI ----------------------------------------------------------

def test_l008_fusion_and_bucket_grid_findings(tmp_path, tune_cache):
    from paddle_tpu.analysis import lint_autotune_cache
    main, startup, feed, fetch = _proxy()
    groups = F._certified(main, list(feed), fetch)
    # a healthy cache: clean
    _put_measured(tune_cache, main, feed, groups, fuse=True)
    _put_grid(tune_cache, "prompt", [32, 64])
    path = tune_cache.save(str(tmp_path / "ok.json"))
    assert lint_autotune_cache(path) == []
    # tampered certificate: the family key no longer re-derives
    c2 = tune.AutotuneCache()
    _put_measured(c2, main, feed, groups[:1], fuse=True,
                  mangle_cert=lambda cert: dict(
                      cert, op_types=["matmul"] + list(
                          cert["op_types"])[1:]))
    diags = lint_autotune_cache(c2.save(str(tmp_path / "cert.json")))
    assert len(diags) == 1 and diags[0].code == "L008"
    assert "does not re-derive" in diags[0].message
    # missing certificate / bad plan / bad grid
    c3 = tune.AutotuneCache()
    c3.put("fusion", "elementwise_chain", "cpu", "a:b:c",
           {"fuse": True}, tune.space_hash("fusion"))
    c3.put("fusion", "elementwise_chain", "cpu", "a:b:d",
           {"fuse": "yes"}, tune.space_hash("fusion"),
           certificate={"kind": "elementwise_chain"})
    c3.put("bucket_grid", "prefill_dispatch", "cpu", "prompt",
           {"buckets": [64, 32]}, tune.space_hash("bucket_grid"))
    diags = lint_autotune_cache(c3.save(str(tmp_path / "bad.json")))
    msgs = " | ".join(d.message for d in diags)
    assert len(diags) == 3
    assert "no dependence certificate" in msgs
    assert "expected {'fuse': true|false}" in msgs
    assert "ascending unique positive ints" in msgs
    # the standalone CLI path exits nonzero on the findings
    from paddle_tpu.cli import main as cli_main
    assert cli_main(["lint", "--autotune-cache",
                     str(tmp_path / "bad.json"),
                     "--fail-on", "warning"]) == 1


def test_tune_from_ledger_check_smoke(tmp_path, capsys):
    """`paddle_tpu tune --from-ledger --check`: synthetic profile sites
    seed the sweep (only implicated spaces run), the seeded families
    count on the obs plane, and the measured loop still closes."""
    from paddle_tpu.cli import main as cli_main
    sites = [{"op": "b0_op5_fused_elementwise_chain", "self_ns": 900000},
             {"op": "b0_op9_paged_decode_attention", "self_ns": 400000},
             {"op": "b0_op2_layer_norm", "self_ns": 10}]
    ledger = tmp_path / "sites.json"
    ledger.write_text(json.dumps(sites))
    path = str(tmp_path / "autotune.json")
    reg = obs.MetricsRegistry()
    with obs.ObsSession(registry=reg).installed():
        rc = cli_main(["tune", "--check", "--cache", path,
                       "--from-ledger", str(ledger)])
    out = capsys.readouterr().out
    assert rc == 0, out
    assert "--check OK" in out
    assert "implicate spaces ['fusion', 'page_block']" in out
    cache = tune.load_cache(path)
    spaces = {e["space"] for e in cache.entries.values()}
    assert spaces == {"fusion", "page_block"}     # seeding restricted
    assert _counter(reg, "tune.ledger_seeded_families_total") > 0
    # fusion entries persisted the full consult payload
    for e in cache.entries.values():
        if e["space"] == "fusion":
            assert isinstance(e["certificate"], dict)
            assert e["program_signature"]
            assert isinstance(e["plan"]["fuse"], bool)
