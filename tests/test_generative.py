"""GAN/VAE demo-model tests (v1_api_demo/gan + vae analogs) + image utils +
Ploter."""

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.data import image as IM
from paddle_tpu.data.dataset import mnist
from paddle_tpu.models.generative import GAN, VAE
from paddle_tpu.optimizer import Adam
from paddle_tpu.trainer.plot import Ploter


def _mnist_batch(n=128):
    imgs, _ = mnist._make(n, 0)
    return jnp.asarray(imgs)


def test_vae_elbo_improves():
    model = VAE(data_dim=784, latent=16, hidden=64)
    params = model.init(jax.random.PRNGKey(0))
    opt = Adam(3e-3)
    state = opt.init(params)
    x = _mnist_batch()

    @jax.jit
    def step(params, state, rng):
        loss, g = jax.value_and_grad(model.loss)(params, x, rng)
        params, state = opt.update(g, state, params)
        return params, state, loss

    rng = jax.random.PRNGKey(1)
    losses = []
    for i in range(80):
        rng, k = jax.random.split(rng)
        params, state, l = step(params, state, k)
        losses.append(float(l))
    assert losses[-1] < losses[0] * 0.9
    samples = model.sample(params, rng, 4)
    assert samples.shape == (4, 784)
    assert 0.0 <= float(samples.min()) and float(samples.max()) <= 1.0


def test_gan_adversarial_steps():
    model = GAN(data_dim=784, noise_dim=16, hidden=64)
    params = model.init(jax.random.PRNGKey(0))
    d_opt, g_opt = Adam(2e-4), Adam(2e-4)
    d_state, g_state = d_opt.init(params), g_opt.init(params)
    real = _mnist_batch(64)

    @jax.jit
    def d_step(params, d_state, z):
        loss, grads = jax.value_and_grad(model.d_loss)(params, real, z)
        _, d_grads = GAN.split_grads(grads)
        # zero G grads: only D updates
        grads = {k: (v if k.startswith("d") else
                     jax.tree_util.tree_map(jnp.zeros_like, v))
                 for k, v in grads.items()}
        params, d_state = d_opt.update(grads, d_state, params)
        return params, d_state, loss

    @jax.jit
    def g_step(params, g_state, z):
        loss, grads = jax.value_and_grad(model.g_loss)(params, z)
        grads = {k: (v if k.startswith("g") else
                     jax.tree_util.tree_map(jnp.zeros_like, v))
                 for k, v in grads.items()}
        params, g_state = g_opt.update(grads, g_state, params)
        return params, g_state, loss

    rng = jax.random.PRNGKey(2)
    d_losses, g_losses = [], []
    for i in range(20):
        rng, k1, k2 = jax.random.split(rng, 3)
        z = jax.random.normal(k1, (64, 16))
        params, d_state, dl = d_step(params, d_state, z)
        z = jax.random.normal(k2, (64, 16))
        params, g_state, gl = g_step(params, g_state, z)
        d_losses.append(float(dl))
        g_losses.append(float(gl))
    # discriminator learns to separate; both stay finite (GAN sanity, not
    # convergence — matches the demo's smoke-level assertions)
    assert d_losses[-1] < d_losses[0]
    assert np.isfinite(d_losses).all() and np.isfinite(g_losses).all()
    fake = model.generate(params, jax.random.normal(rng, (4, 16)))
    assert fake.shape == (4, 784)


def test_image_pipeline():
    rs = np.random.RandomState(0)
    im = rs.rand(40, 60, 3).astype(np.float32)
    r = IM.resize_short(im, 32)
    assert min(r.shape[:2]) == 32
    c = IM.center_crop(r, 32)
    assert c.shape[:2] == (32, 32)
    t = IM.simple_transform(im, 36, 32, is_train=True,
                            mean=[0.5, 0.5, 0.5], rng=rs)
    assert t.shape == (32, 32, 3)
    f = IM.left_right_flip(c)
    np.testing.assert_allclose(f[:, ::-1], c)
    # identity resize
    same = IM._bilinear(im, 40, 60)
    np.testing.assert_allclose(same, im, atol=1e-5)


def test_ploter_collects_and_draws(tmp_path):
    p = Ploter("train_cost", "test_cost")
    for i in range(5):
        p.append("train_cost", i, 1.0 / (i + 1))
    p.append("test_cost", 0, 0.9)
    assert len(p.data["train_cost"][0]) == 5
    out = p.plot(str(tmp_path / "curve.png"))
    if out is not None:
        import os
        assert os.path.exists(out)
    p.reset()
    assert p.data["train_cost"] == ([], [])
