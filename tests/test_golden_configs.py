"""Golden-program tests: the serialized Program JSON for representative
configs must match the checked-in goldens — the trainer_config_helpers
golden-proto discipline (configs/ generate proto, diff vs protostr/,
SURVEY.md §4.4). A legitimate IR change regenerates via:

    python tests/test_golden_configs.py --regen
"""

import json
import os
import sys

import pytest

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from golden_configs import CONFIGS

GOLDEN_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          "goldens")


def _dump(program) -> str:
    return json.dumps(program.to_dict(), indent=1, sort_keys=True,
                      default=lambda o: f"<callable:{getattr(o, '__name__', type(o).__name__)}>")


@pytest.mark.parametrize("name", sorted(CONFIGS))
def test_config_matches_golden(name):
    got = _dump(CONFIGS[name]())
    path = os.path.join(GOLDEN_DIR, f"{name}.json")
    assert os.path.exists(path), f"golden missing; regen: python {__file__} --regen"
    want = open(path).read()
    assert got == want, (
        f"program for {name!r} drifted from its golden; if intentional, "
        f"regenerate with: python {__file__} --regen")


def test_build_is_deterministic():
    a = _dump(CONFIGS["mlp_classifier"]())
    b = _dump(CONFIGS["mlp_classifier"]())
    assert a == b


if __name__ == "__main__":
    if "--regen" in sys.argv:
        os.makedirs(GOLDEN_DIR, exist_ok=True)
        for name, fn in CONFIGS.items():
            with open(os.path.join(GOLDEN_DIR, f"{name}.json"), "w") as f:
                f.write(_dump(fn()))
            print("wrote", name)
