"""Fleet health plane (ISSUE 15): windowed time-series, straggler/anomaly
detection, the alert rules engine, and their consumers.

Everything here runs on FAKE clocks — the faults plane's injectable
sleeper (``FaultPlan(sleep=...)``) means even a chaos ``delay`` advances
a counter instead of stalling the suite. The acceptance chaos test drives
the REAL paths end to end: an elastic worker's ``_timed_grad`` under an
installed ``step.grad`` delay plan, the real ``ela_grad`` dispatch into
the master's health tracker, the aggregator's evaluation loop, the alert
engine, the armed flight recorder, and the merged chrome export.
"""

import json
import threading
import urllib.error
import urllib.request

import numpy as np
import pytest

from elastic_testnet import build
from paddle_tpu import analysis, faults, obs
from paddle_tpu.obs.aggregate import ClusterAggregator, ObsHttpServer
from paddle_tpu.obs.alerts import (AlertEngine, AlertRule, default_rules,
                                   serving_slo_rules)
from paddle_tpu.obs.health import (FleetHealth, TimeSeriesStore, ewma,
                                   health_table, rate)
from paddle_tpu.runtime.membership import autoscale_recommendation
from paddle_tpu.trainer.elastic import (ElasticMaster, ElasticWorker,
                                        _pack_arrays)

pytestmark = pytest.mark.obs

LOSS_FN, PARAMS0, MK_OPT, BATCHES = build(steps=3)


def _counter_sample(name, value, labels=None):
    return {"type": "counter", "name": name, "value": float(value),
            "labels": labels or {}}


def _gauge_sample(name, value, labels=None):
    return {"type": "gauge", "name": name, "value": float(value),
            "labels": labels or {}}


def _hist_sample(name, count, total, buckets, labels=None):
    return {"type": "histogram", "name": name, "count": count,
            "sum": total, "buckets": buckets, "labels": labels or {},
            "max": 0.0}


# ---------------------------------------------------------------------------
# the windowed store
# ---------------------------------------------------------------------------

def test_store_rings_are_bounded_and_windowed():
    clock = [0.0]
    st = TimeSeriesStore(window_s=10.0, max_points=4, max_series=3,
                         clock=lambda: clock[0])
    for i in range(10):
        clock[0] = float(i)
        st.record("w0", [_gauge_sample("goodput.ratio", i / 10.0)])
    # per-series ring bound: only the last max_points survive
    pts = st.points("w0", "goodput.ratio", window_s=100.0)
    assert len(pts) == 4 and pts[-1] == (9.0, 0.9)
    # the read window drops old points even inside the ring
    assert [t for t, _ in st.points("w0", "goodput.ratio", window_s=1.5)] \
        == [8.0, 9.0]
    # total-series bound: the 4th distinct series is dropped and counted
    st.record("w1", [_gauge_sample("goodput.ratio", 0.5)])
    st.record("w2", [_gauge_sample("goodput.ratio", 0.5)])
    st.record("w3", [_gauge_sample("goodput.ratio", 0.5)])
    assert st.n_series() == 3
    assert st.dropped_series == 1
    # pruning dead workers frees their series
    assert st.prune(["w0"]) == 2
    assert st.n_series() == 1


def test_store_memory_bound_under_flood():
    # the aggregator-ring memory bound guardrail: a flood of pushes can
    # never hold more than max_points * max_series points
    st = TimeSeriesStore(max_points=16, max_series=8, clock=lambda: 0.0)
    for i in range(1000):
        st.record(f"w{i % 4}", [
            _gauge_sample("goodput.ratio", 0.5),
            _counter_sample("trainer.steps_total", i)])
    assert st.n_series() == 8
    assert st.n_points() <= 16 * 8


def test_rate_counter_delta_and_reset():
    pts = [(0.0, 100.0), (5.0, 150.0), (10.0, 200.0)]
    assert rate(pts) == pytest.approx(10.0)
    # restart mid-window: the counter fell back to near zero — the rate
    # re-bases at the newest value instead of going negative
    assert rate([(0.0, 100.0), (10.0, 40.0)]) == pytest.approx(4.0)
    assert rate([(0.0, 1.0)]) is None
    assert rate([]) is None


def test_ewma_mean_and_variance():
    m, v = ewma([1.0, 1.0, 1.0])
    assert m == pytest.approx(1.0) and v == pytest.approx(0.0)
    m, _ = ewma([0.0, 1.0], alpha=0.5)
    assert m == pytest.approx(0.5)
    assert ewma([]) == (None, None)


# ---------------------------------------------------------------------------
# derived health
# ---------------------------------------------------------------------------

def test_fleet_health_straggler_and_jitter_and_collapse():
    clock = [0.0]
    st = TimeSeriesStore(window_s=100.0, clock=lambda: clock[0])
    h = FleetHealth(clock=lambda: clock[0])
    # 3 workers; w2's shards run 5x slower than the fleet
    for i in range(8):
        clock[0] += 1.0
        for w, s in (("w0", 0.1), ("w1", 0.11), ("w2", 0.5)):
            h.note_shard(w, s)
        # steady heartbeats for w0/w1; w2's arrivals jitter wildly
        h.note_heartbeat("w0")
        h.note_heartbeat("w1")
        h.note_heartbeat("w2", now=clock[0] + (3.0 if i % 2 else -0.4))
        # goodput pushes: w1 collapses from 0.8 to ~0
        st.record("w0", [_gauge_sample("goodput.ratio", 0.8)])
        st.record("w1", [_gauge_sample("goodput.ratio",
                                       0.8 if i < 2 else 0.02)])
        st.record("w2", [_gauge_sample("goodput.ratio", 0.7)])
    snap = h.snapshot(st)
    # leave-one-out reference: w2 scores against median(w0, w1) medians
    assert snap["w2"]["straggler_score"] == pytest.approx(0.5 / 0.105,
                                                          rel=0.01)
    assert snap["w2"]["straggler"] is True
    assert snap["w0"]["straggler"] is False
    assert snap["w2"]["heartbeat_unstable"] is True
    assert snap["w0"]["heartbeat_unstable"] is False
    assert snap["w1"]["goodput_collapse"] is True
    assert snap["w0"]["goodput_collapse"] is False
    # forget drops the departed worker's feeds (re-join starts clean)
    h.forget("w2")
    snap = h.snapshot(st)
    assert snap["w2"]["straggler_score"] is None


def test_health_step_ewma_from_histogram_deltas():
    clock = [0.0]
    st = TimeSeriesStore(window_s=100.0, clock=lambda: clock[0])
    h = FleetHealth(clock=lambda: clock[0])
    # two snapshots of a step-time histogram: 10 steps totalling 2s, then
    # 20 steps totalling 6s -> windowed mean (6-2)/(20-10) = 0.4
    for count, total in ((10, 2.0), (20, 6.0)):
        clock[0] += 1.0
        st.record("w0", [_hist_sample("trainer.step_seconds", count, total,
                                      [[0.5, count], ["+Inf", count]])])
    snap = h.snapshot(st)
    assert snap["w0"]["step_ewma"] == pytest.approx(0.4)


# ---------------------------------------------------------------------------
# the alert engine
# ---------------------------------------------------------------------------

def test_threshold_rule_hysteresis_fires_and_resolves():
    clock = [0.0]
    st = TimeSeriesStore(window_s=100.0, clock=lambda: clock[0])
    eng = AlertEngine([AlertRule("hot", "cluster.health_straggler_score",
                                 kind="threshold", op=">", threshold=2.0,
                                 for_windows=2)], st)
    r = obs.MetricsRegistry()
    with obs.ObsSession(registry=r).installed() as s:
        def tick(value):
            clock[0] += 1.0
            st.record_value("w1", "cluster.health_straggler_score", value,
                            labels={"worker": "w1"})
            return eng.evaluate()

        assert tick(3.0) == []            # 1st true window: pending
        fired = tick(3.5)                 # 2nd: fires
        assert [e["args"]["state"] for e in fired] == ["fired"]
        assert fired[0]["args"]["worker"] == "w1"
        assert eng.active()[0]["rule"] == "hot"
        assert tick(4.0) == []            # still firing: no re-fire
        assert tick(1.0) == []            # 1st false window: still firing
        resolved = tick(1.0)              # 2nd: resolves
        assert [e["args"]["state"] for e in resolved] == ["resolved"]
        assert eng.active() == []
    # transitions counted and visible in the live tracer
    assert r.counter("alerts.fired_total").get(rule="hot") == 1
    assert r.counter("alerts.resolved_total").get(rule="hot") == 1
    assert r.gauge("alerts.active").get() == 0
    names = [e["args"]["state"] for e in s.dump()["events"]
             if e["name"] == "alert"]
    assert names == ["fired", "resolved"]


def test_firing_alert_resolves_when_series_vanishes():
    # review fix: a SIGKILLed worker whose series prune out of the store
    # must not leave a ghost active alert (or leak engine state) forever
    clock = [0.0]
    st = TimeSeriesStore(window_s=100.0, clock=lambda: clock[0])
    eng = AlertEngine([AlertRule("hot", "cluster.health_straggler_score",
                                 kind="threshold", op=">", threshold=2.0,
                                 for_windows=1)], st)
    st.record_value("w1", "cluster.health_straggler_score", 5.0)
    assert eng.evaluate()[0]["args"]["state"] == "fired"
    assert eng.active()
    st.prune([])                        # the worker aged out entirely
    clock[0] += 10.0
    out = eng.evaluate()
    assert out[0]["args"] == {"rule": "hot", "state": "resolved",
                              "reason": "series_gone", "worker": "w1",
                              "value": 5.0}
    assert eng.active() == [] and eng._state == {}


def test_prune_keeps_health_fed_workers():
    # review fix: elastic workers feed shard timings/heartbeats without
    # ever obs_pushing; a pushing worker ageing out must not wipe their
    # derived-health series
    clock = [0.0]
    agg = ClusterAggregator(ttl=50.0, clock=lambda: clock[0],
                            eval_interval_s=0.0)
    agg.push("pusher", [_gauge_sample("goodput.ratio", 0.9)])
    agg.health.note_shard("ela0", 0.1)
    agg.health.note_shard("ela1", 0.5)
    agg.evaluate()
    assert agg.history.points("ela1", "cluster.health_straggler_score",
                              labels={"worker": "ela1"})
    clock[0] += 100.0                   # pusher TTLs out; ela* still feed
    agg.push("pusher2", [_gauge_sample("goodput.ratio", 0.8)])
    assert agg.history.points("ela1", "cluster.health_straggler_score",
                              labels={"worker": "ela1"}, window_s=1e9)
    # once membership forgets them, the next prune drops their series
    agg.health.forget("ela0")
    agg.health.forget("ela1")
    clock[0] += 100.0
    agg.push("pusher3", [_gauge_sample("goodput.ratio", 0.8)])
    assert agg.history.points("ela1", "cluster.health_straggler_score",
                              labels={"worker": "ela1"},
                              window_s=1e9) == []


def test_absence_rule_fires_when_series_goes_quiet():
    clock = [0.0]
    st = TimeSeriesStore(window_s=500.0, clock=lambda: clock[0])
    eng = AlertEngine([AlertRule("quiet", "goodput.ratio", kind="absence",
                                 window_s=60.0, for_windows=1)], st)
    st.record("w0", [_gauge_sample("goodput.ratio", 0.9)])
    clock[0] = 30.0
    assert eng.evaluate() == []           # fresh enough
    clock[0] = 100.0                      # 100s silent > 60s window
    fired = eng.evaluate()
    assert fired[0]["args"]["rule"] == "quiet"
    assert fired[0]["args"]["silent_s"] == pytest.approx(100.0)
    # a store that never saw the metric stays silent (no series, no rule)
    st2 = TimeSeriesStore(clock=lambda: clock[0])
    assert AlertEngine([AlertRule("q2", "goodput.ratio", kind="absence",
                                  window_s=1.0)], st2).evaluate() == []


def test_burn_rate_rule_multi_window():
    clock = [0.0]
    st = TimeSeriesStore(window_s=600.0, clock=lambda: clock[0])
    rule = AlertRule("ttft_burn", "serving.ttft_seconds", kind="burn_rate",
                     slo_le=1.0, budget=0.1, short_s=60.0, long_s=300.0,
                     for_windows=1)
    eng = AlertEngine([rule], st)

    def push(count, good):
        # cumulative histogram: `good` of `count` within the 1.0s bound
        st.record("serving", [_hist_sample(
            "serving.ttft_seconds", count, count * 0.5,
            [[0.5, good // 2], [1.0, good], ["+Inf", count]])])

    # healthy traffic: 2% bad << 10% budget — no alert across the window
    n = 0
    for i in range(7):
        clock[0] += 50.0
        n += 100
        push(n, int(n * 0.98))
        assert eng.evaluate() == []
    # regression: every new request misses the SLO -> both windows burn
    for i in range(7):
        clock[0] += 50.0
        n += 100
        push(n, int(700 * 0.98))     # good count frozen: all new are bad
        out = eng.evaluate()
        if out:
            assert out[0]["args"]["rule"] == "ttft_burn"
            assert out[0]["args"]["burn_short"] > 1.0
            assert out[0]["args"]["burn_long"] > 1.0
            break
    else:
        pytest.fail("burn-rate rule never fired on sustained SLO misses")


def test_alert_rule_authoring_errors():
    with pytest.raises(ValueError):
        AlertRule("r", "m.x", kind="nope")
    with pytest.raises(ValueError):
        AlertRule("r", "m.x", kind="threshold")          # no threshold
    with pytest.raises(ValueError):
        AlertRule("r", "m.x_seconds", kind="burn_rate")  # no slo_le
    with pytest.raises(ValueError):
        AlertRule("r", "m.x_seconds", kind="burn_rate", slo_le=1.0,
                  budget=2.0)
    with pytest.raises(ValueError):
        AlertRule("r", "m.x_seconds", kind="burn_rate", slo_le=1.0,
                  short_s=300.0, long_s=60.0)
    with pytest.raises(ValueError):
        AlertRule("r", "m.x", kind="threshold", threshold=1, op="!=")


# ---------------------------------------------------------------------------
# L009 + catalogue cleanliness (tree-clean suite tests)
# ---------------------------------------------------------------------------

def test_l009_lint_matrix_and_shipped_rules_clean():
    # the shipped default rule set (incl. the serving SLO burn rates) is
    # L009-clean against the shipped catalogue
    assert analysis.lint_alert_rules() == []
    # and the new catalogue entries are L005-clean (satellite bar)
    assert analysis.lint_metric_names(obs.CATALOGUE) == []
    bad = [
        AlertRule("r1", "nope.metric_total", kind="threshold", threshold=1),
        AlertRule("r2", "serving.ttft_seconds", kind="threshold",
                  threshold=1),
        AlertRule("r3", "goodput.ratio", kind="burn_rate", slo_le=1.0),
        AlertRule("r4", "rpc.calls_total", kind="threshold", threshold=1,
                  labels={"bogus": "x"}),
        # worker label is always legal: the merged-view contract
        AlertRule("r5", "cluster.health_straggler_score", kind="threshold",
                  threshold=2, labels={"worker": "w0"}),
    ]
    diags = analysis.lint_alert_rules(bad)
    assert sorted(d.var for d in diags) == ["r1", "r2", "r3", "r4"]
    assert all(d.code == "L009" for d in diags)
    # engine-parameterized serving rules stay clean at any target
    assert analysis.lint_alert_rules(
        serving_slo_rules(0.5, 0.1, 0.05)) == []


def test_engine_slo_rule_defaults():
    from paddle_tpu.obs.alerts import serving_slo_rules as slo
    rules = slo(2.0, 0.5, 0.2)
    assert [r.metric for r in rules] == ["serving.ttft_seconds",
                                        "serving.tpot_seconds"]
    assert rules[0].slo_le == 2.0 and rules[1].slo_le == 0.5
    assert all(r.kind == "burn_rate" and r.budget == 0.2 for r in rules)


def test_add_rules_replaces_same_named_defaults():
    # review fix: a daemon registering its engine's configured SLO
    # targets must OVERRIDE the aggregator's same-named defaults — a
    # silent dedupe would evaluate the operator's 0.2s SLO at the
    # default 1.0s forever
    clock = [0.0]
    st = TimeSeriesStore(clock=lambda: clock[0])
    eng = AlertEngine(default_rules(), st)
    eng._state[("serving_ttft_slo_burn", ("serving",))] = object()
    eng.add_rules(serving_slo_rules(0.2, 0.05, 0.01))
    by_name = {r.name: r for r in eng.rules}
    assert by_name["serving_ttft_slo_burn"].slo_le == 0.2
    assert by_name["serving_tpot_slo_burn"].slo_le == 0.05
    # no duplicate names, and the replaced rule's stale state is reset
    assert len(by_name) == len(eng.rules)
    assert ("serving_ttft_slo_burn", ("serving",)) not in eng._state


def test_evicted_health_fed_worker_alert_resolves():
    # review fix: an evicted elastic worker (fed shard timings, never
    # obs_pushed) must not leave its straggler alert frozen as firing —
    # membership departure reaps its history series, and the next
    # evaluation resolves series_gone
    clock = [0.0]
    agg = ClusterAggregator(clock=lambda: clock[0], eval_interval_s=0.0)
    for i in range(6):
        clock[0] += 1.0
        agg.health.note_shard("fast", 0.1)
        agg.health.note_shard("slow", 1.0)
        agg.evaluate()
    assert any(a["rule"] == "worker_straggler" and a["worker"] == "slow"
               for a in agg.alerts.active())
    agg.forget_worker("slow")          # the membership eviction hook
    clock[0] += 1.0
    agg.evaluate()
    assert any(e["args"]["state"] == "resolved"
               and e["args"].get("reason") == "series_gone"
               and e["args"]["worker"] == "slow"
               for e in agg.alerts.recent_events())
    assert not any(a["worker"] == "slow" for a in agg.alerts.active())


# ---------------------------------------------------------------------------
# aggregator integration
# ---------------------------------------------------------------------------

def test_aggregator_history_health_and_ttl_pruning():
    clock = [0.0]
    agg = ClusterAggregator(ttl=100.0, clock=lambda: clock[0],
                            eval_interval_s=5.0)
    r = obs.MetricsRegistry()
    with obs.ObsSession(registry=r).installed():
        for i in range(6):
            clock[0] += 10.0
            for w, g in (("w0", 0.8), ("w1", 0.7)):
                agg.push(w, [_gauge_sample("goodput.ratio", g)])
        # history recorded per push
        assert len(agg.history.points("w0", "goodput.ratio")) == 6
        # rate-limited evaluation ran (eval_interval < push spacing) and
        # derived gauges landed in the live registry + back in the store
        agg.health.note_shard("w0", 0.1)
        agg.health.note_shard("w1", 0.3)
        clock[0] += 10.0
        agg.evaluate()
        assert r.gauge("cluster.health_goodput_ewma").get(worker="w0") \
            == pytest.approx(0.8)
        assert agg.history.points("w1", "cluster.health_straggler_score",
                                  labels={"worker": "w1"})
        # TTL ageing drops the worker's snapshot AND (once membership
        # forgot it — it is no longer health-fed) its history series
        agg.health.forget("w1")
        clock[0] += 200.0
        agg.push("w0", [_gauge_sample("goodput.ratio", 0.8)])
        assert agg.workers() == ["w0"]
        assert agg.history.points("w1", "goodput.ratio",
                                  window_s=1e9) == []


# ---------------------------------------------------------------------------
# autoscale hysteresis
# ---------------------------------------------------------------------------

def test_autoscale_hysteresis_no_flapping():
    clock = [0.0]
    st = TimeSeriesStore(window_s=300.0, clock=lambda: clock[0])

    def tick(todo):
        clock[0] += 5.0
        return autoscale_recommendation(
            members=2, todo=todo, pending=0, history=st,
            hysteresis_windows=3)

    # a one-window backlog spike recommends HOLD (tentative join noted)
    r = tick(20)
    assert r["action"] == "hold" and r["tentative"] == "join"
    assert "hysteresis" in r["reason"]
    r = tick(0)
    assert r["action"] == "hold" and "tentative" not in r
    # sustained backlog commits join on the 3rd consecutive window
    actions = [tick(20)["action"] for _ in range(3)]
    assert actions == ["hold", "hold", "join"]
    # members == 0 bypasses hysteresis: a dead fleet must scale NOW
    r = autoscale_recommendation(members=0, todo=5, pending=0, history=st)
    assert r["action"] == "join"
    # pure-function mode (no history) unchanged: instantaneous policy
    r = autoscale_recommendation(members=2, todo=20, pending=0)
    assert r["action"] == "join"


def test_autoscale_hysteresis_sparse_poller_still_scales():
    # review fix: a scaler polling every 150s (window 300s) can never
    # land 3 points in the window — a PERSISTENT backlog must still
    # commit join once agreeing evaluations span >= half the window
    clock = [0.0]
    st = TimeSeriesStore(window_s=300.0, clock=lambda: clock[0])

    def tick(todo):
        clock[0] += 150.0
        return autoscale_recommendation(members=1, todo=todo, pending=0,
                                        history=st, hysteresis_windows=3)

    assert tick(10)["action"] == "hold"        # single point: no span
    assert tick(10)["action"] == "join"        # 2 points spanning 150s
    # but a single sparse spike still never commits
    st2 = TimeSeriesStore(window_s=300.0, clock=lambda: clock[0])
    clock[0] += 150.0
    r = autoscale_recommendation(members=1, todo=10, pending=0,
                                 history=st2, hysteresis_windows=3)
    assert r["action"] == "hold" and r["tentative"] == "join"


# ---------------------------------------------------------------------------
# obs serve endpoints + obs top (file mode AND live-provider mode)
# ---------------------------------------------------------------------------

def _fleet_dump():
    return {
        "meta": {"pid": 11, "process": "master",
                 "clock_origin_unix": 1000.0},
        "metrics": [
            _gauge_sample("goodput.ratio", 0.8, {"worker": "w0"}),
            _gauge_sample("goodput.ratio", 0.2, {"worker": "w1"}),
            _gauge_sample("cluster.health_straggler_score", 3.2,
                          {"worker": "w1"}),
            _gauge_sample("serving.queue_depth", 4, {"worker": "serving"}),
        ],
        "events": [
            {"kind": "instant", "name": "alert", "ts": 1.0, "tid": 0,
             "pid": 11, "parent": None,
             "args": {"rule": "worker_straggler", "state": "fired",
                      "worker": "w1", "value": 3.2,
                      "metric": "cluster.health_straggler_score",
                      "severity": "warning"}},
        ]}


def _get(addr, path):
    host, port = addr
    with urllib.request.urlopen(f"http://{host}:{port}{path}",
                                timeout=10) as resp:
        return resp.status, resp.read().decode()


def test_obs_serve_summary_table_and_alerts_file_mode(tmp_path):
    # file mode: a dump on disk, NO live master anywhere
    p = str(tmp_path / "fleet.jsonl")
    obs.write_jsonl(p, _fleet_dump())
    srv = ObsHttpServer(lambda: obs.read_jsonl(p)).start()
    try:
        code, body = _get(srv.address, "/summary")
        assert code == 200
        assert "== fleet health ==" in body
        row = next(ln for ln in body.splitlines() if ln.startswith("w1"))
        assert "3.20" in row and "worker_straggler" in row
        code, body = _get(srv.address, "/alerts")
        assert code == 200
        al = json.loads(body)
        assert al["events"][0]["args"]["rule"] == "worker_straggler"
        assert al["active"] == []        # no live engine in file mode
    finally:
        srv.stop()


def test_obs_serve_alerts_live_provider_mode():
    # master mode: the provider attaches live health + active alerts the
    # way cmd_obs_serve's --master provider does (obs_health payload)
    dump = _fleet_dump()
    dump["alerts"] = [{"rule": "worker_straggler", "worker": "w1",
                       "state": "firing", "value": 3.2, "since": 5.0,
                       "labels": {}}]
    dump["health"] = {"w2": {"straggler_score": 1.0,
                             "heartbeat_jitter": 0.01,
                             "goodput_ewma": 0.9}}
    srv = ObsHttpServer(lambda: dump).start()
    try:
        code, body = _get(srv.address, "/alerts")
        assert code == 200
        assert json.loads(body)["active"][0]["rule"] == "worker_straggler"
        code, body = _get(srv.address, "/summary")
        # the derived-health worker (w2) appears even with no samples
        assert any(ln.startswith("w2") for ln in body.splitlines())
    finally:
        srv.stop()


def test_obs_top_once_cli(tmp_path, capsys):
    from paddle_tpu.cli import main
    p = str(tmp_path / "fleet.jsonl")
    obs.write_jsonl(p, _fleet_dump())
    assert main(["obs", "top", "--input", p, "--once"]) == 0
    out = capsys.readouterr().out
    assert "worker" in out and "straggler" in out
    row = next(ln for ln in out.splitlines() if ln.startswith("w1"))
    assert "worker_straggler" in row
    # no sources -> structured usage error
    assert main(["obs", "top"]) == 2


# ---------------------------------------------------------------------------
# zero-cost-when-off guardrail
# ---------------------------------------------------------------------------

def test_uninstalled_plane_overhead_per_batch():
    # the worker-side hooks this plane rides (chaos site fire, obs
    # emitters, the shard clock) with NO plan and NO session installed:
    # <= ~5us per batch budget, measured with 10x slack like the flight
    # recorder's precedent (test_obs.py)
    import time as _t
    assert not obs.is_active() and not faults.is_active()

    def per_batch(n=2000):
        t0 = _t.perf_counter()
        for _ in range(n):
            faults.fire("step.grad")
            obs.observe("cluster.shard_seconds", 0.1, worker="w")
            obs.gauge_set("cluster.health_straggler_score", 1.0, worker="w")
            obs.count("alerts.fired_total", rule="r")
            _t.monotonic()
        return (_t.perf_counter() - t0) / n

    assert min(per_batch() for _ in range(3)) < 50e-6


# ---------------------------------------------------------------------------
# the acceptance chaos test
# ---------------------------------------------------------------------------

def test_chaos_straggler_alert_flight_chrome_and_stable_autoscale(tmp_path):
    """ISSUE 15 acceptance: a faults-plane ``delay`` on ONE of three
    elastic workers' ``step.grad`` site is flagged as a straggler within
    K evaluation windows; the alert event lands in the flight-recorder
    dump AND the merged chrome export; and the autoscale recommendation
    is hysteresis-stable (no join/leave flapping) across the injected
    window. Fake clocks everywhere — the injected delay advances the
    shared counter through FaultPlan(sleep=...), nothing really sleeps.
    """
    clock = [0.0]

    def fake():
        return clock[0]

    def advance(s):
        clock[0] += s

    r = obs.MetricsRegistry()
    session = obs.ObsSession(registry=r, tracer=obs.Tracer(clock=fake))
    flight_path = str(tmp_path / "flight.jsonl")
    # three elastic workers sharing the REAL timed shard path; w2 carries
    # the delay plan (0.4s of fake wall time per shard, every shard)
    workers = {w: ElasticWorker(LOSS_FN, ("127.0.0.1", 1), worker=w,
                                clock=fake)
               for w in ("w0", "w1", "w2")}
    for w in workers.values():
        import jax
        w._params = jax.device_put(PARAMS0())
    plan = faults.FaultPlan(sleep=advance).add(
        "step.grad", "delay", delay_s=0.4, nth=1, count=10_000)
    # healthy workers still take (fake) time per shard — without it the
    # fleet median is 0 and no ratio exists; the baseline plan also
    # proves step.grad fires on every worker's shard path
    baseline = faults.FaultPlan(sleep=advance).add(
        "step.grad", "delay", delay_s=0.05, nth=1, count=10_000)

    em = ElasticMaster(LOSS_FN, MK_OPT(), shards_per_step=3)
    agg = ClusterAggregator(clock=fake, eval_interval_s=0.0)
    em.server.aggregator = agg         # fake-clock health plane
    x, y = BATCHES[0]

    with session.installed():
        rec = obs.FlightRecorder(session, flight_path, ring_size=512).arm()
        try:
            for w in workers:
                em.server._dispatch({"op": "mbr_join", "worker": w})
            epoch = em.membership.epoch
            actions = []
            fired_window = None
            for window in range(6):
                # one elastic step per window: each worker computes one
                # shard through the real timed path and pushes ela_grad
                with em._cv:
                    em._pending = (0, window)
                    em._shard_rows = [len(x) // 3] * 3
                    em._grads, em._losses = {}, {}
                for shard, (name, w) in enumerate(workers.items()):
                    payload = {"batch": _pack_arrays(
                        [x[shard::3], y[shard::3]])}
                    with (plan if name == "w2" else baseline).installed():
                        loss, grads, elapsed = w._timed_grad(payload)
                    from paddle_tpu.trainer.elastic import _pack_tree
                    resp = em.server._dispatch({
                        "op": "ela_grad", "worker": name,
                        "member_token": em.membership._members[name].token,
                        "epoch": epoch, "pass": 0, "step": window,
                        "shard": shard, "loss": loss,
                        "grad": _pack_tree(grads), "elapsed_s": elapsed})
                    assert resp["ok"], resp
                # workers' telemetry pushes + the health/alert evaluation
                for name in workers:
                    agg.push(name, [_gauge_sample("goodput.ratio", 0.7)])
                advance(5.0)
                agg.evaluate()
                active = {a["rule"]: a["worker"]
                          for a in agg.alerts.active()}
                if "worker_straggler" in active and fired_window is None:
                    fired_window = window
                # the autoscale consumer over the SAME windowed history:
                # inject a one-window backlog spike mid-run; the
                # recommendation must never flap to join/leave
                spike = 30 if window == 3 else 0
                rec_out = autoscale_recommendation(
                    members=3, todo=spike, pending=0,
                    samples=agg.merged_samples(), history=agg.history,
                    hysteresis_windows=3)
                actions.append(rec_out["action"])
            # 1) the delayed worker (and only it) is flagged, within K
            # windows of the injection (rule needs for_windows=2)
            assert fired_window is not None and fired_window <= 3
            assert active.get("worker_straggler") == "w2"
            assert plan.hits.get("step.grad", 0) >= 1   # the chaos fired
            score = r.gauge("cluster.health_straggler_score").get(
                worker="w2")
            assert score > FleetHealth.STRAGGLER_RATIO
            # 2) hysteresis-stable autoscale: no join/leave across the
            # injected window despite the backlog spike
            assert set(actions) == {"hold"}, actions
            # 3) the alert event is in the flight dump...
            rec.dump("test")
        finally:
            rec.disarm()
        flight = obs.read_jsonl(flight_path)
        alert_evs = [e for e in flight["events"] if e["name"] == "alert"]
        assert any(e["args"]["rule"] == "worker_straggler"
                   and e["args"]["worker"] == "w2"
                   and e["args"]["state"] == "fired" for e in alert_evs)
        # ...and in the merged chrome export (master dump + flight dump)
        merged = obs.merge_dumps([flight, session.dump()])
        trace = obs.chrome_trace(merged)
        assert any(ev.get("name") == "alert"
                   and ev.get("args", {}).get("rule") == "worker_straggler"
                   for ev in trace["traceEvents"])
    em.server.stop()
    em.membership.stop()


# ---------------------------------------------------------------------------
# elastic integration: the real wire path feeds the health plane
# ---------------------------------------------------------------------------

def test_elastic_run_feeds_shard_timings_to_master_health():
    """A REAL 2-worker elastic pass over the RPC plane lands worker-
    reported shard timings in the master's health tracker and the
    cluster.shard_seconds histogram (the straggler score's feed)."""
    r = obs.MetricsRegistry()
    with obs.ObsSession(registry=r).installed():
        em = ElasticMaster(LOSS_FN, MK_OPT(), ttl=5.0, task_timeout_s=10.0,
                           shards_per_step=4, min_workers=2).start()
        host, port = em.address
        stop = threading.Event()
        ws, ts = [], []
        for i in range(2):
            w = ElasticWorker(LOSS_FN, (host, port), worker=f"hw{i}")
            t = threading.Thread(target=w.run, kwargs={"stop": stop},
                                 daemon=True)
            t.start()
            ws.append(w)
            ts.append(t)
        try:
            em.fit(BATCHES, PARAMS0(), num_passes=1,
                   progress_timeout=60.0)
        finally:
            stop.set()
            for t in ts:
                t.join(timeout=10)
            em.stop()
        snap = r.histogram("cluster.shard_seconds")
        counts = {dict(k).get("worker"): s["count"]
                  for k, s in snap.samples()}
        # every shard of every step reported a timing, per worker
        assert set(counts) == {"hw0", "hw1"}
        assert sum(counts.values()) == len(BATCHES) * 4
        # the graceful leave hook wiped the departed workers' health
        # feeds (a re-join under the same name starts clean)
        with em.server.aggregator.health._lock:
            assert set(em.server.aggregator.health._shards) == set()
