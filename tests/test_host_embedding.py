"""Host-offloaded embedding path (runtime/host_embedding.py) — the
sparse-remote capability (trainer/RemoteParameterUpdater.h:265,
pserver/ParameterServer2.h:510 getParameterSparse): host-resident master
table, touched-row streaming, sparse row updates, and the exactness of the
overlapped prefetcher. Equivalence oracle: the same model trained with the
table fully on-device."""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu.runtime import (HostEmbeddingTable, HostEmbedPrefetcher,
                                native_available)

pytestmark = pytest.mark.skipif(not native_available(),
                                reason="native host runtime not built")

VOCAB, DIM, B, T = 50, 8, 4, 6


def _batches(n, seed=0, vocab=VOCAB):
    rs = np.random.RandomState(seed)
    return [rs.randint(0, vocab, (B, T)) for _ in range(n)]


def _head(seed=1):
    rs = np.random.RandomState(seed)
    return jnp.asarray(rs.standard_normal((DIM,)).astype(np.float32))


def _device_loss(rows, inverse, w):
    """Toy objective over the looked-up embeddings; grads wrt rows are the
    merged SelectedRows gradient."""
    e = HostEmbeddingTable.lookup(rows, inverse)       # [B, T, D]
    return jnp.sum(jnp.tanh(e @ w))


@pytest.mark.parametrize("optimizer", ["sgd", "adagrad"])
def test_offloaded_matches_on_device_table(optimizer):
    """N serial steps through the host table == the same steps with the
    whole table on device (the ShardedEmbedding-style dense path)."""
    lr = 0.1
    rs = np.random.RandomState(3)
    init = rs.standard_normal((VOCAB, DIM)).astype(np.float32) * 0.1
    w = _head()
    batches = _batches(5)

    # --- offloaded path
    table = HostEmbeddingTable(VOCAB, DIM, optimizer=optimizer, lr=lr,
                               capacity=B * T, init=init.copy())
    grad_fn = jax.jit(jax.grad(_device_loss))
    for ids in batches:
        batch = table.prefetch(ids)
        g = grad_fn(batch.rows, batch.inverse, w)
        table.apply_grad(batch, g)

    # --- on-device dense oracle (same optimizer math in numpy/f32)
    dense = init.copy()
    accum = np.zeros_like(dense)
    dgrad = jax.jit(jax.grad(
        lambda t, ids, w: _device_loss(t, ids, w)))
    for ids in batches:
        g = np.asarray(dgrad(jnp.asarray(dense), jnp.asarray(ids), w))
        if optimizer == "sgd":
            dense -= lr * g
        else:
            touched = np.unique(ids)
            accum[touched] += g[touched] ** 2
            denom = np.sqrt(accum[touched]) + 1e-6
            dense[touched] -= lr * g[touched] / denom

    got = table.rows_host(np.arange(VOCAB))
    np.testing.assert_allclose(got, dense, rtol=2e-5, atol=2e-6)


def test_untouched_rows_never_move():
    """Adagrad accumulators and params of rows no batch touches must stay
    bit-identical (the sparse contract; dense offload would decay them)."""
    init = np.ones((VOCAB, DIM), np.float32)
    table = HostEmbeddingTable(VOCAB, DIM, optimizer="adagrad", lr=0.5,
                               capacity=8, init=init.copy())
    ids = np.array([[1, 2, 3, 1]])
    w = _head()
    batch = table.prefetch(ids)
    g = jax.grad(_device_loss)(batch.rows, batch.inverse, w)
    table.apply_grad(batch, g)
    untouched = np.setdiff1d(np.arange(VOCAB), np.unique(ids))
    np.testing.assert_array_equal(table.rows_host(untouched),
                                  init[untouched])
    assert not np.allclose(table.rows_host(np.unique(ids)),
                           init[np.unique(ids)])


def test_capacity_exceeded_raises():
    table = HostEmbeddingTable(VOCAB, DIM, capacity=4)
    with pytest.raises(ValueError, match="capacity"):
        table.prefetch(np.arange(10))


def test_prefetcher_overlap_is_exact():
    """Batches with heavy id overlap: the speculative prefetch of batch i+1
    runs before batch i's update, so without the intersection fix-up the
    read would be stale. Final table must equal the serial path's."""
    lr = 0.2
    rs = np.random.RandomState(7)
    init = rs.standard_normal((VOCAB, DIM)).astype(np.float32) * 0.1
    w = _head()
    # consecutive batches share ~half their ids
    batches = [rs.randint(0, 12, (B, T)) for _ in range(6)]

    serial = HostEmbeddingTable(VOCAB, DIM, lr=lr, capacity=B * T,
                                init=init.copy())
    grad_fn = jax.jit(jax.grad(_device_loss))
    for ids in batches:
        b = serial.prefetch(ids)
        serial.apply_grad(b, grad_fn(b.rows, b.inverse, w))

    overlapped = HostEmbeddingTable(VOCAB, DIM, lr=lr, capacity=B * T,
                                    init=init.copy())
    pf = HostEmbedPrefetcher(overlapped, iter(batches))
    steps = 0
    while True:
        b = pf.next()
        if b is None:
            break
        pf.commit(b, grad_fn(b.rows, b.inverse, w))
        steps += 1
    assert steps == len(batches)
    np.testing.assert_array_equal(
        overlapped.rows_host(np.arange(VOCAB)),
        serial.rows_host(np.arange(VOCAB)))


def test_checkpoint_roundtrip():
    table = HostEmbeddingTable(VOCAB, DIM, optimizer="adagrad", capacity=8)
    ids = np.array([[1, 2, 3, 4]])
    w = _head()
    b = table.prefetch(ids)
    table.apply_grad(b, jax.grad(_device_loss)(b.rows, b.inverse, w))
    blob = table.serialize()

    restored = HostEmbeddingTable(VOCAB, DIM, optimizer="adagrad",
                                  capacity=8)
    restored.deserialize(blob)
    np.testing.assert_array_equal(restored.rows_host(np.arange(VOCAB)),
                                  table.rows_host(np.arange(VOCAB)))
    # post-restore updates continue with the restored accumulators
    b2 = restored.prefetch(ids)
    restored.apply_grad(b2, jax.grad(_device_loss)(b2.rows, b2.inverse, w))
    b3 = table.prefetch(ids)
    table.apply_grad(b3, jax.grad(_device_loss)(b3.rows, b3.inverse, w))
    np.testing.assert_array_equal(restored.rows_host(np.arange(VOCAB)),
                                  table.rows_host(np.arange(VOCAB)))
