"""Layer-zoo unit tests (shape/semantics checks, analog of gserver/tests basics)."""

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu import nn
from paddle_tpu.ops import conv as conv_ops
from paddle_tpu.ops import pool as pool_ops
from paddle_tpu.optimizer import SGD


def test_conv2d_transpose_channel_change(rng):
    layer = nn.Conv2DTranspose(8, 16, 3, stride=2, padding=1)
    params = layer.init(rng)
    y = layer(params, jnp.ones((2, 5, 5, 8)))
    assert y.shape[0] == 2 and y.shape[-1] == 16


def test_batchnorm_in_sequential_train(rng):
    model = nn.Sequential(nn.Linear(4, 4), nn.BatchNorm(4), nn.Linear(4, 2))
    params = model.init(rng)
    mut = {}
    y = model(params, jnp.ones((8, 4)), train=True, mutable=mut)
    assert y.shape == (8, 2)
    # updated stats collected and mergeable
    assert len(mut) == 1
    new_params = nn.apply_stat_updates(params, mut)
    path = next(iter(mut))
    assert "moving_mean" in mut[path]
    # eval mode: no mutable needed
    y2 = model(new_params, jnp.ones((8, 4)))
    assert y2.shape == (8, 2)


def test_bn_stats_not_touched_by_optimizer(rng):
    bn = nn.BatchNorm(4)
    params = bn.init(rng)
    opt = SGD(learning_rate=0.5, weight_decay=0.1)
    state = opt.init(params)
    grads = jax.tree_util.tree_map(jnp.zeros_like, params)
    new_params, _ = opt.update(grads, state, params)
    # moving stats must be bit-identical (no decay applied)
    np.testing.assert_array_equal(np.asarray(new_params["stats"]["moving_var"]),
                                  np.asarray(params["stats"]["moving_var"]))
    # trainable gamma DID get weight-decayed
    assert not np.allclose(np.asarray(new_params["gamma"]), np.asarray(params["gamma"]))


def test_spp_fixed_length_across_input_sizes():
    for hw in (4, 5, 7):
        x = jnp.ones((1, hw, hw, 3))
        out = pool_ops.spatial_pyramid_pool(x, pyramid_height=2)
        assert out.shape == (1, (1 + 4) * 3), out.shape


def test_im2col_patch_major_layout():
    # 1x3x3x2 input with distinct values; single 3x3 patch must read as
    # (kh, kw, C) row-major
    x = jnp.arange(18, dtype=jnp.float32).reshape(1, 3, 3, 2)
    patches = conv_ops.im2col(x, kernel=3)
    assert patches.shape == (1, 1, 1, 18)
    np.testing.assert_array_equal(np.asarray(patches).ravel(),
                                  np.asarray(x).ravel())


def test_dropout_eval_identity(rng):
    d = nn.Dropout(0.5)
    params = d.init(rng)
    x = jnp.ones((4, 4))
    np.testing.assert_array_equal(np.asarray(d(params, x)), np.asarray(x))
    y = d(params, x, train=True, rng=jax.random.PRNGKey(1))
    assert float(jnp.sum(y == 0.0)) > 0  # some units dropped
