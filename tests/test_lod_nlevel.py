"""N-level LoD (core/lod.py LoDBatch) — generalizing the reference's
LoDTensor (framework/lod_tensor.h:57,82) beyond 2 nesting levels under the
static-shape regime: one padded axis per level + per-level lengths, with
lossless conversion to/from the reference's offset-vector form.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu.core.lod import (LoDBatch, SeqBatch, lod_batch_from_offsets,
                                 lod_batch_to_offsets, pack_lod, unpack_lod)

RS = np.random.RandomState(7)


def _rand_nested(depth, fanout=3, feat=(2,), dtype=np.float32):
    """Random ragged structure of the given depth (>=1 child per node so the
    structure is well-formed; ragged lengths incl. empty innermost seqs)."""
    if depth == 1:
        return RS.randn(int(RS.randint(0, 5)), *feat).astype(dtype)
    return [_rand_nested(depth - 1, fanout, feat)
            for _ in range(int(RS.randint(1, fanout + 1)))]


@pytest.mark.parametrize("levels", [1, 2, 3, 4])
def test_pack_unpack_roundtrip(levels):
    nested = [_rand_nested(levels) for _ in range(4)]
    batch = pack_lod(nested, levels)
    assert batch.nlevels == levels
    assert batch.data.ndim == levels + 2  # [B, S1..S_{L-1}, T, feat]
    assert len(batch.level_lengths) == levels
    for i, lens in enumerate(batch.level_lengths):
        assert lens.shape == batch.data.shape[:i + 1]
    back = unpack_lod(batch)
    assert len(back) == len(nested)

    def _eq(a, b):
        if isinstance(a, list):
            assert isinstance(b, list) and len(a) == len(b)
            for x, y in zip(a, b):
                _eq(x, y)
        else:
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    _eq(nested, back)


def test_three_level_offsets_roundtrip_matches_reference_form():
    """LoDBatch <-> the reference's (flat rows, offset levels) encoding
    (lod_tensor.h:82): a 3-level LoD round-trips exactly both ways."""
    # 2 samples; sample 0 has 2 level-1 children, sample 1 has 1
    lod = [(0, 2, 3), (0, 2, 5, 7), (0, 3, 5, 9, 11, 12, 15, 17)]
    flat = RS.randn(17, 4).astype(np.float32)
    batch = lod_batch_from_offsets(flat, lod)
    assert batch.nlevels == 3
    assert batch.batch_size == 2
    # padded shape: [B=2, S1=2, S2=3, T=4, 4]
    assert batch.data.shape == (2, 2, 3, 4, 4)
    flat2, lod2 = lod_batch_to_offsets(batch)
    assert [tuple(l) for l in lod2] == [tuple(l) for l in lod]
    np.testing.assert_array_equal(flat2, flat)


def test_from_offsets_rejects_inconsistent_lod():
    with pytest.raises(ValueError, match="covers 3"):
        lod_batch_from_offsets(np.zeros((2, 4), np.float32), [(0, 3)])
    with pytest.raises(ValueError, match="level 0 covers"):
        lod_batch_from_offsets(np.zeros((5, 4), np.float32),
                               [(0, 1), (0, 2, 5)])
    with pytest.raises(ValueError, match="non-decreasing"):
        lod_batch_from_offsets(np.zeros((2, 4), np.float32), [(0, 2, 1, 2)])
    with pytest.raises(ValueError, match="start at 0"):
        lod_batch_from_offsets(np.zeros((2, 4), np.float32), [(1, 2)])


def test_three_level_masks_and_flat_view():
    lod = [(0, 2, 3), (0, 2, 5, 7), (0, 3, 5, 9, 11, 12, 15, 17)]
    flat = RS.randn(17, 4).astype(np.float32)
    b = lod_batch_from_offsets(flat, lod)
    m0 = np.asarray(b.mask(0))             # [B, S1]
    assert m0.tolist() == [[1, 1], [1, 0]]
    m2 = np.asarray(b.mask(2))             # [B, S1, S2, T]
    # total valid timesteps == rows of the flat tensor
    assert int(m2.sum()) == 17
    inner = b.innermost_flat()
    assert isinstance(inner, SeqBatch)
    assert inner.data.shape == (2 * 2 * 3, 4, 4)
    # all valid rows survive in the flat view
    assert int(np.asarray(inner.lengths).sum()) == 17


def test_three_level_sequence_op_composes_and_jits():
    """The reference's nested recurrent_group composition at depth 3:
    reduce innermost sequences (masked mean), lift, reduce again (masked
    sum), lift, then pool the outer sequence — all under one jit."""
    nested = [_rand_nested(3) for _ in range(4)]
    batch = pack_lod(nested, 3)

    @jax.jit
    def pipeline(b: LoDBatch):
        inner = b.innermost_flat()                  # [N2, T, F]
        m = inner.mask()                            # [N2, T]
        denom = jnp.maximum(m.sum(-1, keepdims=True), 1.0)
        mean2 = (inner.data * m[..., None]).sum(1) / denom   # [N2, F]
        lvl2 = b.lift(mean2)                        # 2-level batch [B,S1,S2,F]
        inner1 = lvl2.innermost_flat()              # [N1, S2, F]
        s = (inner1.data * inner1.mask()[..., None]).sum(1)  # [N1, F]
        lvl1 = lvl2.lift(s)                         # 1-level batch [B, S1, F]
        top = lvl1.as_seq_batch()
        return (top.data * top.mask()[..., None]).sum(1)     # [B, F]

    got = np.asarray(pipeline(batch))

    # plain-python reference over the ragged lists
    want = []
    for sample in nested:
        acc = np.zeros(2, np.float32)
        for sub in sample:
            for seq in sub:
                if len(seq):
                    acc += np.asarray(seq).mean(0)
        want.append(acc)
    np.testing.assert_allclose(got, np.stack(want), rtol=1e-5, atol=1e-5)


def test_lod_batch_is_a_pytree():
    nested = [_rand_nested(3) for _ in range(2)]
    b = pack_lod(nested, 3)
    leaves, treedef = jax.tree_util.tree_flatten(b)
    assert len(leaves) == 4  # data + 3 length arrays
    b2 = jax.tree_util.tree_unflatten(treedef, leaves)
    assert isinstance(b2, LoDBatch) and b2.nlevels == 3
    # grads flow through the data leaf (lengths stay int32 aux inputs)
    g = jax.grad(lambda d: jnp.sum(
        LoDBatch(d, b.level_lengths).innermost_flat().data ** 2))(b.data)
    np.testing.assert_allclose(np.asarray(g), 2 * np.asarray(b.data))


def test_as_nested_matches_two_level_packer():
    from paddle_tpu.core.lod import pack_nested_sequences
    nested = [_rand_nested(2) for _ in range(3)]
    a = pack_lod(nested, 2).as_nested()
    b = pack_nested_sequences(nested, bucket=False)
    assert a.data.shape == b.data.shape
    np.testing.assert_array_equal(np.asarray(a.data), np.asarray(b.data))
    np.testing.assert_array_equal(np.asarray(a.sub_lengths),
                                  np.asarray(b.sub_lengths))
    np.testing.assert_array_equal(np.asarray(a.seq_lengths),
                                  np.asarray(b.seq_lengths))
