"""Master service tests: in-process server + clients, elastic re-dispatch —
the reference's in-process multi-node strategy (SURVEY.md §4.3: pserver
objects on localhost ports inside the test process)."""

import threading
import time

import pytest

from paddle_tpu.runtime import native_available

pytestmark = pytest.mark.skipif(not native_available(),
                                reason="native toolchain unavailable")

from paddle_tpu.runtime.master_service import MasterClient, MasterServer  # noqa: E402


@pytest.fixture
def server(tmp_path):
    srv = MasterServer(timeout_s=1.0, failure_max=3,
                       snapshot_path=str(tmp_path / "m.snap"),
                       tick_interval=0.2).start()
    yield srv
    srv.stop()


def _client(server):
    return MasterClient(server.address[0], server.address[1])


def test_dispatch_over_network(server):
    c = _client(server)
    c.set_dataset([f"chunk{i}" for i in range(5)])
    got = []
    while True:
        t = c.get_task()
        if t is None:
            break
        got.append(t[1])
        c.task_finished(t[0])
    assert sorted(got) == [f"chunk{i}" for i in range(5)]
    assert c.new_pass()
    assert c.stats()[0] == 5  # todo refilled


def test_elastic_redispatch_on_consumer_death(server):
    """Consumer A leases a task and dies; the lease expires via the server's
    tick thread and consumer B completes the pass."""
    a, b = _client(server), _client(server)
    a.set_dataset(["t0", "t1"])
    dead_task = a.get_task()
    assert dead_task is not None
    a.close()                         # A dies holding its task

    done = []
    deadline = time.time() + 10.0
    while time.time() < deadline:
        t = b.get_task()
        if t is None:
            if b.stats()[2] == 2:     # done == 2
                break
            time.sleep(0.2)
            continue
        done.append(t[1])
        b.task_finished(t[0])
    assert dead_task[1] in done       # the orphaned task was re-dispatched


def test_concurrent_clients(server):
    c0 = _client(server)
    c0.set_dataset([f"c{i}" for i in range(40)])
    got, lock = [], threading.Lock()

    def worker():
        c = _client(server)
        while True:
            t = c.get_task()
            if t is None:
                todo, pending, done, disc, epoch = c.stats()
                if todo == 0 and pending == 0:
                    return
                time.sleep(0.05)
                continue
            with lock:
                got.append(t[1])
            c.task_finished(t[0])

    threads = [threading.Thread(target=worker) for _ in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=20)
    assert sorted(got) == sorted(f"c{i}" for i in range(40))


def test_oversized_response_degrades_to_structured_error(server, monkeypatch):
    """Responses are now checked against the frame limit (ADVICE r5): a
    payload whose JSON escaping expands past it must come back as a
    structured 'payload too large' error the client RAISES — not as a
    >limit frame the client's guard silently drops as a dead connection.
    $PTMS_MAX_RESPONSE_FRAME shrinks the bound so the test stays small."""
    monkeypatch.setenv("PTMS_MAX_RESPONSE_FRAME", "200000")
    c = _client(server)
    # newlines escape 1 -> 2 bytes: 150 KB raw renders as a ~300 KB
    # get_task response, over the armed 200 KB bound
    c.set_dataset(["\n" * 150000, "small"])
    with pytest.raises(RuntimeError, match="payload too large"):
        while True:
            t = c.get_task()      # big task may not be first in the queue
            assert t is not None and t[1] == "small"
            c.task_finished(t[0])
    # the connection survived: the small task still round-trips
    t = c.get_task()
    if t is not None:
        assert t[1] == "small"
    c.close()


def test_snapshot_written_and_recovered(server, tmp_path):
    c = _client(server)
    c.set_dataset(["a", "b", "c"])
    t = c.get_task()
    c.task_finished(t[0])
    time.sleep(0.5)                   # let the housekeeping thread snapshot

    srv2 = MasterServer(timeout_s=1.0, snapshot_path=str(tmp_path / "m.snap"),
                        tick_interval=0.2).start()
    try:
        c2 = _client(srv2)
        todo, pending, done, disc, epoch = c2.stats()
        assert done == 1 and todo == 2 and pending == 0
    finally:
        srv2.stop()


def test_multihost_helpers_single_process():
    import numpy as np

    from paddle_tpu import parallel as pp
    from paddle_tpu.parallel import multihost as mh
    info = mh.initialize()
    assert info["process_count"] == 1
    mesh = mh.global_mesh(data=8)
    sl = mh.process_batch_slice(64)
    assert sl == slice(0, 64)
    arr = mh.make_global_array(np.ones((16, 4), np.float32), mesh)
    assert arr.shape == (16, 4)


def test_master_failover_lease_election(tmp_path):
    """Standby master takes over through the file lease (etcd-election
    analog) and recovers task state from the CRC-checked snapshot; the
    client's endpoint rotation makes the failover transparent."""
    import socket as _socket

    from paddle_tpu.runtime import FileLease
    from paddle_tpu.runtime.master_service import MasterClient, MasterServer

    def free_port():
        s = _socket.socket()
        s.bind(("127.0.0.1", 0))
        p = s.getsockname()[1]
        s.close()
        return p

    pa, pb = free_port(), free_port()
    lease_path = str(tmp_path / "master.lease")
    snap = str(tmp_path / "master.snap")

    lease_a = FileLease(lease_path, owner="master-a", ttl=0.6)
    a = MasterServer(port=pa, snapshot_path=snap, tick_interval=0.05,
                     lease=lease_a).start()
    client = MasterClient(endpoints=[("127.0.0.1", pa), ("127.0.0.1", pb)])
    try:
        client.set_dataset(["chunk-0", "chunk-1", "chunk-2"])
        t0 = client.get_task()
        assert t0 is not None
        time.sleep(0.2)                      # let a snapshot land

        # master A crashes WITHOUT releasing its lease
        a.stop(release_lease=False)

        # standby B can only serve once A's lease expires
        lease_b = FileLease(lease_path, owner="master-b", ttl=0.6)
        assert not lease_b.try_acquire()     # still A's
        assert lease_b.wait_acquire(poll=0.1, timeout=10)
        b = MasterServer(port=pb, snapshot_path=snap, tick_interval=0.05,
                         lease=lease_b).start()
        try:
            # client reconnects by rotating endpoints; ALL chunks are still
            # dispatchable (A's pending task was snapshotted back to todo)
            seen = set()
            for _ in range(6):
                t = client.get_task()
                if t is None:
                    break
                seen.add(t[1])
                client.task_finished(t[0])
            assert seen == {"chunk-0", "chunk-1", "chunk-2"}
        finally:
            b.stop()
    finally:
        client.close()


def test_snapshot_crc_detects_corruption(tmp_path):
    """Flipping a byte in the snapshot body must make restore fail loudly
    (go/pserver/service.go:119-126 CRC discipline)."""
    from paddle_tpu.runtime import TaskMaster

    snap = str(tmp_path / "m.snap")
    m = TaskMaster()
    m.set_dataset(["alpha", "beta"])
    m.snapshot(snap)

    m2 = TaskMaster()
    m2.restore(snap)                         # clean restore works
    assert m2.stats()[0] == 2

    raw = bytearray(open(snap, "rb").read())
    raw[-3] ^= 0xFF                          # corrupt a payload byte
    open(snap, "wb").write(bytes(raw))
    with pytest.raises(IOError):
        TaskMaster().restore(snap)


def test_master_concurrent_consumers_hammer():
    """Thread-safety discipline (utils/Locks.h analog is a std::mutex in
    task_master.cc): many concurrent consumers over one server must neither
    lose nor double-complete tasks."""
    import threading

    from paddle_tpu.runtime.master_service import MasterClient, MasterServer

    N_TASKS, N_WORKERS = 200, 8
    srv = MasterServer(tick_interval=0.05).start()
    try:
        boot = MasterClient(*srv.address)
        boot.set_dataset([f"chunk-{i:04d}" for i in range(N_TASKS)])
        boot.close()

        seen, lock = [], threading.Lock()

        def worker():
            c = MasterClient(*srv.address)
            while True:
                t = c.get_task()
                if t is None:
                    break
                with lock:
                    seen.append(t[1])
                c.task_finished(t[0])
            c.close()

        threads = [threading.Thread(target=worker) for _ in range(N_WORKERS)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120)
        assert len(seen) == N_TASKS                      # no loss, no dupes
        assert len(set(seen)) == N_TASKS
        todo, pending, done, disc, _ = srv.master.stats()
        assert (todo, pending, done, disc) == (0, 0, N_TASKS, 0)
    finally:
        srv.stop()


def test_lease_fencing_token_monotonic(tmp_path):
    """Every acquisition gets a strictly larger fencing token, even across
    release/re-acquire cycles (etcd-revision monotonicity,
    go/master/etcd_client.go)."""
    from paddle_tpu.runtime import FileLease

    path = str(tmp_path / "l.lease")
    a = FileLease(path, owner="a", ttl=5.0)
    assert a.try_acquire()
    t1 = a.token
    assert t1 is not None and t1 >= 1
    a.release()
    assert a.token is None

    b = FileLease(path, owner="b", ttl=5.0)
    assert b.try_acquire()
    assert b.token > t1                       # survives the release gap
    assert b.current_token() == b.token

    # expiry takeover also bumps
    b2 = FileLease(path, owner="b2", ttl=5.0)
    assert not b2.try_acquire()               # live
    c = FileLease(path, owner="c", ttl=5.0)
    assert c.try_acquire(now=time.time() + 10.0)   # b has expired by then
    assert c.token > b.token


def test_deposed_master_writes_are_fenced(tmp_path):
    """A master that stalls past its TTL (paused keeper) and wakes after a
    standby took over must have BOTH its snapshot writes and its mutating
    RPCs refused — the fencing-token discipline the reference gets from
    etcd revisions (go/master/etcd_client.go)."""
    import socket as _socket

    from paddle_tpu.runtime import FileLease
    from paddle_tpu.runtime.master_service import MasterClient, MasterServer

    def free_port():
        s = _socket.socket()
        s.bind(("127.0.0.1", 0))
        p = s.getsockname()[1]
        s.close()
        return p

    pa, pb = free_port(), free_port()
    lease_path = str(tmp_path / "master.lease")
    snap = str(tmp_path / "master.snap")

    lease_a = FileLease(lease_path, owner="master-a", ttl=0.5)
    # long tick_interval: housekeeping never runs, so the only fence checks
    # are the explicit ones below (deterministic)
    a = MasterServer(port=pa, snapshot_path=snap, tick_interval=60.0,
                     lease=lease_a).start()
    ca = MasterClient("127.0.0.1", pa)
    try:
        ca.set_dataset(["chunk-0", "chunk-1"])
        assert a.try_snapshot()               # current master writes fine

        # simulate a GC-pause: renewal stops but the server keeps running
        a._keeper.stop(release=False)
        a._keeper = None
        deadline = time.time() + 10
        lease_b = FileLease(lease_path, owner="master-b", ttl=5.0)
        while not lease_b.try_acquire():
            assert time.time() < deadline
            time.sleep(0.1)

        b = MasterServer(port=pb, snapshot_path=snap, tick_interval=60.0,
                         lease=lease_b).start()
        try:
            assert b.fence_token > a.fence_token
            # the paused master wakes up: its snapshot write is refused and
            # the snapshot file still belongs to generation B
            assert b.try_snapshot()
            gen_b = open(snap, "rb").read()
            assert not a.try_snapshot()
            assert open(snap, "rb").read() == gen_b

            # ...and its mutating RPCs are refused too
            r = a._dispatch({"op": "set_dataset", "payloads": ["rogue"]})
            assert r["ok"] is False and "fenced" in r["error"]
            r = a._dispatch({"op": "task_finished", "task_id": 0})
            assert r["ok"] is False
            # read-only ops still answer (harmless)
            assert a._dispatch({"op": "stats"})["ok"] is True
        finally:
            b.stop()
    finally:
        ca.close()
        a.stop(release_lease=False)


# ---------------------------------------------------------------------------
# native server robustness (master_server.cc): hostile/degenerate wire input
# must never wedge the C++ accept/dispatch plane (ProtoServer.h:36 analog —
# a control-plane daemon shared by every trainer).
# ---------------------------------------------------------------------------

def _raw(addr, payload: bytes, half_close: bool = False):
    import socket
    import struct

    from paddle_tpu.runtime.master_service import _recv_exact

    s = socket.create_connection(addr, timeout=10.0)
    try:
        s.sendall(payload)
        if half_close:
            s.shutdown(socket.SHUT_WR)   # EOF: no more bytes are coming
        hdr = _recv_exact(s, 4)
        if hdr is None:
            return None
        (n,) = struct.unpack("<I", hdr)
        return _recv_exact(s, n)
    finally:
        s.close()


def test_native_server_survives_hostile_frames(server):
    """Garbage JSON, unknown ops, truncated frames, oversized length
    headers, and unicode-escape payloads: each is answered or the
    connection dropped — and the server keeps serving well-formed clients
    afterwards."""
    import json
    import struct

    addr = server.address

    def frame(obj) -> bytes:
        body = json.dumps(obj).encode()
        return struct.pack("<I", len(body)) + body

    # unknown op -> structured error
    r = json.loads(_raw(addr, frame({"op": "no_such_op"})))
    assert r["ok"] is False and "unknown op" in r["error"]

    # malformed JSON -> bad-request error, not a crash
    bad = b"this is not json"
    r = json.loads(_raw(addr, struct.pack("<I", len(bad)) + bad))
    assert r["ok"] is False

    # unicode escapes (incl. surrogate pair) round-trip through payloads
    snowman = "sn☃man \U0001F600 q\"uote\\slash"
    r = json.loads(_raw(addr, frame({"op": "set_dataset",
                                     "payloads": [snowman]})))
    assert r["ok"] is True
    r = json.loads(_raw(addr, frame({"op": "get_task"})))
    assert r["ok"] is True and r["task"]["payload"] == snowman

    # oversized length header -> connection dropped, no allocation bomb
    assert _raw(addr, struct.pack("<I", 1 << 30)) is None

    # truncated frame (header promises more bytes than ever arrive, then
    # EOF) -> dropped without a reply
    assert _raw(addr, struct.pack("<I", 100) + b"short",
                half_close=True) is None

    # the server still works for a well-formed client
    c = _client(server)
    c.set_dataset(["after-the-storm"])
    t = c.get_task()
    assert t is not None and t[1] == "after-the-storm"
    c.task_finished(t[0])
