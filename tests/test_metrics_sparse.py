"""Metrics ops + SelectedRows sparse path tests (analog of operators/
accuracy_op/auc_op/precision_recall tests and selected_rows functor tests)."""

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.ops import metrics, sparse


def test_accuracy():
    logits = jnp.asarray(np.array([[1, 2, 0], [5, 1, 1], [0, 1, 9]], np.float32))
    labels = jnp.asarray(np.array([1, 0, 1], np.int32))
    correct, total = metrics.accuracy(logits, labels)
    assert float(correct) == 2.0 and float(total) == 3.0
    c5, _ = metrics.top_k_accuracy(logits, labels, 2)
    assert float(c5) == 3.0


def test_auc_streaming_matches_sklearn_style(np_rng):
    probs = np_rng.rand(500).astype(np.float32)
    labels = (np_rng.rand(500) < probs).astype(np.float32)  # correlated -> auc > .5
    # accumulate in two batches like a streaming evaluator
    p1, n1 = metrics.auc_histogram(jnp.asarray(probs[:250]), jnp.asarray(labels[:250]))
    p2, n2 = metrics.auc_histogram(jnp.asarray(probs[250:]), jnp.asarray(labels[250:]))
    auc = float(metrics.auc_from_histogram(p1 + p2, n1 + n2))

    # exact pairwise AUC
    pos = probs[labels == 1]
    neg = probs[labels == 0]
    exact = np.mean((pos[:, None] > neg[None, :]) + 0.5 * (pos[:, None] == neg[None, :]))
    assert abs(auc - exact) < 0.02, (auc, exact)


def test_precision_recall_counts():
    pred = jnp.asarray(np.array([0, 0, 1, 1, 2], np.int32))
    lab = jnp.asarray(np.array([0, 1, 1, 1, 0], np.int32))
    c = np.asarray(metrics.precision_recall_counts(pred, lab, 3))
    # class 0: tp=1 fp=1 fn=1; class 1: tp=2 fp=0 fn=1; class 2: tp=0 fp=1 fn=0
    np.testing.assert_array_equal(c, [[1, 1, 1], [2, 0, 1], [0, 1, 0]])


def test_chunk_count_iob():
    # tags: type0 -> B=0, I=1. seq: [B I O(pad sentinel via len)] compare spans
    # pred:  B I B   label: B I B  -> 2 chunks each, 2 correct
    pred = jnp.asarray(np.array([[0, 1, 0]], np.int32))
    lab = jnp.asarray(np.array([[0, 1, 0]], np.int32))
    lengths = jnp.array([3])
    correct, n_pred, n_lab = metrics.chunk_count(pred, lab, lengths)
    assert (float(n_pred), float(n_lab)) == (2.0, 2.0)
    assert float(correct) == 2.0
    # boundary mismatch: pred merges into one chunk [B I I] vs label [B I B]
    pred2 = jnp.asarray(np.array([[0, 1, 1]], np.int32))
    correct2, n_pred2, n_lab2 = metrics.chunk_count(pred2, lab, lengths)
    assert float(n_pred2) == 1.0 and float(n_lab2) == 2.0
    assert float(correct2) == 0.0


def test_selected_rows_roundtrip_and_updates():
    table = jnp.zeros((10, 4))
    ids = jnp.asarray(np.array([[1, 3], [1, 5]], np.int32))
    g = jnp.ones((2, 2, 4))
    sr = sparse.embedding_grad_rows(ids, g, 10)
    dense = np.asarray(sr.to_dense())
    assert dense[1].sum() == 8.0  # id 1 hit twice
    assert dense[3].sum() == 4.0 and dense[5].sum() == 4.0
    assert dense[0].sum() == 0.0

    t2 = sparse.sgd_sparse_update(table, sr, 0.5)
    np.testing.assert_allclose(np.asarray(t2[1]), -1.0 * np.ones(4))

    moment = jnp.zeros((10, 4))
    t3, m3 = sparse.adagrad_sparse_update(table, moment, sr, 0.5)
    # duplicate rows merged BEFORE squaring: id 1 grad = 1+1 = 2 -> moment = 4
    np.testing.assert_allclose(np.asarray(m3)[1], 4.0)
    # and the table row updated exactly once with the merged grad
    np.testing.assert_allclose(np.asarray(t3)[1], -0.5 * 2.0 / (2.0 + 1e-6), rtol=1e-5)


def test_sparse_matches_dense_sgd():
    """Equivalence: sparse embedding update == dense autodiff update
    (analog of test_CompareSparse.cpp dense-vs-sparse training)."""
    rng = np.random.RandomState(0)
    table = jnp.asarray(rng.randn(8, 3).astype(np.float32))
    ids = jnp.asarray(np.array([1, 2, 2, 7], np.int32))
    target = jnp.asarray(rng.randn(4, 3).astype(np.float32))

    def loss(t):
        emb = sparse.lookup_table(t, ids)
        return 0.5 * jnp.sum(jnp.square(emb - target))

    dense_grad = jax.grad(loss)(table)
    dense_new = table - 0.1 * dense_grad

    emb = sparse.lookup_table(table, ids)
    out_grad = emb - target
    sr = sparse.embedding_grad_rows(ids, out_grad, 8)
    sparse_new = sparse.sgd_sparse_update(table, sr, 0.1)
    np.testing.assert_allclose(np.asarray(dense_new), np.asarray(sparse_new), rtol=1e-5)


def test_csr_csc_general_sparse_matmul():
    """General sparse beyond row-sparse: CSR/CSC/COO constructors and
    differentiable sparse-dense matmuls (math/CpuSparseMatrix.h,
    SparseMatrix.h) on the BCOO backend."""
    import jax

    from paddle_tpu.ops import sparse as sp

    rs = np.random.RandomState(0)
    dense_m = rs.randn(4, 6).astype(np.float32)
    dense_m[rs.rand(4, 6) < 0.6] = 0.0

    # CSR arrays from scipy-free construction
    rows, cols = np.nonzero(dense_m)
    vals = dense_m[rows, cols]
    row_ptr = np.zeros(5, np.int64)
    for r in rows:
        row_ptr[r + 1] += 1
    row_ptr = np.cumsum(row_ptr)

    m_csr = sp.csr_matrix(vals, cols, row_ptr, (4, 6))
    np.testing.assert_allclose(np.asarray(sp.sparse_to_dense(m_csr)), dense_m)

    # CSC of the same matrix
    order = np.lexsort((rows, cols))
    col_ptr = np.zeros(7, np.int64)
    for c in cols:
        col_ptr[c + 1] += 1
    col_ptr = np.cumsum(col_ptr)
    m_csc = sp.csc_matrix(vals[order], rows[order], col_ptr, (4, 6))
    np.testing.assert_allclose(np.asarray(sp.sparse_to_dense(m_csc)), dense_m)

    x = rs.randn(6, 3).astype(np.float32)
    got = sp.sparse_dense_matmul(m_csr, jnp.asarray(x))
    np.testing.assert_allclose(np.asarray(got), dense_m @ x, rtol=1e-5,
                               atol=1e-5)

    y = rs.randn(2, 4).astype(np.float32)
    got2 = sp.dense_sparse_matmul(jnp.asarray(y), m_csr)
    np.testing.assert_allclose(np.asarray(got2), y @ dense_m, rtol=1e-5,
                               atol=1e-5)

    # differentiable w.r.t. the dense operand (sparse-input fc training path)
    g = jax.grad(lambda w: (sp.sparse_dense_matmul(m_csr, w) ** 2).sum())(
        jnp.asarray(x))
    np.testing.assert_allclose(np.asarray(g),
                               2 * dense_m.T @ (dense_m @ x), rtol=1e-4,
                               atol=1e-4)

    # non-value (binary) format: all-ones values
    m_bin = sp.csr_matrix(np.ones_like(vals), cols, row_ptr, (4, 6))
    np.testing.assert_allclose(np.asarray(sp.sparse_to_dense(m_bin)),
                               (dense_m != 0).astype(np.float32))
