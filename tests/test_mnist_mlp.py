"""End-to-end acceptance test: MNIST-style MLP trains below a loss threshold.

Analog of fluid/tests/book/test_recognize_digits_mlp.py:67-68, which trains until
avg_cost < threshold then exits — the reference's v0 acceptance gate (SURVEY.md §7
build order step 4). Uses synthetic digits (no network in CI) with a learnable
structure so loss genuinely falls.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu.models import MnistMLP
from paddle_tpu.optimizer import Adam


def synth_digits(rng, n, in_dim=64, classes=10):
    """Linearly-separable-ish synthetic 'digits': class prototypes + noise."""
    protos = rng.randn(classes, in_dim).astype(np.float32)
    labels = rng.randint(0, classes, size=n).astype(np.int32)
    x = protos[labels] + 0.5 * rng.randn(n, in_dim).astype(np.float32)
    return x, labels


def test_mlp_trains_to_threshold(np_rng):
    x, y = synth_digits(np_rng, 512)
    model = MnistMLP(in_dim=64, hidden=64, classes=10)
    params = model.init(jax.random.PRNGKey(0))
    opt = Adam(learning_rate=1e-3)
    state = opt.init(params)

    @jax.jit
    def step(params, state, xb, yb):
        loss, grads = jax.value_and_grad(model.loss)(params, xb, yb)
        params, state = opt.update(grads, state, params)
        return params, state, loss

    bs = 64
    loss = None
    for epoch in range(30):
        for i in range(0, len(x), bs):
            xb, yb = jnp.asarray(x[i:i + bs]), jnp.asarray(y[i:i + bs])
            params, state, loss = step(params, state, xb, yb)
        if float(loss) < 0.05:
            break
    assert float(loss) < 0.5, f"training failed to converge, loss={float(loss)}"
    acc = model.accuracy(params, jnp.asarray(x), jnp.asarray(y))
    assert float(acc) > 0.9


def test_param_shapes():
    model = MnistMLP(in_dim=784, hidden=128, classes=10)
    params = model.init(jax.random.PRNGKey(1))
    assert params["fc1"]["w"].shape == (784, 128)
    assert params["out"]["b"].shape == (10,)
