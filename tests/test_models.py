"""End-to-end model tests — the 'book' acceptance suite.

Each test trains a tiny config on its synthetic dataset until the loss clearly
drops (the reference trains to a loss threshold then exits —
fluid/tests/book/test_recognize_digits_mlp.py:67-68; SURVEY.md §4.4)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu.data import (DataFeeder, DenseSlot, IndexSlot, SeqSlot,
                             SparseSlot, batch)
from paddle_tpu.data.dataset import (conll05, criteo, imdb, imikolov, mnist,
                                     movielens, wmt14)
from paddle_tpu.models import (AttentionSeq2Seq, BiLSTMCRFTagger, ConvTextCls,
                               DeepFM, LeNet, LSTMTextCls, Recommender, ResNet,
                               VGG, Word2Vec)
from paddle_tpu.optimizer import Adam


def _train(loss_fn, params, batches, lr=1e-2, passes=1):
    opt = Adam(lr)
    state = opt.init(params)

    @jax.jit
    def step(params, state, *b):
        l, g = jax.value_and_grad(loss_fn)(params, *b)
        params, state = opt.update(g, state, params)
        return params, state, l

    costs = []
    for _ in range(passes):
        for b in batches:
            params, state, l = step(params, state, *b)
            costs.append(float(l))
    return params, costs


def test_lstm_text_cls_learns():
    model = LSTMTextCls(imdb.VOCAB, embed_dim=32, hidden=32)
    feeder = DataFeeder([SeqSlot(), IndexSlot()])
    batches = [feeder.feed(rows) for rows in batch(imdb.train(256), 32)()]
    params = model.init(jax.random.PRNGKey(0))
    params, costs = _train(model.loss, params, batches, passes=3)
    assert costs[-1] < costs[0] * 0.7


def test_conv_text_cls_learns():
    model = ConvTextCls(imdb.VOCAB, embed_dim=32, num_filters=32)
    feeder = DataFeeder([SeqSlot(), IndexSlot()])
    batches = [feeder.feed(rows) for rows in batch(imdb.train(256), 32)()]
    params = model.init(jax.random.PRNGKey(0))
    params, costs = _train(model.loss, params, batches, passes=3)
    assert costs[-1] < costs[0] * 0.7


def test_lenet_learns():
    model = LeNet()
    feeder = DataFeeder([DenseSlot(784), IndexSlot()])

    def conv_feed(rows):
        x, y = feeder.feed(rows)
        return x.reshape(-1, 28, 28, 1), y

    batches = [conv_feed(rows) for rows in batch(mnist.train(256), 32)()]
    params = model.init(jax.random.PRNGKey(0))
    # lr matters here: at the _train default (Adam 1e-2) this conv stack
    # diverges on step 2 (loss 3.5 -> 53) and settles into the uniform-
    # prediction minimum (ln 10 ~ 2.30, ratio 0.66 > 0.6) — a
    # deterministic FAIL on this backend, the last standing tier-1 red.
    # 1e-3 trains stably to ratio ~0.50, so the 0.6 bar now has real
    # margin and a red run means a genuine regression.
    params, costs = _train(model.loss, params, batches, lr=1e-3, passes=2)
    assert costs[-1] < costs[0] * 0.6


@pytest.mark.parametrize("cls,kw", [
    # slow: VGG adds conv-stack DEPTH, not new ops — the conv stem,
    # BN-stat forward and grad path it runs are tier-1-covered by the
    # ResNet-18 case below plus the AlexNet/GoogLeNet sweep (~19s back
    # in the PR 12 --durations=25 triage; ResNet-50 precedent, PR 7)
    pytest.param(VGG, dict(classes=10, width_mult=0.125),
                 marks=pytest.mark.slow),
    (ResNet, dict(depth=18, classes=10, width_mult=0.25, small_input=True)),
    # slow: the depth-50 bottleneck variant is the single costliest tier-1
    # case (~30s compile+grad); depth-18 keeps the ResNet path (incl.
    # projection shortcuts) in tier-1 and benchmarks/resnet50.py exercises
    # depth-50 on-chip (ROADMAP item 5)
    pytest.param(ResNet, dict(depth=50, classes=10, width_mult=0.125,
                              small_input=True), marks=pytest.mark.slow),
])
def test_image_models_forward_and_grad(cls, kw):
    model = cls(**kw)
    params = model.init(jax.random.PRNGKey(0))
    x = jax.random.normal(jax.random.PRNGKey(1), (4, 32, 32, 3))
    y = jnp.array([0, 1, 2, 3])
    logits = model(params, x)
    assert logits.shape == (4, 10)

    def loss_with_stats(p):
        mut = {}
        l = model.loss(p, x, y, train=True, mutable=mut)
        return l

    g = jax.jit(jax.grad(loss_with_stats))(params)
    assert np.isfinite(float(jax.tree_util.tree_reduce(
        lambda a, b: a + jnp.sum(jnp.abs(b)), g, 0.0)))


def test_conv2d_stem_auto_route_matches_direct():
    """nn.Conv2D routes the 7x7/s2/p3 stem shape through the exact
    space-to-depth rewrite (ops/conv.py::conv7s2): the layer output —
    including bias and act — equals the direct conv math on the SAME
    params, on both input parities (odd sizes take the direct path), and
    the ResNet-18 stem that relies on it is differentiable end-to-end
    (docs/design/conv_mfu.md)."""
    from paddle_tpu import nn
    from paddle_tpu.ops import conv as conv_ops

    layer = nn.Conv2D(3, 16, 7, stride=2, padding=3, act="relu")
    params = layer.init(jax.random.PRNGKey(0))
    for seed, hw in ((1, 64), (2, 63)):
        x = jax.random.normal(jax.random.PRNGKey(seed), (2, hw, hw, 3))
        want = jax.nn.relu(
            conv_ops.conv2d(x, params["w"], stride=2, padding=3)
            + params["b"])
        np.testing.assert_allclose(np.asarray(layer(params, x)),
                                   np.asarray(want), rtol=2e-4, atol=2e-4)

    m = ResNet(depth=18, classes=5, width_mult=0.25, small_input=False)
    rp = m.init(jax.random.PRNGKey(0))
    x = jax.random.normal(jax.random.PRNGKey(1), (2, 64, 64, 3))
    g = jax.grad(lambda p: m(p, x).sum())(rp)
    assert np.isfinite(float(jnp.sum(jnp.abs(g["stem"]["conv"]["w"]))))


def test_inception_branch_fusion_matches_unfused():
    """The fused 1x1-branch conv (one weight-concat conv instead of three)
    and the s2d GoogleNet stem are exact rewrites: forward equals the
    per-branch computation on the same params."""
    from paddle_tpu.models.image import _Inception

    blk = _Inception(32, 8, 12, 16, 4, 8, 8)
    params = blk.init(jax.random.PRNGKey(0))
    x = jax.random.normal(jax.random.PRNGKey(1), (2, 14, 14, 32))
    got = blk(params, x)

    from paddle_tpu.ops import pool as P
    a = blk.b1(params["b1"], x)
    b = blk.b3(params["b3"], blk.b3r(params["b3r"], x))
    c = blk.b5(params["b5"], blk.b5r(params["b5r"], x))
    d = blk.bp(params["bp"], P.max_pool2d(x, 3, 1, padding=1))
    want = jnp.concatenate([a, b, c, d], axis=-1)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)


# slow: stem-equivalence variant (37s); test_conv2d_stem_auto_route_matches_direct
# keeps the auto-route stem covered in tier-1
@pytest.mark.slow
def test_googlenet_s2d_stem_matches_direct():
    """GoogleNet's s2d stem path equals the direct 7x7 conv (odd input
    sizes take the direct path)."""
    from paddle_tpu.models import GoogleNet

    m = GoogleNet(classes=7)
    params = m.init(jax.random.PRNGKey(0))
    x_even = jax.random.normal(jax.random.PRNGKey(1), (1, 64, 64, 3))
    x_odd = jax.random.normal(jax.random.PRNGKey(2), (1, 63, 63, 3))
    from paddle_tpu.ops import conv as conv_ops
    s2d = conv_ops.conv7s2_space_to_depth(x_even, params["stem1"]["w"])
    direct = conv_ops.conv2d(x_even, params["stem1"]["w"], stride=2,
                             padding=3)
    np.testing.assert_allclose(np.asarray(s2d), np.asarray(direct),
                               rtol=1e-4, atol=1e-4)
    # both input parities run end-to-end
    assert m(params, x_even).shape == (1, 7)
    assert m(params, x_odd).shape == (1, 7)


def test_seq2seq_learns_and_decodes():
    model = AttentionSeq2Seq(wmt14.SRC_VOCAB, wmt14.TRG_VOCAB, embed_dim=32,
                             hidden=32)
    feeder = DataFeeder([SeqSlot(), SeqSlot(), SeqSlot()])
    batches = [feeder.feed(rows) for rows in batch(wmt14.train(320), 32)()]
    params = model.init(jax.random.PRNGKey(0))
    params, costs = _train(model.loss, params, batches, lr=1e-2, passes=5)
    assert costs[-1] < costs[0] * 0.95  # NLL moves slowly on the toy task; decode below is the substance
    src, _, _ = batches[0]
    toks, scores = model.generate(params, src, beam_size=3, max_len=8,
                                  bos_id=wmt14.START, eos_id=wmt14.END)
    assert toks.shape == (32, 3, 8)
    # beam scores sorted best-first
    assert np.all(np.diff(np.asarray(scores), axis=1) <= 1e-5)
    gt, _ = model.greedy_generate(params, src, max_len=8, bos_id=wmt14.START,
                                  eos_id=wmt14.END)
    assert gt.shape == (32, 8)


def test_bilstm_crf_learns_and_decodes():
    model = BiLSTMCRFTagger(conll05.VOCAB, conll05.TAGS, embed_dim=32, hidden=32)
    feeder = DataFeeder([SeqSlot(), SeqSlot()])
    batches = [feeder.feed(rows) for rows in batch(conll05.train(128), 16)()]
    params = model.init(jax.random.PRNGKey(0))
    params, costs = _train(model.loss, params, batches, passes=2)
    assert costs[-1] < costs[0] * 0.9
    words, tags = batches[0]
    pred, score = model.decode(params, words)
    assert pred.shape == words.data.shape
    assert score.shape == (words.batch_size,)


def test_word2vec_learns():
    model = Word2Vec(imikolov.VOCAB, embed_dim=16, context=4, hidden=32)
    rows = list(batch(imikolov.train(512), 64)())

    def feed(b):
        arr = np.asarray(b, np.int32)
        return jnp.asarray(arr[:, :4]), jnp.asarray(arr[:, 4])

    batches = [feed(b) for b in rows]
    params = model.init(jax.random.PRNGKey(0))
    params, costs = _train(model.loss, params, batches, passes=6)
    assert costs[-1] < costs[0] * 0.9


def test_recommender_learns():
    model = Recommender(movielens.USERS, movielens.MOVIES, movielens.CATEGORIES,
                        movielens.JOBS, movielens.AGES, dim=16)
    feeder = DataFeeder([IndexSlot(), IndexSlot(), IndexSlot(), IndexSlot(),
                         IndexSlot(), SparseSlot(movielens.CATEGORIES),
                         DenseSlot(1)])
    def feed(rows):
        u, g, a, j, m, (cids, cvals), r = feeder.feed(rows)
        return u, g, a, j, m, cids, cvals, r[:, 0]
    batches = [feed(rows) for rows in batch(movielens.train(512), 64)()]
    params = model.init(jax.random.PRNGKey(0))
    params, costs = _train(model.loss, params, batches, passes=3)
    assert costs[-1] < costs[0] * 0.8


def test_deepfm_learns():
    model = DeepFM(criteo.HASH, criteo.FIELDS, criteo.DENSE, factor=4)

    def feed(rows):
        dense = jnp.asarray(np.stack([r[0] for r in rows]))
        ids = jnp.asarray(np.stack([r[1] for r in rows]).astype(np.int32))
        y = jnp.asarray(np.array([r[2] for r in rows], np.int32))
        return dense, ids, y

    batches = [feed(rows) for rows in batch(criteo.train(512), 64)()]
    params = model.init(jax.random.PRNGKey(0))
    params, costs = _train(model.loss, params, batches, passes=3)
    assert costs[-1] < costs[0] * 0.9


@pytest.mark.parametrize("cls", [
    "alexnet",
    # slow: the googlenet variant compiles 35s of inception stacks; alexnet
    # keeps the big-image-model forward+grad path covered in tier-1
    pytest.param("googlenet", marks=pytest.mark.slow),
])
def test_alexnet_googlenet_forward_and_grad(cls):
    """AlexNet / GoogleNet (benchmark/paddle/image/{alexnet,googlenet}.py):
    ImageNet-shaped forward, and a finite training gradient with dropout /
    LRN / aux towers live (GoogleNet combines its two 0.3-weighted aux
    losses in train mode)."""
    from paddle_tpu.models import AlexNet, GoogleNet
    model = AlexNet(classes=7) if cls == "alexnet" else GoogleNet(classes=7)
    params = model.init(jax.random.PRNGKey(0))
    x = jax.random.normal(jax.random.PRNGKey(1), (2, 224, 224, 3)) * 0.1
    y = jnp.array([1, 5])

    logits = model(params, x)                 # eval mode: single head
    assert logits.shape == (2, 7)

    rng = jax.random.PRNGKey(2)
    l0 = float(model.loss(params, x, y, train=True, rng=rng))
    assert np.isfinite(l0)
    if cls == "googlenet":                    # aux losses included
        l_eval = float(model.loss(params, x, y))
        assert l0 > l_eval * 1.2

    g = jax.jit(lambda p: jax.grad(
        lambda p: model.loss(p, x, y, train=True, rng=rng))(p))(params)
    total = float(jax.tree_util.tree_reduce(
        lambda a, b: a + jnp.sum(jnp.abs(b)), g, 0.0))
    assert np.isfinite(total) and total > 0
