"""Expert parallelism (parallel/moe.py): top-k token-choice MoE with experts
sharded over the ``expert`` mesh axis — the modern extension of the
reference's sparse/embedding sharding (SURVEY §2.5). Dense-equivalence
discipline as everywhere else (test_CompareSparse.cpp shape)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu import parallel as pp
from paddle_tpu.parallel.moe import (ExpertParallelMoE, init_moe_params,
                                     moe_ffn_dense)

D, F, E = 8, 16, 8
N_DEV = 8


@pytest.fixture
def mesh():
    if len(jax.devices()) < N_DEV:
        pytest.skip("needs 8 virtual devices")
    return pp.make_mesh(expert=N_DEV)


def _setup(k=1, T=64, seed=0):
    params = init_moe_params(jax.random.PRNGKey(seed), D, F, E)
    x = jax.random.normal(jax.random.PRNGKey(seed + 1), (T, D))
    return params, x


@pytest.mark.parametrize("k", [1, 2])
def test_sharded_matches_dense_no_drops(mesh, k):
    """With capacity >= local tokens nothing drops, so the expert-sharded
    all_to_all pipeline must reproduce the dense math exactly."""
    params, x = _setup(k=k)
    T_local = x.shape[0] // N_DEV
    moe = ExpertParallelMoE(mesh, k=k, capacity=T_local)
    ys, _ = moe(moe.shard_params(params), moe.shard_tokens(x))

    # dense reference with the SAME per-shard routing semantics: route each
    # shard's token block independently (capacity is per shard+expert)
    outs = []
    for s in range(N_DEV):
        blk = x[s * T_local:(s + 1) * T_local]
        yd, _ = moe_ffn_dense(params, blk, k=k, capacity=T_local)
        outs.append(yd)
    want = jnp.concatenate(outs, axis=0)
    np.testing.assert_allclose(np.asarray(ys), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_dense_topk_covers_all_tokens():
    """k=2 with full capacity: every token reaches two distinct experts and
    the combine weights are the true gate probs (sum < 1)."""
    params, x = _setup(T=32)
    y1, _ = moe_ffn_dense(params, x, k=1)
    y2, _ = moe_ffn_dense(params, x, k=2)
    # the 2nd expert's contribution must change the output for ~all tokens
    diff = np.abs(np.asarray(y1) - np.asarray(y2)).max(axis=-1)
    assert (diff > 1e-7).mean() > 0.9


def test_capacity_drops_tokens(mesh):
    """GShard contract: over-capacity tokens drop (contribute zero), the
    rest still compute; static shapes throughout."""
    params, x = _setup(T=64)
    moe = ExpertParallelMoE(mesh, k=1, capacity=1)   # 1 slot/expert/shard
    ys, _ = moe(moe.shard_params(params), moe.shard_tokens(x))
    ys = np.asarray(ys)
    dropped = (np.abs(ys).max(axis=-1) < 1e-9).sum()
    assert 0 < dropped < x.shape[0]   # some dropped, not all


def test_aux_loss_balanced_vs_skewed():
    """The load-balance aux loss must be ~1 for uniform routing and larger
    for skewed routing."""
    params, x = _setup(T=256)
    # skew the gate so everything prefers expert 0
    skew = dict(params)
    skew["gate_w"] = jnp.zeros((D, E)).at[:, 0].set(5.0)
    _, aux_skew = moe_ffn_dense(skew, x, k=1)
    _, aux_rand = moe_ffn_dense(params, x, k=1)
    assert float(aux_skew) > 2.0          # one expert takes everything -> ~E
    assert 0.5 < float(aux_rand) < 3.0


def test_gradients_flow_through_sharded_path(mesh):
    """d(loss)/d(params) through the a2a dispatch pipeline matches the
    dense reference (no-drop capacity)."""
    params, x = _setup(T=64)
    T_local = x.shape[0] // N_DEV
    moe = ExpertParallelMoE(mesh, k=1, capacity=T_local)
    sp = moe.shard_params(params)
    xs = moe.shard_tokens(x)

    def loss_sharded(p):
        y, aux = moe(p, xs)
        return jnp.mean(y * y) + 0.01 * aux

    def loss_dense(p):
        outs, auxes = [], []
        for s in range(N_DEV):
            y, a = moe_ffn_dense(p, x[s * T_local:(s + 1) * T_local],
                                 k=1, capacity=T_local)
            outs.append(y)
            auxes.append(a)
        y = jnp.concatenate(outs, 0)
        return jnp.mean(y * y) + 0.01 * jnp.mean(jnp.stack(auxes))

    gs = jax.grad(loss_sharded)(sp)
    gd = jax.grad(loss_dense)(params)
    for name in ("gate_w", "w1", "w2"):
        np.testing.assert_allclose(np.asarray(jax.device_get(gs[name])),
                                   np.asarray(gd[name]),
                                   rtol=3e-4, atol=3e-5, err_msg=name)


def test_train_step_reduces_loss(mesh):
    """One jitted train step over the expert mesh: fit random targets; loss
    falls — the ep axis is trainable end to end."""
    from paddle_tpu.optimizer import Adam

    params, x = _setup(T=64)
    y_target = jax.random.normal(jax.random.PRNGKey(9), (64, D))
    T_local = 64 // N_DEV
    moe = ExpertParallelMoE(mesh, k=2, capacity=T_local)
    sp = moe.shard_params(params)
    xs = moe.shard_tokens(x)
    yt = moe.shard_tokens(y_target)
    opt = Adam(3e-3)
    state = jax.device_put(opt.init(sp))

    def loss_fn(p):
        y, aux = moe(p, xs)
        return jnp.mean((y - yt) ** 2) + 0.01 * aux

    losses = []
    for _ in range(30):
        l, g = jax.value_and_grad(loss_fn)(sp)
        sp, state = opt.update(g, state, sp)
        losses.append(float(l))
    assert losses[-1] < losses[0] * 0.9, (losses[0], losses[-1])


def test_k_exceeding_experts_rejected():
    params, x = _setup(T=8)
    with pytest.raises(ValueError, match="k <= n_experts"):
        moe_ffn_dense(params, x, k=E + 1)
