"""Multi-process data parallelism: REAL cross-process collectives.

Spawns two OS processes (2 virtual CPU devices each) that join one
jax.distributed job and train the same toy net over a 4-device global mesh,
then checks the result equals single-process training on the full batch —
the shape of the reference's in-process distributed tests
(gserver/tests/test_CompareSparse.cpp:55-110: same config under {local,
multi-trainer, remote pserver}, final parameter buffers compared).
"""

import os
import subprocess
import socket
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WORKER = os.path.join(REPO, "tests", "mp_dp_worker.py")


def _free_port():
    s = socket.socket()
    s.bind(("localhost", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _single_process_reference():
    from paddle_tpu import nn
    from paddle_tpu.optimizer import SGD

    class Net(nn.Module):
        def __init__(self):
            super().__init__()
            self.fc1 = nn.Linear(8, 16, act="relu")
            self.fc2 = nn.Linear(16, 2)

        def __call__(self, params, x, **kw):
            return self.fc2(params["fc2"], self.fc1(params["fc1"], x))

    model = Net()

    def loss(params, x, y):
        logits = model(params, x)
        logp = jax.nn.log_softmax(logits)
        return -jnp.take_along_axis(logp, y[:, None], 1).mean()

    rs = np.random.RandomState(0)
    GB = 32
    X = jnp.asarray(rs.randn(GB, 8), jnp.float32)
    Y = jnp.asarray(rs.randint(0, 2, GB), jnp.int32)
    params = model.init(jax.random.PRNGKey(7))
    opt = SGD(0.1)
    state = opt.init(params)
    for _ in range(5):
        _, grads = jax.value_and_grad(loss)(params, X, Y)
        params, state = opt.update(grads, state, params)
    return dict(nn.Module.named_parameters(jax.device_get(params)))


def test_two_process_dp_matches_single(tmp_path):
    from conftest import require_multiprocess_cpu
    require_multiprocess_cpu()
    port = _free_port()
    out = str(tmp_path / "mp_params.npz")
    env = {k: v for k, v in os.environ.items() if k != "XLA_FLAGS"}
    env["JAX_PLATFORMS"] = "cpu"
    procs = [subprocess.Popen(
        [sys.executable, WORKER, str(i), "2", str(port), out],
        env=env, cwd=REPO, stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
        for i in range(2)]
    logs = []
    try:
        for p in procs:
            stdout, _ = p.communicate(timeout=240)
            logs.append(stdout.decode(errors="replace"))
    finally:
        for p in procs:       # a hung peer must not outlive the test
            if p.poll() is None:
                p.kill()
    assert all(p.returncode == 0 for p in procs), "\n---\n".join(logs)

    got = np.load(out)
    want = _single_process_reference()
    assert set(got.files) == set(want)
    for k in want:
        np.testing.assert_allclose(got[k], np.asarray(want[k]),
                                   rtol=2e-5, atol=2e-5, err_msg=k)
