"""Nested-sequence (2-level LoD) tests.

The analog of the reference's nested-sequence machinery and its equivalence
tests (parameter/Argument.h:84-90 subSequenceStartPositions,
gserver/tests/sequence_nest_rnn*.py: nested recurrent groups must match the
flattened computation when the data is equivalent).
"""

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.core import NestedSeqBatch, pack_nested_sequences
from paddle_tpu.ops import rnn as R
from paddle_tpu.ops import sequence as S


def _toy_nested():
    r = np.random.RandomState(0)
    nested = [
        [r.randn(3, 4).astype(np.float32), r.randn(2, 4).astype(np.float32)],
        [r.randn(1, 4).astype(np.float32)],
    ]
    return nested, pack_nested_sequences(nested, bucket=False)


def test_pack_nested_roundtrip():
    nested, nb = _toy_nested()
    assert nb.data.shape == (2, 2, 3, 4)
    np.testing.assert_array_equal(np.asarray(nb.seq_lengths), [2, 1])
    np.testing.assert_array_equal(np.asarray(nb.sub_lengths), [[3, 2], [1, 0]])
    np.testing.assert_allclose(np.asarray(nb.data[0, 1, :2]), nested[0][1])
    # masks agree with lengths
    assert float(nb.inner_mask().sum()) == 3 + 2 + 1
    assert float(nb.outer_mask().sum()) == 2 + 1


def test_nested_pool_matches_manual():
    nested, nb = _toy_nested()
    pooled = S.nested_seq_pool(nb, "average")
    # valid entries equal per-subsequence means
    np.testing.assert_allclose(np.asarray(pooled.data[0, 0]),
                               nested[0][0].mean(0), rtol=1e-6)
    np.testing.assert_allclose(np.asarray(pooled.data[0, 1]),
                               nested[0][1].mean(0), rtol=1e-6)
    np.testing.assert_allclose(np.asarray(pooled.data[1, 0]),
                               nested[1][0].mean(0), rtol=1e-6)
    np.testing.assert_array_equal(np.asarray(pooled.lengths), [2, 1])
    last = S.nested_last_step(nb)
    np.testing.assert_allclose(np.asarray(last.data[0, 0]), nested[0][0][-1],
                               rtol=1e-6)


def test_sub_seq_expand_broadcasts_and_masks():
    _, nb = _toy_nested()
    vals = jnp.arange(2 * 2 * 5, dtype=jnp.float32).reshape(2, 2, 5)
    ex = S.sub_seq_expand(vals, nb)
    assert ex.shape == (2, 2, 3, 5)
    np.testing.assert_allclose(np.asarray(ex[0, 0, 2]), np.asarray(vals[0, 0]))
    # masked: subseq (1,1) is padding -> zeros everywhere
    np.testing.assert_allclose(np.asarray(ex[1, 1]), 0.0)


def test_nested_rnn_matches_per_subsequence_rnn():
    """sequence_nest_rnn equivalence: the inner RNN restarts per sub-sequence,
    so running it nested must equal running it on each sub-sequence alone."""
    nested, nb = _toy_nested()
    r = np.random.RandomState(1)
    D, H = 4, 6
    w = jnp.asarray(r.randn(D, 4 * H).astype(np.float32) * 0.3)
    u = jnp.asarray(r.randn(H, 4 * H).astype(np.float32) * 0.3)
    b = jnp.zeros((4 * H,), jnp.float32)

    out_n, last_n = S.nested_rnn(R.lstm, nb, w, u, b)
    assert out_n.shape == (2, 2, 3, H)
    for bi, sample in enumerate(nested):
        for si, sub in enumerate(sample):
            ref_out, ref_state = R.lstm(
                jnp.asarray(sub)[None], jnp.asarray([sub.shape[0]], jnp.int32),
                w, u, b)
            np.testing.assert_allclose(
                np.asarray(out_n[bi, si, :sub.shape[0]]),
                np.asarray(ref_out[0]), rtol=2e-5, atol=2e-6)
            np.testing.assert_allclose(np.asarray(last_n.data[bi, si]),
                                       np.asarray(ref_state.h[0]),
                                       rtol=2e-5, atol=2e-6)


def test_nested_vs_flattened_single_subsequence():
    """With exactly one sub-sequence per example, the nested path must equal
    the flat single-level path (the degenerate-equivalence the reference's
    nested/flat config pairs rely on)."""
    r = np.random.RandomState(2)
    seqs = [r.randn(5, 3).astype(np.float32), r.randn(2, 3).astype(np.float32)]
    nb = pack_nested_sequences([[s] for s in seqs], bucket=False)
    from paddle_tpu.core import pack_sequences
    sb = pack_sequences(seqs, bucket=False)

    pooled_nested = S.nested_seq_pool(nb, "sum")
    pooled_flat = S.sequence_pool(sb.data, sb.lengths, "sum")
    np.testing.assert_allclose(np.asarray(pooled_nested.data[:, 0]),
                               np.asarray(pooled_flat), rtol=1e-6)


def test_hierarchical_model_trains():
    """Inner LSTM over words per sentence -> outer LSTM over sentence
    vectors -> classifier: the nested recurrent_group composition, end to end
    with gradients."""
    r = np.random.RandomState(3)
    B, S_, T, D, H = 4, 3, 5, 4, 8
    data = r.randn(B, S_, T, D).astype(np.float32)
    sub_lengths = r.randint(1, T + 1, (B, S_)).astype(np.int32)
    seq_lengths = r.randint(1, S_ + 1, (B,)).astype(np.int32)
    for bi in range(B):   # zero-out padding subseqs for realism
        sub_lengths[bi, seq_lengths[bi]:] = 0
    nb = NestedSeqBatch(jnp.asarray(data), jnp.asarray(sub_lengths),
                        jnp.asarray(seq_lengths))
    labels = jnp.asarray((data.sum((1, 2, 3)) > 0).astype(np.int32))

    def init(key):
        ks = jax.random.split(key, 6)
        s = 0.3
        return {
            "wi": jax.random.normal(ks[0], (D, 4 * H)) * s,
            "ui": jax.random.normal(ks[1], (H, 4 * H)) * s,
            "wo": jax.random.normal(ks[2], (H, 4 * H)) * s,
            "uo": jax.random.normal(ks[3], (H, 4 * H)) * s,
            "cw": jax.random.normal(ks[4], (H, 2)) * s,
            "cb": jnp.zeros((2,)),
        }

    def loss_fn(p, nb, labels):
        _, sent = S.nested_rnn(R.lstm, nb, p["wi"], p["ui"], None)
        out, state = R.lstm(sent.data, sent.lengths, p["wo"], p["uo"], None)
        logits = state.h @ p["cw"] + p["cb"]
        logp = jax.nn.log_softmax(logits)
        return -jnp.mean(jnp.take_along_axis(logp, labels[:, None], 1))

    p = init(jax.random.PRNGKey(0))
    g = jax.jit(jax.value_and_grad(loss_fn))
    losses = []
    for _ in range(80):
        l, grads = g(p, nb, labels)
        losses.append(float(l))
        p = jax.tree_util.tree_map(lambda a, b: a - 0.5 * b, p, grads)
    assert losses[-1] < losses[0] * 0.5, (losses[0], losses[-1])


def test_v2_nested_pipeline_end_to_end():
    """integer_value_sub_sequence data -> embedding -> inner LSTM ->
    outer LSTM -> classify, fed through the v2 trainer feed path."""
    import paddle_tpu.fluid as fluid
    from paddle_tpu.fluid import layers as FL
    from paddle_tpu.v2 import layer as L
    from paddle_tpu.v2.data_type import integer_value_sub_sequence
    from paddle_tpu.v2.trainer import _V2Feeder

    fluid.reset_default_programs()
    fluid.executor._global_scope = fluid.Scope()
    V, E, H = 10, 5, 6
    docs = L.data("docs", integer_value_sub_sequence(V))
    label = FL.data("label", shape=(), dtype="int64")
    emb = L.embedding(docs, E)                  # nested-ness propagates
    sents = L.nested_lstmemory(emb, H)          # [B, S, H] outer sequence
    doc_vec = L.last_seq(L.lstmemory(sents, H))
    logits = FL.fc(doc_vec.var, 2)
    loss = FL.mean(FL.softmax_with_cross_entropy(logits, label))
    fluid.AdamOptimizer(0.05).minimize(loss)

    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())
    tr = _V2Feeder([docs])
    rows = [([[1, 2, 3], [4, 5]],), ([[6], [7, 8], [9, 1]],),
            ([[2, 2]],), ([[3], [3, 3, 3]],)]
    feed = tr(rows)
    feed["label"] = np.array([0, 1, 0, 1], np.int64)
    losses = [float(exe.run(feed=feed, fetch_list=[loss])[0])
              for _ in range(25)]
    assert losses[-1] < losses[0], (losses[0], losses[-1])


def test_nested_recurrent_group_equals_flat_rnn():
    """sequence_nest_rnn.conf equivalence at the user DSL: an outer
    recurrent_group over sub-sequences whose inner recurrent_group's memory
    boots from the outer memory (so state chains across sub-sequence
    boundaries) must equal ONE flat recurrent_group over the flattened
    tokens — the reference's hierarchical-RNN design contract
    (gserver/tests/sequence_nest_rnn.conf vs sequence_rnn.conf)."""
    import paddle_tpu.fluid as fluid
    from paddle_tpu.fluid.executor import Executor
    from paddle_tpu.v2 import layer as L
    from paddle_tpu.v2.data_type import dense_vector_sequence

    fluid.reset_default_programs()
    B, S_, T, D, H = 2, 2, 3, 4, 5
    r = np.random.RandomState(5)
    nested_data = r.randn(B, S_, T, D).astype(np.float32)
    flat_data = nested_data.reshape(B, S_ * T, D)

    # ---- nested config: outer rg over sub-sequences, inner rg over tokens
    x = L.data("x", dense_vector_sequence(D))        # fed [B, S*T... ] flat
    # feed nested as [B, S, T, D] directly through a fresh data var
    # (FL.data prepends the batch dim)
    from paddle_tpu.fluid import layers as FL
    xn = FL.data("xn", shape=(-1, -1, D))
    xn_lo = L.LayerOutput(xn)
    sublen = FL.data("sublen", shape=(-1,), dtype="int32")      # [B, S]

    def outer_step(x_seq, sub_len):
        outer_mem = L.memory("outer_state", H)
        inner_in = L.LayerOutput(x_seq.var, sub_len.var)

        def inner_step(y):
            inner_mem = L.memory("inner_state", H, boot_layer=outer_mem)
            return L.fc([y, inner_mem], H, act="tanh", bias_attr=True,
                        name="inner_state")

        inner_out = L.recurrent_group(inner_step, inner_in)
        last = L.last_seq(inner_out)
        L.identity(last, name="outer_state")
        return inner_out

    nested_out = L.recurrent_group(
        outer_step, [xn_lo, L.LayerOutput(sublen)])

    # ---- flat config: one rg over all tokens
    def flat_step(y):
        mem = L.memory("state", H)
        return L.fc([y, mem], H, act="tanh", bias_attr=True, name="state")

    flat_out = L.recurrent_group(flat_step, x)

    exe = Executor()
    exe.run(fluid.default_startup_program())
    # share weights: copy the nested rg's fc params onto the flat rg's
    params = [n for n, v in
              fluid.default_main_program().global_block().vars.items()
              if v.persistable and v.trainable]
    assert len(params) == 4, params      # (w, b) x 2 configs
    nested_p, flat_p = params[:2], params[2:]
    for a, b in zip(nested_p, flat_p):
        exe.scope.set(b, exe.scope.get(a))

    feeds = {"xn": nested_data,
             "sublen": np.full((B, S_), T, np.int32),
             "x": flat_data, "x__len__": np.full((B,), S_ * T, np.int32)}
    nv, fv = exe.run(fluid.default_main_program(), feed=feeds,
                     fetch_list=[nested_out.var.name, flat_out.var.name])
    nv = np.asarray(nv).reshape(B, S_ * T, H)
    np.testing.assert_allclose(nv, np.asarray(fv), rtol=2e-5, atol=2e-6)
