"""Observability-plane tests (ISSUE 3): registry/label semantics, histogram
bucketing, span nesting + Chrome-export schema, zero-cost-when-uninstalled,
retry/StatSet/train_stats satellites, and an end-to-end train-2-passes run
asserting step/RPC/checkpoint metrics — fake clocks, no real sleeps.
"""

import json
import os
import threading

import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu import analysis, cli, faults, obs
from paddle_tpu.optimizer import SGD
from paddle_tpu.trainer import Trainer
from paddle_tpu.utils.retry import RetryBudgetExceeded, RetryPolicy
from paddle_tpu.utils.stats import StatSet, StatSnapshot

pytestmark = pytest.mark.obs


def _fake_clock(step=1.0):
    t = [0.0]

    def clock():
        t[0] += step
        return t[0]

    return clock, t


# -- registry / metric semantics ------------------------------------------------

def test_registry_get_or_create_and_kind_conflict():
    r = obs.MetricsRegistry()
    c1 = r.counter("trainer.steps_total")
    assert r.counter("trainer.steps_total") is c1
    with pytest.raises(ValueError, match="already registered as counter"):
        r.gauge("trainer.steps_total")
    with pytest.raises(ValueError, match="subsystem.noun_qualifier"):
        r.counter("NotSnake.Case")
    with pytest.raises(ValueError, match="subsystem.noun_qualifier"):
        r.counter("nodots")


def test_counter_labels_are_independent_series():
    r = obs.MetricsRegistry()
    c = r.counter("rpc.calls_total")
    c.inc(rpc="master")
    c.inc(2, rpc="coord")
    c.inc()                                     # unlabeled series
    assert c.get(rpc="master") == 1
    assert c.get(rpc="coord") == 2
    assert c.get() == 1
    bound = c.labels(rpc="master")
    bound.inc(3)
    assert bound.get() == 4
    with pytest.raises(ValueError, match="cannot decrease"):
        c.inc(-1)
    # collect() emits one sample per (metric, label-set)
    samples = [s for s in r.collect() if s["name"] == "rpc.calls_total"]
    assert {frozenset(s["labels"].items()) for s in samples} == {
        frozenset(), frozenset({("rpc", "master")}),
        frozenset({("rpc", "coord")})}


def test_gauge_set_and_high_water():
    r = obs.MetricsRegistry()
    g = r.gauge("data.queue_depth")
    g.set(3)
    g.set(7)
    g.set(2)
    assert g.get() == 2
    assert g.high_water() == 7
    g.inc()
    g.dec(2)
    assert g.get() == 1


def test_histogram_fixed_bucket_boundaries():
    r = obs.MetricsRegistry()
    h = r.histogram("rpc.call_seconds", buckets=(0.01, 0.1, 1.0))
    for v in (0.005, 0.05, 0.05, 0.5, 99.0):
        h.observe(v)
    snap = h.snapshot()
    # cumulative le-style counts, overflow in +Inf
    assert snap["buckets"] == [[0.01, 1], [0.1, 3], [1.0, 4], ["+Inf", 5]]
    assert snap["count"] == 5
    assert snap["max"] == 99.0
    assert snap["sum"] == pytest.approx(99.605)
    # boundary value lands in its bucket (le semantics)
    h2 = r.histogram("fluid.run_seconds", buckets=(1.0, 2.0))
    h2.observe(1.0)
    assert h2.snapshot()["buckets"][0] == [1.0, 1]
    # same name + different boundaries is a contract violation
    with pytest.raises(ValueError, match="different bucket"):
        r.histogram("rpc.call_seconds", buckets=(0.5,))
    with pytest.raises(ValueError, match="strictly increasing"):
        obs.Histogram("a.b_seconds", buckets=(1.0, 1.0))


def test_histogram_labelled_series():
    r = obs.MetricsRegistry()
    h = r.histogram("rpc.call_seconds", buckets=(0.1, 1.0))
    h.observe(0.05, rpc="master")
    h.observe(0.5, rpc="coord")
    assert h.snapshot(rpc="master")["count"] == 1
    assert h.snapshot(rpc="coord")["buckets"] == [[0.1, 0], [1.0, 1],
                                                  ["+Inf", 1]]


# -- tracer / spans -------------------------------------------------------------

def test_span_nesting_parent_ids_and_fake_clock():
    clock, _ = _fake_clock()
    tr = obs.Tracer(clock=clock)
    with tr.span("trainer.pass", pass_id=0):
        with tr.span("trainer.step"):
            pass
        with tr.span("trainer.step"):
            pass
    spans = tr.spans()                   # recorded in exit order
    assert [s["name"] for s in spans] == ["trainer.step", "trainer.step",
                                          "trainer.pass"]
    outer = spans[2]
    assert outer["parent"] is None
    assert spans[0]["parent"] == outer["id"] == spans[1]["parent"]
    # fake clock: every enter/exit ticks 1s -> exact durations
    assert spans[0]["dur"] == 1.0
    assert outer["dur"] == 5.0
    assert all(s["tid"] == threading.get_ident() for s in spans)


def test_span_threads_get_independent_stacks():
    tr = obs.Tracer(clock=_fake_clock()[0])
    done = threading.Event()

    def worker():
        with tr.span("data.prefetch"):
            done.set()

    with tr.span("trainer.pass"):
        t = threading.Thread(target=worker)
        t.start()
        t.join()
    by_name = {s["name"]: s for s in tr.spans()}
    # the worker's span must NOT claim the main thread's open span as parent
    assert by_name["data.prefetch"]["parent"] is None
    assert by_name["data.prefetch"]["tid"] != by_name["trainer.pass"]["tid"]


def test_span_records_error_and_survives_exception():
    tr = obs.Tracer(clock=_fake_clock()[0])
    with pytest.raises(RuntimeError):
        with tr.span("fluid.run"):
            raise RuntimeError("boom")
    (s,) = tr.spans()
    assert s["args"]["error"] == "RuntimeError"


def test_chrome_export_schema():
    clock, _ = _fake_clock()
    r = obs.MetricsRegistry()
    s = obs.ObsSession(registry=r, tracer=obs.Tracer(clock=clock))
    with s.installed():
        with obs.span("trainer.pass", pass_id=3):
            with obs.span("ckpt.publish"):
                pass
        obs.instant("jax.compile", event="e")
        obs.count("faults.injected_total", site="rpc.send", action="raise")
    trace = obs.chrome_trace(s.dump())
    evs = trace["traceEvents"]
    assert json.dumps(trace)             # serializable as-is
    xs = {e["name"]: e for e in evs if e["ph"] == "X"}
    assert set(xs) == {"trainer.pass", "ckpt.publish"}
    # µs timestamps; child contained within parent (what Perfetto nests on)
    par, chd = xs["trainer.pass"], xs["ckpt.publish"]
    assert par["ts"] <= chd["ts"]
    assert chd["ts"] + chd["dur"] <= par["ts"] + par["dur"]
    assert par["args"] == {"pass_id": 3}
    assert [e for e in evs if e["ph"] == "i" and e["name"] == "jax.compile"]
    (c,) = [e for e in evs if e["ph"] == "C"]
    assert c["name"] == "faults.injected_total{action=raise,site=rpc.send}"
    assert c["args"]["value"] == 1
    assert any(e["ph"] == "M" for e in evs)


def test_tracer_caps_events_and_reports_dropped():
    clock, _ = _fake_clock()
    tr = obs.Tracer(clock=clock, max_events=3)
    s = obs.ObsSession(registry=obs.MetricsRegistry(), tracer=tr)
    with s.installed():
        for _ in range(5):
            with obs.span("trainer.step"):
                pass
    assert len(tr.events) == 3           # bounded: telemetry can't OOM
    assert tr.dropped == 2
    assert s.dump()["meta"]["events_dropped"] == 2
    tr.reset()
    assert tr.dropped == 0


def test_summary_quantiles_clamped_to_observed_max():
    r = obs.MetricsRegistry()
    h = r.histogram("trainer.step_seconds", buckets=(0.0005, 1.0))
    h.observe(0.000035)                  # 0.035ms in the le=0.5ms bucket
    dump = {"metrics": r.collect()}
    rep = obs.summary(dump)
    # p50/p99 must not exceed the observed max (0.035ms), not read 0.5ms
    line = next(l for l in rep.splitlines() if "trainer.step_seconds" in l)
    assert "0.035ms" in line and "0.500ms" not in line


def test_read_jsonl_tolerates_torn_tail(tmp_path):
    # a process killed mid-save leaves a partial final line; the dump of
    # exactly that crashed run must still export its intact prefix
    s = obs.ObsSession(registry=obs.MetricsRegistry(),
                       tracer=obs.Tracer(clock=_fake_clock()[0]))
    with s.installed():
        obs.count("trainer.steps_total", 5)
        with obs.span("trainer.pass"):
            pass
    p = s.save(str(tmp_path / "torn.jsonl"))
    raw = open(p, "rb").read()
    open(p, "wb").write(raw[:-5])        # tear the last line
    back = obs.read_jsonl(p)
    assert [m for m in back["metrics"] if m["name"] == "trainer.steps_total"]
    assert cli.main(["obs", "summary", "--input", p]) == 0


def test_jsonl_round_trip(tmp_path):
    clock, _ = _fake_clock()
    s = obs.ObsSession(registry=obs.MetricsRegistry(),
                       tracer=obs.Tracer(clock=clock))
    with s.installed():
        with obs.span("rpc.call", metric="rpc.call_seconds"):
            pass
        obs.count("rpc.calls_total", rpc="master")
    p = s.save(str(tmp_path / "run.jsonl"))
    back = obs.read_jsonl(p)
    assert back["meta"]["version"] == 1
    assert [m for m in back["metrics"] if m["name"] == "rpc.calls_total"]
    hist = [m for m in back["metrics"] if m["name"] == "rpc.call_seconds"]
    assert hist and hist[0]["count"] == 1
    assert [e for e in back["events"] if e["name"] == "rpc.call"]
    # exporters accept the reloaded dump unchanged
    assert "rpc_calls_total" in obs.prometheus_text(back)
    assert obs.chrome_trace(back)["traceEvents"]
    assert "rpc.call_seconds" in obs.summary(back)


# -- zero cost when uninstalled -------------------------------------------------

def test_zero_cost_hooks_are_noops_without_session():
    assert not obs.is_active()
    # hooks must neither raise nor record anywhere
    obs.count("trainer.steps_total")
    obs.gauge_set("data.queue_depth", 5)
    obs.observe("rpc.call_seconds", 0.1)
    obs.instant("jax.compile")
    sp = obs.span("trainer.step", metric="trainer.step_seconds")
    assert sp is obs.NULL_SPAN           # ONE shared object, no allocation
    with sp:
        pass
    r = obs.MetricsRegistry()
    with obs.ObsSession(registry=r).installed():
        pass
    assert r.collect() == []             # nothing leaked into the session


def test_exclusive_install():
    a = obs.ObsSession(registry=obs.MetricsRegistry())
    b = obs.ObsSession(registry=obs.MetricsRegistry())
    with a.installed():
        with pytest.raises(RuntimeError, match="already installed"):
            b.install()
    assert not obs.is_active()


# -- satellites -----------------------------------------------------------------

def test_statset_items_returns_immutable_snapshots():
    ss = StatSet()
    ss.add("TrainBatch", 0.5)
    ss.add("TrainBatch", 1.5)
    items = ss.items()
    snap = items["TrainBatch"]
    assert isinstance(snap, StatSnapshot)
    assert snap.total == 2.0 and snap.count == 2
    assert snap.avg == 1.0 and snap.max == 1.5
    with pytest.raises(AttributeError):
        snap.total = 99.0                # immutable: callers can't corrupt
    ss.add("TrainBatch", 1.0)
    assert snap.total == 2.0             # a snapshot, not a live reference
    assert ss.items()["TrainBatch"].total == 3.0


def test_train_stats_is_readonly_counter_view():
    t = Trainer(lambda p, x: jnp.sum(x), SGD(0.1), nan_guard=False)
    assert dict(t.train_stats) == {"nonfinite_batches": 0,
                                   "skipped_batches": 0, "preemptions": 0}
    with pytest.raises(TypeError):
        t.train_stats["preemptions"] = 1
    t.metrics.counter("trainer.preemptions_total").inc()
    assert t.train_stats["preemptions"] == 1
    # injectable registry
    r = obs.MetricsRegistry()
    t2 = Trainer(lambda p, x: jnp.sum(x), SGD(0.1), metrics=r)
    t2.metrics.counter("trainer.nonfinite_total").inc(2)
    assert t2.train_stats["nonfinite_batches"] == 2
    assert r.counter("trainer.nonfinite_total").get() == 2


def test_retry_policy_observer_no_sleeps():
    sleeps = []
    clock = [0.0]
    events = []
    policy = RetryPolicy(max_attempts=3, base_delay=0.1, multiplier=2.0,
                         jitter=0.0, sleep=sleeps.append,
                         clock=lambda: clock[0],
                         observer=lambda ev, **kw: events.append((ev, kw)))
    calls = [0]

    def flaky():
        calls[0] += 1
        if calls[0] < 3:
            raise ConnectionError("nope")
        return "ok"

    assert policy.call(flaky) == "ok"
    kinds = [e[0] for e in events]
    assert kinds == ["attempt", "attempt", "success"]
    assert events[0][1]["attempt"] == 1
    assert events[0][1]["delay"] == pytest.approx(0.1)
    assert events[1][1]["delay"] == pytest.approx(0.2)
    assert events[2][1]["attempts"] == 3
    events.clear()
    with pytest.raises(RetryBudgetExceeded):
        policy.call(lambda: (_ for _ in ()).throw(ConnectionError("x")))
    assert [e[0] for e in events] == ["attempt", "attempt", "giveup"]
    assert events[-1][1]["attempts"] == 3


def test_retry_observer_bridge_counts_into_session():
    r = obs.MetricsRegistry()
    policy = RetryPolicy(max_attempts=2, base_delay=0.25, jitter=0.0,
                         sleep=lambda s: None, clock=lambda: 0.0,
                         observer=obs.retry_observer("rpc"))
    with obs.ObsSession(registry=r).installed():
        with pytest.raises(RetryBudgetExceeded):
            policy.call(lambda: (_ for _ in ()).throw(OSError("x")))
    assert r.counter("rpc.retries_total").get() == 1
    assert r.counter("rpc.giveups_total").get() == 1
    assert r.counter("rpc.backoff_seconds_total").get() == \
        pytest.approx(0.25)
    # without a session the observer is inert (no import cycle, no cost)
    policy.call(lambda: "fine")


def test_metric_name_lint_L005():
    assert analysis.lint_metric_names(obs.CATALOGUE) == []
    diags = analysis.lint_metric_names({
        "BadName": ("counter", ""),                 # no dot / case
        "three.dots.here": ("counter", ""),         # two dots
        "trainer.steps": ("counter", ""),           # counter w/o _total
        "fluid.run_seconds": ("histogram", ""),     # fine
        "data.queue_total": ("gauge", ""),          # gauge w/ reserved suffix
    })
    assert {d.var for d in diags} == {"BadName", "three.dots.here",
                                      "trainer.steps", "data.queue_total"}
    assert all(d.code == "L005" for d in diags)
    # plain-iterable form: shape check only
    assert analysis.lint_metric_names(["trainer.steps"]) == []
    assert len(analysis.lint_metric_names(["nodots"])) == 1


def test_catalogue_covers_spans_and_lint_catalogue_entry():
    assert "L005" in analysis.LINT_CATALOGUE
    # every span the instrumentation emits is documented
    for name in ("trainer.pass", "trainer.step", "rpc.call", "ckpt.publish",
                 "fluid.run", "fluid.verify"):
        assert name in obs.SPANS


# -- end-to-end: train 2 passes, RPC + checkpoint + step metrics ---------------

def _loss(params, x, y):
    return jnp.mean((x @ params["w"] - y) ** 2)


def _batches(n=3, bs=8, d=4):
    rs = np.random.RandomState(0)
    return [(rs.randn(bs, d).astype(np.float32),
             rs.randn(bs, 1).astype(np.float32)) for _ in range(n)]


def test_e2e_train_two_passes_populates_metrics(tmp_path):
    from paddle_tpu.runtime.coord import CoordServer, _CoordClient
    srv = CoordServer().start()
    client = _CoordClient(*srv.address)
    batches = _batches()

    def reader():
        # an RPC inside the read path: rpc.call spans/latency nest under
        # the open trainer.pass span exactly like a cloud_reader's
        # get_task pulls would
        client.call({"op": "ping"})
        return iter(batches)

    r = obs.MetricsRegistry()
    clock, _ = _fake_clock(0.001)
    try:
        with obs.ObsSession(registry=r, clock=clock).installed() as s:
            t = Trainer(_loss, SGD(0.1), output_dir=str(tmp_path))
            params, _ = t.train(reader,
                                {"w": np.zeros((4, 1), np.float32)},
                                num_passes=2)
    finally:
        client.close()
        srv.stop()
    # step metrics
    assert r.counter("trainer.steps_total").get() == 6
    assert r.counter("trainer.examples_total").get() == 48
    assert r.histogram("trainer.step_seconds").snapshot()["count"] == 6
    # RPC metrics (latency histogram labeled by client)
    assert r.counter("rpc.calls_total").get(rpc="coord rpc", op="ping") == 2
    assert r.histogram("rpc.call_seconds").snapshot(
        rpc="coord rpc")["count"] == 2
    # checkpoint metrics: one save per pass, real bytes, timed members
    assert r.counter("ckpt.saves_total").get() == 2
    assert r.counter("ckpt.bytes_total").get() > 0
    assert r.histogram("ckpt.write_seconds").snapshot()["count"] >= 4
    # span nesting: rpc.call and ckpt.publish both inside trainer.pass
    spans = {e["id"]: e for e in s.dump()["events"] if e["kind"] == "span"}

    def ancestors(e):
        while e.get("parent"):
            e = spans[e["parent"]]
            yield e["name"]

    for name in ("rpc.call", "ckpt.publish"):
        e = next(x for x in spans.values() if x["name"] == name)
        assert "trainer.pass" in list(ancestors(e)), name
    # the summary subsumes StatSet.report(): timers appear next to metrics
    rep = t.summary()
    assert "TrainBatch" in rep and "trainer.steps_total" in rep


def test_chaos_run_exports_chrome_trace_via_cli(tmp_path, capsys):
    plan = faults.FaultPlan(seed=3)
    plan.add("ckpt.write", "corrupt", nth=1)
    plan.add("step.grad", "delay", nth=2, delay_s=0.0)
    r = obs.MetricsRegistry()
    with obs.ObsSession(registry=r).installed() as s, plan.installed():
        t = Trainer(_loss, SGD(0.1), output_dir=str(tmp_path / "out"))
        t.train(lambda: iter(_batches()),
                {"w": np.zeros((4, 1), np.float32)}, num_passes=1)
    # per-site injected-fault counters match the plan's fired log exactly
    fired = {}
    for site, _, action in plan.fired:
        fired[(site, action)] = fired.get((site, action), 0) + 1
    for (site, action), n in fired.items():
        assert r.counter("faults.injected_total").get(
            site=site, action=action) == n
    dump = str(tmp_path / "run.jsonl")
    s.save(dump)
    out = str(tmp_path / "trace.json")
    assert cli.main(["obs", "export", "--input", dump,
                     "--format", "chrome", "--output", out]) == 0
    trace = json.load(open(out))
    names = {e["name"] for e in trace["traceEvents"] if e["ph"] == "X"}
    assert {"trainer.pass", "trainer.step", "trainer.checkpoint",
            "ckpt.publish", "ckpt.member"} <= names
    counters = {e["name"]: e["args"]["value"]
                for e in trace["traceEvents"] if e["ph"] == "C"}
    assert counters[
        "faults.injected_total{action=corrupt,site=ckpt.write}"] == 1
    # prom + summary forms of the same dump
    assert cli.main(["obs", "export", "--input", dump,
                     "--format", "prom"]) == 0
    assert "paddle_tpu_trainer_steps_total" in capsys.readouterr().out
    assert cli.main(["obs", "summary", "--input", dump]) == 0
    assert "trainer.steps_total" in capsys.readouterr().out


def test_no_double_count_when_session_shares_trainer_registry():
    # Trainer(metrics=R) under a session whose registry IS R: the session
    # mirror must be skipped or every counter reads 2x (code-review find)
    r = obs.MetricsRegistry()
    with obs.ObsSession(registry=r).installed():
        t = Trainer(_loss, SGD(0.1), metrics=r)
        t.train(lambda: iter(_batches(2)),
                {"w": np.zeros((4, 1), np.float32)}, num_passes=1)
        t._count("trainer.preemptions_total")
    assert r.counter("trainer.steps_total").get() == 2
    assert t.train_stats["preemptions"] == 1
    # distinct registries: both sides see the count exactly once
    r2, local = obs.MetricsRegistry(), obs.MetricsRegistry()
    with obs.ObsSession(registry=r2).installed():
        t2 = Trainer(_loss, SGD(0.1), metrics=local)
        t2._count("trainer.preemptions_total")
    assert local.counter("trainer.preemptions_total").get() == 1
    assert r2.counter("trainer.preemptions_total").get() == 1


def test_jax_compile_hook_counts_backend_compiles_only():
    from paddle_tpu.obs import jaxhooks
    r = obs.MetricsRegistry()
    with obs.ObsSession(registry=r).installed():
        # one jit emits several duration events; only backend_compile counts
        for ev in ("/jax/core/compile/jaxpr_trace_duration",
                   "/jax/core/compile/mlir_lowering_duration",
                   "/jax/core/compile/backend_compile_duration"):
            jaxhooks._on_duration(ev, 0.5)
    assert r.counter("jax.compiles_total").get() == 1
    assert r.histogram("jax.compile_seconds").snapshot()["count"] == 1


def test_rpc_client_does_not_mutate_caller_policy():
    from paddle_tpu.runtime.master_service import _RpcClient
    mine = RetryPolicy(max_attempts=2)
    c = _RpcClient("127.0.0.1", 1, retry_policy=mine)
    assert mine.observer is None          # caller's shared policy untouched
    c2 = _RpcClient("127.0.0.1", 1)
    assert c2.policy.observer is not None  # our own default gets telemetry
    c.close()
    c2.close()


def test_prefetch_queue_metrics():
    from paddle_tpu.data.prefetch import DoubleBuffer
    r = obs.MetricsRegistry()
    with obs.ObsSession(registry=r).installed():
        got = list(DoubleBuffer(lambda: iter(range(5)), depth=2))
    assert got == list(range(5))
    assert r.counter("data.prefetch_iters_total").get() == 1
    # the first get always races the producer: starvation is >= 1 and the
    # gauge saw some depth (possibly 0) — presence, not exact timing
    assert r.counter("data.starved_total").get() >= 0
    samples = [s for s in r.collect() if s["name"] == "data.queue_depth"]
    assert samples and samples[0]["type"] == "gauge"


# -- ISSUE 4: distributed tracing, cluster aggregation, flight recorder --------

def test_prom_label_value_escaping():
    # regression: values holding '"', '\' or newlines previously emitted
    # unparseable exposition text
    r = obs.MetricsRegistry()
    r.counter("rpc.calls_total").inc(op='we"ird\\path\nx')
    text = obs.prometheus_text({"metrics": r.collect()})
    line = next(l for l in text.splitlines()
                if l.startswith("paddle_tpu_rpc_calls_total{"))
    assert 'op="we\\"ird\\\\path\\nx"' in line
    # escaped text has no raw newline inside the label braces
    assert "\n" not in line


def test_wire_context_shape_and_sanitize():
    assert obs.wire_context(obs.NULL_SPAN) is None   # plane off: no key
    r = obs.MetricsRegistry()
    with obs.ObsSession(registry=r).installed() as s:
        with obs.span("rpc.call") as sp:
            ctx = obs.wire_context(sp)
        assert ctx == {"id": obs.context.trace_id(), "span": sp.id,
                       "pid": os.getpid()}
        # hostile/malformed contexts degrade to no remote, never raise
        for bad in (None, 42, "x", {}, {"id": 1}, {"id": "a", "span": "NaN",
                                                   "pid": 1},
                    {"id": "a", "span": -1, "pid": 1}):
            with obs.server_span("master.dispatch", bad, op="t"):
                pass
        long_id = {"id": "q" * 500, "span": 7, "pid": 8}
        with obs.server_span("master.dispatch", long_id, op="t"):
            pass
    spans = [e for e in s.dump()["events"] if e["name"] == "master.dispatch"]
    assert all("remote" not in e for e in spans[:-1])
    assert spans[-1]["remote"] == {"id": "q" * 64, "span": 7, "pid": 8}


def test_coord_server_span_parents_under_client_rpc_call():
    from paddle_tpu.runtime.coord import CoordServer, _CoordClient
    srv = CoordServer().start()
    client = _CoordClient(*srv.address)
    r = obs.MetricsRegistry()
    try:
        with obs.ObsSession(registry=r).installed() as s:
            client.call({"op": "ping"})
    finally:
        client.close()
        srv.stop()
    spans = {e["id"]: e for e in s.dump()["events"] if e["kind"] == "span"}
    disp = next(e for e in spans.values() if e["name"] == "coord.dispatch")
    # the server-side span names the client's rpc.call span as its remote
    # parent — the cross-process edge (same pid here; the multiprocess
    # e2e in test_obs_distributed.py asserts the distinct-pid case)
    assert spans[disp["remote"]["span"]]["name"] == "rpc.call"
    assert disp["remote"]["id"] == obs.context.trace_id()
    assert disp["args"]["op"] == "ping"
    # per-request-type counters on the server peer
    assert r.counter("coord.requests_total").get(type="ping") == 1
    assert r.counter("coord.request_errors_total").get(type="ping") == 0
    # errors counted too
    srv2 = CoordServer().start()
    c2 = _CoordClient(*srv2.address)
    try:
        with obs.ObsSession(registry=r).installed():
            c2.call({"op": "nope"})
    finally:
        c2.close()
        srv2.stop()
    # arbitrary op strings clamp to "unknown": a hostile peer must not
    # mint unbounded counter series (the L005 cardinality failure mode)
    assert r.counter("coord.request_errors_total").get(type="unknown") == 1
    assert r.counter("coord.requests_total").get(type="nope") == 0


def test_wire_context_absent_from_envelope_without_session():
    # with no session the request bytes must stay identical to an
    # un-instrumented client's: no "trace" key reaches the server
    from paddle_tpu.runtime.coord import CoordServer, _CoordClient
    seen = []
    srv = CoordServer()
    orig = srv._dispatch

    def spy(req):
        seen.append(req)
        return orig(req)

    srv._dispatch = spy
    srv.start()
    client = _CoordClient(*srv.address)
    try:
        assert not obs.is_active()
        client.call({"op": "ping"})
        r = obs.MetricsRegistry()
        with obs.ObsSession(registry=r).installed():
            client.call({"op": "ping"})
    finally:
        client.close()
        srv.stop()
    assert "trace" not in seen[0]
    assert "trace" in seen[1]


def test_merge_dumps_and_multi_pid_chrome_export():
    # two synthetic per-process dumps: worker rpc.call -> master dispatch
    worker = {
        "meta": {"pid": 100, "process": "worker-0",
                 "clock_origin_unix": 1000.0},
        "metrics": [{"type": "counter", "name": "trainer.steps_total",
                     "labels": {}, "value": 3}],
        "events": [{"kind": "span", "name": "rpc.call", "ts": 1.0,
                    "dur": 0.5, "tid": 1, "pid": 100, "id": 7,
                    "parent": None, "args": {"op": "obs_push"}}]}
    master = {
        "meta": {"pid": 200, "process": "master",
                 "clock_origin_unix": 1000.25},
        "metrics": [{"type": "counter", "name": "trainer.steps_total",
                     "labels": {}, "value": 9}],
        "events": [{"kind": "span", "name": "master.dispatch", "ts": 0.9,
                    "dur": 0.1, "tid": 9, "pid": 200, "id": 3,
                    "parent": None, "args": {"op": "obs_push"},
                    "remote": {"id": "t", "span": 7, "pid": 100}}]}
    merged = obs.merge_dumps([worker, master])
    # same-named series stay distinct via the worker label contract
    series = {(m["labels"]["worker"], m["value"])
              for m in merged["metrics"]}
    assert series == {("worker-0", 3), ("master", 9)}
    # clock alignment: master events shift by its later origin
    disp = next(e for e in merged["events"]
                if e["name"] == "master.dispatch")
    assert disp["ts"] == pytest.approx(1.15)
    trace = obs.chrome_trace(merged)
    evs = trace["traceEvents"]
    lanes = {e["pid"]: e["args"]["name"] for e in evs
             if e["ph"] == "M" and e["name"] == "process_name"}
    assert lanes == {100: "worker-0", 200: "master"}
    xs = {e["name"]: e for e in evs if e["ph"] == "X"}
    assert xs["master.dispatch"]["args"]["remote_parent"]["span"] == 7
    # the stitch: a flow arrow from the client slice to the server slice
    s_ev = next(e for e in evs if e["ph"] == "s")
    f_ev = next(e for e in evs if e["ph"] == "f")
    assert s_ev["id"] == f_ev["id"]
    assert s_ev["pid"] == 100 and f_ev["pid"] == 200


def test_master_dispatch_obs_push_and_merged_stats():
    from paddle_tpu.runtime import native_available
    if not native_available():
        pytest.skip("native task master not built")
    from paddle_tpu.runtime.master_service import MasterServer
    r = obs.MetricsRegistry()
    srv = MasterServer()          # in-process dispatch; no network start
    with obs.ObsSession(registry=r).installed() as s:
        wr = obs.MetricsRegistry()
        wr.counter("trainer.steps_total").inc(5)
        ctx = {"id": "t", "span": 11, "pid": 999}
        resp = srv._dispatch({"op": "obs_push", "worker": "w1",
                              "samples": wr.collect(), "trace": ctx})
        assert resp["ok"] and resp["accepted"] == 1
        # junk samples are filtered, never stored
        assert srv._dispatch({"op": "obs_push", "worker": "w2",
                              "samples": ["junk", {"no_name": 1},
                                          {"name": "a.b_total",
                                           "type": "counter", "value": 2,
                                           "labels": {"x": "y"},
                                           "evil": "dropped"}]}
                             )["accepted"] == 1
        out = srv._dispatch({"op": "obs_stats"})
    assert out["workers"] == ["w1", "w2"]
    by_worker = {}
    for m in out["samples"]:
        by_worker.setdefault(m["labels"]["worker"], []).append(m)
    assert by_worker["w1"][0]["name"] == "trainer.steps_total"
    assert by_worker["w1"][0]["value"] == 5
    assert "evil" not in by_worker["w2"][0]
    # dispatch span carries the wire context; counters tallied by type
    disp = [e for e in s.dump()["events"]
            if e.get("name") == "master.dispatch"]
    assert disp[0]["remote"] == ctx
    assert r.counter("master.requests_total").get(type="obs_push") == 2
    assert r.counter("master.requests_total").get(type="obs_stats") == 1
    assert r.gauge("master.obs_workers").get() == 2


def test_flight_recorder_ring_keeps_tail_and_deltas(tmp_path):
    r = obs.MetricsRegistry()
    clock, _ = _fake_clock(0.001)
    s = obs.ObsSession(registry=r, tracer=obs.Tracer(clock=clock))
    p = str(tmp_path / "flight.jsonl")
    with s.installed():
        r.counter("trainer.steps_total").inc(10)     # pre-arm baseline
        rec = obs.FlightRecorder(s, p, ring_size=4).arm()
        try:
            r.counter("trainer.steps_total").inc(3)
            for i in range(10):
                with obs.span("trainer.step", batch=i):
                    pass
            out = rec.dump("test")
        finally:
            rec.disarm()
    assert out == p
    assert s.tracer.ring is None         # disarm releases the ring too
    back = obs.read_jsonl(p)
    assert back["meta"]["flight"] is True
    assert back["meta"]["reason"] == "test"
    # the ring keeps the END of the run — the last 4 steps, not the first
    assert [e["args"]["batch"] for e in back["events"]] == [6, 7, 8, 9]
    steps = next(m for m in back["metrics"]
                 if m["name"] == "trainer.steps_total")
    assert steps["value"] == 13 and steps["delta"] == 3
    # the flight dump is a normal dump: every exporter accepts it
    assert obs.chrome_trace(back)["traceEvents"]
    assert "trainer_steps_total" in obs.prometheus_text(back)


def test_flight_dump_written_at_injected_fault(tmp_path):
    r = obs.MetricsRegistry()
    s = obs.ObsSession(registry=r)
    p = str(tmp_path / "crash.jsonl")
    plan = faults.FaultPlan().add("rpc.send", "raise", nth=1)
    with s.installed():
        rec = obs.FlightRecorder(s, p, ring_size=16).arm()
        try:
            with plan.installed():
                with obs.span("trainer.step"):
                    with pytest.raises(faults.FaultError):
                        faults.fire("rpc.send")
        finally:
            rec.disarm()
    back = obs.read_jsonl(p)
    assert back["meta"]["reason"] == "fault:rpc.send"
    # the dump precedes the unwind: the enclosing step span is still open
    # (not yet in the ring) but the injected-fault counter is in
    inj = next(m for m in back["metrics"]
               if m["name"] == "faults.injected_total")
    assert inj["labels"] == {"site": "rpc.send", "action": "raise"}
    assert not obs.flight_dump("noop")        # disarmed: hook is inert


def test_flight_recorder_overhead_per_batch():
    # acceptance: the armed ring adds <= ~5µs per batch (5 span records).
    # Measured ~0.5µs on CI-class CPUs; the bound below is 10x slack for
    # noisy neighbours, while still catching an accidental O(ring) cost.
    import time as _t
    s = obs.ObsSession(registry=obs.MetricsRegistry())

    def per_batch(n=300):
        t0 = _t.perf_counter()
        for _ in range(n):
            for _ in range(5):
                with s.tracer.span("trainer.step"):
                    pass
        return (_t.perf_counter() - t0) / n

    with s.installed():
        base = min(per_batch() for _ in range(3))
        s.tracer.enable_ring(2048)
        armed = min(per_batch() for _ in range(3))
    assert armed - base < 50e-6, (base, armed)
    # and the uninstalled fast path is untouched by the feature
    assert obs.span("trainer.step") is obs.NULL_SPAN


def test_metric_lint_flags_unbounded_labels():
    # catalogue-declared label keys from the unbounded set are flagged
    diags = analysis.lint_metric_names({
        "data.reads_total": ("counter", "", ("path",)),
        "rpc.calls_total": ("counter", "", ("rpc", "op")),     # bounded: ok
    })
    assert [d.var for d in diags] == ["data.reads_total"]
    assert all(d.code == "L005" for d in diags)
    # live samples: path-like values and runaway per-key cardinality
    samples = [{"name": "ckpt.saves_total", "type": "counter",
                "labels": {"dest": "/data/run/pass-00001"}, "value": 1}]
    assert len(analysis.lint_metric_names(["ckpt.saves_total"],
                                          samples=samples)) == 1
    many = [{"name": "rpc.calls_total", "type": "counter",
             "labels": {"op": f"op{i}"}, "value": 1} for i in range(40)]
    d = analysis.lint_metric_names(["rpc.calls_total"], samples=many)
    assert len(d) == 1 and "40 distinct values" in d[0].message
    # the shipped catalogue stays clean under the extended lint
    assert analysis.lint_metric_names(obs.CATALOGUE) == []


def test_obs_http_server_serves_metrics_trace_summary():
    import urllib.request

    from paddle_tpu.obs.aggregate import ObsHttpServer
    r = obs.MetricsRegistry()
    s = obs.ObsSession(registry=r, tracer=obs.Tracer(clock=_fake_clock()[0]))
    with s.installed():
        r.counter("trainer.steps_total").inc(4)
        with obs.span("trainer.pass"):
            pass
    srv = ObsHttpServer(s.dump).start()
    host, port = srv.address

    def get(path):
        with urllib.request.urlopen(f"http://{host}:{port}{path}",
                                    timeout=10) as resp:
            return resp.status, resp.read().decode()

    try:
        code, body = get("/metrics")
        assert code == 200
        assert "paddle_tpu_trainer_steps_total 4" in body
        code, body = get("/trace")
        assert code == 200
        assert any(e["name"] == "trainer.pass"
                   for e in json.loads(body)["traceEvents"])
        code, body = get("/summary")
        assert code == 200 and "trainer.steps_total" in body
        with pytest.raises(urllib.error.HTTPError) as ei:
            get("/nope")
        assert ei.value.code == 404
    finally:
        srv.stop()


def test_obs_pusher_pushes_and_counts_failures():
    class FakeClient:
        def __init__(self):
            self.pushed = []
            self.fail = False

        def obs_push(self, worker, samples):
            if self.fail:
                raise ConnectionError("down")
            self.pushed.append((worker, samples))

    from paddle_tpu.obs.aggregate import ObsPusher
    r = obs.MetricsRegistry()
    client = FakeClient()
    with obs.ObsSession(registry=r).installed():
        r.counter("trainer.steps_total").inc()
        pusher = ObsPusher(client, worker="w0", interval=3600)
        assert pusher.push_once()
        client.fail = True
        assert not pusher.push_once()      # counted, never raised
    assert client.pushed[0][0] == "w0"
    assert r.counter("obs.pushes_total").get() == 1
    assert r.counter("obs.push_failures_total").get() == 1


def test_executor_cache_hit_metrics():
    import paddle_tpu.fluid as fluid
    r = obs.MetricsRegistry()
    fluid.reset_default_programs()
    prog = fluid.Program()
    with fluid.program_guard(prog):
        x = fluid.layers.data("x", shape=(2,))
        y = fluid.layers.mean(fluid.layers.elementwise_add(x, x))
    exe = fluid.Executor()
    feed = {"x": np.ones((3, 2), np.float32)}
    with obs.ObsSession(registry=r).installed():
        exe.run(prog, feed=feed, fetch_list=[y])
        exe.run(prog, feed=feed, fetch_list=[y])
    assert r.counter("fluid.runs_total").get() == 2
    # hit/miss counters carry the bucketed label (no BucketSpec -> "false")
    assert r.counter("fluid.cache_misses_total").get(bucketed="false") == 1
    assert r.counter("fluid.cache_hits_total").get(bucketed="false") == 1
    assert r.gauge("fluid.cache_size").get() == 1
    assert r.histogram("fluid.run_seconds").snapshot()["count"] == 2
