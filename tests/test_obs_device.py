"""Device-time obs plane tests (ISSUE 9): goodput ledger bucket accounting
under an injectable clock (no real sleeps), the roofline cost ledger +
kernel-cost override of the zero-FLOP custom-call default, derived
MFU/HBM-bw gauges, the xplane fixture parse -> chrome-merge round trip
off-TPU, the L007 catalogue-drift lint, and the 2-pass CPU acceptance run
(non-null goodput ratio + device FLOPs as a byproduct of just running).
"""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu import analysis, obs
from paddle_tpu.obs import goodput, roofline
from paddle_tpu.obs import xplane as xp

pytestmark = pytest.mark.obs

FIXTURE = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                       "fixtures", "tiny.xplane.pb")


@pytest.fixture(autouse=True)
def _fresh_derivers():
    # derivation state is weak-keyed on the registry object; clear it
    # anyway so a registry a test holds alive can't leak a baseline into
    # the next test
    roofline._reset_derivers()
    yield
    roofline._reset_derivers()


def _manual_clock():
    t = {"v": 0.0}
    return (lambda: t["v"]), t


# -- goodput ledger -------------------------------------------------------------

def test_goodput_buckets_and_idle_under_fake_clock():
    r = obs.MetricsRegistry()
    clock, t = _manual_clock()
    led = goodput.GoodputLedger(r, component="test", clock=clock).open()
    t["v"] = 10.0
    with led.bucket("host_input"):
        t["v"] = 12.0                        # 2 s reading
    with led.bucket("device"):
        t["v"] = 17.0                        # 5 s dispatch+block
    led.add("host_sync", 1.0)
    t["v"] = 20.0
    led.close()                              # wall 20, accounted 8 -> idle 12

    def c(bucket):
        return r.counter(f"goodput.{bucket}_seconds_total").get(
            component="test")

    assert c("host_input") == pytest.approx(2.0)
    assert c("device") == pytest.approx(5.0)
    assert c("host_sync") == pytest.approx(1.0)
    assert c("compile") == 0.0
    assert c("idle") == pytest.approx(12.0)
    assert r.gauge("goodput.ratio").get(component="test") == \
        pytest.approx(5.0 / 20.0)
    with pytest.raises(ValueError, match="unknown goodput bucket"):
        led.add("gpu", 1.0)


def test_goodput_compile_steal_and_nested_buckets():
    r = obs.MetricsRegistry()
    clock, t = _manual_clock()
    led = goodput.GoodputLedger(r, component="test", clock=clock).open()
    with led.bucket("device"):
        # a 3 s backend compile fires inside the 10 s device region: the
        # wall second is counted ONCE — compile gets 3, device keeps 7
        led.note_compile(3.0)
        t["v"] = 10.0
    with led.bucket("host_sync"):            # outer: 10 -> 18
        with led.bucket("host_input"):       # inner: 10 -> 16
            t["v"] = 16.0
        t["v"] = 18.0
    led.close()

    def c(bucket):
        return r.counter(f"goodput.{bucket}_seconds_total").get(
            component="test")

    assert c("compile") == pytest.approx(3.0)
    assert c("device") == pytest.approx(7.0)
    # the inner bucket's whole span is not the outer's own time
    assert c("host_input") == pytest.approx(6.0)
    assert c("host_sync") == pytest.approx(2.0)


def test_goodput_note_compile_routes_to_open_ledger_only():
    r = obs.MetricsRegistry()
    clock, t = _manual_clock()
    goodput.note_compile(5.0)                # none open: cheap no-op
    led = goodput.GoodputLedger(r, component="test", clock=clock).open()
    goodput.note_compile(2.0)                # module-level forwarder
    led.close()
    assert r.counter("goodput.compile_seconds_total").get(
        component="test") == pytest.approx(2.0)
    goodput.note_compile(9.0)                # closed again: dropped
    assert r.counter("goodput.compile_seconds_total").get(
        component="test") == pytest.approx(2.0)


def test_open_ledger_is_none_without_session():
    assert goodput.open_ledger("test") is None
    with goodput.maybe_bucket(None, "device"):
        pass                                 # the zero-cost path


# -- roofline: peaks, kernel costs, derived gauges ------------------------------

def test_kernel_cost_registry_overrides_zero_flop_default():
    """The Pallas custom-call default is ZERO bytes to XLA; the registered
    model is what every consumer resolves instead."""
    assert "decode_attention" in roofline.registered_kernels()
    assert "paged_decode_attention" in roofline.registered_kernels()
    got = roofline.kernel_cost("decode_attention", batch=2, read=128,
                               n_heads=4, d_head=8, layers=3, kv_dtype=None,
                               itemsize=2)
    assert got == 2.0 * 2 * 128 * (4 * 8 * 2) * 3      # k+v rows stream once
    int8 = roofline.kernel_cost("decode_attention", batch=2, read=128,
                                n_heads=4, d_head=8, layers=3,
                                kv_dtype="int8", itemsize=2)
    assert int8 == 2.0 * 2 * 128 * (4 * (8 + 4)) * 3   # 1 B/elt + f32 scale
    assert roofline.kernel_cost("no_such_kernel", batch=1) is None


def test_account_extra_bytes_reaches_device_counter():
    r = obs.MetricsRegistry()
    roofline.account(None, extra_bytes=1024.0, registry=r, now=0.0)
    assert r.counter("fluid.device_bytes_total").get() == 1024.0
    assert r.counter("fluid.device_flops_total").get() == 0.0


def test_derived_gauges_from_counter_deltas(monkeypatch):
    monkeypatch.setenv("PADDLE_TPU_PEAK_TFLOPS", "1")      # 1e12 FLOP/s
    monkeypatch.setenv("PADDLE_TPU_PEAK_HBM_GBPS", "1")    # 1e9 B/s
    r = obs.MetricsRegistry()
    cost = roofline.Cost(flops=5e11, bytes=5e8)
    roofline.account(cost, registry=r, now=0.0)            # baseline
    roofline.account(cost, registry=r, now=1.0)            # 1 s window
    assert r.gauge("roofline.mfu").get() == pytest.approx(0.5)
    assert r.gauge("roofline.hbm_bw_util").get() == pytest.approx(0.5)


def test_gauges_never_set_when_peak_unknown(monkeypatch):
    monkeypatch.delenv("PADDLE_TPU_PEAK_TFLOPS", raising=False)
    monkeypatch.delenv("PADDLE_TPU_PEAK_HBM_GBPS", raising=False)
    if jax.devices()[0].device_kind != "cpu":
        pytest.skip("on-TPU: peaks are known")
    r = obs.MetricsRegistry()
    roofline.account(roofline.Cost(flops=1e9, bytes=1e6), registry=r,
                     now=0.0)
    roofline.account(roofline.Cost(flops=1e9, bytes=1e6), registry=r,
                     now=1.0)
    names = {s["name"] for s in r.collect()}
    # absence, not a fabricated zero: a dashboard reads null off-TPU
    assert "roofline.mfu" not in names
    assert "roofline.hbm_bw_util" not in names
    assert "fluid.device_flops_total" in names


def test_cost_instrumented_jit_ledger_and_accounting():
    r = obs.MetricsRegistry()
    wrapped = roofline.instrument(lambda x: x @ x, "test.step",
                                  extra_bytes=lambda x: 1000.0)
    x = jnp.ones((32, 32), jnp.float32)
    with obs.ObsSession(registry=r).installed():
        y = wrapped(x)
        np.testing.assert_allclose(np.asarray(y), np.asarray(x @ x))
        wrapped(x)                            # same signature: one entry
        wrapped(jnp.ones((16, 16), jnp.float32))
    assert len(wrapped.ledger) == 2           # one executable per shape
    cost = wrapped.cost_of(x)
    assert cost is not None and cost.flops and cost.flops > 0
    assert r.counter("fluid.device_flops_total").get() >= 2 * cost.flops
    # the kernel-modeled extra bytes ride every accounted dispatch
    assert r.counter("fluid.device_bytes_total").get() >= 3 * 1000.0


def test_note_kernel_bytes_eager_vs_collected():
    """Outside a trace collector a launch site counts its own bytes (one
    call == one dispatch); inside one, the collector absorbs them and the
    owner re-emits per dispatch."""
    r = obs.MetricsRegistry()
    with obs.ObsSession(registry=r).installed():
        roofline.note_kernel_bytes("fake_kernel", 64.0)
        assert r.counter("kernels.bytes_total").get(
            kernel="fake_kernel") == 64.0
        with roofline.collect_kernel_bytes() as col:
            assert roofline.record_kernel_bytes("fake_kernel", 10.0)
            roofline.note_kernel_bytes("fake_kernel", 5.0)
        assert col.per_kernel == {"fake_kernel": 15.0}
        # the site did NOT count while collected
        assert r.counter("kernels.bytes_total").get(
            kernel="fake_kernel") == 64.0
    assert not roofline.record_kernel_bytes("fake_kernel", 1.0)


def test_trace_collected_kernel_bytes_count_per_dispatch():
    """A launch site runs once per TRACE; the instrumented jit re-emits
    its collected bytes once per DISPATCH — per-trace counting would
    undercount a run by the step count (the fused-RNN semantics bug)."""
    r = obs.MetricsRegistry()

    def step(x):
        roofline.note_kernel_bytes("fake_kernel", 256.0)  # trace-time site
        return x * 2.0

    wrapped = roofline.instrument(step, "test.fake")
    x = jnp.ones((4,), jnp.float32)
    with obs.ObsSession(registry=r).installed():
        for _ in range(3):
            wrapped(x)
    assert r.counter("kernels.bytes_total").get(
        kernel="fake_kernel") == 3 * 256.0
    assert r.counter("fluid.device_bytes_total").get() >= 3 * 256.0


def test_executor_reemits_collected_kernel_bytes_per_run(monkeypatch):
    """The fluid Executor collects note_kernel_bytes sites during its AOT
    trace and re-emits them on every run() of the cached executable."""
    from paddle_tpu.fluid.registry import OpRegistry
    real = OpRegistry.get("relu")

    def fake(ins, attrs):
        roofline.note_kernel_bytes("fake_kernel", 128.0)
        return real(ins, attrs)

    monkeypatch.setitem(OpRegistry._ops, "relu", fake)
    fluid.reset_default_programs()
    x = fluid.layers.data("x", shape=(4,))
    y = fluid.layers.relu(x)
    exe = fluid.Executor(scope=fluid.Scope())
    r = obs.MetricsRegistry()
    xs = np.ones((2, 4), np.float32)
    with obs.ObsSession(registry=r).installed():
        for _ in range(3):
            out, = exe.run(feed={"x": xs}, fetch_list=[y])
    np.testing.assert_allclose(out, xs)
    assert r.counter("kernels.bytes_total").get(
        kernel="fake_kernel") == 3 * 128.0
    assert r.counter("fluid.device_bytes_total").get() >= 3 * 128.0


def test_cost_failure_warns_once_and_counts():
    r = obs.MetricsRegistry()
    roofline._warned_cost_failure = False
    try:
        with obs.ObsSession(registry=r).installed():
            with pytest.warns(RuntimeWarning, match="cost analysis failed"):
                from benchmarks.mfu import step_flops
                assert step_flops(lambda: (_ for _ in ()).throw(
                    ValueError("boom"))) is None
            # second failure: counted, NOT warned again
            assert step_flops("not even callable") is None
    finally:
        roofline._warned_cost_failure = False
    assert r.counter("roofline.cost_analysis_failures_total").get() == 2


# -- xplane: parse -> attribute -> merge ----------------------------------------

def test_xplane_fixture_round_trip_parse():
    space = xp.read_xspace(FIXTURE)
    names = [p["name"] for p in space["planes"]]
    assert names == ["/device:TPU:0", "/host:CPU"]
    dev = xp.device_planes(space)
    assert [p["name"] for p in dev] == ["/device:TPU:0"]
    evs = xp.plane_events(dev[0])
    assert {e["name"] for e in evs} >= {"fusion.7/b0_op3_mul.1",
                                        "custom-call.2/b1_op0_lstm_fused",
                                        "copy.3"}
    # integer-ns timestamps: adjacent events must not mis-nest
    mul = [e for e in evs if "b0_op3" in e["name"]]
    assert mul[0]["dur_ns"] == 400_000 and mul[1]["dur_ns"] == 200_000


def test_xplane_site_attribution_and_op_totals():
    rows = xp.op_totals(xp.read_xspace(FIXTURE))
    by_op = {r["op"]: r for r in rows}
    mul = by_op["fusion.7/b0_op3_mul.1"]
    assert mul["site"] == "block 0, op #3 (mul)"
    assert mul["count"] == 2 and mul["self_ns"] == 600_000
    cc = by_op["custom-call.2/b1_op0_lstm_fused"]
    assert cc["site"] == "block 1, op #0 (lstm_fused)"
    assert cc["self_ns"] == 250_000           # back-to-back, no nesting
    assert by_op["copy.3"]["site"] is None    # unstamped op
    # the "XLA Modules" envelope line and the host plane must not count
    assert "jit_train_step" not in by_op
    assert "PjitFunction(train_step)" not in by_op
    # rows sort by self time descending — the profile CLI's top-k order
    assert rows[0]["op"] == "fusion.7/b0_op3_mul.1"
    report = xp.top_ops_report(xp.read_xspace(FIXTURE), topk=5, steps=2)
    assert "block 0, op #3 (mul)" in report
    assert "self ms/step" in report


def test_xplane_chrome_merge_round_trip():
    clock = [0.0]

    def c():
        clock[0] += 0.01
        return clock[0]

    r = obs.MetricsRegistry()
    with obs.ObsSession(registry=r, clock=c).installed() as s:
        with obs.span("trainer.step"):
            obs.count("trainer.steps_total")
    host = s.dump()
    dev = xp.xplane_dump(xp.read_xspace(FIXTURE),
                         anchor_unix=host["meta"].get("clock_origin_unix"))
    assert dev["meta"]["processes"] == {str(xp.DEVICE_PID_BASE):
                                       "/device:TPU:0"}
    tr = obs.chrome_trace(obs.merge_dumps([host, dev]))
    evs = tr["traceEvents"] if isinstance(tr, dict) else tr
    names = {e.get("name") for e in evs}
    assert "trainer.step" in names            # host span lane survives
    assert any(n and "b0_op3_mul" in n for n in names)   # device op lane
    site_args = {e["args"].get("site") for e in evs
                 if e.get("args") and e["args"].get("site")}
    assert "block 0, op #3 (mul)" in site_args
    lanes = {e["args"]["name"] for e in evs
             if e.get("ph") == "M" and e.get("name") == "process_name"}
    assert "/device:TPU:0" in lanes


def test_xplane_encoder_decoder_inverse():
    planes = [{"name": "/device:TPU:1",
               "lines": [{"name": "XLA Ops", "timestamp_ns": 123,
                          "events": [{"name": "dot.1", "offset_ps": 5000,
                                      "duration_ps": 2000}]}]}]
    space = xp.read_xspace(xp.encode_xspace(planes))
    assert space["planes"][0]["name"] == "/device:TPU:1"
    ev = space["planes"][0]["lines"][0]["events"][0]
    assert ev["name"] == "dot.1"
    assert ev["offset_ps"] == 5000 and ev["duration_ps"] == 2000


# -- L007 catalogue drift -------------------------------------------------------

def test_L007_tree_is_clean():
    """The shipped tree: every emit site catalogued, no orphans — run in
    the suite so drift fails CI, not a dashboard."""
    assert analysis.lint_catalogue_drift() == []


def test_L007_flags_both_directions(tmp_path):
    (tmp_path / "mod.py").write_text(
        "def f(obs, reg):\n"
        "    obs.count('bogus.thing_total')\n"
        "    'a string'.count('x')\n"                 # not metric-shaped
        "    reg.counter(f'family.{x}_seconds_total')\n")
    catalogue = {"known.orphan_total": ("counter", "never emitted"),
                 "family.a_seconds_total": ("counter", "f-string emitted")}
    diags = analysis.lint_catalogue_drift(root=str(tmp_path),
                                          catalogue=catalogue)
    assert {d.code for d in diags} == {"L007"}
    by_var = {d.var for d in diags}
    assert "bogus.thing_total" in by_var       # undeclared emit site
    assert "known.orphan_total" in by_var      # orphaned entry
    # the f-string family anchors its entry; str.count noise is ignored
    assert "family.a_seconds_total" not in by_var
    assert "x" not in by_var


# -- acceptance: 2-pass CPU training run ----------------------------------------

def test_e2e_two_pass_train_derives_goodput_and_flops(tmp_path):
    """ISSUE 9 acceptance: after a 2-pass CPU training run with obs
    installed, `obs summary` shows a non-null goodput ratio and
    fluid.device_flops_total > 0 — chip utilization as a byproduct of
    just running."""
    import paddle_tpu.v2 as paddle
    fluid.reset_default_programs()
    x = paddle.layer.data("x", paddle.data_type.dense_vector(4))
    y = paddle.layer.data("y", paddle.data_type.dense_vector(1))
    pred = paddle.layer.fc(x, 1)
    cost = paddle.layer.square_error_cost(pred, y)
    rs = np.random.RandomState(0)
    rows = [[(rs.rand(4).astype(np.float32), rs.rand(1).astype(np.float32))
             for _ in range(8)] for _ in range(3)]

    r = obs.MetricsRegistry()
    with obs.ObsSession(registry=r).installed() as s:
        trainer = paddle.SGD(cost, paddle.optimizer.SGD(0.05))
        trainer.train(lambda: iter(rows), num_passes=2, feeding=[x, y])
        dump = s.dump()
    assert r.counter("fluid.device_flops_total").get() > 0
    ratio = r.gauge("goodput.ratio").get(component="v2_sgd")
    assert ratio is not None and 0.0 < ratio <= 1.0
    assert r.counter("goodput.device_seconds_total").get(
        component="v2_sgd") > 0
    # the wall second is counted once: every bucket is a timed sub-region
    # of the window, so at close sum(buckets) == wall and the final ratio
    # gauge is exactly device / sum
    device = r.counter("goodput.device_seconds_total").get(
        component="v2_sgd")
    total = sum(
        r.counter(f"goodput.{b}_seconds_total").get(component="v2_sgd")
        for b in goodput.BUCKETS)
    assert ratio == pytest.approx(device / total, rel=1e-3)
    rep = obs.summary(dump)
    assert "goodput.ratio" in rep
    assert "fluid.device_flops_total" in rep
