"""Distributed-tracing chaos e2e (ISSUE 4 acceptance): trainer and master
run as REAL separate processes (pattern of tests/test_multiprocess_dp.py),
the faults plane kills the worker mid-pass, and the surviving artifacts —
the worker's crash flight-recorder dump + the master's session dump —
merge into one Chrome trace with spans from two pids where the master's
server-side dispatch span is parented (via wire context) under the
worker's ``rpc.call`` span.
"""

import json
import os
import subprocess
import sys

import pytest

from paddle_tpu import cli, obs
from paddle_tpu.runtime import native_available

pytestmark = pytest.mark.obs

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
NODE = os.path.join(REPO, "tests", "obs_cluster_node.py")


@pytest.mark.chaos
def test_worker_crash_leaves_stitchable_cross_process_trace(tmp_path):
    if not native_available():
        pytest.skip("native task master not built")
    master_out = str(tmp_path / "master.jsonl")
    worker_out = str(tmp_path / "worker.jsonl")
    done = str(tmp_path / "done")
    env = {k: v for k, v in os.environ.items() if k != "XLA_FLAGS"}
    env["JAX_PLATFORMS"] = "cpu"
    env["PADDLE_TPU_TRACE_ID"] = "e2e0feedfacef00d"

    master = subprocess.Popen(
        [sys.executable, NODE, "master", master_out, done],
        env=env, cwd=REPO, stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT, text=True)
    worker = None
    try:
        line = master.stdout.readline().strip()
        assert line.startswith("ADDR "), line
        _, host, port = line.split()

        worker = subprocess.Popen(
            [sys.executable, NODE, "worker", worker_out, host, port],
            env=env, cwd=REPO, stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT, text=True)
        wlog, _ = worker.communicate(timeout=240)
        # the chaos worked: the worker DIED on the injected fault
        assert worker.returncode != 0, wlog
        assert "injected fault at step.grad" in wlog, wlog

        open(done, "w").close()
        mlog, _ = master.communicate(timeout=120)
        assert master.returncode == 0, mlog
    finally:
        for p in (worker, master):
            if p is not None and p.poll() is None:
                p.kill()

    # the worker left a flight dump (no clean save ever ran)
    wdump = obs.read_jsonl(worker_out)
    assert wdump["meta"]["flight"] is True
    assert wdump["meta"]["reason"].startswith(("fault:step.grad",
                                               "exception:"))
    assert wdump["meta"]["trace_id"] == "e2e0feedfacef00d"
    mdump = obs.read_jsonl(master_out)
    assert not mdump["meta"].get("flight")

    # ---- the acceptance assertions, on the merged view -------------------
    merged = obs.merge_dumps([wdump, mdump])
    spans = [e for e in merged["events"] if e["kind"] == "span"]
    pids = {e["pid"] for e in spans}
    assert len(pids) >= 2, pids
    by_key = {(e["pid"], e["id"]): e for e in spans}
    wpid, mpid = wdump["meta"]["pid"], mdump["meta"]["pid"]
    stitched = []
    for e in spans:
        r = e.get("remote")
        if not r or e["pid"] != mpid:
            continue
        client = by_key.get((r["pid"], r["span"]))
        if client is not None:
            stitched.append((e, client))
    # at least one server span is parented under a worker rpc.call span
    # from a DIFFERENT pid
    assert any(e["name"] == "master.dispatch"
               and c["name"] == "rpc.call" and c["pid"] == wpid
               for e, c in stitched), [(e["name"], c["name"])
                                       for e, c in stitched]

    # ---- and the CLI converts the pair into one stitched Chrome trace ----
    trace_path = str(tmp_path / "trace.json")
    assert cli.main(["obs", "export", "--input", worker_out,
                     "--input", master_out, "--format", "chrome",
                     "--output", trace_path]) == 0
    trace = json.load(open(trace_path))
    evs = trace["traceEvents"]
    xs_pids = {e["pid"] for e in evs if e["ph"] == "X"}
    assert len(xs_pids) >= 2
    lanes = {e["pid"]: e["args"]["name"] for e in evs
             if e["ph"] == "M" and e["name"] == "process_name"}
    assert lanes[wpid] == "worker-0" and lanes[mpid] == "master"
    # the cross-process flow arrow both starts and finishes
    assert any(e["ph"] == "s" for e in evs)
    assert any(e["ph"] == "f" for e in evs)
    # merged metrics keep per-process series distinct
    workers = {m["labels"].get("worker") for m in merged["metrics"]}
    assert {"worker-0", "master"} <= workers
