"""Per-request timelines & SLO attribution (obs/requests.py, ISSUE 19):
the always-on ledger of phase records behind ``obs.req_phase``, re-route
leg stitching onto one unix-time axis, the router/master RequestStore
with slowest-K exemplars decorating burn-rate alert transitions, the
``/requests`` endpoint, the ``paddle_tpu obs trace`` CLI — and the two
acceptance bars: the reconciliation invariant (phase-duration sums equal
observed TTFT + decode wall on a shared fake clock) and the
zero-cost-when-uninstalled overhead budget.
"""

import json
import urllib.request

import numpy as np
import pytest

from paddle_tpu import cli, obs
from paddle_tpu.obs.requests import (ATTRIBUTED, RequestLedger, RequestStore,
                                     base_key, format_timeline, group_legs,
                                     leg_of, stitch)

pytestmark = pytest.mark.obs


def _clk(start=0.0):
    t = [start]
    return (lambda: t[0]), t


def _hist_sample(name, count, total, buckets, labels=None):
    return {"type": "histogram", "name": name, "count": count,
            "sum": total, "buckets": buckets, "labels": labels or {},
            "max": 0.0}


def _leg(key, recorder, origin, events, worker=None):
    tl = {"key": key, "recorder": recorder, "origin": origin,
          "events": events, "done": any(e["phase"] in ("done", "cancel")
                                        for e in events),
          "updated": events[-1]["t"] if events else 0.0}
    if worker is not None:
        tl["worker"] = worker
    return tl


def _slow_ship_legs(key="req-1", ship_s=0.30):
    """One stitched-ready request whose TTFT is dominated by the ship
    hop: router point records + a prefill leg with explicit durs + the
    decode leg that adopted and finished the stream."""
    router = _leg(key, "router", 1000.0, [
        {"phase": "admitted", "t": 0.000, "dur": 0.0},
        {"phase": "route", "t": 0.001, "dur": 0.0, "worker": "d0"},
    ], worker="router")
    prefill = _leg(key, "p0", 1000.0, [
        {"phase": "prefill", "t": 0.010, "dur": 0.008},
        {"phase": "ship", "t": 0.010 + ship_s, "dur": ship_s},
    ], worker="p0")
    decode = _leg(key, "d0", 1000.0, [
        {"phase": "queued", "t": 0.012, "dur": 0.002},
        {"phase": "scheduled", "t": 0.012 + ship_s, "dur": 0.001},
        {"phase": "adopt", "t": 0.015 + ship_s, "dur": 0.003},
        {"phase": "first_token", "t": 0.016 + ship_s, "dur": 0.0},
        {"phase": "decode", "t": 0.066 + ship_s, "dur": 0.05, "n": 8},
        {"phase": "done", "t": 0.066 + ship_s, "dur": 0.0,
         "reason": "length", "tokens": 9},
    ], worker="d0")
    return [router, prefill, decode]


# -- key helpers --------------------------------------------------------------

def test_base_key_and_leg_of():
    assert base_key("k") == "k"
    assert base_key("k#r1") == "k"
    assert base_key("k#r12") == "k"
    assert leg_of("k") == 0
    assert leg_of("k#r3") == 3
    # a malformed suffix degrades to leg 0, never raises (wire data)
    assert leg_of("k#rx") == 0


# -- the per-process ledger ---------------------------------------------------

def test_ledger_telescopes_durations_and_observes_attributed_phases():
    clock, t = _clk()
    reg = obs.MetricsRegistry()
    with obs.ObsSession(registry=reg).installed():
        led = RequestLedger(clock=clock, ident="w0")
        led.phase("k1", "admitted")
        t[0] = 0.004
        led.phase("k1", "queued")
        t[0] = 0.014
        led.phase("k1", "prefill")
        led.phase("k1", "first_token", ttft_s=0.014)
        t[0] = 0.034
        led.phase("k1", "decode", n=4)
        t[0] = 0.035
        led.phase("k1", "done", reason="length")
    tl = led.get("k1")
    assert tl["recorder"] == "w0" and tl["done"]
    durs = {e["phase"]: e["dur"] for e in tl["events"]}
    assert durs["admitted"] == 0.0            # first event: nothing before
    assert durs["queued"] == pytest.approx(0.004)
    assert durs["prefill"] == pytest.approx(0.010)
    assert durs["first_token"] == 0.0         # same instant as prefill end
    assert durs["decode"] == pytest.approx(0.020)
    # telescoping is exact: the ledger's total is the wall span
    assert sum(durs.values()) == pytest.approx(0.035)
    # only ATTRIBUTED phases with dur > 0 feed the SLO histogram
    sums = {s["labels"]["phase"]: s["sum"] for s in reg.collect()
            if s["name"] == "serving.phase_seconds"}
    assert sums == {"queued": pytest.approx(0.004),
                    "prefill": pytest.approx(0.010),
                    "decode": pytest.approx(0.020)}


def test_ledger_folds_decode_segments():
    clock, t = _clk()
    led = RequestLedger(clock=clock)
    led.phase("k", "first_token")
    for i in range(50):
        t[0] += 0.01
        led.phase("k", "decode", n=2)
    tl = led.get("k")
    # 50 segments, ONE event: a long generation stays O(1) in the list
    decode = [e for e in tl["events"] if e["phase"] == "decode"]
    assert len(decode) == 1
    assert decode[0]["n"] == 100
    assert decode[0]["folds"] == 49
    assert decode[0]["dur"] == pytest.approx(0.5)


def test_ledger_bounds_events_and_timelines():
    clock, t = _clk()
    led = RequestLedger(cap=2, events_cap=4, clock=clock)
    for i in range(6):
        t[0] += 1.0
        led.phase("k", "queued", slot=i)      # not foldable: distinct events
    tl = led.get("k")
    assert len(tl["events"]) == 4 and tl["overflow"] == 2
    led.phase("k2", "admitted")
    led.phase("k3", "admitted")               # ring cap 2: k evicted
    assert led.get("k") is None and led.dropped == 1
    assert len(led) == 2
    # export: most-recent n, oldest-update first; forget drops one
    led.phase("k2", "done")
    assert [tl["key"] for tl in led.export()] == ["k3", "k2"]
    assert [tl["key"] for tl in led.export(n=1)] == ["k2"]
    assert led.forget("k3") and not led.forget("k3")


def test_ledger_extra_payloads_are_bounded():
    led = RequestLedger(clock=_clk()[0])
    led.phase("k", "admitted", tenant="t" * 500, a=1, b=2, c=3, d=4,
              e=5, f=6, g=7)
    ev = led.get("k")["events"][0]
    extras = {k: v for k, v in ev.items()
              if k not in ("phase", "t", "dur")}
    assert len(extras) <= 6                   # _MAX_EXTRA
    assert all(len(v) <= 80 for v in extras.values()
               if isinstance(v, str))         # _MAX_EXTRA_STR


# -- stitching ----------------------------------------------------------------

def test_stitch_merges_reroute_legs_without_double_counting_ttft():
    key = "req-7"
    leg0 = _leg(key, "d0", 1000.0, [
        {"phase": "queued", "t": 0.00, "dur": 0.0},
        {"phase": "prefill", "t": 0.02, "dur": 0.02},
        {"phase": "first_token", "t": 0.02, "dur": 0.0},
        {"phase": "decode", "t": 0.10, "dur": 0.08, "n": 4},
    ], worker="d0")
    router = _leg(key, "router", 1000.0, [
        {"phase": "admitted", "t": 0.00, "dur": 0.0},
        {"phase": "reroute", "t": 0.12, "dur": 0.0, "why": "evicted"},
    ], worker="router")
    # the re-routed remainder: a DERIVED key on the survivor, whose
    # re-prefill emits its own (resumed) first token
    leg1 = _leg(f"{key}#r1", "d1", 1000.0, [
        {"phase": "queued", "t": 0.13, "dur": 0.0},
        {"phase": "prefill", "t": 0.16, "dur": 0.03},
        {"phase": "first_token", "t": 0.16, "dur": 0.0},
        {"phase": "decode", "t": 0.26, "dur": 0.10, "n": 5},
        {"phase": "done", "t": 0.26, "dur": 0.0, "reason": "length"},
    ], worker="d1")
    st = stitch([leg1, router, leg0])         # order must not matter
    assert st["key"] == key and st["done"]
    assert st["legs"] == [0, 1] and st["reroutes"] == 1
    assert st["workers"] == ["d0", "d1", "router"]
    # exactly one canonical first_token; the survivor's is flagged
    fts = [e for e in st["events"] if e["phase"] == "first_token"]
    assert len(fts) == 2
    assert [bool(e.get("resumed")) for e in fts] == [False, True]
    assert st["ttft_s"] == pytest.approx(0.02)
    assert st["wall_s"] == pytest.approx(0.26)
    # breakdown sums ATTRIBUTED phases across BOTH legs
    assert st["breakdown"]["prefill"] == pytest.approx(0.05)
    assert st["breakdown"]["decode"] == pytest.approx(0.18)
    assert st["dominant"] == "decode"
    assert set(st["breakdown"]) <= set(ATTRIBUTED)
    assert st["total_s"] == pytest.approx(sum(
        e["dur"] for e in st["events"]))
    # events came out time-sorted with leg/worker stamps
    ts = [e["t_unix"] for e in st["events"]]
    assert ts == sorted(ts)
    assert {e["leg"] for e in st["events"]} == {0, 1}
    assert stitch([]) is None


def test_group_legs_dedups_recorder_key_pairs():
    a1 = _leg("k", "d0", 0.0, [{"phase": "queued", "t": 0.0, "dur": 0.0}])
    a2 = _leg("k", "d0", 0.0, [{"phase": "queued", "t": 0.0, "dur": 0.0},
                               {"phase": "done", "t": 1.0, "dur": 1.0}])
    b = _leg("k#r1", "d1", 0.0, [{"phase": "queued", "t": 2.0, "dur": 0.0}])
    other = _leg("x", "d0", 0.0, [{"phase": "done", "t": 0.0, "dur": 0.0}])
    groups = group_legs([a1, a2, b, other])
    assert sorted(groups) == ["k", "x"]
    assert len(groups["k"]) == 2              # the a-pair deduped
    # the copy with MORE events won (scrape + loopback race)
    dedup = next(tl for tl in groups["k"] if tl["key"] == "k")
    assert len(dedup["events"]) == 2


def test_format_timeline_renders_head_breakdown_and_rows():
    st = stitch(_slow_ship_legs())
    out = format_timeline(st)
    head = out.splitlines()[0]
    assert head.startswith("request req-1  done  legs=1")
    assert "ttft=" in head and "dominant=ship" in head
    assert "breakdown:" in out and "ship=300.0ms" in out
    assert "first_token" in out and "leg0" in out
    # a re-routed stream renders the resumed marker on the later leg
    st2 = stitch([_leg("k", "d0", 0.0, [
        {"phase": "first_token", "t": 0.0, "dur": 0.0}]),
        _leg("k#r1", "d1", 0.0, [
            {"phase": "first_token", "t": 1.0, "dur": 0.0}])])
    assert "resumed" in format_timeline(st2)


# -- the router/master store --------------------------------------------------

def test_request_store_replaces_legs_and_reaps_only_completed():
    clock, t = _clk()
    store = RequestStore(cap=2, clock=clock)
    legs = _slow_ship_legs("done-req")
    assert store.push("d0", [legs[2]]) == 1
    # same (recorder, key) pushed again REPLACES, never duplicates
    assert store.push("d0", [legs[2]]) == 1
    assert store.push("p0", [legs[1]]) == 1
    assert store.push("router", [legs[0]]) == 1
    st = store.get("done-req")
    assert st["done"] and len(st["events"]) == len(stitch(legs)["events"])
    # an in-flight request holds a dead worker's legs for stitching...
    inflight = _leg("live-req", "d9", 0.0, [
        {"phase": "queued", "t": 0.0, "dur": 0.0},
        {"phase": "first_token", "t": 0.1, "dur": 0.0}])
    store.push("d9", [inflight])
    assert store.forget_worker("d9") == 0
    assert store.get("live-req") is not None
    # ...while a COMPLETED request's legs from that worker are reaped
    assert store.forget_worker("d0") >= 1
    st = store.get("done-req")
    assert st is None or "d0" not in st["workers"]
    # ring cap: a third base evicts the oldest
    store.push("d1", [_leg("third", "d1", 0.0,
                           [{"phase": "queued", "t": 0.0, "dur": 0.0}])])
    assert len(store) <= 2 and store.dropped >= 1
    # wire tolerance: garbage never raises, never lands
    assert store.push("d1", [None, 3, {"key": ""}, {"key": "x"},
                             {"key": "y", "events": "nope"}]) == 0


def test_request_store_exemplars_slowest_k_windowed():
    clock, t = _clk()
    store = RequestStore(exemplar_k=2, window_s=10.0, clock=clock)
    reg = obs.MetricsRegistry()
    with obs.ObsSession(registry=reg).installed():
        for i, ship_s in enumerate((0.05, 0.40, 0.20)):
            store.push("d0", _slow_ship_legs(f"r{i}", ship_s=ship_s))
    ex = store.exemplars()
    # slowest-K by TTFT, slowest first, bounded at k=2
    assert [e["key"] for e in ex] == ["r1", "r2"]
    assert all(e["dominant"] == "ship" for e in ex)
    assert all("events" not in e for e in ex)  # compact alert form
    assert all("events" in e for e in store.exemplars(full=True))
    # the capture is counted, labeled by dominant phase (catalogue L005)
    assert sum(s["value"] for s in reg.collect()
               if s["name"] == "serving.exemplars_total"
               and s["labels"].get("phase") == "ship") == 3
    # exemplars age out of the alert window
    t[0] = 11.0
    assert store.exemplars() == []


def test_burn_alert_transition_names_ship_dominant_exemplar():
    """THE attribution bar: a fired serving SLO burn transition carries
    the slowest stitched timelines, so ``/alerts`` answers 'the TTFT
    burn is driven by ship' without a second query."""
    from paddle_tpu.obs.aggregate import ClusterAggregator
    from paddle_tpu.obs.alerts import AlertRule
    clock, t = _clk()
    rule = AlertRule("serving_ttft_slo_burn", "serving.ttft_seconds",
                     kind="burn_rate", slo_le=1.0, budget=0.1,
                     short_s=60.0, long_s=300.0, for_windows=1)
    agg = ClusterAggregator(clock=clock, rules=[rule],
                            eval_interval_s=0.0)
    # the slow-ship request completed -> noted as an exemplar
    agg.push_requests("d0", _slow_ship_legs(ship_s=0.9))

    def push_hist(count, good):
        agg.push("serving", [_hist_sample(
            "serving.ttft_seconds", count, count * 0.5,
            [[0.5, good // 2], [1.0, good], ["+Inf", count]])])

    n, fired = 0, None
    for i in range(7):                         # healthy: no transition
        t[0] += 50.0
        n += 100
        push_hist(n, int(n * 0.98))
        assert not [ev for ev in agg.alerts.evaluate(t[0])
                    if ev["args"].get("state") == "fired"]
    good_frozen = int(n * 0.98)
    for i in range(7):                         # regression: all-new bad
        t[0] += 50.0
        n += 100
        push_hist(n, good_frozen)
        agg.evaluate(t[0])
        fired = [ev for ev in agg.alerts.recent_events()
                 if ev["args"].get("state") == "fired"]
        if fired:
            break
    assert fired, "burn rule never fired under sustained SLO misses"
    ex = fired[-1]["args"]["exemplars"]
    assert ex and ex[0]["dominant"] == "ship"
    assert ex[0]["breakdown"]["ship"] == pytest.approx(0.9)
    assert "events" not in ex[0]               # compact, bounded payload


# -- reconciliation (acceptance invariant) ------------------------------------

def test_engine_timeline_reconciles_with_observed_ttft(
        paged_model_and_params):
    """One fake clock drives BOTH the engine and the ledger: the
    stitched breakdown must sum exactly to the observed TTFT + decode
    wall — the reconciliation invariant that makes the phase histograms
    trustworthy attribution rather than vibes."""
    from paddle_tpu.serving import ServingEngine
    model, params = paged_model_and_params
    clock, t = _clk()
    reg = obs.MetricsRegistry()
    with obs.ObsSession(registry=reg).installed():
        led = RequestLedger(clock=clock, ident="eng").install()
        try:
            eng = ServingEngine(model, params, slots=2, segment=8,
                                page_block=8, cache_bucket=32, clock=clock)
            rs = np.random.RandomState(5)
            rid = eng.submit(rs.randint(0, 97, 9), 12, submit_key="k-rec")
            while not eng.poll(rid)[1]:
                t[0] += 0.01
                eng.step()
            st = stitch([led.get("k-rec")])
        finally:
            led.uninstall()
    assert st["done"]
    phases = [e["phase"] for e in st["events"]]
    assert phases[0] == "admitted" and phases[-1] == "done"
    assert "queued" in phases and "prefill" in phases
    assert "first_token" in phases and "decode" in phases
    # telescoping is exact on one ledger: every second of wall time is
    # in exactly one dur — total == wall, and the ATTRIBUTED breakdown
    # covers it (admitted/first_token/done are instants on this clock).
    # Tolerance: wall_s/ttft_s live on the unix axis (origin + t), where
    # float64 resolution at ~1.7e9 is ~1e-7 s; the dur sums are exact.
    assert st["total_s"] == pytest.approx(st["wall_s"], abs=1e-6)
    assert sum(st["breakdown"].values()) == pytest.approx(st["wall_s"],
                                                          abs=1e-6)
    # the stitched TTFT is the engine's own observation, to the tick
    ttft = next(s for s in reg.collect()
                if s["name"] == "serving.ttft_seconds")
    assert st["ttft_s"] == pytest.approx(ttft["sum"], abs=1e-6)
    assert st["ttft_s"] == pytest.approx(
        st["breakdown"]["queued"] + st["breakdown"]["prefill"], abs=1e-6)
    # and the phase histograms the alerts read reconcile with the ledger
    for s in reg.collect():
        if s["name"] == "serving.phase_seconds":
            ph = s["labels"]["phase"]
            assert s["sum"] == pytest.approx(st["breakdown"][ph], abs=1e-9)


# -- surfacing: /requests endpoint, session dump, CLI -------------------------

def test_http_requests_endpoint_serves_stitched_timelines():
    from paddle_tpu.obs.aggregate import ObsHttpServer
    legs = _slow_ship_legs("http-req")
    provider = lambda: {"requests": legs,                 # noqa: E731
                        "exemplars": [{"key": "http-req",
                                       "dominant": "ship"}]}
    srv = ObsHttpServer(provider).start()
    host, port = srv.address
    try:
        with urllib.request.urlopen(
                f"http://{host}:{port}/requests", timeout=10) as resp:
            assert resp.status == 200
            body = json.loads(resp.read().decode())
    finally:
        srv.stop()
    assert body["exemplars"][0]["dominant"] == "ship"
    reqs = body["requests"]
    assert [r["key"] for r in reqs] == ["http-req"]
    assert reqs[0]["dominant"] == "ship" and reqs[0]["done"]


def test_session_dump_and_jsonl_roundtrip_carry_requests(tmp_path):
    reg = obs.MetricsRegistry()
    s = obs.ObsSession(registry=reg)
    with s.installed():
        led = obs.ensure_request_ledger(ident="w0")
        assert led is not None and obs.request_ledger() is led
        obs.req_phase("k1", "admitted", tenant="t0")
        obs.req_phase("k1", "done")
        dump = s.dump()
    assert [tl["key"] for tl in dump["requests"]] == ["k1"]
    p = str(tmp_path / "d.jsonl")
    obs.write_jsonl(p, dump)
    back = obs.read_jsonl(p)
    assert [tl["key"] for tl in back["requests"]] == ["k1"]
    # merge stamps the source worker onto unstamped timelines
    merged = obs.merge_dumps([back], workers=["w0"])
    assert merged["requests"][0]["worker"] == "w0"


def test_cli_obs_trace_prints_stitched_timeline(tmp_path, capsys):
    p = str(tmp_path / "dump.jsonl")
    obs.write_jsonl(p, {"meta": {"process": "router"},
                        "requests": _slow_ship_legs("cli-req")})
    assert cli.main(["obs", "trace", "cli-req", "--input", p]) == 0
    out = capsys.readouterr().out
    assert out.startswith("request cli-req  done")
    assert "dominant=ship" in out and "first_token" in out
    # a leg key resolves to its base request
    assert cli.main(["obs", "trace", "cli-req#r1", "--input", p]) == 0
    # unknown key: structured failure that lists what IS known
    assert cli.main(["obs", "trace", "nope", "--input", p]) == 1
    err = capsys.readouterr().err
    assert "no timeline for 'nope'" in err and "cli-req" in err
    # no sources at all is a usage error
    assert cli.main(["obs", "trace", "k"]) == 2


# -- zero-cost-when-uninstalled (satellite 6) ---------------------------------

def test_req_phase_uninstalled_overhead_budget():
    """Acceptance: the always-on hook costs <= ~5µs/request with the obs
    plane uninstalled (bound is 10x slack over the measured ~0.2µs, same
    discipline as the flight-recorder budget)."""
    import time as _t
    assert obs.request_ledger() is None
    obs.req_phase("k", "decode", n=1)         # no session: pure no-op
    assert obs.request_ledger() is None

    def per_request(n=300):
        t0 = _t.perf_counter()
        for _ in range(n):
            obs.req_phase("k", "decode", n=1)
        return (_t.perf_counter() - t0) / n

    cost = min(per_request() for _ in range(3))
    assert cost < 50e-6, cost
    # a session WITHOUT a ledger stays on the cheap path too, and
    # key=None (no submit_key) records nothing even with one installed
    with obs.ObsSession(registry=obs.MetricsRegistry()).installed():
        obs.req_phase("k", "decode", n=1)     # no ledger installed
        assert obs.request_ledger() is None
        led = obs.ensure_request_ledger(ident="w0")
        obs.req_phase(None, "decode", n=1)
        assert len(led) == 0
