"""Pallas kernel numerics vs the jnp reference path (interpret mode on CPU) —
the per-op equivalence discipline of the MKLDNN tester (SURVEY.md §8.3)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu.ops.pallas_kernels import flash_attention


def _full_attention(q, k, v, causal=False):
    B, T, H, D = q.shape
    s = jnp.einsum("bthd,bshd->bhts", q, k) * (D ** -0.5)
    if causal:
        mask = jnp.tril(jnp.ones((T, T), bool))
        s = jnp.where(mask[None, None], s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhts,bshd->bthd", p, v)


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("T", [32, 48])   # 48 exercises the padded-tail path
def test_flash_attention_matches_reference(causal, T):
    rng = jax.random.PRNGKey(0)
    kq, kk, kv = jax.random.split(rng, 3)
    B, H, D = 2, 2, 16
    q = jax.random.normal(kq, (B, T, H, D))
    k = jax.random.normal(kk, (B, T, H, D))
    v = jax.random.normal(kv, (B, T, H, D))
    ref = _full_attention(q, k, v, causal)
    out = flash_attention(q, k, v, causal=causal, block_q=16, block_k=16,
                          interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-4)


def test_flash_attention_jits_and_grads():
    rng = jax.random.PRNGKey(1)
    q = jax.random.normal(rng, (1, 32, 2, 16))

    def loss(q):
        return jnp.sum(flash_attention(q, q, q, causal=True, block_q=16,
                                       block_k=16, interpret=True))

    g = jax.jit(jax.grad(loss))(q)
    assert np.isfinite(np.asarray(g)).all()


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("T", [32, 48])   # 48 exercises the padded-tail path
def test_flash_backward_kernels_match_reference(causal, T):
    """The Pallas dq / dkv kernels vs autodiff through dense attention —
    the grad-side analog of the MKLDNN equivalence discipline."""
    rng = jax.random.PRNGKey(7)
    kq, kk, kv, kg = jax.random.split(rng, 4)
    B, H, D = 2, 2, 16
    q = jax.random.normal(kq, (B, T, H, D))
    k = jax.random.normal(kk, (B, T, H, D))
    v = jax.random.normal(kv, (B, T, H, D))
    g = jax.random.normal(kg, (B, T, H, D))

    def f(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal=causal, block_q=16,
                                       block_k=16, interpret=True) * g)

    def f_ref(q, k, v):
        return jnp.sum(_full_attention(q, k, v, causal) * g)

    got = jax.grad(f, (0, 1, 2))(q, k, v)
    want = jax.grad(f_ref, (0, 1, 2))(q, k, v)
    for a, b in zip(got, want):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-4)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_attention_kv_lens_matches_masked_reference(causal):
    """Per-sample kv-length masking (the LoD / padded-source path): output
    AND all grads must match dense attention with an explicit key mask, and
    masked keys' dk/dv must be exactly zero."""
    rng = jax.random.PRNGKey(11)
    kq, kk, kv, kg = jax.random.split(rng, 4)
    B, T, S, H, D = 3, 32, 32, 2, 16
    q = jax.random.normal(kq, (B, T, H, D))
    k = jax.random.normal(kk, (B, S, H, D))
    v = jax.random.normal(kv, (B, S, H, D))
    g = jax.random.normal(kg, (B, T, H, D))
    lens = jnp.array([32, 17, 5], jnp.int32)

    def ref(q, k, v):
        s = jnp.einsum("bthd,bshd->bhts", q, k) * (D ** -0.5)
        key_ok = (jnp.arange(S)[None, :] < lens[:, None])[:, None, None, :]
        s = jnp.where(key_ok, s, -1e30)
        if causal:
            mask = jnp.tril(jnp.ones((T, S), bool))
            s = jnp.where(mask[None, None], s, -1e30)
        p = jax.nn.softmax(s, axis=-1)
        return jnp.einsum("bhts,bshd->bthd", p, v)

    def f(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal=causal, kv_lens=lens,
                                       block_q=16, block_k=16,
                                       interpret=True) * g)

    def f_ref(q, k, v):
        return jnp.sum(ref(q, k, v) * g)

    np.testing.assert_allclose(
        np.asarray(flash_attention(q, k, v, causal=causal, kv_lens=lens,
                                   block_q=16, block_k=16, interpret=True)),
        np.asarray(ref(q, k, v)), rtol=2e-4, atol=2e-4)
    got = jax.jit(jax.grad(f, (0, 1, 2)))(q, k, v)
    want = jax.grad(f_ref, (0, 1, 2))(q, k, v)
    for a, b in zip(got, want):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-4)
    _, dk, dv = got
    assert np.all(np.asarray(dk)[1, 17:] == 0)      # masked keys: exact zero
    assert np.all(np.asarray(dv)[2, 5:] == 0)


@pytest.mark.parametrize("dense_route", [True, False])
def test_flash_attention_kv_len_zero_sample_is_zeroed(dense_route):
    """A fully-masked sample (kv_lens == 0) must produce exactly-zero output
    rows and exactly-zero grads — not garbage/NaN — on both the short-seq
    dense route and the Pallas route; other samples must be unaffected."""
    rng = jax.random.PRNGKey(13)
    kq, kk, kv = jax.random.split(rng, 3)
    B, T, S, H, D = 3, 32, 32, 2, 16
    q = jax.random.normal(kq, (B, T, H, D))
    k = jax.random.normal(kk, (B, S, H, D))
    v = jax.random.normal(kv, (B, S, H, D))
    lens = jnp.array([32, 0, 5], jnp.int32)
    blocks = {} if dense_route else dict(block_q=16, block_k=16,
                                         interpret=True)

    def f(q, k, v):
        return flash_attention(q, k, v, kv_lens=lens, **blocks)

    out = np.asarray(f(q, k, v))
    assert np.all(np.isfinite(out))
    assert np.all(out[1] == 0)
    # the other samples match a run without the dead sample in the batch
    ref = np.asarray(flash_attention(q[::2], k[::2], v[::2],
                                     kv_lens=lens[::2], **blocks))
    np.testing.assert_allclose(out[::2], ref, rtol=2e-5, atol=2e-5)

    dq, dk, dv = jax.grad(lambda *a: jnp.sum(f(*a)), (0, 1, 2))(q, k, v)
    for garr in (dq, dk, dv):
        garr = np.asarray(garr)
        assert np.all(np.isfinite(garr))
        assert np.all(garr[1] == 0)


def test_flash_cross_attention_shorter_kv():
    """S != T cross-attention shape with kv_lens (the NMT decoder->encoder
    use): matches the dense reference."""
    rng = jax.random.PRNGKey(13)
    kq, kk, kv = jax.random.split(rng, 3)
    B, T, S, H, D = 2, 48, 32, 2, 16
    q = jax.random.normal(kq, (B, T, H, D))
    k = jax.random.normal(kk, (B, S, H, D))
    v = jax.random.normal(kv, (B, S, H, D))
    lens = jnp.array([32, 9], jnp.int32)
    s = jnp.einsum("bthd,bshd->bhts", q, k) * (D ** -0.5)
    key_ok = (jnp.arange(S)[None, :] < lens[:, None])[:, None, None, :]
    p = jax.nn.softmax(jnp.where(key_ok, s, -1e30), axis=-1)
    ref = jnp.einsum("bhts,bshd->bthd", p, v)
    out = flash_attention(q, k, v, kv_lens=lens, block_q=16, block_k=16,
                          interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-4)


def test_flash_backward_no_dense_scores_in_jaxpr():
    """The [T, T] score matrix must not materialise in HBM in the backward
    jaxpr (the round-1 fallback recomputed dense attention)."""
    T = 64
    q = jnp.zeros((1, T, 1, 16))

    def loss(q):
        return jnp.sum(flash_attention(q, q, q, block_q=16, block_k=16,
                                       interpret=True))

    jaxpr = jax.make_jaxpr(jax.grad(loss))(q)
    for eqn in jaxpr.jaxpr.eqns:
        for var in eqn.outvars:
            shape = getattr(var.aval, "shape", ())
            assert not (len(shape) >= 2 and shape[-1] == T and
                        shape[-2] == T), f"dense [T,T] tensor in bwd: {eqn}"


@pytest.mark.parametrize("block_b,chunk_t", [(2, None), (5, 3)])
def test_lstm_sequence_fused_matches_scan(block_b, chunk_t):
    """The fused whole-sequence LSTM kernel (hl_cuda_lstm.cu analog: u and
    h/c resident in VMEM across all T steps) must match the lax.scan LSTM
    bit-for-bit, including variable-length masking and padded batch tails."""
    from paddle_tpu.ops import rnn as R
    from paddle_tpu.ops.pallas_kernels import lstm_sequence_fused

    rs = np.random.RandomState(3)
    B, T, D, H = 5, 7, 4, 6
    x = jnp.asarray(rs.randn(B, T, D), jnp.float32)
    lens = jnp.asarray(rs.randint(1, T + 1, B), jnp.int32)
    w = jnp.asarray(rs.randn(D, 4 * H) * 0.3, jnp.float32)
    u = jnp.asarray(rs.randn(H, 4 * H) * 0.3, jnp.float32)
    b = jnp.asarray(rs.randn(4 * H) * 0.1, jnp.float32)

    ref_out, ref_state = R.lstm(x, lens, w, u, b, forget_bias=1.0)
    xw = jnp.matmul(x.reshape(B * T, D), w).reshape(B, T, 4 * H)
    out, ht, ct = lstm_sequence_fused(xw, lens, u, b, forget_bias=1.0,
                                      block_b=block_b, chunk_t=chunk_t,
                                      interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref_out),
                               rtol=1e-6, atol=1e-6)
    np.testing.assert_allclose(np.asarray(ht), np.asarray(ref_state.h),
                               rtol=1e-6, atol=1e-6)
    np.testing.assert_allclose(np.asarray(ct), np.asarray(ref_state.c),
                               rtol=1e-6, atol=1e-6)


@pytest.mark.parametrize("block_b,chunk_t", [(2, None), (5, 3)])
def test_gru_sequence_fused_matches_scan(block_b, chunk_t):
    """Fused whole-sequence GRU kernel (hl_gpu_gru.cuh analog) vs the
    lax.scan GRU: bit-exact incl. masking and padded batch tails."""
    from paddle_tpu.ops import rnn as R
    from paddle_tpu.ops.pallas_kernels import gru_sequence_fused

    rs = np.random.RandomState(5)
    B, T, D, H = 5, 7, 4, 6
    x = jnp.asarray(rs.randn(B, T, D), jnp.float32)
    lens = jnp.asarray(rs.randint(1, T + 1, B), jnp.int32)
    w = jnp.asarray(rs.randn(D, 3 * H) * 0.3, jnp.float32)
    u = jnp.asarray(rs.randn(H, 3 * H) * 0.3, jnp.float32)
    b = jnp.asarray(rs.randn(3 * H) * 0.1, jnp.float32)

    ref_out, ref_h = R.gru(x, lens, w, u, b)
    xw = jnp.matmul(x.reshape(B * T, D), w).reshape(B, T, 3 * H)
    out, ht = gru_sequence_fused(xw, lens, u, b, block_b=block_b,
                                 chunk_t=chunk_t, interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref_out),
                               rtol=1e-6, atol=1e-6)
    np.testing.assert_allclose(np.asarray(ht), np.asarray(ref_h),
                               rtol=1e-6, atol=1e-6)


@pytest.mark.parametrize("block_b,chunk_t", [(2, None), (5, 4)])
def test_lstm_fused_backward_kernel_matches_scan_grads(block_b, chunk_t):
    """The hand-written reverse-recurrence LSTM kernel
    (hl_lstm_parallel_backward_data/_weight analog) must produce the same
    dx/dw/du/db/dh0/dc0 as autodiff through the scan, incl. variable
    lengths, nonzero initial state, and padded batch tails."""
    from paddle_tpu.ops import rnn as R

    rs = np.random.RandomState(7)
    B, T, D, H = 5, 7, 4, 6
    x = jnp.asarray(rs.randn(B, T, D), jnp.float32)
    lens = jnp.asarray(rs.randint(1, T + 1, B), jnp.int32)
    w = jnp.asarray(rs.randn(D, 4 * H) * 0.3, jnp.float32)
    u = jnp.asarray(rs.randn(H, 4 * H) * 0.3, jnp.float32)
    b = jnp.asarray(rs.randn(4 * H) * 0.1, jnp.float32)
    h0 = jnp.asarray(rs.randn(B, H) * 0.2, jnp.float32)
    c0 = jnp.asarray(rs.randn(B, H) * 0.2, jnp.float32)
    # weight every output element differently so all grad paths are probed
    wo = jnp.asarray(rs.randn(B, T, H), jnp.float32)
    wh = jnp.asarray(rs.randn(B, H), jnp.float32)
    wc = jnp.asarray(rs.randn(B, H), jnp.float32)

    def loss(fn):
        def inner(x, w, u, b, h0, c0):
            out, state = fn(x, w, u, b, h0, c0)
            return (jnp.sum(out * wo) + jnp.sum(state.h * wh)
                    + jnp.sum(state.c * wc))
        return inner

    def scan_path(x, w, u, b, h0, c0):
        return R.lstm(x, lens, w, u, b, h0=h0, c0=c0, forget_bias=1.0,
                      fused=False)

    def fused_path(x, w, u, b, h0, c0):
        out, ht, ct = R._lstm_fused(x, lens, w, u, b, h0, c0, 1.0, block_b,
                                    chunk_t)
        return out, R.LSTMState(ht, ct)

    g_ref = jax.grad(loss(scan_path), argnums=(0, 1, 2, 3, 4, 5))(
        x, w, u, b, h0, c0)
    g_fused = jax.grad(loss(fused_path), argnums=(0, 1, 2, 3, 4, 5))(
        x, w, u, b, h0, c0)
    for name, a, bb in zip("x w u b h0 c0".split(), g_ref, g_fused):
        np.testing.assert_allclose(np.asarray(bb), np.asarray(a),
                                   rtol=2e-5, atol=2e-5, err_msg=name)


@pytest.mark.parametrize("block_b,chunk_t", [(2, None), (5, 4)])
def test_gru_fused_backward_kernel_matches_scan_grads(block_b, chunk_t):
    """Hand-written whole-sequence GRU backward kernel vs autodiff through
    the scan."""
    from paddle_tpu.ops import rnn as R

    rs = np.random.RandomState(11)
    B, T, D, H = 5, 7, 4, 6
    x = jnp.asarray(rs.randn(B, T, D), jnp.float32)
    lens = jnp.asarray(rs.randint(1, T + 1, B), jnp.int32)
    w = jnp.asarray(rs.randn(D, 3 * H) * 0.3, jnp.float32)
    u = jnp.asarray(rs.randn(H, 3 * H) * 0.3, jnp.float32)
    b = jnp.asarray(rs.randn(3 * H) * 0.1, jnp.float32)
    h0 = jnp.asarray(rs.randn(B, H) * 0.2, jnp.float32)
    wo = jnp.asarray(rs.randn(B, T, H), jnp.float32)
    wh = jnp.asarray(rs.randn(B, H), jnp.float32)

    def loss(fn):
        def inner(x, w, u, b, h0):
            out, ht = fn(x, w, u, b, h0)
            return jnp.sum(out * wo) + jnp.sum(ht * wh)
        return inner

    def scan_path(x, w, u, b, h0):
        return R.gru(x, lens, w, u, b, h0=h0, fused=False)

    def fused_path(x, w, u, b, h0):
        return R._gru_fused(x, lens, w, u, b, h0, block_b, chunk_t)

    g_ref = jax.grad(loss(scan_path), argnums=(0, 1, 2, 3, 4))(x, w, u, b, h0)
    g_fused = jax.grad(loss(fused_path), argnums=(0, 1, 2, 3, 4))(
        x, w, u, b, h0)
    for name, a, bb in zip("x w u b h0".split(), g_ref, g_fused):
        np.testing.assert_allclose(np.asarray(bb), np.asarray(a),
                                   rtol=2e-5, atol=2e-5, err_msg=name)
